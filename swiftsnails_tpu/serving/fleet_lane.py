"""The bench ``fleet`` lane: max sustainable QPS at a fixed p99 SLO, 1 vs N.

The headline question a replica pool must answer is not "how fast is one
request" but "how much offered load can the fleet absorb before the tail
blows through the SLO" — the metric that decides how many replicas a
deployment needs. This lane sweeps an open-loop zipf workload
(:mod:`swiftsnails_tpu.serving.loadgen`) up a geometric QPS ladder against
one servant and against an N-replica :class:`~swiftsnails_tpu.serving.fleet.Fleet`,
and reports the highest offered rate each sustains with ``p99 <= SLO`` and
a clean error rate; ``scaling_x`` is the fleet/single ratio the
``ledger-report --check-regression`` gate floors at 1.6x for 2 replicas.

**Why this is CPU-valid.** What the lane measures is the *routing
machinery* — queueing, affinity, spill, hedging — not device kernel speed.
Per-dispatch device service time is modeled with an injectable
``service_floor_ms`` stall on each replica's dispatch hook (the same seam
the chaos drill uses), which sleeps without holding the GIL exactly as an
accelerator kernel would run without holding the host. That makes each
replica an honest single-server queue with a known service rate on any
host, so 1-vs-N scaling reflects the router's ability to spread load — the
thing this lane exists to gate — rather than how many idle cores the CI
box happens to have. The floor is recorded in the bench block.

Two controlled comparisons ride along, both at equal offered load:

* **affinity vs random**: the same zipf traffic through ring-affinity
  routing and through round-robin spray, with per-replica LRUs much
  smaller than the working set — affinity's aggregate hit rate must win.
* **hedge vs no-hedge**: one replica intermittently stalled, hedging on
  (budget-capped) vs off — hedging must cut the measured p99.
"""

from __future__ import annotations

import os
import statistics
import tempfile
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from swiftsnails_tpu.serving.bench_lane import _build_word2vec_checkpoint
from swiftsnails_tpu.serving.fleet import Fleet
from swiftsnails_tpu.serving.loadgen import run_open_loop
from swiftsnails_tpu.telemetry.request_trace import (
    RequestTracer,
    tree_complete,
)

FLEET_SEED = 13
SLO_P99_MS = 60.0
SERVICE_FLOOR_MS = 6.0
BATCH = 8
ZIPF_A = 1.1
SCALING_FLOOR = 1.6
AVAILABILITY_FLOOR_PCT = 99.0
TRACE_OVERHEAD_CEIL_PCT = 3.0
TRACE_SAMPLE_RATE = 0.1
_BASE_QPS = 30.0
_LADDER_GROWTH = 1.35
_MAX_POINTS = 12
_REFINE_RATIO = 1.15  # stop bisecting when fail/pass is this tight


def _floor_hook(floor_ms: float) -> Callable[[str, int], None]:
    """Model per-dispatch device service time: a GIL-free stall on the
    dispatcher thread, where a real kernel would be executing."""
    floor_s = floor_ms / 1e3

    def hook(kernel: str, index: int) -> None:
        time.sleep(floor_s)

    return hook


def _install_floor(fleet: Fleet, floor_ms: float) -> None:
    for rep in fleet.replicas():
        rep.servant.fault_hook = _floor_hook(floor_ms)


def _prewarm(fleet: Fleet, capacity: int) -> None:
    """Compile each replica's pull kernel off the measured path."""
    ids = np.arange(BATCH, dtype=np.int32) % capacity
    for rep in fleet.replicas():
        rep.servant.pull(ids)


def _quiesce(fleet: Fleet, timeout_s: float = 10.0) -> None:
    """Wait for every queue to empty between sweep points so one
    overloaded point cannot poison the next measurement."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        busy = any(
            rep.inflight > 0 or any(rep.servant.queue_depths().values())
            for rep in fleet.replicas()
        )
        if not busy:
            break
        time.sleep(0.05)
    time.sleep(0.05)


def _point_ok(point: Dict, slo_ms: float) -> bool:
    return point["p99_ms"] <= slo_ms and point["error_rate_pct"] <= 1.0


def _sweep(
    fleet: Fleet,
    *,
    capacity: int,
    duration_s: float,
    slo_ms: float,
    seed: int,
) -> Dict:
    """Ascend the offered-QPS ladder until the SLO breaks, then bisect
    between the last passing and first failing rung — max sustainable must
    not be quantized by the geometric ladder spacing, or the 1-vs-N ratio
    inherits up to a full ladder step of error."""
    points: List[Dict] = []
    step = [0]

    def probe(qps: float) -> bool:
        res = run_open_loop(
            lambda anchor, ids: fleet.pull(ids),
            qps=qps, duration_s=duration_s, seed=seed + step[0],
            id_space=capacity, batch=BATCH, zipf_a=ZIPF_A,
        )
        step[0] += 1
        points.append({k: res[k] for k in (
            "offered_qps", "achieved_qps", "p50_ms", "p95_ms", "p99_ms",
            "error_rate_pct")})
        _quiesce(fleet)
        return _point_ok(res, slo_ms)

    max_qps, fail_qps = 0.0, 0.0
    qps = _BASE_QPS
    for _ in range(_MAX_POINTS):
        if probe(qps):
            max_qps = qps
            qps *= _LADDER_GROWTH
        else:
            fail_qps = qps
            break
    while (max_qps > 0 and fail_qps > 0
           and fail_qps / max_qps > _REFINE_RATIO):
        mid = (max_qps * fail_qps) ** 0.5
        if probe(mid):
            max_qps = mid
        else:
            fail_qps = mid
    return {"max_qps": round(max_qps, 2), "points": points}


def _confirm(
    fleet: Fleet,
    *,
    qps: float,
    capacity: int,
    duration_s: float,
    slo_ms: float,
    seed: int,
) -> tuple:
    """Reproduce the claimed max before reporting it: re-run at the rate
    the sweep found, retry once on failure (knee-region runs are noisy at
    these durations), then demote geometrically. The operating point the
    lane reports is one that actually held the SLO when re-measured — both
    the single and fleet maxes go through this, so the scaling ratio
    compares two confirmed rates, not two lucky rungs."""
    rate, res = qps, None
    for attempt in range(5):
        for rep in fleet.replicas():
            rep.servant.reset_metrics()
            rep.requests = 0  # per-replica split describes this pass only
        res = run_open_loop(
            lambda anchor, ids: fleet.pull(ids),
            qps=rate, duration_s=duration_s, seed=seed + attempt,
            id_space=capacity, batch=BATCH, zipf_a=ZIPF_A,
        )
        _quiesce(fleet)
        if _point_ok(res, slo_ms):
            break
        if attempt % 2 == 1:
            rate /= _REFINE_RATIO
    return round(rate, 2), res


def _aggregate_hit_rate(fleet: Fleet) -> float:
    hits = sum(r.servant.cache.hits for r in fleet.replicas())
    misses = sum(r.servant.cache.misses for r in fleet.replicas())
    return hits / (hits + misses) if (hits + misses) else 0.0


def _affinity_leg(
    mk_fleet: Callable[..., Fleet],
    *,
    capacity: int,
    qps: float,
    duration_s: float,
    affinity: bool,
    seed: int,
) -> Dict:
    """Steady-state aggregate LRU hit rate under one routing policy: warm
    pass first, then counters reset, then the measured pass."""
    with mk_fleet(affinity=affinity, cache_rows=16 * BATCH) as fleet:
        _install_floor(fleet, SERVICE_FLOOR_MS)
        _prewarm(fleet, capacity)
        submit = lambda anchor, ids: fleet.pull(ids)  # noqa: E731
        run_open_loop(submit, qps=qps, duration_s=duration_s / 2,
                      seed=seed, id_space=capacity, batch=BATCH,
                      zipf_a=ZIPF_A)
        _quiesce(fleet)
        for rep in fleet.replicas():
            rep.servant.reset_metrics()
        res = run_open_loop(submit, qps=qps, duration_s=duration_s,
                            seed=seed + 1, id_space=capacity, batch=BATCH,
                            zipf_a=ZIPF_A)
        _quiesce(fleet)
        return {"hit_rate": round(_aggregate_hit_rate(fleet), 4),
                "requests": res["requests"], "p99_ms": res["p99_ms"]}


def _stall_hook(floor_ms: float, stall_ms: float,
                every: int) -> Callable[[str, int], None]:
    """An intermittently sick replica: every ``every``-th dispatch stalls
    ``stall_ms`` on top of the service floor."""
    def hook(kernel: str, index: int) -> None:
        time.sleep(floor_ms / 1e3)
        if index % every == every - 1:
            time.sleep(stall_ms / 1e3)

    return hook


def _hedge_leg(
    mk_fleet: Callable[..., Fleet],
    *,
    capacity: int,
    qps: float,
    duration_s: float,
    budget_pct: float,
    stall_ms: float,
    seed: int,
) -> Dict:
    """p99 at equal offered load with one stalling replica; ``budget_pct``
    0 is the no-hedge control."""
    with mk_fleet(hedge_budget_pct=budget_pct) as fleet:
        reps = fleet.replicas()
        for rep in reps[:-1]:
            rep.servant.fault_hook = _floor_hook(SERVICE_FLOOR_MS)
        reps[-1].servant.fault_hook = _stall_hook(
            SERVICE_FLOOR_MS, stall_ms, every=5)
        _prewarm(fleet, capacity)
        res = run_open_loop(
            lambda anchor, ids: fleet.pull(ids),
            qps=qps, duration_s=duration_s, seed=seed,
            id_space=capacity, batch=BATCH, zipf_a=ZIPF_A,
        )
        _quiesce(fleet)
        reg = fleet.registry
        return {
            "p99_ms": res["p99_ms"],
            "p50_ms": res["p50_ms"],
            "error_rate_pct": res["error_rate_pct"],
            "hedged": int(reg.counter("serve.hedged").value),
            "hedge_won": int(reg.counter("serve.hedge_won").value),
            "hedge_rate_pct": round(fleet._gov.rate_pct, 3),
        }


def _trace_overhead_leg(
    mk_fleet: Callable[..., Fleet],
    *,
    capacity: int,
    qps: float,
    duration_s: float,
    seed: int,
    reps: int = 3,
) -> Dict:
    """Tracing on vs off at equal offered load — the ride-along that keeps
    the observability plane honest: head sampling at ``TRACE_SAMPLE_RATE``
    plus tail-keep must cost no more than ``TRACE_OVERHEAD_CEIL_PCT`` of
    throughput or p99 (the ``ledger-report --check-regression`` gate).

    A single on/off pair at these durations measures scheduler jitter, not
    tracing cost, so the legs run interleaved ``reps`` times and report
    medians plus ``p99_noise_ms`` — the off leg's own max-min spread. The
    gate only trips when the on-vs-off delta exceeds that spread: tracing
    has to cost more than the baseline disagrees with itself."""
    samples: Dict[str, List[Dict]] = {"off": [], "on": []}
    kept = 0
    for rep in range(max(1, reps)):
        for label, tracer in (
            ("off", None),
            ("on", RequestTracer(TRACE_SAMPLE_RATE, anomaly_keep=True,
                                 seed=FLEET_SEED + rep)),
        ):
            with mk_fleet(cache_rows=BATCH, hedge_budget_pct=0.0,
                          request_tracer=tracer) as fleet:
                _install_floor(fleet, SERVICE_FLOOR_MS)
                _prewarm(fleet, capacity)
                res = run_open_loop(
                    lambda anchor, ids: fleet.pull(ids),
                    qps=qps, duration_s=duration_s, seed=seed + rep,
                    id_space=capacity, batch=BATCH, zipf_a=ZIPF_A,
                )
                _quiesce(fleet)
                samples[label].append(res)
                if tracer is not None:
                    kept += tracer.stats()["kept"]

    def med(label: str, key: str) -> float:
        return float(statistics.median(s[key] for s in samples[label]))

    qps_off, qps_on = med("off", "achieved_qps"), med("on", "achieved_qps")
    p99_off, p99_on = med("off", "p99_ms"), med("on", "p99_ms")
    off_p99s = [s["p99_ms"] for s in samples["off"]]
    return {
        "offered_qps": round(qps, 1),
        "sample_rate": TRACE_SAMPLE_RATE,
        "reps": max(1, reps),
        "qps_off": qps_off,
        "qps_on": qps_on,
        "p99_off_ms": p99_off,
        "p99_on_ms": p99_on,
        "p99_off_reps": off_p99s,
        "p99_on_reps": [s["p99_ms"] for s in samples["on"]],
        "p99_noise_ms": round(max(off_p99s) - min(off_p99s), 3),
        "overhead_qps_pct": round(
            100.0 * (qps_off - qps_on) / qps_off if qps_off else 0.0, 3),
        "overhead_p99_pct": round(
            100.0 * (p99_on - p99_off) / p99_off if p99_off else 0.0, 3),
        "overhead_ceil_pct": TRACE_OVERHEAD_CEIL_PCT,
        "kept_traces": int(kept),
    }


def fleet_bench(
    small: bool = False,
    workdir: Optional[str] = None,
    ledger=None,
    replicas: int = 2,
) -> Dict:
    """Run the fleet lane; returns the ``fleet`` block for the bench JSON.

    Headline fields (gated by ``ledger-report --check-regression``):
    ``qps`` (fleet max sustainable at the p99 SLO), ``scaling_x``
    (fleet/single), ``affinity`` hit rates, and the ``hedge`` comparison.
    """
    from swiftsnails_tpu.utils.config import Config  # noqa: F401 (doc link)

    t_start = time.monotonic()
    dim = 16
    capacity = 1 << 11
    duration_s = 0.7 if small else 1.5
    rng_seed = FLEET_SEED

    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ssn-fleet-bench-")
        workdir = own_tmp.name
    try:
        root = os.path.join(workdir, "ckpt-w2v")
        cfg = _build_word2vec_checkpoint(root, dim, capacity)

        def mk_fleet(n: int = replicas, affinity: bool = True,
                     hedge_budget_pct: float = 10.0,
                     cache_rows: int = 1024, **extra) -> Fleet:
            return Fleet.from_checkpoint(
                root, cfg, replicas=n, ledger=ledger,
                batch_buckets=(BATCH,), cache_rows=cache_rows,
                queue_depth=64, **extra,
            ).configure(affinity=affinity,
                         hedge_budget_pct=hedge_budget_pct)

        # -- 1 vs N: max sustainable QPS at the p99 SLO --------------------
        # sweep legs pin the LRU to one batch so every request dispatches
        # and pays the modeled device service time — the sweep measures
        # dispatch/queueing capacity; cache economics are the affinity
        # leg's controlled comparison, not a confound here. Hedging is off
        # for the same reason: across homogeneous replicas at the knee
        # every hedge is pure work amplification (the duplicate steals a
        # service slot and then loses the race); its tail-rescue value is
        # measured in the dedicated stalled-replica leg below
        with mk_fleet(n=1, cache_rows=BATCH,
                      hedge_budget_pct=0.0) as single_fleet:
            _install_floor(single_fleet, SERVICE_FLOOR_MS)
            _prewarm(single_fleet, capacity)
            single = _sweep(single_fleet, capacity=capacity,
                            duration_s=duration_s, slo_ms=SLO_P99_MS,
                            seed=rng_seed)
            if single["max_qps"] > 0:
                single["max_qps"], _ = _confirm(
                    single_fleet, qps=single["max_qps"],
                    capacity=capacity, duration_s=duration_s,
                    slo_ms=SLO_P99_MS, seed=rng_seed + 50)

        with mk_fleet(cache_rows=BATCH, hedge_budget_pct=0.0) as fleet:
            _install_floor(fleet, SERVICE_FLOOR_MS)
            _prewarm(fleet, capacity)
            swept = _sweep(fleet, capacity=capacity, duration_s=duration_s,
                           slo_ms=SLO_P99_MS, seed=rng_seed + 100)
            # confirmation pass at the sustained rate with fresh counters:
            # the per-replica numbers describe the SLO-compliant operating
            # point, not the overloaded rungs above it
            at_max = None
            per_replica: Dict[str, Dict] = {}
            if swept["max_qps"] > 0:
                swept["max_qps"], at_max = _confirm(
                    fleet, qps=swept["max_qps"], capacity=capacity,
                    duration_s=duration_s, slo_ms=SLO_P99_MS,
                    seed=rng_seed + 200)
            fstats = fleet.stats()
            dur = (at_max or {}).get("duration_s") or 0.0
            for rid, rs in fstats["replicas"].items():
                per_replica[rid] = {
                    "requests": rs["requests"],
                    "qps": round(rs["requests"] / dur, 1) if dur else None,
                    "p50_ms": rs["kernels"]["pull"]["p50_ms"],
                    "p99_ms": rs["kernels"]["pull"]["p99_ms"],
                    "cache_hit_rate": rs["cache_hit_rate"],
                }
            hedge_info = fstats["hedge"]

        scaling = (swept["max_qps"] / single["max_qps"]
                   if single["max_qps"] > 0 else 0.0)

        # -- affinity vs random at equal offered load ----------------------
        probe_qps = max(min(0.6 * swept["max_qps"], 250.0), 60.0)
        aff = _affinity_leg(mk_fleet, capacity=capacity, qps=probe_qps,
                            duration_s=duration_s, affinity=True,
                            seed=rng_seed + 300)
        rnd = _affinity_leg(mk_fleet, capacity=capacity, qps=probe_qps,
                            duration_s=duration_s, affinity=False,
                            seed=rng_seed + 300)  # identical traffic

        # -- hedge vs no-hedge with one stalling replica -------------------
        hedge_qps = max(min(0.4 * swept["max_qps"], 120.0), 50.0)
        stall_ms = 80.0
        hedged = _hedge_leg(mk_fleet, capacity=capacity, qps=hedge_qps,
                            duration_s=1.5, budget_pct=30.0,
                            stall_ms=stall_ms, seed=rng_seed + 400)
        control = _hedge_leg(mk_fleet, capacity=capacity, qps=hedge_qps,
                             duration_s=1.5, budget_pct=0.0,
                             stall_ms=stall_ms, seed=rng_seed + 400)

        # -- tracing overhead at equal offered load ------------------------
        # 0.6x the knee: at saturation p99 measures queueing instability,
        # not tracing cost, and the comparison drowns in its own noise
        trace_qps = max(min(0.6 * swept["max_qps"], 150.0), 50.0)
        trace_overhead = _trace_overhead_leg(
            mk_fleet, capacity=capacity, qps=trace_qps,
            duration_s=duration_s, seed=rng_seed + 500)

        return {
            "seed": FLEET_SEED,
            "small": bool(small),
            "replicas": int(replicas),
            "slo_p99_ms": SLO_P99_MS,
            "service_floor_ms": SERVICE_FLOOR_MS,
            "batch": BATCH,
            "zipf_a": ZIPF_A,
            "duration_s": duration_s,
            "single": single,
            "fleet": {
                "max_qps": swept["max_qps"],
                "points": swept["points"],
                "at_max": {k: at_max[k] for k in (
                    "offered_qps", "achieved_qps", "p50_ms", "p95_ms",
                    "p99_ms", "error_rate_pct")} if at_max else None,
                "per_replica": per_replica,
                "hedge": hedge_info,
            },
            "scaling_x": round(scaling, 3),
            "scaling_floor": SCALING_FLOOR,
            "affinity": {
                "offered_qps": round(probe_qps, 1),
                "affinity_hit_rate": aff["hit_rate"],
                "random_hit_rate": rnd["hit_rate"],
                "affinity_p99_ms": aff["p99_ms"],
                "random_p99_ms": rnd["p99_ms"],
            },
            "hedge": {
                "offered_qps": round(hedge_qps, 1),
                "stall_ms": stall_ms,
                "budget_pct": 30.0,
                "p99_ms": hedged["p99_ms"],
                "nohedge_p99_ms": control["p99_ms"],
                "hedged": hedged["hedged"],
                "hedge_won": hedged["hedge_won"],
                "hedge_rate_pct": hedged["hedge_rate_pct"],
            },
            "trace_overhead": trace_overhead,
            "qps": swept["max_qps"],
            "p99_ms": (at_max or {}).get("p99_ms", 0.0),
            "elapsed_s": round(time.monotonic() - t_start, 2),
        }
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()


# ------------------------------------------------------------ chaos drill ---


def fleet_chaos_drill(
    small: bool = True,
    workdir: Optional[str] = None,
    ledger=None,
    floor_pct: float = AVAILABILITY_FLOOR_PCT,
) -> Dict[str, Dict]:
    """``tools/chaos_drill.py --fleet``: one replica gets sick mid-storm;
    the fleet must hold the availability floor via re-route + hedging.

    Two drills, reusing the serving chaos kinds against exactly one
    replica: ``kill_replica`` storms it with ``serve_io_error`` dispatch
    faults (breaker trips, routing walks around it), ``slow_replica``
    storms it with ``serve_slow`` stalls (hedges rescue the stragglers).
    """
    from swiftsnails_tpu.resilience.chaos import ChaosPlan, parse_chaos_spec

    dim, capacity = 16, 1 << 11
    duration_s = 1.2 if small else 2.5
    qps = 80.0

    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ssn-fleet-chaos-")
        workdir = own_tmp.name
    try:
        root = os.path.join(workdir, "ckpt-w2v")
        cfg = _build_word2vec_checkpoint(root, dim, capacity)
        results: Dict[str, Dict] = {}
        for drill, kind, stall_ms in (
            ("kill_replica", "serve_io_error", 0.0),
            ("slow_replica", "serve_slow", 90.0),
        ):
            # storm the victim's first ~60 dispatches (the whole run, at
            # this rate, is ~100 dispatches on that replica)
            spec = ",".join(f"{kind}@{i}" for i in range(0, 60))
            plan = ChaosPlan(parse_chaos_spec(spec), seed=FLEET_SEED,
                             ledger=ledger)
            # tail-keep only (rate 0): every hedged / re-routed / degraded
            # request must still land in the ring as a complete span tree
            tracer = RequestTracer(0.0, anomaly_keep=True, seed=FLEET_SEED)
            with Fleet.from_checkpoint(
                root, cfg, replicas=2, ledger=ledger,
                batch_buckets=(BATCH,), cache_rows=256, queue_depth=64,
                breaker_threshold=3, breaker_cooldown_ms=400.0,
                request_tracer=tracer,
            ).configure(hedge_budget_pct=30.0) as fleet:
                reps = fleet.replicas()
                for rep in reps[:-1]:
                    rep.servant.fault_hook = _floor_hook(SERVICE_FLOOR_MS)
                victim = reps[-1]

                def sick_hook(kernel: str, index: int,
                              _plan=plan) -> None:
                    time.sleep(SERVICE_FLOOR_MS / 1e3)
                    k = _plan.serve_fault(index)
                    if k == "serve_io_error":
                        raise OSError("chaos: injected serve I/O error")
                    if k == "serve_slow":
                        time.sleep(stall_ms / 1e3)

                victim.servant.fault_hook = sick_hook
                _prewarm_healthy(fleet, capacity, exclude=victim.id)
                res = run_open_loop(
                    lambda anchor, ids: fleet.pull(ids),
                    qps=qps, duration_s=duration_s, seed=FLEET_SEED,
                    id_space=capacity, batch=BATCH, zipf_a=ZIPF_A,
                )
                _quiesce(fleet)
                reg = fleet.registry
                availability = 100.0 - res["error_rate_pct"]
                victim_breaker = \
                    victim.servant.breakers["pull"].snapshot()
                # every anomaly trace must be a complete tree, and the
                # drill's signature anomaly must be drillable end to end:
                # a re-route hop (kill) / both hedge attempts (slow)
                anomalies = [c.to_dict() for c in tracer.anomaly_traces()]
                trees_ok = bool(anomalies) and all(
                    tree_complete(t, require=("attempt", "request"))
                    for t in anomalies)
                if drill == "kill_replica":
                    sig = [t for t in anomalies
                           if "reroute" in t["anomalies"]
                           and tree_complete(t, require=(
                               "attempt", "reroute", "request"))]
                else:
                    sig = [t for t in anomalies
                           if "hedge" in t["anomalies"]
                           and sum(1 for s in t["spans"]
                                   if s["name"] == "attempt") >= 2
                           and tree_complete(t, require=(
                               "attempt", "request"))]
                trace_ok = trees_ok and bool(sig)
                trace_path = os.path.join(
                    workdir, f"fleet-{drill}-traces.json")
                try:
                    tracer.export_chrome(trace_path)
                except OSError:
                    trace_path = None
                results[drill] = {
                    "anomaly_traces": len(anomalies),
                    "trace_trees_complete": trace_ok,
                    "trace_id": sig[0]["trace_id"] if sig else None,
                    "trace_export": trace_path,
                    "availability_pct": round(availability, 3),
                    "floor_pct": float(floor_pct),
                    "p99_ms": res["p99_ms"],
                    "requests": res["requests"],
                    "errors": res["error_types"],
                    "reroutes": int(reg.counter("fleet.reroute").value),
                    "hedged": int(reg.counter("serve.hedged").value),
                    "hedge_won": int(reg.counter("serve.hedge_won").value),
                    "victim": victim.id,
                    "victim_breaker_trips": victim_breaker["trips"],
                    "recovered": availability >= floor_pct and trace_ok,
                }
        return results
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()


def _prewarm_healthy(fleet: Fleet, capacity: int, exclude: str) -> None:
    ids = np.arange(BATCH, dtype=np.int32) % capacity
    for rep in fleet.replicas():
        if rep.id != exclude:
            rep.servant.pull(ids)
