"""The bench ``chaos-serve`` lane: an availability drill on the read path.

One implementation used by ``bench.py --lane chaos-serve``,
``tools/chaos_drill.py --serve``, and the tier-1 lane smoke test. It loads a
tiny verified word2vec checkpoint into a live :class:`Servant` and runs a
seeded :class:`~swiftsnails_tpu.resilience.chaos.ChaosPlan` fault matrix
(``serve_io_error`` storms + ``serve_slow`` stalls via the Servant's
``fault_hook``) against it twice:

* **protected leg** — circuit breakers + degraded stale-LRU reads on. The
  lane measures availability % (fresh + degraded serves over all requests),
  degraded-hit share, p99 latency under fault, and the breaker trip /
  recover latencies.
* **unprotected control leg** — breakers and degraded mode disabled; the
  same fault schedule must produce a *hard failure* (an unhandled dispatch
  error reaching the caller). A control that survives means the matrix is
  not actually exercising the serve path, so the gate fails it.

Two more drills ride along: ``reload_corrupt`` (the newest checkpoint is
corrupted on disk, then a live reload is requested — the shadow-verify swap
must reject it and keep the old version serving) and, when requested, the
``tier_bitflip`` recovery drill from :mod:`swiftsnails_tpu.resilience.drill`.

Availability under fault is correctness, not device speed, so the lane is
valid on CPU; the block lands in the bench JSON (``chaos_serve``), the run
ledger, and the ``ledger-report --check-regression`` gate on ANY platform.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Dict, Optional

import numpy as np

from swiftsnails_tpu.serving.bench_lane import SERVE_SEED

AVAILABILITY_FLOOR_PCT = 99.0
_SLOW_MS = 25.0


def _build_checkpoint(root: str, dim: int, capacity: int):
    """Init and save a verified packed word2vec checkpoint; returns the
    serving config AND the trainer/state (the reload drill needs to write a
    second, newer checkpoint into the same root)."""
    from swiftsnails_tpu.framework.checkpoint import save_checkpoint
    from swiftsnails_tpu.framework.quality import paired_corpus
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    ids, vocab = paired_corpus(n_pairs=32, reps=4, seed=SERVE_SEED)
    cfg = Config({
        "dim": str(dim), "capacity": str(capacity), "packed": "1",
        "seed": str(SERVE_SEED), "subsample": "0",
    })
    trainer = Word2VecTrainer(cfg, mesh=None, corpus_ids=ids, vocab=vocab)
    state = trainer.init_state()
    save_checkpoint(root, state, step=1, wait=True)
    return cfg, state


def _fault_hook(plan, slow_ms: float = _SLOW_MS):
    """Servant ``fault_hook`` driven by the plan's serve schedule: the hook
    fires once per dispatched batch, indexed per kernel."""

    def hook(kernel: str, index: int) -> None:
        kind = plan.serve_fault(index)
        if kind == "serve_io_error":
            raise OSError(f"chaos: injected {kernel} read error @{index}")
        if kind == "serve_slow":
            time.sleep(slow_ms / 1e3)

    return hook


def _drive_leg(servant, plan, hot: np.ndarray, requests: int,
               cooldown_ms: float, slow_ms: float = _SLOW_MS) -> Dict:
    """Fire ``requests`` pulls over the ``hot`` id set under the plan's
    fault schedule; every request is tallied as fresh, degraded, or failed.
    The stale-LRU inventory was warmed (and version-bumped) by the caller,
    so each pull goes through dispatch — and through the fault hook —
    unless the breaker short-circuits it to a degraded serve."""
    from swiftsnails_tpu.serving.breaker import Unavailable

    servant.fault_hook = _fault_hook(plan, slow_ms=slow_ms)
    reg = servant.registry
    degraded0 = int(reg.counter("serve.degraded_hits").value)
    served = failed = 0
    first_error: Optional[str] = None
    t_first_fault = None
    t_trip = None
    br = servant.breakers.get("pull")
    for n in range(requests):
        trips_before = br.trips if br is not None else 0
        deg_before = int(reg.counter("serve.pull.degraded").value)
        try:
            servant.pull(hot)
            served += 1
        except (Unavailable, OSError, RuntimeError) as e:
            failed += 1
            if first_error is None:
                first_error = f"{type(e).__name__}: {e}"
        now = time.perf_counter()
        if t_first_fault is None and (
                failed
                or int(reg.counter("serve.pull.degraded").value) > deg_before):
            # first visible fault effect — a shed OR a degraded fallback
            # (the dispatch failed even though the caller was served)
            t_first_fault = now
        if br is not None and br.trips > trips_before and t_trip is None:
            t_trip = now
            if t_first_fault is None:
                t_first_fault = now
    # recovery phase: faults exhausted — wait out the cooldown and keep
    # pulling until the half-open probe closes the breaker again
    recovered = br is None or br.state == "closed"
    if br is not None and not recovered:
        deadline = time.perf_counter() + 50 * (cooldown_ms / 1e3)
        while time.perf_counter() < deadline:
            time.sleep(cooldown_ms / 1e3 / 4)
            try:
                servant.pull(hot)
                served += 1
            except (Unavailable, OSError, RuntimeError):
                failed += 1
            if br.state == "closed":
                recovered = True
                break
    servant.fault_hook = None
    total = served + failed
    stats = servant.stats()
    degraded_hits = int(reg.counter("serve.degraded_hits").value) - degraded0
    return {
        "requests": total,
        "served": served,
        "failed": failed,
        "availability_pct": round(100.0 * served / max(total, 1), 3),
        "degraded_share_pct": round(
            100.0 * degraded_hits / max(total * len(hot), 1), 3),
        "p99_under_fault_ms": stats["kernels"]["pull"]["p99_ms"],
        "first_error": first_error,
        "recovered": bool(recovered),
        "trip_ms": (
            round((t_trip - t_first_fault) * 1e3, 3)
            if t_trip is not None and t_first_fault is not None else None),
        "breaker": br.snapshot() if br is not None else None,
    }


def chaos_serve_bench(
    small: bool = False,
    workdir: Optional[str] = None,
    ledger=None,
    floor_pct: float = AVAILABILITY_FLOOR_PCT,
    include_tier_drill: bool = True,
) -> Dict:
    """Run the availability drill; returns the ``chaos_serve`` block for the
    bench JSON. Gated fields (``ledger-report --check-regression``, any
    platform): ``availability_pct`` >= ``floor_pct``,
    ``unprotected_hard_failure``, ``reload_corrupt_rejected``, and (when the
    tier drill ran) ``tier_bitflip.recovered``."""
    from swiftsnails_tpu.framework.checkpoint import save_checkpoint
    from swiftsnails_tpu.resilience.chaos import (
        ChaosPlan, corrupt_checkpoint_dir, parse_chaos_spec,
    )
    from swiftsnails_tpu.serving.engine import Servant

    dim = 16 if small else 32
    capacity = 1 << (9 if small else 11)
    requests = 24 if small else 80
    cooldown_ms = 60.0
    hot = np.arange(32, dtype=np.int32)
    # storm of read errors early (trips the breaker), a second burst after
    # the first recovery window, and a couple of stalls in between
    spec = ("serve_io_error@0-5,serve_slow@8-9,"
            f"serve_io_error@{requests // 2}-{requests // 2 + 3}")

    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ssn-chaos-serve-")
        workdir = own_tmp.name
    try:
        root = os.path.join(workdir, "ckpt")
        cfg, state = _build_checkpoint(root, dim, capacity)

        def _open(protected: bool) -> Servant:
            sv = Servant.from_checkpoint(
                root, cfg, ledger=ledger if protected else None,
                cache_rows=max(len(hot) * 2, 128),
                breaker_threshold=3 if protected else 0,
                breaker_cooldown_ms=cooldown_ms,
                degraded=protected,
            )
            # warm the stale-LRU inventory, then bump the version so every
            # drill pull goes through dispatch (where the faults live) while
            # the warmed rows stay available for degraded serves
            sv.pull(hot)
            sv.reload(dict(sv._tables), manifest=sv.manifest)
            return sv

        with _open(protected=True) as served:
            protected = _drive_leg(
                served, ChaosPlan(parse_chaos_spec(spec), seed=SERVE_SEED,
                                  ledger=ledger),
                hot, requests, cooldown_ms)
            health = served.health()

            # reload_corrupt drill against the SAME live servant: write a
            # newer checkpoint, corrupt it on disk, ask for a live reload —
            # the shadow verify must reject it and keep the version serving
            plan = ChaosPlan(parse_chaos_spec("reload_corrupt@0"),
                             seed=SERVE_SEED, ledger=ledger)
            save_checkpoint(root, state, step=2, wait=True)
            if plan.wants_reload_corrupt(0):
                corrupt_checkpoint_dir(root, step=2, rng=plan.rng,
                                       ledger=ledger)
            kept = served.version
            reload_rejected = False
            reload_error = None
            try:
                served.reload_from_checkpoint(root, cfg, step=2)
            except Exception as e:  # noqa: BLE001 — the rejection IS the pass
                reload_rejected = True
                reload_error = f"{type(e).__name__}: {str(e)[:90]}"
            still_serving = bool(
                served.version == kept
                and len(served.pull(hot[:4])) == 4)

        with _open(protected=False) as bare:
            control = _drive_leg(
                bare, ChaosPlan(parse_chaos_spec(spec), seed=SERVE_SEED),
                hot, requests, cooldown_ms)

        out = {
            "spec": spec,
            "seed": SERVE_SEED,
            "small": bool(small),
            "floor_pct": float(floor_pct),
            "availability_pct": protected["availability_pct"],
            "degraded_share_pct": protected["degraded_share_pct"],
            "p99_under_fault_ms": protected["p99_under_fault_ms"],
            "trip_ms": protected["trip_ms"],
            "recover_ms": (protected["breaker"] or {}).get(
                "last_recovery_latency_ms"),
            "breaker": protected["breaker"],
            "recovered": protected["recovered"],
            "health": {"status": health["status"],
                       "degraded_hits": health["degraded_hits"]},
            "unprotected_hard_failure": control["failed"] > 0,
            "control_availability_pct": control["availability_pct"],
            "control_first_error": control["first_error"],
            "reload_corrupt_rejected": bool(
                reload_rejected and still_serving),
            "reload_corrupt_error": reload_error,
        }
        if include_tier_drill:
            from swiftsnails_tpu.resilience.drill import drill_tier_bitflip

            try:
                out["tier_bitflip"] = drill_tier_bitflip(
                    os.path.join(workdir, "tier-drill"))
            except Exception as e:  # noqa: BLE001 — an unrecovered drill
                out["tier_bitflip"] = {
                    "recovered": False, "error": f"{type(e).__name__}: {e}"}
        return out
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()
