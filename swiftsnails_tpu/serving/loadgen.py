"""Open-loop load generator: Poisson arrivals, zipf keys, honest queueing.

A closed-loop driver (fire, wait, fire again) can never offer more load
than the system absorbs — when the servant slows down, the driver slows
down with it and the measured latency silently excludes the queueing that
real, independent clients would have experienced (the "coordinated
omission" trap). This generator is **open-loop**: the arrival times are a
Poisson process drawn up front from the offered rate — closed-form offered
load ``E[arrivals] = qps x duration`` — and every request's latency is
measured from its *scheduled arrival*, not from when a worker got around
to sending it. An overloaded fleet therefore shows its queueing delay in
p99 instead of masking it as a lower achieved rate.

Keys follow a bounded zipf distribution (the skew every production trace
in PAPERS.md shows, and the one PR 11's placement audit measured): each
request samples an *anchor* rank and pulls that anchor's fixed id slice,
so a repeated anchor re-touches exactly the same rows — what makes
affinity routing's warm-LRU effect observable.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

import numpy as np


def zipf_weights(n: int, a: float) -> np.ndarray:
    """Normalized zipf pmf over ranks ``0..n-1``: ``p(r) ~ 1/(r+1)^a``."""
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), a)
    return w / w.sum()


def anchor_ids(anchor: int, batch: int, id_space: int) -> np.ndarray:
    """The fixed id slice owned by ``anchor``: ``batch`` consecutive rows.

    Disjoint across anchors (for ``anchor < id_space // batch``), so each
    replica's hot-row LRU warms a clean per-anchor working set.
    """
    return (np.int64(anchor) * batch + np.arange(batch)) % id_space


def run_open_loop(
    submit: Callable[[int, np.ndarray], None],
    *,
    qps: float,
    duration_s: float,
    seed: int,
    id_space: int,
    batch: int = 8,
    zipf_a: float = 1.2,
    anchors: Optional[int] = None,
    workers: int = 64,
    clock: Callable[[], float] = time.monotonic,
) -> Dict:
    """Drive ``submit(anchor, ids)`` at ``qps`` for ``duration_s``.

    Deterministic given ``seed``: the arrival schedule and key sequence are
    drawn up front. ``workers`` bounds concurrency only — when all workers
    are busy a request starts late and its lateness is *charged to its
    latency* (open-loop accounting), never dropped.

    Returns offered/achieved QPS, scheduled-arrival latency percentiles,
    error counts by type, and late-start count.
    """
    rng = np.random.default_rng(seed)
    n_anchors = anchors if anchors is not None else max(id_space // batch, 1)
    n_req = max(int(rng.poisson(qps * duration_s)), 1)
    arrivals = np.sort(rng.uniform(0.0, duration_s, size=n_req))
    keys = rng.choice(n_anchors, size=n_req, p=zipf_weights(n_anchors, zipf_a))

    latencies = np.zeros(n_req, np.float64)
    ok = np.zeros(n_req, bool)
    errors: Dict[str, int] = {}
    late = [0]
    cursor = [0]
    lock = threading.Lock()
    t_start = clock()

    def worker() -> None:
        while True:
            with lock:
                i = cursor[0]
                if i >= n_req:
                    return
                cursor[0] = i + 1
            sched = t_start + arrivals[i]
            now = clock()
            if now < sched:
                time.sleep(sched - now)
            elif now - sched > 1e-3:
                with lock:
                    late[0] += 1
            anchor = int(keys[i])
            try:
                submit(anchor, anchor_ids(anchor, batch, id_space))
                done = clock()
                latencies[i] = (done - sched) * 1e3
                ok[i] = True
            except Exception as e:  # noqa: BLE001 — loadgen counts, never dies
                with lock:
                    name = type(e).__name__
                    errors[name] = errors.get(name, 0) + 1

    threads = [
        threading.Thread(target=worker, name=f"ssn-loadgen-{w}", daemon=True)
        for w in range(min(int(workers), n_req))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 120.0)
    elapsed = max(clock() - t_start, 1e-9)

    lat = latencies[ok]
    n_ok = int(ok.sum())
    n_err = n_req - n_ok

    def pct(p: float) -> float:
        return round(float(np.percentile(lat, p)), 3) if n_ok else 0.0

    return {
        "offered_qps": round(float(qps), 3),
        "achieved_qps": round(n_ok / elapsed, 3),
        "requests": n_req,
        "completed": n_ok,
        "errors": n_err,
        "error_rate_pct": round(100.0 * n_err / n_req, 3),
        "error_types": dict(sorted(errors.items())),
        "late_starts": late[0],
        "duration_s": round(elapsed, 3),
        "mean_ms": round(float(lat.mean()), 3) if n_ok else 0.0,
        "p50_ms": pct(50.0),
        "p95_ms": pct(95.0),
        "p99_ms": pct(99.0),
        "max_ms": round(float(lat.max()), 3) if n_ok else 0.0,
    }
