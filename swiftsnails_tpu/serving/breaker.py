"""Per-kernel circuit breakers for the serving read path.

The reference parameter server stays up by *failing fast*: a worker whose
RPC target is sick stops hammering it and retries elsewhere. The serving
engine's equivalent is a classic closed / open / half-open breaker per
kernel (``pull`` / ``topk`` / ``score``):

* **closed** — healthy. Dispatch failures increment a consecutive-failure
  count; ``threshold`` of them in a row trips the breaker open.
* **open** — the kernel is presumed sick; no dispatch is attempted until
  ``cooldown_ms`` elapses. Pull traffic is served DEGRADED from the hot-row
  LRU (counted separately, never mixed into fresh counters); anything that
  cannot be degraded sheds with a typed :class:`Unavailable` instead of
  queuing up behind a dead kernel.
* **half-open** — cooldown expired; up to ``halfopen_probes`` in-flight
  requests are let through as probes. A probe success closes the breaker
  (recovery — the trip→close latency is recorded), a probe failure re-opens
  it for another cooldown.

``clock`` is injectable so tests drive the cooldown without sleeping. Every
state transition can be observed via ``on_transition(name, old, new,
snapshot)`` — the Servant turns these into structured ``breaker`` ledger
events.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

__all__ = ["CircuitBreaker", "Unavailable", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class Unavailable(RuntimeError):
    """The kernel's breaker is open and the request could not be served
    degraded: shed immediately, do not retry against the sick kernel."""


class CircuitBreaker:
    """Thread-safe closed/open/half-open breaker (see module docstring)."""

    def __init__(
        self,
        name: str,
        threshold: int = 5,
        cooldown_ms: float = 1_000.0,
        halfopen_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable] = None,
    ):
        self.name = name
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = float(cooldown_ms) / 1000.0
        self.halfopen_probes = max(int(halfopen_probes), 1)
        self.clock = clock
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._tripped_at: Optional[float] = None  # first trip of this episode
        self._probes_inflight = 0
        self.trips = 0
        self.recoveries = 0
        self.open_sheds = 0  # allow() == False while open
        self.last_recovery_latency_ms: Optional[float] = None

    # -- state machine -------------------------------------------------------

    def _transition(self, new: str) -> None:
        old = self._state
        if old == new:
            return
        self._state = new
        if self.on_transition is not None:
            try:
                self.on_transition(self.name, old, new, self.snapshot())
            except Exception:
                pass  # observers never break the serve path

    def allow(self) -> bool:
        """May a dispatch be attempted right now? Open→half-open happens
        here once the cooldown has elapsed; half-open admits at most
        ``halfopen_probes`` concurrent probes."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self.clock() - self._opened_at >= self.cooldown_s:
                    self._transition(HALF_OPEN)
                    self._probes_inflight = 1
                    return True
                self.open_sheds += 1
                return False
            # HALF_OPEN
            if self._probes_inflight < self.halfopen_probes:
                self._probes_inflight += 1
                return True
            self.open_sheds += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state == HALF_OPEN:
                self._probes_inflight = max(self._probes_inflight - 1, 0)
                if self._tripped_at is not None:
                    self.last_recovery_latency_ms = (
                        (self.clock() - self._tripped_at) * 1e3)
                self.recoveries += 1
                self._opened_at = None
                self._tripped_at = None
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # the probe found the kernel still sick: re-open, new cooldown
                self._probes_inflight = max(self._probes_inflight - 1, 0)
                self._opened_at = self.clock()
                self._transition(OPEN)
                return
            self._consecutive += 1
            if self._state == CLOSED and self._consecutive >= self.threshold:
                self.trips += 1
                now = self.clock()
                self._opened_at = now
                self._tripped_at = now
                self._transition(OPEN)

    # -- introspection -------------------------------------------------------

    @property
    def state(self) -> str:
        """Current state; open→half-open promotion happens lazily in
        :meth:`allow`, so a cooled-down breaker still reads ``open`` here
        until the next request probes it."""
        with self._lock:
            return self._state

    def snapshot(self) -> Dict:
        # called with or without the lock held (on_transition fires inside
        # it) — reads of ints/strs are atomic enough for a status report
        open_for_ms = None
        if self._opened_at is not None:
            open_for_ms = round((self.clock() - self._opened_at) * 1e3, 3)
        return {
            "state": self._state,
            "consecutive_failures": self._consecutive,
            "threshold": self.threshold,
            "cooldown_ms": round(self.cooldown_s * 1e3, 3),
            "trips": self.trips,
            "recoveries": self.recoveries,
            "open_sheds": self.open_sheds,
            "open_for_ms": open_for_ms,
            "last_recovery_latency_ms": (
                round(self.last_recovery_latency_ms, 3)
                if self.last_recovery_latency_ms is not None else None),
        }
