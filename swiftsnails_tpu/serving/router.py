"""Routing policy for the serving fleet: affinity ring, spill, hedge budget.

The reference deployment put a consistent-hash layer in front of its servant
pool so a key's pull traffic always lands on the same replica (the agent-side
``hashfrag`` routing, ``src/core/parameter/hashfrag.h:48-53``, applied here to
*replicas* instead of shards). That affinity is what makes a per-replica
hot-row LRU pay: under the zipf skew measured in PR 11, N replicas that each
see a 1/N slice of the anchor space keep their slice's head rows warm, where
random spraying makes all N caches fight over the same global head and
cold-miss the rest.

This module is the pure-policy half of the fleet (no threads, no Servants):

* :class:`HashRing` — consistent-hash ring with virtual nodes. Ring points
  use the same murmur fmix64 mixer as key->row placement
  (:mod:`swiftsnails_tpu.ops.hashing`) so ownership is reproducible across
  processes and restarts; adding or removing one replica only moves the keys
  adjacent to its vnode points (elastic add/drain).
* :func:`spill_order` — bounded-load-factor spill (Mirrokni et al.'s
  "consistent hashing with bounded loads"): the owner serves a key unless its
  load exceeds ``spill x fleet-mean``, in which case the request walks the
  ring to the next under-cap node. Affinity is preserved in the common case;
  a hot replica sheds overflow instead of queueing it.
* :class:`EwmaQuantile` — EWMA-smoothed windowed quantile; tracks the
  per-kernel p95 the hedge timer arms against.
* :class:`HedgeGovernor` — caps the hedge rate at ``serve_hedge_budget_pct``
  of observed requests so hedges cannot storm a fleet that is slow because it
  is overloaded (hedging an overload makes it worse; hedging a straggler
  fixes it — the cap keeps the former bounded while allowing the latter).
"""

from __future__ import annotations

import bisect
import math
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from swiftsnails_tpu.ops.hashing import murmur_fmix64_int

DEFAULT_VNODES = 64
DEFAULT_SPILL = 1.5
DEFAULT_HEDGE_BUDGET_PCT = 10.0
DEFAULT_HEDGE_P95_MS = 25.0

_GOLDEN = 0x9E3779B97F4A7C15  # vnode index mixer (Fibonacci hashing constant)
_MASK64 = (1 << 64) - 1


def _str64(s: str) -> int:
    """Fold a node/replica id into 64 bits, order-sensitively."""
    h = len(s) & _MASK64
    for ch in s.encode("utf-8"):
        h = ((h * 131) + ch) & _MASK64  # the reference's BKDR string fold
    return h


def route_hash(key) -> int:
    """Request key (row id, anchor int, or string) -> 64-bit ring position.

    Ints go straight through the murmur finalizer — the same mixer that
    places the key's row — so replica affinity and row placement share one
    hash family end to end.
    """
    if isinstance(key, str):
        return murmur_fmix64_int(_str64(key))
    return murmur_fmix64_int(int(key))


class HashRing:
    """Consistent-hash ring over replica ids with ``vnodes`` points each.

    Deterministic: two rings built from the same member set (in any insertion
    order) place every key identically — ownership tests and cross-process
    routing rely on it. Not thread-safe by itself; the Fleet mutates it under
    its own lock.
    """

    def __init__(self, vnodes: int = DEFAULT_VNODES):
        self.vnodes = max(1, int(vnodes))
        self._points: List[int] = []       # sorted ring positions
        self._owner_at: Dict[int, str] = {}  # position -> node id
        self._nodes: set = set()

    def _node_points(self, node: str) -> List[int]:
        base = _str64(node)
        return [
            murmur_fmix64_int((base + i * _GOLDEN) & _MASK64)
            for i in range(self.vnodes)
        ]

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for p in self._node_points(node):
            # collisions across nodes are ~2^-64; keep first owner if one hits
            if p in self._owner_at:
                continue
            bisect.insort(self._points, p)
            self._owner_at[p] = node

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        for p in self._node_points(node):
            if self._owner_at.get(p) == node:
                del self._owner_at[p]
                i = bisect.bisect_left(self._points, p)
                if i < len(self._points) and self._points[i] == p:
                    self._points.pop(i)

    def members(self) -> List[str]:
        return sorted(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, node: str) -> bool:
        return node in self._nodes

    def owner(self, key_hash: int) -> Optional[str]:
        order = self.successors(key_hash)
        return order[0] if order else None

    def successors(self, key_hash: int) -> List[str]:
        """All member nodes in ring order starting at the key's owner.

        Position 0 is the affinity owner; position 1 is "the next ring
        replica" that spill and hedging escalate to; and so on — one
        deterministic escalation order per key.
        """
        if not self._points:
            return []
        i = bisect.bisect_right(self._points, key_hash & _MASK64)
        seen: List[str] = []
        n = len(self._points)
        for j in range(n):
            node = self._owner_at[self._points[(i + j) % n]]
            if node not in seen:
                seen.append(node)
                if len(seen) == len(self._nodes):
                    break
        return seen


def spill_order(
    ordered: Sequence,
    load_of: Callable[[object], int],
    *,
    spill: float = DEFAULT_SPILL,
    active: Optional[int] = None,
) -> Tuple[List, bool, int]:
    """Bounded-load-factor spill over a ring-ordered candidate list.

    A node may carry at most ``cap = ceil(spill x (total_load + 1) / active)``
    in-flight/queued requests; the first candidate under cap leads the
    returned order (affinity owner in the common case). When every candidate
    is at cap the owner keeps the request — the fleet is uniformly loaded and
    moving the key elsewhere would only shed affinity, not queueing; the
    engine's bounded admission queue is the real backstop.

    Returns ``(reordered, spilled, cap)``.
    """
    ordered = list(ordered)
    if len(ordered) <= 1:
        return ordered, False, max(1, int(math.ceil(spill)))
    n = active if active is not None else len(ordered)
    total = sum(load_of(r) for r in ordered) + 1  # +1: the request being placed
    cap = max(1, int(math.ceil(spill * total / max(n, 1))))
    for idx, r in enumerate(ordered):
        if load_of(r) < cap:
            return ordered[idx:] + ordered[:idx], idx > 0, cap
    return ordered, False, cap


def route_annotation(
    ordered: Sequence[str],
    picked: Sequence[str],
    *,
    affinity: bool,
    last_resort: bool = False,
) -> Dict:
    """The owner-vs-spill routing decision, as flat trace-annotation facts.

    ``ordered`` is the pre-spill candidate order (ring successors or the
    least-loaded spray), ``picked`` the post-spill order actually used.
    Pure policy-to-telemetry glue: the fleet attaches the returned dict to
    the request's trace so a re-read of one anomaly trace answers "did the
    owner serve this, or did it spill — and to whom?".
    """
    owner = ordered[0] if ordered else None
    target = picked[0] if picked else None
    return {
        "route_owner": owner,
        "route_target": target,
        "route_spilled": bool(affinity and owner is not None
                              and target != owner),
        "route_affinity": bool(affinity),
        "route_last_resort": bool(last_resort),
    }


class EwmaQuantile:
    """EWMA-smoothed windowed quantile — the hedge timer's p95 estimate.

    A plain EWMA of latencies tracks the *mean*; hedging needs the tail, so
    each observation recomputes the quantile over a sliding window and folds
    it into an EWMA (``alpha``) for stability. Until ``min_samples`` have
    arrived the estimate stays at ``initial`` (the ``serve_hedge_p95_ms``
    floor) so a cold fleet doesn't hedge off two lucky samples.
    """

    def __init__(
        self,
        q: float = 0.95,
        initial: float = DEFAULT_HEDGE_P95_MS,
        alpha: float = 0.25,
        window: int = 64,
        min_samples: int = 8,
    ):
        self.q = float(q)
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self._win: "deque[float]" = deque(maxlen=int(window))
        self._est = float(initial)
        self._warm = False
        self._lock = threading.Lock()

    def observe(self, x: float) -> None:
        with self._lock:
            self._win.append(float(x))
            if len(self._win) < self.min_samples:
                return
            s = sorted(self._win)
            wq = s[min(int(self.q * (len(s) - 1)), len(s) - 1)]
            if not self._warm:
                self._est = wq  # first full estimate replaces the floor
                self._warm = True
            else:
                self._est = (1.0 - self.alpha) * self._est + self.alpha * wq

    @property
    def value(self) -> float:
        with self._lock:
            return self._est

    @property
    def samples(self) -> int:
        with self._lock:
            return len(self._win)


class HedgeGovernor:
    """Caps hedges at ``budget_pct`` of observed requests (0 disables).

    The check is cumulative and race-tolerant: a hedge is allowed while
    ``hedged + 1 <= budget_pct/100 x requests``, so early in a run (few
    requests observed) no hedge fires at all — a deliberate cold-start bias
    toward not amplifying load before the fleet's latency profile is known.
    """

    def __init__(self, budget_pct: float = DEFAULT_HEDGE_BUDGET_PCT):
        self.budget_pct = float(budget_pct)
        self.requests = 0
        self.hedged = 0
        self._lock = threading.Lock()

    def note_request(self) -> None:
        with self._lock:
            self.requests += 1

    def allow(self) -> bool:
        if self.budget_pct <= 0:
            return False
        with self._lock:
            return (self.hedged + 1) <= self.budget_pct / 100.0 * self.requests

    def note_hedge(self) -> None:
        with self._lock:
            self.hedged += 1

    @property
    def rate_pct(self) -> float:
        with self._lock:
            return 100.0 * self.hedged / self.requests if self.requests else 0.0

    def snapshot(self) -> Dict:
        with self._lock:
            rate = 100.0 * self.hedged / self.requests if self.requests else 0.0
            return {
                "budget_pct": self.budget_pct,
                "requests": self.requests,
                "hedged": self.hedged,
                "rate_pct": round(rate, 3),
            }
