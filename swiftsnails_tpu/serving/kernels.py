"""The three jitted query kernels of the serving read path.

Every kernel operates on a *normalized* read-only table — a dense 2-D
``[capacity, dim]`` float array produced at load time by
:func:`swiftsnails_tpu.serving.engine.normalize_tables` from whatever plane
the trainer checkpointed (2-D, word2vec packed ``[C, S, 128]``, or the CTR
small-row packed ``[T, S, 128]``). Normalization is an exact lane select, so
the f32 wire keeps serving pulls bit-identical to the checkpointed rows.

* :func:`pull_rows` — batched embedding lookup. Under a mesh it reuses the
  training stack's pull collective (``parallel/transfer.pull_collective``:
  shard-local gather + psum over ``model``) with the same ``comm_dtype``
  wire compression; single-device it is the XLA gather with the equivalent
  wire cast.
* :func:`topk_tiled` — tiled on-device scan over the full table (the
  serving twin of ``tools/eval_embeddings.py``'s NumPy scan): per-tile
  matmul + running top-k merge via ``lax.scan``, so the score matrix never
  materializes beyond one ``[B, tile_rows]`` block.
* :func:`ctr_logits` — the registry CTR models' forward pass over pulled
  rows (mask semantics identical to training: PAD=-1 fields contribute
  nothing).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from swiftsnails_tpu.parallel.comm import (
    dequantize_int4,
    dequantize_int8,
    int4_block,
    is_int4,
    quantize_int4,
    quantize_int8,
    resolve_comm_dtype,
)
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS  # noqa: F401
from swiftsnails_tpu.parallel.store import TableState


def _wire_cast(vals: jax.Array, comm_dtype: str) -> jax.Array:
    """Single-device twin of the collective wire: the same precision loss
    the psum-over-model applies, so a 1-chip servant and a mesh servant
    answer identically for a given ``comm_dtype``. f32 is a no-op
    (bit-identical pulls). int8/int4 round deterministically — the pull
    path never dithers (psum_quantized quantizes without a seed), so the
    round trip here matches the owner-exclusive psum exactly."""
    if comm_dtype == "bfloat16":
        return vals.astype(jnp.bfloat16).astype(vals.dtype)
    if comm_dtype == "int8":
        q, scale = quantize_int8(vals)
        return dequantize_int8(q, scale).astype(vals.dtype)
    if is_int4(comm_dtype):
        blk = int4_block(comm_dtype)
        packed, scales = quantize_int4(vals, block=blk)
        return dequantize_int4(packed, scales, vals.shape,
                               block=blk).astype(vals.dtype)
    return vals  # float32: exact


def pull_rows(
    table: jax.Array,
    rows: jax.Array,
    mesh=None,
    comm_dtype: str = "float32",
) -> jax.Array:
    """[N] row ids -> [N, dim] rows of a normalized read-only table."""
    comm_dtype = resolve_comm_dtype(comm_dtype)
    if mesh is not None:
        from swiftsnails_tpu.parallel.transfer import pull_collective

        return pull_collective(
            mesh, TableState(table=table, slots={}), rows, comm_dtype
        )
    vals = table.at[rows].get(mode="promise_in_bounds")
    return _wire_cast(vals, comm_dtype)


@partial(jax.jit, static_argnames=("k", "tile_rows", "normalize"))
def topk_tiled(
    table: jax.Array,
    queries: jax.Array,
    k: int,
    tile_rows: int = 4096,
    normalize: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k rows of ``table`` by dot-product score against ``queries``.

    ``table`` [C, D], ``queries`` [B, D] -> (scores [B, k], ids [B, k]),
    scores descending. With ``normalize`` both sides are L2-normalized
    (cosine similarity — the eval tool's semantics); pass False to rank raw
    inner products. The scan walks ``tile_rows``-row tiles carrying the
    running best-k, so peak memory is one [B, tile_rows] score block
    regardless of capacity.
    """
    c, d = table.shape
    b = queries.shape[0]
    k = min(k, c)
    q = queries.astype(jnp.float32)
    if normalize:
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-9)
    tile_rows = min(tile_rows, c)
    n_tiles = -(-c // tile_rows)
    pad = n_tiles * tile_rows - c
    tbl = table.astype(jnp.float32)
    if pad:
        tbl = jnp.pad(tbl, ((0, pad), (0, 0)))
    if normalize:
        tbl = tbl / jnp.maximum(
            jnp.linalg.norm(tbl, axis=-1, keepdims=True), 1e-9
        )
    tiles = tbl.reshape(n_tiles, tile_rows, d)
    bases = jnp.arange(n_tiles, dtype=jnp.int32) * tile_rows

    def body(carry, inp):
        best_s, best_i = carry
        tile, base = inp
        scores = q @ tile.T  # [B, tile_rows]
        ids = base + jnp.arange(tile_rows, dtype=jnp.int32)
        scores = jnp.where(ids[None, :] < c, scores, -jnp.inf)
        cat_s = jnp.concatenate([best_s, scores], axis=1)
        cat_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(ids[None, :], (b, tile_rows))], axis=1
        )
        top_s, sel = jax.lax.top_k(cat_s, k)
        top_i = jnp.take_along_axis(cat_i, sel, axis=1)
        return (top_s, top_i), None

    init = (
        jnp.full((b, k), -jnp.inf, jnp.float32),
        jnp.full((b, k), -1, jnp.int32),
    )
    (best_s, best_i), _ = jax.lax.scan(body, init, (tiles, bases))
    return best_s, best_i


def ctr_logits(
    forward: Callable[[jax.Array, Any, jax.Array], jax.Array],
    pulled: jax.Array,
    dense: Any,
    mask: jax.Array,
) -> jax.Array:
    """Registry-model forward over pulled rows -> logits [B]."""
    return forward(pulled, dense, mask)


def ctr_scores(
    forward: Callable[[jax.Array, Any, jax.Array], jax.Array],
    pulled: jax.Array,
    dense: Any,
    mask: jax.Array,
) -> jax.Array:
    """CTR probability scores: sigmoid of the model logits."""
    return jax.nn.sigmoid(ctr_logits(forward, pulled, dense, mask))
