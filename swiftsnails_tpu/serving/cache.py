"""LRU hot-row cache keyed on table version.

Zipf traffic concentrates on head keys; serving them from a host-side LRU
short-circuits the device pull (and, under a mesh, the collective) entirely.
Correctness rules:

* Entries are keyed ``(table_name, row_id)`` and stamped with the table
  **version** the row was pulled at. A version bump (table reload) makes
  every older entry a miss — stale rows can never be served *fresh* after a
  reload. They stay in the LRU though (overwritten by the next fresh pull or
  aged out by capacity): :meth:`HotRowCache.get_stale` reads them for
  DEGRADED serves when the pull kernel's circuit breaker is open.
* The micro-batcher's pad sentinel (row id 0 in the pad tail) must never be
  inserted: the engine only inserts the rows of *real* requests, and
  ``put`` additionally drops rows explicitly flagged as padding.

Thread-safe: the servant's dispatcher inserts while request threads read.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np


class HotRowCache:
    """Bounded LRU of ``(table, row) -> (version, row_values)``."""

    def __init__(self, capacity_rows: int):
        self.capacity = int(capacity_rows)
        self._rows: "OrderedDict[Tuple[str, int], Tuple[int, np.ndarray]]" = (
            OrderedDict()
        )
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._rows)

    def get_many(
        self, table: str, version: int, ids: np.ndarray
    ) -> Tuple[Dict[int, np.ndarray], List[int]]:
        """(found id -> row, missing ids). Counts one hit/miss per id."""
        if self.capacity <= 0:
            self.misses += len(ids)
            return {}, [int(i) for i in ids]
        found: Dict[int, np.ndarray] = {}
        missing: List[int] = []
        with self._lock:
            for i in ids:
                i = int(i)
                entry = self._rows.get((table, i))
                if entry is not None and entry[0] == version:
                    self._rows.move_to_end((table, i))
                    found[i] = entry[1]
                    self.hits += 1
                else:
                    # A version-stale entry is a miss but is NOT evicted: it
                    # is the inventory for degraded reads (breaker open after
                    # a reload). The fresh put overwrites it; otherwise plain
                    # LRU pressure ages it out.
                    missing.append(i)
                    self.misses += 1
        return found, missing

    def put_many(
        self,
        table: str,
        version: int,
        ids: np.ndarray,
        rows: np.ndarray,
        pad_mask: Optional[np.ndarray] = None,
    ) -> int:
        """Insert pulled rows; returns how many were admitted.

        ``pad_mask`` marks micro-batch padding rows (sentinel id 0) — those
        are dropped here as a second line of defense even if a caller hands
        the full padded batch over.
        """
        if self.capacity <= 0:
            return 0
        admitted = 0
        with self._lock:
            for n, i in enumerate(ids):
                if pad_mask is not None and pad_mask[n]:
                    continue
                key = (table, int(i))
                self._rows[key] = (int(version), np.asarray(rows[n]))
                self._rows.move_to_end(key)
                admitted += 1
            while len(self._rows) > self.capacity:
                self._rows.popitem(last=False)
        return admitted

    def get_stale(
        self, table: str, ids: np.ndarray
    ) -> Tuple[Dict[int, np.ndarray], List[int]]:
        """Version-agnostic, side-effect-free peek for DEGRADED reads only
        (circuit breaker open / kernel dispatch failed): returns whatever the
        LRU still holds for ``ids`` regardless of the version stamp.

        Deliberately touches nothing — no hit/miss counters (degraded serves
        are accounted separately and never mixed into the fresh-path stats),
        no eviction, no LRU reordering (the fresh traffic alone decides what
        stays hot)."""
        found: Dict[int, np.ndarray] = {}
        missing: List[int] = []
        with self._lock:
            for i in ids:
                i = int(i)
                entry = self._rows.get((table, i))
                if entry is not None:
                    found[i] = entry[1]
                else:
                    missing.append(i)
        return found, missing

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0
