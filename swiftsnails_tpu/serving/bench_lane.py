"""The bench ``serve`` lane: latency-SLO numbers for the read path.

One implementation used by ``bench.py --lane serve`` and
``tests/test_serving.py``'s lane smoke test. It builds two tiny *verified*
checkpoints (a packed word2vec table and a packed-small logreg table), loads
each through the real :meth:`Servant.from_checkpoint` path, and drives all
three query kernels — pull, top-k, CTR score — at two batch buckets,
reporting qps and p50/p95/p99 latency per (kernel, bucket) plus cache hit
rate and shed count. Latency distribution is correctness of the serving
machinery, not raw device speed, so the lane is valid on CPU; the block
lands in the bench JSON (``serving``), the run ledger, and the
``ledger-report --check-regression`` gate.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Dict, Optional, Sequence

import numpy as np

SERVE_SEED = 11


def _build_word2vec_checkpoint(root: str, dim: int, capacity: int):
    """Init (no training needed — serving is layout + lookup) and save a
    verified packed word2vec checkpoint; returns its serving config."""
    from swiftsnails_tpu.framework.checkpoint import save_checkpoint
    from swiftsnails_tpu.framework.quality import paired_corpus
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    ids, vocab = paired_corpus(n_pairs=32, reps=4, seed=SERVE_SEED)
    cfg = Config({
        "dim": str(dim), "capacity": str(capacity), "packed": "1",
        "seed": str(SERVE_SEED), "subsample": "0",
    })
    trainer = Word2VecTrainer(cfg, mesh=None, corpus_ids=ids, vocab=vocab)
    state = trainer.init_state()
    save_checkpoint(root, state, step=1, wait=True)
    return cfg


def _build_logreg_checkpoint(root: str, num_fields: int, capacity: int):
    """Init and save a verified packed-small logreg checkpoint."""
    from swiftsnails_tpu.framework.checkpoint import save_checkpoint
    from swiftsnails_tpu.models.registry import get_model
    from swiftsnails_tpu.utils.config import Config

    cfg = Config({
        "model": "logreg", "num_fields": str(num_fields),
        "capacity": str(capacity), "packed": "1", "seed": str(SERVE_SEED),
        "init_scale": "1.0",
    })
    trainer = get_model("logreg")(
        cfg, mesh=None,
        data=(np.zeros(0, np.float32), np.zeros((0, num_fields), np.int32)),
    )
    state = trainer.init_state()
    save_checkpoint(root, state, step=1, wait=True)
    return cfg


def _drive(servant, kernel: str, bucket: int, requests: int,
           rng: np.random.Generator, capacity: int,
           num_fields: int = 0) -> Dict:
    """Fire ``requests`` back-to-back requests of ``bucket`` units each and
    report qps + the latency percentiles the servant observed."""
    servant.reset_metrics()
    zipf = rng.zipf(1.3, size=(requests, max(bucket, 1)))  # head-heavy ids
    t0 = time.perf_counter()
    for n in range(requests):
        if kernel == "pull":
            ids = np.minimum(zipf[n], capacity - 1).astype(np.int32)
            servant.pull(ids[:bucket])
        elif kernel == "topk":
            q = rng.standard_normal(
                servant._tables[servant.default_table].shape[1]
            ).astype(np.float32)
            servant.topk(q)
        else:  # score
            feats = np.minimum(zipf[n, :num_fields], capacity - 1)
            servant.score(
                np.broadcast_to(feats, (bucket, num_fields)).astype(np.int32))
    dt = max(time.perf_counter() - t0, 1e-9)
    stats = servant.stats()["kernels"][kernel]
    return {
        "requests": requests,
        "bucket": bucket,
        "qps": round(requests / dt, 2),
        "p50_ms": stats["p50_ms"],
        "p95_ms": stats["p95_ms"],
        "p99_ms": stats["p99_ms"],
    }


def serve_bench(
    small: bool = False,
    workdir: Optional[str] = None,
    ledger=None,
    buckets: Sequence[int] = (8, 64),
) -> Dict:
    """Run the serve lane; returns the ``serving`` block for the bench JSON.

    Headline fields (gated by ``ledger-report --check-regression``):
    ``qps`` (pull at the largest bucket) and ``p99_ms`` (same leg).
    """
    from swiftsnails_tpu.serving.engine import Servant

    dim = 16 if small else 64
    capacity = 1 << (9 if small else 12)
    requests = 8 if small else 40
    rng = np.random.default_rng(SERVE_SEED)
    buckets = tuple(sorted(int(b) for b in buckets))

    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ssn-serve-bench-")
        workdir = own_tmp.name
    try:
        w2v_root = os.path.join(workdir, "ckpt-w2v")
        ctr_root = os.path.join(workdir, "ckpt-ctr")
        w2v_cfg = _build_word2vec_checkpoint(w2v_root, dim, capacity)
        num_fields = 8
        ctr_cfg = _build_logreg_checkpoint(ctr_root, num_fields, capacity)

        kernels: Dict[str, Dict] = {"pull": {}, "topk": {}, "ctr_score": {}}
        with Servant.from_checkpoint(
            w2v_root, w2v_cfg, batch_buckets=buckets, ledger=ledger,
        ) as served:
            step = served.step
            for b in buckets:
                kernels["pull"][f"b{b}"] = _drive(
                    served, "pull", b, requests, rng, capacity)
                kernels["topk"][f"b{b}"] = _drive(
                    served, "topk", b, max(requests // 4, 2), rng, capacity)
            # cache behavior over the whole pull run (zipf head re-hits)
            cache_stats = served.stats()["cache"]
            # hit rate over a fresh repeated working set: deterministic
            served.reset_metrics()
            hot = np.arange(min(64, capacity), dtype=np.int32)
            for _ in range(4):
                served.pull(hot)
            cache_hit_rate = served.stats()["cache"]["hit_rate"]
            shed = served.shed_count()

        with Servant.from_checkpoint(
            ctr_root, ctr_cfg, batch_buckets=buckets, ledger=ledger,
        ) as scorer:
            for b in buckets:
                kernels["ctr_score"][f"b{b}"] = _drive(
                    scorer, "score", b, requests, rng, capacity,
                    num_fields=num_fields)
            shed += scorer.shed_count()

        head = kernels["pull"][f"b{buckets[-1]}"]
        return {
            "checkpoint_step": step,
            "buckets": list(buckets),
            "small": bool(small),
            "kernels": kernels,
            "qps": head["qps"],
            "p99_ms": head["p99_ms"],
            "cache_hit_rate": round(float(cache_hit_rate), 4),
            "cache_rows": cache_stats.get("rows", 0),
            "shed_count": int(shed),
        }
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()
