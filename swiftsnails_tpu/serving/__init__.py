"""Query-only serving runtime: the read path of the parameter server.

Load a verified checkpoint into read-only sharded tables and serve three
jitted query kernels — row pull, top-k nearest-neighbor, CTR score — behind
a micro-batcher with a hot-row LRU cache and bounded-queue admission
control. Availability hardening: per-kernel circuit breakers with
degraded-mode (stale-LRU) reads and typed :class:`Unavailable` sheds.
Horizontal scale: a :class:`Fleet` of replicas sharing the loaded planes
behind a consistent-hash affinity router with bounded spill, tail-latency
hedging, and elastic add/drain. See ``docs/SERVING.md``.
"""

from swiftsnails_tpu.serving.breaker import CircuitBreaker, Unavailable
from swiftsnails_tpu.serving.cache import HotRowCache
from swiftsnails_tpu.serving.engine import (
    MicroBatcher,
    Overloaded,
    Servant,
    bucket_for,
    normalize_table,
)
from swiftsnails_tpu.serving.fleet import Fleet, Replica
from swiftsnails_tpu.serving.loadgen import run_open_loop
from swiftsnails_tpu.serving.router import (
    EwmaQuantile,
    HashRing,
    HedgeGovernor,
    route_hash,
    spill_order,
)
from swiftsnails_tpu.serving.kernels import (
    ctr_logits,
    ctr_scores,
    pull_rows,
    topk_tiled,
)

__all__ = [
    "CircuitBreaker",
    "EwmaQuantile",
    "Fleet",
    "HashRing",
    "HedgeGovernor",
    "HotRowCache",
    "MicroBatcher",
    "Overloaded",
    "Replica",
    "Servant",
    "Unavailable",
    "bucket_for",
    "ctr_logits",
    "ctr_scores",
    "normalize_table",
    "pull_rows",
    "route_hash",
    "run_open_loop",
    "spill_order",
    "topk_tiled",
]
