"""The serving fleet: N Servant replicas behind an affinity/hedging router.

A single :class:`~swiftsnails_tpu.serving.engine.Servant` is one admission
queue, one hot-row LRU, one jit cache — its QPS is the fleet ceiling no
matter how fast the kernels are. The reference system scaled reads by
running many servant processes behind a key-hash router (PAPER §0 serves
"heavy traffic from millions of users"); :class:`Fleet` is the in-process
analog: N replicas sharing the *same* loaded checkpoint planes (device
arrays are immutable — replication costs threads and per-replica caches,
not table memory) behind four routing layers:

1. **Affinity** (:class:`~swiftsnails_tpu.serving.router.HashRing`):
   ``pull``/``topk`` requests route by their hashed key slice so each
   replica's version-keyed hot-row LRU stays warm for its 1/N of the
   anchor space. ``score`` has no key identity and routes least-loaded.
2. **Bounded spill** (:func:`~swiftsnails_tpu.serving.router.spill_order`):
   a deep-queued owner sheds overflow to the next ring node instead of
   queueing it (``serve_ring_spill`` load factor).
3. **Hedging**: when a request outlives the EWMA-tracked per-kernel p95
   (``serve_hedge_p95_ms`` floor), it is duplicated to the next ring
   replica; first writer wins, the loser's answer is discarded when it
   lands (an in-flight micro-batch cannot be revoked — the *result* is
   cancelled, not the kernel). ``serve.hedged`` / ``serve.hedge_won``
   count both edges and :class:`~swiftsnails_tpu.serving.router.HedgeGovernor`
   caps the hedge rate at ``serve_hedge_budget_pct``.
4. **Breaker awareness**: replicas whose per-kernel breaker (PR 8) is open
   sort to the back of every candidate list — a degraded replica serves
   only when it is the last one standing. A typed
   :class:`~swiftsnails_tpu.serving.breaker.Unavailable` /
   :class:`~swiftsnails_tpu.serving.engine.Overloaded` from the winner
   triggers one synchronous re-route to the next healthy candidate.

**Elastic add/drain.** :meth:`Fleet.add_replica` spins a fresh replica over
the shared planes and splices its vnodes into the ring (only adjacent keys
move). :meth:`Fleet.drain` removes the replica from the ring first — new
requests re-route immediately — then blocks until its in-flight requests
finish before closing it: connection draining, no mid-request kills. Both
edges land in the run ledger as ``drain`` events.

Per-replica injectable hooks (``Replica.request_hook`` at admission, the
engine's ``Servant.fault_hook`` at dispatch) are the chaos/bench seam: the
fleet lane models device service time with them, the chaos drill slows or
kills exactly one replica through them.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from swiftsnails_tpu.serving.breaker import OPEN, Unavailable
from swiftsnails_tpu.serving.engine import (
    DEFAULT_BREAKER_COOLDOWN_MS,
    DEFAULT_BREAKER_PROBES,
    DEFAULT_BREAKER_THRESHOLD,
    Overloaded,
    Servant,
    _normalize_state_tables,
)
from swiftsnails_tpu.serving.router import (
    DEFAULT_HEDGE_BUDGET_PCT,
    DEFAULT_HEDGE_P95_MS,
    DEFAULT_SPILL,
    DEFAULT_VNODES,
    EwmaQuantile,
    HashRing,
    HedgeGovernor,
    route_annotation,
    route_hash,
    spill_order,
)
from swiftsnails_tpu.telemetry import request_trace

ACTIVE = "active"
DRAINING = "draining"
CLOSED = "closed"

_KERNELS = ("pull", "topk", "score")
_REQUEST_TIMEOUT_S = 120.0


class Replica:
    """One Servant plus the fleet's view of it: id, lifecycle state,
    in-flight accounting (what drain waits on), and the injectable
    per-replica ``request_hook(kernel)`` — called on the fleet worker
    thread at admission, before the servant sees the request; it may stall
    (a slow replica) or raise (a sick one)."""

    __slots__ = ("id", "servant", "state", "inflight", "request_hook",
                 "requests", "_cv")

    def __init__(self, rid: str, servant: Servant):
        self.id = rid
        self.servant = servant
        self.state = ACTIVE
        self.inflight = 0
        self.requests = 0
        self.request_hook: Optional[Callable[[str], None]] = None
        self._cv = threading.Condition()

    def begin(self) -> None:
        with self._cv:
            self.inflight += 1
            self.requests += 1

    def end(self) -> None:
        with self._cv:
            self.inflight -= 1
            if self.inflight <= 0:
                self._cv.notify_all()

    def wait_idle(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cv:
            while self.inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=left)
            return True

    def load(self, kernel: str) -> int:
        """Fleet-visible load: requests the fleet has admitted but not
        finished, plus what is already queued inside the engine (the
        queue-depth introspection the spill policy keys on)."""
        return self.inflight + self.servant.queue_depths().get(kernel, 0)


class _Flight:
    """First-writer-wins rendezvous between a primary and its hedge."""

    __slots__ = ("done", "winner", "errors", "pending", "_lock")

    def __init__(self):
        self.done = threading.Event()
        self.winner = None  # (replica_id, result, hedged)
        self.errors: List[BaseException] = []
        self.pending = 0
        self._lock = threading.Lock()

    def arm(self) -> None:
        with self._lock:
            self.pending += 1

    def complete(self, rid: str, result, error, hedged: bool) -> bool:
        """Record one leg's outcome; returns True iff this leg won."""
        with self._lock:
            self.pending -= 1
            if error is None and self.winner is None:
                self.winner = (rid, result, hedged)
                self.done.set()
                return True
            if error is not None:
                self.errors.append(error)
            if self.pending == 0 and self.winner is None:
                self.done.set()  # all legs failed: release the caller
            return False


class Fleet:
    """N replicas, one query API (``pull``/``topk``/``score`` mirror the
    Servant's signatures, plus an optional explicit ``key=`` affinity
    override).

    ``factory(replica_id) -> Servant`` builds each replica; pass ``first``
    to adopt an already-constructed Servant as replica 0 (how
    :meth:`from_checkpoint` avoids loading the planes twice). ``registry``
    holds the fleet-level counters/histograms; each Servant keeps its own
    per-replica registry.
    """

    def __init__(
        self,
        factory: Callable[[str], Servant],
        *,
        replicas: int = 1,
        first: Optional[Servant] = None,
        registry=None,
        ledger=None,
        hedge_budget_pct: float = DEFAULT_HEDGE_BUDGET_PCT,
        hedge_p95_ms: float = DEFAULT_HEDGE_P95_MS,
        ring_spill: float = DEFAULT_SPILL,
        vnodes: int = DEFAULT_VNODES,
        affinity: bool = True,
        max_inflight: int = 64,
        clock: Callable[[], float] = time.perf_counter,
        request_tracer=None,
        slo=None,
    ):
        if replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        if registry is None:
            from swiftsnails_tpu.telemetry.registry import MetricRegistry

            registry = MetricRegistry()
        self.registry = registry
        self.ledger = ledger
        # ops plane: one fleet-level RequestTracer owns each request's span
        # tree (per-attempt child spans ride in from replica servants via
        # the thread-local context); one SloTracker burns the error budget.
        self.request_tracer = request_tracer
        self.slo = slo
        self.affinity = bool(affinity)
        self.ring_spill = float(ring_spill)
        self.hedge_p95_ms = float(hedge_p95_ms)
        self._factory = factory
        self._clock = clock
        self._lock = threading.Lock()
        self._next_rid = 0
        self._rr = 0  # round-robin cursor for keyless (no-affinity) routing
        self._replicas: Dict[str, Replica] = {}
        self._ring = HashRing(vnodes=vnodes)
        self._gov = HedgeGovernor(hedge_budget_pct)
        self._p95 = {k: EwmaQuantile(initial=hedge_p95_ms) for k in _KERNELS}
        self._hedge_events = 0
        self._freshness = None  # an attached DeltaSubscriber (health rollup)
        self._pool = ThreadPoolExecutor(
            max_workers=max(int(max_inflight), 2 * replicas + 2),
            thread_name_prefix="ssn-fleet",
        )
        for _ in range(replicas):
            self._add(first)
            first = None

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def from_checkpoint(
        cls,
        root: str,
        config,
        *,
        step: Optional[int] = None,
        mesh=None,
        replicas: Optional[int] = None,
        registry=None,
        ledger=None,
        **servant_kwargs,
    ) -> "Fleet":
        """Load the checkpoint ONCE, then replicate the read path.

        Replica 0 is a plain :meth:`Servant.from_checkpoint`; every further
        replica is constructed over replica 0's already-normalized (and
        already device-resident) planes — N replicas share one copy of the
        tables and differ only in batchers, caches, and breakers. Fleet
        knobs come from the same typed config: ``serve_replicas``,
        ``serve_hedge_budget_pct``, ``serve_hedge_p95_ms``,
        ``serve_ring_spill``.
        """
        # trace + SLO live at the FLEET level (one trace per request, one
        # budget per fleet); replicas join the active context instead of
        # minting their own, so their servants get neither
        from swiftsnails_tpu.telemetry.request_trace import RequestTracer
        from swiftsnails_tpu.telemetry.slo import SloTracker

        tracer = servant_kwargs.pop(
            "request_tracer", None) or RequestTracer.from_config(
                config, ledger=ledger, source="fleet")
        slo = servant_kwargs.pop(
            "slo", None) or SloTracker.from_config(
                config, ledger=ledger, source="fleet")
        proto = Servant.from_checkpoint(
            root, config, step=step, mesh=mesh, ledger=ledger,
            request_tracer=None, slo=None, **servant_kwargs)
        n = int(replicas) if replicas is not None else \
            config.get_int("serve_replicas", 1)

        def factory(rid: str) -> Servant:
            return Servant(
                proto._tables,
                manifest=proto.manifest,
                mesh=proto.mesh,
                scorer=proto.scorer,
                dense=proto._dense,
                default_table=proto.default_table,
                ledger=ledger,
                batch_buckets=proto.buckets,
                cache_rows=proto.cache.capacity,
                queue_depth=proto._batchers["pull"].queue_depth,
                comm_dtype=proto.comm_dtype,
                topk=proto.topk_default,
                topk_tile_rows=proto.topk_tile_rows,
                tier_hbm_budget_mb=proto.tier_budget_mb,
                breaker_threshold=config.get_int(
                    "breaker_threshold", DEFAULT_BREAKER_THRESHOLD),
                breaker_cooldown_ms=config.get_float(
                    "breaker_cooldown_ms", DEFAULT_BREAKER_COOLDOWN_MS),
                breaker_halfopen_probes=config.get_int(
                    "breaker_halfopen_probes", DEFAULT_BREAKER_PROBES),
                degraded=config.get_bool("serve_degraded", True),
            )

        return cls(
            factory,
            replicas=n,
            first=proto,
            registry=registry,
            ledger=ledger,
            hedge_budget_pct=config.get_float(
                "serve_hedge_budget_pct", DEFAULT_HEDGE_BUDGET_PCT),
            hedge_p95_ms=config.get_float(
                "serve_hedge_p95_ms", DEFAULT_HEDGE_P95_MS),
            ring_spill=config.get_float("serve_ring_spill", DEFAULT_SPILL),
            request_tracer=tracer,
            slo=slo,
        )

    def _add(self, servant: Optional[Servant] = None) -> Replica:
        with self._lock:
            rid = f"r{self._next_rid}"
            self._next_rid += 1
        rep = Replica(rid, servant if servant is not None else
                      self._factory(rid))
        with self._lock:
            self._replicas[rid] = rep
            self._ring.add(rid)
        return rep

    def add_replica(self) -> str:
        """Elastic scale-up: a new replica over the shared planes joins the
        ring; only the keys adjacent to its vnode points move to it."""
        rep = self._add()
        self.registry.counter("fleet.replicas_added").inc()
        return rep.id

    def drain(self, replica_id: str, timeout_s: float = 30.0) -> Dict:
        """Connection-draining removal: ring exit first (new requests
        re-route from this instant), then wait for in-flight requests to
        finish, then close the underlying servant. Returns the drain
        record; both edges land in the ledger as ``drain`` events."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.state != ACTIVE:
                raise KeyError(f"no active replica {replica_id!r}")
            rep.state = DRAINING
            self._ring.remove(replica_id)
            inflight_at_start = rep.inflight
        self._ledger_event("drain", {
            "phase": "start",
            "replica": replica_id,
            "inflight": inflight_at_start,
            "remaining_replicas": len(self._ring),
        })
        t0 = time.monotonic()
        drained = rep.wait_idle(timeout_s)
        waited_ms = (time.monotonic() - t0) * 1e3
        rep.state = CLOSED
        rep.servant.close()
        with self._lock:
            self._replicas.pop(replica_id, None)
        self.registry.counter("fleet.replicas_drained").inc()
        record = {
            "phase": "complete",
            "replica": replica_id,
            "inflight_at_start": inflight_at_start,
            "waited_ms": round(waited_ms, 3),
            "clean": bool(drained),
            "remaining_replicas": len(self._ring),
        }
        self._ledger_event("drain", record)
        return record

    def configure(
        self,
        *,
        affinity: Optional[bool] = None,
        hedge_budget_pct: Optional[float] = None,
        hedge_p95_ms: Optional[float] = None,
        ring_spill: Optional[float] = None,
    ) -> "Fleet":
        """Post-construction routing-knob override (bench legs and tests
        build control fleets this way); returns ``self`` for chaining."""
        if affinity is not None:
            self.affinity = bool(affinity)
        if hedge_budget_pct is not None:
            self._gov = HedgeGovernor(float(hedge_budget_pct))
        if hedge_p95_ms is not None:
            self.hedge_p95_ms = float(hedge_p95_ms)
            self._p95 = {k: EwmaQuantile(initial=self.hedge_p95_ms)
                         for k in _KERNELS}
        if ring_spill is not None:
            self.ring_spill = float(ring_spill)
        return self

    # -- fleet-wide epoch cutover (freshness/; docs/FRESHNESS.md) -----------
    #
    # Shared-plane swaps (delta apply, live reload) must land every replica
    # on the SAME cache version: independent per-replica bumps would let two
    # replicas disagree mid-cutover on which planes a version number means.
    # One epoch — strictly above every replica's current version — is chosen
    # up front and installed everywhere.

    @property
    def step(self) -> int:
        """Newest checkpoint/watermark step any replica serves."""
        with self._lock:
            return max((r.servant.step for r in self._replicas.values()),
                       default=0)

    @property
    def version(self) -> int:
        """The fleet cache epoch (max over replicas; equal everywhere
        outside the instants of a cutover)."""
        with self._lock:
            return max((r.servant.version for r in self._replicas.values()),
                       default=0)

    def _next_epoch(self) -> int:
        with self._lock:
            return max((r.servant.version for r in self._replicas.values()),
                       default=0) + 1

    def apply_rows(self, updates: Dict[str, Any], *,
                   step: Optional[int] = None) -> int:
        """Apply one freshness delta batch fleet-wide at a single epoch.

        Resident fleets share one set of planes, so the post-delta arrays
        are computed ONCE (``prepare_rows`` on the first replica) and the
        same arrays install into every replica — no replica ever serves a
        torn batch, and every cache cuts over to the same version. Tiered
        replicas own separate host masters and apply individually, still at
        the shared epoch."""
        epoch = self._next_epoch()
        reps = self.replicas()
        if not reps:
            raise Unavailable("fleet: no active replicas")
        first = reps[0].servant
        if first.tier_budget_mb > 0:
            for rep in reps:
                rep.servant.apply_rows(updates, version=epoch, step=step)
        else:
            new_tables = first.prepare_rows(updates)
            for rep in reps:
                rep.servant.install_tables(new_tables, version=epoch,
                                           step=step)
        return epoch

    def reload(self, tables: Dict[str, Any], manifest: Optional[Dict] = None,
               dense=None) -> int:
        """Swap new planes into every replica at one shared epoch."""
        epoch = self._next_epoch()
        for rep in self.replicas():
            rep.servant.reload(tables, manifest=manifest, dense=dense,
                               version=epoch)
        return epoch

    def reload_from_checkpoint(self, root: str, config, *,
                               step: Optional[int] = None,
                               retry=None) -> int:
        """The fleet twin of the Servant's shadow reload: load + verify the
        checkpoint ONCE off the serving path, then cut every replica over
        to the same planes at one epoch (mixed versions can never serve)."""
        from swiftsnails_tpu.framework.checkpoint import load_tables

        reps = self.replicas()
        if not reps:
            raise Unavailable("fleet: no active replicas")
        first = reps[0].servant
        try:
            state, manifest = load_tables(
                root, step=step, verify=True, retry=retry)
            tables, dense, _ = _normalize_state_tables(
                state, config, first.scorer, first.mesh)
        except Exception as e:
            self.registry.counter("fleet.reload_rejected").inc()
            self._ledger_event("cache_error", {
                "probe": "fleet_reload",
                "root": root,
                "step": step,
                "kept_version": self.version,
                "error": f"{type(e).__name__}: {e}",
            })
            raise
        return self.reload(tables, manifest=manifest, dense=dense)

    def attach_freshness(self, subscriber) -> None:
        """Roll a :class:`~swiftsnails_tpu.freshness.subscriber.
        DeltaSubscriber`'s watermark/lag/fallback state into
        :meth:`health`."""
        self._freshness = subscriber

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        with self._lock:
            reps = list(self._replicas.values())
            self._replicas.clear()
        for rep in reps:
            rep.state = CLOSED
            rep.servant.close()

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- routing -----------------------------------------------------------

    def replicas(self) -> List[Replica]:
        with self._lock:
            return [r for r in self._replicas.values() if r.state == ACTIVE]

    def _breaker_open(self, rep: Replica, kernel: str) -> bool:
        br = rep.servant.breakers.get(kernel)
        return br is not None and br.state == OPEN

    def _route(self, kernel: str, key) -> Tuple[List[Replica], Dict]:
        """Candidate replicas, best first: ring order from the key's owner
        (or least-loaded when there is no affinity key), open-breaker
        replicas demoted to last resort, bounded-load spill applied within
        the healthy prefix. Returns ``(candidates, decision)`` — the
        decision is the owner-vs-spill annotation a request trace records.
        """
        keyed = self.affinity and key is not None
        with self._lock:
            active = {rid: r for rid, r in self._replicas.items()
                      if r.state == ACTIVE}
            if not active:
                raise Unavailable("fleet: no active replicas")
            if keyed:
                order = [active[rid]
                         for rid in self._ring.successors(route_hash(key))
                         if rid in active]
            else:
                # keyless spray: least-loaded with a round-robin tiebreak
                # (a stable sort over a rotated list), so an idle fleet
                # spreads instead of dog-piling the lexically-first replica
                reps = sorted(active.values(), key=lambda r: r.id)
                self._rr = (self._rr + 1) % len(reps)
                rotated = reps[self._rr:] + reps[:self._rr]
                order = sorted(rotated, key=lambda r: r.load(kernel))
        if not order:
            raise Unavailable("fleet: no routable replicas")
        healthy = [r for r in order if not self._breaker_open(r, kernel)]
        last_resort = [r for r in order if self._breaker_open(r, kernel)]
        if not healthy:
            self.registry.counter("fleet.route_last_resort").inc()
            return last_resort, route_annotation(
                [r.id for r in order], [r.id for r in last_resort],
                affinity=keyed, last_resort=True)
        picked, spilled, _cap = spill_order(
            healthy, lambda r: r.load(kernel),
            spill=self.ring_spill, active=len(order))
        if spilled:
            self.registry.counter("fleet.spill").inc()
        return picked + last_resort, route_annotation(
            [r.id for r in order], [r.id for r in picked], affinity=keyed)

    # -- request path ------------------------------------------------------

    def pull(self, ids, table: Optional[str] = None, *,
             key=None) -> np.ndarray:
        """Affinity-routed row pull. ``key`` overrides the affinity key;
        by default the request routes by its first id — the anchor of the
        key slice — so a repeated slice always warms the same replica."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        if key is None and len(ids):
            key = int(ids[0])
        return self._request(
            "pull", key, lambda s: s.pull(ids, table=table))

    def topk(self, query, k: Optional[int] = None,
             table: Optional[str] = None, exclude: Sequence[int] = (),
             normalize: bool = True, *, key=None) -> List:
        q = np.asarray(query, np.float32).reshape(-1)
        if key is None:
            key = int(q.view(np.uint32).sum())  # stable per query vector
        return self._request(
            "topk", key,
            lambda s: s.topk(q, k=k, table=table, exclude=exclude,
                             normalize=normalize))

    def score(self, feats) -> np.ndarray:
        """CTR scores; no key identity, so least-loaded routing."""
        return self._request("score", None, lambda s: s.score(feats))

    def _request(self, kernel: str, key, fn: Callable[[Servant], Any]):
        t0 = self._clock()
        rt = self.request_tracer
        ctx = None
        if rt is not None:
            try:
                ctx = rt.start(kernel)
            except Exception:
                ctx = None  # tracing never blocks the serve path
        try:
            result = self._request_traced(kernel, key, fn, t0, ctx)
        except BaseException as e:
            self._finish_request(kernel, t0, ctx, error=e)
            raise
        self._finish_request(kernel, t0, ctx)
        return result

    def _finish_request(self, kernel: str, t0: float, ctx,
                        error: Optional[BaseException] = None) -> None:
        if self.slo is not None:
            try:
                self.slo.record(kernel, (self._clock() - t0) * 1e3,
                                ok=error is None)
            except Exception:
                pass  # record-keeping never blocks the serve path
        if ctx is not None and self.request_tracer is not None:
            try:
                self.request_tracer.finish(ctx, error=error)
            except Exception:
                pass

    def _request_traced(self, kernel: str, key,
                        fn: Callable[[Servant], Any], t0: float, ctx):
        self._gov.note_request()
        self.registry.counter(f"fleet.{kernel}.requests").inc()
        candidates, decision = self._route(kernel, key)
        if ctx is not None:
            ctx.annotate(**decision)
            fr = self._freshness
            if fr is not None:
                try:
                    ctx.annotate(watermark_step=fr.applied_step,
                                 watermark_age_ms=round(fr.last_lag_ms, 3))
                except Exception:
                    pass
        flight = _Flight()
        launched: List[Replica] = []

        def launch(rep: Replica, hedged: bool) -> None:
            flight.arm()
            launched.append(rep)
            rep.begin()
            self._pool.submit(self._run_leg, flight, rep, kernel, fn,
                              hedged, ctx)

        launch(candidates[0], hedged=False)
        budget_s = self._p95[kernel].value / 1e3
        if not flight.done.wait(timeout=budget_s):
            hedge_to = next(
                (r for r in candidates[1:] if r not in launched), None)
            if hedge_to is not None and self._gov.allow():
                self._gov.note_hedge()
                self.registry.counter("serve.hedged").inc()
                self.registry.counter(f"fleet.{kernel}.hedged").inc()
                self._note_hedge(kernel, candidates[0].id, hedge_to.id,
                                 budget_s * 1e3)
                if ctx is not None:
                    ctx.mark_anomaly("hedge")
                    ctx.annotate(hedge_to=hedge_to.id,
                                 hedge_budget_ms=round(budget_s * 1e3, 3))
                launch(hedge_to, hedged=True)
        if not flight.done.wait(timeout=_REQUEST_TIMEOUT_S):
            raise TimeoutError(f"fleet {kernel} request timed out")

        if flight.winner is not None:
            rid, result, hedged = flight.winner
            if hedged:
                self.registry.counter("serve.hedge_won").inc()
            if ctx is not None:
                ctx.annotate(winner=rid, winner_hedged=hedged)
            self._observe(kernel, t0, ctx)
            return result

        # every launched leg failed: one synchronous re-route when the
        # failure is a routable condition (breaker shed / queue full), so a
        # single sick replica costs affinity, not availability
        err = flight.errors[0] if flight.errors else \
            Unavailable(f"fleet {kernel}: request lost")
        if isinstance(err, (Unavailable, Overloaded)):
            for rep in candidates:
                if rep in launched or rep.state != ACTIVE:
                    continue
                self.registry.counter("fleet.reroute").inc()
                if ctx is not None:
                    ctx.mark_anomaly("reroute")
                rep.begin()
                try:
                    with request_trace.use(ctx):
                        if ctx is not None:
                            with ctx.span("reroute", replica=rep.id) as sp:
                                result = fn(rep.servant)
                                sp.set(outcome="won")
                        else:
                            result = fn(rep.servant)
                except BaseException as e:  # noqa: BLE001 — keep first error type
                    err = e
                    continue
                finally:
                    rep.end()
                if ctx is not None:
                    ctx.annotate(winner=rep.id, rerouted=True)
                self._observe(kernel, t0, ctx)
                return result
        raise err

    def _run_leg(self, flight: _Flight, rep: Replica, kernel: str,
                 fn: Callable[[Servant], Any], hedged: bool,
                 ctx=None) -> None:
        # per-attempt child span: replica, breaker state at admission, and
        # the first-writer-wins outcome. The thread-local activation lets
        # the replica servant hang its queue-wait/kernel spans inside this
        # attempt rather than minting its own trace.
        sp = None
        if ctx is not None:
            try:
                br = rep.servant.breakers.get(kernel)
                sp = ctx.span("attempt", replica=rep.id, hedged=hedged,
                              breaker=br.state if br is not None else "none")
                sp.__enter__()
            except Exception:
                sp = None
        activation = request_trace.use(ctx)
        activation.__enter__()
        try:
            hook = rep.request_hook
            if hook is not None:
                hook(kernel)
            result, error = fn(rep.servant), None
        except BaseException as e:  # noqa: BLE001 — delivered to the caller
            result, error = None, e
        finally:
            rep.end()
            activation.__exit__(None, None, None)
        won = flight.complete(rep.id, result, error, hedged)
        if sp is not None:
            try:
                sp.set(outcome="won" if won else
                       ("error" if error is not None else "lost"))
                if error is not None:
                    sp.set(error=type(error).__name__)
                sp.__exit__(None, None, None)
            except Exception:
                pass
        if hedged and not won and error is None:
            self.registry.counter("serve.hedge_lost").inc()

    # -- metrics / events --------------------------------------------------

    def _observe(self, kernel: str, t0: float, ctx=None) -> None:
        ms = (self._clock() - t0) * 1e3
        self._p95[kernel].observe(ms)
        # exemplar: only link traces that will be kept (sampled/anomalous)
        tid = ctx.trace_id if ctx is not None and \
            (ctx.sampled or ctx.anomalous) else None
        self.registry.histogram(f"fleet.{kernel}.latency_ms").observe(
            ms, trace_id=tid)

    def _note_hedge(self, kernel: str, primary: str, hedge: str,
                    budget_ms: float) -> None:
        """Rate-limited hedge ledger events: the first and every 100th —
        same policy as the engine's overload/degraded streams."""
        total = int(self.registry.counter("serve.hedged").value)
        if self.ledger is not None and (total == 1 or total % 100 == 0):
            self._ledger_event("hedge", {
                "kernel": kernel,
                "primary": primary,
                "hedge": hedge,
                "budget_ms": round(budget_ms, 3),
                "hedged_total": total,
                "hedge_rate_pct": round(self._gov.rate_pct, 3),
            })
            self._hedge_events = total

    def _ledger_event(self, kind: str, record: Dict) -> None:
        if self.ledger is None:
            return
        try:
            self.ledger.append(kind, {"source": "fleet", **record})
        except Exception:
            pass  # record-keeping never blocks the serve path

    def hedge_budget(self, kernel: str) -> float:
        """Current hedge-arm delay for ``kernel`` in ms (EWMA p95)."""
        return self._p95[kernel].value

    def stats(self) -> Dict:
        reg = self.registry
        with self._lock:
            reps = dict(self._replicas)
        per_replica = {}
        for rid, rep in sorted(reps.items()):
            s = rep.servant.stats()
            per_replica[rid] = {
                "state": rep.state,
                "requests": rep.requests,
                "inflight": rep.inflight,
                "queue_depths": rep.servant.queue_depths(),
                "kernels": s["kernels"],
                "cache_hit_rate": s["cache"]["hit_rate"],
                "breakers": {k: b["state"] for k, b in s["breakers"].items()},
            }
        kernels = {}
        for k in _KERNELS:
            summ = reg.histogram(f"fleet.{k}.latency_ms").summary()
            kernels[k] = {
                "requests": int(reg.counter(f"fleet.{k}.requests").value),
                "hedged": int(reg.counter(f"fleet.{k}.hedged").value),
                "p50_ms": round(summ.get("p50", 0.0), 4),
                "p95_ms": round(summ.get("p95", 0.0), 4),
                "p99_ms": round(summ.get("p99", 0.0), 4),
                "hedge_budget_ms": round(self._p95[k].value, 3),
            }
        return {
            "replicas": per_replica,
            "ring": {"members": self._ring.members(),
                     "vnodes": self._ring.vnodes,
                     "spill": self.ring_spill,
                     "affinity": self.affinity},
            "kernels": kernels,
            "hedge": self._gov.snapshot() | {
                "won": int(reg.counter("serve.hedge_won").value),
                "lost": int(reg.counter("serve.hedge_lost").value),
            },
            "spills": int(reg.counter("fleet.spill").value),
            "reroutes": int(reg.counter("fleet.reroute").value),
            "replicas_added": int(reg.counter("fleet.replicas_added").value),
            "replicas_drained": int(
                reg.counter("fleet.replicas_drained").value),
            **({"trace": self.request_tracer.stats()}
               if self.request_tracer is not None else {}),
            **({"slo": self.slo.snapshot()} if self.slo is not None else {}),
        }

    def health(self) -> Dict:
        """Fleet-level liveness: ``ok`` when every active replica is ok,
        ``degraded`` when at least one still answers, ``down`` otherwise."""
        with self._lock:
            reps = dict(self._replicas)
        statuses = {}
        for rid, rep in sorted(reps.items()):
            statuses[rid] = {
                "state": rep.state,
                "status": rep.servant.health()["status"]
                if rep.state != CLOSED else "closed",
                "version": rep.servant.version,
                "step": rep.servant.step,
            }
        active = [v for v in statuses.values() if v["state"] == ACTIVE]
        if not active:
            status = "down"
        elif all(v["status"] == "ok" for v in active):
            status = "ok"
        else:
            status = "degraded"
        out = {
            "status": status,
            "replicas": statuses,
            "active": len(active),
            "hedge": self._gov.snapshot(),
        }
        if self._freshness is not None:
            try:
                fr = self._freshness.status()
                fr["replica_versions"] = {
                    rid: v["version"] for rid, v in statuses.items()}
                out["freshness"] = fr
            except Exception:
                pass  # introspection never blocks the health probe
        return out
