"""The serving engine: micro-batched, cached, admission-controlled queries.

The reference system's whole point is a sharded key->value store that
serves *pull* traffic (PAPER §0: "serves heavy traffic from millions of
users"); PRs 1-5 built the write/train side only. :class:`Servant` is the
read path: it owns normalized read-only tables (dense ``[capacity, dim]``
device arrays produced by :func:`normalize_table` from any checkpointed
plane) and answers three request kinds through per-kernel micro-batchers:

* ``pull(ids)``    — row lookup (:func:`serving.kernels.pull_rows`)
* ``topk(query)``  — nearest-neighbor scan (:func:`serving.kernels.topk_tiled`)
* ``score(feats)`` — CTR forward over pulled rows (registry model)

**Micro-batcher.** Concurrent requests coalesce into fixed padded shapes:
request units (rows / queries) are concatenated, chunked at the largest
configured bucket, and each chunk pads up to the smallest bucket that holds
it — so the jit cache holds at most ``len(serve_batch_buckets)`` entries per
kernel. Pull padding uses sentinel row id 0; pad rows are sliced off before
results return, are **never** inserted into the hot-row cache, and are
counted in ``serve.<k>.pad_rows`` rather than the real-row counters.

**Hot-row cache.** An LRU keyed on ``(table, row_id)`` and stamped with the
servant's table *version*; :meth:`Servant.reload` bumps the version so a
table swap invalidates every cached row at once (``docs/SERVING.md``).

**Admission control.** Each batcher's queue is bounded
(``serve_queue_depth``); a submit against a full queue sheds immediately
with a typed :class:`Overloaded` instead of stalling the caller, counts a
shed, and (rate-limited) records an ``overload`` ledger event that
``ledger-report --failures`` renders.

**Availability.** Each kernel sits behind a closed/open/half-open
:class:`~swiftsnails_tpu.serving.breaker.CircuitBreaker`
(``breaker_threshold`` consecutive dispatch failures trip it;
``breaker_cooldown_ms`` later a half-open probe decides). While a pull
breaker is open — or when a pull dispatch fails outright — the request is
served DEGRADED from the hot-row LRU when every id is present (counted as
``serve.pull.degraded`` / ``degraded_hits``, never mixed into the fresh
counters); otherwise it sheds with a typed
:class:`~swiftsnails_tpu.serving.breaker.Unavailable`. ``topk``/``score``
have no row cache to degrade from, so an open breaker sheds them.
``serve_degraded: 0`` disables the stale fallback (strict freshness).
:meth:`Servant.reload_from_checkpoint` is shadow-load → CRC verify →
atomic version swap: a corrupt newer checkpoint is rejected while the live
tables keep serving. :meth:`Servant.health` (and the serve REPL's
``health`` command) exposes breaker/tier/version state.

Latency histograms (p50/p95/p99) and cache-hit/shed counters feed the
shared telemetry :class:`~swiftsnails_tpu.telemetry.registry.MetricRegistry`
and the run ledger.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from swiftsnails_tpu.serving.breaker import CLOSED, CircuitBreaker, Unavailable
from swiftsnails_tpu.serving.cache import HotRowCache
from swiftsnails_tpu.serving.kernels import pull_rows, topk_tiled
from swiftsnails_tpu.telemetry import request_trace

DEFAULT_BUCKETS = (8, 64)
DEFAULT_BREAKER_THRESHOLD = 5
DEFAULT_BREAKER_COOLDOWN_MS = 1_000.0
DEFAULT_BREAKER_PROBES = 1
DEFAULT_CACHE_ROWS = 4096
DEFAULT_QUEUE_DEPTH = 64
DEFAULT_TOPK = 10
PAD_ROW = 0  # pull-pad sentinel: a real row id, sliced off before returning
PAD_FIELD = -1  # CTR pad field (masked out of the forward, as in training)
_LATENCY_WINDOW = 4096
_REQUEST_TIMEOUT_S = 120.0


class Overloaded(RuntimeError):
    """The serve queue is full: the request was shed, not queued."""


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest configured bucket that holds ``n`` units (callers chunk at
    the largest bucket first, so ``n <= max(buckets)`` here)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


# ---------------------------------------------------------- normalization ---


def normalize_table(
    arr,
    dim: int,
    layout: str,
    capacity: Optional[int] = None,
):
    """Any checkpointed table plane -> dense ``[capacity, dim]`` rows.

    ``layout``: ``dense`` (2-D ``[C, dim]``, as-is), ``packed`` (word2vec
    ``[C, S, 128]``, one logical row per tile — ``ops/rowdma.unpack_rows``),
    or ``packed_small`` (CTR ``[T, S, 128]``, ``small_group(dim)`` rows per
    tile, sublane 0 = params). Every case is an exact lane select — no
    arithmetic — so normalized rows are bit-identical to the trained ones.
    """
    a = jnp.asarray(arr)
    if layout == "dense":
        return a
    if layout == "packed":
        from swiftsnails_tpu.ops.rowdma import unpack_rows

        return unpack_rows(a, dim)
    if layout == "packed_small":
        from swiftsnails_tpu.ops.rowdma import ROW_LANES
        from swiftsnails_tpu.parallel.store import small_group

        g = small_group(dim)
        stride = ROW_LANES // g
        t = a.shape[0]
        cap = capacity if capacity is not None else t * g
        # sublane 0 = params (sublane 1, when present, is the fused AdaGrad
        # accumulator); row r lives in tile r//g at lanes (r%g)*stride
        rows = a[:, 0, :].reshape(t * g, stride)
        return rows[:cap, :dim]
    raise ValueError(f"unknown table layout {layout!r}")


def _normalize_state_tables(state, config, scorer, mesh):
    """Checkpoint state tree -> ``(tables, dense, default_table)``: the one
    normalization used by both the cold start (:meth:`Servant.from_checkpoint`)
    and the live shadow reload (:meth:`Servant.reload_from_checkpoint`).
    ``scorer`` carries the CTR geometry (None for word2vec)."""
    model_name = config.get_str("model", "word2vec")
    if model_name == "word2vec":
        dim = config.get_int("dim", 100)
        layout = "packed" if config.get_bool("packed", True) else "dense"
        tables = {
            name: normalize_table(state[name]["table"], dim, layout)
            for name in ("in_table", "out_table")
            if name in state
        }
        dense = None
        default_table = "in_table"
    else:
        layout = "packed_small" if scorer.packed else "dense"
        tables = {
            "table": normalize_table(
                state["table"]["table"], scorer.table_dim, layout,
                capacity=scorer.capacity,
            )
        }
        dense = state.get("dense") or {}
        default_table = "table"
    if mesh is not None:
        from swiftsnails_tpu.parallel.mesh import table_sharding

        sharding = table_sharding(mesh)
        tables = {k: jax.device_put(v, sharding) for k, v in tables.items()}
    return tables, dense, default_table


# ------------------------------------------------------------ micro-batch ---


class _Request:
    __slots__ = ("payload", "n", "event", "result", "error", "t0",
                 "t_dispatch", "kernel_ms", "pad_buckets", "pad_rows")

    def __init__(self, payload: Dict, n: int):
        self.payload = payload
        self.n = n
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t0 = time.perf_counter()
        # dispatcher-thread stamps: when the batch was taken, how long the
        # kernel ran, and the pad buckets it rode in. The *request* thread
        # turns these into retroactive trace spans (queue-wait / kernel)
        # after _wait returns — the dispatcher never touches the context.
        self.t_dispatch = 0.0
        self.kernel_ms = 0.0
        self.pad_buckets: Tuple[int, ...] = ()
        self.pad_rows = 0


class MicroBatcher:
    """Bounded-queue request coalescer with a dispatcher thread.

    ``dispatch(batch)`` receives a list of :class:`_Request` whose total
    units fit the largest bucket; it must set each request's ``result`` (or
    ``error``) and ``event``. Submits against a full queue raise
    :class:`Overloaded` (after invoking ``on_shed``) — callers never stall.
    """

    def __init__(
        self,
        name: str,
        buckets: Sequence[int],
        queue_depth: int,
        dispatch,
        linger_s: float = 0.0,
        on_shed=None,
    ):
        self.name = name
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.queue_depth = int(queue_depth)
        self.linger_s = float(linger_s)
        self._dispatch = dispatch
        self._on_shed = on_shed
        self._queue: "deque[_Request]" = deque()
        self._cv = threading.Condition()
        self._closed = False
        self.shed = 0
        self._thread = threading.Thread(
            target=self._loop, name=f"ssn-serve-{name}", daemon=True
        )
        self._thread.start()

    def submit(self, payload: Dict, n: int) -> _Request:
        req = _Request(payload, n)
        with self._cv:
            if self._closed:
                raise RuntimeError(f"{self.name} batcher is closed")
            if len(self._queue) >= self.queue_depth:
                self.shed += 1
                if self._on_shed is not None:
                    self._on_shed(self.name)
                raise Overloaded(
                    f"{self.name} queue full "
                    f"({len(self._queue)}/{self.queue_depth}); request shed"
                )
            self._queue.append(req)
            self._cv.notify()
        return req

    @property
    def depth(self) -> int:
        """Requests queued but not yet taken by the dispatcher — the load
        signal the fleet router's bounded spill keys on. A racy snapshot by
        design (len() on a deque is atomic under CPython)."""
        return len(self._queue)

    def _take_batch(self) -> List[_Request]:
        """Drain queued requests up to the largest bucket's unit budget."""
        batch: List[_Request] = []
        units = 0
        cap = self.buckets[-1]
        while self._queue and units + self._queue[0].n <= cap:
            req = self._queue.popleft()
            batch.append(req)
            units += req.n
        if not batch and self._queue:
            # one oversized request: dispatch chunks it internally
            batch.append(self._queue.popleft())
        return batch

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if self._closed and not self._queue:
                    return
                if self.linger_s > 0 and len(self._queue) == 1:
                    self._cv.wait(timeout=self.linger_s)
                batch = self._take_batch()
            if not batch:
                continue
            try:
                self._dispatch(batch)
            except BaseException as e:  # noqa: BLE001 — fail the batch, not the thread
                for req in batch:
                    if not req.event.is_set():
                        req.error = e
                        req.event.set()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)


def _wait(req: _Request):
    if not req.event.wait(timeout=_REQUEST_TIMEOUT_S):
        raise TimeoutError("serving request timed out")
    if req.error is not None:
        raise req.error
    return req.result


def _percentile(samples: List[float], p: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(int(p * (len(s) - 1)), len(s) - 1)]


# ---------------------------------------------------------------- servant ---


class Servant:
    """In-process query API over normalized read-only tables.

    ``tables``: name -> dense ``[capacity, dim]`` device array.
    ``scorer``: a registry CTR trainer instance (forward + feature hashing)
    when the ``score`` kernel should be live; ``dense`` is its checkpointed
    dense pytree. ``registry`` is a telemetry
    :class:`~swiftsnails_tpu.telemetry.registry.MetricRegistry` (a private
    one is created when omitted); ``ledger`` receives ``overload`` events.
    """

    def __init__(
        self,
        tables: Dict[str, Any],
        *,
        manifest: Optional[Dict] = None,
        mesh=None,
        scorer=None,
        dense=None,
        registry=None,
        ledger=None,
        batch_buckets: Sequence[int] = DEFAULT_BUCKETS,
        cache_rows: int = DEFAULT_CACHE_ROWS,
        queue_depth: int = DEFAULT_QUEUE_DEPTH,
        linger_s: float = 0.0,
        comm_dtype: str = "float32",
        topk: int = DEFAULT_TOPK,
        topk_tile_rows: int = 4096,
        default_table: Optional[str] = None,
        tier_hbm_budget_mb: float = 0.0,
        breaker_threshold: int = DEFAULT_BREAKER_THRESHOLD,
        breaker_cooldown_ms: float = DEFAULT_BREAKER_COOLDOWN_MS,
        breaker_halfopen_probes: int = DEFAULT_BREAKER_PROBES,
        degraded: bool = True,
        request_tracer=None,
        slo=None,
    ):
        if not tables:
            raise ValueError("Servant needs at least one table")
        self.mesh = mesh
        # ops plane: a telemetry RequestTracer captures per-request span
        # trees (head-sampled + anomaly tail-keep); an SloTracker burns the
        # error budget. Both optional — None costs one attribute check.
        self.request_tracer = request_tracer
        self.slo = slo
        self.comm_dtype = comm_dtype
        self.topk_default = int(topk)
        self.topk_tile_rows = int(topk_tile_rows)
        self.scorer = scorer
        self.ledger = ledger
        self.manifest = manifest or {}
        self.step = int(self.manifest.get("step", 0) or 0)
        self.version = 0  # bumped by every reload; keys the hot-row cache
        # table_tier: host (tier_hbm_budget_mb > 0): the full normalized
        # tables stay in host RAM and the device holds fixed-budget read
        # caches — cold rows fault in batched behind the hot-row LRU
        # (serving vocabularies bigger than device memory). 0 = resident.
        self.tier: Dict[str, Any] = {}
        self._tier_cache: Dict[str, Any] = {}
        self._tier_lock = threading.Lock()
        self.tier_budget_mb = float(tier_hbm_budget_mb)
        self._tier_stats = None
        if self.tier_budget_mb > 0:
            self._tables = {k: np.asarray(v) for k, v in tables.items()}
            self._build_tier()
        else:
            self._tables = {k: jnp.asarray(v) for k, v in tables.items()}
        self._dense = dense if dense is not None else {}
        self.default_table = default_table or (
            "in_table" if "in_table" in self._tables else
            sorted(self._tables)[0]
        )
        self.buckets = tuple(sorted(int(b) for b in batch_buckets))

        if registry is None:
            from swiftsnails_tpu.telemetry.registry import MetricRegistry

            registry = MetricRegistry()
        self.registry = registry
        self.cache = HotRowCache(cache_rows)
        self._latency: Dict[str, "deque[float]"] = {
            k: deque(maxlen=_LATENCY_WINDOW)
            for k in ("pull", "topk", "score")
        }
        self._shed_events = 0  # overload ledger events already written
        self._degraded_events = 0  # degraded ledger events already written
        self._lock = threading.Lock()
        # availability layer: per-kernel breakers (threshold 0 disables) +
        # degraded-mode stale reads. `fault_hook` is the seeded chaos
        # injection point — fn(kernel, dispatch_index) may raise or stall,
        # exactly as a sick device/storage read would (chaos-serve lane).
        self.degraded_enabled = bool(degraded)
        self.fault_hook = None
        # freshness: an attached DeltaSubscriber surfaces its watermark/lag
        # through health() (cli `freshness` op; Fleet rolls replicas up)
        self._freshness = None
        self._dispatch_seq = {"pull": 0, "topk": 0, "score": 0}
        self.breakers: Dict[str, CircuitBreaker] = {}
        if int(breaker_threshold) > 0:
            self.breakers = {
                k: CircuitBreaker(
                    k,
                    threshold=int(breaker_threshold),
                    cooldown_ms=float(breaker_cooldown_ms),
                    halfopen_probes=int(breaker_halfopen_probes),
                    on_transition=self._on_breaker_transition,
                )
                for k in ("pull", "topk", "score")
            }

        self._pull_fn = jax.jit(
            lambda table, rows: pull_rows(
                table, rows, mesh=self.mesh, comm_dtype=self.comm_dtype
            )
        )
        self._score_fn = jax.jit(self._score_impl) if scorer is not None else None

        self._batchers = {
            "pull": MicroBatcher(
                "pull", self.buckets, queue_depth, self._dispatch_pull,
                linger_s=linger_s, on_shed=self._note_shed,
            ),
            "topk": MicroBatcher(
                "topk", self.buckets, queue_depth, self._dispatch_topk,
                linger_s=linger_s, on_shed=self._note_shed,
            ),
            "score": MicroBatcher(
                "score", self.buckets, queue_depth, self._dispatch_score,
                linger_s=linger_s, on_shed=self._note_shed,
            ),
        }

    # -- tiered read path (table_tier: host; see tiered/) -------------------

    def _build_tier(self) -> None:
        """Wrap each host master in a read-only :class:`TieredTable` with a
        prewarmed device cache. Vocab ids are frequency-ranked (the training
        ordering contract), so the id head IS the zipf head — prewarm it."""
        from swiftsnails_tpu.parallel.store import TableState
        from swiftsnails_tpu.tiered.store import (
            HostMaster, TieredTable, TierStats,
        )

        if self._tier_stats is None:
            self._tier_stats = TierStats()
        budget_each = self.tier_budget_mb / max(len(self._tables), 1)
        self.tier = {}
        self._tier_cache = {}
        for name, arr in self._tables.items():
            master = HostMaster(TableState(table=arr, slots={}), "dense")
            units = int(budget_each * (1 << 20) // max(master.unit_nbytes, 1))
            tt = TieredTable(
                master, units, mesh=self.mesh, name=name,
                stats=self._tier_stats, read_only=True,
            )
            cache = tt.make_cache()
            cache = tt.prewarm(
                cache, np.arange(min(tt.budget, master.units), dtype=np.int64))
            self.tier[name] = tt
            self._tier_cache[name] = cache

    def _tier_pull(self, name: str, ids: np.ndarray) -> np.ndarray:
        """Cold-row fault: make ``ids`` resident in the cache plane, remap to
        slots, gather from the cache. The lock serializes fault + remap +
        gather across the kernel batcher threads — a concurrent eviction must
        never overwrite a slot between the remap and its device read."""
        tt = self.tier[name]
        with self._tier_lock:
            cache = tt.ensure(self._tier_cache[name], np.asarray(ids))
            self._tier_cache[name] = cache
            slots = tt.remap(np.asarray(ids, np.int64))
            return np.asarray(
                self._pull_fn(cache.table, jnp.asarray(slots, jnp.int32)))

    def _topk_master(self, name: str, queries: np.ndarray, k: int,
                     normalize: bool):
        """Over-budget topk: stream the host master through the device one
        ``topk_tile_rows`` tile at a time with a running best-k merge — the
        full table never resides in HBM. Scores are per-row (cosine or raw
        dot), so chunk results merge exactly."""
        master = self.tier[name].master.table
        tile = max(int(self.topk_tile_rows), 1)
        q = np.asarray(queries, np.float32)
        parts_s: List[np.ndarray] = []
        parts_i: List[np.ndarray] = []
        for lo in range(0, master.shape[0], tile):
            chunk = master[lo : lo + tile]
            s, i = topk_tiled(
                jnp.asarray(chunk), jnp.asarray(q),
                k=min(k, chunk.shape[0]), tile_rows=tile,
                normalize=normalize,
            )
            parts_s.append(np.asarray(s))
            parts_i.append(np.asarray(i) + lo)
        s = np.concatenate(parts_s, axis=1)
        i = np.concatenate(parts_i, axis=1)
        order = np.argsort(-s, axis=1, kind="stable")[:, :k]
        rows = np.arange(s.shape[0])[:, None]
        return s[rows, order], i[rows, order]

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def from_checkpoint(
        cls,
        root: str,
        config,
        *,
        step: Optional[int] = None,
        mesh=None,
        **kwargs,
    ) -> "Servant":
        """Load a verified checkpoint into a query-only servant.

        ``config`` is the same typed config the training run used — it
        carries the model family and table geometry the checkpointed arrays
        are laid out with (``model``, ``dim``/``num_fields``, ``packed``,
        ``capacity``), plus the ``serve_*`` knobs.
        """
        from swiftsnails_tpu.framework.checkpoint import load_tables

        state, manifest = load_tables(root, step=step)
        model_name = config.get_str("model", "word2vec")
        scorer = None
        if model_name != "word2vec":
            from swiftsnails_tpu.models.registry import get_model

            trainer_cls = get_model(model_name)
            # a scorer instance carries forward() + the feature hashing; the
            # empty data tuple keeps the constructor off the data path
            n_fields = config.get_int("num_fields")
            scorer = trainer_cls(
                config, mesh=None,
                data=(np.zeros(0, np.float32),
                      np.zeros((0, n_fields), np.int32)),
            )
        tables, dense, default_table = _normalize_state_tables(
            state, config, scorer, mesh)
        kwargs.setdefault("batch_buckets", _int_list(
            config.get_str("serve_batch_buckets", ""), DEFAULT_BUCKETS))
        kwargs.setdefault("cache_rows",
                          config.get_int("serve_cache_rows", DEFAULT_CACHE_ROWS))
        kwargs.setdefault("queue_depth",
                          config.get_int("serve_queue_depth", DEFAULT_QUEUE_DEPTH))
        kwargs.setdefault("topk", config.get_int("serve_topk", DEFAULT_TOPK))
        kwargs.setdefault("comm_dtype", config.get_str("comm_dtype", "float32"))
        kwargs.setdefault("breaker_threshold", config.get_int(
            "breaker_threshold", DEFAULT_BREAKER_THRESHOLD))
        kwargs.setdefault("breaker_cooldown_ms", config.get_float(
            "breaker_cooldown_ms", DEFAULT_BREAKER_COOLDOWN_MS))
        kwargs.setdefault("breaker_halfopen_probes", config.get_int(
            "breaker_halfopen_probes", DEFAULT_BREAKER_PROBES))
        kwargs.setdefault("degraded", config.get_bool("serve_degraded", True))
        if "request_tracer" not in kwargs:
            from swiftsnails_tpu.telemetry.request_trace import RequestTracer

            kwargs["request_tracer"] = RequestTracer.from_config(
                config, ledger=kwargs.get("ledger"))
        if "slo" not in kwargs:
            from swiftsnails_tpu.telemetry.slo import SloTracker

            kwargs["slo"] = SloTracker.from_config(
                config, ledger=kwargs.get("ledger"))
        if config.get_str("table_tier", "device") == "host":
            kwargs.setdefault(
                "tier_hbm_budget_mb",
                config.get_float("tier_hbm_budget_mb", 64.0))
        return cls(
            tables, manifest=manifest, mesh=mesh, scorer=scorer, dense=dense,
            default_table=default_table, **kwargs,
        )

    def reload(self, tables: Dict[str, Any], manifest: Optional[Dict] = None,
               dense=None, *, version: Optional[int] = None) -> int:
        """Swap in new tables; bumps the version so every cached row of the
        old tables misses (stale rows can never be served). ``version`` is
        the fleet-epoch override: replicas sharing one logical swap all cut
        over to the SAME number instead of bumping independently."""
        with self._lock:
            if self.tier_budget_mb > 0:
                # new masters + fresh caches/slot maps: a stale slot mapping
                # against the old tables must never serve again (the version
                # bump below already invalidates the hot-row LRU)
                self._tables = {k: np.asarray(v) for k, v in tables.items()}
                with self._tier_lock:
                    self._build_tier()
            else:
                self._tables = {k: jnp.asarray(v) for k, v in tables.items()}
            if dense is not None:
                self._dense = dense
            if manifest is not None:
                self.manifest = manifest
                self.step = int(manifest.get("step", self.step) or 0)
            self.version = int(version) if version is not None \
                else self.version + 1
            return self.version

    # -- freshness delta apply (freshness/; docs/FRESHNESS.md) ---------------

    def prepare_rows(self, updates: Dict[str, Any]) -> Dict[str, Any]:
        """Build the post-delta table planes OFF the serving path (pure —
        nothing is installed). ``updates``: ``{table: (row_ids, [n, dim]
        values)}`` of absolute normalized rows. Split from
        :meth:`install_tables` so a fleet computes the new planes once and
        installs the SAME arrays into every replica at one shared epoch."""
        out: Dict[str, Any] = {}
        for name, (ids, vals) in updates.items():
            if name not in self._tables:
                continue  # a delta stream may carry tables we don't serve
            tab = self._tables[name]
            ids = np.asarray(ids)
            vals = np.asarray(vals)
            # pad to the next power of two by repeating the last row (same
            # id + same value scatters are no-ops), so a stream of
            # arbitrary-sized delta batches compiles O(log n) scatter
            # shapes instead of one per distinct batch size
            n = int(ids.shape[0])
            m = 1 << max(n - 1, 0).bit_length()
            if m > n:
                ids = np.concatenate([ids, np.repeat(ids[-1:], m - n)])
                vals = np.concatenate(
                    [vals, np.repeat(vals[-1:], m - n, axis=0)])
            ids = jnp.asarray(ids, jnp.int32)
            vals = jnp.asarray(vals, tab.dtype)
            out[name] = tab.at[ids].set(vals)
        return out

    def install_tables(self, new_tables: Dict[str, Any], *,
                       version: Optional[int] = None,
                       step: Optional[int] = None) -> int:
        """Atomic cutover of (some) resident planes: the table dict is
        replaced wholesale under the lock, so a concurrent request sees the
        whole old set or the whole new set — never a torn batch. The version
        bump invalidates every hot-row cache entry of the old planes."""
        with self._lock:
            self._tables = {**self._tables, **new_tables}
            if step is not None:
                self.step = max(self.step, int(step))
            self.version = int(version) if version is not None \
                else self.version + 1
            return self.version

    def apply_rows(self, updates: Dict[str, Any], *,
                   version: Optional[int] = None,
                   step: Optional[int] = None) -> int:
        """Apply one delta batch of absolute rows with an atomic version
        cutover; returns the new version. Resident tables go through the
        pure :meth:`prepare_rows` + locked :meth:`install_tables` pair;
        tiered tables scatter into the host masters (through
        ``HostMaster.scatter``, so the integrity digests stay true), bump
        the touched units' write-back generation, and invalidate their
        resident cache slots so the next pull refaults the fresh rows."""
        if self.tier_budget_mb <= 0:
            return self.install_tables(self.prepare_rows(updates),
                                       version=version, step=step)
        with self._lock, self._tier_lock:
            for name, (ids, vals) in updates.items():
                if name not in self.tier:
                    continue  # delta table this servant doesn't serve
                tt = self.tier[name]
                ids = np.asarray(ids, np.int64)
                vals = np.asarray(vals, tt.master.table_dtype)
                # serving masters are dense group-1 planes: unit == row
                tt.master.scatter(ids, vals, {})
                self._tables[name][ids] = vals
                tt.master_ver[ids] += 1
                res = ids[tt.slot_of[ids] >= 0]
                if res.size:
                    slots = tt.slot_of[res]
                    tt.unit_of[slots] = -1
                    tt.ref[slots] = 0
                    tt.slot_of[res] = -1
            if step is not None:
                self.step = max(self.step, int(step))
            self.version = int(version) if version is not None \
                else self.version + 1
            return self.version

    def reload_from_checkpoint(self, root: str, config, *,
                               step: Optional[int] = None,
                               retry=None) -> int:
        """Shadow-load → CRC verify → atomic version swap.

        The candidate checkpoint is fully loaded and manifest-verified OFF
        the serving path (:func:`load_tables` with ``verify=True``), then
        normalized into dense planes, and only then swapped in under the
        servant lock with a version bump — a corrupt newer checkpoint is
        rejected here (``CheckpointError``) while the live tables keep
        serving the old version untouched. ``retry`` (a
        :class:`~swiftsnails_tpu.resilience.retry.RetryPolicy`) absorbs
        transient storage errors during the shadow load."""
        from swiftsnails_tpu.framework.checkpoint import load_tables

        try:
            state, manifest = load_tables(
                root, step=step, verify=True, retry=retry)
            tables, dense, _ = _normalize_state_tables(
                state, config, self.scorer, self.mesh)
        except Exception as e:
            self.registry.counter("serve.reload_rejected").inc()
            if self.ledger is not None:
                try:
                    self.ledger.append("cache_error", {
                        "source": "serve_reload",
                        "root": root,
                        "step": step,
                        "kept_version": self.version,
                        "error": f"{type(e).__name__}: {e}",
                    })
                except Exception:
                    pass
            raise
        return self.reload(tables, manifest=manifest, dense=dense)

    def close(self) -> None:
        for b in self._batchers.values():
            b.close()
        self._flush_overloads(final=True)

    def __enter__(self) -> "Servant":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request API -------------------------------------------------------

    def pull(self, ids, table: Optional[str] = None) -> np.ndarray:
        """[N] row ids -> [N, dim] rows (cache -> micro-batch -> kernel).

        Availability ladder: fresh cache hits and a healthy dispatch serve
        normally; an open pull breaker — or a dispatch failure — falls back
        to the stale hot-row LRU when every id is present (a DEGRADED serve,
        counted apart from the fresh path); otherwise the typed error
        propagates (:class:`Unavailable` when the breaker shed it)."""
        t0 = time.perf_counter()
        name = table or self.default_table
        ids = np.asarray(ids, np.int32).reshape(-1)
        ctx, owned = self._trace_begin("pull", table=name, n=len(ids))
        try:
            with request_trace.use(ctx):
                out = self._pull_traced(name, ids, t0, ctx)
        except BaseException as e:
            self._trace_end("pull", ctx, owned, t0, error=e)
            raise
        self._trace_end("pull", ctx, owned, t0)
        return out

    def _pull_traced(self, name: str, ids: np.ndarray, t0: float,
                     ctx) -> np.ndarray:
        version = self.version
        found, missing = self.cache.get_many(name, version, ids)
        if ctx is not None:
            ctx.annotate(table=name, table_version=version,
                         cache_hits=len(found), cache_misses=len(missing))
            self._annotate_freshness(ctx)
        if missing:
            br = self.breakers.get("pull")
            if br is not None and not br.allow():
                if ctx is not None:
                    ctx.annotate(breaker="open")
                return self._pull_degraded(name, ids, t0, reason="open")
            try:
                req = self._batchers["pull"].submit(
                    {"table": name, "ids": np.asarray(missing, np.int32),
                     "version": version},
                    n=len(missing),
                )
                pulled = _wait(req)  # [len(missing), dim]
            except Overloaded:
                raise  # queue pressure, not kernel health
            except Exception:
                if br is not None:
                    br.record_failure()
                if self.degraded_enabled:
                    return self._pull_degraded(
                        name, ids, t0, reason="dispatch_failure")
                raise
            if br is not None:
                br.record_success()
            self._trace_dispatch(ctx, req)
            found.update(
                (int(i), pulled[n]) for n, i in enumerate(missing)
            )
        out = np.stack([found[int(i)] for i in ids]) if len(ids) else \
            np.zeros((0,) + self._tables[name].shape[1:], np.float32)
        self._observe("pull", t0, units=len(ids), ctx=ctx)
        return out

    def _pull_degraded(self, name: str, ids: np.ndarray, t0: float,
                       reason: str) -> np.ndarray:
        """Serve a pull from the stale hot-row LRU, or shed. Only complete
        answers are served — a partially-stale response would silently mix
        row generations within one request."""
        if self.degraded_enabled:
            found, missing = self.cache.get_stale(name, ids)
            if not missing:
                self._note_degraded("pull", len(ids), reason)
                self._observe("pull", t0, units=len(ids),
                              ctx=request_trace.current())
                return np.stack([found[int(i)] for i in ids]) if len(ids) \
                    else np.zeros(
                        (0,) + self._tables[name].shape[1:], np.float32)
            detail = f"{len(missing)}/{len(ids)} id(s) not in the stale cache"
        else:
            detail = "degraded reads disabled (serve_degraded: 0)"
        self.registry.counter("serve.pull.unavailable").inc()
        raise Unavailable(f"pull[{name}]: breaker {reason}; {detail}")

    def topk(
        self,
        query,
        k: Optional[int] = None,
        table: Optional[str] = None,
        exclude: Sequence[int] = (),
        normalize: bool = True,
    ) -> List[Tuple[int, float]]:
        """Nearest rows to ``query`` ([dim]) by cosine (or raw dot) score.

        ``exclude`` ids are filtered host-side (the kernel scans the full
        table); the request over-fetches by ``len(exclude)`` to compensate.
        """
        t0 = time.perf_counter()
        name = table or self.default_table
        k = int(k or self.topk_default)
        q = np.asarray(query, np.float32).reshape(1, -1)
        ctx, owned = self._trace_begin("topk", table=name, k=k)
        try:
            with request_trace.use(ctx):
                scores, ids = self._guarded_dispatch(
                    "topk",
                    {"table": name, "queries": q, "k": k + len(exclude),
                     "normalize": normalize},
                    n=1,
                )  # ([1, k+x], [1, k+x])
        except BaseException as e:
            self._trace_end("topk", ctx, owned, t0, error=e)
            raise
        out = [
            (int(i), float(s))
            for i, s in zip(ids[0], scores[0])
            if int(i) not in set(int(e) for e in exclude) and int(i) >= 0
        ][:k]
        self._observe("topk", t0, units=1, ctx=ctx)
        self._trace_end("topk", ctx, owned, t0)
        return out

    def score(self, feats) -> np.ndarray:
        """CTR probability scores for ``feats`` [B, F] (or [F])."""
        if self.scorer is None:
            raise RuntimeError("this servant has no CTR scorer model")
        t0 = time.perf_counter()
        feats = np.asarray(feats, np.int32)
        if feats.ndim == 1:
            feats = feats[None, :]
        ctx, owned = self._trace_begin("score", n=len(feats))
        try:
            with request_trace.use(ctx):
                out = self._guarded_dispatch(
                    "score", {"feats": feats}, n=len(feats))
        except BaseException as e:
            self._trace_end("score", ctx, owned, t0, error=e)
            raise
        self._observe("score", t0, units=len(feats), ctx=ctx)
        self._trace_end("score", ctx, owned, t0)
        return out

    def _guarded_dispatch(self, kernel: str, payload: Dict, n: int):
        """Submit + wait under the kernel's breaker. ``topk``/``score`` have
        no row cache to degrade from: an open breaker sheds with a typed
        :class:`Unavailable`; dispatch failures feed the breaker and
        propagate."""
        br = self.breakers.get(kernel)
        if br is not None and not br.allow():
            self.registry.counter(f"serve.{kernel}.unavailable").inc()
            ctx = request_trace.current()
            if ctx is not None:
                ctx.annotate(breaker="open")
            raise Unavailable(f"{kernel}: breaker open; request shed")
        try:
            req = self._batchers[kernel].submit(payload, n=n)
            result = _wait(req)
        except Overloaded:
            raise  # queue pressure, not kernel health
        except Exception:
            if br is not None:
                br.record_failure()
            raise
        if br is not None:
            br.record_success()
        self._trace_dispatch(request_trace.current(), req)
        return result

    # -- dispatch (batcher thread) ----------------------------------------

    def _maybe_fault(self, kernel: str) -> None:
        """Chaos injection point, once per dispatched batch: the hook may
        raise (``serve_io_error``) or stall (``serve_slow``) exactly where a
        sick storage/device read would. No-op (one attribute load) when no
        hook is installed."""
        hook = self.fault_hook
        if hook is None:
            return
        idx = self._dispatch_seq[kernel]
        self._dispatch_seq[kernel] = idx + 1
        hook(kernel, idx)

    def _dispatch_pull(self, batch: List[_Request]) -> None:
        self._maybe_fault("pull")
        by_table: Dict[str, List[_Request]] = {}
        for req in batch:
            by_table.setdefault(req.payload["table"], []).append(req)
        for name, reqs in by_table.items():
            ids = np.concatenate([r.payload["ids"] for r in reqs])
            t_disp = time.perf_counter()
            rows, buckets, pad_rows = self._pull_padded(name, ids)
            kernel_ms = (time.perf_counter() - t_disp) * 1e3
            # split back per request; insert REAL rows into the cache (pad
            # rows never reach here — _pull_padded slices them off)
            version = reqs[0].payload["version"]
            if version == self.version:
                self.cache.put_many(name, version, ids, rows)
            off = 0
            for req in reqs:
                req.t_dispatch = t_disp
                req.kernel_ms = kernel_ms
                req.pad_buckets = buckets
                req.pad_rows = pad_rows
                req.result = rows[off : off + req.n]
                off += req.n
                req.event.set()

    def _pull_padded(
        self, name: str, ids: np.ndarray,
    ) -> Tuple[np.ndarray, Tuple[int, ...], int]:
        """Chunk at the largest bucket, pad each chunk to its bucket with
        the sentinel row, pull, slice the pads off. Pad rows are excluded
        from the pulled-rows counter (they count as ``pad_rows``) and are
        never cached. Returns ``(rows, buckets_used, pad_rows)`` so the
        dispatcher can stamp pad attribution onto each request's trace."""
        table = self._tables[name]
        cap = self.buckets[-1]
        out: List[np.ndarray] = []
        buckets_used: List[int] = []
        pad_total = 0
        for lo in range(0, len(ids), cap):
            chunk = ids[lo : lo + cap]
            b = bucket_for(len(chunk), self.buckets)
            pad = b - len(chunk)
            padded = np.concatenate(
                [chunk, np.full(pad, PAD_ROW, np.int32)]
            ) if pad else chunk
            if name in self.tier:
                vals = self._tier_pull(name, padded)
            else:
                vals = np.asarray(self._pull_fn(table, jnp.asarray(padded)))
            out.append(vals[: len(chunk)])
            buckets_used.append(b)
            pad_total += pad
            self.registry.counter("serve.pull.rows").inc(len(chunk))
            self.registry.counter("serve.pull.pad_rows").inc(pad)
        rows = np.concatenate(out) if out else np.zeros(
            (0, table.shape[1]), np.float32)
        return rows, tuple(buckets_used), pad_total

    def _dispatch_topk(self, batch: List[_Request]) -> None:
        self._maybe_fault("topk")
        by_key: Dict[Tuple[str, int, bool], List[_Request]] = {}
        for req in batch:
            p = req.payload
            by_key.setdefault(
                (p["table"], p["k"], p["normalize"]), []
            ).append(req)
        for (name, k, normalize), reqs in by_key.items():
            table = self._tables[name]
            queries = np.concatenate([r.payload["queries"] for r in reqs])
            t_disp = time.perf_counter()
            pad_total = 0
            buckets_used: List[int] = []
            cap = self.buckets[-1]
            all_s: List[np.ndarray] = []
            all_i: List[np.ndarray] = []
            for lo in range(0, len(queries), cap):
                chunk = queries[lo : lo + cap]
                b = bucket_for(len(chunk), self.buckets)
                pad = b - len(chunk)
                padded = np.concatenate(
                    [chunk, np.zeros((pad, chunk.shape[1]), np.float32)]
                ) if pad else chunk
                if name in self.tier:
                    # exhaustive scans never fault the cache: stream the host
                    # master through the device in tiles instead
                    s, i = self._topk_master(name, padded, k, normalize)
                else:
                    s, i = topk_tiled(
                        table, jnp.asarray(padded), k=k,
                        tile_rows=self.topk_tile_rows, normalize=normalize,
                    )
                all_s.append(np.asarray(s)[: len(chunk)])
                all_i.append(np.asarray(i)[: len(chunk)])
                buckets_used.append(b)
                pad_total += pad
                self.registry.counter("serve.topk.queries").inc(len(chunk))
                self.registry.counter("serve.topk.pad_rows").inc(pad)
            s = np.concatenate(all_s)
            i = np.concatenate(all_i)
            kernel_ms = (time.perf_counter() - t_disp) * 1e3
            off = 0
            for req in reqs:
                req.t_dispatch = t_disp
                req.kernel_ms = kernel_ms
                req.pad_buckets = tuple(buckets_used)
                req.pad_rows = pad_total
                req.result = (s[off : off + req.n], i[off : off + req.n])
                off += req.n
                req.event.set()

    def _score_impl(self, table, dense, feats):
        b, f = feats.shape
        mask = feats >= 0
        rows = self.scorer._rows(feats).reshape(-1)
        pulled = pull_rows(
            table, rows, mesh=self.mesh, comm_dtype=self.comm_dtype
        ).reshape(b, f, self.scorer.table_dim)
        logits = self.scorer.forward(pulled, dense, mask)
        return jax.nn.sigmoid(logits)

    def _score_tiered(self, feats: np.ndarray) -> np.ndarray:
        """Score through the cache tier: hash the fields eagerly, fault the
        rows via the shared pull path, then run the forward pass on the
        gathered embeddings (padding fields hash like real rows but their
        gathered values are mask-zeroed by ``forward``)."""
        b, f = feats.shape
        feats_j = jnp.asarray(feats)
        rows = np.asarray(self.scorer._rows(feats_j)).reshape(-1)
        pulled = self._tier_pull(self.default_table, rows).reshape(
            b, f, self.scorer.table_dim)
        logits = self.scorer.forward(
            jnp.asarray(pulled), self._dense, feats_j >= 0)
        return np.asarray(jax.nn.sigmoid(logits))

    def _dispatch_score(self, batch: List[_Request]) -> None:
        self._maybe_fault("score")
        table = self._tables[self.default_table]
        feats = np.concatenate([r.payload["feats"] for r in batch])
        t_disp = time.perf_counter()
        pad_total = 0
        buckets_used: List[int] = []
        cap = self.buckets[-1]
        outs: List[np.ndarray] = []
        for lo in range(0, len(feats), cap):
            chunk = feats[lo : lo + cap]
            b = bucket_for(len(chunk), self.buckets)
            pad = b - len(chunk)
            padded = np.concatenate(
                [chunk, np.full((pad, chunk.shape[1]), PAD_FIELD, np.int32)]
            ) if pad else chunk
            if self.default_table in self.tier:
                scores = self._score_tiered(padded)
            else:
                scores = np.asarray(
                    self._score_fn(table, self._dense, jnp.asarray(padded))
                )
            outs.append(scores[: len(chunk)])
            buckets_used.append(b)
            pad_total += pad
            self.registry.counter("serve.score.rows").inc(len(chunk))
            self.registry.counter("serve.score.pad_rows").inc(pad)
        scores = np.concatenate(outs)
        kernel_ms = (time.perf_counter() - t_disp) * 1e3
        off = 0
        for req in batch:
            req.t_dispatch = t_disp
            req.kernel_ms = kernel_ms
            req.pad_buckets = tuple(buckets_used)
            req.pad_rows = pad_total
            req.result = scores[off : off + req.n]
            off += req.n
            req.event.set()

    # -- request tracing ---------------------------------------------------

    def _trace_begin(self, kernel: str, **baggage):
        """Join the thread's active request context (a fleet leg carried one
        in), or mint a fresh trace when this servant fronts the request and
        a tracer is attached. Returns ``(ctx, owned)`` — only an owned
        context is finished here."""
        ctx = request_trace.current()
        if ctx is not None:
            return ctx, False
        rt = self.request_tracer
        if rt is None:
            return None, False
        try:
            return rt.start(kernel, **baggage), True
        except Exception:
            return None, False  # tracing never blocks the serve path

    def _trace_end(self, kernel: str, ctx, owned: bool, t0: float,
                   error: Optional[BaseException] = None) -> None:
        ms = (time.perf_counter() - t0) * 1e3
        if self.slo is not None:
            try:
                self.slo.record(kernel, ms, ok=error is None)
            except Exception:
                pass  # record-keeping never blocks the serve path
        if owned and ctx is not None and self.request_tracer is not None:
            try:
                self.request_tracer.finish(ctx, error=error)
            except Exception:
                pass

    @staticmethod
    def _trace_dispatch(ctx, req: _Request) -> None:
        """Turn the dispatcher-thread stamps on ``req`` into retroactive
        child spans: admission-queue wait, then batch kernel time with the
        pad buckets it rode in."""
        if ctx is None or not req.t_dispatch:
            return
        try:
            ctx.add_span("queue-wait", int(req.t0 * 1e9),
                         int((req.t_dispatch - req.t0) * 1e9))
            ctx.add_span("kernel", int(req.t_dispatch * 1e9),
                         int(req.kernel_ms * 1e6),
                         buckets=list(req.pad_buckets),
                         pad_rows=req.pad_rows)
        except Exception:
            pass  # tracing never blocks the serve path

    def _annotate_freshness(self, ctx) -> None:
        """Stamp the freshness the request is served at: the table version
        plus the delta-subscriber watermark (trainer step / age)."""
        fr = self._freshness
        if fr is None:
            return
        try:
            ctx.annotate(watermark_step=fr.applied_step,
                         watermark_age_ms=round(fr.last_lag_ms, 3))
        except Exception:
            pass

    # -- metrics -----------------------------------------------------------

    def _observe(self, kernel: str, t0: float, units: int, ctx=None) -> None:
        ms = (time.perf_counter() - t0) * 1e3
        self._latency[kernel].append(ms)
        # exemplar: only link traces that will actually be kept (sampled or
        # already anomalous) — a dropped trace id would dangle
        tid = ctx.trace_id if ctx is not None and \
            (ctx.sampled or ctx.anomalous) else None
        self.registry.histogram(f"serve.{kernel}.latency_ms").observe(
            ms, trace_id=tid)
        self.registry.counter(f"serve.{kernel}.requests").inc()

    def _on_breaker_transition(self, kernel: str, old: str, new: str,
                               snapshot: Dict) -> None:
        """Every breaker state change is observable: a counter bump plus a
        structured ``breaker`` ledger event (trip AND recovery — the failure
        timeline should show both edges)."""
        self.registry.counter(f"serve.{kernel}.breaker_{new}").inc()
        if self.ledger is not None:
            try:
                self.ledger.append("breaker", {
                    "source": "serving",
                    "kernel": kernel,
                    "from": old,
                    "to": new,
                    **{k: snapshot[k] for k in
                       ("consecutive_failures", "threshold", "trips",
                        "recoveries", "last_recovery_latency_ms")},
                })
            except Exception:
                pass  # record-keeping never blocks the serve path

    def _note_degraded(self, kernel: str, rows: int, reason: str) -> None:
        """Count a degraded (stale-LRU) serve — a separate ledger/metric
        stream from the fresh counters, rate-limited like overloads."""
        ctx = request_trace.current()
        if ctx is not None:
            ctx.mark_anomaly("degraded")
            ctx.annotate(degraded_reason=reason)
        self.registry.counter(f"serve.{kernel}.degraded").inc()
        self.registry.counter("serve.degraded_hits").inc(rows)
        total = int(self.registry.counter(f"serve.{kernel}.degraded").value)
        if self.ledger is not None and (total == 1 or total % 100 == 0):
            try:
                self.ledger.append("degraded", {
                    "source": "serving",
                    "kernel": kernel,
                    "reason": reason,
                    "rows": rows,
                    "degraded_total": total,
                })
                self._degraded_events = total
            except Exception:
                pass

    def _note_shed(self, kernel: str) -> None:
        ctx = request_trace.current()
        if ctx is not None:
            ctx.mark_anomaly("shed")
        self.registry.counter(f"serve.{kernel}.shed").inc()
        self.registry.counter("serve.shed").inc()
        total = int(self.registry.counter("serve.shed").value)
        # rate-limited overload events: the first shed and every 100th after
        if self.ledger is not None and (total == 1 or total % 100 == 0):
            self._append_overload(kernel, total)

    def _append_overload(self, kernel: str, total: int) -> None:
        try:
            self.ledger.append("overload", {
                "source": "serving",
                "kernel": kernel,
                "shed_total": total,
                "queue_depth": self._batchers[kernel].queue_depth,
            })
            self._shed_events = total
        except Exception:
            pass  # record-keeping never blocks the serve path

    def _flush_overloads(self, final: bool = False) -> None:
        total = int(self.registry.counter("serve.shed").value)
        if final and self.ledger is not None and total > self._shed_events:
            self._append_overload("all", total)

    def shed_count(self) -> int:
        return int(self.registry.counter("serve.shed").value)

    def queue_depths(self) -> Dict[str, int]:
        """Per-kernel admission-queue depth right now — the introspection
        surface the fleet router (and the serve REPL's ``stats``) reads to
        decide when an owner replica is deep enough to spill past."""
        return {k: b.depth for k, b in self._batchers.items()}

    def reset_metrics(self) -> None:
        for d in self._latency.values():
            d.clear()
        self.cache.hits = 0
        self.cache.misses = 0

    def stats(self) -> Dict:
        kernels = {}
        for name, samples in self._latency.items():
            s = list(samples)
            kernels[name] = {
                "count": len(s),
                "mean_ms": round(float(np.mean(s)), 4) if s else 0.0,
                "p50_ms": round(_percentile(s, 0.50), 4),
                "p95_ms": round(_percentile(s, 0.95), 4),
                "p99_ms": round(_percentile(s, 0.99), 4),
            }
        reg = self.registry
        return {
            "version": self.version,
            "step": self.step,
            "tables": {k: list(v.shape) for k, v in self._tables.items()},
            "kernels": kernels,
            "cache": {
                "rows": len(self.cache),
                "capacity": self.cache.capacity,
                "hits": self.cache.hits,
                "misses": self.cache.misses,
                "hit_rate": round(self.cache.hit_rate, 4),
            },
            "shed": {
                k: int(reg.counter(f"serve.{k}.shed").value)
                for k in ("pull", "topk", "score")
            },
            "shed_total": self.shed_count(),
            "pad_rows": {
                k: int(reg.counter(f"serve.{k}.pad_rows").value)
                for k in ("pull", "topk", "score")
            },
            "breakers": {k: br.snapshot() for k, br in self.breakers.items()},
            "degraded": {
                "enabled": self.degraded_enabled,
                "hits": int(reg.counter("serve.degraded_hits").value),
                **{k: int(reg.counter(f"serve.{k}.degraded").value)
                   for k in ("pull", "topk", "score")},
            },
            "unavailable": {
                k: int(reg.counter(f"serve.{k}.unavailable").value)
                for k in ("pull", "topk", "score")
            },
            **({"tiered": {
                **self._tier_stats.as_dict(),
                "tables": {
                    name: {"budget_slots": tt.budget,
                           "master_units": tt.master.units}
                    for name, tt in self.tier.items()
                },
            }} if self.tier else {}),
            **({"trace": self.request_tracer.stats()}
               if self.request_tracer is not None else {}),
            **({"slo": self.slo.snapshot()} if self.slo is not None else {}),
        }

    def health(self) -> Dict:
        """One-call liveness/availability report: overall ``status`` is
        ``"ok"`` when every breaker is closed, ``"degraded"`` otherwise —
        the Servant keeps answering in both cases, the caller just learns
        whether answers may be stale or shed."""
        reg = self.registry
        states = {k: br.state for k, br in self.breakers.items()}
        status = "ok" if all(s == CLOSED for s in states.values()) else "degraded"
        out = {
            "status": status,
            "version": self.version,
            "step": self.step,
            "tables": {k: list(v.shape) for k, v in self._tables.items()},
            "breakers": {k: br.snapshot() for k, br in self.breakers.items()},
            "degraded_enabled": self.degraded_enabled,
            "degraded_hits": int(reg.counter("serve.degraded_hits").value),
            "shed_total": self.shed_count(),
        }
        if self.tier:
            out["tier"] = {
                name: {"budget_slots": tt.budget,
                       "master_units": tt.master.units,
                       "resident": int((tt.unit_of >= 0).sum())}
                for name, tt in self.tier.items()
            }
        if self._freshness is not None:
            try:
                out["freshness"] = self._freshness.status()
            except Exception:
                pass  # introspection never blocks the health probe
        return out

    def attach_freshness(self, subscriber) -> None:
        """Surface a :class:`~swiftsnails_tpu.freshness.subscriber.
        DeltaSubscriber`'s watermark/lag/fallback state through
        :meth:`health`."""
        self._freshness = subscriber


def _int_list(raw: str, default: Sequence[int]) -> Tuple[int, ...]:
    """Parse a ``serve_batch_buckets``-style comma list, e.g. ``8,64``."""
    raw = (raw or "").strip()
    if not raw:
        return tuple(default)
    return tuple(int(tok) for tok in raw.replace(",", " ").split())
