"""Fused SGNS substep: gather -> loss/grads -> SGD writeback, one kernel.

The maximal fusion of the word2vec fast path (see models/word2vec.py): for
each block of ``P`` pairs sharing ``PN`` pooled negatives, the kernel DMAs
the center/context/pool rows into VMEM, computes the pooled-negative SGNS
gradients on the MXU, applies the SGD update in VMEM, and DMAs the updated
rows back — 2 row DMAs per touched row and zero HBM activation traffic,
versus gather + sort-merge + read-modify-write (3+ DMAs and two argsorts)
on the unfused path.

**Semantics: hogwild.** Rows duplicated within a block, colliding between
pool and context slots, or touched by two in-flight blocks race
(last-write-wins / stale-read). This is precisely the reference's
asynchronous-SGD behavior — M workers racing pushes on hot keys with no
cross-worker ordering (``SwiftWorker``'s async pull/push; the original
word2vec C implementation is hogwild across threads, and the reference's
lock striping orders single-key writes but not read-modify-write cycles).
The unfused path (``fused: 0``) keeps the deterministic merged semantics.

In interpret mode the grid runs sequentially, so the result is exactly the
"apply blocks in order, within a block V then U then pool writes, later
slot wins" reference that the unit test implements.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from swiftsnails_tpu.utils.compat import install_pallas_compat

install_pallas_compat()  # modern pltpu.CompilerParams / BlockSpec on jax 0.4.x


_WAIT_CHUNK = 64


def _wait_rows(row_ref, chunk_ref, sem, count):
    """Retire ``count`` single-row DMA completions in ~count/chunk scalar ops.

    The wait side of the DMA loops used to be one scalar op PER COPY —
    half of the ~60ns/op scalar floor every kernel family hits
    (docs/ARCHITECTURE.md round-5 ablation). A wait's decrement is
    derived from its descriptor size, and completions increment the
    shared semaphore in row-additive 32-byte granules (measured:
    tools/sem_probe.py — 15.8x on the wait loop of a bench-shaped DMA
    pipeline), so ONE wait on a ``ch``-row descriptor retires ``ch``
    equal-size single-row copies at once. ``row_ref``/``chunk_ref`` must
    match the row shape+dtype of every copy sharing ``sem`` (the step
    wrappers enforce equal table dtypes).
    """
    ch = chunk_ref.shape[0]  # the chunk wait retires exactly this many rows
    nch = count // ch

    def wch(_, c):
        pltpu.make_async_copy(chunk_ref, chunk_ref, sem).wait()
        return c

    jax.lax.fori_loop(0, nch, wch, 0)

    def w(_, c):
        pltpu.make_async_copy(row_ref, row_ref, sem).wait()
        return c

    jax.lax.fori_loop(0, count - nch * ch, w, 0)


def _kernel(in_rows_ref, pos_rows_ref, pool_rows_ref, lr_ref,
            in_t_in, out_t_in, in_table, out_table, loss_ref,
            v_buf, u_buf, p_buf, read_sems, write_sems,
            *, lam, inv_b, pairs, pool):
    del in_t_in, out_t_in
    # lr rides scalar prefetch (SMEM) so a decay schedule never recompiles
    lr = lr_ref[0]
    P, PN = pairs, pool
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)

    def dmas(b, slot, table_dir):
        """All row DMAs of block b. table_dir: 'read' or 'write'."""
        sems = read_sems if table_dir == "read" else write_sems

        def mk(buf, j, table, row):
            pair = (table.at[row], buf.at[slot, j])
            src, dst = pair if table_dir == "read" else pair[::-1]
            return pltpu.make_async_copy(src, dst, sems.at[slot])

        def v_dma(j, _):
            mk(v_buf, j, in_table, in_rows_ref[b * P + j]).start()
            return 0

        def u_dma(j, _):
            mk(u_buf, j, out_table, pos_rows_ref[b * P + j]).start()
            return 0

        def p_dma(q, _):
            mk(p_buf, q, out_table, pool_rows_ref[b * PN + q]).start()
            return 0

        jax.lax.fori_loop(0, P, v_dma, 0)
        jax.lax.fori_loop(0, P, u_dma, 0)
        jax.lax.fori_loop(0, PN, p_dma, 0)

    def wait_all(b, slot, table_dir):
        sems = read_sems if table_dir == "read" else write_sems
        # equal-size copies share the semaphore; the (fixed, in-bounds)
        # refs only supply the wait size
        ch = min(_WAIT_CHUNK, P)
        _wait_rows(v_buf.at[slot, 0], v_buf.at[slot, :ch],
                   sems.at[slot], 2 * P + PN)

    @pl.when(i == 0)
    def _():
        dmas(0, 0, "read")

    @pl.when(i + 1 < nblocks)
    def _():
        slot_next = (i + 1) % 2

        @pl.when(i >= 1)
        def _():
            wait_all(i - 1, slot_next, "write")

        dmas(i + 1, slot_next, "read")

    slot = i % 2
    wait_all(i, slot, "read")

    # ---- compute (f32, MXU for the pair x pool logits) -------------------
    vv = v_buf[slot].astype(jnp.float32).reshape(P, -1)
    uv = u_buf[slot].astype(jnp.float32).reshape(P, -1)
    pv = p_buf[slot].astype(jnp.float32).reshape(PN, -1)

    # keepdims throughout: rank-1 [P] intermediates hit a Mosaic relayout
    # limitation (implicit-dim vector<1x512xf32> -> replicated-lane form)
    pos = jnp.sum(vv * uv, axis=1, keepdims=True)  # [P, 1]
    neg = jax.lax.dot_general(
        vv, pv, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [P, PN]

    g_pos = (jax.nn.sigmoid(pos) - 1.0) * inv_b  # [P, 1]
    g_neg = (lam * inv_b) * jax.nn.sigmoid(neg)  # [P, PN]

    dv = g_pos * uv + jax.lax.dot_general(
        g_neg, pv, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    du = g_pos * vv
    dp = jax.lax.dot_general(
        g_neg, vv, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [PN, D]

    shape_v = v_buf[slot].shape
    v_buf[slot] = (vv - lr * dv).reshape(shape_v).astype(v_buf.dtype)
    u_buf[slot] = (uv - lr * du).reshape(shape_v).astype(u_buf.dtype)
    p_buf[slot] = (pv - lr * dp).reshape(p_buf[slot].shape).astype(p_buf.dtype)

    loss = -(jax.nn.log_sigmoid(pos).sum() + lam * jax.nn.log_sigmoid(-neg).sum())
    loss_ref[...] = jnp.full(loss_ref.shape, loss * inv_b, dtype=jnp.float32)

    # ---- writeback -------------------------------------------------------
    dmas(i, slot, "write")

    @pl.when(i == nblocks - 1)
    def _():
        wait_all(i, slot, "write")

        @pl.when(nblocks >= 2)
        def _():
            wait_all(i - 1, (i - 1) % 2, "write")


_ROW_MASK = (1 << 30) - 1  # c_rows: row id | is-last-occurrence << 30
_SLOT_MASK = (1 << 20) - 1  # ctx_slot: buffer slot | is-last-occurrence << 20


def _last_occurrence(rows: jax.Array, valid: jax.Array) -> jax.Array:
    """Per block-row: True where element k is the LAST valid occurrence of
    its value (``rows`` [NB, K] i32, ``valid`` [NB, K] bool)."""
    nb, k = rows.shape
    big = jnp.int32(2**31 - 1)
    keyed = jnp.where(valid, rows, big)
    # stable sort groups equal rows in ascending original index, so the last
    # element of each run is the last occurrence
    order = jnp.argsort(keyed, axis=1, stable=True)
    srow = jnp.take_along_axis(keyed, order, axis=1)
    last_sorted = jnp.concatenate(
        [srow[:, :-1] != srow[:, 1:], jnp.ones((nb, 1), bool)], axis=1
    ) & (srow != big)
    out = jnp.zeros((nb, k), bool)
    return out.at[jnp.arange(nb)[:, None], order].set(last_sorted)


def _grouped_kernel(c_rows_ref, ctx_rows_ref, ctx_slot_ref, nctx_ref,
                    nwc_ref, nwu_ref, pool_rows_ref, lr_ref, mask_in, in_t_in,
                    out_t_in, in_table, out_table, loss_ref,
                    v_buf, u_buf, p_buf, read_sems, write_sems,
                    *, lam, inv_b, pc, cw, pool):
    """Center-major fused SGNS substep (see fused_sgns_grouped_step).

    The flat kernel issues ~4.25 row copies per pair; per-copy issue cost is
    the measured bound (throughput is flat in row size AND row locality).
    Grouping by center loads each center row once for its whole window and
    skips padded context slots entirely (host-compacted copy list, dynamic
    wait counts), cutting copies/pair to ~2.5. Writeback skips every
    non-LAST duplicate-row slot (flag bits packed by the wrapper): under
    last-write-wins those writes can never survive, so the final table is
    bit-identical with ~dup-fraction fewer write copies.
    """
    del in_t_in, out_t_in
    lr = lr_ref[0]
    PC, CW, PN = pc, cw, pool
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)
    cap = PC * CW

    def dmas(b, slot, table_dir):
        read = table_dir == "read"
        sems = read_sems if read else write_sems

        def mk(buf_at, table, row):
            pair = (table.at[row], buf_at)
            src, dst = pair if read else pair[::-1]
            return pltpu.make_async_copy(src, dst, sems.at[slot])

        def v_dma(p, _):
            v = c_rows_ref[b * PC + p]
            if read:
                mk(v_buf.at[slot, p], in_table, v & _ROW_MASK).start()
            else:
                @pl.when((v >> 30) != 0)
                def _():
                    mk(v_buf.at[slot, p], in_table, v & _ROW_MASK).start()
            return 0

        def u_dma(k, _):
            # two-segment copy list (_cold_compact): the first nwu entries
            # are exactly the flagged last-occurrence writes, so the write
            # loop is bounded by nwu and issues UNCONDITIONALLY — no
            # ~60ns/slot branch over mostly-skipped entries
            s = ctx_slot_ref[b * cap + k]
            row = ctx_rows_ref[b * cap + k]
            mk(u_buf.at[slot, s & _SLOT_MASK], out_table, row).start()
            return 0

        def p_dma(q, _):
            mk(p_buf.at[slot, q], out_table, pool_rows_ref[b * PN + q]).start()
            return 0

        jax.lax.fori_loop(0, PC, v_dma, 0)
        # read: all real slots; write: flagged prefix only
        jax.lax.fori_loop(0, nctx_ref[b] if read else nwu_ref[b], u_dma, 0)
        jax.lax.fori_loop(0, PN, p_dma, 0)

    def wait_all(b, slot, table_dir):
        read = table_dir == "read"
        sems = read_sems if read else write_sems
        count = (
            PC + PN + nctx_ref[b]
            if read
            else nwc_ref[b] + PN + nwu_ref[b]
        )
        wc = min(_WAIT_CHUNK, cap)
        _wait_rows(v_buf.at[slot, 0], u_buf.at[slot, :wc],
                   sems.at[slot], count)

    @pl.when(i == 0)
    def _():
        dmas(0, 0, "read")

    @pl.when(i + 1 < nblocks)
    def _():
        slot_next = (i + 1) % 2

        @pl.when(i >= 1)
        def _():
            wait_all(i - 1, slot_next, "write")

        dmas(i + 1, slot_next, "read")

    slot = i % 2
    wait_all(i, slot, "read")

    # ---- compute ([CW, PC] orientation: PC=lanes) ------------------------
    vv = v_buf[slot].astype(jnp.float32).reshape(PC, -1)  # [PC, D]
    uu = u_buf[slot].astype(jnp.float32).reshape(CW, PC, -1)  # [CW, PC, D]
    pv = p_buf[slot].astype(jnp.float32).reshape(PN, -1)  # [PN, D]
    mask = mask_in[0]  # [CW, PC], 1.0 on real context slots
    # pad slots were never DMA'd: whatever is in that VMEM (stale rows,
    # poison) must not reach the arithmetic — 0*NaN would still be NaN
    uu = jnp.where(mask[:, :, None] > 0, uu, 0.0)

    pos = jnp.sum(uu * vv[None, :, :], axis=-1)  # [CW, PC]
    n_real = jnp.sum(mask, axis=0, keepdims=True)  # [1, PC]
    neg = jax.lax.dot_general(
        vv, pv, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [PC, PN]

    g_pos = (jax.nn.sigmoid(pos) - 1.0) * inv_b * mask  # [CW, PC]
    # the pool is shared center-wide: each real pair contributes the same
    # negative term, so the per-center weight is its real-context count
    g_neg = (lam * inv_b) * jax.nn.sigmoid(neg) * n_real.reshape(PC, 1)

    dv = jnp.sum(g_pos[:, :, None] * uu, axis=0) + jax.lax.dot_general(
        g_neg, pv, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [PC, D]
    du = g_pos[:, :, None] * vv[None, :, :]  # [CW, PC, D]
    dp = jax.lax.dot_general(
        g_neg, vv, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [PN, D]

    v_shape = v_buf[slot].shape
    u_shape = u_buf[slot].shape
    v_buf[slot] = (vv - lr * dv).reshape(v_shape).astype(v_buf.dtype)
    u_buf[slot] = (
        (uu - lr * du).reshape(CW * PC, -1).reshape(u_shape).astype(u_buf.dtype)
    )
    p_buf[slot] = (pv - lr * dp).reshape(p_buf[slot].shape).astype(p_buf.dtype)

    loss = -(
        jnp.sum(jax.nn.log_sigmoid(pos) * mask)
        + lam * jnp.sum(jax.nn.log_sigmoid(-neg) * n_real.reshape(PC, 1))
    )
    loss_ref[...] = jnp.full(loss_ref.shape, loss * inv_b, dtype=jnp.float32)

    dmas(i, slot, "write")

    @pl.when(i == nblocks - 1)
    def _():
        wait_all(i, slot, "write")

        @pl.when(nblocks >= 2)
        def _():
            wait_all(i - 1, (i - 1) % 2, "write")


@functools.partial(
    jax.jit,
    static_argnames=("lam", "centers_per_block", "pool_size", "window",
                     "interpret"),
    donate_argnums=(0, 1),
)
def fused_sgns_grouped_step(
    in_table: jax.Array,
    out_table: jax.Array,
    centers: jax.Array,  # [N] row ids
    ctxs: jax.Array,  # [N, CW] row ids, -1 = pad
    pool_rows: jax.Array,  # [N // centers_per_block * pool_size]
    lr: float,
    lam: float,
    window: int,
    centers_per_block: int = 128,
    pool_size: int = 64,
    interpret: bool = False,
):
    """Center-major fused substep. Returns (in_table, out_table, loss).

    Loss/grads are normalized by the EXPECTED pair count ``N * (window+1)``
    (dynamic window b~U(1,window) gives 2*E[b] = window+1 pairs per center),
    so the per-pair update magnitude matches the flat kernel's 1/B. The
    in-kernel compaction (sort pads last per block) happens here in XLA.
    """
    n, cw = ctxs.shape
    pc, pn = centers_per_block, pool_size
    if n % pc:
        raise ValueError(f"centers {n} not a multiple of centers_per_block {pc}")
    nblocks = n // pc
    if pool_rows.shape[0] != nblocks * pn:
        raise ValueError(f"pool_rows {pool_rows.shape[0]} != {nblocks * pn}")
    cap = pc * cw
    inv_b = 1.0 / (n * (window + 1))

    if cap > _SLOT_MASK:
        raise ValueError(f"centers_per_block*2*window {cap} exceeds slot bits")
    if in_table.shape[0] > _ROW_MASK or out_table.shape[0] > _ROW_MASK:
        raise ValueError("table capacity exceeds 2^30 (row-id flag bit)")
    if in_table.shape[1:] != out_table.shape[1:] or in_table.dtype != out_table.dtype:
        raise ValueError("in/out tables must share row shape and dtype")

    # [CW, PC] orientation throughout (PC = lanes): flat slot k = c*PC + p
    flat = (
        ctxs.reshape(nblocks, pc, cw).transpose(0, 2, 1).reshape(nblocks, cap)
    ).astype(jnp.int32)
    valid = flat >= 0
    # compact real context slots to the front of each block's copy list,
    # with last-occurrence write flags (under last-write-wins only the
    # LAST write of a duplicated row within a block survives, so all
    # others are skipped in the writeback — bit-identical result, fewer
    # copies); one shared single-sort pass does both
    ctx_rows, ctx_slot, nctx, nwrite_u = _cold_compact(flat, valid)
    mask = valid.reshape(nblocks, cw, pc).astype(jnp.float32)

    c_blocks = centers.astype(jnp.int32).reshape(nblocks, pc)
    c_last = _last_occurrence(c_blocks, jnp.ones_like(c_blocks, bool))
    nwrite_c = c_last.sum(axis=1).astype(jnp.int32)
    c_packed = (c_blocks | jnp.where(c_last, 1 << 30, 0)).reshape(-1)

    kern = functools.partial(
        _grouped_kernel, lam=lam, inv_b=inv_b, pc=pc, cw=cw, pool=pn
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((1, cw, pc), lambda i, *_: (i, 0, 0)),  # mask
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 8, 128), lambda i, *_: (i, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, pc) + in_table.shape[1:], in_table.dtype),
            pltpu.VMEM((2, cap) + out_table.shape[1:], out_table.dtype),
            pltpu.VMEM((2, pn) + out_table.shape[1:], out_table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    new_in, new_out, loss_parts = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(in_table.shape, in_table.dtype),
            jax.ShapeDtypeStruct(out_table.shape, out_table.dtype),
            jax.ShapeDtypeStruct((nblocks, 8, 128), jnp.float32),
        ),
        input_output_aliases={9: 0, 10: 1},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(
        c_packed,
        ctx_rows.reshape(-1),
        ctx_slot.reshape(-1),
        nctx,
        nwrite_c,
        nwrite_u,
        pool_rows.astype(jnp.int32),
        jnp.asarray(lr, jnp.float32).reshape(1),
        mask,
        in_table,
        out_table,
    )
    return new_in, new_out, loss_parts[:, 0, 0].sum()


def _resident_kernel(ccold_rows_ref, ccold_slot_ref, ncc_ref, nwc_ref,
                     ctx_rows_ref, ctx_slot_ref, nctx_ref, nwu_ref,
                     pcold_rows_ref, pcold_slot_ref, npc_ref, nwp_ref, lr_ref,
                     hot_c_in, hot_u_in, hot_p_in, cold_u_in, mask_in,
                     in_t_in, out_t_in,
                     in_table, out_table, loss_ref,
                     v_buf, u_buf, p_buf, hot_in, hot_out,
                     read_sems, write_sems, bulk_sem,
                     *, lam, inv_b, pc, cw, pool, hot_n, ch):
    """Grouped kernel + VMEM-resident head rows (see fused_sgns_resident_step).

    The grouped kernel's throughput is bound by per-row DMA issue rate, and
    under a zipf vocabulary the head rows soak up most of the row traffic
    (ids are frequency-ranked, so "row < hot_n" = the head). This kernel
    keeps the first ``hot_n`` rows of BOTH tables resident in VMEM for the
    whole grid: one bulk DMA loads them at block 0 and one writes them back
    at the last block; per block, hot-row reads are one-hot matmuls out of
    the resident buffers (measured ~8 us per [cap x 1024] @ [1024, D]
    expansion — far below the ~50 ns/copy issue cost they replace) and
    hot-row updates are exact merged accumulations (H^T @ per-slot grads)
    into the resident buffers. Only tail ("cold") rows still move per-row.

    Semantics: cold rows keep the grouped kernel's hogwild behavior; hot
    rows become DETERMINISTIC sequential merged updates (duplicate hot slots
    within a block sum their gradients — the reference's merge_push_value
    semantics, sparsetable.h:176-179 — and block b reads every hot write of
    blocks < b). Strictly closer to the faithful path than the hogwild
    last-write-wins it replaces.
    """
    del in_t_in, out_t_in
    lr = lr_ref[0]
    PC, CW, PN, HOT, CH = pc, cw, pool, hot_n, ch
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)
    cap = PC * CW
    s_t, lanes = in_table.shape[1], in_table.shape[2]
    dp = s_t * lanes
    f32 = jnp.float32

    def bulk_start(table_dir):
        for tbl, buf in ((in_table, hot_in), (out_table, hot_out)):
            src, dst = (tbl.at[pl.ds(0, HOT)], buf)
            if table_dir == "write":
                src, dst = dst, src
            pltpu.make_async_copy(src, dst, bulk_sem).start()

    def bulk_wait():
        for _ in range(2):  # equal sizes: each wait retires one copy
            pltpu.make_async_copy(hot_in, hot_in, bulk_sem).wait()

    def dmas(b, slot, table_dir):
        read = table_dir == "read"
        sems = read_sems if read else write_sems

        def mk(buf_at, table, row):
            pair = (table.at[row], buf_at)
            src, dst = pair if read else pair[::-1]
            return pltpu.make_async_copy(src, dst, sems.at[slot])

        def cold_dma(rows_ref, slot_ref, buf, table, stride):
            # two-segment lists (_cold_compact): write loops are bounded by
            # the flagged-write count and issue unconditionally
            def go(k, _):
                row = rows_ref[b * stride + k]
                sl = slot_ref[b * stride + k]
                mk(buf.at[slot, sl & _SLOT_MASK], table, row).start()
                return 0
            return go

        jax.lax.fori_loop(
            0, ncc_ref[b] if read else nwc_ref[b],
            cold_dma(ccold_rows_ref, ccold_slot_ref, v_buf, in_table, PC), 0)
        jax.lax.fori_loop(
            0, nctx_ref[b] if read else nwu_ref[b],
            cold_dma(ctx_rows_ref, ctx_slot_ref, u_buf, out_table, cap), 0)
        jax.lax.fori_loop(
            0, npc_ref[b] if read else nwp_ref[b],
            cold_dma(pcold_rows_ref, pcold_slot_ref, p_buf, out_table, PN), 0)

    def wait_all(b, slot, table_dir):
        read = table_dir == "read"
        sems = read_sems if read else write_sems
        count = (
            ncc_ref[b] + nctx_ref[b] + npc_ref[b]
            if read
            else nwc_ref[b] + nwu_ref[b] + nwp_ref[b]
        )
        wc = min(_WAIT_CHUNK, cap)
        _wait_rows(v_buf.at[slot, 0], u_buf.at[slot, :wc],
                   sems.at[slot], count)

    @pl.when(i == 0)
    def _():
        bulk_start("read")
        dmas(0, 0, "read")
        bulk_wait()

    @pl.when(i + 1 < nblocks)
    def _():
        slot_next = (i + 1) % 2

        @pl.when(i >= 1)
        def _():
            wait_all(i - 1, slot_next, "write")

        dmas(i + 1, slot_next, "read")

    slot = i % 2
    wait_all(i, slot, "read")

    # ---- hot-row expansion (pass 1): resident rows -> slot-ordered values
    hot_u_idx = hot_u_in[0, 0]  # [cap] i32, sentinel HOT on pads/cold
    hot_c_idx = hot_c_in[0, 0]  # [PC]
    hot_p_idx = hot_p_in[0, 0]  # [PN]
    mask = mask_in[0]  # [CW, PC] f32, 1.0 on real (hot or cold) slots

    def expand(idx, buf, n_rows):
        """one_hot(idx) @ buf[0:HOT] -> [n_rows, dp]; zeros where idx==HOT."""
        acc = jnp.zeros((n_rows, dp), f32)
        for c0 in range(0, HOT, CH):
            j = jax.lax.broadcasted_iota(jnp.int32, (n_rows, CH), 1) + c0
            h = (j == idx[:, None]).astype(f32)
            acc = acc + jax.lax.dot_general(
                h, buf[pl.ds(c0, CH)].reshape(CH, dp).astype(f32),
                (((1,), (0,)), ((), ())), preferred_element_type=f32)
        return acc

    uu_hot = expand(hot_u_idx, hot_out, cap)
    vc_hot = expand(hot_c_idx, hot_in, PC)
    pv_hot = expand(hot_p_idx, hot_out, PN)

    # minor-dim insert must happen on the 32-bit side (Mosaic can't reshape
    # i1 vectors), so compare after the [:, None]; the cold-slot mask comes
    # pre-flattened from the host (reshaping mask [CW, PC] -> [cap, 1]
    # in-kernel is an unsupported shape cast)
    is_hot_u = hot_u_idx[:, None] < HOT  # [cap, 1]
    is_hot_c = hot_c_idx[:, None] < HOT
    is_hot_p = hot_p_idx[:, None] < HOT
    cold_real = cold_u_in[0, 0][:, None] > 0  # [cap, 1]

    # merged slot values: hot from expansion, cold from DMA, pads zero
    # (cold-slot VMEM at hot/pad positions was never DMA'd — poison must not
    # reach arithmetic, so where() everywhere)
    vv = jnp.where(is_hot_c, vc_hot, v_buf[slot].astype(f32).reshape(PC, dp))
    uu = jnp.where(
        is_hot_u, uu_hot,
        jnp.where(cold_real, u_buf[slot].astype(f32).reshape(cap, dp), 0.0))
    pv = jnp.where(is_hot_p, pv_hot, p_buf[slot].astype(f32).reshape(PN, dp))

    # ---- compute (identical math to the grouped kernel) ------------------
    uu3 = uu.reshape(CW, PC, dp)
    pos = jnp.sum(uu3 * vv[None, :, :], axis=-1)  # [CW, PC]
    n_real = jnp.sum(mask, axis=0, keepdims=True)  # [1, PC]
    neg = jax.lax.dot_general(
        vv, pv, (((1,), (1,)), ((), ())), preferred_element_type=f32
    )  # [PC, PN]

    g_pos = (jax.nn.sigmoid(pos) - 1.0) * inv_b * mask  # [CW, PC]
    g_neg = (lam * inv_b) * jax.nn.sigmoid(neg) * n_real.reshape(PC, 1)

    dv = jnp.sum(g_pos[:, :, None] * uu3, axis=0) + jax.lax.dot_general(
        g_neg, pv, (((1,), (0,)), ((), ())), preferred_element_type=f32
    )  # [PC, dp]
    du_flat = (g_pos[:, :, None] * vv[None, :, :]).reshape(cap, dp)
    dq = jax.lax.dot_general(
        g_neg, vv, (((0,), (0,)), ((), ())), preferred_element_type=f32
    )  # [PN, dp]

    v_shape = v_buf[slot].shape
    v_buf[slot] = (vv - lr * dv).reshape(v_shape).astype(v_buf.dtype)
    u_buf[slot] = (
        (uu - lr * du_flat).reshape(u_buf[slot].shape).astype(u_buf.dtype)
    )
    p_buf[slot] = (pv - lr * dq).reshape(p_buf[slot].shape).astype(p_buf.dtype)

    # ---- hot-row merged updates (pass 2): H^T @ grads into residents -----
    for c0 in range(0, HOT, CH):
        def acc_t(idx, grads, n_rows):
            jt = jax.lax.broadcasted_iota(jnp.int32, (CH, n_rows), 0) + c0
            ht = (jt == idx[None, :]).astype(f32)
            return jax.lax.dot_general(
                ht, grads, (((1,), (0,)), ((), ())), preferred_element_type=f32)

        d_out = acc_t(hot_u_idx, du_flat, cap) + acc_t(hot_p_idx, dq, PN)
        hot_out[pl.ds(c0, CH)] = (
            hot_out[pl.ds(c0, CH)].reshape(CH, dp).astype(f32) - lr * d_out
        ).reshape(CH, s_t, lanes).astype(hot_out.dtype)
        d_in = acc_t(hot_c_idx, dv, PC)
        hot_in[pl.ds(c0, CH)] = (
            hot_in[pl.ds(c0, CH)].reshape(CH, dp).astype(f32) - lr * d_in
        ).reshape(CH, s_t, lanes).astype(hot_in.dtype)

    loss = -(
        jnp.sum(jax.nn.log_sigmoid(pos) * mask)
        + lam * jnp.sum(jax.nn.log_sigmoid(-neg) * n_real.reshape(PC, 1))
    )
    loss_ref[...] = jnp.full(loss_ref.shape, loss * inv_b, dtype=jnp.float32)

    dmas(i, slot, "write")

    @pl.when(i == nblocks - 1)
    def _():
        wait_all(i, slot, "write")

        @pl.when(nblocks >= 2)
        def _():
            wait_all(i - 1, (i - 1) % 2, "write")

        bulk_start("write")
        bulk_wait()


def effective_hot_rows(hot_rows: int, *capacities: int) -> tuple[int, int]:
    """(hot_n, ch): the resident row count the kernel will actually use.

    ``hot_rows`` is clipped to the table capacities and rounded down to the
    one-hot chunk size (256, or a multiple of 8 below 256). Exposed so
    callers (the trainer, logs) can see the real value instead of a silent
    round-down; returns ``(0, 0)`` when no resident rows are possible.
    """
    hot_n = min(hot_rows, *capacities)
    if hot_n >= 256:
        hot_n -= hot_n % 256
        ch = 256
    else:
        hot_n -= hot_n % 8
        ch = hot_n
    return (hot_n, ch) if hot_n > 0 else (0, 0)


# Mosaic scoped-VMEM grant for the resident kernel (see CompilerParams
# below); the budget check keeps a margin for Mosaic's own temporaries.
_RESIDENT_VMEM_BYTES = 100 * 1024 * 1024


def _check_resident_vmem(hot_n, pc, cap, pn, row_shape, dtype):
    """Fail fast with a clear message instead of a Mosaic stack OOM."""
    import math

    row_bytes = math.prod(row_shape) * jnp.dtype(dtype).itemsize
    dp_f32 = math.prod(row_shape) * 4
    scratch = (2 * (pc + cap + pn) + 2 * hot_n) * row_bytes
    # f32 working set: merged slot values + grads for cap/pc/pn slots, twice
    # over for where-selects and update temporaries
    working = 4 * dp_f32 * (cap + pc + pn)
    # one-hot expand temporaries: the [n_rows, ch] one-hot + iota broadcast
    # intermediates of the head-expansion loops (previously uncounted — a
    # large hot_n could pass the check and still hit an opaque Mosaic OOM)
    ch = 256 if hot_n >= 256 else hot_n
    onehot = 4 * 2 * (cap + pc + pn) * ch
    need = scratch + working + onehot
    if need > _RESIDENT_VMEM_BYTES:
        raise ValueError(
            f"resident kernel VMEM estimate {need / 2**20:.1f} MiB exceeds "
            f"the {_RESIDENT_VMEM_BYTES / 2**20:.0f} MiB budget "
            f"(hot_rows={hot_n}, centers_per_block={pc}, ctx slots={cap}, "
            f"pool={pn}); lower hot_rows or centers_per_block"
        )


def _check_dedup_vmem(u_cap, pc, cap, pn, row_shape, dtype, hot_n=0):
    """Dedup-shaped twin of :func:`_check_resident_vmem`: fail fast with a
    clear message instead of an opaque Mosaic OOM when ``u_cap`` /
    ``centers_per_block`` push the scratch + f32 working set past the
    scoped-VMEM grant. ``hot_n > 0`` models the COMPOSED kernel, whose
    scratch is the UNION of the dedup buffers and both resident head
    buffers — two independent single-kernel checks would each pass a
    config whose combined footprint overflows."""
    import math

    row_bytes = math.prod(row_shape) * jnp.dtype(dtype).itemsize
    dp_f32 = math.prod(row_shape) * 4
    # double-buffered v/u/p/u_uniq scratch + the resident head buffers
    scratch = 2 * (pc + cap + pn + u_cap) * row_bytes + 2 * hot_n * row_bytes
    # f32 working set: merged slot values + grads (cap/pc/pn), twice over
    # for where-selects and update temporaries, plus the one-hot broadcast
    # accumulator and the unique-row update temporaries
    working = 4 * dp_f32 * (cap + pc + pn) + 2 * dp_f32 * u_cap
    # one-hot expand/broadcast temporaries (ADVICE r4): the [cap, ch] /
    # [ch, cap] one-hot + iota intermediates of the unique-broadcast loops
    # and, in the composed kernel, the [n_rows, ch_h] head-expansion
    # one-hots — live alongside the working set and previously uncounted
    ch = next(d for d in (256, 128, 64, 32, 16, 8) if u_cap % d == 0)
    ch_h = 256 if hot_n >= 256 else hot_n
    onehot = 4 * (2 * 2 * cap * ch + 2 * (u_cap + pc + pn + cap) * ch_h)
    need = scratch + working + onehot
    if need > _RESIDENT_VMEM_BYTES:
        kind = "composed dedup+resident" if hot_n else "dedup"
        raise ValueError(
            f"{kind} kernel VMEM estimate {need / 2**20:.1f} MiB exceeds "
            f"the {_RESIDENT_VMEM_BYTES / 2**20:.0f} MiB budget "
            f"(u_cap={u_cap}, hot_rows={hot_n}, centers_per_block={pc}, "
            f"ctx slots={cap}, pool={pn}); lower u_cap, hot_rows, or "
            "centers_per_block"
        )


# sort key for pad/non-member entries. Plain int, NOT jnp.int32(...): a
# module-level jnp array would eagerly initialize the default backend at
# import — on this tunnel that means grabbing the single-client TPU grant
# before any platform pinning can run. Weak-typed int promotes to i32
# against the i32 row arrays.
_BIG = 2**31 - 1


# How prep materializes position-indexed arrays: "scatter" uses XLA
# scatter (.at[].set with computed targets), "sort" uses one more stable
# variadic sort keyed by the target position. Both are exact; which is
# faster depends on how the backend lowers scatter (TPU scatters can
# serialize) — tools/dedup_profile.py A/Bs the prologue under each.
_PREP_IMPLS = ("scatter", "sort")


def _validate_prep_impl(impl: str) -> str:
    # a typo'd env value must fail loudly, not silently fall through to
    # scatter (ADVICE r5) — the A/B tool's whole point is knowing which ran
    if impl not in _PREP_IMPLS:
        raise ValueError(
            f"SSN_PREP_IMPL must be one of {_PREP_IMPLS}, got {impl!r}")
    return impl


_PREP_IMPL = _validate_prep_impl(os.environ.get("SSN_PREP_IMPL", "scatter"))


def get_prep_impl() -> str:
    return _PREP_IMPL


def set_prep_impl(impl: str) -> str:
    """Switch the prep placement implementation at runtime; returns the
    previous value (so callers can restore it in a ``finally``).

    The impl is read at TRACE time, so the jit caches of every step function
    whose jaxpr bakes it in are cleared on an actual switch — without this,
    a cached trace would silently keep running the old impl (the failure
    mode ``tools/dedup_profile.py`` used to hand-patch around).
    """
    global _PREP_IMPL
    prev = _PREP_IMPL
    _PREP_IMPL = _validate_prep_impl(impl)
    if prev != _PREP_IMPL:
        for step_fn in (
            fused_sgns_step,
            fused_sgns_grouped_step,
            fused_sgns_resident_step,
            fused_sgns_dedup_step,
            fused_sgns_dedup_resident_step,
        ):
            clear = getattr(step_fn, "clear_cache", None)
            if clear is not None:
                clear()
    return prev


def _place_by_position(tgt, k, values):
    """Order ``values`` ([NB, K] each) by target position ``tgt`` ([NB, K],
    ``k`` = dropped). Entries with distinct tgt < k land at index tgt;
    positions no entry targets are 0 (scatter) or unspecified past the
    member count (sort) — consumers never read them."""
    nb = tgt.shape[0]
    if _PREP_IMPL == "sort":
        out = jax.lax.sort((tgt,) + tuple(values), dimension=1,
                           is_stable=True, num_keys=1)[1:]
        return tuple(out)
    rows_idx = jnp.arange(nb)[:, None]
    return tuple(
        jnp.zeros((nb, k + 1), v.dtype).at[rows_idx, tgt].set(v)[:, :k]
        for v in values)


def _two_segment_scatter(srow, sslot, select, last, slot_bits=20):
    """Scatter sorted entries into the two-segment copy-list order.

    ``srow``/``sslot`` [NB, K]: sorted row ids and their original slots;
    ``select`` marks the entries to keep, ``last`` their run-end
    (last-occurrence) flags. Output order: [flagged write entries][non-last
    duplicates][dropped] — the contract every kernel write loop relies on
    (read loops run [0, n_member), write loops [0, n_write), both
    unconditional). Returns (rows, packed_slot, n_member, n_write).
    """
    nb, k = srow.shape
    keep_last = select & last
    n_write = keep_last.sum(axis=1).astype(jnp.int32)
    n_member = select.sum(axis=1).astype(jnp.int32)
    pos = jnp.where(
        keep_last, jnp.cumsum(keep_last, axis=1) - 1,
        n_write[:, None] + jnp.cumsum(select & ~keep_last, axis=1) - 1)
    tgt = jnp.where(select, pos, k).astype(jnp.int32)
    rows, packed_slot = _place_by_position(
        tgt, k,
        (jnp.where(select, srow, 0),
         sslot | jnp.where(keep_last, 1 << slot_bits, 0)))
    return rows, packed_slot, n_member, n_write


def _unique_prep(keyed, u_cap, row_mask=-1):
    """Unique-list + overflow ("direct") prep from ONE stable variadic sort.

    ``keyed`` [NB, cap] i32: sort key per slot — the row id (optionally
    with priority bits above the id, e.g. the composed kernel's cold bit),
    ``_BIG`` on invalid/pad slots. ``row_mask`` strips priority bits off
    stored row ids (-1 = none). Returns ``(u_list [NB, u_cap] distinct
    rows in key order, nu, ctx_rows [NB, cap] overflow copies compacted
    front, ctx_slot (slot | last-occurrence << 20), nctx_direct,
    nwu_direct, uidx [NB, cap] unique rank per original slot (sentinel
    u_cap))``.

    The previous implementation paid three [NB, cap] argsorts here (rank
    assignment, overflow compaction, overflow last-occurrence) and the
    prep prologue rivaled the kernel itself. One sort carrying the
    original slots yields all three: in key order the overflow slots are
    exactly the entries whose unique rank >= u_cap — a CONTIGUOUS run
    between the in-list entries and the pads — so compaction is a cyclic
    roll, and the end of each equal-key run is the highest original slot
    (stable sort), i.e. the reference's last-write-wins flag.
    """
    nblocks, cap = keyed.shape
    slots = jnp.broadcast_to(
        jnp.arange(cap, dtype=jnp.int32)[None], (nblocks, cap))
    sr, sslot = jax.lax.sort((keyed, slots), dimension=1, is_stable=True,
                             num_keys=1)
    vs = sr != _BIG
    head = jnp.concatenate(
        [jnp.ones((nblocks, 1), bool), sr[:, 1:] != sr[:, :-1]], axis=1
    ) & vs
    ranks_sorted = jnp.cumsum(head, axis=1) - 1  # unique rank per sorted pos
    in_sorted = vs & (ranks_sorted < u_cap)
    direct_sorted = vs & ~in_sorted
    rows_idx = jnp.arange(nblocks)[:, None]
    srow = sr & row_mask  # row ids with any priority bits stripped
    # back to original slot order (sslot is a permutation per block, so a
    # stable sort keyed by it is an exact inverse): member slots get their
    # unique rank, overflow AND pad slots the u_cap sentinel — overflow
    # ("direct") is then just valid & uidx == u_cap at the caller
    rank_or_sentinel = jnp.where(in_sorted, ranks_sorted, u_cap)
    if _PREP_IMPL == "sort":
        uidx = jax.lax.sort((sslot, rank_or_sentinel), dimension=1,
                            is_stable=True, num_keys=1)[1]
    else:
        uidx = jnp.full((nblocks, cap), u_cap, jnp.int32).at[
            rows_idx, sslot].set(rank_or_sentinel)

    tgt = jnp.where(head & (ranks_sorted < u_cap), ranks_sorted, u_cap)
    u_list = jnp.zeros((nblocks, u_cap + 1), jnp.int32)
    u_list = u_list.at[rows_idx, tgt].set(
        jnp.where(head, srow, 0)
    )[:, :u_cap]
    nu = jnp.minimum(head.sum(axis=1), u_cap).astype(jnp.int32)

    # overflow compaction into the two-segment order the write loops need
    # (see _two_segment_scatter): read loops run [0, nctx_direct), write
    # loops [0, nwu_direct), both with unconditional issues
    last_sorted = jnp.concatenate(
        [sr[:, :-1] != sr[:, 1:], jnp.ones((nblocks, 1), bool)], axis=1
    ) & vs
    ctx_rows, ctx_slot, nctx_direct, nwu_direct = _two_segment_scatter(
        srow, sslot, direct_sorted, last_sorted)
    return u_list, nu, ctx_rows, ctx_slot, nctx_direct, nwu_direct, uidx


def dedup_prep(centers, ctxs, pc, u_cap):
    """Per-block dedup prep for :func:`fused_sgns_dedup_step` (pure XLA).

    ``centers`` [N] row ids, ``ctxs`` [N, cw] (-1 pads), block-ordered.
    Ranks each block's distinct context rows in ASCENDING row-id order;
    the first ``u_cap`` get unique-list slots, the rest stay per-slot
    ("direct") copies. Returns the scalar-prefetch/BlockSpec operands of
    the dedup kernel: ``(c_packed [N], u_list [NB, u_cap], nu [NB],
    ctx_rows [NB, cap], ctx_slot [NB, cap], nctx_direct [NB],
    nw_packed [NB] (direct-ctx writes | center writes << 16),
    uidx [NB, cap], direct_real [NB, cap] f32, mask [NB, cw, pc] f32)``.

    Shared by the step wrapper and ``tools/dedup_profile.py`` so the
    profiled prologue can never drift from the shipped math. (If a native
    host-side prep is ever added it must be pinned bit-identical to this
    function by a test — none exists today.)
    """
    n, cw = ctxs.shape
    nblocks = n // pc
    cap = pc * cw
    flat = (
        ctxs.reshape(nblocks, pc, cw).transpose(0, 2, 1).reshape(nblocks, cap)
    ).astype(jnp.int32)
    valid = flat >= 0
    (u_list, nu, ctx_rows, ctx_slot, nctx_direct, nwu_direct,
     uidx) = _unique_prep(jnp.where(valid, flat, _BIG), u_cap)
    direct_real = (valid & (uidx >= u_cap)).astype(jnp.float32)
    mask = valid.reshape(nblocks, cw, pc).astype(jnp.float32)

    c_blocks = centers.astype(jnp.int32).reshape(nblocks, pc)
    c_last = _last_occurrence(c_blocks, jnp.ones_like(c_blocks, bool))
    nwrite_c = c_last.sum(axis=1).astype(jnp.int32)
    c_packed = (c_blocks | jnp.where(c_last, 1 << 30, 0)).reshape(-1)
    # write-count packing: nwu_ref carries direct-ctx writes (low 16 bits)
    # and center writes (high bits) — the wrapper's cap < 2^16 guard
    # bounds both
    nw_packed = (nwu_direct | (nwrite_c << 16)).astype(jnp.int32)
    return (c_packed, u_list, nu, ctx_rows, ctx_slot, nctx_direct,
            nw_packed, uidx, direct_real, mask)


def _cold_compact(rows, is_cold, slot_bits=20):
    """Compact cold entries to the front of each block's copy list.

    ``rows`` [NB, K] i32 row ids, ``is_cold`` [NB, K] bool. Returns
    (cold_rows [NB, K] — cold entries first, 0 elsewhere; packed_slot
    [NB, K] — original slot | is-last-occurrence << slot_bits; n_cold [NB];
    n_write [NB]).

    ONE variadic stable sort by row id (carrying original slots) does all
    the work: duplicate rows form runs whose END is the highest original
    slot — exactly the reference's last-write-wins flag — and non-cold/pad
    entries sink to the back. The previous implementation spent TWO
    [NB, K] argsorts here (slot-order compaction + a separate
    last-occurrence sort); prep sorts were ~the whole XLA prologue of the
    dedup/resident steps.

    TWO-SEGMENT ORDER: the first ``n_write`` entries are exactly the
    flagged (last-occurrence) copies, the rest of the first ``n_cold``
    are the non-last duplicates. Kernel read loops run [0, n_cold) as
    before; WRITE loops run [0, n_write) with an UNCONDITIONAL issue —
    the per-entry flag branch over mostly-skipped slots was a measured
    ~60ns/iteration of pure scalar-core waste (docs/ARCHITECTURE.md
    round-5 ablation; ~1340 skipped iterations per grouped block at the
    bench shape).

    Consumers depend only on the SET of (row, original slot) copies and
    on which slots carry write flags — both are order-invariant, so the
    reordering cannot change results.
    """
    nb, k = rows.shape
    keyed = jnp.where(is_cold, rows, _BIG)
    slots = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[None], (nb, k))
    sr, sslot = jax.lax.sort((keyed, slots), dimension=1, is_stable=True,
                             num_keys=1)
    vs = sr != _BIG
    last = jnp.concatenate(
        [sr[:, :-1] != sr[:, 1:], jnp.ones((nb, 1), bool)], axis=1
    ) & vs
    return _two_segment_scatter(sr, sslot, vs, last, slot_bits=slot_bits)


@functools.partial(
    jax.jit,
    static_argnames=("lam", "centers_per_block", "pool_size", "window",
                     "hot_rows", "interpret"),
    donate_argnums=(0, 1),
)
def fused_sgns_resident_step(
    in_table: jax.Array,
    out_table: jax.Array,
    centers: jax.Array,  # [N] row ids
    ctxs: jax.Array,  # [N, CW] row ids, -1 = pad
    pool_rows: jax.Array,  # [N // centers_per_block * pool_size]
    lr: float,
    lam: float,
    window: int,
    centers_per_block: int = 256,
    pool_size: int = 64,
    hot_rows: int = 1024,
    interpret: bool = False,
):
    """Center-major fused substep with VMEM-resident head rows.

    Returns (in_table, out_table, loss). Rows ``< hot_n`` (``hot_rows``
    clipped to capacity, rounded to the one-hot chunk size) of both tables
    live in VMEM across the whole grid; everything else matches
    :func:`fused_sgns_grouped_step`. Requires frequency-ranked row ids for
    the perf win (Vocab orders by count); correctness never depends on it.
    """
    n, cw = ctxs.shape
    pc, pn = centers_per_block, pool_size
    if n % pc:
        raise ValueError(f"centers {n} not a multiple of centers_per_block {pc}")
    nblocks = n // pc
    if pool_rows.shape[0] != nblocks * pn:
        raise ValueError(f"pool_rows {pool_rows.shape[0]} != {nblocks * pn}")
    cap = pc * cw
    inv_b = 1.0 / (n * (window + 1))
    if cap > _SLOT_MASK:
        raise ValueError(f"centers_per_block*2*window {cap} exceeds slot bits")

    # the bulk DMA retires both tables' copies on one semaphore with
    # equal-size waits — only sound when the row shapes/dtypes agree
    if in_table.shape[1:] != out_table.shape[1:] or in_table.dtype != out_table.dtype:
        raise ValueError(
            f"in/out tables must share row shape and dtype, got "
            f"{in_table.shape[1:]}/{in_table.dtype} vs "
            f"{out_table.shape[1:]}/{out_table.dtype}"
        )
    hot_n, ch = effective_hot_rows(hot_rows, in_table.shape[0], out_table.shape[0])
    if hot_n <= 0:
        raise ValueError("hot_rows too small; use fused_sgns_grouped_step")
    _check_resident_vmem(hot_n, pc, cap, pn, in_table.shape[1:], in_table.dtype)

    # [CW, PC] orientation throughout (PC = lanes): flat slot k = c*PC + p
    flat = (
        ctxs.reshape(nblocks, pc, cw).transpose(0, 2, 1).reshape(nblocks, cap)
    ).astype(jnp.int32)
    valid = flat >= 0
    is_hot = valid & (flat < hot_n)
    hot_u_idx = jnp.where(is_hot, flat, hot_n).astype(jnp.int32)
    cold_u = (valid & ~is_hot).astype(jnp.float32)  # [NB, cap] slot-major
    ctx_rows, ctx_slot, nctx, nwu = _cold_compact(flat, valid & ~is_hot)
    mask = valid.reshape(nblocks, cw, pc).astype(jnp.float32)

    c_blocks = centers.astype(jnp.int32).reshape(nblocks, pc)
    c_hot = c_blocks < hot_n
    hot_c_idx = jnp.where(c_hot, c_blocks, hot_n).astype(jnp.int32)
    cc_rows, cc_slot, ncc, nwc = _cold_compact(c_blocks, ~c_hot)

    p_blocks = pool_rows.astype(jnp.int32).reshape(nblocks, pn)
    p_hot = p_blocks < hot_n
    hot_p_idx = jnp.where(p_hot, p_blocks, hot_n).astype(jnp.int32)
    pc_rows, pc_slot, npc, nwp = _cold_compact(p_blocks, ~p_hot)

    kern = functools.partial(
        _resident_kernel, lam=lam, inv_b=inv_b, pc=pc, cw=cw, pool=pn,
        hot_n=hot_n, ch=ch,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=13,
        grid=(nblocks,),
        in_specs=[
            # [NB, 1, K] with block (1, 1, K): Mosaic wants the last two
            # block dims divisible by (8, 128) or equal to the array dims
            pl.BlockSpec((1, 1, pc), lambda i, *_: (i, 0, 0)),  # hot_c_idx
            pl.BlockSpec((1, 1, cap), lambda i, *_: (i, 0, 0)),  # hot_u_idx
            pl.BlockSpec((1, 1, pn), lambda i, *_: (i, 0, 0)),  # hot_p_idx
            pl.BlockSpec((1, 1, cap), lambda i, *_: (i, 0, 0)),  # cold_u
            pl.BlockSpec((1, cw, pc), lambda i, *_: (i, 0, 0)),  # mask
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 8, 128), lambda i, *_: (i, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, pc) + in_table.shape[1:], in_table.dtype),
            pltpu.VMEM((2, cap) + out_table.shape[1:], out_table.dtype),
            pltpu.VMEM((2, pn) + out_table.shape[1:], out_table.dtype),
            pltpu.VMEM((hot_n,) + in_table.shape[1:], in_table.dtype),
            pltpu.VMEM((hot_n,) + out_table.shape[1:], out_table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
    )
    new_in, new_out, loss_parts = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(in_table.shape, in_table.dtype),
            jax.ShapeDtypeStruct(out_table.shape, out_table.dtype),
            jax.ShapeDtypeStruct((nblocks, 8, 128), jnp.float32),
        ),
        input_output_aliases={18: 0, 19: 1},
        # resident buffers + double-buffered cold slots + expansion
        # intermediates exceed the default 16 MiB scoped-vmem budget; v5e has
        # 128 MiB VMEM — grant the kernel what it actually uses (same
        # constant the fail-fast budget check validates against)
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, vmem_limit_bytes=_RESIDENT_VMEM_BYTES
        ),
        interpret=interpret,
    )(
        cc_rows.reshape(-1), cc_slot.reshape(-1), ncc, nwc,
        ctx_rows.reshape(-1), ctx_slot.reshape(-1), nctx, nwu,
        pc_rows.reshape(-1), pc_slot.reshape(-1), npc, nwp,
        jnp.asarray(lr, jnp.float32).reshape(1),
        hot_c_idx[:, None, :], hot_u_idx[:, None, :], hot_p_idx[:, None, :],
        cold_u[:, None, :], mask,
        in_table, out_table,
    )
    return new_in, new_out, loss_parts[:, 0, 0].sum()


def _dedup_kernel(c_rows_ref, u_list_ref, nu_ref,
                  ctx_rows_ref, ctx_slot_ref, nctx_ref, nwu_ref,
                  pool_rows_ref, lr_ref,
                  uidx_in, direct_in, mask_in, in_t_in, out_t_in,
                  in_table, out_table, loss_ref,
                  v_buf, u_buf, p_buf, u_uniq,
                  read_sems, write_sems,
                  *, lam, inv_b, pc, cw, pool, u_cap, ch):
    """Center-major fused SGNS with per-block READ dedup of context rows.

    With block-ordered batches (adjacent windows overlap), a block of PC
    consecutive centers touches ~PC DISTINCT context rows across ~PC*(w+1)
    real slots. Instead of one DMA per SLOT (the grouped kernel), each
    distinct row is DMA'd ONCE into a compacted unique buffer and broadcast
    to its slots by a one-hot MXU matmul; updates accumulate back through
    the transpose (exact merged gradients per distinct row — the
    reference's merge_push_value semantics, sparsetable.h:176-179 — written
    back with ONE DMA per distinct row). Rows beyond the ``u_cap`` static
    unique capacity fall back to the grouped kernel's per-slot hogwild
    treatment, so correctness never depends on the locality assumption.
    """
    del in_t_in, out_t_in
    lr = lr_ref[0]
    PC, CW, PN, UC, CH = pc, cw, pool, u_cap, ch
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)
    cap = PC * CW
    dp = in_table.shape[1] * in_table.shape[2]
    f32 = jnp.float32

    def dmas(b, slot, table_dir):
        read = table_dir == "read"
        sems = read_sems if read else write_sems

        def mk(buf_at, table, row):
            pair = (table.at[row], buf_at)
            src, dst = pair if read else pair[::-1]
            return pltpu.make_async_copy(src, dst, sems.at[slot])

        def v_dma(p, _):
            v = c_rows_ref[b * PC + p]
            if read:
                mk(v_buf.at[slot, p], in_table, v & _ROW_MASK).start()
            else:
                @pl.when((v >> 30) != 0)
                def _():
                    mk(v_buf.at[slot, p], in_table, v & _ROW_MASK).start()
            return 0

        def u_dma(k, _):  # direct (overflow) ctx slots, per-slot
            # two-segment order (_unique_prep): write prefix is exactly the
            # flagged last-occurrence entries — unconditional issue
            s = ctx_slot_ref[b * cap + k]
            row = ctx_rows_ref[b * cap + k]
            mk(u_buf.at[slot, s & _SLOT_MASK], out_table, row).start()
            return 0

        def p_dma(q, _):
            mk(p_buf.at[slot, q], out_table, pool_rows_ref[b * PN + q]).start()
            return 0

        def uq_dma(j, _):  # one DMA per DISTINCT ctx row
            mk(u_uniq.at[slot, j], out_table, u_list_ref[b * UC + j]).start()
            return 0

        jax.lax.fori_loop(0, PC, v_dma, 0)
        jax.lax.fori_loop(
            0, nctx_ref[b] if read else nwu_ref[b] & 0xFFFF, u_dma, 0)
        jax.lax.fori_loop(0, PN, p_dma, 0)
        jax.lax.fori_loop(0, nu_ref[b], uq_dma, 0)

    def wait_all(b, slot, table_dir):
        read = table_dir == "read"
        sems = read_sems if read else write_sems
        # nwu_ref packs direct-ctx writes (low 16 bits) and center
        # last-occurrence writes (high bits) — see the wrapper
        count = (
            PC + nctx_ref[b] + PN + nu_ref[b]
            if read
            else (nwu_ref[b] & 0xFFFF) + (nwu_ref[b] >> 16) + PN + nu_ref[b]
        )
        wc = min(_WAIT_CHUNK, cap)
        _wait_rows(v_buf.at[slot, 0], u_buf.at[slot, :wc],
                   sems.at[slot], count)

    @pl.when(i == 0)
    def _():
        dmas(0, 0, "read")

    @pl.when(i + 1 < nblocks)
    def _():
        slot_next = (i + 1) % 2

        @pl.when(i >= 1)
        def _():
            wait_all(i - 1, slot_next, "write")

        dmas(i + 1, slot_next, "read")

    slot = i % 2
    wait_all(i, slot, "read")

    # ---- broadcast unique rows to their slots (one-hot MXU) --------------
    uidx = uidx_in[0, 0]  # [cap] i32, sentinel UC on pads/direct
    direct_real = direct_in[0, 0][:, None] > 0  # [cap, 1]
    mask = mask_in[0]  # [CW, PC]

    acc = jnp.zeros((cap, dp), f32)
    for c0 in range(0, UC, CH):
        j = jax.lax.broadcasted_iota(jnp.int32, (cap, CH), 1) + c0
        h = (j == uidx[:, None]).astype(f32)
        # entries >= nu were never DMA'd: 0 * poison-NaN would still be
        # NaN, so zero them by value before the matmul
        ji = jax.lax.broadcasted_iota(jnp.int32, (CH, 1), 0) + c0
        uq = jnp.where(
            ji < nu_ref[i],
            u_uniq[slot, pl.ds(c0, CH)].reshape(CH, dp).astype(f32), 0.0)
        acc = acc + jax.lax.dot_general(
            h, uq, (((1,), (0,)), ((), ())), preferred_element_type=f32)
    is_dedup = uidx[:, None] < UC  # [cap, 1]

    vv = v_buf[slot].astype(f32).reshape(PC, dp)
    uu = jnp.where(
        is_dedup, acc,
        jnp.where(direct_real, u_buf[slot].astype(f32).reshape(cap, dp), 0.0))
    pv = p_buf[slot].astype(f32).reshape(PN, dp)

    # ---- compute (identical math to the grouped kernel) ------------------
    uu3 = uu.reshape(CW, PC, dp)
    pos = jnp.sum(uu3 * vv[None, :, :], axis=-1)
    n_real = jnp.sum(mask, axis=0, keepdims=True)
    neg = jax.lax.dot_general(
        vv, pv, (((1,), (1,)), ((), ())), preferred_element_type=f32)

    g_pos = (jax.nn.sigmoid(pos) - 1.0) * inv_b * mask
    g_neg = (lam * inv_b) * jax.nn.sigmoid(neg) * n_real.reshape(PC, 1)

    dv = jnp.sum(g_pos[:, :, None] * uu3, axis=0) + jax.lax.dot_general(
        g_neg, pv, (((1,), (0,)), ((), ())), preferred_element_type=f32)
    du_flat = (g_pos[:, :, None] * vv[None, :, :]).reshape(cap, dp)
    dq = jax.lax.dot_general(
        g_neg, vv, (((0,), (0,)), ((), ())), preferred_element_type=f32)

    v_shape = v_buf[slot].shape
    v_buf[slot] = (vv - lr * dv).reshape(v_shape).astype(v_buf.dtype)
    u_buf[slot] = (
        (uu - lr * du_flat).reshape(u_buf[slot].shape).astype(u_buf.dtype))
    p_buf[slot] = (pv - lr * dq).reshape(p_buf[slot].shape).astype(p_buf.dtype)

    # ---- merged updates of the unique rows (one-hot transpose) -----------
    for c0 in range(0, UC, CH):
        jt = jax.lax.broadcasted_iota(jnp.int32, (CH, cap), 0) + c0
        ht = (jt == uidx[None, :]).astype(f32)
        d_u = jax.lax.dot_general(
            ht, du_flat, (((1,), (0,)), ((), ())), preferred_element_type=f32)
        u_uniq[slot, pl.ds(c0, CH)] = (
            u_uniq[slot, pl.ds(c0, CH)].reshape(CH, dp).astype(f32) - lr * d_u
        ).reshape((CH,) + u_uniq.shape[2:]).astype(u_uniq.dtype)

    loss = -(
        jnp.sum(jax.nn.log_sigmoid(pos) * mask)
        + lam * jnp.sum(jax.nn.log_sigmoid(-neg) * n_real.reshape(PC, 1))
    )
    loss_ref[...] = jnp.full(loss_ref.shape, loss * inv_b, dtype=jnp.float32)

    dmas(i, slot, "write")

    @pl.when(i == nblocks - 1)
    def _():
        wait_all(i, slot, "write")

        @pl.when(nblocks >= 2)
        def _():
            wait_all(i - 1, (i - 1) % 2, "write")


@functools.partial(
    jax.jit,
    static_argnames=("lam", "centers_per_block", "pool_size", "window",
                     "u_cap", "interpret"),
    donate_argnums=(0, 1),
)
def fused_sgns_dedup_step(
    in_table: jax.Array,
    out_table: jax.Array,
    centers: jax.Array,  # [N] row ids
    ctxs: jax.Array,  # [N, CW] row ids, -1 = pad
    pool_rows: jax.Array,  # [N // centers_per_block * pool_size]
    lr,
    lam: float,
    window: int,
    centers_per_block: int = 256,
    pool_size: int = 64,
    u_cap: int = 512,
    interpret: bool = False,
):
    """Center-major fused substep with per-block context-read dedup.

    Returns (in_table, out_table, loss). Designed for BLOCK-ORDERED batches
    (``data.sampler.batch_stream_blocks``): consecutive windows overlap, so
    each block's ~PC*(w+1) real context slots hit only ~PC distinct rows —
    one read DMA + one merged write DMA per distinct row instead of one per
    slot. Distinct rows are assigned (in ascending row order) to the first
    ``u_cap`` unique buffer entries; overflow rows keep the grouped
    kernel's per-slot hogwild treatment. Semantics: deduped rows get exact
    merged gradient sums (deterministic); centers/pool/overflow match
    :func:`fused_sgns_grouped_step`.
    """
    n, cw = ctxs.shape
    pc, pn = centers_per_block, pool_size
    if n % pc:
        raise ValueError(f"centers {n} not a multiple of centers_per_block {pc}")
    nblocks = n // pc
    if pool_rows.shape[0] != nblocks * pn:
        raise ValueError(f"pool_rows {pool_rows.shape[0]} != {nblocks * pn}")
    if u_cap % 8 or u_cap <= 0:
        raise ValueError(f"u_cap must be a positive multiple of 8, got {u_cap}")
    cap = pc * cw
    inv_b = 1.0 / (n * (window + 1))
    # write counts pack (direct-ctx | centers << 16) into one i32, so the
    # per-block slot count must fit 16 bits (stricter than _SLOT_MASK)
    if cap >= (1 << 16):
        raise ValueError(
            f"centers_per_block*2*window {cap} exceeds the 16-bit write-count "
            "packing; lower centers_per_block")
    if in_table.shape[0] > _ROW_MASK or out_table.shape[0] > _ROW_MASK:
        raise ValueError("table capacity exceeds 2^30 (row-id flag bit)")
    if in_table.shape[1:] != out_table.shape[1:] or in_table.dtype != out_table.dtype:
        raise ValueError("in/out tables must share row shape and dtype")
    _check_dedup_vmem(u_cap, pc, cap, pn, in_table.shape[1:], in_table.dtype)

    (c_packed, u_list, nu, ctx_rows, ctx_slot, nctx_direct, nw_packed,
     uidx, direct_real, mask) = dedup_prep(centers, ctxs, pc, u_cap)

    # one-hot chunk size must DIVIDE u_cap (the ds() slices tile it exactly)
    ch = next(d for d in (256, 128, 64, 32, 16, 8) if u_cap % d == 0)
    kern = functools.partial(
        _dedup_kernel, lam=lam, inv_b=inv_b, pc=pc, cw=cw, pool=pn,
        u_cap=u_cap, ch=ch,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=9,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((1, 1, cap), lambda i, *_: (i, 0, 0)),  # uidx
            pl.BlockSpec((1, 1, cap), lambda i, *_: (i, 0, 0)),  # direct
            pl.BlockSpec((1, cw, pc), lambda i, *_: (i, 0, 0)),  # mask
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 8, 128), lambda i, *_: (i, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, pc) + in_table.shape[1:], in_table.dtype),
            pltpu.VMEM((2, cap) + out_table.shape[1:], out_table.dtype),
            pltpu.VMEM((2, pn) + out_table.shape[1:], out_table.dtype),
            pltpu.VMEM((2, u_cap) + out_table.shape[1:], out_table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    new_in, new_out, loss_parts = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(in_table.shape, in_table.dtype),
            jax.ShapeDtypeStruct(out_table.shape, out_table.dtype),
            jax.ShapeDtypeStruct((nblocks, 8, 128), jnp.float32),
        ),
        input_output_aliases={12: 0, 13: 1},
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, vmem_limit_bytes=_RESIDENT_VMEM_BYTES
        ),
        interpret=interpret,
    )(
        c_packed,
        u_list.reshape(-1),
        nu,
        ctx_rows.reshape(-1),
        ctx_slot.reshape(-1),
        nctx_direct,
        nw_packed,
        pool_rows.astype(jnp.int32),
        jnp.asarray(lr, jnp.float32).reshape(1),
        uidx[:, None, :],
        direct_real[:, None, :],
        mask,
        in_table,
        out_table,
    )
    return new_in, new_out, loss_parts[:, 0, 0].sum()


def _dedup_resident_kernel(
        ccold_rows_ref, ccold_slot_ref, ncc_ref, nwc_ref,
        u_list_ref, nu_ref, nuc_ref,
        ctx_rows_ref, ctx_slot_ref, nctx_ref, nwu_ref,
        pcold_rows_ref, pcold_slot_ref, npc_ref, nwp_ref, lr_ref,
        u_list_in, uidx_in, direct_in, hot_c_in, hot_p_in, mask_in,
        in_t_in, out_t_in,
        in_table, out_table, loss_ref,
        v_buf, u_buf, p_buf, u_uniq, hot_in, hot_out,
        read_sems, write_sems, bulk_sem,
        *, lam, inv_b, pc, cw, pool, u_cap, ch, hot_n, ch_h):
    """Composed kernel: per-block context-read DEDUP + VMEM-RESIDENT head.

    The two round-3 kernels attack the same duplicate row traffic from
    different ends (docs/ARCHITECTURE.md "remaining lever"): dedup removes
    within-block duplicate context DMAs; residency removes ALL copies of
    the zipf head (rows < hot_n of both tables live in VMEM for the whole
    grid). Composed: context rows go through the unique list, and unique
    entries / centers / pool rows that are HOT source from (and update
    into) the resident buffers instead of DMA — on an unsubsampled zipf
    corpus the head carries ~half the row traffic, so this removes ~half
    of the dedup kernel's remaining copies.

    Semantics: hot rows (wherever they appear) get DETERMINISTIC
    sequential merged updates across blocks (merge_push_value parity,
    sparsetable.h:176-179); cold unique context rows get exact per-block
    merged updates; cold centers/pool and overflow context slots keep the
    grouped kernel's hogwild treatment.
    """
    del in_t_in, out_t_in
    lr = lr_ref[0]
    PC, CW, PN, UC, CH, HOT, CHH = pc, cw, pool, u_cap, ch, hot_n, ch_h
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)
    cap = PC * CW
    s_t, lanes = in_table.shape[1], in_table.shape[2]
    dp = s_t * lanes
    f32 = jnp.float32

    def bulk_start(table_dir):
        for tbl, buf in ((in_table, hot_in), (out_table, hot_out)):
            src, dst = (tbl.at[pl.ds(0, HOT)], buf)
            if table_dir == "write":
                src, dst = dst, src
            pltpu.make_async_copy(src, dst, bulk_sem).start()

    def bulk_wait():
        for _ in range(2):
            pltpu.make_async_copy(hot_in, hot_in, bulk_sem).wait()

    def dmas(b, slot, table_dir):
        read = table_dir == "read"
        sems = read_sems if read else write_sems

        def mk(buf_at, table, row):
            pair = (table.at[row], buf_at)
            src, dst = pair if read else pair[::-1]
            return pltpu.make_async_copy(src, dst, sems.at[slot])

        def cold_dma(rows_ref, slot_ref, buf, table, stride):
            # two-segment lists (_cold_compact/_unique_prep): write loops
            # are bounded by the flagged-write count, unconditional issue
            def go(k, _):
                row = rows_ref[b * stride + k]
                sl = slot_ref[b * stride + k]
                mk(buf.at[slot, sl & _SLOT_MASK], table, row).start()
                return 0
            return go

        def uq_dma(j, _):  # one DMA per DISTINCT COLD ctx row
            mk(u_uniq.at[slot, j], out_table, u_list_ref[b * UC + j]).start()
            return 0

        jax.lax.fori_loop(
            0, ncc_ref[b] if read else nwc_ref[b],
            cold_dma(ccold_rows_ref, ccold_slot_ref, v_buf, in_table, PC), 0)
        jax.lax.fori_loop(
            0, nctx_ref[b] if read else nwu_ref[b],
            cold_dma(ctx_rows_ref, ctx_slot_ref, u_buf, out_table, cap), 0)
        jax.lax.fori_loop(
            0, npc_ref[b] if read else nwp_ref[b],
            cold_dma(pcold_rows_ref, pcold_slot_ref, p_buf, out_table, PN), 0)
        # the hot-first sort key makes COLD uniques the [nu-nuc, nu) suffix
        # of the list — loop exactly that range, no per-entry hot branch
        jax.lax.fori_loop(nu_ref[b] - nuc_ref[b], nu_ref[b], uq_dma, 0)

    def wait_all(b, slot, table_dir):
        read = table_dir == "read"
        sems = read_sems if read else write_sems
        # nuc = DMA'd (cold) unique entries; hot entries never move per-row
        count = (
            ncc_ref[b] + nctx_ref[b] + npc_ref[b] + nuc_ref[b]
            if read
            else nwc_ref[b] + nwu_ref[b] + nwp_ref[b] + nuc_ref[b]
        )
        wc = min(_WAIT_CHUNK, cap)
        _wait_rows(v_buf.at[slot, 0], u_buf.at[slot, :wc],
                   sems.at[slot], count)

    @pl.when(i == 0)
    def _():
        bulk_start("read")
        dmas(0, 0, "read")
        bulk_wait()

    @pl.when(i + 1 < nblocks)
    def _():
        slot_next = (i + 1) % 2

        @pl.when(i >= 1)
        def _():
            wait_all(i - 1, slot_next, "write")

        dmas(i + 1, slot_next, "read")

    slot = i % 2
    wait_all(i, slot, "read")

    # ---- assemble unique-row values: resident head or DMA ---------------
    u_list_v = u_list_in[0, 0]  # [UC] i32 (0-padded past nu)
    uidx = uidx_in[0, 0]  # [cap] i32, sentinel UC on pads/direct
    direct_real = direct_in[0, 0][:, None] > 0  # [cap, 1]
    hot_c_idx = hot_c_in[0, 0]  # [PC] i32, sentinel HOT on cold
    hot_p_idx = hot_p_in[0, 0]  # [PN]
    mask = mask_in[0]  # [CW, PC]

    def expand(idx, buf, n_rows):
        """one_hot(idx) @ buf[0:HOT] -> [n_rows, dp]; zeros where idx>=HOT."""
        acc = jnp.zeros((n_rows, dp), f32)
        for c0 in range(0, HOT, CHH):
            j = jax.lax.broadcasted_iota(jnp.int32, (n_rows, CHH), 1) + c0
            h = (j == idx[:, None]).astype(f32)
            acc = acc + jax.lax.dot_general(
                h, buf[pl.ds(c0, CHH)].reshape(CHH, dp).astype(f32),
                (((1,), (0,)), ((), ())), preferred_element_type=f32)
        return acc

    # entries >= nu were never DMA'd AND their u_list value (0) is hot, so
    # the where() below selects the (finite) expansion value — poison never
    # reaches arithmetic; their d_u is zero so nothing is written anywhere
    nu_here = nu_ref[i]
    is_hot_u = u_list_v[:, None] < HOT  # [UC, 1]
    u_hot_vals = expand(jnp.where(u_list_v < HOT, u_list_v, HOT), hot_out, UC)
    valid_j = (jax.lax.broadcasted_iota(jnp.int32, (UC, 1), 0) < nu_here)
    u_vals = jnp.where(
        is_hot_u, u_hot_vals,
        jnp.where(valid_j, u_uniq[slot].astype(f32).reshape(UC, dp), 0.0))

    # ---- broadcast unique rows to their slots (one-hot MXU) --------------
    acc = jnp.zeros((cap, dp), f32)
    for c0 in range(0, UC, CH):
        j = jax.lax.broadcasted_iota(jnp.int32, (cap, CH), 1) + c0
        h = (j == uidx[:, None]).astype(f32)
        # static value slice (c0/CH are trace-time ints): Mosaic TC has no
        # dynamic_slice lowering for VALUES (refs use pl.ds); lax.slice does
        acc = acc + jax.lax.dot_general(
            h, jax.lax.slice(u_vals, (c0, 0), (c0 + CH, dp)),
            (((1,), (0,)), ((), ())), preferred_element_type=f32)
    is_dedup = uidx[:, None] < UC

    vc_hot = expand(hot_c_idx, hot_in, PC)
    pv_hot = expand(hot_p_idx, hot_out, PN)
    is_hot_c = hot_c_idx[:, None] < HOT
    is_hot_p = hot_p_idx[:, None] < HOT

    vv = jnp.where(is_hot_c, vc_hot, v_buf[slot].astype(f32).reshape(PC, dp))
    uu = jnp.where(
        is_dedup, acc,
        jnp.where(direct_real, u_buf[slot].astype(f32).reshape(cap, dp), 0.0))
    pv = jnp.where(is_hot_p, pv_hot, p_buf[slot].astype(f32).reshape(PN, dp))

    # ---- compute (identical math to the grouped kernel) ------------------
    uu3 = uu.reshape(CW, PC, dp)
    pos = jnp.sum(uu3 * vv[None, :, :], axis=-1)
    n_real = jnp.sum(mask, axis=0, keepdims=True)
    neg = jax.lax.dot_general(
        vv, pv, (((1,), (1,)), ((), ())), preferred_element_type=f32)

    g_pos = (jax.nn.sigmoid(pos) - 1.0) * inv_b * mask
    g_neg = (lam * inv_b) * jax.nn.sigmoid(neg) * n_real.reshape(PC, 1)

    dv = jnp.sum(g_pos[:, :, None] * uu3, axis=0) + jax.lax.dot_general(
        g_neg, pv, (((1,), (0,)), ((), ())), preferred_element_type=f32)
    du_flat = (g_pos[:, :, None] * vv[None, :, :]).reshape(cap, dp)
    dq = jax.lax.dot_general(
        g_neg, vv, (((0,), (0,)), ((), ())), preferred_element_type=f32)

    v_shape = v_buf[slot].shape
    v_buf[slot] = (vv - lr * dv).reshape(v_shape).astype(v_buf.dtype)
    u_buf[slot] = (
        (uu - lr * du_flat).reshape(u_buf[slot].shape).astype(u_buf.dtype))
    p_buf[slot] = (pv - lr * dq).reshape(p_buf[slot].shape).astype(p_buf.dtype)

    # ---- merged updates of the unique rows (one-hot transpose) -----------
    # chunkwise transpose-accumulate, assembled with a static concatenate:
    # dynamic_update_slice on a VALUE has no Mosaic TC lowering
    d_u_chunks = []
    for c0 in range(0, UC, CH):
        jt = jax.lax.broadcasted_iota(jnp.int32, (CH, cap), 0) + c0
        ht = (jt == uidx[None, :]).astype(f32)
        d_u_chunks.append(
            jax.lax.dot_general(ht, du_flat, (((1,), (0,)), ((), ())),
                                preferred_element_type=f32))
    d_u = (jnp.concatenate(d_u_chunks, axis=0) if len(d_u_chunks) > 1
           else d_u_chunks[0])
    new_u_vals = u_vals - lr * d_u
    u_uniq[slot] = new_u_vals.reshape(u_uniq[slot].shape).astype(u_uniq.dtype)

    # ---- hot-row merged updates into the resident buffers ----------------
    d_u_hot = jnp.where(is_hot_u, d_u, 0.0)
    for c0 in range(0, HOT, CHH):
        def acc_t(idx, grads, n_rows):
            jt = jax.lax.broadcasted_iota(jnp.int32, (CHH, n_rows), 0) + c0
            ht = (jt == idx[None, :]).astype(f32)
            return jax.lax.dot_general(
                ht, grads, (((1,), (0,)), ((), ())), preferred_element_type=f32)

        d_out = acc_t(u_list_v, d_u_hot, UC) + acc_t(hot_p_idx, dq, PN)
        hot_out[pl.ds(c0, CHH)] = (
            hot_out[pl.ds(c0, CHH)].reshape(CHH, dp).astype(f32) - lr * d_out
        ).reshape(CHH, s_t, lanes).astype(hot_out.dtype)
        d_in = acc_t(hot_c_idx, dv, PC)
        hot_in[pl.ds(c0, CHH)] = (
            hot_in[pl.ds(c0, CHH)].reshape(CHH, dp).astype(f32) - lr * d_in
        ).reshape(CHH, s_t, lanes).astype(hot_in.dtype)

    loss = -(
        jnp.sum(jax.nn.log_sigmoid(pos) * mask)
        + lam * jnp.sum(jax.nn.log_sigmoid(-neg) * n_real.reshape(PC, 1))
    )
    loss_ref[...] = jnp.full(loss_ref.shape, loss * inv_b, dtype=jnp.float32)

    dmas(i, slot, "write")

    @pl.when(i == nblocks - 1)
    def _():
        wait_all(i, slot, "write")

        @pl.when(nblocks >= 2)
        def _():
            wait_all(i - 1, (i - 1) % 2, "write")

        bulk_start("write")
        bulk_wait()


@functools.partial(
    jax.jit,
    static_argnames=("lam", "centers_per_block", "pool_size", "window",
                     "u_cap", "hot_rows", "interpret"),
    donate_argnums=(0, 1),
)
def fused_sgns_dedup_resident_step(
    in_table: jax.Array,
    out_table: jax.Array,
    centers: jax.Array,  # [N] row ids
    ctxs: jax.Array,  # [N, CW] row ids, -1 = pad
    pool_rows: jax.Array,  # [N // centers_per_block * pool_size]
    lr,
    lam: float,
    window: int,
    centers_per_block: int = 256,
    pool_size: int = 64,
    u_cap: int = 512,
    hot_rows: int = 512,
    interpret: bool = False,
):
    """Composed dedup + resident substep (see :func:`_dedup_resident_kernel`).

    Returns (in_table, out_table, loss). Requires frequency-ranked row ids
    for the perf win (the zipf head must be rows < hot_rows); correctness
    never depends on it. Block-ordered batches
    (``data.sampler.batch_stream_blocks``) supply the locality the unique
    list needs, exactly like :func:`fused_sgns_dedup_step`.
    """
    n, cw = ctxs.shape
    pc, pn = centers_per_block, pool_size
    if n % pc:
        raise ValueError(f"centers {n} not a multiple of centers_per_block {pc}")
    nblocks = n // pc
    if pool_rows.shape[0] != nblocks * pn:
        raise ValueError(f"pool_rows {pool_rows.shape[0]} != {nblocks * pn}")
    if u_cap % 8 or u_cap <= 0:
        raise ValueError(f"u_cap must be a positive multiple of 8, got {u_cap}")
    cap = pc * cw
    inv_b = 1.0 / (n * (window + 1))
    if cap > _SLOT_MASK:
        raise ValueError(f"centers_per_block*2*window {cap} exceeds slot bits")
    if in_table.shape[1:] != out_table.shape[1:] or in_table.dtype != out_table.dtype:
        raise ValueError("in/out tables must share row shape and dtype")
    if in_table.shape[0] > _ROW_MASK or out_table.shape[0] > _ROW_MASK:
        raise ValueError("table capacity exceeds 2^30 (cold sort bit)")
    hot_n, ch_h = effective_hot_rows(
        hot_rows, in_table.shape[0], out_table.shape[0])
    if hot_n <= 0:
        raise ValueError("hot_rows too small; use fused_sgns_dedup_step")
    if u_cap < hot_n:
        # hot rows rank FIRST into the unique list (below); u_cap >= hot_n
        # then guarantees every distinct hot row is in-list, so an overflow
        # (direct) slot can never carry a hot row — a direct-hot slot would
        # read stale HBM and its update would be clobbered by the final
        # bulk head writeback
        raise ValueError(
            f"composed kernel requires u_cap ({u_cap}) >= effective "
            f"hot_rows ({hot_n}); raise u_cap or lower hot_rows")
    _check_dedup_vmem(u_cap, pc, cap, pn, in_table.shape[1:], in_table.dtype,
                      hot_n=hot_n)

    flat = (
        ctxs.reshape(nblocks, pc, cw).transpose(0, 2, 1).reshape(nblocks, cap)
    ).astype(jnp.int32)
    valid = flat >= 0

    # sort key: hot rows first (cold bit above the row id), then by row —
    # distinct rows keep distinct keys, and every hot distinct row lands at
    # a rank < hot_n <= u_cap (the correctness guarantee above); one shared
    # single-sort pass yields list, ranks, and overflow compaction
    cold_bit = jnp.where(flat >= hot_n, jnp.int32(1 << 30), 0)
    keyed = jnp.where(valid, flat | cold_bit, _BIG)
    (u_list, nu, ctx_rows, ctx_slot, nctx_direct, nwu_direct,
     uidx) = _unique_prep(keyed, u_cap, row_mask=_ROW_MASK)
    direct_real = (valid & (uidx >= u_cap)).astype(jnp.float32)
    # DMA'd (cold) unique entries per block: rows >= hot_n within the list
    in_range = jnp.arange(u_cap)[None, :] < nu[:, None]
    nu_cold = (in_range & (u_list >= hot_n)).sum(axis=1).astype(jnp.int32)
    mask = valid.reshape(nblocks, cw, pc).astype(jnp.float32)

    c_blocks = centers.astype(jnp.int32).reshape(nblocks, pc)
    c_hot = c_blocks < hot_n
    hot_c_idx = jnp.where(c_hot, c_blocks, hot_n).astype(jnp.int32)
    cc_rows, cc_slot, ncc, nwc = _cold_compact(c_blocks, ~c_hot)

    p_blocks = pool_rows.astype(jnp.int32).reshape(nblocks, pn)
    p_hot = p_blocks < hot_n
    hot_p_idx = jnp.where(p_hot, p_blocks, hot_n).astype(jnp.int32)
    pc_rows, pc_slot, npc, nwp = _cold_compact(p_blocks, ~p_hot)

    ch = next(d for d in (256, 128, 64, 32, 16, 8) if u_cap % d == 0)
    kern = functools.partial(
        _dedup_resident_kernel, lam=lam, inv_b=inv_b, pc=pc, cw=cw, pool=pn,
        u_cap=u_cap, ch=ch, hot_n=hot_n, ch_h=ch_h,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=16,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((1, 1, u_cap), lambda i, *_: (i, 0, 0)),  # u_list
            pl.BlockSpec((1, 1, cap), lambda i, *_: (i, 0, 0)),  # uidx
            pl.BlockSpec((1, 1, cap), lambda i, *_: (i, 0, 0)),  # direct
            pl.BlockSpec((1, 1, pc), lambda i, *_: (i, 0, 0)),  # hot_c_idx
            pl.BlockSpec((1, 1, pn), lambda i, *_: (i, 0, 0)),  # hot_p_idx
            pl.BlockSpec((1, cw, pc), lambda i, *_: (i, 0, 0)),  # mask
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 8, 128), lambda i, *_: (i, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, pc) + in_table.shape[1:], in_table.dtype),
            pltpu.VMEM((2, cap) + out_table.shape[1:], out_table.dtype),
            pltpu.VMEM((2, pn) + out_table.shape[1:], out_table.dtype),
            pltpu.VMEM((2, u_cap) + out_table.shape[1:], out_table.dtype),
            pltpu.VMEM((hot_n,) + in_table.shape[1:], in_table.dtype),
            pltpu.VMEM((hot_n,) + out_table.shape[1:], out_table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
    )
    new_in, new_out, loss_parts = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(in_table.shape, in_table.dtype),
            jax.ShapeDtypeStruct(out_table.shape, out_table.dtype),
            jax.ShapeDtypeStruct((nblocks, 8, 128), jnp.float32),
        ),
        input_output_aliases={22: 0, 23: 1},
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, vmem_limit_bytes=_RESIDENT_VMEM_BYTES
        ),
        interpret=interpret,
    )(
        cc_rows.reshape(-1), cc_slot.reshape(-1), ncc, nwc,
        u_list.reshape(-1), nu, nu_cold,
        ctx_rows.reshape(-1), ctx_slot.reshape(-1), nctx_direct, nwu_direct,
        pc_rows.reshape(-1), pc_slot.reshape(-1), npc, nwp,
        jnp.asarray(lr, jnp.float32).reshape(1),
        u_list[:, None, :], uidx[:, None, :], direct_real[:, None, :],
        hot_c_idx[:, None, :], hot_p_idx[:, None, :], mask,
        in_table, out_table,
    )
    return new_in, new_out, loss_parts[:, 0, 0].sum()


@functools.partial(
    jax.jit,
    static_argnames=("lam", "pairs_per_block", "pool_size", "interpret"),
    donate_argnums=(0, 1),
)
def fused_sgns_step(
    in_table: jax.Array,
    out_table: jax.Array,
    in_rows: jax.Array,
    pos_rows: jax.Array,
    pool_rows: jax.Array,
    lr: float,
    lam: float,
    pairs_per_block: int = 512,
    pool_size: int = 64,
    interpret: bool = False,
):
    """One SGD substep over B pairs. Returns (in_table, out_table, loss).

    ``in_rows``/``pos_rows``: [B]; ``pool_rows``: [B//pairs_per_block *
    pool_size]; all row ids in-bounds. ``lam`` is the negative-term weight
    (``negatives / pool_size``); loss/grads are means over B.
    """
    b = in_rows.shape[0]
    p, pn = pairs_per_block, pool_size
    if b % p:
        raise ValueError(f"batch {b} not a multiple of pairs_per_block {p}")
    nblocks = b // p
    if pool_rows.shape[0] != nblocks * pn:
        raise ValueError(
            f"pool_rows {pool_rows.shape[0]} != nblocks*pool {nblocks * pn}"
        )
    if in_table.shape[1:] != out_table.shape[1:] or in_table.dtype != out_table.dtype:
        raise ValueError("in/out tables must share row shape and dtype")
    c, s, lanes = in_table.shape
    kern = functools.partial(
        _kernel, lam=lam, inv_b=1.0 / b, pairs=p, pool=pn
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 8, 128), lambda i, *_: (i, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, p, s, lanes), in_table.dtype),
            pltpu.VMEM((2, p, s, lanes), out_table.dtype),
            pltpu.VMEM((2, pn, s, lanes), out_table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    new_in, new_out, loss_parts = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(in_table.shape, in_table.dtype),
            jax.ShapeDtypeStruct(out_table.shape, out_table.dtype),
            jax.ShapeDtypeStruct((nblocks, 8, 128), jnp.float32),
        ),
        input_output_aliases={4: 0, 5: 1},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(
        in_rows.astype(jnp.int32),
        pos_rows.astype(jnp.int32),
        pool_rows.astype(jnp.int32),
        jnp.asarray(lr, jnp.float32).reshape(1),
        in_table,
        out_table,
    )
    return new_in, new_out, loss_parts[:, 0, 0].sum()
