"""Row-granularity DMA kernels on the packed table layout — the PS hot path.

The reference's server hot loop is a per-key hashmap probe under a lock
(``src/core/parameter/sparsetable.h:142-149`` find-or-init per pulled key;
``sparsetable.h:181-192`` apply per pushed key). The TPU equivalent of "one
key = one independent memory transaction" is one row DMA per key: XLA's own
gather/scatter on a ``[capacity, dim]`` table serializes at ~100-140 ns/row
on v5e (measured), so these kernels drive the DMA engines directly.

Layout: a **packed table** of shape ``[capacity, S, 128]`` (``S = ceil(dim/
128)``), i.e. one row = one ``(S, 128)`` tile. Mosaic requires DMA slices to
be tile-aligned in the last two dims — a row of a 2-D ``[C, D]`` table can
never be sliced alone (sublane tiling is 8), but a leading-dim slice of the
3-D layout is exactly one row with zero padding waste. Row elements live at
``packed[r, s, l] == row[s * 128 + l]``; all framework math (dots, grads,
optimizer rules) is layout-agnostic — padding lanes hold zeros and stay zero
under every access method whose update is ``f(grad) == 0`` at ``grad == 0``.

Kernels (both double-buffered, one DMA per row, shared per-slot semaphore —
the TPU's semaphore space caps out near 512, so per-row semaphores are not
an option; equal-sized copies make shared byte-accounting exact):

* :func:`gather_rows` — pull: for each of N row ids, DMA ``table[r]`` HBM ->
  VMEM, emitting ``[N, S, 128]``. Block ``i+1``'s row DMAs are issued before
  block ``i`` is consumed, so issue latency overlaps the output pipeline.
* :func:`scatter_add_rows` — push: read-modify-write ``table[r] += delta``
  per row, pipelined two blocks deep (reads of block ``i+1`` overlap writes
  of block ``i``). Rows MUST be unique (or >= capacity for padding slots,
  which are skipped): uniqueness is what makes the RMW race-free, and is
  guaranteed by the caller via ``merge_duplicate_rows`` (the reference's
  ``merge_push_value`` duplicate merge, ``sparsetable.h:176-179``).
* :func:`scatter_write_rows` — write-only scatter ``table[r] = value`` for
  unique rows. This is also the tiered store's slot-install path
  (``tiered/store.py::_scatter_rowdma``): faulted master rows land in the
  HBM cache plane from one fused host staging buffer, one DMA per row.
* :func:`scatter_adagrad_rows` / :func:`scatter_adagrad_fused_rows` —
  fused AdaGrad RMW (split param/accum buffers, or both packed into one
  stored tile so a single DMA pair moves them).

Off-TPU these run in interpret mode (same code path, CPU tests). The XLA
fallback (`jnp.take` / `.at[].add`) remains in ``parallel/store.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from swiftsnails_tpu.utils.compat import install_pallas_compat

install_pallas_compat()  # modern pltpu.CompilerParams / BlockSpec on jax 0.4.x

ROW_LANES = 128


def packed_shape(capacity: int, dim: int):
    """[capacity, S, 128] shape for a logical [capacity, dim] table."""
    s = -(-dim // ROW_LANES)
    return (capacity, s, ROW_LANES)


def pack_rows(rows2d: jax.Array) -> jax.Array:
    """[N, dim] -> [N, S, 128] with zero padding lanes."""
    n, dim = rows2d.shape
    s = -(-dim // ROW_LANES)
    pad = s * ROW_LANES - dim
    if pad:
        rows2d = jnp.pad(rows2d, ((0, 0), (0, pad)))
    return rows2d.reshape(n, s, ROW_LANES)


def unpack_rows(rows3d: jax.Array, dim: int) -> jax.Array:
    """[N, S, 128] -> [N, dim]."""
    n = rows3d.shape[0]
    return rows3d.reshape(n, -1)[:, :dim]


# --------------------------------------------------------------- gather ---


def _gather_kernel(rows_ref, table_ref, out_ref, scratch, sems):
    R = scratch.shape[1]
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)

    def row_dma(b, slot, j):
        return pltpu.make_async_copy(
            table_ref.at[rows_ref[b * R + j]], scratch.at[slot, j], sems.at[slot]
        )

    def start_block(b, slot):
        jax.lax.fori_loop(0, R, lambda j, _: (row_dma(b, slot, j).start(), 0)[1], 0)

    @pl.when(i == 0)
    def _():
        start_block(0, 0)

    @pl.when(i + 1 < nblocks)
    def _():
        start_block(i + 1, (i + 1) % 2)

    slot = i % 2
    jax.lax.fori_loop(0, R, lambda j, _: (row_dma(i, slot, j).wait(), 0)[1], 0)
    out_ref[...] = scratch[slot]


@functools.partial(
    jax.jit, static_argnames=("block_rows", "interpret")
)
def gather_rows(
    table: jax.Array, rows: jax.Array, block_rows: int = 512, interpret: bool = False
) -> jax.Array:
    """``table[rows]`` for a packed ``[C, S, 128]`` table -> ``[N, S, 128]``.

    ``N`` must be a multiple of ``block_rows``; rows must be in
    ``[0, capacity)``. One DMA per row, double-buffered across blocks.
    """
    n = rows.shape[0]
    c, s, lanes = table.shape
    if n % block_rows:
        raise ValueError(f"N={n} not a multiple of block_rows={block_rows}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block_rows,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((block_rows, s, lanes), lambda i, rows_ref: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, block_rows, s, lanes), table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, s, lanes), table.dtype),
        interpret=interpret,
    )(rows.astype(jnp.int32), table)


# ---------------------------------------------------------- scatter-add ---


def _scatter_kernel(rows_ref, table_in_ref, deltas_ref, table_ref,
                    scratch, read_sems, write_sems):
    # table_ref is the aliased output (same HBM buffer as table_in_ref).
    del table_in_ref
    R = scratch.shape[1]
    C = table_ref.shape[0]
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)

    def read_dma(b, slot, j):
        return pltpu.make_async_copy(
            table_ref.at[rows_ref[b * R + j]], scratch.at[slot, j], read_sems.at[slot]
        )

    def write_dma(b, slot, j):
        return pltpu.make_async_copy(
            scratch.at[slot, j], table_ref.at[rows_ref[b * R + j]], write_sems.at[slot]
        )

    def for_valid(b, fn):
        def body(j, _):
            @pl.when(rows_ref[b * R + j] < C)
            def _():
                fn(j)
            return 0
        jax.lax.fori_loop(0, R, body, 0)

    @pl.when(i == 0)
    def _():
        for_valid(0, lambda j: read_dma(0, 0, j).start())

    @pl.when(i + 1 < nblocks)
    def _():
        slot_next = (i + 1) % 2

        # block i-1 used slot_next; its writebacks must land before we
        # overwrite the slot's scratch with new reads.
        @pl.when(i >= 1)
        def _():
            for_valid(i - 1, lambda j: write_dma(i - 1, slot_next, j).wait())

        for_valid(i + 1, lambda j: read_dma(i + 1, slot_next, j).start())

    slot = i % 2

    def rmw(j):
        read_dma(i, slot, j).wait()
        scratch[slot, j] = scratch[slot, j] + deltas_ref[j]
        write_dma(i, slot, j).start()

    for_valid(i, rmw)

    @pl.when(i == nblocks - 1)
    def _():
        for_valid(i, lambda j: write_dma(i, slot, j).wait())

        @pl.when(nblocks >= 2)
        def _():
            for_valid(i - 1, lambda j: write_dma(i - 1, (i - 1) % 2, j).wait())


@functools.partial(
    jax.jit,
    static_argnames=("block_rows", "interpret"),
    donate_argnums=(0,),
)
def scatter_add_rows(
    table: jax.Array,
    rows: jax.Array,
    deltas: jax.Array,
    block_rows: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """``table[rows] += deltas`` in place for UNIQUE rows (packed layout).

    Rows ``>= capacity`` are padding and skipped (the ``mode='drop'``
    equivalent). The table buffer is donated and aliased — no copy.
    """
    n = rows.shape[0]
    c, s, lanes = table.shape
    if n % block_rows:
        raise ValueError(f"N={n} not a multiple of block_rows={block_rows}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((block_rows, s, lanes), lambda i, rows_ref: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, block_rows, s, lanes), table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={1: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(rows.astype(jnp.int32), table, deltas)


# -------------------------------------------------------- scatter-write ---


def _write_kernel(rows_ref, table_in_ref, values_ref, table_ref, sems):
    # Write-only scatter: each valid row of the streamed-in values block is
    # DMA'd VMEM -> HBM. Unique rows => no write races. All of a block's
    # writes are issued, then drained before the body returns: the input
    # pipeline prefetches block i+1 over block i-1's buffer while body i
    # runs, so writes must never outlive their own block's body.
    del table_in_ref
    R = values_ref.shape[0]
    C = table_ref.shape[0]
    i = pl.program_id(0)

    def write_dma(j):
        return pltpu.make_async_copy(
            values_ref.at[j], table_ref.at[rows_ref[i * R + j]], sems.at[0]
        )

    def for_valid(fn):
        def body(j, _):
            @pl.when(rows_ref[i * R + j] < C)
            def _():
                fn(j)
            return 0
        jax.lax.fori_loop(0, R, body, 0)

    for_valid(lambda j: write_dma(j).start())
    for_valid(lambda j: write_dma(j).wait())


@functools.partial(
    jax.jit,
    static_argnames=("block_rows", "interpret"),
    donate_argnums=(0,),
)
def scatter_write_rows(
    table: jax.Array,
    rows: jax.Array,
    values: jax.Array,
    block_rows: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """``table[rows] = values`` in place for UNIQUE rows (packed layout).

    Write-only half of a generic pull-compute-writeback update (AdaGrad and
    friends); rows ``>= capacity`` are skipped.
    """
    n = rows.shape[0]
    c, s, lanes = table.shape
    if n % block_rows:
        raise ValueError(f"N={n} not a multiple of block_rows={block_rows}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((block_rows, s, lanes), lambda i, rows_ref: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((1,))],
    )
    return pl.pallas_call(
        _write_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={1: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(rows.astype(jnp.int32), table, values)


# ------------------------------------------------------- fused AdaGrad ---


def _adagrad_kernel(rows_ref, lr_ref, table_in, accum_in, deltas_ref,
                    table_ref, accum_ref, p_scr, a_scr, read_sems, write_sems,
                    *, eps):
    """Read-modify-write AdaGrad on UNIQUE rows, slot math in-kernel.

    Per row: DMA param + accum in, ``accum += g²``,
    ``param -= lr * g * rsqrt(accum + eps)``, DMA both back — one kernel
    launch for table AND slot (the unfused path costs 2 launches per slot
    array, docs/ARCHITECTURE.md known-limitations r2). Same double-buffered
    schedule as ``_scatter_kernel``; both DMAs of a row share the per-slot
    semaphore (equal sizes — param and accum rows are same shape/dtype).
    """
    del table_in, accum_in
    lr = lr_ref[0]
    R = p_scr.shape[1]
    C = table_ref.shape[0]
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)

    def dma(b, slot, j, buf, hbm, read):
        pair = (hbm.at[rows_ref[b * R + j]], buf.at[slot, j])
        src, dst = pair if read else pair[::-1]
        sems = read_sems if read else write_sems
        return pltpu.make_async_copy(src, dst, sems.at[slot])

    def for_valid(b, fn):
        def body(j, _):
            @pl.when(rows_ref[b * R + j] < C)
            def _():
                fn(j)
            return 0
        jax.lax.fori_loop(0, R, body, 0)

    def start_reads(b, slot):
        def go(j):
            dma(b, slot, j, p_scr, table_ref, True).start()
            dma(b, slot, j, a_scr, accum_ref, True).start()
        for_valid(b, go)

    def wait(b, slot, read):
        def go(j):
            for _ in range(2):  # param + accum copies, equal sizes
                sems = read_sems if read else write_sems
                pltpu.make_async_copy(
                    p_scr.at[slot, 0], p_scr.at[slot, 0], sems.at[slot]
                ).wait()
        for_valid(b, go)

    @pl.when(i == 0)
    def _():
        start_reads(0, 0)

    @pl.when(i + 1 < nblocks)
    def _():
        slot_next = (i + 1) % 2

        @pl.when(i >= 1)
        def _():
            wait(i - 1, slot_next, False)

        start_reads(i + 1, slot_next)

    slot = i % 2
    wait(i, slot, True)

    g = deltas_ref[...].astype(jnp.float32)
    accum = a_scr[slot].astype(jnp.float32) + g * g
    step = lr * g * jax.lax.rsqrt(accum + eps)
    p_scr[slot] = (p_scr[slot].astype(jnp.float32) - step).astype(p_scr.dtype)
    a_scr[slot] = accum.astype(a_scr.dtype)

    def writeback(j):
        dma(i, slot, j, p_scr, table_ref, False).start()
        dma(i, slot, j, a_scr, accum_ref, False).start()
    for_valid(i, writeback)

    @pl.when(i == nblocks - 1)
    def _():
        wait(i, slot, False)

        @pl.when(nblocks >= 2)
        def _():
            wait(i - 1, (i - 1) % 2, False)


@functools.partial(
    jax.jit,
    static_argnames=("eps", "block_rows", "interpret"),
    donate_argnums=(0, 1),
)
def scatter_adagrad_rows(
    table: jax.Array,
    accum: jax.Array,
    rows: jax.Array,
    grads: jax.Array,
    lr,
    eps: float = 1e-8,
    block_rows: int = 512,
    interpret: bool = False,
):
    """Fused AdaGrad RMW for UNIQUE rows: ``accum += g²; table -= lr * g *
    rsqrt(accum + eps)`` in one kernel launch (packed layout, both buffers
    donated/aliased). Rows ``>= capacity`` are padding and skipped. ``accum``
    must match ``table``'s shape/dtype (the shared-semaphore byte accounting
    relies on it). Exact merged-AdaGrad semantics for pre-merged rows —
    bit-identical to ``AdaGradAccess.apply_push_value`` on the same inputs.
    """
    n = rows.shape[0]
    c, s, lanes = table.shape
    if n % block_rows:
        raise ValueError(f"N={n} not a multiple of block_rows={block_rows}")
    if accum.shape != table.shape or accum.dtype != table.dtype:
        raise ValueError(
            f"accum {accum.shape}/{accum.dtype} must match table "
            f"{table.shape}/{table.dtype}"
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((block_rows, s, lanes), lambda i, *_: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, block_rows, s, lanes), table.dtype),
            pltpu.VMEM((2, block_rows, s, lanes), accum.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_adagrad_kernel, eps=eps),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(table.shape, table.dtype),
            jax.ShapeDtypeStruct(accum.shape, accum.dtype),
        ),
        input_output_aliases={2: 0, 3: 1},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(
        rows.astype(jnp.int32),
        jnp.asarray(lr, jnp.float32).reshape(1),
        table,
        accum,
        grads.astype(table.dtype),
    )


# -------------------------------------------- slot-fused AdaGrad (1 tile) ---


def _adagrad_fused_kernel(rows_ref, lr_ref, table_in, deltas_ref, table_ref,
                          scratch, read_sems, write_sems, *, eps):
    """AdaGrad RMW where param AND accum live in ONE stored tile
    (``table[r] = [param_row, accum_row]`` along the sublane axis): one read
    DMA + one write DMA per row moves both, halving the issue-bound DMA
    count of the split-buffer kernel. Rows must be unique; ``>= capacity``
    skipped."""
    del table_in
    lr = lr_ref[0]
    R = scratch.shape[1]
    C = table_ref.shape[0]
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)

    def dma(b, slot, j, read):
        pair = (table_ref.at[rows_ref[b * R + j]], scratch.at[slot, j])
        src, dst = pair if read else pair[::-1]
        sems = read_sems if read else write_sems
        return pltpu.make_async_copy(src, dst, sems.at[slot])

    def for_valid(b, fn):
        def body(j, _):
            @pl.when(rows_ref[b * R + j] < C)
            def _():
                fn(j)
            return 0
        jax.lax.fori_loop(0, R, body, 0)

    @pl.when(i == 0)
    def _():
        for_valid(0, lambda j: dma(0, 0, j, True).start())

    @pl.when(i + 1 < nblocks)
    def _():
        slot_next = (i + 1) % 2

        @pl.when(i >= 1)
        def _():
            for_valid(i - 1, lambda j: dma(i - 1, slot_next, j, False).wait())

        for_valid(i + 1, lambda j: dma(i + 1, slot_next, j, True).start())

    slot = i % 2
    for_valid(i, lambda j: dma(i, slot, j, True).wait())

    g = deltas_ref[...].astype(jnp.float32)  # [R, 1, 128]
    tile = scratch[slot].astype(jnp.float32)  # [R, 2, 128]
    accum = tile[:, 1:2, :] + g * g
    param = tile[:, 0:1, :] - lr * g * jax.lax.rsqrt(accum + eps)
    scratch[slot] = jnp.concatenate([param, accum], axis=1).astype(scratch.dtype)

    for_valid(i, lambda j: dma(i, slot, j, False).start())

    @pl.when(i == nblocks - 1)
    def _():
        for_valid(i, lambda j: dma(i, slot, j, False).wait())

        @pl.when(nblocks >= 2)
        def _():
            for_valid(i - 1, lambda j: dma(i - 1, (i - 1) % 2, j, False).wait())


@functools.partial(
    jax.jit,
    static_argnames=("eps", "block_rows", "interpret"),
    donate_argnums=(0,),
)
def scatter_adagrad_fused_rows(
    table: jax.Array,  # [C, 2, 128]: sublane 0 = param, sublane 1 = accum
    rows: jax.Array,
    grads: jax.Array,  # [N, 1, 128]
    lr,
    eps: float = 1e-8,
    block_rows: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Slot-fused AdaGrad RMW for UNIQUE rows; see ``_adagrad_fused_kernel``."""
    n = rows.shape[0]
    c, s, lanes = table.shape
    if s != 2:
        raise ValueError(f"slot-fused table must be [C, 2, 128], got {table.shape}")
    if n % block_rows:
        raise ValueError(f"N={n} not a multiple of block_rows={block_rows}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((block_rows, 1, lanes), lambda i, *_: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, block_rows, 2, lanes), table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_adagrad_fused_kernel, eps=eps),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={2: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(
        rows.astype(jnp.int32),
        jnp.asarray(lr, jnp.float32).reshape(1),
        table,
        grads.astype(table.dtype),
    )


def on_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")
