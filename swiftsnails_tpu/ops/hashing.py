"""Key hashing for parameter placement.

The reference places every parameter key with the MurmurHash3 64-bit
finalizer (``src/utils/HashFunction.h:17-25``)::

    x ^= x >> 33; x *= 0xff51afd7ed558ccd;
    x ^= x >> 33; x *= 0xc4ceb9fe1a85ec53;
    x ^= x >> 33;

then routes it with ``hash % frag_num`` (``src/core/parameter/hashfrag.h:48-53``)
and within a server with ``hash % shard_num`` (``sparsetable.h:115``).

We keep the exact same mixer so key→row placement is reproducible everywhere:

* :func:`murmur_fmix64_np` — exact, vectorized, host-side (numpy uint64);
* :func:`murmur_fmix64_pair` / :func:`murmur_fmix64` — exact, **jittable
  without ``jax_enable_x64``**: the 64-bit value is carried as a
  ``(hi32, lo32)`` uint32 pair and the modular multiply is done in 16-bit
  limbs, so the same placement can be computed inside a jit'd step on TPU;
* :func:`hash_row` — key → table row for a power-of-two capacity table
  (the hashing-trick replacement for the reference's lazy ``dense_hash_map``
  insert, ``sparsetable.h:142-149``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

_C1 = 0xFF51AFD7ED558CCD
_C2 = 0xC4CEB9FE1A85EC53

_C1_HI = np.uint32(_C1 >> 32)
_C1_LO = np.uint32(_C1 & 0xFFFFFFFF)
_C2_HI = np.uint32(_C2 >> 32)
_C2_LO = np.uint32(_C2 & 0xFFFFFFFF)

_MASK64 = (1 << 64) - 1


def murmur_fmix64_int(x: int) -> int:
    """Exact scalar finalizer on Python ints (host-side vocab/dict use)."""
    x &= _MASK64
    x ^= x >> 33
    x = (x * _C1) & _MASK64
    x ^= x >> 33
    x = (x * _C2) & _MASK64
    x ^= x >> 33
    return x


def murmur_fmix64_np(x: np.ndarray) -> np.ndarray:
    """Exact vectorized finalizer on ``uint64`` numpy arrays."""
    x = np.asarray(x, dtype=np.uint64)
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint64(33))
        x = x * np.uint64(_C1)
        x = x ^ (x >> np.uint64(33))
        x = x * np.uint64(_C2)
        x = x ^ (x >> np.uint64(33))
    return x


# -- jittable 64-bit arithmetic on (hi, lo) uint32 pairs ---------------------


def _mul32x32_64(a, b):
    """Full 64-bit product of two uint32 arrays, as a (hi, lo) uint32 pair.

    Uses 16-bit limbs so every partial product fits in uint32 — this is what
    lets the exact murmur mixer run in-graph without ``jax_enable_x64``.
    """
    a = a.astype(jnp.uint32)
    b = b.astype(jnp.uint32)
    mask16 = jnp.uint32(0xFFFF)
    a0, a1 = a & mask16, a >> 16
    b0, b1 = b & mask16, b >> 16
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> 16) + (p01 & mask16) + (p10 & mask16)
    lo = (p00 & mask16) | ((mid & mask16) << 16)
    hi = p11 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)
    return hi, lo


def _mul64_lo(x_hi, x_lo, c_hi, c_lo):
    """(x * c) mod 2**64 where x is a (hi, lo) pair and c a constant pair."""
    hi, lo = _mul32x32_64(x_lo, c_lo)
    hi = hi + x_lo * c_hi + x_hi * c_lo  # uint32 wrap == mod 2**32
    return hi, lo


def _xorshift33(hi, lo):
    # x ^= x >> 33  ==  lo ^= hi >> 1 (hi unchanged: top 33 bits of the shift are 0)
    return hi, lo ^ (hi >> 1)


def murmur_fmix64_pair(hi, lo):
    """Exact murmur fmix64 on (hi32, lo32) uint32 pairs. Jittable."""
    hi = jnp.asarray(hi, dtype=jnp.uint32)
    lo = jnp.asarray(lo, dtype=jnp.uint32)
    hi, lo = _xorshift33(hi, lo)
    hi, lo = _mul64_lo(hi, lo, jnp.uint32(_C1_HI), jnp.uint32(_C1_LO))
    hi, lo = _xorshift33(hi, lo)
    hi, lo = _mul64_lo(hi, lo, jnp.uint32(_C2_HI), jnp.uint32(_C2_LO))
    hi, lo = _xorshift33(hi, lo)
    return hi, lo


def murmur_fmix64(keys):
    """Finalize 32-bit keys (zero-extended to 64-bit), returning a (hi, lo) pair.

    ``keys`` may be int32/uint32; negative int32 values are reinterpreted as
    their uint32 bit pattern (matching a C++ ``uint64_t`` widening of uint32).
    """
    lo = jnp.asarray(keys).astype(jnp.uint32)
    hi = jnp.zeros_like(lo)
    return murmur_fmix64_pair(hi, lo)


def hash_row(keys, capacity: int):
    """key → table row: ``murmur(key) % capacity`` with power-of-two capacity.

    This replaces the reference's two-level placement (``hash % frag_num`` →
    server, lazy hashmap insert within the shard) with one static mapping into
    a pre-initialized ``capacity``-row table. Power-of-two capacity makes the
    modulo a mask on the low hash word, which keeps the op exact in uint32
    (general modulo of a 64-bit value needs 64-bit arithmetic; do that on the
    host with :func:`murmur_fmix64_np` if a non-pow2 capacity is ever needed).
    """
    if capacity <= 0 or (capacity & (capacity - 1)) != 0:
        raise ValueError(f"capacity must be a positive power of two, got {capacity}")
    _, lo = murmur_fmix64(keys)
    if capacity > (1 << 32):
        raise ValueError("on-device hash_row supports capacity <= 2**32")
    return (lo & jnp.uint32(capacity - 1)).astype(jnp.int32)


def hash_row_np(keys: np.ndarray, capacity: int) -> np.ndarray:
    """Host-side equivalent of :func:`hash_row` (exact for any capacity)."""
    h = murmur_fmix64_np(np.asarray(keys, dtype=np.uint64))
    return (h % np.uint64(capacity)).astype(np.int64)
