"""Pallas TPU kernels for the embedding hot path.

:func:`gather_rows` — dynamic row gather (the pull op) as a scalar-prefetch
pallas kernel: the row-id array is prefetched to SMEM and drives each grid
step's table BlockSpec index, so consecutive row DMAs are double-buffered by
the pallas pipeline. This is the kernel-level equivalent of the reference's
server-side per-key lookup loop (``sparsetable.h:142-149``) — one pipelined
pass instead of per-key hashmap probes.

Scatter-add deliberately stays on XLA's native scatter: under a pipelined
grid, duplicate row ids create read-modify-write hazards between in-flight
block DMAs (step j+2's fetch of row r can overlap step j's writeback), so a
pallas scatter would need pre-deduplicated rows — the exact argsort the fast
path exists to avoid. XLA's scatter handles duplicates correctly.

Runs in interpret mode off-TPU, so the same code path is unit-testable on
the CPU mesh; the bench A/Bs it against the XLA gather on real hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _copy_row_kernel(rows_ref, table_ref, out_ref):
    # rows_ref is scalar-prefetch (SMEM); the gather itself — DMAing
    # table[rows[i]] into VMEM — happened via the BlockSpec index_map.
    del rows_ref
    out_ref[...] = table_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_rows(table: jax.Array, rows: jax.Array, interpret: bool = False) -> jax.Array:
    """``table[rows]`` as a pallas kernel. ``rows`` must be in-bounds."""
    n = rows.shape[0]
    dim = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[pl.BlockSpec((1, dim), lambda i, rows_ref: (rows_ref[i], 0))],
        out_specs=pl.BlockSpec((1, dim), lambda i, rows_ref: (i, 0)),
    )
    fn = pl.pallas_call(
        _copy_row_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, dim), table.dtype),
        interpret=interpret,
    )
    return fn(rows.astype(jnp.int32), table)
