from swiftsnails_tpu.ops.hashing import (
    murmur_fmix64,
    murmur_fmix64_np,
    murmur_fmix64_pair,
    hash_row,
)

__all__ = [
    "murmur_fmix64",
    "murmur_fmix64_np",
    "murmur_fmix64_pair",
    "hash_row",
]
