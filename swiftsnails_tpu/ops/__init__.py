from swiftsnails_tpu.ops.hashing import (
    murmur_fmix64,
    murmur_fmix64_np,
    murmur_fmix64_pair,
    hash_row,
)
from swiftsnails_tpu.ops import rowdma

__all__ = [
    "rowdma",
    "murmur_fmix64",
    "murmur_fmix64_np",
    "murmur_fmix64_pair",
    "hash_row",
]
