"""The sharded parameter store — the PS data plane, TPU-native.

Replaces the reference's entire parameter layer (SURVEY §2.5):

* ``SparseTable`` / ``SparseTableShard`` (lock-striped hashmaps,
  ``src/core/parameter/sparsetable.h``) -> one pre-initialized dense
  ``jax.Array`` of shape ``[capacity, dim]``, row-sharded over the mesh's
  ``model`` axis (the hashing trick: row = murmur(key) % capacity,
  :func:`swiftsnails_tpu.ops.hashing.hash_row`);
* ``GlobalPullAccess::pull_with_barrier`` (per-server RPC fan-out,
  ``global_pull_access.h:40-55``) -> :func:`pull`, an XLA gather whose
  cross-shard movement compiles to ICI collectives under pjit;
* ``GlobalPushAccess::push_with_barrier`` + server-side
  ``apply_push_value`` loop (``global_push_access.h:36-53``,
  ``server/init.h:115-135``) -> :func:`push`, a segment-sum duplicate merge
  followed by one gather-update-scatter of the batch's unique rows;
* ``merge_push_value`` duplicate-gradient combining
  (``sparsetable.h:176-179``) -> :func:`merge_duplicate_rows` (sort +
  segment-sum; additive, batch-wide, deterministic).

Design note (the central memory/performance decision): trainers differentiate
w.r.t. the *pulled rows* (a batch-sized tensor — the analog of the reference's
worker-side ``GlobalParamCache``) and call :func:`push` explicitly. Autodiff
through a ``[capacity, dim]`` gather would build table-shaped gradients, which
is a non-starter at the 1B-row Criteo config; this keeps every per-step tensor
O(batch), exactly like the reference's wire protocol.
"""

from __future__ import annotations

import contextlib
import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding

from swiftsnails_tpu.parallel.access import AccessMethod, Slots
from swiftsnails_tpu.parallel.mesh import table_sharding


@contextlib.contextmanager
def _sharding_invariant_rng():
    """Pin the partitionable threefry lowering around table init.

    Under the default (non-partitionable) lowering, XLA specializes the
    random-bit computation to the ``out_shardings`` layout, so the same seed
    yields a DIFFERENT table on every mesh shape — which breaks mesh-shape
    invariance (a 1x1 and a 2x4 run could never match) and makes resharded
    restarts non-reproducible. The partitionable lowering is
    sharding-invariant by construction; scoping it here keeps every other
    RNG stream (samplers, dropout, dither) on the process-wide default."""
    old = jax.config.jax_threefry_partitionable
    jax.config.update("jax_threefry_partitionable", True)
    try:
        yield
    finally:
        jax.config.update("jax_threefry_partitionable", old)


def _scoped(name: str):
    """Label a pull/push path for the compiled-HLO communication audit
    (``telemetry.audit`` groups collective bytes by these ``ssn_*`` scopes)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.named_scope(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


class TableState(NamedTuple):
    """Sharded parameter table + row-aligned optimizer slots (a pytree)."""

    table: jax.Array  # [capacity, dim]
    slots: Slots  # each [capacity, dim]

    @property
    def capacity(self) -> int:
        return self.table.shape[0]

    @property
    def dim(self) -> int:
        return self.table.shape[1]


def create_table(
    capacity: int,
    dim: int,
    access: AccessMethod,
    mesh: Optional[Mesh] = None,
    dtype=jnp.float32,
    seed: int = 0,
    init_scale: Optional[float] = None,
) -> TableState:
    """Create a fully-initialized sharded table.

    Replaces lazy per-key ``init_param`` (``sparsetable.h:142-149``) with eager
    whole-table init — on TPU a pre-initialized dense table costs one pass of
    HBM writes and removes every data-dependent branch from the hot path.

    With a mesh, initialization runs *sharded* (jit with out_shardings), so no
    host ever materializes the full table — required at 1B-row capacities.
    """
    shape = (capacity, dim)

    if mesh is None:
        with _sharding_invariant_rng():
            return _init_table(shape, access, dtype, seed, init_scale)
    sharding = table_sharding(mesh)
    # enumerate slot keys without allocating (the table may be 1B rows)
    slot_spec = jax.eval_shape(lambda: access.init_slots(shape, dtype))
    with _sharding_invariant_rng():
        return _sharded_init(
            shape, access, dtype, seed, init_scale, sharding,
            tuple(sorted(slot_spec)))()


def _init_impl(shape, access, dtype, seed, init_scale):
    rng = jax.random.PRNGKey(seed)
    param = access.init_param(rng, shape, dtype)
    if init_scale is not None:
        param = param * init_scale
    return TableState(table=param, slots=access.init_slots(shape, dtype))


# jitted ONCE per (shape, access, ...) key: the old ``jax.jit(closure)()``
# form compiled afresh on every call — a fixed quarter-second XLA tax per
# ``TrainLoop.run`` that dominated short bench legs
_init_table = jax.jit(_init_impl, static_argnums=(0, 1, 2, 3, 4))


@functools.lru_cache(maxsize=64)
def _sharded_init(shape, access, dtype, seed, init_scale, sharding,
                  slot_keys):
    """Cached jit wrapper for the sharded-init path (``out_shardings`` is a
    jit parameter, so each distinct sharding needs its own wrapper)."""
    state_shardings = TableState(
        table=sharding, slots={k: sharding for k in slot_keys})
    return jax.jit(
        functools.partial(_init_impl, shape, access, dtype, seed, init_scale),
        out_shardings=state_shardings)


def pull(state: TableState, rows: jax.Array, access: Optional[AccessMethod] = None) -> jax.Array:
    """Gather rows from the table (``GlobalPullAccess`` equivalent).

    ``rows`` are table row ids (already hashed — see
    :func:`swiftsnails_tpu.ops.hashing.hash_row`). Under pjit with a
    row-sharded table, XLA lowers this to shard-local gathers + ICI
    collectives — the entire WORKER_PULL_REQUEST round trip (§3.4 of the
    survey) in one fused op.
    """
    with jax.named_scope("ssn_pull"):
        if isinstance(state.table, np.ndarray):
            # host master-backed state (table_tier: host, end of run): read
            # straight from host RAM — the full table may not fit a device
            vals = jnp.asarray(
                np.take(state.table, np.asarray(rows), axis=0))
        else:
            vals = state.table.at[rows].get(mode="promise_in_bounds")
        if access is not None:
            vals = access.get_pull_value(vals)
        return vals


def merge_duplicate_rows(
    rows: jax.Array, grads: jax.Array, invalid_row: int
) -> Tuple[jax.Array, jax.Array]:
    """Combine gradients of duplicate rows (``merge_push_value`` parity).

    Returns ``(uniq_rows, merged)`` of the same length as the input: slot
    ``i < n_unique`` holds a distinct row id and the sum of its gradients;
    remaining slots hold ``invalid_row`` (and zero gradient) so a subsequent
    ``mode='drop'`` scatter ignores them. Static shapes throughout — this is
    the jit-compatible replacement for per-key hashmap merging
    (``sparsetable.h:176-179``), and it makes duplicate handling additive and
    deterministic rather than last-write-wins.
    """
    n = rows.shape[0]
    order = jnp.argsort(rows)
    r = rows[order]
    g = grads[order]
    head = jnp.concatenate([jnp.ones((1,), dtype=bool), r[1:] != r[:-1]])
    seg = jnp.cumsum(head) - 1  # [n], segment id per sorted element
    merged = jax.ops.segment_sum(g, seg, num_segments=n)
    uniq = jnp.full((n,), invalid_row, dtype=rows.dtype)
    uniq = uniq.at[seg].set(r, mode="drop")  # duplicate writes carry equal values
    return uniq, merged


def apply_rows(
    table: jax.Array,
    slots: "Slots",
    uniq: jax.Array,
    merged: jax.Array,
    access: AccessMethod,
    lr,
):
    """gather current rows/slots -> access update rule -> scatter back.

    Shared body of :func:`push` and the shard-local update in
    :func:`swiftsnails_tpu.parallel.transfer.push_collective`. ``uniq`` must
    contain each row at most once (see :func:`merge_duplicate_rows`), so the
    gather-update-scatter is race-free; out-of-range padding rows read as
    zeros and are dropped on write.
    """
    cur_param = table.at[uniq].get(mode="fill", fill_value=0)
    cur_slots = {k: v.at[uniq].get(mode="fill", fill_value=0) for k, v in slots.items()}
    new_param, new_slots = access.apply_push_value(cur_param, cur_slots, merged, lr)
    new_table = table.at[uniq].set(new_param, mode="drop")
    out_slots = {k: slots[k].at[uniq].set(new_slots[k], mode="drop") for k in slots}
    return new_table, out_slots


def push(
    state: TableState,
    rows: jax.Array,
    grads: jax.Array,
    access: AccessMethod,
    lr,
    exact: bool = False,
) -> TableState:
    """Apply sparse gradients (``GlobalPushAccess`` + server apply equivalent).

    Fast path (default): the access method's sort-free ``scatter_update``
    when it has one — for SGD bit-identical to the exact path, for AdaGrad
    the per-sample-accumulator variant (see ``AccessMethod.scatter_update``).

    Exact path (``exact=True`` or no scatter rule): merge duplicates
    (argsort + segment-sum, the reference's ``merge_push_value`` semantics)
    -> :func:`apply_rows`, each unique row touched exactly once.

    Under pjit either path compiles to the reduce/scatter collectives that
    replace every WORKER_PUSH_REQUEST (§3.4).
    """
    with jax.named_scope("ssn_push"):
        if not exact:
            fast = access.scatter_update(state.table, state.slots, rows, grads, lr)
            if fast is not None:
                table, slots = fast
                return TableState(table=table, slots=slots)
        uniq, merged = merge_duplicate_rows(rows, grads, invalid_row=state.capacity)
        table, slots = apply_rows(state.table, state.slots, uniq, merged, access, lr)
        return TableState(table=table, slots=slots)


def export_rows(state: TableState, rows: jax.Array) -> jax.Array:
    """Raw row read (no pull transform) — used by checkpoint/text export."""
    return state.table.at[rows].get(mode="fill", fill_value=0)


# ---------------------------------------------------- tiered cache plane ---
#
# Host-tier support (swiftsnails_tpu/tiered): the HBM working-set cache is a
# smaller table of the SAME layout, so pull/push above run verbatim in
# cache-slot space — capacity and the invalid-row sentinel already derive
# from table.shape[0]. The two jit'd movers below are the tier's fault/flush
# data plane on a single device (the mesh twin is
# transfer.scatter_slots_collective): an OOB-drop scatter that installs
# faulted rows (pad index == shape[0] drops the update) and a fill-0 gather
# for dirty-slot read-back. Callers bucket the index length (pow2) so the
# trace cache stays logarithmic in fault-batch size.


@jax.jit
def scatter_rows(plane: jax.Array, idx: jax.Array, vals: jax.Array) -> jax.Array:
    """Install rows into a cache plane: ``plane[idx] = vals`` with
    out-of-range indices dropped (the fault path's padding sentinel)."""
    return plane.at[idx].set(vals.astype(plane.dtype), mode="drop")


@jax.jit
def gather_rows(plane: jax.Array, idx: jax.Array) -> jax.Array:
    """Read rows back from a cache plane (dirty-slot flush); out-of-range
    padding reads zeros and is sliced off by the caller."""
    return plane.at[idx].get(mode="fill", fill_value=0)


# ------------------------------------------------ small-row packed plane ---
#
# CTR tables are narrow (Criteo W&D: table_dim 17; FM/FFM similar). The
# word2vec packed layout would burn a whole [1, 128] tile per row (7.5x
# memory at dim 17) — so until round 3 the CTR families ran on the 2-D XLA
# plane whose gather serializes at ~100-140 ns/row (VERDICT r2 missing #3).
# This plane packs G = 128 // stride logical rows per 128-lane tile
# (stride = smallest power-of-two lane group >= dim): row r lives in tile
# r // G at lanes (r % G) * stride. One tile DMA serves one logical row
# (issue-bound, same cost as a wide row), the memory waste drops to
# stride/dim, and the lane groups are disjoint — so merging duplicates BY
# TILE is exactly merging by row, and lanewise AdaGrad on a tile is exact
# per-row AdaGrad. Push is one fused kernel: scatter-add (SGD) or the
# in-kernel slot-math AdaGrad RMW (ops/rowdma.scatter_adagrad_rows).


def small_group(dim: int) -> int:
    """Logical rows per 128-lane tile for a width-``dim`` table."""
    if dim > 128:
        raise ValueError(f"small-row plane requires dim <= 128, got {dim}")
    g = 1
    while g < 128 and 128 // (2 * g) >= dim:
        g *= 2
    return g


def _fuse_small_slots(access: AccessMethod, dtype) -> bool:
    """Slot-fused storage: param + AdaGrad accum share one stored tile
    (``[T, 2, 128]``, sublane 0 = param, 1 = accum) so ONE DMA moves both —
    the RMW drops from 4 to 2 issue-bound copies per row
    (ops/rowdma.scatter_adagrad_fused_rows). Only when the slot dtype
    matches the table's (a bf16-slot config keeps the split layout)."""
    from swiftsnails_tpu.parallel.access import AdaGradAccess

    return isinstance(access, AdaGradAccess) and (
        access.slot_dtype is None or access.slot_dtype == dtype
    )


def create_packed_small_table(
    capacity: int,
    dim: int,
    access: AccessMethod,
    mesh: Optional[Mesh] = None,
    dtype=jnp.float32,
    seed: int = 0,
    init_scale: Optional[float] = None,
) -> PackedTableState:
    """[T, S, 128] table holding ``capacity`` logical ``dim``-rows, G per
    tile; S=2 with the AdaGrad accumulator fused in (see
    :func:`_fuse_small_slots`), else S=1 with separate slot arrays."""
    from swiftsnails_tpu.ops.rowdma import ROW_LANES

    g = small_group(dim)
    stride = ROW_LANES // g
    t = -(-capacity // g)  # round UP: trailing group slots are dead padding
    fused = _fuse_small_slots(access, dtype)
    shape = (t, 2 if fused else 1, ROW_LANES)

    def init():
        rng = jax.random.PRNGKey(seed)
        param = access.init_param(rng, (t, ROW_LANES), dtype, fan_in=dim)
        if init_scale is not None:
            param = param * init_scale
        lane = (jnp.arange(ROW_LANES) % stride) < dim
        param = jnp.where(lane[None, :], param, 0).reshape(t, 1, ROW_LANES)
        if fused:
            accum = jnp.zeros((t, 1, ROW_LANES), dtype)
            return PackedTableState(
                table=jnp.concatenate([param, accum], axis=1), slots={}
            )
        slots = access.init_slots((t, ROW_LANES), dtype)
        slots = {k: v.reshape(shape) for k, v in slots.items()}
        return PackedTableState(table=param, slots=slots)

    if mesh is None:
        with _sharding_invariant_rng():
            return jax.jit(init)()
    sharding = table_sharding(mesh)
    if fused:
        state_shardings = PackedTableState(table=sharding, slots={})
    else:
        slot_spec = jax.eval_shape(lambda: access.init_slots((t, ROW_LANES), dtype))
        state_shardings = PackedTableState(
            table=sharding, slots={k: sharding for k in slot_spec}
        )
    with _sharding_invariant_rng():
        return jax.jit(init, out_shardings=state_shardings)()


@_scoped("ssn_pull_packed_small")
def pull_packed_small(
    state: PackedTableState, rows: jax.Array, dim: int,
    block_rows: int = 512, kernel: bool = True,
) -> jax.Array:
    """Gather logical rows -> [N, dim] (tile DMA + in-register lane select).

    ``kernel=False`` forces the XLA gather — required when the table is a
    GLOBAL sharded array outside shard_map (e.g. text export under a mesh),
    where the row-DMA kernel cannot be auto-partitioned."""
    from swiftsnails_tpu.ops import rowdma
    from swiftsnails_tpu.ops.rowdma import ROW_LANES

    g = small_group(dim)
    stride = ROW_LANES // g
    n = rows.shape[0]
    tiles = rows // g
    if rowdma.on_tpu() and kernel:
        padded, _ = _pad_to_block(tiles, 0, block_rows)
        gathered = rowdma.gather_rows(state.table, padded, block_rows=block_rows)[:n]
    else:
        gathered = state.table.at[tiles].get(mode="promise_in_bounds")
    # sublane 0 holds the params (sublane 1, when present, is the fused
    # AdaGrad accumulator — it rides the same DMA and is sliced off here)
    groups = gathered[:, 0, :].reshape(n, g, stride)
    vals = jnp.take_along_axis(groups, (rows % g)[:, None, None], axis=1)
    return vals[:, 0, :dim]


@_scoped("ssn_push_packed_small")
def push_packed_small(
    state: PackedTableState,
    rows: jax.Array,
    grads: jax.Array,  # [N, dim]
    access: AccessMethod,
    lr,
    dim: int,
    block_rows: int = 512,
) -> PackedTableState:
    """Merge-by-tile -> one fused RMW kernel (SGD add / in-kernel AdaGrad)."""
    from swiftsnails_tpu.ops import rowdma
    from swiftsnails_tpu.ops.rowdma import ROW_LANES, scatter_adagrad_rows
    from swiftsnails_tpu.parallel.access import AdaGradAccess, SgdAccess

    from swiftsnails_tpu.ops.rowdma import scatter_adagrad_fused_rows

    g = small_group(dim)
    stride = ROW_LANES // g
    n = rows.shape[0]
    t = state.table.shape[0]
    fused_slots = state.table.shape[1] == 2 and not state.slots

    pad_w = stride - dim
    grads_s = jnp.pad(grads, ((0, 0), (0, pad_w))) if pad_w else grads
    onehot = (jnp.arange(g)[None, :] == (rows % g)[:, None]).astype(grads_s.dtype)
    tile_grads = (onehot[:, :, None] * grads_s[:, None, :]).reshape(n, ROW_LANES)
    tiles = rows // g
    # lane groups are disjoint, so tile-level merge == per-row merge
    uniq, merged = merge_duplicate_rows(tiles, tile_grads, invalid_row=t)
    merged3 = merged.reshape(n, 1, ROW_LANES)

    if fused_slots:
        if not _fuse_small_slots(access, state.table.dtype):
            raise ValueError(
                "slot-fused table pushed with a non-AdaGrad access method")
        eps = access.eps
        if not rowdma.on_tpu():
            g32 = merged3.astype(jnp.float32)
            safe = jnp.where(uniq < t, uniq, 0)  # invalid: computed, dropped
            cur = state.table.at[safe].get(
                mode="promise_in_bounds").astype(jnp.float32)
            accum = cur[:, 1:2, :] + g32 * g32
            param = cur[:, 0:1, :] - lr * g32 * jax.lax.rsqrt(accum + eps)
            new = jnp.concatenate([param, accum], axis=1).astype(state.table.dtype)
            table = state.table.at[uniq].set(new, mode="drop")
            return PackedTableState(table=table, slots={})
        uniq, _ = _pad_to_block(uniq, t, block_rows)
        if uniq.shape[0] != merged3.shape[0]:
            pad = uniq.shape[0] - merged3.shape[0]
            merged3 = jnp.concatenate(
                [merged3, jnp.zeros((pad, 1, ROW_LANES), merged3.dtype)]
            )
        table = scatter_adagrad_fused_rows(
            state.table, uniq, merged3, lr, eps=eps, block_rows=block_rows
        )
        return PackedTableState(table=table, slots={})

    if not rowdma.on_tpu():
        table, slots = apply_rows(state.table, state.slots, uniq, merged3, access, lr)
        return PackedTableState(table=table, slots=slots)

    uniq, n_real = _pad_to_block(uniq, t, block_rows)
    if uniq.shape[0] != merged3.shape[0]:
        pad = uniq.shape[0] - merged3.shape[0]
        merged3 = jnp.concatenate(
            [merged3, jnp.zeros((pad, 1, ROW_LANES), merged3.dtype)]
        )

    if isinstance(access, SgdAccess) and not state.slots:
        deltas = (-lr * merged3).astype(state.table.dtype)
        table = rowdma.scatter_add_rows(state.table, uniq, deltas, block_rows=block_rows)
        return PackedTableState(table=table, slots=state.slots)
    if (
        isinstance(access, AdaGradAccess)
        and set(state.slots) == {"accum"}
        and state.slots["accum"].dtype == state.table.dtype
    ):
        table, accum = scatter_adagrad_rows(
            state.table, state.slots["accum"], uniq, merged3, lr,
            eps=access.eps, block_rows=block_rows,
        )
        return PackedTableState(table=table, slots={"accum": accum})

    safe = jnp.where(uniq < t, uniq, 0)
    cur = rowdma.gather_rows(state.table, safe, block_rows=block_rows)
    cur_slots = {
        k: rowdma.gather_rows(v, safe, block_rows=block_rows)
        for k, v in state.slots.items()
    }
    new_param, new_slots = access.apply_push_value(cur, cur_slots, merged3, lr)
    table = rowdma.scatter_write_rows(
        state.table, uniq, new_param.astype(state.table.dtype), block_rows=block_rows)
    slots = {
        k: rowdma.scatter_write_rows(
            state.slots[k], uniq, new_slots[k].astype(state.slots[k].dtype),
            block_rows=block_rows)
        for k in state.slots
    }
    return PackedTableState(table=table, slots=slots)


# ------------------------------------------------------- packed variant ---
#
# The DMA-kernel data plane (ops/rowdma.py): rows live as [S, 128] tiles of
# a [capacity, S, 128] table so one key == one row DMA, replacing XLA's
# serialized gather/scatter (~100-140 ns/row on v5e) with pipelined row DMAs.
# Padding lanes hold zeros and stay zero: every access rule satisfies
# update(grad=0) == 0. Same pull/push contract as the 2-D table above.


class PackedTableState(NamedTuple):
    """Packed sharded table [capacity, S, 128] + row-aligned slots.

    The logical row width (dim) is not part of the state — trainers own it;
    padding lanes are zero by construction and stay zero.
    """

    table: jax.Array
    slots: Slots

    @property
    def capacity(self) -> int:
        return self.table.shape[0]


def create_packed_table(
    capacity: int,
    dim: int,
    access: AccessMethod,
    mesh: Optional[Mesh] = None,
    dtype=jnp.float32,
    seed: int = 0,
    init_scale: Optional[float] = None,
) -> PackedTableState:
    """Packed-layout twin of :func:`create_table` (padding lanes zeroed)."""
    from swiftsnails_tpu.ops.rowdma import ROW_LANES, packed_shape

    shape = packed_shape(capacity, dim)
    s = shape[1]

    if mesh is None:
        with _sharding_invariant_rng():
            return _init_packed_table(shape, dim, access, dtype, seed,
                                      init_scale)
    sharding = table_sharding(mesh)  # rows sharded over "model"; S,128 whole
    slot_spec = jax.eval_shape(
        lambda: access.init_slots((capacity, s * ROW_LANES), dtype))
    with _sharding_invariant_rng():
        return _sharded_packed_init(
            shape, dim, access, dtype, seed, init_scale, sharding,
            tuple(sorted(slot_spec)))()


def _init_packed_impl(shape, dim, access, dtype, seed, init_scale):
    from swiftsnails_tpu.ops.rowdma import ROW_LANES

    capacity, s, _ = shape
    rng = jax.random.PRNGKey(seed)
    # init as if [capacity, dim]: same distribution, packed placement
    # (fan_in=dim — scaling by the padded width s*128 would start the
    # table up to 128/dim too small, see test_path_quality)
    param = access.init_param(rng, (capacity, s * ROW_LANES), dtype, fan_in=dim)
    if init_scale is not None:
        param = param * init_scale
    lane = jnp.arange(s * ROW_LANES) < dim
    param = jnp.where(lane[None, :], param, 0).reshape(shape)
    slots = access.init_slots((capacity, s * ROW_LANES), dtype)
    slots = {k: v.reshape(shape) for k, v in slots.items()}
    return PackedTableState(table=param, slots=slots)


# same once-per-key jit caching as _init_table (see the comment there)
_init_packed_table = jax.jit(
    _init_packed_impl, static_argnums=(0, 1, 2, 3, 4, 5))


@functools.lru_cache(maxsize=64)
def _sharded_packed_init(shape, dim, access, dtype, seed, init_scale,
                         sharding, slot_keys):
    state_shardings = PackedTableState(
        table=sharding, slots={k: sharding for k in slot_keys})
    return jax.jit(
        functools.partial(
            _init_packed_impl, shape, dim, access, dtype, seed, init_scale),
        out_shardings=state_shardings)


def _pad_to_block(rows: jax.Array, invalid_row: int, block: int):
    n = rows.shape[0]
    padded = -(-n // block) * block
    if padded == n:
        return rows, n
    return jnp.concatenate(
        [rows, jnp.full((padded - n,), invalid_row, rows.dtype)]
    ), n


@_scoped("ssn_pull_packed")
def pull_packed(state: PackedTableState, rows: jax.Array,
                block_rows: int = 512) -> jax.Array:
    """Gather packed rows -> [N, S, 128] (pull protocol, DMA kernel on TPU)."""
    from swiftsnails_tpu.ops import rowdma

    if rowdma.on_tpu():
        padded, n = _pad_to_block(rows, 0, block_rows)
        out = rowdma.gather_rows(state.table, padded, block_rows=block_rows)
        return out[:n]
    return state.table.at[rows].get(mode="promise_in_bounds")


@_scoped("ssn_push_packed")
def push_packed(
    state: PackedTableState,
    rows: jax.Array,
    grads: jax.Array,
    access: AccessMethod,
    lr,
    block_rows: int = 512,
) -> PackedTableState:
    """Merge duplicates -> apply access rule -> row-DMA writeback.

    ``grads`` is [N, S, 128]. The merge (argsort + segment-sum) implements
    ``merge_push_value`` exactly; unique rows make the DMA writeback
    race-free. SGD takes the add-only RMW kernel (one launch); other access
    methods gather current rows+slots, apply, and write back.
    """
    from swiftsnails_tpu.ops import rowdma
    from swiftsnails_tpu.parallel.access import SgdAccess

    cap = state.capacity
    uniq, merged = merge_duplicate_rows(rows, grads, invalid_row=cap)
    if not rowdma.on_tpu():
        table, slots = apply_rows(state.table, state.slots, uniq, merged, access, lr)
        return PackedTableState(table=table, slots=slots)

    uniq, n = _pad_to_block(uniq, cap, block_rows)
    if n != merged.shape[0]:
        pad = uniq.shape[0] - merged.shape[0]
        merged = jnp.concatenate([merged, jnp.zeros((pad,) + merged.shape[1:], merged.dtype)])

    if isinstance(access, SgdAccess) and not state.slots:
        deltas = (-lr * merged).astype(state.table.dtype)
        table = rowdma.scatter_add_rows(state.table, uniq, deltas, block_rows=block_rows)
        return PackedTableState(table=table, slots=state.slots)

    safe = jnp.where(uniq < cap, uniq, 0)
    cur = rowdma.gather_rows(state.table, safe, block_rows=block_rows)
    cur_slots = {
        k: rowdma.gather_rows(v, safe, block_rows=block_rows)
        for k, v in state.slots.items()
    }
    new_param, new_slots = access.apply_push_value(cur, cur_slots, merged, lr)
    table = rowdma.scatter_write_rows(state.table, uniq, new_param.astype(state.table.dtype),
                                       block_rows=block_rows)
    slots = {
        k: rowdma.scatter_write_rows(state.slots[k], uniq,
                                     new_slots[k].astype(state.slots[k].dtype),
                                     block_rows=block_rows)
        for k in state.slots
    }
    return PackedTableState(table=table, slots=slots)
