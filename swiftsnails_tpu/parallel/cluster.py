"""Multi-host runtime: rendezvous, lifecycle, data sharding.

Replaces the reference's entire cluster system (``src/core/system/``,
survey §2.4):

* master rendezvous + route broadcast (``MasterTransferInit``,
  ``master/init.h:21-171``; ``NodeTransferInit``, ``node_init.h:16-94``)
  -> :func:`initialize_cluster` — ``jax.distributed.initialize`` against a
  coordinator address; process ids come from the coordination service instead
  of the master's id-allocation protocol (``ServerWorkerRoute.h:17-31``);
* init barriers with ``init_timeout`` + CHECK-crash (``node_init.h:73-84``)
  -> the coordination service's own timeout, configured from the same key;
* end-of-training barrier + terminate broadcast (``MasterTerminate``,
  ``master/terminate.h:15-109``; ``ClientTerminate``) -> :func:`barrier`
  over all hosts;
* Hadoop-Streaming stdin data splits (``run_worker.sh``: ``cat > data.txt``)
  -> :func:`local_data_shard` by process index.

Config keys honored (reference inventory, survey §2.9): ``master_addr``
(coordinator address), ``expected_node_num`` (process count),
``init_timeout`` (seconds).

Single-process mode (the reference's ``local_train``) needs none of this:
every function degrades to a no-op/identity.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax

from swiftsnails_tpu.utils.config import Config

log = logging.getLogger("swiftsnails_tpu.cluster")


def initialize_cluster(config: Optional[Config] = None, process_id: Optional[int] = None) -> None:
    """Join the cluster (NodeTransferInit + MasterTransferInit equivalent).

    With ``master_addr`` and ``expected_node_num > 1`` in config, calls
    ``jax.distributed.initialize``. Without them (or with
    ``expected_node_num <= 1``), this is single-process mode and a no-op.
    """
    if config is None:
        return
    num_processes = config.get_int("expected_node_num", 1)
    if num_processes <= 1:
        return
    coordinator = config.get_str("master_addr")
    timeout_s = config.get_int("init_timeout", 300)
    kwargs = {}
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        initialization_timeout=timeout_s,
        **kwargs,
    )
    log.info(
        "joined cluster: process %d/%d via %s",
        jax.process_index(), jax.process_count(), coordinator,
    )


def process_info() -> Tuple[int, int]:
    """(process_index, process_count) — the reference's node id / node num."""
    return jax.process_index(), jax.process_count()


# coordination-service barrier ids must be unique per use; all processes run
# the same program, so a per-name process-local counter agrees fleet-wide
_barrier_seq: dict = {}


def barrier(name: str = "swiftsnails_barrier", timeout_s: float = 120.0) -> None:
    """All-host sync (MasterTerminate/ClientTerminate equivalent).

    Uses the coordination service's key-value barrier when available: it is
    pure control-plane (no device collectives), so it works on every backend
    — the CPU backend has no multiprocess device collectives, which the
    ``sync_global_devices`` fallback would need.
    """
    if jax.process_count() <= 1:
        return
    from jax._src import distributed

    client = getattr(distributed.global_state, "client", None)
    if client is not None:
        seq = _barrier_seq[name] = _barrier_seq.get(name, -1) + 1
        client.wait_at_barrier(f"{name}:{seq}",
                               timeout_in_ms=int(timeout_s * 1000))
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def local_data_shard(paths: Sequence[str]) -> List[str]:
    """Partition input files across hosts (Hadoop stdin-split equivalent).

    Files are assigned round-robin by process index; with fewer files than
    processes, callers should fall back to record-level sharding
    (:func:`shard_rows` / :func:`swiftsnails_tpu.data.text.iter_line_records`).
    """
    idx, count = process_info()
    return [p for i, p in enumerate(paths) if i % count == idx]


def shard_token_stream(
    ids: np.ndarray,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> np.ndarray:
    """This process's contiguous span of an encoded token stream.

    The reference gave each worker a contiguous region of the corpus (its
    Hadoop stdin split, ``run_worker.sh``); contiguity matters for window
    models — a strided split would cut every skip-gram context. Spans come
    from ``np.array_split`` so they are disjoint and cover the corpus.
    """
    if process_count is None:
        process_index, process_count = process_info()
    if process_count <= 1:
        return ids
    return np.array_split(ids, process_count)[process_index]


def byte_span(
    path: str,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> Tuple[int, int]:
    """This process's contiguous [start, end) byte span of a corpus file.

    For streaming ingestion: each host reads ONLY its span (Hadoop input-
    split parity — the reference's workers got their split on stdin,
    ``run_worker.sh``). Token-boundary adjustment happens in the stream
    readers (a token belongs to the span its first byte falls in).
    Returns (0, 0) — whole file — for a single process.
    """
    import os

    if process_count is None:
        process_index, process_count = process_info()
    if process_count <= 1:
        return 0, 0
    size = os.path.getsize(path)
    # per >= 1 and clamped ends: with size < process_count the surplus
    # processes get an EMPTY [size, size) span, never the (0, 0)
    # whole-file sentinel (which would silently duplicate the corpus)
    per = max(size // process_count, 1)
    start = min(process_index * per, size)
    end = size if process_index == process_count - 1 else min(start + per, size)
    return start, end


def shard_rows(
    *arrays: np.ndarray,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> Tuple[np.ndarray, ...]:
    """This process's round-robin row subset of record-oriented arrays.

    Line-record equivalent of the stdin split (same assignment as
    ``iter_line_records``: record ``i`` belongs to process ``i % count``),
    applied in parallel to aligned arrays (labels, features, ...).
    """
    if process_count is None:
        process_index, process_count = process_info()
    if process_count <= 1:
        return arrays
    return tuple(a[process_index::process_count] for a in arrays)
