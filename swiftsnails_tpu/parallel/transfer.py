"""Explicit-collective pull/push — the Transfer/RPC layer, TPU-native.

The reference's universal substrate is an async RPC round trip (survey §3.4):
``Transfer::send`` -> ZeroMQ -> remote handler -> response callback, fanned out
per server and joined on a ``StateBarrier`` (``src/core/transfer/transfer.h:55-268``,
``global_pull_access.h:40-55``, ``global_push_access.h:36-53``).

Here the same two protocols are written as explicit XLA collectives inside
``shard_map`` over a ``(data, model)`` mesh, so the communication pattern is
visible and pinned rather than left to the SPMD partitioner:

* **pull**  (WORKER_PULL_REQUEST): every model shard gathers the rows it owns
  for the local data shard's keys, others contribute zeros; a ``psum`` over
  ``model`` assembles full rows on every device. One all-reduce over ICI
  replaces the per-server request/response fan-out.
* **push**  (WORKER_PUSH_REQUEST): the (rows, grads) batch is ``all_gather``\\ ed
  along ``data`` (workers "send" their gradients), then each model shard
  merges duplicates and applies its owned rows through the access method.
  Replica consistency over ``data`` is by construction: every replica sees the
  same gathered batch and computes the identical update.

:func:`swiftsnails_tpu.parallel.store.pull` / ``push`` are the pjit
auto-partitioned equivalents; tests assert both paths agree bit-for-bit.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from swiftsnails_tpu.utils.compat import shard_map

from swiftsnails_tpu.parallel.access import AccessMethod
from swiftsnails_tpu.parallel.comm import (
    all_gather_quantized,
    psum_quantized,
    resolve_comm_dtype,
    stochastic_wire,
)
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from swiftsnails_tpu.parallel.store import TableState, apply_rows, merge_duplicate_rows

# Payload compression (``comm_dtype`` kwarg on every collective below): the
# (rows, grads) / assembled-row payloads quantize JUST before the
# all_gather/psum and dequantize into f32 accumulation at the owner shard —
# the master table and all shard-local math stay full precision. "float32"
# (the default) takes the original code path untouched, so existing callers
# are bit-identical. See parallel/comm.py for the wire formats and
# docs/SCALING.md for semantics; the int8 ``seed`` operand drives the
# stochastic rounding of gradients (replicated uint32 scalar, salted with
# the data-shard index inside the codec).


def _seed_operand(comm_dtype: str, seed):
    """(extra_args, extra_specs) for the optional int8/int4 dither seed."""
    if not stochastic_wire(comm_dtype):
        return (), ()
    s = jnp.uint32(0) if seed is None else jnp.asarray(seed).astype(jnp.uint32)
    return (s,), (P(),)


def _rows_per_shard(capacity: int, mesh: Mesh) -> int:
    model = mesh.shape[MODEL_AXIS]
    if capacity % model != 0:
        raise ValueError(f"capacity {capacity} not divisible by model axis {model}")
    return capacity // model


def bucket_capacity(local_n: int, model: int, slack: float) -> int:
    """Static per-sender bucket size for the owner-bucketed push.

    Mean occupancy after dedup is ``<= local_n / model`` under hashed (uniform)
    row placement; ``slack`` (default 2) puts the cap at slack x mean, rounded
    up to a multiple of 8 (sublane-friendly), clamped to ``local_n`` (at which
    point the bucketed path degenerates to the exact all_gather).
    """
    if model <= 1:
        return local_n
    cap = -(-int(slack * local_n) // model)
    # floor at one sublane group: slack * local_n < 1 must not produce a
    # zero-row bucket (empty buckets break the gather shapes downstream)
    cap = max(-(-cap // 8) * 8, 8)
    return min(cap, local_n)


def _compact_owned(uniq, merged, m, per, cap, invalid):
    """Select the rows of a deduped batch owned by model shard ``m``,
    compacted (stable, owned-first) into a static ``[cap]`` bucket.

    Returns ``(bucket_rows, bucket_grads, overflow)`` where ``overflow`` is
    the number of distinct owned rows that did not fit (their gradients are
    dropped by the caller — see :func:`push_collective_bucketed`).
    """
    local = uniq - m * per
    owned = (local >= 0) & (local < per)
    order = jnp.argsort(~owned, stable=True)  # owned first, original order
    take = order[:cap]
    ok = owned[take]
    b_rows = jnp.where(ok, uniq[take], invalid)
    mask = ok.reshape(ok.shape + (1,) * (merged.ndim - 1))
    b_grads = jnp.where(mask, merged[take], 0)
    overflow = jnp.maximum(owned.sum() - cap, 0)
    return b_rows, b_grads, overflow


def pull_collective(
    mesh: Mesh, state: TableState, rows: jax.Array,
    comm_dtype: str = "float32",
) -> jax.Array:
    """Sharded gather with explicit psum-over-model (pull protocol)."""
    per = _rows_per_shard(state.capacity, mesh)
    comm_dtype = resolve_comm_dtype(comm_dtype)

    def local_pull(table_shard, rows_local):
        m = lax.axis_index(MODEL_AXIS)
        local_ids = rows_local - m * per
        owned = (local_ids >= 0) & (local_ids < per)
        vals = table_shard.at[jnp.where(owned, local_ids, 0)].get(mode="promise_in_bounds")
        vals = jnp.where(owned[:, None], vals, 0)
        return psum_quantized(vals, MODEL_AXIS, comm_dtype)

    fn = shard_map(
        local_pull,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS, None),
        check_vma=False,
    )
    with jax.named_scope("ssn_pull_collective"):
        return fn(state.table, rows)


def push_collective(
    mesh: Mesh,
    state: TableState,
    rows: jax.Array,
    grads: jax.Array,
    access: AccessMethod,
    lr,
    exact: bool = False,
    comm_dtype: str = "float32",
    seed=None,
) -> TableState:
    """Sharded scatter-update with explicit all_gather-over-data (push protocol).

    Uses the same fast/exact update paths as :func:`~swiftsnails_tpu.parallel.
    store.push`, applied per model shard, so both data planes stay equivalent.
    """
    per = _rows_per_shard(state.capacity, mesh)
    comm_dtype = resolve_comm_dtype(comm_dtype)
    slot_keys = sorted(state.slots.keys())
    extra, extra_specs = _seed_operand(comm_dtype, seed)

    def local_push(table_shard, slot_shards, rows_local, grads_local, *dither):
        rows_all = lax.all_gather(rows_local, DATA_AXIS, tiled=True)
        grads_all = all_gather_quantized(
            grads_local, DATA_AXIS, comm_dtype, stochastic=True,
            seed=dither[0] if dither else None)
        m = lax.axis_index(MODEL_AXIS)
        local_ids = rows_all - m * per
        owned = (local_ids >= 0) & (local_ids < per)
        local_ids = jnp.where(owned, local_ids, per)  # unowned -> out of range
        grads_all = jnp.where(owned[:, None], grads_all, 0)
        if not exact:
            fast = access.scatter_update(table_shard, slot_shards, local_ids, grads_all, lr)
            if fast is not None:
                return fast
        uniq, merged = merge_duplicate_rows(local_ids, grads_all, invalid_row=per)
        return apply_rows(table_shard, slot_shards, uniq, merged, access, lr)

    shard_spec = P(MODEL_AXIS, None)
    fn = shard_map(
        local_push,
        mesh=mesh,
        in_specs=(shard_spec, {k: shard_spec for k in slot_keys},
                  P(DATA_AXIS), P(DATA_AXIS)) + extra_specs,
        out_specs=(shard_spec, {k: shard_spec for k in slot_keys}),
        check_vma=False,
    )
    with jax.named_scope("ssn_push_collective"):
        table, slots = fn(state.table, dict(state.slots), rows, grads, *extra)
    return TableState(table=table, slots=slots)


# ------------------------------------------------------- packed variants ---
#
# Same two protocols over the packed [capacity, S, 128] layout: the local
# shard work inside shard_map goes through the row-DMA kernel data plane
# (ops/rowdma via store.pull_packed/push_packed) on TPU, XLA fallback on CPU.
# The cross-device movement is identical to the 2-D path: pull assembles
# full rows with one psum over `model`; push all_gathers the (rows, grads)
# batch over `data` and every model shard updates only the rows it owns.


def pull_collective_packed(
    mesh: Mesh, state, rows: jax.Array, comm_dtype: str = "float32",
) -> jax.Array:
    """Sharded packed gather -> [N, S, 128] (pull protocol)."""
    from swiftsnails_tpu.parallel.store import PackedTableState, pull_packed

    per = _rows_per_shard(state.capacity, mesh)
    comm_dtype = resolve_comm_dtype(comm_dtype)

    def local_pull(table_shard, rows_local):
        m = lax.axis_index(MODEL_AXIS)
        local_ids = rows_local - m * per
        owned = (local_ids >= 0) & (local_ids < per)
        shard_state = PackedTableState(table=table_shard, slots={})
        vals = pull_packed(shard_state, jnp.where(owned, local_ids, 0))
        vals = jnp.where(owned[:, None, None], vals, 0)
        return psum_quantized(vals, MODEL_AXIS, comm_dtype)

    fn = shard_map(
        local_pull,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS, None, None), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS, None, None),
        check_vma=False,
    )
    with jax.named_scope("ssn_pull_collective_packed"):
        return fn(state.table, rows)


def push_collective_packed(
    mesh: Mesh,
    state,
    rows: jax.Array,
    grads: jax.Array,
    access: AccessMethod,
    lr,
    comm_dtype: str = "float32",
    seed=None,
):
    """Sharded packed push: all_gather over data, row-DMA update of owned rows."""
    from swiftsnails_tpu.parallel.store import PackedTableState, push_packed

    per = _rows_per_shard(state.capacity, mesh)
    comm_dtype = resolve_comm_dtype(comm_dtype)
    slot_keys = sorted(state.slots.keys())
    extra, extra_specs = _seed_operand(comm_dtype, seed)

    def local_push(table_shard, slot_shards, rows_local, grads_local, *dither):
        rows_all = lax.all_gather(rows_local, DATA_AXIS, tiled=True)
        grads_all = all_gather_quantized(
            grads_local, DATA_AXIS, comm_dtype, stochastic=True,
            seed=dither[0] if dither else None)
        m = lax.axis_index(MODEL_AXIS)
        local_ids = rows_all - m * per
        owned = (local_ids >= 0) & (local_ids < per)
        local_ids = jnp.where(owned, local_ids, per)  # unowned -> padding
        grads_all = jnp.where(owned[:, None, None], grads_all, 0)
        shard_state = PackedTableState(table=table_shard, slots=slot_shards)
        new = push_packed(shard_state, local_ids, grads_all, access, lr)
        return new.table, dict(new.slots)

    shard_spec = P(MODEL_AXIS, None, None)
    fn = shard_map(
        local_push,
        mesh=mesh,
        in_specs=(shard_spec, {k: shard_spec for k in slot_keys},
                  P(DATA_AXIS), P(DATA_AXIS)) + extra_specs,
        out_specs=(shard_spec, {k: shard_spec for k in slot_keys}),
        check_vma=False,
    )
    with jax.named_scope("ssn_push_collective_packed"):
        table, slots = fn(state.table, dict(state.slots), rows, grads, *extra)
    return PackedTableState(table=table, slots=slots)


# -------------------------------------------- small-row packed variants ---
#
# The CTR plane's collective twins (VERDICT r3 missing #2): the [T, S, 128]
# small-row table (G logical rows per 128-lane tile, store.small_group)
# shards at TILE granularity over `model` — tile t lives on shard t // perT,
# so logical row r (tile r // G) is owned by shard (r // G) // perT, i.e.
# shards own CONTIGUOUS logical row ranges of perT * G rows. Inside each
# shard the row movement is the same tile-DMA pull / fused-AdaGrad RMW push
# the single-device plane runs (store.pull_packed_small/push_packed_small);
# across shards it is the identical two collectives as every other plane
# (psum over `model` on pull, all_gather over `data` on push). This is the
# distributed serving loop of the reference's LR/CTR tables
# (src/core/parameter/sparsetable.h:123-222) on the packed layout.


def _tiles_per_shard(state, mesh: Mesh, dim: int) -> tuple:
    """(tiles per model shard, logical rows per model shard, G)."""
    from swiftsnails_tpu.parallel.store import small_group

    g = small_group(dim)
    t = state.table.shape[0]
    model = mesh.shape[MODEL_AXIS]
    if t % model != 0:
        raise ValueError(
            f"small-row tile count {t} not divisible by model axis {model}")
    per_t = t // model
    return per_t, per_t * g, g


def pull_collective_packed_small(
    mesh: Mesh, state, rows: jax.Array, dim: int,
    comm_dtype: str = "float32",
) -> jax.Array:
    """Sharded small-row gather -> [N, dim] (pull protocol)."""
    from swiftsnails_tpu.parallel.store import PackedTableState, pull_packed_small

    _, per_rows, _ = _tiles_per_shard(state, mesh, dim)
    comm_dtype = resolve_comm_dtype(comm_dtype)

    def local_pull(table_shard, rows_local):
        m = lax.axis_index(MODEL_AXIS)
        local_ids = rows_local - m * per_rows
        owned = (local_ids >= 0) & (local_ids < per_rows)
        shard_state = PackedTableState(table=table_shard, slots={})
        vals = pull_packed_small(
            shard_state, jnp.where(owned, local_ids, 0), dim)
        vals = jnp.where(owned[:, None], vals, 0)
        return psum_quantized(vals, MODEL_AXIS, comm_dtype)

    fn = shard_map(
        local_pull,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS, None, None), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS, None),
        check_vma=False,
    )
    with jax.named_scope("ssn_pull_collective_packed_small"):
        return fn(state.table, rows)


def push_collective_packed_small(
    mesh: Mesh,
    state,
    rows: jax.Array,
    grads: jax.Array,  # [N, dim]
    access: AccessMethod,
    lr,
    dim: int,
    comm_dtype: str = "float32",
    seed=None,
):
    """Sharded small-row push: all_gather over data, fused RMW of owned rows."""
    from swiftsnails_tpu.parallel.store import PackedTableState, push_packed_small

    _, per_rows, _ = _tiles_per_shard(state, mesh, dim)
    comm_dtype = resolve_comm_dtype(comm_dtype)
    slot_keys = sorted(state.slots.keys())
    extra, extra_specs = _seed_operand(comm_dtype, seed)

    def local_push(table_shard, slot_shards, rows_local, grads_local, *dither):
        rows_all = lax.all_gather(rows_local, DATA_AXIS, tiled=True)
        grads_all = all_gather_quantized(
            grads_local, DATA_AXIS, comm_dtype, stochastic=True,
            seed=dither[0] if dither else None)
        m = lax.axis_index(MODEL_AXIS)
        local_ids = rows_all - m * per_rows
        owned = (local_ids >= 0) & (local_ids < per_rows)
        # unowned -> per_rows: maps to tile per_t == shard tile count, the
        # invalid row the local plane's merge already drops
        local_ids = jnp.where(owned, local_ids, per_rows)
        grads_all = jnp.where(owned[:, None], grads_all, 0)
        shard_state = PackedTableState(table=table_shard, slots=slot_shards)
        new = push_packed_small(shard_state, local_ids, grads_all, access, lr, dim)
        return new.table, dict(new.slots)

    shard_spec = P(MODEL_AXIS, None, None)
    fn = shard_map(
        local_push,
        mesh=mesh,
        in_specs=(shard_spec, {k: shard_spec for k in slot_keys},
                  P(DATA_AXIS), P(DATA_AXIS)) + extra_specs,
        out_specs=(shard_spec, {k: shard_spec for k in slot_keys}),
        check_vma=False,
    )
    with jax.named_scope("ssn_push_collective_packed_small"):
        table, slots = fn(state.table, dict(state.slots), rows, grads, *extra)
    return PackedTableState(table=table, slots=slots)


# --------------------------------------------------- owner-bucketed push ---
#
# The all_gather push above moves every data shard's FULL (rows, grads) batch
# to every model shard, then masks to the ~1/model owned fraction — O(B*dim*
# data) received per device, the naive version of the survey's bucketed
# design (SURVEY §2.3 Transfer row: all_to_all of (key,grad) buckets by
# owner; reference shape: per-server request batching in
# src/core/parameter/global_push_access.h:58-99).
#
# Bucketed variant: the batch is replicated over `model` inside each data
# shard, so every sender can locally (a) merge duplicates, then (b) compact
# the rows owned by ITS OWN model index into a static [cap] bucket. The
# all_gather over `data` then carries cap rows instead of the full local
# batch — a ~model/slack traffic reduction, the exact sparse analog of
# reduce_scatter-by-owner. No model-axis collective is needed at all: the
# "send to owner" hop of the reference protocol is free here because the
# batch is already replicated over `model`.
#
# Static-shape overflow contract (same tradeoff as MoE expert-capacity
# dispatch): a bucket can hold at most `cap` DISTINCT owned rows; rows
# beyond that are dropped for the step and counted in the returned
# `dropped` scalar (replicated). With murmur-hashed placement the owned
# count concentrates at local_n/model (binomial), so slack=2 makes overflow
# probability astronomically small; cap == local_n (slack >= model) is
# byte-exact always. Callers surface `dropped` as a metric so a silent
# quality regression is impossible.


def push_collective_bucketed(
    mesh: Mesh,
    state: TableState,
    rows: jax.Array,
    grads: jax.Array,
    access: AccessMethod,
    lr,
    slack: float = 2.0,
    comm_dtype: str = "float32",
    seed=None,
):
    """Owner-bucketed sharded push. Returns ``(new_state, dropped)``."""
    per = _rows_per_shard(state.capacity, mesh)
    model = mesh.shape[MODEL_AXIS]
    local_n = rows.shape[0] // mesh.shape[DATA_AXIS]
    cap = bucket_capacity(local_n, model, slack)
    comm_dtype = resolve_comm_dtype(comm_dtype)
    slot_keys = sorted(state.slots.keys())
    invalid = state.capacity
    extra, extra_specs = _seed_operand(comm_dtype, seed)

    def local_push(table_shard, slot_shards, rows_local, grads_local, *dither):
        m = lax.axis_index(MODEL_AXIS)
        uniq_l, merged_l = merge_duplicate_rows(rows_local, grads_local, invalid_row=invalid)
        b_rows, b_grads, overflow = _compact_owned(uniq_l, merged_l, m, per, cap, invalid)
        rows_all = lax.all_gather(b_rows, DATA_AXIS, tiled=True)
        grads_all = all_gather_quantized(
            b_grads, DATA_AXIS, comm_dtype, stochastic=True,
            seed=dither[0] if dither else None)
        local_ids = rows_all - m * per  # all owned-by-m or invalid padding
        owned = (local_ids >= 0) & (local_ids < per)
        local_ids = jnp.where(owned, local_ids, per)
        uniq, merged = merge_duplicate_rows(local_ids, grads_all, invalid_row=per)
        table, slots = apply_rows(table_shard, slot_shards, uniq, merged, access, lr)
        dropped = lax.psum(lax.psum(overflow, DATA_AXIS), MODEL_AXIS)
        return table, slots, dropped

    shard_spec = P(MODEL_AXIS, None)
    fn = shard_map(
        local_push,
        mesh=mesh,
        in_specs=(shard_spec, {k: shard_spec for k in slot_keys},
                  P(DATA_AXIS), P(DATA_AXIS)) + extra_specs,
        out_specs=(shard_spec, {k: shard_spec for k in slot_keys}, P()),
        check_vma=False,
    )
    with jax.named_scope("ssn_push_collective_bucketed"):
        table, slots, dropped = fn(state.table, dict(state.slots), rows, grads,
                                   *extra)
    return TableState(table=table, slots=slots), dropped


# ------------------------------------------------- dedup'd packed planes ---
#
# The single-chip headline lever (the dedup kernels' one-DMA-per-distinct-row
# treatment, ops/fused_sgns.py) translated to the collective grouped plane
# (VERDICT r4 #4): each DATA shard builds a shard-local static unique list of
# its row ids, so the `model` psum on pull and the `data` all_gather on push
# carry ``u_cap`` merged rows instead of the full local batch. The cut is the
# STATIC shape ratio n_local/u_cap — verified from compiled psum+all-gather
# bytes (`tools/kernel_lab.py --dedup-traffic`: 4.00x at u_cap=1024, 8.00x at
# u_cap=512, both legs) — and is only real when the unique list does not
# overflow; the same lab asserts zero overflow on a block-ordered zipf window
# batch at the production duplicate rate (4.9% distinct). The reference's analogous
# dedup-before-transfer is the per-server key grouping of
# ``src/core/parameter/global_pull_access.h:58-72`` (one request per server
# carries each key once) and the duplicate merge of ``merge_push_value``
# (``sparsetable.h:176-179``).
#
# Static-capacity contract (same as the bucketed push): a shard's DISTINCT
# row count beyond ``u_cap`` overflows — overflow slots pull zero rows /
# drop their gradients for the step, and the count is returned so callers
# surface it as a metric. Semantics for in-cap rows are the DETERMINISTIC
# merged update, identical to the plain collective plane.


def _unique_static(rows: jax.Array, cap: int, invalid: int):
    """Shard-local static-size dedup.

    Returns ``(uniq [cap], inv [n], overflow)``: ``uniq`` holds the distinct
    row ids in sorted order (``invalid``-padded past the distinct count),
    ``inv[i]`` is the position of ``rows[i]`` in ``uniq`` — or ``cap`` (one
    past the end) when that row's group overflowed — and ``overflow`` counts
    the distinct rows that did not fit.
    """
    n = rows.shape[0]
    order = jnp.argsort(rows)
    sorted_rows = rows[order]
    is_first = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_rows[1:] != sorted_rows[:-1]])
    grp = jnp.cumsum(is_first) - 1  # unique-group index per sorted position
    n_uniq = grp[-1] + 1
    uniq = jnp.full((cap,), invalid, rows.dtype).at[
        jnp.where(grp < cap, grp, cap)
    ].set(sorted_rows, mode="drop")
    inv = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.where(grp < cap, grp, cap).astype(jnp.int32))
    overflow = jnp.maximum(n_uniq - cap, 0)
    return uniq, inv, overflow


def pull_collective_packed_dedup(
    mesh: Mesh, state, rows: jax.Array, u_cap: int,
    comm_dtype: str = "float32",
):
    """Dedup'd sharded packed gather (pull protocol over a unique list).

    Returns ``(vals [N, S, 128], (uniq, inv), overflow)``; overflowed slots
    pull zeros. ``(uniq, inv)`` is the shard-local unique index (data-axis
    sharded) — pass it to :func:`push_collective_packed_dedup` for the same
    ``rows`` to skip the duplicate sort there and avoid double-counting the
    overflow metric.
    """
    from swiftsnails_tpu.parallel.store import PackedTableState, pull_packed

    per = _rows_per_shard(state.capacity, mesh)
    comm_dtype = resolve_comm_dtype(comm_dtype)
    invalid = state.capacity

    def local_pull(table_shard, rows_local):
        uniq, inv, overflow = _unique_static(rows_local, u_cap, invalid)
        m = lax.axis_index(MODEL_AXIS)
        local_ids = uniq - m * per
        owned = (local_ids >= 0) & (local_ids < per)
        shard_state = PackedTableState(table=table_shard, slots={})
        vals = pull_packed(shard_state, jnp.where(owned, local_ids, 0))
        vals = jnp.where(owned[:, None, None], vals, 0)
        vals = psum_quantized(vals, MODEL_AXIS, comm_dtype)  # [u_cap, S, L]
        # expand unique rows back to their slots; overflow slots (inv ==
        # u_cap) read the appended zero row
        vals = jnp.concatenate(
            [vals, jnp.zeros((1,) + vals.shape[1:], vals.dtype)])
        out = vals.at[inv].get(mode="promise_in_bounds")
        return out, uniq, inv, lax.psum(overflow, DATA_AXIS)

    fn = shard_map(
        local_pull,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS, None, None), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS, None, None), P(DATA_AXIS), P(DATA_AXIS), P()),
        check_vma=False,
    )
    with jax.named_scope("ssn_pull_collective_packed_dedup"):
        vals, uniq, inv, overflow = fn(state.table, rows)
    return vals, (uniq, inv), overflow


def push_collective_packed_dedup(
    mesh: Mesh,
    state,
    rows: jax.Array,
    grads: jax.Array,
    access: AccessMethod,
    lr,
    u_cap: int,
    index=None,
    comm_dtype: str = "float32",
    seed=None,
):
    """Sender-dedup'd packed push: duplicates merge into the unique list
    BEFORE the all_gather over ``data``. Returns ``(new_state, dropped)``.

    ``index``: the ``(uniq, inv)`` pair a prior
    :func:`pull_collective_packed_dedup` over the SAME ``rows`` returned —
    skips the duplicate shard-local sort and returns ``dropped = 0`` (the
    pull already counted those distinct-row overflow events; counting both
    legs would double the metric)."""
    from swiftsnails_tpu.parallel.store import PackedTableState, push_packed

    per = _rows_per_shard(state.capacity, mesh)
    comm_dtype = resolve_comm_dtype(comm_dtype)
    slot_keys = sorted(state.slots.keys())
    invalid = state.capacity
    extra, extra_specs = _seed_operand(comm_dtype, seed)

    def local_push(table_shard, slot_shards, rows_local, grads_local, *rest):
        dither = rest[-1:] if extra else ()
        idx = rest[: len(rest) - len(dither)]
        if idx:
            uniq, inv = idx
            overflow = jnp.int32(0)
        else:
            uniq, inv, overflow = _unique_static(rows_local, u_cap, invalid)
            overflow = lax.psum(overflow, DATA_AXIS)
        merged = jnp.zeros(
            (u_cap,) + grads_local.shape[1:], grads_local.dtype
        ).at[inv].add(grads_local, mode="drop")
        rows_all = lax.all_gather(uniq, DATA_AXIS, tiled=True)
        grads_all = all_gather_quantized(
            merged, DATA_AXIS, comm_dtype, stochastic=True,
            seed=dither[0] if dither else None)
        m = lax.axis_index(MODEL_AXIS)
        local_ids = rows_all - m * per
        owned = (local_ids >= 0) & (local_ids < per)
        local_ids = jnp.where(owned, local_ids, per)  # unowned -> padding
        grads_all = jnp.where(owned[:, None, None], grads_all, 0)
        shard_state = PackedTableState(table=table_shard, slots=slot_shards)
        new = push_packed(shard_state, local_ids, grads_all, access, lr)
        return new.table, dict(new.slots), overflow

    shard_spec = P(MODEL_AXIS, None, None)
    idx_args = () if index is None else tuple(index)
    idx_specs = () if index is None else (P(DATA_AXIS), P(DATA_AXIS))
    fn = shard_map(
        local_push,
        mesh=mesh,
        in_specs=(shard_spec, {k: shard_spec for k in slot_keys},
                  P(DATA_AXIS), P(DATA_AXIS)) + idx_specs + extra_specs,
        out_specs=(shard_spec, {k: shard_spec for k in slot_keys}, P()),
        check_vma=False,
    )
    with jax.named_scope("ssn_push_collective_packed_dedup"):
        table, slots, dropped = fn(
            state.table, dict(state.slots), rows, grads, *idx_args, *extra)
    return PackedTableState(table=table, slots=slots), dropped


def push_collective_packed_bucketed(
    mesh: Mesh,
    state,
    rows: jax.Array,
    grads: jax.Array,
    access: AccessMethod,
    lr,
    slack: float = 2.0,
    comm_dtype: str = "float32",
    seed=None,
):
    """Owner-bucketed packed push ([N, S, 128] grads). Returns ``(state, dropped)``."""
    from swiftsnails_tpu.parallel.store import PackedTableState, push_packed

    per = _rows_per_shard(state.capacity, mesh)
    model = mesh.shape[MODEL_AXIS]
    local_n = rows.shape[0] // mesh.shape[DATA_AXIS]
    cap = bucket_capacity(local_n, model, slack)
    comm_dtype = resolve_comm_dtype(comm_dtype)
    slot_keys = sorted(state.slots.keys())
    invalid = state.capacity
    extra, extra_specs = _seed_operand(comm_dtype, seed)

    def local_push(table_shard, slot_shards, rows_local, grads_local, *dither):
        m = lax.axis_index(MODEL_AXIS)
        uniq_l, merged_l = merge_duplicate_rows(rows_local, grads_local, invalid_row=invalid)
        b_rows, b_grads, overflow = _compact_owned(uniq_l, merged_l, m, per, cap, invalid)
        rows_all = lax.all_gather(b_rows, DATA_AXIS, tiled=True)
        grads_all = all_gather_quantized(
            b_grads, DATA_AXIS, comm_dtype, stochastic=True,
            seed=dither[0] if dither else None)
        local_ids = rows_all - m * per
        owned = (local_ids >= 0) & (local_ids < per)
        local_ids = jnp.where(owned, local_ids, per)
        grads_all = jnp.where(owned[:, None, None], grads_all, 0)
        shard_state = PackedTableState(table=table_shard, slots=slot_shards)
        new = push_packed(shard_state, local_ids, grads_all, access, lr)
        dropped = lax.psum(lax.psum(overflow, DATA_AXIS), MODEL_AXIS)
        return new.table, dict(new.slots), dropped

    shard_spec = P(MODEL_AXIS, None, None)
    fn = shard_map(
        local_push,
        mesh=mesh,
        in_specs=(shard_spec, {k: shard_spec for k in slot_keys},
                  P(DATA_AXIS), P(DATA_AXIS)) + extra_specs,
        out_specs=(shard_spec, {k: shard_spec for k in slot_keys}, P()),
        check_vma=False,
    )
    with jax.named_scope("ssn_push_collective_packed_bucketed"):
        table, slots, dropped = fn(state.table, dict(state.slots), rows, grads,
                                   *extra)
    return PackedTableState(table=table, slots=slots), dropped


# ---------------------------------------------------- tiered cache plane ---
#
# Slot-indexed twins for the host tier (swiftsnails_tpu/tiered): under a
# mesh the HBM working-set cache is a row-sharded plane like any other
# table, and because capacity and the invalid-row sentinel derive from
# table.shape[0], the pull/push collectives above already operate correctly
# in cache-slot space. The named wrappers pin that contract; the scatter
# below is the genuinely new mover — the batched host->device fault path
# installing gathered master rows shard-local (no resharding round trip).


def pull_collective_slots(mesh: Mesh, cache_state, slots: jax.Array,
                          comm_dtype: str = "float32") -> jax.Array:
    """Slot-indexed pull over a tiered cache plane.

    Identical protocol to :func:`pull_collective`; ``slots`` are cache-slot
    ids produced by the host-side remap (``tiered.TieredTable.remap``), and
    the per-shard row count derives from the CACHE capacity, so no resident
    assumptions leak in. The packed twins dispatch the same way — a cache
    plane is indistinguishable from a small table.
    """
    return pull_collective(mesh, cache_state, slots, comm_dtype=comm_dtype)


def push_collective_slots(
    mesh: Mesh, cache_state, slots: jax.Array, grads: jax.Array,
    access: AccessMethod, lr, comm_dtype: str = "float32", seed=None,
):
    """Slot-indexed push over a tiered cache plane (see
    :func:`pull_collective_slots`); the invalid-row sentinel is the cache
    budget, so padded/dropped slots behave exactly as on the resident path."""
    return push_collective(mesh, cache_state, slots, grads, access, lr,
                           comm_dtype=comm_dtype, seed=seed)


@functools.partial(jax.jit, static_argnums=(0,))
def scatter_slots_collective(mesh: Mesh, plane: jax.Array, slot_ids,
                             values) -> jax.Array:
    """Install faulted rows into a row-sharded cache plane, shard-local.

    ``slot_ids``/``values`` are replicated (the fault batch is tiny relative
    to the plane); each model shard keeps only its owned slice via an
    OOB-drop scatter, so the plane's sharding is preserved and no
    cross-shard traffic moves table bytes twice. Out-of-range ids
    (``plane.shape[0]`` padding) are dropped everywhere.
    """
    from swiftsnails_tpu.parallel.mesh import MODEL_AXIS as _M

    model = mesh.shape[_M]
    if plane.shape[0] % model:
        raise ValueError(
            f"cache budget {plane.shape[0]} not divisible by model axis {model}")
    per = plane.shape[0] // model
    spec = P(_M, *([None] * (plane.ndim - 1)))

    def body(shard, ids, vals):
        m = lax.axis_index(_M)
        local = ids - m * per
        local = jnp.where((local >= 0) & (local < per), local, per)
        return shard.at[local].set(vals.astype(shard.dtype), mode="drop")

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec, P(), P()),
        out_specs=spec,
        check_vma=False,
    )
    with jax.named_scope("ssn_tier_fault_scatter"):
        return fn(plane, jnp.asarray(slot_ids), jnp.asarray(values))
