"""Hybrid head/tail placement: replicate the zipf head, shard the tail.

Uniform hash sharding (the reference's ``hashfrag``) treats every row the
same, so the zipf head of a skewed vocabulary pays gather/scatter collective
indirection on every substep even though a handful of rows absorb most of
the traffic. Parallax's observation (PAPERS.md) is that placement should
follow sparsity: rows accessed densely want *replication* + a dense
gradient all-reduce (no indirection, no per-row ids on the wire), rows
accessed sparsely want the sharded pull/push protocol.

This module implements that split on top of the existing store/transfer
planes:

* **head** — the first ``cut`` logical rows, replicated on every device
  (``P()``). Pulls are shard-local gathers (ZERO collective bytes); pushes
  scatter-add the batch gradients into a dense ``[cut, ...]`` f32 buffer and
  reduce it once over ``data`` — through the same quantized wire options
  (:func:`~swiftsnails_tpu.parallel.comm.reduce_sum_quantized`) as the
  sharded path.
* **tail** — everything past the cut, kept in today's model-sharded layout.
  Row ids are remapped to *tail slot space* (``row - cut``; head rows map to
  the tail's invalid sentinel, mirroring the tiered remap pattern) and flow
  through the unmodified collective twins. The packed plane additionally
  routes through the dedup twins with a statically smaller unique capacity
  (``tail_cap``) sized from the head's access coverage — this is where the
  wire bytes actually shrink: collective payloads are static shapes, so
  only a statically smaller tail batch cuts audited exchange bytes.

``HybridTableState`` carries ONLY array leaves (head plane, head slots,
tail table state) so it is a well-formed jit/scan pytree; all static
geometry (cut, layout, group) is derived from the leaf shapes or passed by
the caller. Checkpoints never see this type: :func:`merge_table` rebuilds
the uniform layout bit-exactly (split/merge are value-preserving slices
along the stored leading axis), so serving, tiered mode, and resume stay
transparent — see framework/checkpoint.py.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from swiftsnails_tpu.utils.compat import shard_map

from swiftsnails_tpu.parallel.access import AccessMethod
from swiftsnails_tpu.parallel.comm import (
    reduce_scatter_quantized,
    reduce_sum_quantized,
    resolve_comm_dtype,
)
from swiftsnails_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    replicated,
    table_sharding,
)
from swiftsnails_tpu.parallel.store import PackedTableState, TableState
from swiftsnails_tpu.parallel.transfer import (
    _seed_operand,
    pull_collective,
    pull_collective_packed_dedup,
    push_collective,
    push_collective_packed_bucketed,
    push_collective_packed_dedup,
    push_collective_packed_small,
    pull_collective_packed_small,
)

ROW_LANES = 128


class HybridTableState(NamedTuple):
    """Split table: replicated head plane + model-sharded tail state.

    ``head`` is the stored-layout prefix (``[cut, dim]`` dense,
    ``[cut, S, 128]`` packed, ``[cut_tiles, S, 128]`` small-row);
    ``head_slots`` are the matching optimizer-slot prefixes; ``tail`` is a
    regular :class:`TableState` / :class:`PackedTableState` over the
    remaining rows. Only array leaves — safe as a jit donation target and a
    ``lax.scan`` carry.
    """

    head: jax.Array
    head_slots: Dict[str, jax.Array]
    tail: Union[TableState, PackedTableState]


def is_hybrid(state) -> bool:
    return isinstance(state, HybridTableState)


# ------------------------------------------------------------ split/merge ---


def split_table(state, cut: int, mesh=None, group: int = 1) -> HybridTableState:
    """Uniform layout -> hybrid, value-preserving (eager, outside jit).

    ``cut`` counts LOGICAL rows; for the small-row plane it must be a
    multiple of ``group`` so the split lands on a tile boundary (the slice
    index is ``cut // group`` stored tiles). Head leaves are replicated,
    tail leaves keep the model-axis table sharding.
    """
    if cut % group:
        raise ValueError(f"cut {cut} not aligned to small-row group {group}")
    row_cut = cut // group
    head = state.table[:row_cut]
    head_slots = {k: v[:row_cut] for k, v in state.slots.items()}
    tail_table = state.table[row_cut:]
    tail_slots = {k: v[row_cut:] for k, v in state.slots.items()}
    if mesh is not None:
        rep, shard = replicated(mesh), table_sharding(mesh)
        head = jax.device_put(head, rep)
        head_slots = {k: jax.device_put(v, rep) for k, v in head_slots.items()}
        tail_table = jax.device_put(tail_table, shard)
        tail_slots = {k: jax.device_put(v, shard) for k, v in tail_slots.items()}
    tail = state._replace(table=tail_table, slots=tail_slots)
    return HybridTableState(head=head, head_slots=head_slots, tail=tail)


def merge_table(hs: HybridTableState, mesh=None):
    """Hybrid -> uniform layout, bit-exact inverse of :func:`split_table`.

    The concat happens HOST-side: a device ``jnp.concatenate`` of a
    replicated head with a model-sharded tail is exactly the mixed-lineage
    GSPMD shape XLA miscompiles (docs/SCALING.md "sharp edges"; the same
    hazard ``_mesh_safe_cat`` works around in the word2vec model). Merge is
    an eager boundary op (checkpoint/export/end-of-run), so the host
    round-trip costs nothing on the training path.
    """
    import numpy as np

    def cat(a, b):
        return np.concatenate([np.asarray(a), np.asarray(b)], axis=0)

    table = cat(hs.head, hs.tail.table)
    slots = {k: cat(hs.head_slots[k], v) for k, v in hs.tail.slots.items()}
    if mesh is not None:
        shard = table_sharding(mesh)
        table = jax.device_put(table, shard)
        slots = {k: jax.device_put(v, shard) for k, v in slots.items()}
    else:
        table = jnp.asarray(table)
        slots = {k: jnp.asarray(v) for k, v in slots.items()}
    return hs.tail._replace(table=table, slots=slots)


# ------------------------------------------------------------- tail remap ---


def tail_ids(rows: jax.Array, cut: int, tail_sentinel) -> jax.Array:
    """Row ids -> tail slot space: ``row - cut`` for tail rows, the tail's
    invalid sentinel for head rows (the collective twins own-mask them to
    no-ops, mirroring the tiered remap's treatment of out-of-cache ids).
    A uniform-space invalid sentinel (``capacity``) lands on the tail
    sentinel by construction: ``capacity - cut == tail_capacity``."""
    return jnp.where(rows >= cut, rows - cut, tail_sentinel)


# -------------------------------------------------------------- head pull ---
#
# The head plane is replicated, so a pull is a shard-local gather — no
# collective is emitted and the comm audit sees zero bytes for it. Rows at
# or past the cut (tail rows, pad sentinels) read zero; the combined value
# is head_vals + tail_vals since exactly one side is nonzero per row.


def head_pull(mesh: Mesh, head: jax.Array, rows: jax.Array,
              layout: str, dim: int = 0, group: int = 1) -> jax.Array:
    cut_t = head.shape[0]  # rows (dense/packed) or tiles (small)

    def local(head, rows):
        if layout == "small":
            tiles = rows // group
            ok = (rows >= 0) & (tiles < cut_t)
            safe = jnp.clip(tiles, 0, cut_t - 1)
            gathered = head.at[safe].get(mode="promise_in_bounds")
            stride = ROW_LANES // group
            groups = gathered[:, 0, :].reshape(-1, group, stride)
            vals = jnp.take_along_axis(
                groups, (rows % group)[:, None, None], axis=1)[:, 0, :dim]
            return jnp.where(ok[:, None], vals, 0)
        ok = (rows >= 0) & (rows < cut_t)
        safe = jnp.clip(rows, 0, cut_t - 1)
        vals = head.at[safe].get(mode="promise_in_bounds")
        mask = ok[:, None, None] if head.ndim == 3 else ok[:, None]
        return jnp.where(mask, vals, 0)

    out_spec = P(DATA_AXIS, None, None) if (
        layout == "packed") else P(DATA_AXIS, None)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=out_spec,
        check_vma=False,
    )
    with jax.named_scope("ssn_hybrid_head_pull"):
        return fn(head, rows)


# -------------------------------------------------------------- head push ---
#
# All data shards contribute gradients for the same replicated rows, so the
# owner-exclusive psum_quantized contract does NOT hold here — the dense
# reduce goes through comm.reduce_sum_quantized (f32 psum, or quantize-
# per-shard + all_gather + f32 sum for bf16/int8, same stochastic-rounding
# dither as the sharded push wire). Scatter-adds use mode="drop": tail rows
# and pad sentinels index past the head buffer and fall out naturally, so
# callers pass the UNSPLIT (rows, grads) batch. Duplicate rows merge in the
# scatter-add before the optimizer update — the same merge-before-update
# semantics as the sharded twins' merge_duplicate_rows.


def head_push(mesh: Mesh, head: jax.Array, head_slots: Dict[str, jax.Array],
              rows: jax.Array, grads: jax.Array, access: AccessMethod, lr,
              layout: str, dim: int = 0, group: int = 1,
              comm_dtype: str = "float32", seed=None, zero: bool = False):
    comm_dtype = resolve_comm_dtype(comm_dtype)
    data = mesh.shape[DATA_AXIS]
    cut_t = head.shape[0]
    slot_keys = sorted(head_slots)
    extra, extra_specs = _seed_operand(comm_dtype, seed)
    fused_small = (
        layout == "small" and head.ndim == 3 and head.shape[1] == 2
        and not head_slots
    )
    # duplicate-merge parity per layout: the packed/small planes' sharded
    # twins merge duplicates BEFORE the optimizer update (apply_push_value on
    # merged grads), but the 2-D dense plane updates through the per-sample
    # accumulator variant (AdaGradAccess.scatter_update: ``accum += Σ g_i²``,
    # then one step at the final accumulator). The head must follow whichever
    # rule its tail/uniform baseline uses or hybrid-vs-uniform drifts on
    # every duplicated hot row.
    per_sample = layout == "dense" and "accum" in slot_keys
    # ZeRO update sharding (arXiv 2004.13336): the summed grad arrives via
    # reduce-scatter, each data shard updates only its owned 1/data row
    # slice of the head plane, and only the PARAM slice is all-gathered back
    # (exact f32 concat — bit-identical to the replicated update). Slot
    # planes stay resident as shards (out spec P(data)): that is the HBM
    # win. The param must stay replicated because head_pull is a
    # zero-collective local gather.
    if zero and cut_t % data:
        raise ValueError(
            f"optimizer_sharding: zero needs head rows ({cut_t}) aligned to "
            f"the data axis ({data}); widen placement alignment")

    def local(head, slots, rows, grads, *dither):
        if layout == "small":
            stride = ROW_LANES // group
            pad_w = stride - dim
            g_s = jnp.pad(grads, ((0, 0), (0, pad_w))) if pad_w else grads
            onehot = (jnp.arange(group)[None, :]
                      == (rows % group)[:, None]).astype(g_s.dtype)
            flat = (onehot[:, :, None] * g_s[:, None, :]).reshape(-1, ROW_LANES)
            idx = jnp.where(rows >= 0, rows // group, cut_t)
            buf = jnp.zeros((cut_t, ROW_LANES), jnp.float32).at[idx].add(
                flat.astype(jnp.float32), mode="drop")
        else:
            idx = jnp.where(rows >= 0, rows, cut_t)
            buf = jnp.zeros((cut_t,) + grads.shape[1:], jnp.float32).at[
                idx].add(grads.astype(jnp.float32), mode="drop")

        if zero:
            own = cut_t // data
            p = lax.dynamic_slice_in_dim(
                head, lax.axis_index(DATA_AXIS) * own, own, axis=0)

            def reduce(b, s):
                return reduce_scatter_quantized(
                    b, DATA_AXIS, comm_dtype, axis_size=data,
                    stochastic=True, seed=s)
        else:
            p = head

            def reduce(b, s):
                return reduce_sum_quantized(
                    b, DATA_AXIS, comm_dtype, axis_size=data,
                    stochastic=True, seed=s)

        tot = reduce(buf, dither[0] if dither else None)
        if per_sample:
            buf2 = jnp.zeros((cut_t,) + grads.shape[1:], jnp.float32).at[
                idx].add(jnp.square(grads.astype(jnp.float32)), mode="drop")
            tot2 = reduce(
                buf2, dither[0] + jnp.uint32(1) if dither else None)
            accum = slots["accum"].astype(jnp.float32) + tot2
            step = lr * tot * lax.rsqrt(accum + access.eps)
            new_p = p - step.astype(p.dtype)
            out = {"accum": accum.astype(slots["accum"].dtype)}
            new_s = {k: out.get(k, slots[k]) for k in slot_keys}
        elif fused_small:
            cur = p.astype(jnp.float32)
            accum = cur[:, 1, :] + tot * tot
            param = cur[:, 0, :] - lr * tot * lax.rsqrt(accum + access.eps)
            new_p = jnp.stack([param, accum], axis=1).astype(p.dtype)
            new_s = {}
        else:
            merged = tot.reshape(
                (p.shape[0], 1, ROW_LANES)) if layout == "small" else tot
            new_p, ns = access.apply_push_value(p, slots, merged, lr)
            new_s = {k: ns[k] for k in slot_keys}
        if zero:
            new_p = lax.all_gather(new_p, DATA_AXIS, tiled=True)
        return new_p, new_s

    slot_spec = P(DATA_AXIS) if zero else P()
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), {k: slot_spec for k in slot_keys},
                  P(DATA_AXIS), P(DATA_AXIS)) + extra_specs,
        out_specs=(P(), {k: slot_spec for k in slot_keys}),
        check_vma=False,
    )
    scope = "ssn_zero_head_push" if zero else "ssn_hybrid_head_push"
    with jax.named_scope(scope):
        return fn(head, dict(head_slots), rows, grads, *extra)


# ------------------------------------------------------------ dense plane ---


def pull_hybrid(mesh: Mesh, hs: HybridTableState, rows: jax.Array,
                comm_dtype: str = "float32") -> jax.Array:
    """Hybrid twin of transfer.pull_collective over the 2-D dense plane."""
    cut = hs.head.shape[0]
    head_vals = head_pull(mesh, hs.head, rows, layout="dense")
    t_ids = tail_ids(rows, cut, hs.tail.capacity)
    tail_vals = pull_collective(mesh, hs.tail, t_ids, comm_dtype=comm_dtype)
    return head_vals + tail_vals


def push_hybrid(mesh: Mesh, hs: HybridTableState, rows: jax.Array,
                grads: jax.Array, access: AccessMethod, lr,
                exact: bool = False, comm_dtype: str = "float32",
                seed=None, zero: bool = False) -> HybridTableState:
    cut = hs.head.shape[0]
    t_ids = tail_ids(rows, cut, hs.tail.capacity)
    tail = push_collective(mesh, hs.tail, t_ids, grads, access, lr,
                           exact=exact, comm_dtype=comm_dtype, seed=seed)
    head, head_slots = head_push(
        mesh, hs.head, hs.head_slots, rows, grads, access, lr,
        layout="dense", comm_dtype=comm_dtype, seed=seed, zero=zero)
    return HybridTableState(head=head, head_slots=head_slots, tail=tail)


# ----------------------------------------------------------- packed plane ---
#
# The packed tail rides the dedup twins with a static ``tail_cap`` unique
# capacity sized from the head's coverage (placement.tail_cap): the psum /
# all_gather payloads shrink from [n_local, S, 128] to [tail_cap, S, 128].
# This is the structural byte win — the head absorbs most accesses, so a
# small tail_cap still fits the distinct tail rows of a batch; overflow is
# counted (rows drop their update, never corrupt) exactly like the dedup
# lane.


def pull_hybrid_packed(mesh: Mesh, hs: HybridTableState, rows: jax.Array,
                       tail_cap: int, comm_dtype: str = "float32"):
    """-> (vals [N, S, 128], tail (uniq, inv) index, overflow)."""
    cut = hs.head.shape[0]
    head_vals = head_pull(mesh, hs.head, rows, layout="packed")
    t_ids = tail_ids(rows, cut, hs.tail.capacity)
    tail_vals, index, overflow = pull_collective_packed_dedup(
        mesh, hs.tail, t_ids, tail_cap, comm_dtype=comm_dtype)
    return head_vals + tail_vals, index, overflow


def push_hybrid_packed(mesh: Mesh, hs: HybridTableState, rows: jax.Array,
                       grads: jax.Array, access: AccessMethod, lr,
                       tail_cap: int, index=None,
                       comm_dtype: str = "float32", seed=None,
                       zero: bool = False):
    """-> (new_state, dropped). ``index`` reuses a pull's (uniq, inv)."""
    cut = hs.head.shape[0]
    t_ids = tail_ids(rows, cut, hs.tail.capacity)
    tail, dropped = push_collective_packed_dedup(
        mesh, hs.tail, t_ids, grads, access, lr, tail_cap, index=index,
        comm_dtype=comm_dtype, seed=seed)
    head, head_slots = head_push(
        mesh, hs.head, hs.head_slots, rows, grads, access, lr,
        layout="packed", comm_dtype=comm_dtype, seed=seed, zero=zero)
    return HybridTableState(head=head, head_slots=head_slots, tail=tail), dropped


def push_hybrid_packed_bucketed(mesh: Mesh, hs: HybridTableState,
                                rows: jax.Array, grads: jax.Array,
                                access: AccessMethod, lr,
                                slack: float = 2.0,
                                comm_dtype: str = "float32", seed=None,
                                zero: bool = False):
    cut = hs.head.shape[0]
    t_ids = tail_ids(rows, cut, hs.tail.capacity)
    tail, dropped = push_collective_packed_bucketed(
        mesh, hs.tail, t_ids, grads, access, lr, slack=slack,
        comm_dtype=comm_dtype, seed=seed)
    head, head_slots = head_push(
        mesh, hs.head, hs.head_slots, rows, grads, access, lr,
        layout="packed", comm_dtype=comm_dtype, seed=seed, zero=zero)
    return HybridTableState(head=head, head_slots=head_slots, tail=tail), dropped


# -------------------------------------------------------- small-row plane ---


def pull_hybrid_packed_small(mesh: Mesh, hs: HybridTableState,
                             rows: jax.Array, dim: int,
                             comm_dtype: str = "float32") -> jax.Array:
    from swiftsnails_tpu.parallel.store import small_group

    g = small_group(dim)
    cut = hs.head.shape[0] * g
    sentinel = hs.tail.table.shape[0] * g
    head_vals = head_pull(mesh, hs.head, rows, layout="small", dim=dim, group=g)
    t_ids = tail_ids(rows, cut, sentinel)
    tail_vals = pull_collective_packed_small(
        mesh, hs.tail, t_ids, dim, comm_dtype=comm_dtype)
    return head_vals + tail_vals


def push_hybrid_packed_small(mesh: Mesh, hs: HybridTableState,
                             rows: jax.Array, grads: jax.Array,
                             access: AccessMethod, lr, dim: int,
                             comm_dtype: str = "float32", seed=None,
                             zero: bool = False):
    from swiftsnails_tpu.parallel.store import small_group

    g = small_group(dim)
    cut = hs.head.shape[0] * g
    sentinel = hs.tail.table.shape[0] * g
    t_ids = tail_ids(rows, cut, sentinel)
    tail = push_collective_packed_small(
        mesh, hs.tail, t_ids, grads, access, lr, dim,
        comm_dtype=comm_dtype, seed=seed)
    head, head_slots = head_push(
        mesh, hs.head, hs.head_slots, rows, grads, access, lr,
        layout="small", dim=dim, group=g, comm_dtype=comm_dtype, seed=seed,
        zero=zero)
    return HybridTableState(head=head, head_slots=head_slots, tail=tail)
