"""Device mesh construction.

The reference's cluster topology is a peer table of master/server/worker
processes (``src/core/system/ServerWorkerRoute.h:14-84``). On TPU the roles
dissolve into one SPMD mesh with named axes:

* ``data``  — batch parallelism (the reference's M workers);
* ``model`` — parameter-table row sharding (the reference's N servers /
  ``frag_num`` hash fragments, ``src/core/parameter/hashfrag.h:30-53``).

A ``seq`` axis slot is reserved for sequence/context parallelism (ring
attention; module planned as ``swiftsnails_tpu.parallel.sequence``).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def make_mesh(
    shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh over ``devices``.

    ``shape`` maps axis name -> size; at most one axis may be ``-1`` (inferred
    so the product covers every device). Default: all devices on the ``data``
    axis with a trivial ``model`` axis — the safe single-chip / pure-DP layout.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if shape is None:
        shape = {DATA_AXIS: n, MODEL_AXIS: 1}
    names = list(shape.keys())
    sizes = list(shape.values())
    if sizes.count(-1) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {shape}")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if known == 0 or n % known != 0:
            raise ValueError(f"cannot infer -1 axis: {n} devices, shape {shape}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh shape {dict(zip(names, sizes))} does not cover {n} devices")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def table_sharding(mesh: Mesh, axis: str = MODEL_AXIS) -> NamedSharding:
    """Row-sharding spec for a parameter table: shard dim 0 over ``axis``.

    This is the TPU equivalent of the reference's hash fragmentation across
    servers (``hashfrag.h:30-46``): contiguous row ranges per device instead
    of a frag->server map.
    """
    return NamedSharding(mesh, P(axis, None))


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Batch sharding: leading dim over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
