"""Pluggable parameter access methods (update rules).

Capability parity with the reference's ``PullAccessMethod`` /
``PushAccessMethod`` interfaces (``src/core/parameter/sparse_access_method.h:10-48``):

* ``init_param``        -> :meth:`AccessMethod.init_param` (but eager: the whole
  hashed table is initialized at creation instead of lazily per key,
  replacing the dense_hash_map find-or-insert of ``sparsetable.h:142-149``);
* ``get_pull_value``    -> :meth:`AccessMethod.get_pull_value`;
* ``merge_push_value``  -> additive merge, performed batch-wide by
  :func:`swiftsnails_tpu.parallel.store.merge_duplicate_rows` (segment-sum);
* ``apply_push_value``  -> :meth:`AccessMethod.apply_push_value`, vectorized
  over the batch's unique rows instead of per-key virtual calls.

Optimizer state ("slots", e.g. the AdaGrad accumulator) lives row-aligned with
the table so it shards identically (SURVEY §2.5: "AdaGrad accumulator lives
alongside params in the sharded pytree").
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Slots = Dict[str, jax.Array]


class AccessMethod:
    """Base update rule. Subclass and override; all methods are jit-safe."""

    def init_param(
        self, rng: jax.Array, shape: Tuple[int, ...], dtype,
        fan_in: Optional[int] = None,
    ) -> jax.Array:
        """Initial parameter values.

        Default matches the reference's ``Vec::randInit``: U(-0.5, 0.5)/dim
        (``src/utils/vec1.h:223-226``) — the classic word2vec embedding init.
        ``fan_in`` overrides the scaling dim when the storage row is wider
        than the logical row (packed ``[C, S, 128]`` layouts pad the last
        axis; scaling by the padded width would shrink the init by up to
        128/dim and visibly slow early training).
        """
        dim = fan_in or (shape[-1] if len(shape) > 1 else 1)
        return jax.random.uniform(rng, shape, dtype=dtype, minval=-0.5, maxval=0.5) / dim

    def init_slots(self, shape: Tuple[int, ...], dtype) -> Slots:
        """Zero-initialized optimizer slot arrays, row-aligned with the table."""
        return {}

    def get_pull_value(self, param: jax.Array) -> jax.Array:
        """Transform stored param -> pulled value (identity by default)."""
        return param

    def apply_push_value(
        self, param: jax.Array, slots: Slots, grad: jax.Array, lr: jax.Array
    ) -> Tuple[jax.Array, Slots]:
        """Apply merged gradients to a batch of rows. Must be pure.

        ``grad`` follows the reference's push convention: it is the value to
        *subtract* scaled by ``lr`` for plain SGD (workers push raw gradients;
        the server's access method owns the update rule,
        ``server/init.h:115-135``).
        """
        raise NotImplementedError

    def scatter_update(
        self, table: jax.Array, slots: Slots, rows: jax.Array, grads: jax.Array, lr
    ) -> Optional[Tuple[jax.Array, Slots]]:
        """Sort-free duplicate-safe update, or None if only the exact
        merge-then-apply path is valid.

        The exact path (``merge_duplicate_rows`` + ``apply_push_value``)
        argsorts the batch's rows every push — expensive on TPU. Linear rules
        (SGD) are scatter-add-exact; AdaGrad uses the per-sample-accumulator
        variant (``accum += Σ g_i²`` instead of ``(Σ g_i)²`` for duplicate
        keys — standard in hogwild implementations, including effectively the
        reference's own async workers racing on the same key across pushes).
        Rows may contain out-of-range padding; all scatters use mode='drop'.
        """
        return None


class SgdAccess(AccessMethod):
    """Plain SGD: ``param -= lr * grad``."""

    def apply_push_value(self, param, slots, grad, lr):
        return param - lr * grad.astype(param.dtype), slots

    def scatter_update(self, table, slots, rows, grads, lr):
        # scatter-add sums duplicate rows natively — identical math, no sort
        table = table.at[rows].add(-(lr * grads).astype(table.dtype), mode="drop")
        return table, slots


class AdaGradAccess(AccessMethod):
    """AdaGrad: ``accum += grad**2; param -= lr * grad / sqrt(accum + eps)``.

    The Wide&Deep / CTR update rule from BASELINE.json. ``accum`` doubles
    table memory; ``slot_dtype`` allows bf16 compression for 1B-row configs.
    """

    def __init__(self, eps: float = 1e-8, slot_dtype=None):
        self.eps = eps
        self.slot_dtype = slot_dtype

    def init_slots(self, shape, dtype):
        return {"accum": jnp.zeros(shape, dtype=self.slot_dtype or dtype)}

    def apply_push_value(self, param, slots, grad, lr):
        g = grad.astype(jnp.float32)
        accum = slots["accum"].astype(jnp.float32) + g * g
        step = lr * g * jax.lax.rsqrt(accum + self.eps)
        new_param = param - step.astype(param.dtype)
        return new_param, {"accum": accum.astype(slots["accum"].dtype)}

    def scatter_update(self, table, slots, rows, grads, lr):
        # two-phase: (1) scatter-add per-sample g² into the accumulator,
        # (2) gather the post-update accumulator (duplicates all see the
        # final value — deterministic), scale, scatter-add the steps.
        g = grads.astype(jnp.float32)
        accum = slots["accum"].at[rows].add(
            (g * g).astype(slots["accum"].dtype), mode="drop"
        )
        acc_rows = accum.at[rows].get(mode="fill", fill_value=1.0).astype(jnp.float32)
        step = lr * g * jax.lax.rsqrt(acc_rows + self.eps)
        table = table.at[rows].add(-step.astype(table.dtype), mode="drop")
        return table, {"accum": accum}
