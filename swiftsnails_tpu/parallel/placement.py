"""Placement policy: pick the head/tail cut and manage the hybrid split.

The auto-partitioner (``placement: auto``) follows Parallax: the decision
input is the vocabulary's frequency CDF (``data/vocab.py`` cumulative
coverage — vocab ids are frequency ranks, so a prefix cut IS the zipf
head) plus a wire-cost model calibrated against the ``ssn_*`` comm-audit
measured bytes of the uniform layout. For each aligned candidate cut ``k``
it predicts the per-step exchange bytes of a hybrid split at ``k`` and
takes the argmin — ``k = 0`` (stay uniform) always competes, so flat
distributions resolve to uniform automatically.

Cost model (per train substep, per data shard; see docs/SCALING.md):

* uniform — pull assembles + push gathers roughly the full local batch of
  row payloads: ``U ≈ 2 · local_slots · row_bytes``. When a measured
  uniform byte count is available (``placement_calib_bytes``, or the bench
  calibration pass) the model rescales so ``U`` matches it.
* hybrid(k) — the tail rides the dedup twins at a static unique capacity
  ``tail_cap(k) = align8(slack · (1 − cov(k)) · local_slots)``, so tail
  bytes shrink by ``tail_cap / local_slots``; the head adds one dense
  reduce of ``k`` rows (psum for f32; quantized all_gather, so ×data
  received copies, for bf16/int8).

``PlacementManager`` mirrors the TierManager surface (adopt /
master_state / summary) over the same ``tier_tables`` / ``tier_with_tables``
trainer hooks, so TrainLoop, checkpointing, and resume integrate the same
way the tiered store does.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

log = logging.getLogger(__name__)

PLACEMENT_MODES = ("uniform", "hybrid", "auto")


def resolve_placement(name: Optional[str]) -> str:
    name = (name or "uniform").lower()
    if name not in PLACEMENT_MODES:
        raise ValueError(
            f"unknown placement {name!r}; expected one of {PLACEMENT_MODES}")
    return name


def align_down(k: int, align: int) -> int:
    return (int(k) // max(align, 1)) * max(align, 1)


def cap8(n: float, lo: int = 8) -> int:
    """Round a slot-count estimate up to a multiple of 8 (lane-friendly)."""
    return max(-(-int(np.ceil(n)) // 8) * 8, lo)


def tail_cap(local_slots: int, coverage: float, slack: float = 2.0) -> int:
    """Static unique capacity for the hybrid tail's dedup twins."""
    want = slack * max(1.0 - float(coverage), 0.0) * max(local_slots, 1)
    return min(cap8(want), cap8(local_slots, lo=8))


def row_wire_bytes(row_elems: int, comm_dtype: str) -> float:
    """Approximate wire bytes for one row payload at a comm dtype."""
    from swiftsnails_tpu.parallel.comm import int4_block, is_int4

    if comm_dtype == "bfloat16":
        return 2.0 * row_elems
    if comm_dtype == "int8":
        return 1.0 * row_elems + 4.0  # per-row f32 scale rides alongside
    if is_int4(comm_dtype):
        # packed nibbles (padded to a whole block) + one bf16 scale per block
        blk = int4_block(comm_dtype)
        nblocks = max(-(-int(row_elems) // blk), 1)
        return 0.5 * nblocks * blk + 2.0 * nblocks
    return 4.0 * row_elems


def candidate_cuts(capacity: int, align: int, vocab_rows: int,
                   max_head_frac: float = 0.5):
    """Aligned candidate cuts: 0 (uniform) plus a pow2 ladder of ``align``."""
    limit = int(capacity * max_head_frac)
    cuts = [0]
    k = max(align, 1)
    while k <= limit:
        cuts.append(k)
        k *= 2
    tip = align_down(min(vocab_rows, limit), align)
    if tip and tip not in cuts:
        cuts.append(tip)
    return sorted(set(cuts))


def choose_cut(
    counts: np.ndarray,
    capacity: int,
    *,
    align: int,
    local_slots: int,
    row_elems: int,
    data: int = 1,
    slack: float = 2.0,
    comm_dtype: str = "float32",
    measured_uniform_bytes: Optional[float] = None,
    max_head_frac: float = 0.5,
) -> Dict:
    """Pick the head/tail cut from the frequency CDF + calibrated cost model.

    ``counts`` must be frequency-rank ordered (descending), as
    ``Vocab.from_counter`` builds them — row id == rank, so the coverage of
    a prefix cut is the CDF at that rank. Returns the decision dict that
    lands in the bench JSON / run record / ledger."""
    counts = np.asarray(counts, dtype=np.float64)
    total = float(counts.sum()) or 1.0
    cdf = np.concatenate([[0.0], np.cumsum(counts) / total])

    def cov(k: int) -> float:
        return float(cdf[min(k, len(counts))])

    rb = row_wire_bytes(row_elems, comm_dtype)
    uniform_pred = 2.0 * max(local_slots, 1) * rb
    scale = 1.0
    if measured_uniform_bytes:
        scale = float(measured_uniform_bytes) / uniform_pred
    head_copies = 1 if comm_dtype == "float32" else max(data, 1)

    best_k, best_cost = 0, uniform_pred * scale
    for k in candidate_cuts(capacity, align, len(counts), max_head_frac):
        if k == 0:
            continue
        t_cap = tail_cap(local_slots, cov(k), slack)
        tail_bytes = uniform_pred * scale * (t_cap / max(local_slots, 1))
        head_bytes = k * rb * head_copies
        cost = tail_bytes + head_bytes
        if cost < best_cost:
            best_k, best_cost = k, cost
    return {
        "cut": int(best_k),
        "coverage": cov(best_k),
        "predicted_exchange_bytes": float(best_cost),
        "predicted_uniform_bytes": float(uniform_pred * scale),
        "measured_uniform_bytes": (
            float(measured_uniform_bytes) if measured_uniform_bytes else None),
    }


class PlacementManager:
    """Hybrid split lifecycle over the trainer's tier-table hooks.

    ``adopt`` splits a uniform-layout state into head/tail planes after
    init/restore; ``master_state`` merges back to the uniform layout (the
    only layout checkpoints, serving, and the tiered store ever see). Both
    are eager value-preserving reshapes — see parallel/hybrid.py."""

    def __init__(self, trainer, mesh=None):
        self.trainer = trainer
        self.mesh = mesh if mesh is not None else getattr(trainer, "mesh", None)
        self.spec = trainer.placement_spec() or {}

    @property
    def active(self) -> bool:
        return any(sp.get("cut", 0) > 0 for sp in self.spec.values())

    def adopt(self, state):
        from swiftsnails_tpu.parallel.hybrid import is_hybrid, split_table

        if not self.active:
            return state
        tables = self.trainer.tier_tables(state)
        new = {}
        for name, sp in self.spec.items():
            cut = sp.get("cut", 0)
            ts = tables.get(name)
            if ts is None or cut <= 0 or is_hybrid(ts):
                continue
            new[name] = split_table(ts, cut, self.mesh, sp.get("group", 1))
        if new:
            log.info("placement: adopted hybrid split for %s",
                     {k: self.spec[k]["cut"] for k in new})
            state = self.trainer.tier_with_tables(state, new)
        return state

    def master_state(self, state):
        from swiftsnails_tpu.parallel.hybrid import is_hybrid, merge_table

        tables = self.trainer.tier_tables(state)
        new = {name: merge_table(ts, self.mesh)
               for name, ts in tables.items() if is_hybrid(ts)}
        if new:
            state = self.trainer.tier_with_tables(state, new)
        return state

    def summary(self) -> Dict:
        return dict(getattr(self.trainer, "placement_decision", None) or {})
