"""ZeRO-style optimizer-state sharding across the data axis.

``optimizer_sharding: zero`` implements the weight-update sharding of
*Automatic Cross-Replica Sharding of Weight Update Computation*
(arXiv 2004.13336) for every plane this system replicates across the data
axis today:

* the dense-model optimizer state (the optax AdaGrad ``sum_of_squares``
  pytree in the CTR trainers) — pure redundancy: every data shard holds
  the same accumulators and applies the same update;
* the hybrid head's optimizer-slot planes (``HybridTableState.head_slots``,
  the dense AdaGrad ``accum`` prefix) — same redundancy, same fix.

The mechanism is placement, not layout: a sharded plane keeps its logical
shape and is ``jax.device_put`` to ``P("data")`` so each replica holds a
``1/data`` leading-axis slice resident in HBM. The update is then applied
shard-local — the hybrid head reduce-scatters the summed gradient
(:func:`~swiftsnails_tpu.parallel.comm.reduce_scatter_quantized`), updates
its owned slice, and all-gathers only the param slice back; the dense
update is steered by ``with_sharding_constraint`` so GSPMD partitions the
elementwise optimizer math instead of replicating it. Because logical
values are unchanged and ``np.asarray`` on a sharded array materializes
the full plane, checkpoints stay byte-identical to the unsharded format
(:class:`ZeroManager.master_state` additionally commits planes back to
replicated placement before a manifest is built, mirroring
``PlacementManager.master_state``).

``ZeroManager`` mirrors the PlacementManager surface (active / adopt /
master_state / summary) so TrainLoop, checkpointing, and resume integrate
the same way the hybrid split does.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

log = logging.getLogger(__name__)

OPTIMIZER_SHARDING_MODES = ("none", "zero")


def resolve_optimizer_sharding(name: Optional[str]) -> str:
    name = (name or "none").lower()
    if name not in OPTIMIZER_SHARDING_MODES:
        raise ValueError(
            f"unknown optimizer_sharding {name!r}; expected one of "
            f"{OPTIMIZER_SHARDING_MODES}")
    return name


def zero_plane_spec(arr, data: int):
    """PartitionSpec for one optimizer-plane leaf, or None to leave it.

    A leaf is shardable when its leading axis splits evenly across the
    ``data`` axis; scalars (optax step counts) and ragged planes stay
    replicated. The same predicate steers both the resident placement
    (``adopt``) and the in-jit ``with_sharding_constraint`` so they can
    never disagree.
    """
    from jax.sharding import PartitionSpec as P

    shape = getattr(arr, "shape", None)
    if not shape or len(shape) < 1:
        return None
    if shape[0] < data or shape[0] % data:
        return None
    return P("data")


def _leaf_nbytes(leaf) -> int:
    shape = getattr(leaf, "shape", ())
    dt = np.dtype(getattr(leaf, "dtype", np.float32))
    n = 1
    for d in shape:
        n *= int(d)
    return n * dt.itemsize


class ZeroManager:
    """ZeRO plane lifecycle over the trainer's zero/tier hooks.

    ``adopt`` reshards the trainer-declared optimizer planes (and any
    hybrid head slot planes) from replicated to ``P("data")`` after
    init/restore/placement-adopt; ``master_state`` commits them back to
    replicated placement (the only placement checkpoint manifests and
    end-of-run consumers ever see). Both are value-preserving device_puts.
    """

    def __init__(self, trainer, mesh=None):
        self.trainer = trainer
        self.mesh = mesh if mesh is not None else getattr(trainer, "mesh", None)
        self.mode = resolve_optimizer_sharding(
            getattr(trainer, "optimizer_sharding", "none"))
        self.decision: Dict = {}

    @property
    def data(self) -> int:
        from swiftsnails_tpu.parallel.mesh import DATA_AXIS

        return int(self.mesh.shape[DATA_AXIS]) if self.mesh is not None else 1

    @property
    def active(self) -> bool:
        return self.mode == "zero" and self.mesh is not None

    # ---------------------------------------------------------------- adopt

    def _put(self, leaf, spec):
        import jax
        from jax.sharding import NamedSharding

        return jax.device_put(leaf, NamedSharding(self.mesh, spec))

    def adopt(self, state):
        """Reshard every eligible replicated plane to ``P("data")``."""
        import jax
        from jax.sharding import PartitionSpec as P

        if not self.active:
            return state
        data = self.data
        sharded = replicated = 0
        planes = 0

        def reshard(leaf):
            nonlocal sharded, replicated, planes
            spec = zero_plane_spec(leaf, data)
            nb = _leaf_nbytes(leaf)
            if spec is None:
                return leaf
            planes += 1
            replicated += nb
            sharded += nb // data
            return self._put(leaf, spec)

        opt = self.trainer.zero_planes(state)
        if opt is not None:
            state = self.trainer.zero_with_planes(
                state, jax.tree_util.tree_map(reshard, opt))

        from swiftsnails_tpu.parallel.hybrid import is_hybrid

        tables = self.trainer.tier_tables(state)
        new = {}
        for name, ts in tables.items():
            if not is_hybrid(ts) or not ts.head_slots:
                continue
            slots = {k: reshard(v) for k, v in ts.head_slots.items()}
            new[name] = ts._replace(head_slots=slots)
        if new:
            state = self.trainer.tier_with_tables(state, new)

        self.decision = {
            "mode": self.mode,
            "devices": data,
            "planes": planes,
            "replicated_bytes": int(replicated),
            "sharded_bytes_per_replica": int(sharded),
            "reduction": (float(replicated) / float(sharded)
                          if sharded else 1.0),
        }
        if planes:
            log.info(
                "zero: sharded %d optimizer plane(s) across data=%d "
                "(%d -> %d bytes/replica)", planes, data, replicated, sharded)
        return state

    # --------------------------------------------------------- master_state

    def master_state(self, state):
        """Commit planes back to replicated placement (merge-before-manifest).

        Values are unchanged (sharding is placement, not layout) — this
        step pins the *placement* contract: whatever consumes the master
        state (manifest build, serving export, the end-of-run eval) sees
        exactly the unsharded resident layout it would have seen without
        ``optimizer_sharding``, mirroring ``PlacementManager.master_state``.
        """
        import jax
        from jax.sharding import PartitionSpec as P

        if not self.active:
            return state

        def unshard(leaf):
            if zero_plane_spec(leaf, self.data) is None:
                return leaf
            return self._put(leaf, P())

        opt = self.trainer.zero_planes(state)
        if opt is not None:
            state = self.trainer.zero_with_planes(
                state, jax.tree_util.tree_map(unshard, opt))

        from swiftsnails_tpu.parallel.hybrid import is_hybrid

        tables = self.trainer.tier_tables(state)
        new = {}
        for name, ts in tables.items():
            if not is_hybrid(ts) or not ts.head_slots:
                continue
            new[name] = ts._replace(
                head_slots={k: unshard(v) for k, v in ts.head_slots.items()})
        if new:
            state = self.trainer.tier_with_tables(state, new)
        return state

    def summary(self) -> Dict:
        return dict(self.decision)
