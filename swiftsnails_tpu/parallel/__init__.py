from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh, table_sharding
from swiftsnails_tpu.parallel.access import AccessMethod, SgdAccess, AdaGradAccess
from swiftsnails_tpu.parallel.comm import COMM_DTYPES, resolve_comm_dtype
from swiftsnails_tpu.parallel.store import (
    TableState,
    create_table,
    merge_duplicate_rows,
    pull,
    push,
)

__all__ = [
    "COMM_DTYPES",
    "resolve_comm_dtype",
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "table_sharding",
    "AccessMethod",
    "SgdAccess",
    "AdaGradAccess",
    "TableState",
    "create_table",
    "merge_duplicate_rows",
    "pull",
    "push",
]
