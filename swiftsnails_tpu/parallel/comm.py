"""Quantized collective payloads — the ``comm_dtype`` codec.

The reference's wire format was already narrower than its math: the push
message serialized ``(key, grad)`` pairs per server with no requirement that
the grad bytes match the table's storage precision (survey §2.3). Here the
same idea applies to the ICI payload of every pull/push collective in
:mod:`swiftsnails_tpu.parallel.transfer`: quantize just before the
``all_gather`` / ``psum``, dequantize into f32 accumulation at the owner
shard, master table untouched. EQuARX (arXiv 2506.17615) measures this
recovering most of the interconnect-bandwidth cost of scale-out collectives
at negligible quality loss.

Three wire formats (config key ``comm_dtype``):

* ``float32`` (default) — no codec; the collectives are **bit-identical** to
  a build without this module (the transfer functions never call in here).
* ``bfloat16`` — payload cast; ~2x byte cut, exponent range preserved. The
  payload moves as **bitcast uint16**: backends whose float-normalization
  pass would silently promote a bf16 collective back to f32 (CPU does —
  the ``convert_convert_fusion`` pattern re-widens the wire format and
  erases the byte cut) leave integer collectives alone, and for the
  owner-exclusive psum the integer add of one nonzero contribution plus
  zeros is exact — bit-for-bit the bf16 value, with no second rounding.
* ``int8``   — per-row symmetric scale (``amax/127`` over the trailing
  axes); ~3.5x byte cut (the f32 scale vector rides alongside, 1 scalar per
  row). Gradients are **stochastically rounded** so the quantizer is
  unbiased: ``E[dequant(quant(g))] = g`` — plain round-to-nearest would bias
  small persistent gradient components to zero across steps.

Two collective patterns are wrapped, matching the two protocols:

* :func:`psum_quantized` — the pull protocol's assemble-rows reduction. Each
  row position is nonzero on exactly ONE shard (the owner; everyone else
  contributes zeros), so quantizing per shard and reducing payload + scale
  separately is exact: the zero rows carry zero scale, and the sum passes
  the owner's ``(q, scale)`` through untouched. int8 sums cannot overflow
  (one nonzero contribution per position).
* :func:`all_gather_quantized` — the push protocol's batch movement. The
  gather is lossless w.r.t. its operand, so the only error is the one
  quantization step on the sender.

Stochastic rounding uses a counter-based integer hash (no PRNG key plumbing
through ``shard_map``): a ``uint32`` seed operand is combined with the
element index and the data shard's ``axis_index``, avalanched, and mapped to
a uniform in ``[0, 1)``. Deterministic given (seed, position, shard) — the
same trace replays identically — while unbiased over positions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

COMM_DTYPES = ("float32", "bfloat16", "int8")

_GOLDEN = np.uint32(0x9E3779B9)  # Weyl increment for the seed stream


def resolve_comm_dtype(name: Optional[str]) -> str:
    """Validate / canonicalize a ``comm_dtype`` config value."""
    if not name:
        return "float32"
    canon = {"float32": "float32", "f32": "float32",
             "bfloat16": "bfloat16", "bf16": "bfloat16",
             "int8": "int8", "s8": "int8"}.get(str(name).strip().lower())
    if canon is None:
        raise ValueError(
            f"comm_dtype must be one of {COMM_DTYPES}, got {name!r}")
    return canon


def seed_from_key(key) -> Optional[jax.Array]:
    """uint32 stochastic-rounding seed from a jax PRNG key (``None`` -> None).

    Works for both raw ``uint32[2]`` keys and new-style typed keys; only the
    low word is used (the fold_in stream already decorrelates steps).
    """
    if key is None:
        return None
    try:
        data = jax.random.key_data(key)
    except (AttributeError, TypeError):
        data = jnp.asarray(key)
    return data.reshape(-1)[-1].astype(jnp.uint32)


def _hash_uniform(shape, seed) -> jax.Array:
    """Deterministic uniform[0,1) noise from (element index, seed).

    lowbias32-style avalanche over a position iota — cheap, vectorized, and
    trace-friendly (no key threading); quality is far beyond what dithered
    rounding needs.
    """
    n = int(np.prod(shape)) if shape else 1
    x = lax.iota(jnp.uint32, max(n, 1))
    x = x * jnp.uint32(2654435761) + seed.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = x.astype(jnp.float32) * jnp.float32(1.0 / 4294967296.0)
    return u[:n].reshape(shape)


def _salted(seed, axis_name: Optional[str]) -> jax.Array:
    """Mix the data-shard index into the seed so shards draw distinct noise.
    Must be called inside ``shard_map`` when ``axis_name`` is given."""
    s = jnp.uint32(0) if seed is None else jnp.asarray(seed, jnp.uint32)
    if axis_name is not None:
        s = s + lax.axis_index(axis_name).astype(jnp.uint32) * _GOLDEN
    return s


def _bf16_wire(x: jax.Array) -> jax.Array:
    """bf16 payload as bitcast uint16 (collective-safe on every backend)."""
    return lax.bitcast_convert_type(x.astype(jnp.bfloat16), jnp.uint16)


def _bf16_unwire(w: jax.Array, dtype) -> jax.Array:
    return lax.bitcast_convert_type(w, jnp.bfloat16).astype(dtype)


def quantize_int8(
    x: jax.Array, stochastic: bool = False, seed=None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8: returns ``(q [x.shape] int8, scale [N] f32)``.

    Row = leading axis; scale is ``amax/127`` over the trailing axes and 0
    for all-zero rows (so zero contributions stay exactly zero through a
    reduction — the owner-exclusive psum relies on this).
    """
    red = tuple(range(1, x.ndim))
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=red) if red else jnp.abs(xf)
    scale = (amax * jnp.float32(1.0 / 127.0)).astype(jnp.float32)
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    y = xf * inv.reshape((-1,) + (1,) * (x.ndim - 1))
    if stochastic:
        y = jnp.floor(y + _hash_uniform(y.shape, jnp.uint32(0) if seed is None
                                        else seed))
    else:
        y = jnp.round(y)
    q = jnp.clip(y, -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jax.Array, scale: jax.Array) -> jax.Array:
    """int8 payload + per-row scale -> f32 (the owner-side accumulation
    dtype; callers cast to the table dtype if they need to)."""
    return q.astype(jnp.float32) * scale.reshape(
        (-1,) + (1,) * (q.ndim - 1)).astype(jnp.float32)


def psum_quantized(vals: jax.Array, axis_name: str, comm_dtype: str) -> jax.Array:
    """Pull-protocol reduction with a compressed payload.

    ``vals`` must be owner-exclusive: each leading-axis position is nonzero
    on at most one shard of ``axis_name`` (the collective planes mask
    non-owned rows to zero before reducing). f32 passes straight through to
    ``lax.psum`` — bit-identical to the pre-codec path.
    """
    if comm_dtype == "float32":
        return lax.psum(vals, axis_name)
    if comm_dtype == "bfloat16":
        # owner-exclusive: the u16 integer sum of one nonzero contribution
        # plus zero words IS the owner's bf16 bit pattern (0.0 bitcasts to
        # 0x0000), so the bitcast wire format loses nothing beyond the one
        # f32->bf16 rounding
        out = lax.psum(_bf16_wire(vals), axis_name)
        return _bf16_unwire(out, vals.dtype)
    # int8: owner-exclusive rows -> the sum of (q, scale) pairs IS the
    # owner's pair (zeros elsewhere carry zero scale); no overflow possible
    q, scale = quantize_int8(vals)
    q_sum = lax.psum(q.astype(jnp.int8), axis_name)
    s_sum = lax.psum(scale, axis_name)
    return dequantize_int8(q_sum, s_sum).astype(vals.dtype)


def reduce_sum_quantized(
    x: jax.Array,
    axis_name: str,
    comm_dtype: str,
    axis_size: int,
    stochastic: bool = False,
    seed=None,
) -> jax.Array:
    """Dense gradient all-reduce with a compressed payload (NOT
    owner-exclusive: every shard contributes to every position, so the
    psum_quantized trick of summing (q, scale) pairs would be wrong).

    f32 is a plain ``lax.psum``. bf16/int8 quantize per shard, move the
    compressed payload with a tiled all_gather, and accumulate in f32 at
    the receiver — the wire stays narrow, the sum stays full precision.
    ``axis_size`` must be the static size of ``axis_name`` (it shapes the
    de-tiling reshape). Used by the hybrid head push
    (parallel/hybrid.py), where all data shards hold gradients for the
    same replicated rows."""
    if comm_dtype == "float32":
        return lax.psum(x, axis_name)
    g = all_gather_quantized(x, axis_name, comm_dtype,
                             stochastic=stochastic, seed=seed)
    return g.reshape((axis_size,) + x.shape).astype(jnp.float32).sum(axis=0)


def all_gather_quantized(
    x: jax.Array,
    axis_name: str,
    comm_dtype: str,
    stochastic: bool = False,
    seed=None,
) -> jax.Array:
    """Push-protocol movement with a compressed payload (tiled all_gather).

    ``stochastic=True`` dithers the int8 rounding (gradients); ``seed`` is a
    replicated uint32 scalar — it is salted with this shard's data-axis
    index so shards draw independent noise.
    """
    if comm_dtype == "float32":
        return lax.all_gather(x, axis_name, tiled=True)
    if comm_dtype == "bfloat16":
        out = lax.all_gather(_bf16_wire(x), axis_name, tiled=True)
        return _bf16_unwire(
            out, jnp.float32 if x.dtype == jnp.float32 else x.dtype)
    q, scale = quantize_int8(
        x, stochastic=stochastic,
        seed=_salted(seed, axis_name) if stochastic else None,
    )
    q_all = lax.all_gather(q, axis_name, tiled=True)
    s_all = lax.all_gather(scale, axis_name, tiled=True)
    return dequantize_int8(q_all, s_all).astype(
        jnp.float32 if x.dtype == jnp.float32 else x.dtype)
