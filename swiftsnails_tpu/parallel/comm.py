"""Quantized collective payloads — the ``comm_dtype`` codec.

The reference's wire format was already narrower than its math: the push
message serialized ``(key, grad)`` pairs per server with no requirement that
the grad bytes match the table's storage precision (survey §2.3). Here the
same idea applies to the ICI payload of every pull/push collective in
:mod:`swiftsnails_tpu.parallel.transfer`: quantize just before the
``all_gather`` / ``psum``, dequantize into f32 accumulation at the owner
shard, master table untouched. EQuARX (arXiv 2506.17615) measures this
recovering most of the interconnect-bandwidth cost of scale-out collectives
at negligible quality loss.

Three wire formats (config key ``comm_dtype``):

* ``float32`` (default) — no codec; the collectives are **bit-identical** to
  a build without this module (the transfer functions never call in here).
* ``bfloat16`` — payload cast; ~2x byte cut, exponent range preserved. The
  payload moves as **bitcast uint16**: backends whose float-normalization
  pass would silently promote a bf16 collective back to f32 (CPU does —
  the ``convert_convert_fusion`` pattern re-widens the wire format and
  erases the byte cut) leave integer collectives alone, and for the
  owner-exclusive psum the integer add of one nonzero contribution plus
  zeros is exact — bit-for-bit the bf16 value, with no second rounding.
* ``int8``   — per-row symmetric scale (``amax/127`` over the trailing
  axes); ~3.5x byte cut (the f32 scale vector rides alongside, 1 scalar per
  row). Gradients are **stochastically rounded** so the quantizer is
  unbiased: ``E[dequant(quant(g))] = g`` — plain round-to-nearest would bias
  small persistent gradient components to zero across steps.
* ``int4``   — block-wise symmetric 4-bit codes, two per uint8. The row's
  trailing axes are flattened and cut into fixed blocks (default 32 lanes;
  ``int4/N`` picks another even block size), each with its own ``amax/7``
  scale so one outlier only poisons its block, not the row. Codes are
  two's-complement nibbles in ``[-7, 7]`` packed low-first; scales ride as
  **bitcast-uint16 bf16** (f32 scales would double the sideband and drag
  the byte cut below the 6x gate at small dims). ~7x byte cut at dim 128.
  Same hash-dithered stochastic rounding as int8 on the gradient path.

Two collective patterns are wrapped, matching the two protocols:

* :func:`psum_quantized` — the pull protocol's assemble-rows reduction. Each
  row position is nonzero on exactly ONE shard (the owner; everyone else
  contributes zeros), so quantizing per shard and reducing payload + scale
  separately is exact: the zero rows carry zero scale, and the sum passes
  the owner's ``(q, scale)`` through untouched. int8 sums cannot overflow
  (one nonzero contribution per position).
* :func:`all_gather_quantized` — the push protocol's batch movement. The
  gather is lossless w.r.t. its operand, so the only error is the one
  quantization step on the sender.

Stochastic rounding uses a counter-based integer hash (no PRNG key plumbing
through ``shard_map``): a ``uint32`` seed operand is combined with the
element index and the data shard's ``axis_index``, avalanched, and mapped to
a uniform in ``[0, 1)``. Deterministic given (seed, position, shard) — the
same trace replays identically — while unbiased over positions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

COMM_DTYPES = ("float32", "bfloat16", "int8", "int4")

INT4_BLOCK = 32  # default int4 scale-block width (lanes per amax group)

_GOLDEN = np.uint32(0x9E3779B9)  # Weyl increment for the seed stream


def resolve_comm_dtype(name: Optional[str]) -> str:
    """Validate / canonicalize a ``comm_dtype`` config value.

    Canonical values are :data:`COMM_DTYPES`; ``int4`` additionally accepts a
    block-size spec ``int4/N`` (even N >= 2) for non-default scale blocks —
    ``int4/32`` normalizes back to plain ``int4``.
    """
    if not name:
        return "float32"
    s = str(name).strip().lower()
    canon = {"float32": "float32", "f32": "float32",
             "bfloat16": "bfloat16", "bf16": "bfloat16",
             "int8": "int8", "s8": "int8",
             "int4": "int4", "s4": "int4"}.get(s)
    if canon is not None:
        return canon
    if s.startswith("int4/") or s.startswith("s4/"):
        spec = s.split("/", 1)[1]
        try:
            blk = int(spec)
        except ValueError:
            raise ValueError(f"bad int4 block spec {name!r}: {spec!r} "
                             "is not an integer")
        if blk < 2 or blk % 2:
            raise ValueError(
                f"int4 block must be an even integer >= 2, got {blk}")
        return "int4" if blk == INT4_BLOCK else f"int4/{blk}"
    raise ValueError(
        f"comm_dtype must be one of {COMM_DTYPES} (int4 takes an optional "
        f"/block spec), got {name!r}")


def is_int4(comm_dtype: str) -> bool:
    """True for ``int4`` and any ``int4/N`` block spec."""
    return comm_dtype == "int4" or comm_dtype.startswith("int4/")


def int4_block(comm_dtype: str) -> int:
    """The scale-block width encoded in a canonical int4 comm_dtype."""
    if comm_dtype == "int4":
        return INT4_BLOCK
    if comm_dtype.startswith("int4/"):
        return int(comm_dtype.split("/", 1)[1])
    raise ValueError(f"not an int4 comm_dtype: {comm_dtype!r}")


def apply_int4_block(comm_dtype: str, block) -> str:
    """Rewrite a canonical int4 ``comm_dtype`` with an explicit block width
    (the ``comm_int4_block`` config key; 0/None keeps the spec as-is). A
    no-op for non-int4 wires so configs can set the key unconditionally."""
    if not block or not is_int4(comm_dtype):
        return comm_dtype
    return resolve_comm_dtype(f"int4/{int(block)}")


def stochastic_wire(comm_dtype: str) -> bool:
    """True when the wire format rounds to integer codes and therefore wants
    the dithered (stochastic) rounding path on gradients — int8 and int4.
    bf16 keeps the f32 exponent, so round-to-nearest is already unbiased
    enough; f32 has no codec at all."""
    return comm_dtype == "int8" or is_int4(comm_dtype)


def seed_from_key(key) -> Optional[jax.Array]:
    """uint32 stochastic-rounding seed from a jax PRNG key (``None`` -> None).

    Works for both raw ``uint32[2]`` keys and new-style typed keys; only the
    low word is used (the fold_in stream already decorrelates steps).
    """
    if key is None:
        return None
    try:
        data = jax.random.key_data(key)
    except (AttributeError, TypeError):
        data = jnp.asarray(key)
    return data.reshape(-1)[-1].astype(jnp.uint32)


def _hash_uniform(shape, seed) -> jax.Array:
    """Deterministic uniform[0,1) noise from (element index, seed).

    lowbias32-style avalanche over a position iota — cheap, vectorized, and
    trace-friendly (no key threading); quality is far beyond what dithered
    rounding needs.
    """
    n = int(np.prod(shape)) if shape else 1
    x = lax.iota(jnp.uint32, max(n, 1))
    x = x * jnp.uint32(2654435761) + seed.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = x.astype(jnp.float32) * jnp.float32(1.0 / 4294967296.0)
    return u[:n].reshape(shape)


def _salted(seed, axis_name: Optional[str]) -> jax.Array:
    """Mix the data-shard index into the seed so shards draw distinct noise.
    Must be called inside ``shard_map`` when ``axis_name`` is given."""
    s = jnp.uint32(0) if seed is None else jnp.asarray(seed, jnp.uint32)
    if axis_name is not None:
        s = s + lax.axis_index(axis_name).astype(jnp.uint32) * _GOLDEN
    return s


def _bf16_wire(x: jax.Array) -> jax.Array:
    """bf16 payload as bitcast uint16 (collective-safe on every backend)."""
    return lax.bitcast_convert_type(x.astype(jnp.bfloat16), jnp.uint16)


def _bf16_unwire(w: jax.Array, dtype) -> jax.Array:
    return lax.bitcast_convert_type(w, jnp.bfloat16).astype(dtype)


def quantize_int8(
    x: jax.Array, stochastic: bool = False, seed=None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8: returns ``(q [x.shape] int8, scale [N] f32)``.

    Row = leading axis; scale is ``amax/127`` over the trailing axes and 0
    for all-zero rows (so zero contributions stay exactly zero through a
    reduction — the owner-exclusive psum relies on this).
    """
    red = tuple(range(1, x.ndim))
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=red) if red else jnp.abs(xf)
    scale = (amax * jnp.float32(1.0 / 127.0)).astype(jnp.float32)
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    y = xf * inv.reshape((-1,) + (1,) * (x.ndim - 1))
    if stochastic:
        y = jnp.floor(y + _hash_uniform(y.shape, jnp.uint32(0) if seed is None
                                        else seed))
    else:
        y = jnp.round(y)
    q = jnp.clip(y, -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jax.Array, scale: jax.Array) -> jax.Array:
    """int8 payload + per-row scale -> f32 (the owner-side accumulation
    dtype; callers cast to the table dtype if they need to)."""
    return q.astype(jnp.float32) * scale.reshape(
        (-1,) + (1,) * (q.ndim - 1)).astype(jnp.float32)


def _int4_padded_cols(t: int, block: int) -> int:
    """Trailing-elem count padded up to a whole number of scale blocks."""
    nb = max(-(-t // block), 1)
    return nb * block


def quantize_int4(
    x: jax.Array, stochastic: bool = False, seed=None, block: int = INT4_BLOCK,
) -> Tuple[jax.Array, jax.Array]:
    """Block-wise symmetric int4: ``(packed [N, Tp/2] uint8, scales
    [N, Tp/block] uint16)`` where ``Tp`` is the flattened trailing size
    padded up to a whole number of ``block``-lane groups.

    Codes are two's-complement nibbles in ``[-7, 7]`` packed low-first
    (element ``2k`` in the low nibble of byte ``k``); scales are
    ``block_amax/7`` carried as bitcast-uint16 bf16, and the *rounded* scale
    is the one used for quantization so dequant error is bounded by half a
    step. All-zero blocks get zero scale and 0x00 codes — the
    owner-exclusive psum identity (zeros pass through an integer sum
    untouched) holds exactly as it does for int8.
    """
    n = x.shape[0] if x.ndim else 1
    t = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
    tp = _int4_padded_cols(t, block)
    xf = x.astype(jnp.float32).reshape(n, t)
    if tp != t:
        xf = jnp.pad(xf, ((0, 0), (0, tp - t)))
    xb = xf.reshape(n, tp // block, block)
    amax = jnp.max(jnp.abs(xb), axis=-1)
    # round the scale through the bf16 wire FIRST, then quantize against the
    # rounded value — sender and receiver agree on the exact step size
    scale_w = _bf16_wire(amax * jnp.float32(1.0 / 7.0))
    scale = _bf16_unwire(scale_w, jnp.float32)
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    y = xb * inv[:, :, None]
    if stochastic:
        y = jnp.floor(y + _hash_uniform(y.shape, jnp.uint32(0) if seed is None
                                        else seed))
    else:
        y = jnp.round(y)
    q = jnp.clip(y, -7.0, 7.0).astype(jnp.int32).reshape(n, tp)
    packed = ((q[:, 0::2] & 0xF) | ((q[:, 1::2] & 0xF) << 4)).astype(jnp.uint8)
    return packed, scale_w


def dequantize_int4(
    packed: jax.Array, scales: jax.Array, shape, block: int = INT4_BLOCK,
) -> jax.Array:
    """Packed nibbles + bf16-wire block scales -> f32 of ``shape``.

    ``shape`` must be the original (pre-pad) array shape — the codec cannot
    recover it from the padded payload alone."""
    n = packed.shape[0]
    t = int(np.prod(shape[1:])) if len(shape) > 1 else 1
    tp = packed.shape[1] * 2
    b = packed.astype(jnp.int32)
    lo = b & 0xF
    hi = (b >> 4) & 0xF
    q = jnp.stack([lo, hi], axis=-1).reshape(n, tp)
    q = (q ^ 8) - 8  # sign-extend the two's-complement nibble
    scale = _bf16_unwire(scales, jnp.float32)
    out = (q.reshape(n, tp // block, block).astype(jnp.float32)
           * scale[:, :, None]).reshape(n, tp)
    return out[:, :t].reshape(shape)


def psum_quantized(vals: jax.Array, axis_name: str, comm_dtype: str) -> jax.Array:
    """Pull-protocol reduction with a compressed payload.

    ``vals`` must be owner-exclusive: each leading-axis position is nonzero
    on at most one shard of ``axis_name`` (the collective planes mask
    non-owned rows to zero before reducing). f32 passes straight through to
    ``lax.psum`` — bit-identical to the pre-codec path.
    """
    if comm_dtype == "float32":
        return lax.psum(vals, axis_name)
    if comm_dtype == "bfloat16":
        # owner-exclusive: the u16 integer sum of one nonzero contribution
        # plus zero words IS the owner's bf16 bit pattern (0.0 bitcasts to
        # 0x0000), so the bitcast wire format loses nothing beyond the one
        # f32->bf16 rounding
        out = lax.psum(_bf16_wire(vals), axis_name)
        return _bf16_unwire(out, vals.dtype)
    if is_int4(comm_dtype):
        # owner-exclusive rows: non-owners contribute 0x00 packed bytes and
        # 0x0000 scale words, so the integer psums pass the owner's payload
        # through bit-exactly (one nonzero byte per position -> no overflow)
        block = int4_block(comm_dtype)
        packed, scale_w = quantize_int4(vals, block=block)
        p_sum = lax.psum(packed, axis_name)
        s_sum = lax.psum(scale_w, axis_name)
        return dequantize_int4(p_sum, s_sum, vals.shape,
                               block=block).astype(vals.dtype)
    # int8: owner-exclusive rows -> the sum of (q, scale) pairs IS the
    # owner's pair (zeros elsewhere carry zero scale); no overflow possible
    q, scale = quantize_int8(vals)
    q_sum = lax.psum(q.astype(jnp.int8), axis_name)
    s_sum = lax.psum(scale, axis_name)
    return dequantize_int8(q_sum, s_sum).astype(vals.dtype)


def reduce_sum_quantized(
    x: jax.Array,
    axis_name: str,
    comm_dtype: str,
    axis_size: int,
    stochastic: bool = False,
    seed=None,
) -> jax.Array:
    """Dense gradient all-reduce with a compressed payload (NOT
    owner-exclusive: every shard contributes to every position, so the
    psum_quantized trick of summing (q, scale) pairs would be wrong).

    f32 is a plain ``lax.psum``. bf16/int8/int4 quantize per shard, move the
    compressed payload with a tiled all_gather, and accumulate in f32 at
    the receiver — the wire stays narrow, the sum stays full precision.
    ``axis_size`` must be the static size of ``axis_name`` (it shapes the
    de-tiling reshape). Used by the hybrid head push
    (parallel/hybrid.py), where all data shards hold gradients for the
    same replicated rows."""
    if comm_dtype == "float32":
        return lax.psum(x, axis_name)
    g = all_gather_quantized(x, axis_name, comm_dtype,
                             stochastic=stochastic, seed=seed)
    return g.reshape((axis_size,) + x.shape).astype(jnp.float32).sum(axis=0)


def reduce_scatter_quantized(
    x: jax.Array,
    axis_name: str,
    comm_dtype: str,
    axis_size: int,
    stochastic: bool = False,
    seed=None,
) -> jax.Array:
    """ZeRO twin of :func:`reduce_sum_quantized`: each shard receives only
    its OWN ``1/axis_size`` leading-axis slice of the summed gradient (the
    weight-update-sharding reduce of arXiv 2004.13336), instead of every
    shard materializing the full sum.

    Bit-parity contract: the returned slice is **bit-identical** to the same
    slice of ``reduce_sum_quantized(x, ...)`` for every wire format —

    * ``float32`` — ``lax.psum_scatter`` (tiled). XLA's reduce-scatter applies
      the same shard-order f32 adds as the psum, so slicing the psum result
      and psum-scattering agree bit-for-bit (pinned by tests).
    * ``bfloat16``/``int8``/``int4`` — each shard quantizes its FULL local
      buffer with the same codec + dither seed as the all-gather path, but
      moves it with a tiled ``all_to_all`` (shard ``j`` receives every
      shard's quantized rows of slice ``j`` only — 1/axis_size the received
      bytes of the all_gather), then dequantizes and f32-sums in shard order.
      Same per-shard quantization, same accumulation order => the owned
      slice of the unsharded sum, exactly.

    ``x.shape[0]`` must divide by ``axis_size`` (callers pad/align the plane
    the way the hybrid head aligns its cut).
    """
    if x.shape[0] % axis_size:
        raise ValueError(
            f"reduce_scatter_quantized: leading dim {x.shape[0]} not "
            f"divisible by axis size {axis_size}")
    if comm_dtype == "float32":
        return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    own = x.shape[0] // axis_size

    def _a2a(w):
        return lax.all_to_all(w, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)

    if comm_dtype == "bfloat16":
        w = _a2a(_bf16_wire(x))
        contrib = _bf16_unwire(w, jnp.float32)
        return contrib.reshape((axis_size, own) + x.shape[1:]).sum(axis=0)
    if is_int4(comm_dtype):
        block = int4_block(comm_dtype)
        packed, scale_w = quantize_int4(
            x, stochastic=stochastic,
            seed=_salted(seed, axis_name) if stochastic else None,
            block=block)
        p_all = _a2a(packed)
        s_all = _a2a(scale_w)
        contrib = dequantize_int4(
            p_all, s_all, (p_all.shape[0],) + x.shape[1:], block=block)
        return contrib.reshape((axis_size, own) + x.shape[1:]).sum(axis=0)
    q, scale = quantize_int8(
        x, stochastic=stochastic,
        seed=_salted(seed, axis_name) if stochastic else None,
    )
    q_all = _a2a(q)
    s_all = _a2a(scale)
    contrib = dequantize_int8(q_all, s_all)
    return contrib.reshape((axis_size, own) + x.shape[1:]).sum(axis=0)


def all_gather_quantized(
    x: jax.Array,
    axis_name: str,
    comm_dtype: str,
    stochastic: bool = False,
    seed=None,
) -> jax.Array:
    """Push-protocol movement with a compressed payload (tiled all_gather).

    ``stochastic=True`` dithers the int8 rounding (gradients); ``seed`` is a
    replicated uint32 scalar — it is salted with this shard's data-axis
    index so shards draw independent noise.
    """
    if comm_dtype == "float32":
        return lax.all_gather(x, axis_name, tiled=True)
    if comm_dtype == "bfloat16":
        out = lax.all_gather(_bf16_wire(x), axis_name, tiled=True)
        return _bf16_unwire(
            out, jnp.float32 if x.dtype == jnp.float32 else x.dtype)
    if is_int4(comm_dtype):
        block = int4_block(comm_dtype)
        packed, scale_w = quantize_int4(
            x, stochastic=stochastic,
            seed=_salted(seed, axis_name) if stochastic else None,
            block=block)
        p_all = lax.all_gather(packed, axis_name, tiled=True)
        s_all = lax.all_gather(scale_w, axis_name, tiled=True)
        return dequantize_int4(
            p_all, s_all, (p_all.shape[0],) + x.shape[1:], block=block,
        ).astype(jnp.float32 if x.dtype == jnp.float32 else x.dtype)
    q, scale = quantize_int8(
        x, stochastic=stochastic,
        seed=_salted(seed, axis_name) if stochastic else None,
    )
    q_all = lax.all_gather(q, axis_name, tiled=True)
    s_all = lax.all_gather(scale, axis_name, tiled=True)
    return dequantize_int8(q_all, s_all).astype(
        jnp.float32 if x.dtype == jnp.float32 else x.dtype)
