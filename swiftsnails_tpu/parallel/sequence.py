"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no sequence models at all (survey §5: "no attention, no
notion of sequence length"); its only long axis is vocabulary. This module is
the framework's forward-looking long-context layer so transformer workloads
scale the same way the parameter table does — by adding a mesh axis:

* :func:`ring_attention` — blockwise flash-style attention where K/V shards
  rotate around the ``seq`` mesh axis via ``lax.ppermute`` (one ICI hop per
  step), with online-softmax accumulation. Memory per device stays
  O(L/P · L/P block), enabling sequences P× longer than one device's HBM
  would allow. Causal masking is applied per block pair.
* :func:`ulysses_attention` — the all-to-all alternative: reshard
  (seq-sharded, all heads) -> (full seq, head-sharded) with
  ``lax.all_to_all``, run exact local attention per head group, reshard
  back. Cheaper at moderate L (two all-to-alls), requires heads % P == 0.

Both are written against a named ``seq`` axis inside ``shard_map`` (mesh from
:func:`swiftsnails_tpu.parallel.mesh.make_mesh` with a ``seq`` axis) and are
differentiable (scan-based ring), so they drop into a jit'd train step.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from swiftsnails_tpu.parallel.mesh import SEQ_AXIS
from swiftsnails_tpu.utils.compat import shard_map

_NEG_INF = -1e30


def reference_attention(q, k, v, causal: bool = False) -> jax.Array:
    """Dense softmax attention (the single-device ground truth).

    Shapes: q [B, Lq, H, D], k/v [B, Lk, H, D] -> [B, Lq, H, D].
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), dtype=bool), k=lk - lq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block_update(q, k, v, o, l, m, block_mask):
    """One online-softmax accumulation step (flash-attention recurrence).

    q [B, Lq, H, D]; k/v [B, Lk, H, D]; o running output; l running
    denominator [B, H, Lq]; m running max [B, H, Lq]; block_mask [Lq, Lk]
    boolean or None.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B, H, Lq, Lk]
    if block_mask is not None:
        s = jnp.where(block_mask[None, None, :, :], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard: fully-masked rows keep m at -inf; exp underflows to 0 safely
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v
    )
    return o_new, l_new, m_new


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """shard_map body: q/k/v are the local sequence shards [B, Lb, H, D]."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, lb, h, d = q.shape

    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, lb), dtype=jnp.float32)
    m0 = jnp.full((b, h, lb), _NEG_INF, dtype=jnp.float32)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    qf = q.astype(jnp.float32)

    def step(carry, i):
        o, l, m, k_cur, v_cur = carry
        kv_idx = (my_idx - i) % axis_size  # whose K/V shard we hold this step
        if causal:
            # block-level causality on global positions
            q_pos = my_idx * lb + jnp.arange(lb)  # [Lb]
            k_pos = kv_idx * lb + jnp.arange(lb)
            block_mask = q_pos[:, None] >= k_pos[None, :]
        else:
            block_mask = None
        o2, l2, m2 = _block_update(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32), o, l, m, block_mask
        )
        if causal:
            # skip blocks strictly in the future (all-masked): keep carry
            keep = (kv_idx <= my_idx)
            o2 = jnp.where(keep, o2, o)
            l2 = jnp.where(keep, l2, l)
            m2 = jnp.where(keep, m2, m)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o2, l2, m2, k_next, v_next), ()

    (o, l, m, _, _), _ = lax.scan(step, (o0, l0, m0, k, v), jnp.arange(axis_size))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
) -> jax.Array:
    """Ring attention over the ``seq`` mesh axis.

    Inputs are globally [B, L, H, D] sharded on L; output has the same
    sharding. L must divide evenly by the seq axis size.
    """
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name: str, causal: bool):
    """shard_map body: seq-sharded in, all-to-all to head-sharded, exact
    attention over the full sequence, and back."""
    axis_size = lax.psum(1, axis_name)

    def seq_to_heads(x):  # [B, Lb, H, D] -> [B, L, H/P, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):  # [B, L, H/P, D] -> [B, Lb, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = reference_attention(qh, kh, vh, causal=causal)
    return heads_to_seq(out)


def ulysses_attention(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence-parallel attention.

    Requires num_heads % seq_axis_size == 0.
    """
    if q.shape[2] % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"heads {q.shape[2]} not divisible by {axis_name} axis {mesh.shape[axis_name]}"
        )
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
