"""Freshness pipeline — version-stamped hot-row delta shipping.

The trainer side (:mod:`.publisher`) publishes monotonically-sequenced
batches of absolute row values — sourced from the tier's dirty-flush
stream under ``table_tier: host``, or from a per-step touched-row
collector on the resident path — onto a bounded file-backed delta log
(:mod:`.log`). The serving side (:mod:`.subscriber`) applies them behind
the version-keyed hot-row cache with an atomic version cutover per
batch; any sequence gap, publisher restart, or CRC mismatch falls back
to the existing ``reload_from_checkpoint`` shadow swap and re-subscribes
from the new base. See docs/FRESHNESS.md.
"""

from swiftsnails_tpu.freshness.log import (  # noqa: F401
    DeltaCorrupt, list_seqs, prune, read_base, read_batch, write_base,
    write_batch,
)
from swiftsnails_tpu.freshness.publisher import (  # noqa: F401
    DeltaPublisher, TouchedRowCollector, TrainPublisher,
)
from swiftsnails_tpu.freshness.subscriber import DeltaSubscriber  # noqa: F401

__all__ = [
    "DeltaCorrupt",
    "DeltaPublisher",
    "DeltaSubscriber",
    "TouchedRowCollector",
    "TrainPublisher",
    "list_seqs",
    "prune",
    "read_base",
    "read_batch",
    "write_base",
    "write_batch",
]
