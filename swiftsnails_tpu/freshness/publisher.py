"""Trainer-side delta publishing.

Three layers:

* :class:`DeltaPublisher` — owns one log directory: stamps each batch
  with ``(publisher, seq, base_step, step, ts_ns)``, writes it
  atomically, applies the ``freshness_log_mb`` retention, and emits
  rate-limited ``delta`` ledger events.
* :class:`TouchedRowCollector` — resident-path row source: per step it
  asks the trainer's ``tier_plan`` for the exact master row ids the step
  touches (hashing + the replicated negative draw included — the same
  determinism contract the tiered store runs on), falling back to the
  union of integer batch leaves when a trainer has no plan. Extra rows
  are harmless: payloads carry absolute values, not diffs.
* :class:`TrainPublisher` — the TrainLoop-owned facade wiring source to
  sink: under ``table_tier: host`` it taps the tier's dirty-flush stream
  (``TieredTable.delta_tap``) and gathers flushed units from the host
  masters; on the resident (or transparent-tier) path it drains the
  collector and gathers rows straight from the live state planes. Either
  way the gathered values are normalized dense rows — bit-identical to
  the serving engine's ``normalize_table`` lane selects.

Publishing never blocks or kills training: every cadence publish is
wrapped, failures land as ``freshness_gap`` ledger events and the stream
simply misses a beat (subscribers see a late batch, not a torn one).
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from swiftsnails_tpu.freshness.log import prune, write_base, write_batch
from swiftsnails_tpu.utils.config import ConfigError

_LEDGER_EVERY = 100  # rate limit: first publish + every 100th


class HybridFreshnessError(ConfigError):
    """``placement: hybrid`` + freshness publishing + a TCP delta stream
    (``freshness_listen``) don't compose: hybrid head/tail planes leave
    the master row layout mid-run, so published rows would carry the
    wrong id space — and with a socket listener configured, remote
    subscribers would be *silently* starved if we just disabled
    publishing (the local-file case keeps the old disable-with-notice
    behavior, where the operator sees the stderr line). Raised at
    TrainLoop construction, before any step runs."""


# ------------------------------------------------- normalized row gathers ---


def gather_normalized_rows(plane, rows: np.ndarray, *, layout: str,
                           dim: int) -> np.ndarray:
    """Gather logical ``rows`` from a table plane in its trainer layout ->
    ``[n, dim]`` f32, via the same exact lane selects the serving engine's
    ``normalize_table`` uses (no arithmetic — bit-identical rows)."""
    rows = np.asarray(rows, np.int64)
    a = np.asarray(plane)
    if layout == "dense":
        return np.asarray(a[rows], np.float32)
    if layout == "packed":
        import jax.numpy as jnp

        from swiftsnails_tpu.ops.rowdma import unpack_rows

        tiles = jnp.asarray(a[rows])  # [n, S, 128], one row per tile
        return np.asarray(unpack_rows(tiles, dim), np.float32)
    if layout == "packed_small":
        from swiftsnails_tpu.ops.rowdma import ROW_LANES
        from swiftsnails_tpu.parallel.store import small_group

        g = small_group(dim)
        stride = ROW_LANES // g
        sub0 = a[rows // g, 0, :]  # [n, 128]: sublane 0 = params
        idx = ((rows % g) * stride)[:, None] + np.arange(dim)[None, :]
        return np.take_along_axis(sub0, idx, axis=1).astype(
            np.float32, copy=False)
    raise ValueError(f"unknown table layout {layout!r}")


def normalize_units(t_units: np.ndarray, units: np.ndarray, *, layout: str,
                    dim: int, group: int,
                    capacity: int) -> Tuple[np.ndarray, np.ndarray]:
    """Master-gathered units -> ``(row_ids, [n, dim] f32 values)``. A unit
    is one logical row except ``packed_small`` (one tile = ``group`` rows:
    a dirty tile publishes all its resident rows)."""
    units = np.asarray(units, np.int64)
    if layout in ("dense", "packed"):
        vals = gather_normalized_rows(
            t_units, np.arange(units.size), layout=layout, dim=dim)
        return units, vals
    if layout == "packed_small":
        from swiftsnails_tpu.ops.rowdma import ROW_LANES

        g = int(group)
        stride = ROW_LANES // g
        sub0 = np.asarray(t_units)[:, 0, :]  # [n, 128]
        rows = (units[:, None] * g + np.arange(g)[None, :]).ravel()
        rep = np.repeat(np.arange(units.size), g)
        idx = ((rows % g) * stride)[:, None] + np.arange(dim)[None, :]
        vals = np.take_along_axis(sub0[rep], idx, axis=1).astype(
            np.float32, copy=False)
        keep = rows < int(capacity)
        return rows[keep], vals[keep]
    raise ValueError(f"unknown table layout {layout!r}")


# --------------------------------------------------------------- publisher ---


class DeltaPublisher:
    """One publisher incarnation over one delta-log directory."""

    def __init__(self, dirpath: str, *, base_step: int,
                 dtype: str = "float32", log_mb: float = 64.0,
                 ledger=None, request_tracer=None):
        if dtype not in ("float32", "int8"):
            raise ValueError(
                f"freshness_delta_dtype must be float32|int8, got {dtype!r}")
        self.dir = os.path.abspath(dirpath)
        self.dtype = dtype
        self.log_mb = float(log_mb)
        self.ledger = ledger
        self.request_tracer = request_tracer
        self.base_step = int(base_step)
        self.id = uuid.uuid4().hex[:12]
        self.seq = 0
        self.published_batches = 0
        self.published_rows = 0
        self.published_bytes = 0
        self.pruned = 0
        # a new incarnation owns the directory: stale segments from a dead
        # publisher use an unrelated numbering and must never be read as
        # ours — drop them BEFORE the new base becomes visible
        try:
            from swiftsnails_tpu.freshness.log import list_seqs, seg_path
            for s in list_seqs(self.dir):
                try:
                    os.remove(seg_path(self.dir, s))
                except OSError:
                    pass
        except OSError:
            pass
        write_base(self.dir, {
            "publisher": self.id,
            "base_step": self.base_step,
            "first_seq": 1,
            "dtype": self.dtype,
        })

    def publish(self, updates: Dict[str, Tuple[np.ndarray, np.ndarray]],
                step: int) -> Optional[int]:
        """Write one batch of ``{table: (row_ids, [n, dim] f32 values)}``
        current as of trainer ``step``; returns the assigned seq (None when
        every table came up empty — an empty batch is not published)."""
        tables: Dict[str, Dict[str, np.ndarray]] = {}
        total_rows = 0
        for name, (rows, values) in updates.items():
            rows = np.asarray(rows, np.int64).ravel()
            if rows.size == 0:
                continue
            values = np.asarray(values, np.float32)
            if self.dtype == "int8":
                from swiftsnails_tpu.tiered.store import _np_quant_unit_rows

                codes, scales = _np_quant_unit_rows(values)
                tables[name] = {"rows": rows, "values": codes,
                                "scales": scales}
            else:
                tables[name] = {"rows": rows, "values": values}
            total_rows += int(rows.size)
        if not tables:
            return None
        self.seq += 1
        ctx = None
        if self.request_tracer is not None:
            try:
                ctx = self.request_tracer.start(
                    "delta_publish", publisher=self.id)
            except Exception:
                ctx = None  # tracing never blocks the publish path
        header = {
            "seq": self.seq,
            "publisher": self.id,
            "base_step": self.base_step,
            "step": int(step),
            "ts_ns": time.time_ns(),
            "dtype": self.dtype,
        }
        if ctx is not None:
            # the wire form rides the batch header: the subscriber resumes
            # this trace, so publish->apply->cutover is one drillable tree
            try:
                header["trace"] = ctx.wire()
            except Exception:
                pass
        t_write = time.perf_counter_ns()
        path = write_batch(self.dir, header, tables)
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            nbytes = 0
        if ctx is not None:
            try:
                ctx.add_span("write", t_write,
                             time.perf_counter_ns() - t_write,
                             tables=len(tables))
                ctx.annotate(seq=self.seq, step=int(step),
                             rows=total_rows, bytes=nbytes)
                self.request_tracer.finish(ctx)
            except Exception:
                pass
        self.published_batches += 1
        self.published_rows += total_rows
        self.published_bytes += nbytes
        self.pruned += prune(self.dir, int(self.log_mb * (1 << 20)))
        if self.ledger is not None and (
                self.published_batches == 1
                or self.published_batches % _LEDGER_EVERY == 0):
            try:
                self.ledger.append("delta", {
                    "source": "freshness",
                    "publisher": self.id,
                    "seq": self.seq,
                    "step": int(step),
                    "rows": total_rows,
                    "bytes": nbytes,
                    "dtype": self.dtype,
                    "published_batches": self.published_batches,
                })
            except Exception:
                pass  # record-keeping never blocks the publish path
        return self.seq

    def stats(self) -> Dict:
        return {
            "publisher": self.id,
            "seq": self.seq,
            "base_step": self.base_step,
            "dtype": self.dtype,
            "published_batches": self.published_batches,
            "published_rows": self.published_rows,
            "published_bytes": self.published_bytes,
            "pruned": self.pruned,
        }


# --------------------------------------------------------------- collector ---


class TouchedRowCollector:
    """Union of master row ids touched since the last drain (resident path).

    Primary source: the trainer's ``tier_plan`` (exact ids, hashing and the
    deterministic negative draw included). Fallback when a trainer has no
    plan: every integer batch leaf, attributed to every table and masked to
    capacity at drain — an over-approximation, harmless for absolute-value
    payloads.
    """

    _COMPACT_EVERY = 64  # chunks per table before an in-place unique

    def __init__(self, trainer):
        self.trainer = trainer
        self._plan_ok = True
        self._acc: Dict[Optional[str], List[np.ndarray]] = {}

    def observe(self, batch: Dict, root_rng, step: int) -> None:
        ids = None
        if self._plan_ok:
            try:
                ids, _aug, _remap = self.trainer.tier_plan(
                    batch, root_rng, np.uint32(step))
            except Exception:
                self._plan_ok = False
        if ids is None:
            leaves = [
                np.asarray(v).ravel() for v in batch.values()
                if np.issubdtype(np.asarray(v).dtype, np.integer)
            ]
            ids = {None: np.concatenate(leaves) if leaves
                   else np.zeros(0, np.int64)}
        for name, rows in ids.items():
            chunks = self._acc.setdefault(name, [])
            chunks.append(np.asarray(rows, np.int64).ravel())
            if len(chunks) > self._COMPACT_EVERY:
                self._acc[name] = [np.unique(np.concatenate(chunks))]

    def drain(self, geometry: Dict[str, Dict]) -> Dict[str, np.ndarray]:
        """Pending ids -> ``{table: unique in-capacity row ids}``; resets."""
        acc, self._acc = self._acc, {}
        out: Dict[str, np.ndarray] = {}
        for name, g in geometry.items():
            chunks = list(acc.get(name, ()))
            chunks.extend(acc.get(None, ()))  # fallback leaves: every table
            if not chunks:
                continue
            rows = np.unique(np.concatenate(chunks))
            rows = rows[(rows >= 0) & (rows < int(g["capacity"]))]
            if rows.size:
                out[name] = rows
        return out


# ---------------------------------------------------------- loop-side hook ---


class TrainPublisher:
    """The TrainLoop's freshness hook: decide the row source once, then
    ``on_batch`` each step and ``maybe_publish`` at the configured cadence
    (``freshness_publish`` steps; a final forced publish at end of run)."""

    def __init__(self, trainer, *, tier=None, placement=None, ledger=None,
                 request_tracer=None):
        cfg = trainer.config
        self.trainer = trainer
        self.tier = tier
        self.ledger = ledger
        if request_tracer is None:
            try:
                from swiftsnails_tpu.telemetry.request_trace import (
                    RequestTracer,
                )
                request_tracer = RequestTracer.from_config(
                    cfg, ledger=ledger, source="freshness")
            except Exception:
                request_tracer = None
        self.request_tracer = request_tracer
        self.period = cfg.get_int("freshness_publish", 0)
        self.dir = cfg.get_str("freshness_dir", "")
        self.dtype = cfg.get_str("freshness_delta_dtype", "float32")
        self.log_mb = cfg.get_float("freshness_log_mb", 64.0)
        self.geometry = trainer.table_geometry()
        self.active = bool(self.period > 0 and self.dir and self.geometry)
        if self.active and placement is not None:
            # hybrid head/tail planes aren't in master row layout mid-run;
            # publishing would ship rows from the wrong id space
            listen = cfg.get_str("freshness_listen", "")
            if listen:
                raise HybridFreshnessError(
                    "placement: hybrid cannot be combined with freshness "
                    "publishing to a TCP delta stream (freshness_listen="
                    f"{listen!r}): hybrid planes leave master row layout "
                    "mid-run, and remote subscribers would be silently "
                    "starved. Drop freshness_listen (file-dir publishing "
                    "is disabled with a notice) or drop placement: hybrid.")
            import sys

            print("freshness: publishing disabled under hybrid placement "
                  "(planes leave master layout mid-run)", file=sys.stderr)
            self.active = False
        self.listen = cfg.get_str("freshness_listen", "")
        self.stream_server = None
        self.pub: Optional[DeltaPublisher] = None
        self.collector: Optional[TouchedRowCollector] = None
        self._tap: Dict[str, List[np.ndarray]] = {}
        self._tap_lock = threading.Lock()
        self.errors = 0

    # -- lifecycle ----------------------------------------------------------

    def open(self, base_step: int) -> None:
        """Start an incarnation: called once per run, after tier adopt (so
        transparent pass-through mode is known) with the resume step."""
        if not self.active:
            return
        self.pub = DeltaPublisher(
            self.dir, base_step=base_step, dtype=self.dtype,
            log_mb=self.log_mb, ledger=self.ledger,
            request_tracer=self.request_tracer)
        if self.listen:
            # freshness_listen: HOST:PORT — push this log's frames to TCP
            # subscribers (net/delta_stream.py) alongside the file dir
            from swiftsnails_tpu.net.delta_stream import DeltaStreamServer

            host, _, port = self.listen.rpartition(":")
            self.stream_server = DeltaStreamServer(
                self.dir, host=host or "127.0.0.1", port=int(port or 0),
                ledger=self.ledger).start()
        if self.tier is not None and not self.tier.all_transparent:
            # dirty-flush tee: every landed write-back records its units
            for name, tt in self.tier.tables.items():
                tt.delta_tap = self._on_flush
        else:
            # resident (or transparent-tier: identity slot map, raw-id
            # batches, live full planes) — collect touched rows per step
            self.collector = TouchedRowCollector(self.trainer)

    def close(self) -> None:
        """End the incarnation: stop the TCP stream server (if any); the
        delta files stay for file-poll subscribers and resubscribes."""
        if self.stream_server is not None:
            self.stream_server.stop()
            self.stream_server = None

    # -- per-step hooks ------------------------------------------------------

    def on_batch(self, batch: Dict, root_rng, step: int) -> None:
        """Observe BEFORE ``tier.prepare`` remaps ids to slot space."""
        if self.collector is not None and self.pub is not None:
            try:
                self.collector.observe(batch, root_rng, step)
            except Exception:
                self.errors += 1

    def _on_flush(self, name: str, units: np.ndarray) -> None:
        with self._tap_lock:
            self._tap.setdefault(name, []).append(
                np.asarray(units, np.int64).copy())

    def maybe_publish(self, state, step: int, force: bool = False) -> None:
        if self.pub is None:
            return
        if not force and (self.period <= 0 or step == 0
                          or step % self.period != 0):
            return
        try:
            self._publish(state, step)
        except Exception as e:  # publishing must never kill training
            self.errors += 1
            if self.ledger is not None:
                try:
                    self.ledger.append("freshness_gap", {
                        "source": "publisher",
                        "reason": "publish_error",
                        "step": int(step),
                        "error": f"{type(e).__name__}: {e}",
                    })
                except Exception:
                    pass

    # -- the publish itself --------------------------------------------------

    def _publish(self, state, step: int) -> None:
        updates: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        if self.collector is not None:
            pending = self.collector.drain(self.geometry)
            if pending:
                tabs = self.trainer.tier_tables(state)
                for name, rows in pending.items():
                    g = self.geometry[name]
                    vals = gather_normalized_rows(
                        tabs[name].table, rows,
                        layout=g["layout"], dim=int(g["dim"]))
                    updates[name] = (rows, vals)
        else:
            # flush first so the masters hold the exact step-`step` rows —
            # the flush tee below records every landed unit
            self.tier.flush_dirty(state)
            with self._tap_lock:
                tapped, self._tap = self._tap, {}
            for name, chunks in tapped.items():
                tt = self.tier.tables.get(name)
                g = self.geometry.get(name)
                if tt is None or g is None or not chunks:
                    continue
                units = np.unique(np.concatenate(chunks))
                t_units, _slots = tt.master.gather(units)
                rows, vals = normalize_units(
                    np.asarray(t_units), units, layout=g["layout"],
                    dim=int(g["dim"]), group=int(g.get("group", 1)),
                    capacity=int(g["capacity"]))
                updates[name] = (rows, vals)
        self.pub.publish(updates, step)

    def stats(self) -> Dict:
        out = {"active": self.active, "period": self.period,
               "errors": self.errors}
        if self.pub is not None:
            out.update(self.pub.stats())
        return out
