"""Serving-side delta subscription.

:class:`DeltaSubscriber` sits between a delta-log directory and a serving
target (a :class:`~swiftsnails_tpu.serving.engine.Servant` or a whole
:class:`~swiftsnails_tpu.serving.fleet.Fleet` — both expose the same
``apply_rows`` / ``reload_from_checkpoint`` / ``step`` / ``version``
surface). ``poll()`` scans the directory, decodes batches, and applies
them strictly in sequence order with one atomic version cutover per
batch; apply is idempotent and out-of-order-safe, keyed on
``(table, row, seq)`` — a re-delivered or older batch can never regress
a row a newer batch already wrote.

Fallback contract (the only recovery path — deltas are an optimization,
checkpoints are the truth):

* **gap** — the next expected batch is missing but a later one exists
  (retention outran us, or the publisher lost a write), or an
  out-of-order direct ``apply_batch`` ran past the reorder ``window``;
* **restart** — the ``publisher`` id in ``BASE.json`` (or a batch
  header) changed: a new incarnation's seq numbering is unrelated;
* **crc** — a batch failed its CRC/framing check.

All three trigger the same sequence: a ``freshness_gap`` ledger event,
``reload_from_checkpoint`` (the existing shadow-load + verify + atomic
swap — the NEWEST verified checkpoint, not the stream's base), then
re-subscribe: batches whose ``step`` watermark is at or below the
reloaded checkpoint's step are skipped-but-acknowledged (their rows are
already in the reloaded planes or superseded), and the row-seq memory is
cleared because the reload re-based every row.

The freshness watermark is ``applied_step`` (the trainer step the newest
applied batch was current as of); the staleness gauge is the wall-clock
lag between publish and apply, with ``freshness_max_lag_ms`` bounding
when ``status()`` reports the target stale.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from swiftsnails_tpu.freshness.log import (
    DeltaCorrupt, list_seqs, read_base, read_batch, seg_path,
)

_LAG_WINDOW = 512  # lag samples kept for the p50/p99 gauge
_ROW_SEQ_CAP = 1 << 20  # bound the (table,row)->seq memory


def _percentile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(int(q * len(s)), len(s) - 1)
    return s[idx]


class DeltaSubscriber:
    """Apply a delta stream to a serving target with bounded staleness."""

    def __init__(
        self,
        target,
        dirpath: str,
        *,
        config=None,
        checkpoint_root: Optional[str] = None,
        max_lag_ms: float = 0.0,
        window: int = 64,
        ledger=None,
        request_tracer=None,
    ):
        self.target = target
        self.dir = os.path.abspath(dirpath)
        self.config = config
        self.checkpoint_root = checkpoint_root
        self.max_lag_ms = float(max_lag_ms)
        self.window = max(int(window), 1)
        self.ledger = ledger
        if request_tracer is None and config is not None:
            try:
                from swiftsnails_tpu.telemetry.request_trace import (
                    RequestTracer,
                )
                request_tracer = RequestTracer.from_config(
                    config, ledger=ledger, source="freshness")
            except Exception:
                request_tracer = None
        self.request_tracer = request_tracer
        self._lock = threading.RLock()
        # stream position
        self.publisher: Optional[str] = None
        self.base_step: Optional[int] = None
        self.next_seq = 1
        self.applied_seq = 0
        self.applied_step = 0  # the freshness watermark
        self.floor_step = 0  # batches at/below this step are already served
        # out-of-order buffer for direct apply_batch deliveries
        self._pending: Dict[int, tuple] = {}
        # (table, row) -> seq of the newest applied write
        self._row_seq: Dict[tuple, int] = {}
        # counters / gauges
        self.applied_batches = 0
        self.applied_rows = 0
        self.skipped_batches = 0  # at/below the reload floor
        self.duplicate_batches = 0
        self.fallbacks = 0
        self.gaps = 0
        self._lag_ms: "deque[float]" = deque(maxlen=_LAG_WINDOW)
        self.last_lag_ms = 0.0
        self._gap_events = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- subscription --------------------------------------------------------

    def subscribe(self) -> bool:
        """Adopt the directory's current base; returns False when no
        publisher has opened the stream yet (poll again later)."""
        with self._lock:
            base = read_base(self.dir)
            if base is None:
                return False
            seqs = list_seqs(self.dir)
            self.adopt_base(base, first_seq=seqs[0] if seqs else None)
            return True

    def adopt_base(self, base: Dict,
                   first_seq: Optional[int] = None) -> None:
        """Adopt a publisher base record — the transport-agnostic half of
        :meth:`subscribe` (a TCP stream source delivers the base as a frame
        instead of a ``BASE.json`` read). ``first_seq`` overrides the base's
        own ``first_seq`` when the transport knows the oldest batch it can
        still deliver."""
        with self._lock:
            self.publisher = base.get("publisher")
            self.base_step = int(base.get("base_step", 0) or 0)
            self.next_seq = int(first_seq if first_seq is not None
                                else base.get("first_seq", 1) or 1)
            # everything the target already serves needs no replay
            self.floor_step = max(self.floor_step,
                                  int(getattr(self.target, "step", 0) or 0))

    def corrupt_fallback(self, failed_seq: Optional[int] = None) -> None:
        """Public CRC-failure entry for alternate transports: a stream
        source that decodes a corrupt batch falls back exactly like the
        file poll does."""
        with self._lock:
            self._fallback("crc", failed_seq=failed_seq)

    def restart_fallback(self) -> None:
        """Public restart entry for alternate transports: a stream source
        that observes a new publisher incarnation (its base frame changed
        under it) falls back exactly like the file poll does."""
        with self._lock:
            self._fallback("restart")

    # -- polling -------------------------------------------------------------

    def poll(self, max_batches: Optional[int] = None) -> int:
        """Scan + apply every ready batch in order; returns how many were
        applied (skipped-but-acknowledged batches count too — they advance
        the sequence). Detection of gap/restart/crc falls back inline."""
        with self._lock:
            if self.publisher is None and not self.subscribe():
                return 0
            base = read_base(self.dir)
            if base is not None and base.get("publisher") != self.publisher:
                self._fallback("restart")
                return 0
            applied = 0
            while max_batches is None or applied < max_batches:
                path = seg_path(self.dir, self.next_seq)
                if not os.path.exists(path):
                    later = [s for s in list_seqs(self.dir)
                             if s > self.next_seq]
                    if later:
                        # atomic sequential writes: a visible later batch
                        # means this one existed and is gone (retention
                        # outran us) — a real gap, not a race
                        self._fallback("gap", failed_seq=self.next_seq)
                    return applied
                try:
                    header, tables = read_batch(path)
                except DeltaCorrupt:
                    self._fallback("crc", failed_seq=self.next_seq)
                    return applied
                if not self.apply_batch(header, tables):
                    return applied
                applied += 1
            return applied

    # -- apply ---------------------------------------------------------------

    def apply_batch(self, header: Dict, tables: Dict) -> bool:
        """Deliver one decoded batch; public so tests (and alternate
        transports) can push batches directly. Returns True when the stream
        advanced (applied, skipped past the floor, or buffered+drained),
        False when the batch was a duplicate, was buffered for later, or
        triggered a fallback."""
        with self._lock:
            if self.publisher is not None and \
                    header.get("publisher") != self.publisher:
                self._fallback("restart")
                return False
            seq = int(header["seq"])
            if seq < self.next_seq:
                self.duplicate_batches += 1  # idempotent: already applied
                return False
            if seq > self.next_seq:
                if seq - self.next_seq >= self.window:
                    self.gaps += 1
                    # resume AT the far-ahead batch: its re-delivery (or its
                    # successor) must apply on the reloaded planes
                    self._fallback("gap", failed_seq=seq - 1)
                    return False
                self._pending[seq] = (header, tables)
                return False
            self._apply_now(header, tables)
            # drain any buffered successors that are now contiguous
            while self.next_seq in self._pending:
                h, t = self._pending.pop(self.next_seq)
                self._apply_now(h, t)
            return True

    def _apply_now(self, header: Dict, tables: Dict) -> None:
        seq = int(header["seq"])
        step = int(header.get("step", 0) or 0)
        rt = self.request_tracer
        ctx = None
        if rt is not None:
            try:
                # continue the publisher's trace (same id -> same sampling
                # decision on both sides, no coordination needed)
                ctx = rt.resume(header.get("trace"), "delta_apply",
                                publisher=header.get("publisher"))
                ctx.annotate(seq=seq, step=step)
            except Exception:
                ctx = None  # tracing never blocks the apply path
        if step <= self.floor_step:
            # the fallback reload already serves rows at/after this step
            self.skipped_batches += 1
            self.next_seq = seq + 1
            self.applied_seq = seq
            if ctx is not None:
                try:
                    ctx.annotate(skipped=True, floor_step=self.floor_step)
                    rt.finish(ctx)
                except Exception:
                    pass
            return
        dtype = header.get("dtype", "float32")
        updates = {}
        n_rows = 0
        t_apply = time.perf_counter_ns()
        for name, t in tables.items():
            rows = np.asarray(t["rows"], np.int64)
            if dtype == "int8":
                from swiftsnails_tpu.tiered.store import _np_dequant_unit_rows

                values = _np_dequant_unit_rows(
                    np.asarray(t["values"]), np.asarray(t["scales"]),
                    np.float32)
            else:
                values = np.asarray(t["values"], np.float32)
            # (table, row, seq) keying: drop rows a newer seq already wrote
            # (can only happen through direct out-of-order apply paths)
            keep = np.fromiter(
                (self._row_seq.get((name, int(r)), 0) <= seq for r in rows),
                bool, count=rows.size)
            rows, values = rows[keep], values[keep]
            if rows.size == 0:
                continue
            for r in rows:
                self._row_seq[(name, int(r))] = seq
            updates[name] = (rows, values)
            n_rows += int(rows.size)
        if len(self._row_seq) > _ROW_SEQ_CAP:
            self._row_seq.clear()  # cheap reset: absolute values stay safe
        apply_dur = time.perf_counter_ns() - t_apply
        t_cutover = cutover_dur = 0
        if updates:
            # atomic version cutover inside; the step kwarg advances the
            # target's serving watermark to what the batch was current as of
            t_cutover = time.perf_counter_ns()
            self.target.apply_rows(updates, step=step)
            cutover_dur = time.perf_counter_ns() - t_cutover
        self.applied_seq = seq
        self.applied_step = max(self.applied_step, step)
        self.next_seq = seq + 1
        self.applied_batches += 1
        self.applied_rows += n_rows
        ts_ns = int(header.get("ts_ns", 0) or 0)
        if ts_ns:
            self.last_lag_ms = max((time.time_ns() - ts_ns) / 1e6, 0.0)
            self._lag_ms.append(self.last_lag_ms)
        if ctx is not None:
            try:
                ctx.add_span("apply", t_apply, apply_dur,
                             rows=n_rows, tables=len(updates))
                if updates:
                    ctx.add_span("cutover", t_cutover, cutover_dur)
                ctx.annotate(rows=n_rows,
                             target_version=getattr(
                                 self.target, "version", None))
                if ts_ns:
                    ctx.annotate(lag_ms=round(self.last_lag_ms, 3))
                rt.finish(ctx)
            except Exception:
                pass

    # -- fallback ------------------------------------------------------------

    def _fallback(self, reason: str,
                  failed_seq: Optional[int] = None) -> None:
        """Gap/restart/crc -> full reload of the newest verified checkpoint,
        then re-subscribe from the stream's current base. ``failed_seq``
        (gap/crc only — a restart's new incarnation renumbers from scratch)
        pins the resume point PAST the offending batch: the missing or
        corrupt segment is permanent, so resuming at or before it would
        re-trigger the same fallback forever. The reload already re-based
        every row, so skipping the dead batch loses nothing durable."""
        self.fallbacks += 1
        rt = self.request_tracer
        ctx = None
        if rt is not None:
            try:
                ctx = rt.start("delta_fallback")
                ctx.mark_anomaly("fallback")  # tail-keep: always retrievable
                ctx.annotate(reason=reason, failed_seq=failed_seq,
                             next_seq=self.next_seq,
                             applied_seq=self.applied_seq)
            except Exception:
                ctx = None
        t_detect = time.perf_counter_ns()
        self._ledger_event({
            "phase": "detect",
            "reason": reason,
            "next_seq": self.next_seq,
            "applied_seq": self.applied_seq,
            "fallbacks": self.fallbacks,
        })
        detect_dur = time.perf_counter_ns() - t_detect
        version = None
        t_reload = reload_dur = 0
        if self.checkpoint_root and self.config is not None:
            t_reload = time.perf_counter_ns()
            version = self.target.reload_from_checkpoint(
                self.checkpoint_root, self.config)
            reload_dur = time.perf_counter_ns() - t_reload
            # a batch current as of a step the reload already covers must
            # not re-apply on top of the newer planes
            self.floor_step = int(getattr(self.target, "step", 0) or 0)
        self._pending.clear()
        self._row_seq.clear()  # the reload re-based every row
        prev = self.publisher
        self.publisher = None
        t_resub = time.perf_counter_ns()
        self.subscribe()
        if (failed_seq is not None and self.publisher is not None
                and self.publisher == prev):
            # same incarnation: its numbering still holds, so skip the dead
            # batch (a NEW incarnation renumbers — subscribe() already set
            # the right position from its base)
            later = [s for s in list_seqs(self.dir) if s > failed_seq]
            self.next_seq = max(
                self.next_seq, later[0] if later else failed_seq + 1)
        resub_dur = time.perf_counter_ns() - t_resub
        self._ledger_event({
            "phase": "fallback",
            "reason": reason,
            "recovered": True,
            "version": version,
            "resubscribed_seq": self.next_seq,
            "floor_step": self.floor_step,
        })
        if ctx is not None:
            try:
                ctx.add_span("detect", t_detect, detect_dur, reason=reason)
                if t_reload:
                    ctx.add_span("reload", t_reload, reload_dur,
                                 version=version)
                ctx.add_span("resubscribe", t_resub, resub_dur,
                             resubscribed_seq=self.next_seq)
                ctx.annotate(recovered=True, version=version,
                             resubscribed_seq=self.next_seq,
                             floor_step=self.floor_step)
                rt.finish(ctx)
            except Exception:
                pass

    def _ledger_event(self, record: Dict) -> None:
        if self.ledger is None:
            return
        try:
            self.ledger.append("freshness_gap",
                               {"source": "subscriber", **record})
        except Exception:
            pass  # record-keeping never blocks the serve path

    # -- background poll (the CLI's `subscribe <dir>`) -----------------------

    def start(self, interval_s: float = 0.05) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll()
                except Exception:
                    pass  # the poller must survive transient I/O errors

        self._thread = threading.Thread(
            target=loop, name="ssn-freshness-poll", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None

    # -- introspection -------------------------------------------------------

    def status(self) -> Dict:
        with self._lock:
            lag = list(self._lag_ms)
            return {
                "dir": self.dir,
                "publisher": self.publisher,
                "base_step": self.base_step,
                "applied_seq": self.applied_seq,
                "applied_step": self.applied_step,
                "next_seq": self.next_seq,
                "pending": len(self._pending),
                "applied_batches": self.applied_batches,
                "applied_rows": self.applied_rows,
                "skipped_batches": self.skipped_batches,
                "duplicate_batches": self.duplicate_batches,
                "fallbacks": self.fallbacks,
                "gaps": self.gaps,
                "last_lag_ms": round(self.last_lag_ms, 3),
                "lag_p50_ms": round(_percentile(lag, 0.50), 3),
                "lag_p99_ms": round(_percentile(lag, 0.99), 3),
                "max_lag_ms": self.max_lag_ms,
                "stale": bool(self.max_lag_ms > 0
                              and self.last_lag_ms > self.max_lag_ms),
                "polling": self._thread is not None,
                **({"trace": self.request_tracer.stats()}
                   if self.request_tracer is not None else {}),
            }
