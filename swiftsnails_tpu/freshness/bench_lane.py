"""The bench ``freshness`` lane: hot-row delta shipping trainer -> fleet.

One implementation used by ``bench.py --lane freshness``,
``tools/chaos_drill.py --freshness``, and ``tests/test_freshness.py``'s lane
smoke test. The main leg runs the real pipeline end to end on CPU:

- train a dense word2vec model to a checkpoint at step S1 and load it into
  a 2-replica :class:`Fleet`;
- resume training S1 -> S2 with ``freshness_publish: 1`` on a background
  thread while a :class:`DeltaSubscriber` poll thread applies every delta
  batch to the fleet and an open-loop load generator drives pulls against
  it — delta lag and serve p99 are measured *under* concurrent apply;
- at the S2 watermark, delta-applied fleet rows must be **bit-identical**
  to a fresh ``Servant.from_checkpoint`` of the step-S2 checkpoint
  (``bit_parity`` = mismatched-element fraction, 0.0 required);
- a gap drill deletes a delta segment mid-stream: the subscriber must fall
  back to a full checkpoint reload, resubscribe past the gap, and converge
  back to parity 0.0.

Correctness (parity, gap recovery) gates on any platform; the latency
numbers are serving-machinery latencies, valid on CPU. The block lands in
the bench JSON (``freshness``), the run ledger, and the ``ledger-report
--check-regression`` gate (see ``_check_freshness_regression``).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

FRESHNESS_SEED = 17
# delta lag ceiling (publish ts -> applied ts, p99): file tail + poll loop on
# the same host — generous because CI boxes stall, but a wedged subscriber
# (seconds behind) must fail the gate
LAG_CEILING_MS = 2500.0


def _corpus(small: bool, vocab_n: int):
    """Zipf corpus over ``vocab_n`` words, frequency-ranked ids."""
    from swiftsnails_tpu.data.vocab import Vocab

    n_tokens = 20_000 if small else 80_000
    rng = np.random.default_rng(FRESHNESS_SEED)
    ranks = np.arange(1, vocab_n + 1, dtype=np.float64)
    w = 1.0 / ranks ** 1.1
    cdf = np.cumsum(w) / w.sum()
    ids = np.searchsorted(cdf, rng.random(n_tokens)).astype(np.int32)
    counts = np.maximum(
        np.bincount(ids, minlength=vocab_n), 1).astype(np.int64)
    return ids, Vocab([f"w{i}" for i in range(vocab_n)], counts)


def _make_trainer(corpus, workdir: str, **overrides):
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    ids, vocab = corpus
    base = {
        "dim": "16", "window": "1", "negatives": "4",
        "learning_rate": "0.3", "num_iters": "40", "batch_size": "128",
        "subsample": "0", "seed": "0", "packed": "0",
        "prefetch_batches": "0",
    }
    base.update({k: str(v) for k, v in overrides.items()})
    cfg = Config(base)
    return Word2VecTrainer(cfg, mesh=None, corpus_ids=ids, vocab=vocab), cfg


class _RecordingTarget:
    """Forwarding wrapper that remembers which rows deltas touched, so the
    parity check compares exactly the delta-applied set (public subscriber
    surface only — no reaching into its internals)."""

    def __init__(self, inner):
        self._inner = inner
        self.rows: Dict[str, set] = {}

    @property
    def step(self) -> int:
        return self._inner.step

    def apply_rows(self, updates, **kw):
        for name, (ids, _vals) in updates.items():
            self.rows.setdefault(name, set()).update(
                int(r) for r in np.asarray(ids))
        return self._inner.apply_rows(updates, **kw)

    def reload_from_checkpoint(self, root, config, **kw):
        return self._inner.reload_from_checkpoint(root, config, **kw)


def _parity(reference, served, rows: Dict[str, set]) -> float:
    """Mismatched-element fraction over the delta-applied rows: 0.0 means
    every applied row serves bit-identically to the reference planes."""
    bad = total = 0
    for name, rowset in rows.items():
        if not rowset or name not in reference._tables:
            continue
        ids = np.fromiter(sorted(rowset), np.int64)
        want = np.asarray(reference._tables[name])[ids]
        got = np.asarray(served._tables[name])[ids]
        bad += int(np.sum(want != got))
        total += int(want.size)
    return float(bad) / float(total) if total else 1.0


def _full_parity(reference, served) -> float:
    """Whole-plane mismatch fraction (post-fallback: a full reload must
    leave every row equal to the reference checkpoint)."""
    bad = total = 0
    for name, want in reference._tables.items():
        got = np.asarray(served._tables[name])
        want = np.asarray(want)
        bad += int(np.sum(want != got))
        total += int(want.size)
    return float(bad) / float(total) if total else 1.0


def freshness_bench(small: bool = False, workdir: Optional[str] = None,
                    ledger=None) -> Dict:
    """Run the freshness lane; returns the ``freshness`` block for the
    bench JSON.

    Gated fields (``ledger-report --check-regression``): ``bit_parity``
    (0.0 required, any platform), ``gap_drill.recovered`` +
    ``gap_drill.parity``, ``lag_p99_ms`` vs ``lag_ceiling_ms``, and
    ``serve_p99_ms`` vs ``slo_p99_ms`` while deltas were applying.
    """
    from swiftsnails_tpu.framework.trainer import TrainLoop
    from swiftsnails_tpu.freshness.subscriber import DeltaSubscriber
    from swiftsnails_tpu.serving.engine import Servant
    from swiftsnails_tpu.serving.fleet import Fleet
    from swiftsnails_tpu.serving.fleet_lane import SLO_P99_MS
    from swiftsnails_tpu.serving.loadgen import run_open_loop
    from swiftsnails_tpu.utils.config import Config

    vocab_n = 512 if small else 1024
    s1, s2 = (8, 48) if small else (16, 96)
    load_qps, load_s = (40.0, 2.0) if small else (80.0, 4.0)
    corpus = _corpus(small, vocab_n)

    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ssn-freshness-")
        workdir = own_tmp.name
    try:
        ck_root = os.path.join(workdir, "ckpt")
        delta_dir = os.path.join(workdir, "deltas")
        common = {
            "param_backup_root": ck_root,
            "param_backup_period": s1,
            "ledger_path": os.path.join(workdir, "LEDGER.jsonl"),
        }
        # -- phase A: train to S1, checkpoint, serve it ---------------------
        tr_a, _ = _make_trainer(corpus, workdir, **common)
        TrainLoop(tr_a, log_every=0).run(max_steps=s1)

        serve_cfg = Config({
            "dim": "16", "packed": "0", "seed": str(FRESHNESS_SEED),
        })
        fleet = Fleet.from_checkpoint(
            ck_root, serve_cfg, replicas=2, ledger=ledger)
        try:
            # warm the delta-apply scatter compiles at the power-of-two
            # shapes prepare_rows pads to, with the planes' own values —
            # value-level no-ops, so the lag/p99 measurement below sees the
            # steady-state apply cost, not first-compile stalls
            first_servant = next(iter(fleet._replicas.values())).servant
            for m in (64, 256, min(1024, vocab_n)):
                warm_ids = np.arange(min(m, vocab_n), dtype=np.int64)
                fleet.apply_rows({
                    name: (warm_ids, np.asarray(plane)[warm_ids])
                    for name, plane in first_servant._tables.items()})
            target = _RecordingTarget(fleet)
            sub = DeltaSubscriber(
                target, delta_dir, config=serve_cfg,
                checkpoint_root=ck_root, max_lag_ms=LAG_CEILING_MS,
                ledger=ledger)

            # -- phase B: resume S1 -> S2 publishing deltas, under load -----
            tr_b, _ = _make_trainer(
                corpus, workdir, **common, resume="auto",
                freshness_publish=1, freshness_dir=delta_dir,
                freshness_delta_dtype="float32")
            loop_b = TrainLoop(tr_b, log_every=0)
            trainer_err: List[BaseException] = []

            def _train():
                try:
                    loop_b.run(max_steps=s2)
                except BaseException as e:  # surfaced after join
                    trainer_err.append(e)

            th = threading.Thread(
                target=_train, name="ssn-freshness-train", daemon=True)
            th.start()
            # publisher BASE appears when the resumed run opens; subscribe
            # as soon as it does so lag is measured from the start
            deadline = time.monotonic() + 60.0
            while not sub.subscribe() and time.monotonic() < deadline:
                time.sleep(0.02)
            sub.start(interval_s=0.02)

            # warmup half (pull-path compiles + batcher fill), then measure
            # — the fleet lane's probe discipline
            run_open_loop(
                lambda anchor, ids: fleet.pull(ids),
                qps=load_qps, duration_s=load_s / 2, seed=FRESHNESS_SEED - 1,
                id_space=vocab_n, batch=16, zipf_a=1.2,
            )
            res = run_open_loop(
                lambda anchor, ids: fleet.pull(ids),
                qps=load_qps, duration_s=load_s, seed=FRESHNESS_SEED,
                id_space=vocab_n, batch=16, zipf_a=1.2,
            )
            th.join(timeout=300.0)
            if trainer_err:
                raise trainer_err[0]
            # drain the tail of the stream (final force-publish included)
            deadline = time.monotonic() + 30.0
            while (sub.status()["applied_step"] < s2
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            sub.stop()
            st = sub.status()

            # -- parity at the watermark ------------------------------------
            reference = Servant.from_checkpoint(ck_root, serve_cfg, step=s2)
            first = next(iter(fleet._replicas.values())).servant
            bit_parity = _parity(reference, first, target.rows)
            versions = {rid: rep.servant.version
                        for rid, rep in fleet._replicas.items()}
            cutover_atomic = len(set(versions.values())) == 1

            # -- gap drill: missing segment -> full reload -> reconverge ----
            gap = _gap_drill(
                fleet, reference, serve_cfg, ck_root,
                os.path.join(workdir, "deltas-gap"), s2, ledger=ledger)

            return {
                "small": bool(small),
                "steps": {"base": s1, "watermark": s2},
                "published_batches": st["applied_seq"],
                "applied_batches": st["applied_batches"],
                "applied_rows": st["applied_rows"],
                "applied_step": st["applied_step"],
                "lag_p50_ms": st["lag_p50_ms"],
                "lag_p99_ms": st["lag_p99_ms"],
                "lag_ceiling_ms": LAG_CEILING_MS,
                "serve_p99_ms": res["p99_ms"],
                "serve_qps": res["achieved_qps"],
                "slo_p99_ms": SLO_P99_MS,
                "bit_parity": bit_parity,
                "cutover_atomic": cutover_atomic,
                "replica_versions": versions,
                "gap_drill": gap,
            }
        finally:
            fleet.close()
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()


def _gap_drill(fleet, reference, serve_cfg, ck_root: str, drill_dir: str,
               watermark: int, ledger=None) -> Dict:
    """Delete a delta segment mid-stream; the subscriber must detect the
    gap, fall back to a full checkpoint reload, resubscribe past it, and
    end bit-identical to the reference planes."""
    from swiftsnails_tpu.freshness.log import seg_path
    from swiftsnails_tpu.freshness.publisher import DeltaPublisher
    from swiftsnails_tpu.freshness.subscriber import DeltaSubscriber

    # real rows from the reference planes, so post-gap re-apply is
    # value-identical to the fallback reload they land on
    name = next(iter(reference._tables))
    plane = np.asarray(reference._tables[name])
    rng = np.random.default_rng(FRESHNESS_SEED + 1)
    pub = DeltaPublisher(drill_dir, base_step=watermark, ledger=ledger)
    sub = DeltaSubscriber(
        fleet, drill_dir, config=serve_cfg, checkpoint_root=ck_root,
        ledger=ledger)

    def _batch(step):
        rows = np.sort(rng.choice(plane.shape[0], size=8, replace=False))
        return {name: (rows.astype(np.int64), plane[rows])}

    pub.publish(_batch(watermark + 1), step=watermark + 1)
    pub.publish(_batch(watermark + 2), step=watermark + 2)
    sub.subscribe()
    sub.poll()
    before = sub.status()["applied_seq"]
    # write 3..5, then destroy 3 before the subscriber sees it
    for k in (3, 4, 5):
        pub.publish(_batch(watermark + k), step=watermark + k)
    os.remove(seg_path(drill_dir, 3))
    sub.poll()  # gap at seq 3 -> fallback reload -> resubscribe at 4
    sub.poll()  # apply 4..5 on the reloaded planes
    st = sub.status()
    first = next(iter(fleet._replicas.values())).servant
    parity = _full_parity(reference, first)
    return {
        "recovered": bool(st["fallbacks"] >= 1 and st["applied_seq"] == 5),
        "fallbacks": st["fallbacks"],
        "applied_seq_before": before,
        "applied_seq": st["applied_seq"],
        "parity": parity,
    }


def freshness_chaos_drill(small: bool = True,
                          workdir: Optional[str] = None,
                          ledger=None) -> Dict:
    """The ``tools/chaos_drill.py --freshness`` matrix: three induced
    freshness failures against a live fleet, each required to fall back to
    a full checkpoint reload and converge to parity 0.0.

    - ``publisher_kill``: the publisher dies mid-stream and a NEW
      incarnation takes over the same directory (restart detection);
    - ``corrupt_delta``: one delta batch is bit-flipped on disk (CRC);
    - ``forced_gap``: a published segment is deleted before the subscriber
      reads it (sequence gap).
    """
    from swiftsnails_tpu.freshness.log import seg_path
    from swiftsnails_tpu.freshness.publisher import DeltaPublisher
    from swiftsnails_tpu.freshness.subscriber import DeltaSubscriber
    from swiftsnails_tpu.serving.engine import Servant
    from swiftsnails_tpu.serving.fleet import Fleet
    from swiftsnails_tpu.utils.config import Config

    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ssn-freshness-drill-")
        workdir = own_tmp.name
    try:
        from swiftsnails_tpu.framework.checkpoint import save_checkpoint
        from swiftsnails_tpu.models.word2vec import Word2VecTrainer
        from swiftsnails_tpu.framework.quality import paired_corpus

        dim, capacity = (16, 1 << 9) if small else (32, 1 << 11)
        ids, vocab = paired_corpus(n_pairs=32, reps=4, seed=FRESHNESS_SEED)
        cfg = Config({
            "dim": str(dim), "capacity": str(capacity), "packed": "0",
            "seed": str(FRESHNESS_SEED), "subsample": "0",
        })
        trainer = Word2VecTrainer(cfg, mesh=None, corpus_ids=ids, vocab=vocab)
        state = trainer.init_state()
        ck_root = os.path.join(workdir, "ckpt")
        save_checkpoint(ck_root, state, step=1, wait=True)
        reference = Servant.from_checkpoint(ck_root, cfg)
        rng = np.random.default_rng(FRESHNESS_SEED)
        plane = np.asarray(reference._tables["in_table"])

        def _batch():
            rows = np.sort(
                rng.choice(plane.shape[0], size=8, replace=False))
            return {"in_table": (rows.astype(np.int64), plane[rows])}

        from swiftsnails_tpu.telemetry.request_trace import (
            RequestTracer,
            tree_complete,
        )

        drills: Dict[str, Dict] = {}
        for drill in ("publisher_kill", "corrupt_delta", "forced_gap"):
            fleet = Fleet.from_checkpoint(
                ck_root, cfg, replicas=2, ledger=ledger)
            # tail-keep only: the gap->fallback must land as a complete,
            # drillable span tree even at sample rate 0
            tracer = RequestTracer(
                0.0, anomaly_keep=True, seed=FRESHNESS_SEED)
            try:
                d = os.path.join(workdir, drill)
                pub = DeltaPublisher(d, base_step=1, ledger=ledger,
                                     request_tracer=tracer)
                sub = DeltaSubscriber(
                    fleet, d, config=cfg, checkpoint_root=ck_root,
                    ledger=ledger, request_tracer=tracer)
                pub.publish(_batch(), step=2)
                pub.publish(_batch(), step=3)
                sub.subscribe()
                sub.poll()
                if drill == "publisher_kill":
                    # the old incarnation dies; a new one reopens the dir
                    pub2 = DeltaPublisher(d, base_step=3, ledger=ledger)
                    pub2.publish(_batch(), step=4)
                    sub.poll()  # detects the restart -> fallback
                    sub.poll()  # applies the new incarnation's stream
                elif drill == "corrupt_delta":
                    p = pub.publish(_batch(), step=4)
                    path = seg_path(d, p)
                    blob = bytearray(open(path, "rb").read())
                    blob[len(blob) // 2] ^= 0xFF
                    open(path, "wb").write(bytes(blob))
                    sub.poll()
                else:  # forced_gap
                    gone = pub.publish(_batch(), step=4)
                    pub.publish(_batch(), step=5)
                    os.remove(seg_path(d, gone))
                    sub.poll()
                    sub.poll()  # re-apply past the gap after the reload
                st = sub.status()
                first = next(iter(fleet._replicas.values())).servant
                parity = _full_parity(reference, first)
                versions = {rid: rep.servant.version
                            for rid, rep in fleet._replicas.items()}
                # the fallback must be drillable: a kept anomaly trace with
                # the full detect -> reload -> resubscribe timeline
                fb_traces = [
                    t for t in (c.to_dict()
                                for c in tracer.anomaly_traces())
                    if "fallback" in t["anomalies"] and tree_complete(
                        t, require=("detect", "reload", "resubscribe",
                                    "request"))]
                drills[drill] = {
                    "recovered": bool(st["fallbacks"] >= 1
                                      and parity == 0.0
                                      and len(set(versions.values())) == 1
                                      and fb_traces),
                    "fallbacks": st["fallbacks"],
                    "parity": parity,
                    "applied_seq": st["applied_seq"],
                    "fallback_traces": len(fb_traces),
                    "trace_id": (fb_traces[-1]["trace_id"]
                                 if fb_traces else None),
                }
            finally:
                fleet.close()
        drills["recovered_all"] = all(
            v["recovered"] for k, v in drills.items() if isinstance(v, dict))
        return drills
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()
