"""File-backed delta log: the wire format between trainer and fleet.

One directory per stream. ``BASE.json`` names the current publisher
incarnation and the checkpoint step it publishes on top of; each batch is
one ``delta-<seq:010d>`` file::

    b"SSD1" | uint32 header_len | header JSON | payload | uint32 CRC32

The CRC covers header + payload, so a torn or bit-flipped batch is
detected at read time (:class:`DeltaCorrupt`) — the subscriber treats it
exactly like a gap. Every write is atomic (tmp + ``os.replace``): a
reader either sees a whole batch or no batch, never a partial one.

The header carries ``seq`` (monotonic, per publisher incarnation),
``publisher`` (a fresh id per open — a changed id IS the restart
signal), ``base_step`` (the checkpoint the stream builds on), ``step``
(the trainer step this batch's rows are current as of — the freshness
watermark), ``ts_ns`` (publish wall clock, for the lag gauge), ``dtype``
(``float32`` or ``int8``), and per-table row counts/dims/offsets into
the payload. Payload values are *absolute* row values (not diffs), so
re-applying a batch is idempotent by construction; ``int8`` payloads add
one f32 scale per row (symmetric ``amax/127``, round-to-nearest — the
same quantizer :func:`~swiftsnails_tpu.tiered.store._np_quant_unit_rows`
uses for a master reload).

Retention: :func:`prune` deletes oldest-first once the directory exceeds
the ``freshness_log_mb`` budget. A subscriber that lagged past retention
sees a real gap and full-reloads — bounded disk beats unbounded replay.
"""

from __future__ import annotations

import json
import os
import tempfile
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"SSD1"
BASE_NAME = "BASE.json"
SEG_PREFIX = "delta-"
_ROW_DTYPE = np.dtype("<i8")
_VAL_DTYPES = {"float32": np.dtype("<f4"), "int8": np.dtype("int8")}
_SCALE_DTYPE = np.dtype("<f4")


class DeltaCorrupt(Exception):
    """A delta batch failed its magic/length/CRC check."""


def seg_name(seq: int) -> str:
    return f"{SEG_PREFIX}{int(seq):010d}"


def seg_path(dirpath: str, seq: int) -> str:
    return os.path.join(dirpath, seg_name(seq))


def _atomic_write(path: str, data: bytes) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".delta-tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# -- base record -------------------------------------------------------------


def write_base(dirpath: str, base: Dict) -> None:
    """Publisher-open record (atomic): a new incarnation rewrites it, and
    the changed ``publisher`` id is how subscribers detect the restart."""
    os.makedirs(dirpath, exist_ok=True)
    _atomic_write(os.path.join(dirpath, BASE_NAME),
                  (json.dumps(base) + "\n").encode("utf-8"))


def read_base(dirpath: str) -> Optional[Dict]:
    try:
        with open(os.path.join(dirpath, BASE_NAME), "r",
                  encoding="utf-8") as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


# -- batch encode/decode -----------------------------------------------------


def write_batch(
    dirpath: str,
    header: Dict,
    tables: Dict[str, Dict[str, np.ndarray]],
) -> str:
    """Write one delta batch; returns the file path.

    ``tables``: name -> ``{"rows": [n] int64, "values": [n, dim]}`` plus
    ``"scales": [n] f32`` when ``header["dtype"] == "int8"``.
    """
    dtype = header.get("dtype", "float32")
    val_dt = _VAL_DTYPES[dtype]
    entries = []
    chunks: List[bytes] = []
    off = 0
    for name in sorted(tables):
        t = tables[name]
        rows = np.ascontiguousarray(np.asarray(t["rows"], _ROW_DTYPE))
        values = np.ascontiguousarray(np.asarray(t["values"], val_dt))
        n = int(rows.size)
        dim = int(values.shape[1]) if values.ndim == 2 else 0
        if values.shape[0] != n:
            raise ValueError(
                f"{name}: {n} rows but {values.shape[0]} value rows")
        entry = {"name": name, "n": n, "dim": dim, "offset": off}
        chunks.append(rows.tobytes())
        chunks.append(values.tobytes())
        off += rows.nbytes + values.nbytes
        if dtype == "int8":
            scales = np.ascontiguousarray(
                np.asarray(t["scales"], _SCALE_DTYPE))
            if scales.size != n:
                raise ValueError(f"{name}: {n} rows but {scales.size} scales")
            chunks.append(scales.tobytes())
            off += scales.nbytes
        entries.append(entry)
    hdr = dict(header)
    hdr["tables"] = entries
    hjson = json.dumps(hdr).encode("utf-8")
    payload = b"".join(chunks)
    crc = zlib.crc32(hjson + payload) & 0xFFFFFFFF
    blob = (MAGIC + np.uint32(len(hjson)).tobytes() + hjson + payload
            + np.uint32(crc).tobytes())
    path = seg_path(dirpath, int(hdr["seq"]))
    _atomic_write(path, blob)
    return path


def read_batch(path: str) -> Tuple[Dict, Dict[str, Dict[str, np.ndarray]]]:
    """Decode one batch file -> ``(header, tables)``; :class:`DeltaCorrupt`
    on any framing or CRC failure (the subscriber's fallback trigger)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise DeltaCorrupt(f"{path}: unreadable ({e})") from e
    return decode_batch(blob, label=path)


def decode_batch(
    blob: bytes, label: str = "<stream>",
) -> Tuple[Dict, Dict[str, Dict[str, np.ndarray]]]:
    """Decode one batch blob (file contents or a TCP frame payload) ->
    ``(header, tables)``; :class:`DeltaCorrupt` on any framing/CRC failure.
    ``label`` names the source in error messages."""
    if len(blob) < len(MAGIC) + 8 or not blob.startswith(MAGIC):
        raise DeltaCorrupt(f"{label}: bad magic/short file")
    hlen = int(np.frombuffer(blob[4:8], np.uint32)[0])
    body_end = len(blob) - 4
    if 8 + hlen > body_end:
        raise DeltaCorrupt(f"{label}: truncated header")
    stored = int(np.frombuffer(blob[body_end:], np.uint32)[0])
    if (zlib.crc32(blob[8:body_end]) & 0xFFFFFFFF) != stored:
        raise DeltaCorrupt(f"{label}: CRC mismatch")
    try:
        header = json.loads(blob[8 : 8 + hlen].decode("utf-8"))
    except ValueError as e:
        raise DeltaCorrupt(f"{label}: unparseable header") from e
    dtype = header.get("dtype", "float32")
    val_dt = _VAL_DTYPES.get(dtype)
    if val_dt is None:
        raise DeltaCorrupt(f"{label}: unknown dtype {dtype!r}")
    payload = blob[8 + hlen : body_end]
    tables: Dict[str, Dict[str, np.ndarray]] = {}
    for entry in header.get("tables", []):
        n, dim, off = int(entry["n"]), int(entry["dim"]), int(entry["offset"])
        rows_nb = n * _ROW_DTYPE.itemsize
        vals_nb = n * dim * val_dt.itemsize
        need = off + rows_nb + vals_nb + (
            n * _SCALE_DTYPE.itemsize if dtype == "int8" else 0)
        if need > len(payload):
            raise DeltaCorrupt(f"{label}: payload shorter than header claims")
        rows = np.frombuffer(payload, _ROW_DTYPE, count=n, offset=off)
        values = np.frombuffer(
            payload, val_dt, count=n * dim, offset=off + rows_nb,
        ).reshape(n, dim)
        t = {"rows": rows, "values": values}
        if dtype == "int8":
            t["scales"] = np.frombuffer(
                payload, _SCALE_DTYPE, count=n, offset=off + rows_nb + vals_nb)
        tables[entry["name"]] = t
    return header, tables


# -- directory scan / retention ----------------------------------------------


def list_seqs(dirpath: str) -> List[int]:
    """Sorted sequence numbers present (atomic writes: present = whole)."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    out = []
    for name in names:
        if name.startswith(SEG_PREFIX):
            try:
                out.append(int(name[len(SEG_PREFIX):]))
            except ValueError:
                continue
    out.sort()
    return out


def prune(dirpath: str, max_bytes: int) -> int:
    """Delete oldest batches until the directory fits ``max_bytes`` (the
    newest batch always survives). Returns how many were deleted."""
    seqs = list_seqs(dirpath)
    sizes = {}
    for s in seqs:
        try:
            sizes[s] = os.path.getsize(seg_path(dirpath, s))
        except OSError:
            sizes[s] = 0
    total = sum(sizes.values())
    deleted = 0
    for s in seqs[:-1]:  # never delete the newest
        if total <= max_bytes:
            break
        try:
            os.unlink(seg_path(dirpath, s))
        except OSError:
            continue
        total -= sizes[s]
        deleted += 1
    return deleted
