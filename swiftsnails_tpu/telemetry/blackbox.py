"""Failure black-box: a flight-recorder ring for the last N training steps.

A mid-run failure used to leave *nothing* — the round-5 outage produced only
a hand-typed text file, and a crashed run's spans/metrics died with the
process. The black-box keeps a bounded in-memory ring of per-step snapshots
(step number, wall time, host metrics like loss/grad-norm when available,
prefetch queue depth) and, on a trigger, dumps the ring plus the tracer's
recent spans and the environment fingerprint to disk atomically — the
post-mortem artifact the next ``docs/OUTAGE_*.txt`` writes itself from.

Triggers (wired in :class:`~swiftsnails_tpu.framework.trainer.TrainLoop`):

* an exception escaping the training loop;
* a NaN/Inf loss observed at a metrics window (the host already has the
  value there — no extra device sync is added to the hot path);
* SIGTERM (preemption), via :meth:`BlackBox.install_signal_handler`.

Cost contract: recording one step is one small dict append into a
``deque(maxlen=N)``; the black-box only exists when telemetry is enabled
(``blackbox_steps > 0``), mirroring the tracer's off-by-default stance.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from swiftsnails_tpu.telemetry.ledger import (
    Ledger, atomic_write_json, env_fingerprint,
)


class BlackBox:
    """Bounded ring of step snapshots with atomic crash dumps.

    ``capacity``: steps retained; ``directory``: where dumps land
    (``blackbox-<utc>-<reason>.json``); ``ledger``: optional
    :class:`Ledger` that receives a ``blackbox`` event per dump, so
    ``ledger-report`` can point at the artifact.
    """

    def __init__(
        self,
        capacity: int = 32,
        directory: str = "blackbox",
        ledger: Optional[Ledger] = None,
        context: Optional[Dict] = None,
        max_spans: int = 512,
    ):
        self.capacity = max(int(capacity), 1)
        self.directory = directory
        self.ledger = ledger
        self.context = dict(context or {})
        self.max_spans = max_spans
        self._ring: Deque[Dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dumped_reasons: set = set()
        self._prev_sigterm = None

    # -- recording ---------------------------------------------------------

    def record_step(self, step: int, **fields) -> None:
        """Append one step snapshot (cheap: one dict + deque append)."""
        snap = {"step": int(step), "t": time.time()}
        snap.update(fields)
        with self._lock:
            self._ring.append(snap)

    def record_metrics(self, step: int, metrics: Dict) -> None:
        """Attach host metric values (loss, grad norms) to the ring entry for
        ``step`` — called at flush windows where the values are already on
        the host."""
        with self._lock:
            for snap in reversed(self._ring):
                if snap["step"] == step:
                    snap["metrics"] = dict(metrics)
                    return
            self._ring.append(
                {"step": int(step), "t": time.time(), "metrics": dict(metrics)}
            )

    @staticmethod
    def nonfinite(metrics: Dict) -> List[str]:
        """Metric names whose host value is NaN/Inf (the NaN-loss trigger)."""
        bad = []
        for k, v in metrics.items():
            if isinstance(v, float) and (v != v or v in (float("inf"), float("-inf"))):
                bad.append(k)
        return bad

    # -- dumping -----------------------------------------------------------

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def dump(
        self,
        reason: str,
        exc: Optional[BaseException] = None,
        tracer=None,
        once: bool = True,
    ) -> Optional[str]:
        """Write the post-mortem artifact; returns its path (None when this
        reason already dumped and ``once`` is set — a NaN loss that persists
        for thousands of steps must not write thousands of files)."""
        if once and reason in self._dumped_reasons:
            return None
        self._dumped_reasons.add(reason)
        steps = self.snapshot()
        doc: Dict = {
            "reason": reason,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "env": env_fingerprint(include_devices=True),
            "context": self.context,
            "steps": steps,
        }
        if exc is not None:
            doc["exception"] = {"type": type(exc).__name__, "message": str(exc)}
        if tracer is not None:
            try:
                doc["spans"] = tracer.events()[-self.max_spans:]
            except Exception:
                doc["spans"] = []
        os.makedirs(self.directory, exist_ok=True)
        fname = "blackbox-{}-{}.json".format(
            time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
            "".join(c if c.isalnum() else "-" for c in reason),
        )
        path = os.path.join(self.directory, fname)
        atomic_write_json(path, doc)
        if self.ledger is not None:
            try:
                self.ledger.append(
                    "blackbox",
                    {
                        "reason": reason,
                        "dump_path": os.path.abspath(path),
                        "first_step": steps[0]["step"] if steps else None,
                        "last_step": steps[-1]["step"] if steps else None,
                        "exception": doc.get("exception"),
                    },
                )
            except OSError:
                pass  # the dump itself is the priority artifact
        return path

    # -- signals -----------------------------------------------------------

    def install_signal_handler(self, tracer=None) -> bool:
        """Dump on SIGTERM (preemption), then hand control back to whatever
        handler was installed before (default: process death). Main-thread
        only; returns False (and stays uninstalled) elsewhere."""

        def _on_term(signum, frame):
            self.dump("sigterm", tracer=tracer)
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, _on_term)
            return True
        except ValueError:  # not the main thread
            return False

    def uninstall_signal_handler(self) -> None:
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
