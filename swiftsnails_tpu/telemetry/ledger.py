"""Durable run ledger: append-only, atomically-written JSONL run records.

The reference system's only run record was stdout from Hadoop reducers; this
repo's was barely better — round 5's headline bench artifact lived in one
fragile ``BENCH_LAST_GOOD.json`` that a workspace restart erased (it had to be
hand-reconstructed, ``BENCH_r05.json`` ``errors[0]``), and a 27-failure
accelerator outage was logged by hand in ``docs/OUTAGE_r5_probe.txt``. The
ledger replaces both: every bench run, training run, outage/probe event, and
black-box dump appends one self-describing record, and the single-file cache
becomes a **derived view** regenerated from the ledger
(:func:`derive_last_good`).

Durability contract: every append rewrites the file via write-tmp + fsync +
rename (+ directory fsync), so the ledger on disk is *always* a complete,
parseable JSONL file — a crash mid-append leaves the previous version, never
a torn line. Appends are rare (one per run/outage), so the O(file) rewrite is
irrelevant; single-writer per path is assumed (the bench and trainer are).

Record envelope::

    {"schema": 1, "kind": "bench"|"run"|"outage"|"blackbox"|"chaos"
                          |"checkpoint"|"cache_error",
     "ts": "<UTC ISO8601>", "env": {...fingerprint...}, ...kind fields...}

(``chaos`` = an injected drill fault, ``checkpoint`` = a verified save
commit, ``cache_error`` = a corrupt bench cache OR checkpoint rejected /
walked back — see ``ledger-report --failures`` for the timeline view.)

``python -m swiftsnails_tpu ledger-report`` (or ``tools/ledger_report.py``)
renders the ledger; its ``--check-regression`` mode is the bench gate.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 1

# default ledger location: next to BENCH_LAST_GOOD.json at the repo root,
# overridable per-call (config `ledger_path`) or via env for the bench
DEFAULT_LEDGER = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "RUN_LEDGER.jsonl",
)


def _utc_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


# ------------------------------------------------------- env fingerprint ---


def _git_sha(cwd: Optional[str] = None) -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5,
        )
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except (OSError, subprocess.SubprocessError):
        return None


def env_fingerprint(include_devices: bool = False) -> Dict:
    """Environment identity of a run: git sha, jax/jaxlib/libtpu versions,
    python, host — and device topology when ``include_devices`` is set.

    ``include_devices`` intentionally defaults to False: querying devices
    *initializes the backend*, and the bench must never touch the
    accelerator before its pre-flight probe (the round-1 wedged-grant
    lesson). Pass True only where jax is already live, or fill the
    ``devices`` block from probe output instead.
    """
    fp: Dict = {
        "git_sha": _git_sha(),
        "python": sys.version.split()[0],
        "host": os.uname().nodename if hasattr(os, "uname") else None,
    }
    try:
        import jax

        fp["jax"] = jax.__version__
        try:
            import jaxlib

            fp["jaxlib"] = getattr(jaxlib, "__version__", None)
        except ImportError:
            fp["jaxlib"] = None
        try:
            from importlib import metadata

            fp["libtpu"] = metadata.version("libtpu")
        except Exception:
            fp["libtpu"] = None
        if include_devices:
            devs = jax.devices()
            fp["devices"] = {
                "platform": devs[0].platform,
                "count": len(devs),
                "kind": getattr(devs[0], "device_kind", None),
                "process_count": jax.process_count(),
            }
    except Exception as e:  # jax missing/broken must not kill record-keeping
        fp["jax_error"] = f"{type(e).__name__}: {e}"
    return fp


def config_hash(conf: Dict) -> str:
    """Stable short hash of a flat config mapping (order-independent)."""
    blob = json.dumps(
        {str(k): str(v) for k, v in conf.items()}, sort_keys=True
    ).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:16]


# ---------------------------------------------------------- atomic write ---


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via tmp + fsync + rename (+ dir fsync):
    readers only ever see the old or the new complete file."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".", dir=d)
    try:
        try:
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.rename(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:  # persist the rename itself
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # e.g. directories that reject O_RDONLY open; data is renamed


def atomic_write_json(path: str, obj) -> None:
    atomic_write_bytes(path, (json.dumps(obj) + "\n").encode("utf-8"))


# ----------------------------------------------------------------- ledger ---


class Ledger:
    """Append-only JSONL run ledger with atomic rewrites.

    ``append`` returns the full record written (envelope included) so call
    sites can echo/forward it. All read paths tolerate a corrupt line
    (reported, never raised) — a half-written legacy file or a foreign line
    must not take down the bench.
    """

    def __init__(self, path: str = DEFAULT_LEDGER):
        self.path = os.path.abspath(path)

    # -- write -------------------------------------------------------------

    def append(self, kind: str, record: Dict, env: Optional[Dict] = None) -> Dict:
        full = {"schema": SCHEMA_VERSION, "kind": kind, "ts": _utc_now()}
        if env is not None:
            full["env"] = env
        full.update(record)
        line = json.dumps(full) + "\n"
        try:
            with open(self.path, "rb") as f:
                existing = f.read()
            if existing and not existing.endswith(b"\n"):
                existing += b"\n"  # heal a torn legacy tail
        except OSError:
            existing = b""
        atomic_write_bytes(self.path, existing + line.encode("utf-8"))
        return full

    # -- read --------------------------------------------------------------

    def replay(self) -> Tuple[List[Dict], List[str]]:
        """All parseable records plus a list of corrupt-line descriptions."""
        records: List[Dict] = []
        bad: List[str] = []
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            return records, bad
        for lineno, line in enumerate(lines, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad.append(f"{self.path}:{lineno}: unparseable line skipped")
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                bad.append(f"{self.path}:{lineno}: non-object record skipped")
        return records, bad

    def records(self, kind: Optional[str] = None) -> List[Dict]:
        recs, _ = self.replay()
        if kind is None:
            return recs
        return [r for r in recs if r.get("kind") == kind]

    def latest(self, kind: str) -> Optional[Dict]:
        recs = self.records(kind)
        return recs[-1] if recs else None


# --------------------------------------------- bench cache (derived view) ---

# minimal self-consistency schema for a bench result payload: what the
# outage-fallback path needs to emit a trustworthy headline
_BENCH_REQUIRED = {
    "metric": str,
    "value": (int, float),
    "unit": str,
    "config": dict,
}


def validate_bench_payload(payload) -> List[str]:
    """Problems that make a bench payload unusable as a cached headline."""
    if not isinstance(payload, dict):
        return [f"payload is {type(payload).__name__}, not an object"]
    problems = []
    for key, typ in _BENCH_REQUIRED.items():
        if key not in payload:
            problems.append(f"missing required key {key!r}")
        elif not isinstance(payload[key], typ):
            problems.append(
                f"key {key!r} has type {type(payload[key]).__name__}"
            )
    value = payload.get("value")
    if isinstance(value, (int, float)) and not value > 0:
        problems.append(f"non-positive headline value {value!r}")
    return problems


def load_bench_cache(path: str) -> Tuple[Optional[Dict], Optional[str]]:
    """Read + schema-validate a BENCH_LAST_GOOD-style cache file.

    Returns ``(payload, None)`` on success, ``(None, reason)`` on a missing,
    partial, or unparseable cache — the caller records the reason as a
    ledger event instead of crashing (or silently emitting garbage).
    """
    try:
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
    except OSError as e:
        return None, f"cache unreadable: {e}"
    except ValueError as e:
        return None, f"cache unparseable (partial write?): {e}"
    problems = validate_bench_payload(payload)
    if problems:
        return None, "cache failed schema validation: " + "; ".join(problems)
    return payload, None


def derive_last_good(
    ledger: Ledger, out_path: str
) -> Tuple[Optional[Dict], Optional[str]]:
    """Regenerate the BENCH_LAST_GOOD.json **derived view** from the ledger.

    The newest ``bench`` record flagged ``cacheable`` whose payload passes
    schema validation wins. Returns ``(payload_written, None)`` or
    ``(None, reason)`` when the ledger holds no cacheable record.
    """
    candidates = [
        r for r in ledger.records("bench")
        if r.get("cacheable") and isinstance(r.get("payload"), dict)
    ]
    for rec in reversed(candidates):
        payload = rec["payload"]
        if validate_bench_payload(payload):
            continue
        payload = dict(payload)
        payload.setdefault("measured_at", rec.get("ts"))
        atomic_write_json(out_path, payload)
        return payload, None
    return None, "no cacheable bench record in ledger"


def outage_summary(ledger: Ledger) -> Optional[Dict]:
    """Structured summary of the most recent outage: the line that used to be
    hand-written into ``docs/OUTAGE_*.txt``."""
    outages = ledger.records("outage")
    if not outages:
        return None
    last = outages[-1]
    return {
        "at": last.get("ts"),
        "probe_duration_s": last.get("probe_duration_s"),
        "rc": last.get("rc"),
        "error": last.get("error"),
        "outages_recorded": len(outages),
    }


# -------------------------------------------------------------- reporting ---


def _fmt_num(v) -> str:
    if isinstance(v, float):
        return f"{v:,.3f}" if abs(v) < 10 else f"{v:,.1f}"
    return str(v)


def render_report(ledger: Ledger) -> str:
    """Terminal rendering of the ledger: run/bench/outage/black-box history."""
    records, bad = ledger.replay()
    if not records and not bad:
        return f"{ledger.path}: empty or missing ledger"
    lines = [f"ledger: {ledger.path}  ({len(records)} records)"]
    counts: Dict[str, int] = {}
    for r in records:
        counts[r.get("kind", "?")] = counts.get(r.get("kind", "?"), 0) + 1
    lines.append(
        "  " + "  ".join(f"{k}={n}" for k, n in sorted(counts.items()))
    )
    for warn in bad:
        lines.append(f"  WARNING: {warn}")

    bench = ledger.records("bench")
    if bench:
        lines.append("")
        lines.append("bench records (newest last):")
        for r in bench[-5:]:
            p = r.get("payload", {}) if isinstance(r.get("payload"), dict) else {}
            env = r.get("env", {}) or {}
            flags = []
            if r.get("cacheable"):
                flags.append("cacheable")
            if p.get("cached"):
                flags.append("cached")
            if p.get("reconstructed"):
                flags.append("reconstructed")
            lines.append(
                f"  {r.get('ts', '?')}  value={_fmt_num(p.get('value', 0))} "
                f"{p.get('unit', '')}  path={p.get('path')}  "
                f"platform={p.get('platform')}  git={str(env.get('git_sha'))[:9]}"
                f"  config_hash={r.get('config_hash', '?')}"
                + (f"  [{','.join(flags)}]" if flags else "")
            )

    runs = ledger.records("run")
    if runs:
        lines.append("")
        lines.append("training runs (newest last):")
        for r in runs[-5:]:
            g = r.get("goodput", {}) or {}
            mfu = g.get("mfu")
            dec = g.get("decomposition", {}) or {}
            # active quantization knobs, when the run recorded them: the
            # wire format and (for tiered runs) the host-master storage dtype
            dtypes = ""
            if r.get("comm_dtype"):
                dtypes += f"  wire={r['comm_dtype']}"
            t = r.get("tiered")
            if isinstance(t, dict) and t.get("master_dtype"):
                dtypes += f"  tier_master={t['master_dtype']}"
            lines.append(
                f"  {r.get('ts', '?')}  model={r.get('model')}  "
                f"steps={r.get('steps')}  items={r.get('items')}  "
                f"config_hash={r.get('config_hash', '?')}  "
                f"mfu={'%.3g' % mfu if isinstance(mfu, (int, float)) else 'n/a'}"
                + dtypes
            )
            if dec:
                lines.append(
                    "    step-time: "
                    + "  ".join(
                        f"{k}={dec[k] * 100:.1f}%"
                        for k in ("compute_frac", "h2d_frac",
                                  "host_blocked_frac", "other_frac")
                        if isinstance(dec.get(k), (int, float))
                    )
                )
            # continuous-profiling sparklines, when the run carried a
            # timeseries summary (profile_cadence > 0)
            ts_block = r.get("timeseries")
            if isinstance(ts_block, dict) and ts_block.get("series"):
                from swiftsnails_tpu.telemetry.timeseries import (
                    render_sparklines,
                )

                names = [n for n in ("step_ms", "loss",
                                     "win_host_blocked_frac",
                                     "win_compute_frac", "prefetch_stall_ms",
                                     "tier_hit_rate")
                         if n in ts_block["series"]]
                lines.append(
                    f"    profile: {ts_block.get('window')} samples, steps "
                    f"{ts_block.get('first_step')}.."
                    f"{ts_block.get('last_step')}"
                )
                lines.extend(render_sparklines(ts_block, names=names,
                                               indent="      "))
            drift = r.get("drift")
            if isinstance(drift, dict) and (drift.get("drifted")
                                            or drift.get("events")):
                tripped = drift.get("tripped") or []
                lines.append(
                    f"    drift: {drift.get('events', 0)} event(s) on "
                    + (", ".join(tripped) if tripped else "-")
                )

    # tiered parameter store: run records carry a `tiered` summary when
    # table_tier: host was on; bench records carry the `tiered` lane block
    tiered_rows = []
    for r in runs:
        t = r.get("tiered")
        if isinstance(t, dict):
            tiered_rows.append((r.get("ts", "?"), "run  ", t))
    for r in ledger.records("bench"):
        p = r.get("payload") if isinstance(r.get("payload"), dict) else {}
        t = (p or {}).get("tiered")
        if isinstance(t, dict):
            tiered_rows.append((r.get("ts", "?"), "bench", t))
    if tiered_rows:
        lines.append("")
        lines.append("tiered parameter store (newest last):")
        for ts, kind, t in tiered_rows[-5:]:
            cache = t.get("cache") if isinstance(t.get("cache"), dict) else t
            lines.append(
                f"  {ts}  {kind}  hit_rate={cache.get('hit_rate')}  "
                f"faulted_rows={cache.get('faulted_rows')}  "
                f"evictions={cache.get('evictions')}  "
                f"h2d={_fmt_num(cache.get('h2d_bytes', 0))}B  "
                f"d2h={_fmt_num(cache.get('d2h_bytes', 0))}B"
            )
            if kind == "bench":
                lines.append(
                    f"    lane: {_fmt_num(t.get('words_per_sec', 0))} words/s "
                    f"({t.get('tiered_over_resident')}x resident)  "
                    f"parity={t.get('parity_bit_identical')}  "
                    f"over_budget_round_trip={t.get('round_trip_ok')}"
                )
                q = t.get("quantized")
                if isinstance(q, dict):
                    lines.append(
                        f"    quantized[{q.get('master_dtype')}]: "
                        f"capacity={q.get('capacity_ratio_vs_f32')}x f32  "
                        f"rel_err={q.get('master_rel_err_vs_f32')}  "
                        f"digests_clean={q.get('digests_clean')}  "
                        f"serve_requant_exact={q.get('serve_requant_exact')}  "
                        f"ok={q.get('ok')}"
                    )
            elif t.get("master_dtype"):
                lines.append(f"    master_dtype={t['master_dtype']}")
            bd = t.get("breakdown")
            if isinstance(bd, dict) and any(
                    bd.get(k) for k in ("plan_ns", "fault_ns", "flush_ns",
                                        "remap_ns", "h2d_ns")):
                lines.append(
                    "    step-time: "
                    + "  ".join(
                        f"{k[:-3]}={bd[k] / 1e6:.1f}ms"
                        for k in ("plan_ns", "fault_ns", "flush_ns",
                                  "remap_ns", "h2d_ns", "flush_wait_ns")
                        if isinstance(bd.get(k), (int, float)) and bd[k]
                    )
                    + (f"  flush_q={bd.get('flush_queue_depth', 0)}"
                       if "flush_queue_depth" in bd else "")
                )

    # serving fleet: bench records carry the `fleet` lane block (replica
    # pool QPS at the p99 SLO, per-replica split, hedge + affinity legs)
    fleet_rows = []
    for r in ledger.records("bench"):
        p = r.get("payload") if isinstance(r.get("payload"), dict) else {}
        fb = (p or {}).get("fleet")
        if isinstance(fb, dict):
            fleet_rows.append((r.get("ts", "?"), fb))
    if fleet_rows:
        lines.append("")
        lines.append("serving fleet (newest last):")
        for ts, fb in fleet_rows[-5:]:
            single = fb.get("single") or {}
            lines.append(
                f"  {ts}  fleet={_fmt_num(fb.get('qps', 0))} qps "
                f"(single={_fmt_num(single.get('max_qps', 0))}, "
                f"scaling={fb.get('scaling_x')}x, "
                f"floor {fb.get('scaling_floor')}x)  "
                f"p99={fb.get('p99_ms')}ms @ SLO {fb.get('slo_p99_ms')}ms  "
                f"replicas={fb.get('replicas')}"
            )
            per = fb.get("fleet", {}).get("per_replica") \
                if isinstance(fb.get("fleet"), dict) else None
            if isinstance(per, dict):
                for rid, row in sorted(per.items()):
                    lines.append(
                        f"    {rid}: {_fmt_num(row.get('qps', 0))} qps  "
                        f"p99={row.get('p99_ms')}ms  "
                        f"requests={row.get('requests')}  "
                        f"cache_hit_rate={row.get('cache_hit_rate')}"
                    )
            aff = fb.get("affinity")
            if isinstance(aff, dict):
                lines.append(
                    f"    affinity: hit_rate={aff.get('affinity_hit_rate')} "
                    f"vs random={aff.get('random_hit_rate')} "
                    f"@ {_fmt_num(aff.get('offered_qps', 0))} qps"
                )
            hg = fb.get("hedge")
            if isinstance(hg, dict):
                lines.append(
                    f"    hedge: p99={hg.get('p99_ms')}ms vs "
                    f"no-hedge={hg.get('nohedge_p99_ms')}ms  "
                    f"rate={hg.get('hedge_rate_pct')}% "
                    f"(budget {hg.get('budget_pct')}%)  "
                    f"won={hg.get('hedge_won')}/{hg.get('hedged')}"
                )

    # hybrid placement: run records carry a `placement` decision when the
    # mode was hybrid/auto (including auto runs that resolved back to
    # uniform, with the reason); bench records carry the skewed scaling
    # leg's uniform-vs-hybrid exchange comparison
    placement_rows = []
    for r in runs:
        pl = r.get("placement")
        if isinstance(pl, dict):
            if r.get("comm_dtype"):
                pl = {**pl, "comm_dtype": r["comm_dtype"]}
            placement_rows.append((r.get("ts", "?"), "run  ", pl, None))
    for r in ledger.records("bench"):
        p = r.get("payload") if isinstance(r.get("payload"), dict) else {}
        scal = (p or {}).get("scaling")
        sk = scal.get("skewed") if isinstance(scal, dict) else None
        if isinstance(sk, dict):
            placement_rows.append(
                (r.get("ts", "?"), "bench", sk.get("decision") or {}, sk))
    if placement_rows:
        lines.append("")
        lines.append("hybrid placement (newest last):")
        for ts, kind, pl, sk in placement_rows[-5:]:
            cov = pl.get("coverage")
            lines.append(
                f"  {ts}  {kind}  mode={pl.get('mode', 'hybrid')}  "
                f"cut={pl.get('cut')}  "
                f"replicated_rows={pl.get('replicated_rows', pl.get('cut'))}  "
                f"coverage="
                + (f"{cov:.3f}" if isinstance(cov, (int, float)) else "n/a")
                + (f"  wire={pl['comm_dtype']}" if pl.get("comm_dtype")
                   else "")
            )
            if pl.get("reason"):
                lines.append(f"    reason: {pl['reason']}")
            pred = pl.get("predicted_exchange_bytes")
            meas = pl.get("measured_exchange_bytes")
            if pred is not None or meas is not None:
                lines.append(
                    f"    exchange bytes: predicted={_fmt_num(pred or 0)}B  "
                    f"uniform={_fmt_num(pl.get('predicted_uniform_bytes', 0))}B"
                    f"  measured={_fmt_num(meas or 0)}B"
                )
            if sk is not None and isinstance(sk.get("per_dtype"), dict):
                for dt, row in sorted(sk["per_dtype"].items()):
                    red = row.get("exchange_reduction")
                    lines.append(
                        f"    skewed[{dt}]: "
                        f"uniform={_fmt_num(row.get('uniform_exchange_bytes', 0))}B  "
                        f"hybrid={_fmt_num(row.get('hybrid_exchange_bytes', 0))}B  "
                        "reduction="
                        + (f"{red:.2f}x" if isinstance(red, (int, float))
                           else "n/a")
                        + f"  loss_delta={row.get('loss_delta')}"
                    )

    # sharded optimizer state: bench records carrying the zero lane's HBM
    # census + grad-reduce exchange + parity block
    zero_rows = [
        (r.get("ts", "?"), r["payload"]["zero"])
        for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and isinstance(r["payload"].get("zero"), dict)
        and not r["payload"]["zero"].get("skipped")
    ]
    if zero_rows:
        lines.append("")
        lines.append("sharded optimizer state (zero; newest last):")
        for ts, z in zero_rows[-5:]:
            hbm = z.get("hbm") or {}
            gr = z.get("grad_reduce") or {}
            red = hbm.get("reduction")
            lines.append(
                f"  {ts}  devices={z.get('n_devices')} "
                f"(data={(z.get('mesh') or {}).get('data')})  "
                f"hbm/replica={_fmt_num(hbm.get('replicated_bytes', 0))}B"
                f"->{_fmt_num(hbm.get('sharded_bytes_per_replica', 0))}B  "
                "reduction="
                + (f"{red:.2f}x" if isinstance(red, (int, float)) else "n/a")
            )
            lines.append(
                f"    grad reduce: psum={_fmt_num(gr.get('baseline_bytes', 0))}B"
                f"  zero={_fmt_num(gr.get('zero_bytes', 0))}B  "
                f"loss_parity={z.get('loss_parity_f32')}  "
                f"ckpt_identical={z.get('checkpoint_identical')}"
            )
            ov = z.get("overlap")
            if isinstance(ov, dict):
                split = ov.get("step_split_est") or {}
                lines.append(
                    f"    overlap2: {_fmt_num(ov.get('aggregate_words_per_sec', 0))} words/s "
                    f"({ov.get('speedup_vs_sequential')}x vs sequential)  "
                    f"collective_frac={split.get('collective_frac')}"
                )

    outages = ledger.records("outage")
    if outages:
        lines.append("")
        lines.append(f"outages ({len(outages)} recorded, newest last):")
        for r in outages[-5:]:
            lines.append(
                f"  {r.get('ts', '?')}  probe={_fmt_num(r.get('probe_duration_s', 0))}s"
                f"  rc={r.get('rc')}  {r.get('error', '')[:90]}"
            )

    boxes = ledger.records("blackbox")
    if boxes:
        lines.append("")
        lines.append("black-box dumps (newest last):")
        for r in boxes[-5:]:
            lines.append(
                f"  {r.get('ts', '?')}  reason={r.get('reason')}  "
                f"steps={r.get('first_step')}..{r.get('last_step')}  "
                f"file={r.get('dump_path')}"
            )
    return "\n".join(lines)


# failure-timeline view: every kind that marks something going wrong (or a
# chaos drill making it go wrong on purpose), interleaved with run records
# for context — `ledger-report --failures`
FAILURE_KINDS = ("outage", "chaos", "blackbox", "cache_error", "overload",
                 "retry_exhausted", "breaker", "degraded", "membership",
                 "hedge", "drain", "freshness_gap", "slo_burn",
                 "trace_anomaly", "drift", "scale_hint", "transport")


def _failure_line(r: Dict) -> str:
    kind = r.get("kind", "?")
    ts = r.get("ts", "?")
    if kind == "outage":
        what = r.get("error") or r.get("reason") or ""
        probe = r.get("probe")
        extra = f" probe={probe}" if probe else ""
        step = r.get("step")
        extra += f" step={step}" if step is not None else ""
        return f"  {ts}  OUTAGE   {extra.strip()}  {str(what)[:90]}"
    if kind == "chaos":
        return (
            f"  {ts}  CHAOS    fault={r.get('fault')} step={r.get('step')}"
            f" seed={r.get('seed')}"
            + (f"  {r.get('detail')}" if r.get("detail") else "")
        )
    if kind == "blackbox":
        return (
            f"  {ts}  BLACKBOX reason={r.get('reason')} "
            f"steps={r.get('first_step')}..{r.get('last_step')}  "
            f"{r.get('dump_path')}"
        )
    if kind == "cache_error":
        return (
            f"  {ts}  CKPT/CACHE-ERROR source={r.get('source', 'bench-cache')}"
            f"  {str(r.get('error', ''))[:90]}"
        )
    if kind == "overload":
        return (
            f"  {ts}  OVERLOAD kernel={r.get('kernel')} "
            f"shed_total={r.get('shed_total')} "
            f"queue_depth={r.get('queue_depth')}"
        )
    if kind == "retry_exhausted":
        return (
            f"  {ts}  RETRY-EXHAUSTED op={r.get('op')} "
            f"attempts={r.get('attempts')} "
            f"elapsed={_fmt_num(r.get('elapsed_ms', 0))}ms "
            f"reason={r.get('reason')}  {str(r.get('error', ''))[:70]}"
        )
    if kind == "breaker":
        snap = ""
        if r.get("to") == "closed" and r.get("last_recovery_latency_ms"):
            snap = f"  recovered_in={r['last_recovery_latency_ms']}ms"
        return (
            f"  {ts}  BREAKER  kernel={r.get('kernel')} "
            f"{r.get('from')}->{r.get('to')} "
            f"trips={r.get('trips')}{snap}"
        )
    if kind == "degraded":
        return (
            f"  {ts}  DEGRADED kernel={r.get('kernel')} "
            f"reason={r.get('reason')} rows={r.get('rows')} "
            f"total={r.get('degraded_total')}"
        )
    if kind == "hedge":
        # the fleet router's rate-limited tail-hedge stream (first + every
        # 100th, like the engine's overload/degraded streams)
        return (
            f"  {ts}  HEDGE    kernel={r.get('kernel')} "
            f"{r.get('primary')}->{r.get('hedge')} "
            f"budget={_fmt_num(r.get('budget_ms', 0))}ms "
            f"total={r.get('hedged_total')} "
            f"rate={r.get('hedge_rate_pct')}%"
        )
    if kind == "drain":
        if r.get("phase") == "complete":
            return (
                f"  {ts}  DRAIN    {r.get('replica')} complete "
                f"waited={_fmt_num(r.get('waited_ms', 0))}ms "
                f"clean={r.get('clean')} "
                f"remaining={r.get('remaining_replicas')}"
            )
        return (
            f"  {ts}  DRAIN    {r.get('replica')} start "
            f"inflight={r.get('inflight')} "
            f"remaining={r.get('remaining_replicas')}"
        )
    if kind == "freshness_gap":
        # delta-subscriber breakpoints (freshness/subscriber.py): phase
        # "detect" is the gap/crc/restart trigger; phase "fallback" is the
        # full-reload recovery that follows it
        if r.get("phase") == "fallback":
            return (
                f"  {ts}  FRESHNESS-FALLBACK reason={r.get('reason')} "
                f"recovered={r.get('recovered')} "
                f"version={r.get('version')} "
                f"reseq={r.get('resubscribed_seq')} "
                f"floor_step={r.get('floor_step')}"
            )
        return (
            f"  {ts}  DELTA-GAP  source={r.get('source')} "
            f"reason={r.get('reason')} "
            f"next_seq={r.get('next_seq')} "
            f"applied_seq={r.get('applied_seq')} "
            f"fallbacks={r.get('fallbacks')}"
            + (f"  {str(r.get('error', ''))[:70]}" if r.get("error") else "")
        )
    if kind == "slo_burn":
        # the SLO tracker's transition-edged burn alerts (telemetry/slo.py):
        # one line when a kernel ENTERS the alerting state, not per request
        return (
            f"  {ts}  SLO-BURN kernel={r.get('kernel')} "
            f"source={r.get('source')} "
            f"burn={r.get('burn_short')}/{r.get('burn_long')} "
            f"(alert>={r.get('alert_burn')}) "
            f"budget_left={r.get('budget_remaining_pct')}% "
            f"slo={r.get('slo_latency_ms')}ms@{r.get('slo_availability')}"
        )
    if kind == "trace_anomaly":
        # the request tracer's rate-limited anomaly stream (first + every
        # 100th kept anomaly trace) — each line names a drillable trace_id
        kinds = r.get("anomalies")
        return (
            f"  {ts}  TRACE-ANOMALY kernel={r.get('kernel')} "
            f"trace={r.get('trace_id')} "
            f"kinds={','.join(kinds) if isinstance(kinds, list) else kinds} "
            f"dur={_fmt_num(r.get('dur_ms', 0))}ms "
            f"total={r.get('anomalies_total')}"
        )
    if kind == "drift":
        # the drift sentinel's transition-edged confirmations (telemetry/
        # drift.py): one line per incident, naming every tripped signal
        sigs = r.get("signals")
        return (
            f"  {ts}  DRIFT    step={r.get('step')} "
            f"signals={','.join(sigs) if isinstance(sigs, list) else sigs} "
            f"model={r.get('model', '?')}"
        )
    if kind == "scale_hint":
        # the SLO tracker's should_scale() advisory edge (telemetry/slo.py)
        kerns = r.get("kernels")
        return (
            f"  {ts}  SCALE-HINT source={r.get('source')} "
            f"kernels={','.join(kerns) if isinstance(kerns, list) else kerns}"
        )
    if kind == "transport":
        # the TCP layer's connection timeline (net/rpc.py clients, the
        # delta stream source, and the replica manager's drain/respawn) —
        # interleaves with membership/breaker lines so one read shows a
        # replica die, get declared lost, drained, and rejoin
        event = r.get("event", "?")
        who = r.get("replica") or r.get("peer", "?")
        if event == "conn_lost":
            return (f"  {ts}  CONN-LOST    {who}  peer={r.get('peer')}  "
                    f"{str(r.get('error', ''))[:70]}")
        if event == "reconnect":
            return (f"  {ts}  RECONNECT    {who}  peer={r.get('peer')}  "
                    f"reconnects={r.get('reconnects')}")
        if event == "drained":
            return (f"  {ts}  DRAINED      {r.get('replica')}  "
                    f"pid={r.get('pid')}")
        if event == "respawn":
            return (f"  {ts}  RESPAWN      {r.get('replica')} -> "
                    f"{r.get('replacement')}  "
                    f"incarnation={r.get('incarnation')}  "
                    f"pid={r.get('pid')}")
        if event == "proc_kill":
            return (f"  {ts}  PROC-KILL    {who}  pid={r.get('pid')}")
        if event == "partition":
            return (f"  {ts}  PARTITION    {who}  "
                    f"duration={_fmt_num(r.get('duration_ms', 0))}ms")
        extra = f"  source={r.get('source')}" if r.get("source") else ""
        return f"  {ts}  TRANSPORT    {event} {who}{extra}"
    if kind == "membership":
        # the cluster supervisor's lifecycle timeline (cluster/supervisor.py)
        action = r.get("action", "?")
        w = r.get("worker")
        if action == "worker-lost":
            return (f"  {ts}  WORKER-LOST  {w}  {r.get('reason', '')}"
                    f"  steps={r.get('steps')}")
        if action == "reassigned":
            return (f"  {ts}  REASSIGNED   {w} -> {r.get('to')}  "
                    f"ranges={r.get('ranges')}")
        if action == "straggler":
            return (f"  {ts}  STRAGGLER    {w}  "
                    f"ewma={r.get('ewma_ms')}ms vs median="
                    f"{r.get('median_ms')}ms  share->{r.get('share')}")
        if action == "straggler-clear":
            return (f"  {ts}  STRAGGLER    {w}  cleared "
                    f"(ewma={r.get('ewma_ms')}ms)")
        if action == "backup":
            return (f"  {ts}  BACKUP       {w} duplicates "
                    f"{r.get('of')} ranges={r.get('ranges')}")
        if action == "restore":
            return (f"  {ts}  MEMBERSHIP   restore frontier="
                    f"{r.get('frontier')} pool={r.get('pool')}")
        return f"  {ts}  MEMBERSHIP   {action} {w}"
    return f"  {ts}  {kind}"


def render_failures(ledger: Ledger) -> str:
    """Timeline of failure / chaos / black-box events next to run records —
    the drill-audit view: what was injected, what broke, what recovered."""
    records, bad = ledger.replay()
    lines = [f"failure timeline: {ledger.path}"]
    for warn in bad:
        lines.append(f"  WARNING: {warn}")
    shown = 0
    for r in records:
        kind = r.get("kind")
        if kind in FAILURE_KINDS:
            lines.append(_failure_line(r))
            shown += 1
        elif kind == "run":
            g = r.get("guardrail") or {}
            extra = ""
            if g.get("trips_total"):
                extra = (f"  guard: {g['trips_total']} trips, "
                         f"{g['steps_skipped']} skipped")
            if r.get("preempted"):
                extra += "  [preempted]"
            lines.append(
                f"  {r.get('ts', '?')}  run      model={r.get('model')} "
                f"steps={r.get('steps')}{extra}"
            )
        elif kind == "bench" and isinstance(r.get("payload"), dict) \
                and isinstance(r["payload"].get("chaos"), dict):
            c = r["payload"]["chaos"]
            lines.append(
                f"  {r.get('ts', '?')}  bench    chaos lane: "
                f"recovered_all={c.get('recovered_all')} "
                f"guard_overhead={c.get('guard_overhead_pct')}% "
                f"loss_parity={c.get('loss_parity')}"
            )
        elif kind == "bench" and isinstance(r.get("payload"), dict) \
                and isinstance(r["payload"].get("chaos_serve"), dict):
            c = r["payload"]["chaos_serve"]
            lines.append(
                f"  {r.get('ts', '?')}  bench    chaos-serve lane: "
                f"availability={c.get('availability_pct')}% "
                f"degraded_share={c.get('degraded_share_pct')}% "
                f"p99_under_fault={c.get('p99_under_fault_ms')}ms"
            )
        elif kind == "bench" and isinstance(r.get("payload"), dict) \
                and isinstance(r["payload"].get("chaos_cluster"), dict):
            c = r["payload"]["chaos_cluster"]
            lines.append(
                f"  {r.get('ts', '?')}  bench    chaos-cluster lane: "
                f"exact={c.get('accounting_exact')} "
                f"lost={c.get('lost_count')} dup={c.get('duplicated_count')} "
                f"reassigned={c.get('reassignments')} "
                f"loss_parity={c.get('loss_parity')}"
            )
        elif kind == "bench" and isinstance(r.get("payload"), dict) \
                and isinstance(r["payload"].get("freshness"), dict):
            c = r["payload"]["freshness"]
            gap = c.get("gap_drill") or {}
            lines.append(
                f"  {r.get('ts', '?')}  bench    freshness lane: "
                f"bit_parity={c.get('bit_parity')} "
                f"lag_p99={c.get('lag_p99_ms')}ms "
                f"serve_p99={c.get('serve_p99_ms')}ms "
                f"gap_recovered={gap.get('recovered')}"
            )
        elif kind == "bench" and isinstance(r.get("payload"), dict) \
                and isinstance(r["payload"].get("net"), dict):
            c = r["payload"]["net"]
            pk = c.get("proc_kill") or {}
            dl = c.get("delta") or {}
            lines.append(
                f"  {r.get('ts', '?')}  bench    net lane: "
                f"availability={c.get('availability_pct')}% "
                f"tcp_parity={c.get('tcp_parity')} "
                f"delta_parity={dl.get('parity')} "
                f"envelope={c.get('envelope_x')}x "
                f"respawns={c.get('respawns')} "
                f"kill_recovered={pk.get('recovered')}"
            )
    if shown == 0:
        lines.append("  (no failure events recorded)")
    return "\n".join(lines)


def check_regression(
    ledger: Ledger,
    max_drop_pct: float,
    baseline: Optional[float] = None,
) -> Tuple[int, str]:
    """Bench gate: newest *measured* bench value vs the pinned baseline.

    ``baseline``: explicit pinned words/sec value; default is the best value
    among all earlier measured (non-cached, non-reconstructed, on-chip —
    CPU smoke runs never count) bench records. Returns ``(exit_code,
    message)`` — nonzero when the newest run is more than ``max_drop_pct``
    percent below the baseline (or nothing to gate on).
    """
    measured = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and not r["payload"].get("cached")
        and not r["payload"].get("reconstructed")
        and r["payload"].get("platform") != "cpu"
        and isinstance(r["payload"].get("value"), (int, float))
        and r["payload"]["value"] > 0
    ]
    if not measured:
        msg = "check-regression: no measured bench record in ledger"
        # chaos recovery is gated on correctness, not measured perf — a CPU
        # chaos-lane record must still be able to fail (or pass) CI here;
        # the serve lane gates same-platform, so CPU records count there too
        c_rc, c_msg = _check_chaos_regression(ledger)
        if c_msg:
            msg = f"{msg}\n{c_msg}"
        v_rc, v_msg = _check_serving_regression(ledger, max_drop_pct)
        if v_msg:
            msg = f"{msg}\n{v_msg}"
        f_rc, f_msg = _check_fleet_regression(ledger, max_drop_pct)
        if f_msg:
            msg = f"{msg}\n{f_msg}"
        t_rc, t_msg = _check_tiered_regression(ledger, max_drop_pct)
        if t_msg:
            msg = f"{msg}\n{t_msg}"
        a_rc, a_msg = _check_chaos_serve_regression(ledger)
        if a_msg:
            msg = f"{msg}\n{a_msg}"
        k_rc, k_msg = _check_chaos_cluster_regression(ledger)
        if k_msg:
            msg = f"{msg}\n{k_msg}"
        p_rc, p_msg = _check_placement_regression(ledger)
        if p_msg:
            msg = f"{msg}\n{p_msg}"
        q_rc, q_msg = _check_quantized_wire_regression(ledger)
        if q_msg:
            msg = f"{msg}\n{q_msg}"
        n_rc, n_msg = _check_freshness_regression(ledger)
        if n_msg:
            msg = f"{msg}\n{n_msg}"
        o_rc, o_msg = _check_trace_overhead_regression(ledger)
        if o_msg:
            msg = f"{msg}\n{o_msg}"
        d_rc, d_msg = _check_drift_regression(ledger)
        if d_msg:
            msg = f"{msg}\n{d_msg}"
        w_rc, w_msg = _check_profiler_overhead_regression(ledger)
        if w_msg:
            msg = f"{msg}\n{w_msg}"
        z_rc, z_msg = _check_zero_regression(ledger)
        if z_msg:
            msg = f"{msg}\n{z_msg}"
        e_rc, e_msg = _check_net_regression(ledger)
        if e_msg:
            msg = f"{msg}\n{e_msg}"
        return max(
            2, c_rc, v_rc, f_rc, t_rc, a_rc, k_rc, p_rc, q_rc, n_rc,
            o_rc, d_rc, w_rc, z_rc, e_rc), msg
    newest = measured[-1]["payload"]["value"]
    if baseline is None:
        earlier = [r["payload"]["value"] for r in measured[:-1]]
        if not earlier:
            msg = (
                f"check-regression: single measured record "
                f"(value={newest:,.1f}); nothing to compare against"
            )
            # the correctness/latency lanes still gate (CPU records count)
            c_rc, c_msg = _check_chaos_regression(ledger)
            if c_msg:
                msg = f"{msg}\n{c_msg}"
            v_rc, v_msg = _check_serving_regression(ledger, max_drop_pct)
            if v_msg:
                msg = f"{msg}\n{v_msg}"
            f_rc, f_msg = _check_fleet_regression(ledger, max_drop_pct)
            if f_msg:
                msg = f"{msg}\n{f_msg}"
            t_rc, t_msg = _check_tiered_regression(ledger, max_drop_pct)
            if t_msg:
                msg = f"{msg}\n{t_msg}"
            a_rc, a_msg = _check_chaos_serve_regression(ledger)
            if a_msg:
                msg = f"{msg}\n{a_msg}"
            k_rc, k_msg = _check_chaos_cluster_regression(ledger)
            if k_msg:
                msg = f"{msg}\n{k_msg}"
            p_rc, p_msg = _check_placement_regression(ledger)
            if p_msg:
                msg = f"{msg}\n{p_msg}"
            q_rc, q_msg = _check_quantized_wire_regression(ledger)
            if q_msg:
                msg = f"{msg}\n{q_msg}"
            n_rc, n_msg = _check_freshness_regression(ledger)
            if n_msg:
                msg = f"{msg}\n{n_msg}"
            o_rc, o_msg = _check_trace_overhead_regression(ledger)
            if o_msg:
                msg = f"{msg}\n{o_msg}"
            d_rc, d_msg = _check_drift_regression(ledger)
            if d_msg:
                msg = f"{msg}\n{d_msg}"
            w_rc, w_msg = _check_profiler_overhead_regression(ledger)
            if w_msg:
                msg = f"{msg}\n{w_msg}"
            z_rc, z_msg = _check_zero_regression(ledger)
            if z_msg:
                msg = f"{msg}\n{z_msg}"
            e_rc, e_msg = _check_net_regression(ledger)
            if e_msg:
                msg = f"{msg}\n{e_msg}"
            return max(
                0, c_rc, v_rc, f_rc, t_rc, a_rc, k_rc, p_rc, q_rc, n_rc,
                o_rc, d_rc, w_rc, z_rc, e_rc), msg
        baseline = max(earlier)
    floor = baseline * (1.0 - max_drop_pct / 100.0)
    if newest < floor:
        rc, msg = 1, (
            f"REGRESSION: newest value {newest:,.1f} is "
            f"{(1 - newest / baseline) * 100:.1f}% below baseline "
            f"{baseline:,.1f} (allowed {max_drop_pct:.1f}%)"
        )
    else:
        rc, msg = 0, (
            f"ok: newest value {newest:,.1f} vs baseline {baseline:,.1f} "
            f"({(newest / baseline - 1) * 100:+.1f}%, floor {floor:,.1f})"
        )
    s_rc, s_msg = _check_scaling_regression(measured, max_drop_pct)
    if s_msg:
        msg = f"{msg}\n{s_msg}"
    c_rc, c_msg = _check_chaos_regression(ledger)
    if c_msg:
        msg = f"{msg}\n{c_msg}"
    v_rc, v_msg = _check_serving_regression(ledger, max_drop_pct)
    if v_msg:
        msg = f"{msg}\n{v_msg}"
    f_rc, f_msg = _check_fleet_regression(ledger, max_drop_pct)
    if f_msg:
        msg = f"{msg}\n{f_msg}"
    t_rc, t_msg = _check_tiered_regression(ledger, max_drop_pct)
    if t_msg:
        msg = f"{msg}\n{t_msg}"
    a_rc, a_msg = _check_chaos_serve_regression(ledger)
    if a_msg:
        msg = f"{msg}\n{a_msg}"
    k_rc, k_msg = _check_chaos_cluster_regression(ledger)
    if k_msg:
        msg = f"{msg}\n{k_msg}"
    p_rc, p_msg = _check_placement_regression(ledger)
    if p_msg:
        msg = f"{msg}\n{p_msg}"
    q_rc, q_msg = _check_quantized_wire_regression(ledger)
    if q_msg:
        msg = f"{msg}\n{q_msg}"
    n_rc, n_msg = _check_freshness_regression(ledger)
    if n_msg:
        msg = f"{msg}\n{n_msg}"
    o_rc, o_msg = _check_trace_overhead_regression(ledger)
    if o_msg:
        msg = f"{msg}\n{o_msg}"
    d_rc, d_msg = _check_drift_regression(ledger)
    if d_msg:
        msg = f"{msg}\n{d_msg}"
    w_rc, w_msg = _check_profiler_overhead_regression(ledger)
    if w_msg:
        msg = f"{msg}\n{w_msg}"
    z_rc, z_msg = _check_zero_regression(ledger)
    if z_msg:
        msg = f"{msg}\n{z_msg}"
    e_rc, e_msg = _check_net_regression(ledger)
    if e_msg:
        msg = f"{msg}\n{e_msg}"
    return max(
        rc, s_rc, c_rc, v_rc, f_rc, t_rc, a_rc, k_rc, p_rc, q_rc, n_rc,
        o_rc, d_rc, w_rc, z_rc, e_rc), msg


def _scaling_value(record: Dict) -> Optional[float]:
    """Gateable number from a bench payload's ``scaling`` block (aggregate
    f32 words/sec across the mesh), or None when the lane didn't run."""
    scal = record.get("payload", {}).get("scaling")
    if not isinstance(scal, dict):
        return None
    v = scal.get("aggregate_words_per_sec")
    return float(v) if isinstance(v, (int, float)) and v > 0 else None


def _check_scaling_regression(
    measured: List[Dict], max_drop_pct: float
) -> Tuple[int, Optional[str]]:
    """Gate the scale-out lane's aggregate words/sec alongside the headline.

    Only measured records that carried a populated ``scaling`` block count;
    a ledger without any (pre-lane history) or with a single one gates
    nothing — the lane must not be able to fail CI before it has a
    comparable history.
    """
    with_scaling = [
        (r, _scaling_value(r)) for r in measured if _scaling_value(r)
    ]
    if not with_scaling:
        return 0, None
    newest_rec, newest = with_scaling[-1]
    if measured and measured[-1] is not newest_rec:
        return 0, (
            "scaling: newest measured record has no scaling block "
            f"(last seen {newest:,.1f} aggregate words/s)"
        )
    earlier = [v for _, v in with_scaling[:-1]]
    if not earlier:
        return 0, (
            f"scaling: single measured record (aggregate {newest:,.1f} "
            "words/s); nothing to compare against"
        )
    baseline = max(earlier)
    floor = baseline * (1.0 - max_drop_pct / 100.0)
    if newest < floor:
        return 1, (
            f"scaling REGRESSION: aggregate {newest:,.1f} words/s is "
            f"{(1 - newest / baseline) * 100:.1f}% below baseline "
            f"{baseline:,.1f} (allowed {max_drop_pct:.1f}%)"
        )
    return 0, (
        f"scaling ok: aggregate {newest:,.1f} vs baseline {baseline:,.1f} "
        f"words/s ({(newest / baseline - 1) * 100:+.1f}%)"
    )


# the skewed scaling leg must keep cutting audited exchange bytes by at
# least this factor (uniform / hybrid) at every comm dtype it ran
_SKEWED_EXCHANGE_FLOOR = 2.0


def _check_placement_regression(ledger: Ledger) -> Tuple[int, Optional[str]]:
    """Gate the skewed lane's exchange-byte win alongside the perf headline.

    The numbers are compiled-HLO collective bytes (telemetry/audit.py) —
    static shapes, platform-independent — so CPU lane runs count, same as
    the chaos gates. A ledger with no skewed block (pre-lane history) gates
    nothing."""
    with_skew = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and isinstance(r["payload"].get("scaling"), dict)
        and isinstance(r["payload"]["scaling"].get("skewed"), dict)
    ]
    if not with_skew:
        return 0, None
    sk = with_skew[-1]["payload"]["scaling"]["skewed"]
    per = sk.get("per_dtype")
    if not isinstance(per, dict) or not per:
        return 1, ("placement REGRESSION: skewed leg ran but recorded no "
                   "per-dtype exchange rows")
    bad = []
    worst = None
    for dt, row in sorted(per.items()):
        red = row.get("exchange_reduction")
        if not isinstance(red, (int, float)):
            bad.append(f"{dt}=n/a")
            continue
        worst = red if worst is None else min(worst, red)
        if red < _SKEWED_EXCHANGE_FLOOR:
            bad.append(f"{dt}={red:.2f}x")
    if bad:
        return 1, (
            "placement REGRESSION: skewed-lane exchange reduction below the "
            f"{_SKEWED_EXCHANGE_FLOOR:.1f}x floor: " + ", ".join(bad)
        )
    return 0, (
        f"placement ok: skewed-lane exchange reduction >= "
        f"{_SKEWED_EXCHANGE_FLOOR:.1f}x at every comm dtype "
        f"(worst {worst:.2f}x)"
    )


# the int4 wire must keep its audited exchange-byte win vs the f32 wire on
# the scaling lane (codes pack two per byte; scales ride as bf16 words),
# and its short-run loss must stay within 1% of the f32 lane's
_INT4_PAYLOAD_FLOOR = 6.0
_INT4_LOSS_PARITY_MAX = 0.01


def _check_quantized_wire_regression(
    ledger: Ledger,
) -> Tuple[int, Optional[str]]:
    """Gate the int4 wire on the scaling lane: the newest bench record whose
    ``scaling.per_dtype`` carries an ``int4`` row must show an audited
    exchange-byte reduction vs the f32 wire of at least
    ``_INT4_PAYLOAD_FLOOR`` with loss parity within
    ``_INT4_LOSS_PARITY_MAX``. The bytes are compiled-HLO collective shapes
    (platform-independent), so CPU lane runs gate the same as the placement
    check. No int4 history gates nothing."""
    with_int4 = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and isinstance(r["payload"].get("scaling"), dict)
        and isinstance(r["payload"]["scaling"].get("per_dtype"), dict)
        and isinstance(
            r["payload"]["scaling"]["per_dtype"].get("int4"), dict)
    ]
    if not with_int4:
        return 0, None
    row = with_int4[-1]["payload"]["scaling"]["per_dtype"]["int4"]
    red = row.get("payload_reduction_vs_f32")
    parity = row.get("loss_parity_vs_f32")
    problems = []
    if not (isinstance(red, (int, float)) and red >= _INT4_PAYLOAD_FLOOR):
        problems.append(
            f"audited exchange-byte reduction {red} vs f32 is below the "
            f"{_INT4_PAYLOAD_FLOOR:.1f}x floor")
    if not (isinstance(parity, (int, float))
            and parity <= _INT4_LOSS_PARITY_MAX):
        problems.append(
            f"loss parity {parity} vs f32 exceeds the "
            f"{_INT4_LOSS_PARITY_MAX} bar")
    if problems:
        return 1, "int4-wire REGRESSION: " + "; ".join(problems)
    return 0, (
        f"int4-wire ok: exchange bytes {red:.2f}x below f32 "
        f"(floor {_INT4_PAYLOAD_FLOOR:.1f}x), loss parity {parity}"
    )


# the zero lane must keep its replicated-plane HBM win (per-replica bytes
# of the optimizer/parameter planes, >= 2x at >= 2 data shards), keep the
# dense-grad reduce's audited exchange no larger than the psum baseline,
# hold f32 loss parity, and its checkpoints must stay byte-identical to the
# unsharded run's (correctness — any platform gates, hard fail)
_ZERO_HBM_FLOOR = 2.0
_ZERO_LOSS_PARITY_MAX = 0.01


def _check_zero_regression(ledger: Ledger) -> Tuple[int, Optional[str]]:
    """Gate the sharded-optimizer-state lane (``optimizer_sharding: zero``).

    The newest bench record carrying a populated ``zero`` block must show:
    replicated-plane HBM per replica reduced >= ``_ZERO_HBM_FLOOR`` when the
    lane ran on >= 2 data shards; audited dense-grad-reduce bytes no larger
    than the psum baseline (compiled-HLO shapes, platform-independent);
    f32 loss parity within ``_ZERO_LOSS_PARITY_MAX``; and
    ``checkpoint_identical`` true — a sharded run whose checkpoint differs
    from the unsharded format is a hard fail on ANY platform (restore
    compatibility is the lane's core contract). No zero history gates
    nothing."""
    with_zero = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and isinstance(r["payload"].get("zero"), dict)
        and not r["payload"]["zero"].get("skipped")
    ]
    if not with_zero:
        return 0, None
    z = with_zero[-1]["payload"]["zero"]
    problems = []
    hbm = z.get("hbm") or {}
    red = hbm.get("reduction")
    mesh_data = (z.get("mesh") or {}).get("data")
    if isinstance(mesh_data, int) and mesh_data >= 2:
        if not (isinstance(red, (int, float)) and red >= _ZERO_HBM_FLOOR):
            problems.append(
                f"replicated-plane HBM reduction {red} at data={mesh_data} "
                f"is below the {_ZERO_HBM_FLOOR:.1f}x floor")
    gr = z.get("grad_reduce") or {}
    zb, bb = gr.get("zero_bytes"), gr.get("baseline_bytes")
    if isinstance(zb, (int, float)) and isinstance(bb, (int, float)):
        if zb > bb:
            problems.append(
                f"dense-grad reduce exchange {zb:,.0f} B exceeds the psum "
                f"baseline {bb:,.0f} B")
    parity = z.get("loss_parity_f32")
    if not (isinstance(parity, (int, float))
            and parity <= _ZERO_LOSS_PARITY_MAX):
        problems.append(
            f"f32 loss parity {parity} vs unsharded exceeds the "
            f"{_ZERO_LOSS_PARITY_MAX} bar")
    if z.get("checkpoint_identical") is not True:
        problems.append(
            "checkpoint is NOT byte-identical to the unsharded run's "
            f"(checkpoint_identical={z.get('checkpoint_identical')!r})")
    if problems:
        return 1, "zero-sharding REGRESSION: " + "; ".join(problems)
    wire = (
        f"grad reduce {zb:,.0f} B <= psum {bb:,.0f} B"
        if isinstance(zb, (int, float)) and isinstance(bb, (int, float))
        else "grad reduce bytes n/a"
    )
    return 0, (
        f"zero-sharding ok: HBM {red}x/replica at data={mesh_data} "
        f"(floor {_ZERO_HBM_FLOOR:.1f}x), {wire}, loss parity {parity}, "
        "checkpoints byte-identical"
    )


def _check_freshness_regression(ledger: Ledger) -> Tuple[int, Optional[str]]:
    """Gate the freshness lane: the newest bench record carrying a
    ``freshness`` block must show bit-identical delta-applied rows vs the
    same-watermark checkpoint (correctness — any platform gates), a
    recovered gap drill, delta lag p99 under the lane's ceiling, and serve
    p99 within the SLO while deltas were applying. No freshness history
    gates nothing."""
    with_fresh = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and isinstance(r["payload"].get("freshness"), dict)
    ]
    if not with_fresh:
        return 0, None
    f = with_fresh[-1]["payload"]["freshness"]
    problems = []
    parity = f.get("bit_parity")
    if not (isinstance(parity, (int, float)) and parity == 0.0):
        problems.append(
            f"delta-applied rows are not bit-identical to the "
            f"same-watermark checkpoint (parity={parity})")
    gap = f.get("gap_drill") or {}
    if not gap.get("recovered"):
        problems.append("gap drill did not recover via full reload")
    gap_parity = gap.get("parity")
    if isinstance(gap_parity, (int, float)) and gap_parity != 0.0:
        problems.append(f"post-fallback parity {gap_parity} != 0.0")
    lag = f.get("lag_p99_ms")
    ceiling = f.get("lag_ceiling_ms")
    if (isinstance(lag, (int, float)) and isinstance(ceiling, (int, float))
            and ceiling > 0 and lag > ceiling):
        problems.append(
            f"freshness lag p99 {lag:.1f}ms above the "
            f"{ceiling:.0f}ms ceiling")
    p99 = f.get("serve_p99_ms")
    slo = f.get("slo_p99_ms")
    if (isinstance(p99, (int, float)) and isinstance(slo, (int, float))
            and slo > 0 and p99 > slo):
        problems.append(
            f"serve p99 {p99:.1f}ms above the {slo:.0f}ms SLO while "
            f"applying deltas")
    if problems:
        return 1, "freshness REGRESSION: " + "; ".join(problems)
    return 0, (
        f"freshness ok: bit parity {parity}, lag p99 "
        f"{_fmt_num(lag)}ms (ceiling {_fmt_num(ceiling)}ms), serve p99 "
        f"{_fmt_num(p99)}ms (SLO {_fmt_num(slo)}ms), gap drill recovered"
    )


def _check_net_regression(ledger: Ledger) -> Tuple[int, Optional[str]]:
    """Gate the net lane: the newest bench record carrying a ``net`` block
    must show availability at/over the floor through a SIGKILL'd replica
    with the lost -> drain -> respawn -> rejoin arc completing, a refused
    stale write on partition heal, bit parity 0.0 for both the TCP read
    path and the post-publisher-kill delta stream (correctness — any
    platform gates), and TCP serving p99 within the recorded envelope of
    the same run's in-process p99 (same platform by construction, so it
    gates anywhere too). No net history gates nothing."""
    with_net = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and isinstance(r["payload"].get("net"), dict)
    ]
    if not with_net:
        return 0, None
    n = with_net[-1]["payload"]["net"]
    problems = []
    avail = n.get("availability_pct")
    floor = n.get("availability_floor_pct", 99.0)
    if not (isinstance(avail, (int, float)) and avail >= floor):
        problems.append(
            f"availability {avail}% under proc_kill is below the "
            f"{floor}% floor")
    pk = n.get("proc_kill") or {}
    if not pk.get("recovered"):
        problems.append(
            "proc_kill drill did not recover (lost -> drain -> respawn "
            "-> rejoin arc incomplete)")
    pt = n.get("partition") or {}
    if not pt.get("stale_write_refused"):
        problems.append(
            "partitioned replica ACCEPTED a stale write on heal")
    tcp_parity = n.get("tcp_parity")
    if not (isinstance(tcp_parity, (int, float)) and tcp_parity == 0.0):
        problems.append(
            f"TCP-pulled rows are not bit-identical to the reference "
            f"(parity={tcp_parity})")
    dl = n.get("delta") or {}
    d_parity = dl.get("parity")
    if not (isinstance(d_parity, (int, float)) and d_parity == 0.0):
        problems.append(
            f"post-publisher-kill delta parity {d_parity} != 0.0")
    env = n.get("envelope_x")
    limit = n.get("envelope_limit_x")
    if (isinstance(env, (int, float)) and isinstance(limit, (int, float))
            and limit > 0 and env > limit):
        problems.append(
            f"TCP serving p99 is {env:.1f}x in-process "
            f"(envelope {limit:.0f}x)")
    if problems:
        return 1, "net REGRESSION: " + "; ".join(problems)
    return 0, (
        f"net ok: availability {_fmt_num(avail)}% through proc_kill "
        f"(floor {_fmt_num(floor)}%), stale write refused on heal, TCP "
        f"parity {tcp_parity}, delta parity {d_parity}, envelope "
        f"{_fmt_num(env)}x (limit {_fmt_num(limit)}x)"
    )


def _check_chaos_regression(ledger: Ledger) -> Tuple[int, Optional[str]]:
    """Gate the chaos lane's *recovery* alongside the perf headline: the
    newest bench record carrying a ``chaos`` block (any platform — recovery
    is correctness, so CPU lane runs count) must have recovered every drill
    and held resume loss parity. No chaos history gates nothing."""
    with_chaos = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and isinstance(r["payload"].get("chaos"), dict)
    ]
    if not with_chaos:
        return 0, None
    c = with_chaos[-1]["payload"]["chaos"]
    problems = []
    if not c.get("recovered_all"):
        bad = [k for k, v in (c.get("drills") or {}).items()
               if not v.get("recovered")]
        problems.append(
            "unrecovered chaos drill(s): " + (", ".join(bad) or "unknown"))
    parity = c.get("loss_parity")
    if isinstance(parity, (int, float)) and parity > 0.05:
        problems.append(f"resume loss parity {parity:.4f} > 0.05")
    if problems:
        return 1, "chaos REGRESSION: " + "; ".join(problems)
    return 0, (
        f"chaos ok: all drills recovered, guard overhead "
        f"{c.get('guard_overhead_pct')}%, resume loss parity {parity}"
    )


def _check_chaos_serve_regression(ledger: Ledger) -> Tuple[int, Optional[str]]:
    """Gate the chaos-serve lane's *availability* alongside the perf
    headline: the newest bench record carrying a ``chaos_serve`` block (any
    platform — availability under fault is correctness, so CPU lane runs
    count) must hold the lane's availability floor, prove the unprotected
    control actually hard-fails, and reject the corrupt-reload drill. No
    chaos-serve history gates nothing."""
    with_cs = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and isinstance(r["payload"].get("chaos_serve"), dict)
    ]
    if not with_cs:
        return 0, None
    c = with_cs[-1]["payload"]["chaos_serve"]
    avail = c.get("availability_pct")
    floor = c.get("floor_pct", 99.0)
    problems = []
    if not (isinstance(avail, (int, float)) and avail >= floor):
        problems.append(
            f"availability {avail}% under fault is below the "
            f"{floor}% floor")
    if not c.get("unprotected_hard_failure", True):
        problems.append(
            "breakers-off control leg did NOT hard-fail (fault matrix "
            "is not exercising the serve path)")
    if not c.get("reload_corrupt_rejected", True):
        problems.append("corrupt-reload drill was not rejected")
    if c.get("tier_bitflip") is not None and not (
            c["tier_bitflip"] or {}).get("recovered"):
        problems.append("tier_bitflip drill did not recover")
    if problems:
        return 1, "chaos-serve REGRESSION: " + "; ".join(problems)
    return 0, (
        f"chaos-serve ok: availability {avail:.2f}% (floor {floor}%), "
        f"degraded share {c.get('degraded_share_pct')}%, "
        f"p99 under fault {c.get('p99_under_fault_ms')}ms"
    )


def _check_chaos_cluster_regression(
    ledger: Ledger,
) -> Tuple[int, Optional[str]]:
    """Gate the chaos-cluster lane's exactly-once proof alongside the perf
    headline: the newest bench record carrying a ``chaos_cluster`` block
    (any platform — batch accounting is correctness, so CPU lane runs
    count) must show zero lost and zero double-applied batches under the
    kill/slow/partition storm, a detected + reassigned worker loss, loss
    parity within the lane's bar, and an unprotected control leg that
    demonstrably lost its dead worker's range. No chaos-cluster history
    gates nothing."""
    with_cc = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict)
        and isinstance(r["payload"].get("chaos_cluster"), dict)
    ]
    if not with_cc:
        return 0, None
    c = with_cc[-1]["payload"]["chaos_cluster"]
    problems = []
    if c.get("lost_count", 0) or not c.get("accounting_exact", False):
        problems.append(
            f"batch accounting is not exact: lost={c.get('lost_count')} "
            f"({c.get('committed')}/{c.get('total_batches')} committed)")
    if c.get("duplicated_count", 0):
        problems.append(
            f"{c.get('duplicated_count')} batches double-applied "
            "(first-writer-wins dedup is broken)")
    if not c.get("workers_lost"):
        problems.append("no worker loss was detected under the storm")
    if not c.get("reassignments"):
        problems.append("the dead worker's range was never reassigned")
    parity = c.get("loss_parity")
    bar = c.get("parity_bar", 0.05)
    if not (isinstance(parity, (int, float)) and parity <= bar):
        problems.append(
            f"loss parity {parity} vs the undisturbed control exceeds "
            f"the {bar} bar")
    if not c.get("unprotected_hard_failure", True):
        problems.append(
            "supervisor-off control leg did NOT lose the dead worker's "
            "range (the storm is not exercising reassignment)")
    if problems:
        return 1, "chaos-cluster REGRESSION: " + "; ".join(problems)
    return 0, (
        f"chaos-cluster ok: {c.get('committed')}/{c.get('total_batches')} "
        f"exactly-once (dup_discarded={c.get('dup_discarded')}, "
        f"stale_rejected={c.get('stale_rejected')}), "
        f"{c.get('reassignments')} reassignments, "
        f"loss parity {parity}"
    )


def _serving_values(record: Dict) -> Optional[Tuple[float, Optional[float]]]:
    """(qps, p99_ms) from a bench payload's ``serving`` block, or None when
    the serve lane didn't run in that record."""
    s = record.get("payload", {}).get("serving")
    if not isinstance(s, dict):
        return None
    qps = s.get("qps")
    if not (isinstance(qps, (int, float)) and qps > 0):
        return None
    p99 = s.get("p99_ms")
    p99 = float(p99) if isinstance(p99, (int, float)) and p99 > 0 else None
    return float(qps), p99


def _check_serving_regression(
    ledger: Ledger, max_drop_pct: float
) -> Tuple[int, Optional[str]]:
    """Gate the serve lane's headline (pull qps + p99 latency) alongside the
    training headline: the newest bench record carrying a ``serving`` block
    must hold the qps floor AND the p99 ceiling against the best earlier
    record of the *same platform* (absolute latency is platform-bound, so a
    CPU record never gates a TPU one — but CPU-vs-CPU CI runs do gate).
    No serving history (or a single record) gates nothing."""
    with_serving = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict) and _serving_values(r)
    ]
    if not with_serving:
        return 0, None
    newest_rec = with_serving[-1]
    platform = newest_rec["payload"].get("platform")
    same = [r for r in with_serving
            if r["payload"].get("platform") == platform]
    qps, p99 = _serving_values(newest_rec)
    earlier = [_serving_values(r) for r in same[:-1]]
    if not earlier:
        return 0, (
            f"serving: single {platform or '?'} record (pull {qps:,.1f} qps)"
            "; nothing to compare against"
        )
    base_qps = max(q for q, _ in earlier)
    qps_floor = base_qps * (1.0 - max_drop_pct / 100.0)
    problems = []
    if qps < qps_floor:
        problems.append(
            f"pull qps {qps:,.1f} is {(1 - qps / base_qps) * 100:.1f}% below "
            f"baseline {base_qps:,.1f} (allowed {max_drop_pct:.1f}%)"
        )
    earlier_p99 = [p for _, p in earlier if p]
    if p99 is not None and earlier_p99:
        base_p99 = min(earlier_p99)
        p99_ceiling = base_p99 * (1.0 + max_drop_pct / 100.0)
        if p99 > p99_ceiling:
            problems.append(
                f"pull p99 {p99:.2f}ms is {(p99 / base_p99 - 1) * 100:.1f}% "
                f"above baseline {base_p99:.2f}ms "
                f"(allowed {max_drop_pct:.1f}%)"
            )
    if problems:
        return 1, "serving REGRESSION: " + "; ".join(problems)
    return 0, (
        f"serving ok: pull {qps:,.1f} qps / p99 {p99}ms vs "
        f"qps baseline {base_qps:,.1f} ({platform or '?'})"
    )


def _fleet_values(record: Dict) -> Optional[Tuple[float, Optional[float]]]:
    """(fleet qps, p99_ms) from a bench payload's ``fleet`` block, or None
    when the fleet lane didn't run in that record."""
    f = record.get("payload", {}).get("fleet")
    if not isinstance(f, dict):
        return None
    qps = f.get("qps")
    if not (isinstance(qps, (int, float)) and qps > 0):
        return None
    p99 = f.get("p99_ms")
    p99 = float(p99) if isinstance(p99, (int, float)) and p99 > 0 else None
    return float(qps), p99


def _check_fleet_regression(
    ledger: Ledger, max_drop_pct: float
) -> Tuple[int, Optional[str]]:
    """Gate the fleet lane alongside the perf headline. Four checks on the
    newest bench record carrying a ``fleet`` block:

    * p99 at the reported max must be inside the lane's SLO and the
      scaling ratio at/above the lane's floor (1.6x for 2 replicas) — the
      router's whole job, platform-independent, so CPU lane runs gate;
    * affinity routing's aggregate LRU hit rate must beat random spray on
      the same zipf traffic (the warm-cache win the ring exists for);
    * hedging must not make the stalled-replica leg's p99 worse than its
      no-hedge control at equal offered load;
    * fleet qps must hold its floor vs the best earlier record of the
      *same platform* (absolute qps is machine-bound, like the serve gate).

    No fleet history gates nothing."""
    with_fleet = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict) and _fleet_values(r)
    ]
    if not with_fleet:
        return 0, None
    newest_rec = with_fleet[-1]
    fb = newest_rec["payload"]["fleet"]
    qps, p99 = _fleet_values(newest_rec)
    problems = []
    slo = fb.get("slo_p99_ms")
    if isinstance(slo, (int, float)) and p99 is not None and p99 > slo:
        problems.append(
            f"p99 {p99:.2f}ms at the reported max exceeds the "
            f"{slo}ms SLO")
    scaling = fb.get("scaling_x")
    floor_x = fb.get("scaling_floor", 1.6)
    if int(fb.get("replicas") or 0) >= 2 and not (
            isinstance(scaling, (int, float)) and scaling >= floor_x):
        problems.append(
            f"scaling {scaling}x for {fb.get('replicas')} replicas is "
            f"below the {floor_x}x floor")
    aff = fb.get("affinity")
    if isinstance(aff, dict):
        a, rnd = aff.get("affinity_hit_rate"), aff.get("random_hit_rate")
        if not (isinstance(a, (int, float)) and isinstance(rnd, (int, float))
                and a > rnd):
            problems.append(
                f"affinity hit rate {a} does not beat random routing {rnd}")
    hg = fb.get("hedge")
    if isinstance(hg, dict):
        hp, cp = hg.get("p99_ms"), hg.get("nohedge_p99_ms")
        if not (isinstance(hp, (int, float)) and isinstance(cp, (int, float))
                and hp <= cp):
            problems.append(
                f"hedged p99 {hp}ms is worse than the no-hedge control "
                f"{cp}ms")
    platform = newest_rec["payload"].get("platform")
    same = [r for r in with_fleet
            if r["payload"].get("platform") == platform]
    earlier = [_fleet_values(r)[0] for r in same[:-1]]
    if earlier:
        base = max(earlier)
        qps_floor = base * (1.0 - max_drop_pct / 100.0)
        if qps < qps_floor:
            problems.append(
                f"fleet qps {qps:,.1f} is {(1 - qps / base) * 100:.1f}% "
                f"below baseline {base:,.1f} (allowed {max_drop_pct:.1f}%)")
    if problems:
        return 1, "fleet REGRESSION: " + "; ".join(problems)
    if not earlier:
        return 0, (
            f"fleet: single {platform or '?'} record ({qps:,.1f} qps, "
            f"scaling {scaling}x, p99 {p99}ms <= SLO {slo}ms); "
            "qps floor has nothing to compare against"
        )
    return 0, (
        f"fleet ok: {qps:,.1f} qps (scaling {scaling}x >= {floor_x}x, "
        f"p99 {p99}ms <= SLO {slo}ms) vs qps baseline {max(earlier):,.1f} "
        f"({platform or '?'})"
    )


def _trace_overhead_values(record: Dict) -> Optional[Dict]:
    """The ``trace_overhead`` block from a bench payload's ``fleet`` block
    (the fleet lane's tracing on-vs-off ride-along), or None when the leg
    didn't run in that record."""
    fb = record.get("payload", {}).get("fleet")
    if not isinstance(fb, dict):
        return None
    to = fb.get("trace_overhead")
    if not isinstance(to, dict):
        return None
    q, p = to.get("overhead_qps_pct"), to.get("overhead_p99_pct")
    if not (isinstance(q, (int, float)) and isinstance(p, (int, float))):
        return None
    return to


def _check_trace_overhead_regression(
    ledger: Ledger,
) -> Tuple[int, Optional[str]]:
    """Gate the observability plane's own cost: in the newest bench record
    carrying the fleet lane's ``trace_overhead`` leg, tracing on (head
    sampling + tail-keep) vs off at equal offered load must cost no more
    than the leg's ceiling (3%) of throughput or p99. The p99 comparison
    carries a noise floor: 1ms, widened to the off leg's own max-min
    spread across its repetitions (``p99_noise_ms``) when the leg ships
    one — a delta inside the baseline's self-disagreement is scheduler
    jitter, not tracing cost. Same-platform comparison is free here (both
    legs run in the same process); no history gates nothing."""
    with_to = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict) and _trace_overhead_values(r)
    ]
    if not with_to:
        return 0, None
    to = _trace_overhead_values(with_to[-1])
    ceil = float(to.get("overhead_ceil_pct", 3.0) or 3.0)
    q = float(to["overhead_qps_pct"])
    p99_off = float(to.get("p99_off_ms") or 0.0)
    p99_on = float(to.get("p99_on_ms") or 0.0)
    problems = []
    if q > ceil:
        problems.append(
            f"tracing costs {q:.2f}% of throughput at equal offered load "
            f"(ceiling {ceil}%)")
    noise = float(to.get("p99_noise_ms") or 0.0)
    if (p99_on - p99_off) > max(ceil / 100.0 * p99_off, 1.0, noise):
        problems.append(
            f"tracing p99 {p99_on}ms vs {p99_off}ms off exceeds the "
            f"{ceil}% ceiling (noise floor {max(1.0, noise):.1f}ms)")
    if problems:
        return 1, "trace-overhead REGRESSION: " + "; ".join(problems)
    return 0, (
        f"trace-overhead ok: qps {q:+.2f}%, p99 {p99_off}->{p99_on}ms "
        f"at sample rate {to.get('sample_rate')} (ceiling {ceil}%)"
    )


def _drift_block(record: Dict) -> Optional[Dict]:
    d = record.get("payload", {}).get("drift")
    return d if isinstance(d, dict) else None


def _check_drift_regression(ledger: Ledger) -> Tuple[int, Optional[str]]:
    """Gate the drift drill: the newest bench record carrying a ``drift``
    block (the ``--lane drift`` / ``tools/chaos_drill.py --drift`` leg) must
    show the injected ``slow_step`` chaos *detected* within the configured
    window, exactly one transition-edged ``drift`` ledger event, a complete
    incident bundle (timeseries window + blackbox + fingerprint), and the
    before/after ``--diff`` attribution naming host-blocked as dominant.
    Correctness, not perf — gated on any platform; no history gates
    nothing."""
    with_drift = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict) and _drift_block(r)
    ]
    if not with_drift:
        return 0, None
    d = _drift_block(with_drift[-1])
    problems = []
    if not d.get("detected"):
        problems.append(
            "injected slow_step drift was NOT detected within the window")
    ev = d.get("drift_events")
    if ev != 1:
        problems.append(
            f"expected exactly one transition-edged drift event, got {ev}")
    if not d.get("bundle_complete"):
        problems.append(
            "incident bundle incomplete (needs timeseries + blackbox + "
            "fingerprint)")
    dom = (d.get("attribution") or {}).get("dominant")
    if dom != "host_blocked":
        problems.append(
            f"--diff attribution named {dom!r} dominant, expected "
            "host_blocked")
    if problems:
        return 1, "drift REGRESSION: " + "; ".join(problems)
    return 0, (
        f"drift ok: detected at step {d.get('detect_step')} "
        f"(injected at {d.get('inject_step')}), 1 transition-edged event, "
        "bundle complete, --diff dominant=host_blocked"
    )


def _profile_overhead_block(record: Dict) -> Optional[Dict]:
    po = record.get("payload", {}).get("profile_overhead")
    return po if isinstance(po, dict) else None


def _check_profiler_overhead_regression(
    ledger: Ledger,
) -> Tuple[int, Optional[str]]:
    """Gate the continuous profiler's own cost, mirroring the fleet lane's
    trace-overhead leg: in the newest bench record carrying a
    ``profile_overhead`` block, profiling on (sampler + sentinel at the
    drill cadence) vs off at equal work must cost no more than the block's
    ceiling (3%) of words/sec. The comparison carries a noise floor — the
    off leg's own best-vs-worst spread across repetitions (``noise_pct``)
    when the block ships one; a delta inside the baseline's
    self-disagreement is scheduler jitter, not profiler cost. Same-process
    comparison, so same-platform is free; no history gates nothing."""
    with_po = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict) and _profile_overhead_block(r)
    ]
    if not with_po:
        return 0, None
    po = _profile_overhead_block(with_po[-1])
    ceil = float(po.get("overhead_ceil_pct", 3.0) or 3.0)
    pct = po.get("overhead_pct")
    if not isinstance(pct, (int, float)):
        return 1, ("profiler-overhead REGRESSION: block carries no "
                   "overhead_pct")
    noise = float(po.get("noise_pct") or 0.0)
    if pct > max(ceil, noise):
        return 1, (
            f"profiler-overhead REGRESSION: continuous profiling costs "
            f"{pct:.2f}% of words/sec (ceiling {ceil}%, noise floor "
            f"{noise:.2f}%)")
    return 0, (
        f"profiler-overhead ok: {pct:+.2f}% of words/sec at cadence "
        f"{po.get('cadence')} (ceiling {ceil}%, noise floor {noise:.2f}%)"
    )


def _tiered_values(record: Dict) -> Optional[Tuple[float, bool]]:
    """(words_per_sec, parity_ok) from a bench payload's ``tiered`` block, or
    None when the tiered lane didn't run in that record. ``parity_ok``
    collapses the lane's correctness flags: equal-vocab bit-parity AND the
    over-budget train->checkpoint->serve round trip."""
    t = record.get("payload", {}).get("tiered")
    if not isinstance(t, dict):
        return None
    wps = t.get("words_per_sec")
    if not (isinstance(wps, (int, float)) and wps > 0):
        return None
    parity = bool(t.get("parity_bit_identical")) and bool(t.get("round_trip_ok"))
    return float(wps), parity


_TIERED_RESIDENT_FLOOR = 0.95  # equal-vocab leg: tiered words/sec vs resident


def _check_tiered_regression(
    ledger: Ledger, max_drop_pct: float
) -> Tuple[int, Optional[str]]:
    """Gate the tiered lane: the newest bench record carrying a ``tiered``
    block must hold bit-parity + the over-budget round trip (correctness —
    gated on ANY platform, like chaos recovery), keep the equal-vocab leg at
    >= ``_TIERED_RESIDENT_FLOOR`` of resident speed (any platform; older
    records without the ratio are not gated on it), and hold its words/sec
    floor against the best earlier record of the same platform. No tiered
    history gates nothing."""
    with_tiered = [
        r for r in ledger.records("bench")
        if isinstance(r.get("payload"), dict) and _tiered_values(r)
    ]
    if not with_tiered:
        return 0, None
    newest_rec = with_tiered[-1]
    wps, parity = _tiered_values(newest_rec)
    if not parity:
        return 1, (
            "tiered REGRESSION: newest lane record failed bit-parity or the "
            "over-budget round trip (correctness gate)")
    # quantized-master (int8) leg: correctness + capacity, any platform.
    # Older records without the block are not gated on it.
    q = newest_rec["payload"]["tiered"].get("quantized")
    if isinstance(q, dict) and not q.get("ok"):
        bad = [k for k in ("digests_clean", "serve_requant_exact",
                           "checkpoint_dtype_f32") if not q.get(k)]
        cap = q.get("capacity_ratio_vs_f32")
        if not (isinstance(cap, (int, float)) and cap >= 2.0):
            bad.append(f"capacity_ratio_vs_f32={cap} (floor 2.0x)")
        err = q.get("master_rel_err_vs_f32")
        budget = q.get("rel_err_budget", 0.05)
        if not (isinstance(err, (int, float)) and err <= budget):
            bad.append(f"master_rel_err_vs_f32={err} (budget {budget})")
        return 1, (
            "tiered REGRESSION: quantized-master (int8) leg failed: "
            + ", ".join(bad or ["ok flag unset"]))
    ratio = newest_rec["payload"]["tiered"].get("tiered_over_resident")
    if isinstance(ratio, (int, float)) and ratio < _TIERED_RESIDENT_FLOOR:
        return 1, (
            f"tiered REGRESSION: equal-vocab leg ran at {ratio:.4f}x "
            f"resident speed (floor {_TIERED_RESIDENT_FLOOR:.2f}x) — the "
            "tier's hot path is paying per-step cost it shouldn't")
    platform = newest_rec["payload"].get("platform")
    same = [r for r in with_tiered
            if r["payload"].get("platform") == platform]
    earlier = [_tiered_values(r)[0] for r in same[:-1]]
    if not earlier:
        return 0, (
            f"tiered: single {platform or '?'} record ({wps:,.1f} words/s, "
            "parity ok); nothing to compare against"
        )
    base = max(earlier)
    floor = base * (1.0 - max_drop_pct / 100.0)
    if wps < floor:
        return 1, (
            f"tiered REGRESSION: {wps:,.1f} words/s is "
            f"{(1 - wps / base) * 100:.1f}% below baseline {base:,.1f} "
            f"(allowed {max_drop_pct:.1f}%)"
        )
    return 0, (
        f"tiered ok: {wps:,.1f} words/s vs baseline {base:,.1f} "
        f"({(wps / base - 1) * 100:+.1f}%), parity ok ({platform or '?'})"
        + (f", int8 masters {q.get('capacity_ratio_vs_f32')}x capacity"
           if isinstance(q, dict) else "")
    )


# ----------------------------------------------- regression attribution ---


def _resolve_diff_record(ledger: Ledger, spec: str) -> Tuple[Dict, str]:
    """One side of ``--diff``: an integer indexes the ledger's run records
    (negative from the end, so ``-2 -1`` is before/after the newest pair);
    anything else is a path to a JSON record/bench-payload file. Raises
    ``ValueError`` with a usable message on a bad spec."""
    try:
        idx = int(spec)
    except ValueError:
        if not os.path.exists(spec):
            raise ValueError(
                f"--diff: {spec!r} is neither a run-record index nor a file")
        with open(spec, "r", encoding="utf-8") as f:
            try:
                rec = json.load(f)
            except ValueError:
                # a one-record-per-line file: take the last parseable line
                f.seek(0)
                rec = None
                for line in f:
                    line = line.strip()
                    if line:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                if rec is None:
                    raise ValueError(f"--diff: no JSON object in {spec!r}")
        if not isinstance(rec, dict):
            raise ValueError(f"--diff: {spec!r} is not a JSON object")
        return rec, spec
    runs = ledger.records("run")
    if not runs:
        raise ValueError("--diff: ledger has no run records")
    try:
        rec = runs[idx]
    except IndexError:
        raise ValueError(
            f"--diff: run index {idx} out of range ({len(runs)} run records)")
    return rec, f"run[{idx}] {rec.get('ts', '?')} {rec.get('model', '')}"


def render_diff(rec_a: Dict, rec_b: Dict,
                label_a: str = "A", label_b: str = "B") -> str:
    """``ledger-report --diff A B``: decompose the words/sec delta between
    two run/bench records into goodput components and per-scope comm bytes,
    and name the dominant contributor (telemetry/goodput.py does the
    arithmetic; this renders it)."""
    from swiftsnails_tpu.telemetry.goodput import throughput_attribution

    att = throughput_attribution(rec_a, rec_b)
    lines = [f"perf diff: A = {label_a}", f"           B = {label_b}"]
    ra, rb = att["items_per_sec_a"], att["items_per_sec_b"]
    dp = att["delta_pct"]
    lines.append(
        "items/sec: "
        f"{_fmt_num(ra) if ra else 'n/a'} -> {_fmt_num(rb) if rb else 'n/a'}"
        + (f"  ({dp:+.2f}%)" if isinstance(dp, (int, float)) else "")
    )
    lines.append("per-step seconds by component (B - A):")
    for name in ("compute", "h2d", "host_blocked", "other", "unaccounted"):
        c = att["components"].get(name) or {}
        a_s, b_s, d_s = c.get("a_s"), c.get("b_s"), c.get("delta_s")
        if a_s is None and b_s is None:
            continue
        fmt = lambda v: f"{v * 1e3:8.3f}ms" if isinstance(v, (int, float)) \
            else "     n/a"
        mark = "  <-- dominant" if name == att.get("dominant") else ""
        lines.append(
            f"  {name:<12} {fmt(a_s)} -> {fmt(b_s)}  "
            f"delta={fmt(d_s)}{mark}")
    if att["comm_bytes"]:
        lines.append("comm bytes by scope (per audited step, B - A):")
        for scope, row in sorted(att["comm_bytes"].items()):
            lines.append(
                f"  {scope:<24} {_fmt_num(row.get('a_bytes') or 0)}B -> "
                f"{_fmt_num(row.get('b_bytes') or 0)}B  "
                f"delta={_fmt_num(row.get('delta_bytes') or 0)}B")
    dom = att.get("dominant")
    share = att.get("dominant_share")
    lines.append(
        f"dominant contributor: {dom}"
        + (f" ({share * 100:.0f}% of the per-step delta)"
           if isinstance(share, (int, float)) else "")
    )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="ledger_report",
        description="Render the run ledger; optionally gate on bench regression.",
    )
    p.add_argument(
        "path", nargs="?", default=DEFAULT_LEDGER,
        help=f"ledger JSONL (default: {DEFAULT_LEDGER})",
    )
    p.add_argument(
        "--check-regression", type=float, metavar="PCT", default=None,
        help="exit nonzero if the newest measured bench value is more than "
             "PCT%% below the pinned baseline (bench gate mode); also "
             "gates the correctness lanes on any platform — chaos "
             "recovery, freshness bit parity, and the net lane "
             "(availability through proc_kill, stale-write refusal on "
             "partition heal, TCP/delta parity, p99 envelope)",
    )
    p.add_argument(
        "--baseline", type=float, default=None,
        help="explicit pinned baseline value for --check-regression "
             "(default: best earlier measured record in the ledger)",
    )
    p.add_argument(
        "--baseline-file", default=None,
        help="JSON file whose 'value' field is the pinned baseline "
             "(e.g. a preserved BENCH_LAST_GOOD.json)",
    )
    p.add_argument(
        "--failures", action="store_true",
        help="render the failure timeline (outage/chaos/blackbox/"
             "cache_error/transport events next to run records — "
             "CONN-LOST / PARTITION / PROC-KILL / RECONNECT interleaved "
             "with the membership and breaker lines) instead of the "
             "full report",
    )
    p.add_argument(
        "--diff", nargs=2, metavar=("A", "B"), default=None,
        help="regression attribution between two records: each side is a "
             "run-record index into the ledger (negative ok; e.g. -2 -1) "
             "or a JSON record file; decomposes the words/sec delta into "
             "goodput components + per-scope comm bytes and names the "
             "dominant contributor",
    )
    args = p.parse_args(argv)
    ledger = Ledger(args.path)
    if args.diff:
        try:
            rec_a, label_a = _resolve_diff_record(ledger, args.diff[0])
            rec_b, label_b = _resolve_diff_record(ledger, args.diff[1])
        except ValueError as e:
            print(f"ledger_report: {e}")
            return 2
        print(render_diff(rec_a, rec_b, label_a, label_b))
        return 0
    if args.failures:
        print(render_failures(ledger))
        return 0
    if args.check_regression is not None:
        baseline = args.baseline
        if baseline is None and args.baseline_file:
            payload, err = load_bench_cache(args.baseline_file)
            if err:
                print(f"ledger_report: --baseline-file: {err}")
                return 2
            baseline = float(payload["value"])
        rc, msg = check_regression(ledger, args.check_regression, baseline)
        print(msg)
        return rc
    print(render_report(ledger))
    return 0
