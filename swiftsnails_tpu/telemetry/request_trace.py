"""Request-scoped distributed tracing: per-request span trees with
deterministic head sampling and always-keep tail sampling for anomalies.

The aggregate gauges (``telemetry/registry.py``) say *how often*; a request
trace says *why this one*. Each request gets a :class:`RequestContext` —
a ``trace_id`` / per-span ids / parent links plus propagable baggage — and
the read path hangs bounded child spans off it (queue wait, kernel time,
hedge attempts, re-route hops, delta apply/cutover). Capture is decided
twice:

* **head sampling** — deterministic from the trace id alone
  (``trace_sample_rate``), so the publish side and the apply side of a
  delta batch, or any two processes a trace id travels between, make the
  same keep/drop call with no coordination;
* **tail keep** — a request that turned out *interesting* (typed failure,
  hedge fired, re-route hop, degraded hit, latency over SLO, freshness
  fallback) is kept regardless (``trace_anomaly_keep``), so the traces
  you actually want to read are never sampled away.

Kept traces land in a bounded ring (oldest evicted first) and export as
JSONL (one trace per line) or as a Chrome trace — ``ph:"X"`` complete
events with the ``trace_id`` in ``args`` — that the existing
``trace-summary`` CLI (:mod:`swiftsnails_tpu.telemetry.summary`) renders
unchanged.

Tracing never blocks or fails the serve path: span capture is a few list
appends under a lock, everything else is ``try/except`` best-effort, and
with no tracer attached the instrumentation reduces to one ``None`` check
per request.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "ANOMALY_KINDS",
    "RequestContext",
    "RequestTracer",
    "current",
    "use",
]

# Every way a request can turn out interesting enough for tail-keep.
ANOMALY_KINDS = (
    "typed_failure",   # Unavailable / Overloaded / dispatch exception
    "hedge",           # a hedge leg was fired
    "reroute",         # the request walked to another replica
    "degraded",        # served stale from the degraded LRU
    "slo_violation",   # latency over the kernel's SLO
    "fallback",        # freshness gap -> full checkpoint reload
    "shed",            # load-shed / queue-full rejection
)

_DEFAULT_MAX_SPANS = 64
_DEFAULT_CAPACITY = 256
_SAMPLE_DENOM = 1 << 24


def _mix64(x: int) -> int:
    """splitmix64 finalizer: cheap, well-distributed 64-bit mixing."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


# -- thread-local context propagation ----------------------------------------

_tls = threading.local()


def current() -> Optional["RequestContext"]:
    """The request context active on this thread, if any."""
    return getattr(_tls, "ctx", None)


class use:
    """Activate ``ctx`` on this thread for the ``with`` body (restores the
    previous context on exit). How the fleet carries a request's context
    onto its worker-pool legs."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional["RequestContext"]):
        self._ctx = ctx
        self._prev = None

    def __enter__(self) -> Optional["RequestContext"]:
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc) -> None:
        _tls.ctx = self._prev


# -- the per-request context --------------------------------------------------


class _SpanHandle:
    """Context manager for one live span inside a :class:`RequestContext`."""

    __slots__ = ("_ctx", "_name", "_args", "_t0", "_sid", "_parent")

    def __init__(self, ctx: "RequestContext", name: str, args: Dict):
        self._ctx = ctx
        self._name = name
        self._args = args
        self._t0 = 0
        self._sid = 0
        self._parent = 0

    def __enter__(self) -> "_SpanHandle":
        ctx = self._ctx
        self._parent = ctx._thread_parent()
        self._sid = ctx._new_span_id()
        ctx._push(self._sid)
        self._t0 = ctx._clock_ns()
        return self

    def set(self, **kv) -> None:
        """Attach args to the live span (outcome fields, counts)."""
        self._args.update(kv)

    def __exit__(self, exc_type, exc, tb) -> None:
        ctx = self._ctx
        dur = ctx._clock_ns() - self._t0
        ctx._pop(self._sid)
        if exc_type is not None:
            self._args.setdefault("error", exc_type.__name__)
        ctx._record(self._name, self._t0, dur, self._sid, self._parent,
                    self._args)


class RequestContext:
    """One request's trace: a bounded span tree plus baggage/annotations.

    Thread-safe — fleet hedge legs append spans from pool threads while the
    request thread owns the root. Parent linkage is per-thread: a span
    opened on a thread nests under that thread's innermost open span, or
    under the root when the thread has none (a fresh hedge leg).
    """

    __slots__ = (
        "trace_id", "kernel", "sampled", "resumed", "baggage",
        "annotations", "anomalies", "spans", "dropped_spans",
        "t0_ns", "dur_ns", "ts_unix_ns", "root_span_id",
        "_max_spans", "_clock_ns", "_next_sid", "_lock", "_stacks",
    )

    def __init__(
        self,
        trace_id: str,
        kernel: str,
        *,
        sampled: bool = False,
        resumed: bool = False,
        parent_span_id: int = 0,
        baggage: Optional[Dict[str, Any]] = None,
        max_spans: int = _DEFAULT_MAX_SPANS,
        clock_ns: Callable[[], int] = time.perf_counter_ns,
    ):
        self.trace_id = trace_id
        self.kernel = kernel
        self.sampled = bool(sampled)
        self.resumed = bool(resumed)
        self.baggage: Dict[str, Any] = dict(baggage or {})
        self.annotations: Dict[str, Any] = {}
        self.anomalies: List[str] = []
        # recorded spans: (name, t0_ns, dur_ns, span_id, parent_id, args)
        self.spans: List[Tuple[str, int, int, int, int, Dict]] = []
        self.dropped_spans = 0
        self.ts_unix_ns = time.time_ns()
        self._max_spans = int(max_spans)
        self._clock_ns = clock_ns
        self._lock = threading.Lock()
        self._stacks = threading.local()
        self._next_sid = 1
        # Root span: id 1 locally, or the remote parent when resumed so the
        # tree stitches together across the wire.
        self.root_span_id = self._new_span_id()
        if resumed and parent_span_id:
            self.root_span_id = int(parent_span_id)
        self.t0_ns = clock_ns()
        self.dur_ns = 0

    # -- span recording -------------------------------------------------

    def _new_span_id(self) -> int:
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
        return sid

    def _stack(self) -> List[int]:
        st = getattr(self._stacks, "stack", None)
        if st is None:
            st = self._stacks.stack = []
        return st

    def _thread_parent(self) -> int:
        st = self._stack()
        return st[-1] if st else self.root_span_id

    def _push(self, sid: int) -> None:
        self._stack().append(sid)

    def _pop(self, sid: int) -> None:
        st = self._stack()
        if st and st[-1] == sid:
            st.pop()

    def _record(self, name, t0_ns, dur_ns, sid, parent, args) -> None:
        with self._lock:
            if len(self.spans) >= self._max_spans:
                self.dropped_spans += 1
                return
            self.spans.append((name, int(t0_ns), int(dur_ns), sid, parent,
                               args))

    def span(self, name: str, **args) -> _SpanHandle:
        """Open a child span; nests under this thread's innermost span."""
        return _SpanHandle(self, name, args)

    def add_span(self, name: str, t0_ns: int, dur_ns: int,
                 parent: Optional[int] = None, **args) -> None:
        """Record a span retroactively from explicit timestamps — how the
        engine attributes queue-wait and batch kernel time measured on the
        dispatcher thread without touching the context from it."""
        if parent is None:
            parent = self._thread_parent()
        self._record(name, t0_ns, max(0, int(dur_ns)), self._new_span_id(),
                     parent, args)

    # -- annotation ------------------------------------------------------

    def annotate(self, **kv) -> None:
        """Attach request-level facts (cache hits, table version, winner)."""
        with self._lock:
            self.annotations.update(kv)

    def mark_anomaly(self, kind: str) -> None:
        """Flag the request for tail-keep; idempotent per kind."""
        with self._lock:
            if kind not in self.anomalies:
                self.anomalies.append(kind)

    @property
    def anomalous(self) -> bool:
        return bool(self.anomalies)

    # -- wire propagation ------------------------------------------------

    def wire(self) -> Dict[str, Any]:
        """The propagable form: what travels in a delta-batch header (or,
        later, an RPC header) so the far side continues this trace."""
        out: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "span_id": self._thread_parent(),
        }
        if self.baggage:
            out["baggage"] = dict(self.baggage)
        return out

    # -- export ----------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            spans = [
                {"name": n, "t0_us": t0 // 1000, "dur_us": d // 1000,
                 "span_id": sid, "parent": par, "args": dict(a)}
                for n, t0, d, sid, par, a in self.spans
            ]
            return {
                "trace_id": self.trace_id,
                "kernel": self.kernel,
                "ts_unix_ns": self.ts_unix_ns,
                "dur_ms": round(self.dur_ns / 1e6, 3),
                "sampled": self.sampled,
                "resumed": self.resumed,
                "anomalies": list(self.anomalies),
                "baggage": dict(self.baggage),
                "annotations": dict(self.annotations),
                "dropped_spans": self.dropped_spans,
                "spans": spans,
            }


# -- the capture engine -------------------------------------------------------


class RequestTracer:
    """Per-process trace capture: mints contexts, applies the sampling
    policy at :meth:`finish`, and ring-buffers kept traces.

    ``sample_rate`` is the head-sampling probability; the decision is a
    pure function of the trace id, so every process that sees the same id
    agrees. ``anomaly_keep`` retains any trace that marked an anomaly.
    ``slo_ms`` (scalar or per-kernel dict) auto-marks ``slo_violation``
    on finish. ``seed`` makes the minted id sequence — and therefore the
    head-sampling pattern — deterministic for drills and tests.
    """

    def __init__(
        self,
        sample_rate: float = 0.0,
        *,
        anomaly_keep: bool = True,
        capacity: int = _DEFAULT_CAPACITY,
        slo_ms: Any = None,
        seed: int = 0,
        max_spans: int = _DEFAULT_MAX_SPANS,
        clock_ns: Callable[[], int] = time.perf_counter_ns,
        ledger=None,
        source: str = "serving",
    ):
        self.sample_rate = max(0.0, min(1.0, float(sample_rate)))
        self.anomaly_keep = bool(anomaly_keep)
        self.slo_ms = slo_ms
        self.seed = int(seed)
        self.ledger = ledger
        self.source = source
        self.max_spans = int(max_spans)
        self._clock_ns = clock_ns
        self._lock = threading.Lock()
        self._counter = 0
        self._kept: deque = deque(maxlen=max(1, int(capacity)))
        self._stats = {
            "started": 0, "finished": 0, "sampled": 0, "kept": 0,
            "anomalies": 0, "dropped": 0, "resumed": 0,
        }

    @classmethod
    def from_config(cls, config, *, seed: Optional[int] = None,
                    slo_ms: Any = None, ledger=None,
                    source: str = "serving") -> Optional["RequestTracer"]:
        """Build from typed config keys, or ``None`` when tracing is off.

        ``trace_sample_rate`` > 0 enables head sampling;
        ``trace_anomaly_keep`` (default: on whenever sampling is on)
        enables tail-keep alone even at rate 0. Both absent/zero -> no
        tracer, and the serve path pays one ``None`` check."""
        rate = config.get_float("trace_sample_rate", 0.0)
        keep = config.get_bool("trace_anomaly_keep", rate > 0)
        if rate <= 0 and not keep:
            return None
        if slo_ms is None:
            lat = config.get_float("slo_latency_ms", 0.0)
            slo_ms = lat if lat > 0 else None
        return cls(
            rate, anomaly_keep=keep, slo_ms=slo_ms,
            seed=config.get_int("seed", 0) if seed is None else seed,
            ledger=ledger, source=source,
        )

    # -- minting / sampling ---------------------------------------------

    def _mint_id(self) -> str:
        with self._lock:
            self._counter += 1
            n = self._counter
        return f"{_mix64((self.seed << 32) ^ n):016x}"

    def head_sampled(self, trace_id: str) -> bool:
        """Deterministic head-sampling decision from the id alone."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        try:
            h = _mix64(int(trace_id, 16))
        except (TypeError, ValueError):
            return False
        return (h % _SAMPLE_DENOM) < int(self.sample_rate * _SAMPLE_DENOM)

    def start(self, kernel: str, **baggage) -> RequestContext:
        """Mint a fresh trace for a request entering the plane here."""
        trace_id = self._mint_id()
        ctx = RequestContext(
            trace_id, kernel,
            sampled=self.head_sampled(trace_id),
            baggage=baggage or None,
            max_spans=self.max_spans, clock_ns=self._clock_ns,
        )
        with self._lock:
            self._stats["started"] += 1
            if ctx.sampled:
                self._stats["sampled"] += 1
        return ctx

    def resume(self, wire: Optional[Dict[str, Any]], kernel: str,
               **baggage) -> RequestContext:
        """Continue a trace that arrived over a wire (delta-batch header).
        Falls back to :meth:`start` when the wire form is absent/garbled,
        so a pre-tracing publisher still yields usable apply traces."""
        trace_id = None
        parent = 0
        if isinstance(wire, dict):
            trace_id = wire.get("trace_id")
            try:
                parent = int(wire.get("span_id") or 0)
            except (TypeError, ValueError):
                parent = 0
            inherited = wire.get("baggage")
            if isinstance(inherited, dict):
                merged = dict(inherited)
                merged.update(baggage)
                baggage = merged
        if not isinstance(trace_id, str) or not trace_id:
            return self.start(kernel, **baggage)
        ctx = RequestContext(
            trace_id, kernel,
            sampled=self.head_sampled(trace_id),
            resumed=True, parent_span_id=parent,
            baggage=baggage or None,
            max_spans=self.max_spans, clock_ns=self._clock_ns,
        )
        with self._lock:
            self._stats["started"] += 1
            self._stats["resumed"] += 1
            if ctx.sampled:
                self._stats["sampled"] += 1
        return ctx

    # -- finish / keep ---------------------------------------------------

    def _slo_for(self, kernel: str) -> Optional[float]:
        slo = self.slo_ms
        if slo is None:
            return None
        if isinstance(slo, dict):
            v = slo.get(kernel)
            return float(v) if v is not None else None
        return float(slo)

    def finish(self, ctx: RequestContext,
               error: Optional[BaseException] = None) -> bool:
        """Close the trace; returns True when it was kept."""
        ctx.dur_ns = max(0, self._clock_ns() - ctx.t0_ns)
        if error is not None:
            ctx.mark_anomaly("typed_failure")
            ctx.annotate(error=type(error).__name__)
        slo = self._slo_for(ctx.kernel)
        if slo is not None and ctx.dur_ns / 1e6 > slo:
            ctx.mark_anomaly("slo_violation")
        ctx._record("request", ctx.t0_ns, ctx.dur_ns, ctx.root_span_id, 0,
                    {"kernel": ctx.kernel})
        keep = ctx.sampled or (self.anomaly_keep and ctx.anomalous)
        n_anom = 0
        with self._lock:
            self._stats["finished"] += 1
            if ctx.anomalous:
                self._stats["anomalies"] += 1
                n_anom = self._stats["anomalies"]
            if keep:
                self._stats["kept"] += 1
                self._kept.append(ctx)
            else:
                self._stats["dropped"] += 1
        # rate-limited trace_anomaly ledger stream (first + every 100th),
        # each line naming a trace_id still retrievable from the ring
        if (keep and n_anom and self.ledger is not None
                and (n_anom == 1 or n_anom % 100 == 0)):
            try:
                self.ledger.append("trace_anomaly", {
                    "source": self.source,
                    "trace_id": ctx.trace_id,
                    "kernel": ctx.kernel,
                    "anomalies": list(ctx.anomalies),
                    "dur_ms": round(ctx.dur_ns / 1e6, 3),
                    "anomalies_total": n_anom,
                })
            except Exception:
                pass  # record-keeping never blocks the serve path
        return keep

    # -- retrieval -------------------------------------------------------

    def traces(self) -> List[RequestContext]:
        with self._lock:
            return list(self._kept)

    def get(self, trace_id: str) -> Optional[RequestContext]:
        with self._lock:
            for ctx in reversed(self._kept):
                if ctx.trace_id == trace_id:
                    return ctx
        return None

    def anomaly_traces(self, n: Optional[int] = None) -> List[RequestContext]:
        """Most-recent-last anomaly traces (the ops-report feed)."""
        out = [c for c in self.traces() if c.anomalous]
        return out[-n:] if n else out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._stats)
        out["ring"] = len(self._kept)
        out["sample_rate"] = self.sample_rate
        out["anomaly_keep"] = self.anomaly_keep
        return out

    # -- export ----------------------------------------------------------

    def export_jsonl(self, path: str) -> int:
        """One kept trace per line; returns the count written. The
        ``trace-summary`` CLI renders this file directly (it treats each
        line's ``dur_ms`` like any JSONL record stream)."""
        traces = self.traces()
        with open(path, "w", encoding="utf-8") as f:
            for ctx in traces:
                f.write(json.dumps(ctx.to_dict()) + "\n")
        return len(traces)

    def chrome_trace(self, trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Chrome trace-event JSON of the kept ring (or one trace).
        Same shape :class:`~swiftsnails_tpu.telemetry.tracer.Tracer`
        emits, so ``trace-summary`` and chrome://tracing both read it;
        every span carries its ``trace_id`` in ``args``."""
        traces = self.traces()
        if trace_id is not None:
            traces = [c for c in traces if c.trace_id == trace_id]
        events: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
            "args": {"name": "swiftsnails-requests"},
        }]
        base = min((c.t0_ns for c in traces), default=0)
        for tid, ctx in enumerate(traces, start=1):
            snap = ctx.to_dict()
            for s in snap["spans"]:
                args = dict(s["args"])
                args["trace_id"] = ctx.trace_id
                if s["name"] == "request":
                    args["kernel"] = ctx.kernel
                    if snap["anomalies"]:
                        args["anomalies"] = snap["anomalies"]
                events.append({
                    "name": s["name"], "ph": "X", "pid": 0, "tid": tid,
                    "ts": s["t0_us"] - base // 1000, "dur": s["dur_us"],
                    "args": args,
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str,
                      trace_id: Optional[str] = None) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(trace_id), f)


# -- trace-tree verification --------------------------------------------------


def tree_complete(trace: Dict[str, Any],
                  require: Tuple[str, ...] = ()) -> bool:
    """True when a trace dict (``RequestContext.to_dict()`` shape) is a
    *complete* tree: has a root ``request`` span, every span's parent
    resolves, and every span name in ``require`` appears. The chaos drills
    use this to assert causality is drillable, not just counted."""
    spans = trace.get("spans") or []
    ids = {s.get("span_id") for s in spans}
    roots = [s for s in spans if s.get("name") == "request"]
    if not roots:
        return False
    root_ids = {s.get("span_id") for s in roots}
    for s in spans:
        par = s.get("parent", 0)
        if par and par not in ids and par not in root_ids:
            return False
    names = {s.get("name") for s in spans}
    return all(r in names for r in require)
