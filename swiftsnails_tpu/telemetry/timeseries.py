"""Bounded ring time-series store: continuous profiling off the hot path.

The bench and ledger capture *point-in-time* records; the blackbox captures
the *last N steps* for post-mortems. What neither gives is the shape of a
run while it happens — did the host-blocked fraction creep up over the last
thousand steps, did the flush queue start backing up at step 40k? This
module is that middle layer: a :class:`TimeSeriesStore` holds a bounded
ring of periodic samples (every registry metric via
``MetricRegistry.snapshot()``, the per-step goodput decomposition, the
tiered breakdown, comm-audit bytes per scope), the TrainLoop feeds it at a
configurable cadence (``profile_cadence`` steps, ``0`` = off), and the
store renders three ways:

* ``export_jsonl(path)`` — one JSON object per sample, for offline tools;
* ``summary(max_points=...)`` — a bounded, downsampled block embedded in
  the run record so ``ledger-report`` / ``ops`` can draw sparklines from
  the ledger alone;
* :func:`sparkline` — the terminal rendering primitive both use.

Everything is plain host-side Python over already-recorded numbers: the
only hot-path cost is the cadence check the loop already pays, and a dict
copy every ``profile_cadence`` steps. The ring is bounded
(``profile_window`` samples), so a week-long run holds a sliding window,
not an unbounded log.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: Sequence[float], width: int = 32) -> str:
    """Render a numeric sequence as a unicode sparkline.

    Non-finite values render as ``·``; a flat series renders as all-low
    bars rather than dividing by zero. ``width`` caps the output by
    piecewise-averaging (not truncating) so the whole window stays visible.
    The scale is clamped to the p5..p95 band (values outside clamp to the
    extreme bars): one outlier — the jit-compile first step — must not
    flatten the rest of the series into invisibility.
    """
    vals = [float(v) for v in values]
    if not vals:
        return ""
    if width and len(vals) > width:
        vals = downsample(vals, width)
    finite = [v for v in vals if v == v and v not in (float("inf"), float("-inf"))]
    if not finite:
        return "·" * len(vals)
    ranked = sorted(finite)
    lo = ranked[int(0.05 * (len(ranked) - 1))]
    hi = ranked[int(0.95 * (len(ranked) - 1) + 0.5)]
    span = hi - lo
    out = []
    for v in vals:
        if not (v == v) or v in (float("inf"), float("-inf")):
            out.append("·")
            continue
        if span <= 0:
            out.append(_SPARK_CHARS[0])
            continue
        idx = int((v - lo) / span * (len(_SPARK_CHARS) - 1) + 0.5)
        out.append(_SPARK_CHARS[max(0, min(idx, len(_SPARK_CHARS) - 1))])
    return "".join(out)


def downsample(values: Sequence[float], n: int) -> List[float]:
    """Piecewise-mean downsample to at most ``n`` points (order-preserving)."""
    vals = [float(v) for v in values]
    if n <= 0 or len(vals) <= n:
        return vals
    out: List[float] = []
    for i in range(n):
        lo = i * len(vals) // n
        hi = max((i + 1) * len(vals) // n, lo + 1)
        chunk = [v for v in vals[lo:hi] if v == v]
        out.append(sum(chunk) / len(chunk) if chunk else float("nan"))
    return out


class TimeSeriesStore:
    """Bounded ring of periodic metric samples.

    Each sample is a flat ``name -> float`` dict plus ``step`` and ``ts``.
    Thread-safe: the sampler runs on the training thread, readers
    (``ops``, export) may run elsewhere.
    """

    def __init__(self, window: int = 512):
        self.window = int(window)
        self._ring: Deque[Dict] = deque(maxlen=max(self.window, 1))
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def sample(self, step: int, metrics: Dict, ts: Optional[float] = None) -> None:
        """Record one sample. Non-numeric values are dropped (a registry
        snapshot can carry exemplar trace-id strings)."""
        rec: Dict = {"step": int(step), "ts": float(ts if ts is not None else time.time())}
        for k, v in metrics.items():
            if isinstance(v, bool):
                rec[k] = float(v)
            elif isinstance(v, (int, float)):
                rec[k] = float(v)
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> List[Dict]:
        """The current window, oldest first (copies — safe to mutate)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def latest(self) -> Optional[Dict]:
        with self._lock:
            return dict(self._ring[-1]) if self._ring else None

    def series(self, name: str) -> Tuple[List[int], List[float]]:
        """(steps, values) for one metric across the window (samples that
        lack the metric are skipped, not zero-filled)."""
        steps: List[int] = []
        vals: List[float] = []
        with self._lock:
            for r in self._ring:
                if name in r:
                    steps.append(r["step"])
                    vals.append(r[name])
        return steps, vals

    def names(self) -> List[str]:
        """All metric names seen anywhere in the window, sorted."""
        seen = set()
        with self._lock:
            for r in self._ring:
                seen.update(r)
        seen.discard("step")
        seen.discard("ts")
        return sorted(seen)

    def export_jsonl(self, path) -> int:
        """Write the window as JSONL (atomic via the ledger helper).

        Returns the number of samples written.
        """
        from .ledger import atomic_write_bytes

        rows = self.snapshot()
        body = "".join(json.dumps(r, sort_keys=True) + "\n" for r in rows)
        atomic_write_bytes(path, body.encode("utf-8"))
        return len(rows)

    def summary(self, max_points: int = 40,
                names: Optional[Sequence[str]] = None) -> Dict:
        """Bounded block for embedding in a run record.

        ``{"window": N, "first_step": s0, "last_step": s1,
        "series": {name: [<=max_points floats]}}`` — enough for sparklines
        from the ledger alone, small enough to live in every run record.
        """
        # shallow refs, not snapshot(): samples are write-once, and this
        # runs in run finalization where a 512-row deep copy is real cost
        with self._lock:
            rows = list(self._ring)
        if not rows:
            return {"window": 0, "series": {}}
        if names is not None:
            wanted = list(names)
        else:
            seen: set = set()
            for r in rows:
                seen.update(r)
            seen.discard("step")
            seen.discard("ts")
            wanted = sorted(seen)
        series: Dict[str, List[float]] = {}
        for name in wanted:
            vals = [r[name] for r in rows if name in r]
            if vals:
                series[name] = [round(v, 6) for v in downsample(vals, max_points)]
        return {
            "window": len(rows),
            "first_step": rows[0]["step"],
            "last_step": rows[-1]["step"],
            "series": series,
        }


def render_sparklines(summary: Dict, names: Optional[Sequence[str]] = None,
                      width: int = 32, indent: str = "  ") -> List[str]:
    """Terminal lines for a :meth:`TimeSeriesStore.summary` block (also
    accepts the block re-read from a ledger record). Shared by
    ``ledger-report`` and the ``ops`` dashboard training section."""
    if not summary or not summary.get("series"):
        return []
    series = summary["series"]
    wanted = [n for n in (names or sorted(series))]
    label_w = max((len(n) for n in wanted if n in series), default=0)
    lines: List[str] = []
    for name in wanted:
        vals = series.get(name)
        if not vals:
            continue
        last = vals[-1]
        lines.append(
            f"{indent}{name:<{label_w}}  {sparkline(vals, width)}  "
            f"last={last:.6g}")
    return lines
