"""Goodput / MFU accounting: hardware-utilization numbers for a run.

Combines the two raw signal sources PR 1 built —

* the host span tracer (prefetch-wait / h2d / step spans per step), and
* the compiled-HLO audit (per-step FLOPs, bytes accessed, collective bytes)

— into the metrics TPU training stacks report as first-class: **MFU**
(model FLOP utilization: achieved FLOP/s over the chip's peak), a
**step-time decomposition** (compute vs collective vs host-blocked vs h2d),
**goodput** (fraction of wall-clock spent inside productive steps), and a
**words/sec-vs-roofline ratio** (measured throughput over the
compute/memory-roofline bound for the compiled step).

Everything here is pure host-side arithmetic over already-recorded data:
no device work, no extra hot-path cost. Peaks come from a per-device-kind
table (published chip specs) overridable via the ``peak_flops`` /
``peak_hbm_gbps`` / ``peak_ici_gbps`` config keys — on CPU (tier-1 tests,
smoke runs) there is no meaningful peak, so MFU degrades to ``None``
rather than inventing a number.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

# Published per-chip peaks: (bf16 TFLOP/s, HBM GB/s, ICI GB/s per link-set).
# Keyed by substrings of jax's ``device_kind`` / platform names; first match
# wins. These anchor MFU the way the pjit-at-scale reports do (arXiv:
# 2204.06514 reports hardware FLOP/s utilization against the chip peak).
_PEAKS = (
    ("v6", (918.0, 1640.0, 448.0)),       # Trillium / v6e
    ("v5p", (459.0, 2765.0, 600.0)),
    ("v5 lite", (197.0, 819.0, 200.0)),   # v5e device_kind is "TPU v5 lite"
    ("v5e", (197.0, 819.0, 200.0)),
    ("v5", (459.0, 2765.0, 600.0)),
    ("v4", (275.0, 1228.0, 300.0)),
    ("v3", (123.0, 900.0, 100.0)),
    ("v2", (46.0, 700.0, 62.0)),
)


def peaks_for(device_kind: Optional[str]) -> Dict[str, Optional[float]]:
    """Peak FLOP/s, HBM B/s, ICI B/s for a device kind (None when unknown,
    e.g. CPU — never invent a utilization denominator)."""
    if device_kind:
        kind = device_kind.lower()
        for key, (tf, hbm, ici) in _PEAKS:
            if key in kind:
                return {
                    "flops_per_s": tf * 1e12,
                    "hbm_bytes_per_s": hbm * 1e9,
                    "ici_bytes_per_s": ici * 1e9,
                    "source": f"builtin table ({key})",
                }
    return {
        "flops_per_s": None,
        "hbm_bytes_per_s": None,
        "ici_bytes_per_s": None,
        "source": "unknown device kind",
    }


def peaks_from_config(cfg, device_kind: Optional[str]) -> Dict:
    """Table peaks with config-key overrides (``peak_flops`` in FLOP/s,
    ``peak_hbm_gbps`` / ``peak_ici_gbps`` in GB/s)."""
    peaks = peaks_for(device_kind)
    if cfg is not None:
        pf = cfg.get_float("peak_flops", 0.0)
        if pf > 0:
            peaks["flops_per_s"] = pf
            peaks["source"] = "config"
        hbm = cfg.get_float("peak_hbm_gbps", 0.0)
        if hbm > 0:
            peaks["hbm_bytes_per_s"] = hbm * 1e9
        ici = cfg.get_float("peak_ici_gbps", 0.0)
        if ici > 0:
            peaks["ici_bytes_per_s"] = ici * 1e9
    return peaks


# ------------------------------------------------------ span decomposition ---

# spans the TrainLoop emits, bucketed for the decomposition
_SPAN_BUCKETS = {
    "step": "compute_s",          # jitted dispatch + device sync
    "h2d": "h2d_s",
    "prefetch-wait": "host_blocked_s",
    "tier-fault": "host_blocked_s",       # tiered residency work on the step
    "tier-flush-wait": "host_blocked_s",  # async write-back drain barriers
    "chaos-slow": "host_blocked_s",       # injected slow_step host sleep
    "metrics-flush": "other_s",
    "checkpoint": "other_s",
}


def step_time_decomposition(events: Iterable[Dict]) -> Dict:
    """Bucketed wall-clock split from tracer span events.

    ``events`` is ``Tracer.events()`` output (dicts with ``name``/``ts_us``/
    ``dur_us``). Top-level spans only (depth<=1 buckets; the per-step outer
    ``step_span`` carries the trainer name and is skipped so nothing is
    counted twice). Fractions are of the traced wall-clock between the first
    span start and the last span end.
    """
    out = {
        "wall_s": 0.0, "compute_s": 0.0, "h2d_s": 0.0,
        "host_blocked_s": 0.0, "other_s": 0.0, "steps": 0,
    }
    t0, t1 = float("inf"), float("-inf")
    for e in events:
        ts = float(e.get("ts_us", 0.0))
        dur = float(e.get("dur_us", 0.0))
        t0 = min(t0, ts)
        t1 = max(t1, ts + dur)
        bucket = _SPAN_BUCKETS.get(e.get("name"))
        if bucket is not None:
            out[bucket] += dur / 1e6
            if e.get("name") == "step":
                out["steps"] += 1
    if t1 > t0:
        out["wall_s"] = (t1 - t0) / 1e6
    wall = out["wall_s"]
    if wall > 0:
        accounted = (
            out["compute_s"] + out["h2d_s"] + out["host_blocked_s"] + out["other_s"]
        )
        out["compute_frac"] = out["compute_s"] / wall
        out["h2d_frac"] = out["h2d_s"] / wall
        out["host_blocked_frac"] = out["host_blocked_s"] / wall
        out["other_frac"] = out["other_s"] / wall
        out["unaccounted_frac"] = max(1.0 - accounted / wall, 0.0)
    return out


# ------------------------------------------------------------- roofline ---


def roofline_step_seconds(
    flops: Optional[float],
    hbm_bytes: Optional[float],
    collective_bytes: Optional[float],
    peaks: Dict,
) -> Optional[float]:
    """Lower bound on one step's duration from the compiled cost analysis:
    max over the compute, HBM, and interconnect rooflines (each skipped when
    its peak or numerator is unknown)."""
    bounds = []
    if flops and peaks.get("flops_per_s"):
        bounds.append(flops / peaks["flops_per_s"])
    if hbm_bytes and peaks.get("hbm_bytes_per_s"):
        bounds.append(hbm_bytes / peaks["hbm_bytes_per_s"])
    if collective_bytes and peaks.get("ici_bytes_per_s"):
        bounds.append(collective_bytes / peaks["ici_bytes_per_s"])
    return max(bounds) if bounds else None


def goodput_report(
    *,
    events: Optional[Sequence[Dict]] = None,
    audit: Optional[Dict] = None,
    steps: Optional[int] = None,
    items: Optional[int] = None,
    step_seconds: Optional[float] = None,
    peaks: Optional[Dict] = None,
    n_chips: int = 1,
) -> Dict:
    """The per-run goodput block.

    Inputs are all optional — the report states what it could compute and
    carries ``None`` for the rest (a CPU smoke run has spans but no peak;
    an audit-less run has timings but no FLOPs).

    * ``events``: tracer span dicts (gives the decomposition + step timing);
    * ``audit``: a :func:`telemetry.audit.audit_step` report (FLOPs, bytes
      accessed, collective bytes) for ONE step dispatch;
    * ``steps`` / ``items``: loop totals (items = words/examples);
    * ``step_seconds``: measured per-step seconds — derived from the spans
      when absent;
    * ``peaks``: :func:`peaks_for` / :func:`peaks_from_config` output;
    * ``n_chips``: devices sharing the audited step's FLOPs (per-chip MFU).
    """
    peaks = peaks or peaks_for(None)
    report: Dict = {"peaks": {k: v for k, v in peaks.items()}}

    dec = None
    if events:
        dec = step_time_decomposition(events)
        report["decomposition"] = dec
        if steps is None:
            steps = dec["steps"] or None
    if steps:
        report["steps"] = int(steps)
    if items is not None:
        report["items"] = int(items)

    if step_seconds is None and dec and dec["steps"]:
        step_seconds = dec["compute_s"] / dec["steps"]
    report["step_seconds"] = step_seconds

    # goodput: productive (in-step) fraction of the traced wall-clock
    if dec and dec["wall_s"] > 0:
        report["goodput"] = dec["compute_s"] / dec["wall_s"]

    flops = hbm_bytes = coll_bytes = None
    if audit:
        cost = audit.get("cost", {}) or {}
        flops = cost.get("flops")
        hbm_bytes = cost.get("bytes_accessed")
        coll_bytes = audit.get("total_bytes", audit.get("collective_bytes"))
        report["flops_per_step"] = flops
        report["hbm_bytes_per_step"] = hbm_bytes
        report["collective_bytes_per_step"] = coll_bytes

    # MFU: achieved FLOP/s over peak, per chip
    mfu = None
    if flops and step_seconds and peaks.get("flops_per_s"):
        mfu = (flops / n_chips) / step_seconds / peaks["flops_per_s"]
    report["mfu"] = mfu

    # model-based split of the measured step time into compute vs collective
    # (roofline estimates normalized onto the measured step — labeled est)
    if step_seconds and step_seconds > 0:
        comp_est = (
            flops / n_chips / peaks["flops_per_s"]
            if flops and peaks.get("flops_per_s") else None
        )
        coll_est = (
            coll_bytes / n_chips / peaks["ici_bytes_per_s"]
            if coll_bytes and peaks.get("ici_bytes_per_s") else None
        )
        if comp_est is not None or coll_est is not None:
            # the seconds estimates are kept alongside the fractions so the
            # collective share can be cross-checked directly against the
            # audited bytes / ICI peak (the scale-out lane records both and
            # attributes an overlap/quantization win to the right term)
            report["step_split_est"] = {
                "compute_frac": (comp_est or 0.0) / step_seconds,
                "collective_frac": (coll_est or 0.0) / step_seconds,
                "compute_seconds_est": comp_est,
                "collective_seconds_est": coll_est,
            }

    # words/sec vs roofline: measured items/s over the bound the compiled
    # step admits on this chip
    ideal_s = roofline_step_seconds(
        flops / n_chips if flops else None,
        hbm_bytes / n_chips if hbm_bytes else None,
        coll_bytes / n_chips if coll_bytes else None,
        peaks,
    )
    report["roofline_step_seconds"] = ideal_s
    if ideal_s and steps and items and step_seconds:
        items_per_step = items / steps
        measured_rate = items_per_step / step_seconds
        roofline_rate = items_per_step / ideal_s
        report["items_per_sec"] = measured_rate
        report["roofline_items_per_sec"] = roofline_rate
        report["vs_roofline"] = measured_rate / roofline_rate
    elif steps and items and step_seconds:
        report["items_per_sec"] = (items / steps) / step_seconds
    return report


# -------------------------------------------------- regression attribution ---

_ATTR_COMPONENTS = ("compute", "h2d", "host_blocked", "other", "unaccounted")


def _per_step_components(rec: Dict) -> Dict[str, Optional[float]]:
    """Per-step seconds for each decomposition component of one run/bench
    record (``None`` when the record carries no decomposition)."""
    gp = rec.get("goodput") or rec
    dec = gp.get("decomposition") or {}
    steps = dec.get("steps") or gp.get("steps") or 0
    out: Dict[str, Optional[float]] = {}
    if not steps:
        return {c: None for c in _ATTR_COMPONENTS}
    wall = dec.get("wall_s") or 0.0
    accounted = 0.0
    for comp in ("compute", "h2d", "host_blocked", "other"):
        sec = dec.get(f"{comp}_s")
        out[comp] = (sec / steps) if sec is not None else None
        accounted += sec or 0.0
    out["unaccounted"] = max(wall - accounted, 0.0) / steps if wall else None
    return out


def _record_rate(rec: Dict) -> Optional[float]:
    """items/sec (words/sec) of a run/bench record, from whichever field
    the record carries.

    A record with a span decomposition is rated as items over traced
    *wall-clock*: ``goodput.items_per_sec`` divides by the mean ``step``
    span instead, which excludes exactly the host-blocked time a ``--diff``
    exists to attribute (a run slowed by sleeps would look *faster*)."""
    gp = rec.get("goodput") or {}
    for probe in (
        rec.get("words_per_sec"),
        rec.get("items_per_sec"),
        rec.get("best"),
    ):
        if isinstance(probe, (int, float)) and probe > 0:
            return float(probe)
    items = gp.get("items") or rec.get("items")
    dec = gp.get("decomposition") or rec.get("decomposition") or {}
    wall = dec.get("wall_s")
    if items and isinstance(wall, (int, float)) and wall > 0:
        return float(items) / wall
    probe = gp.get("items_per_sec")
    if isinstance(probe, (int, float)) and probe > 0:
        return float(probe)
    steps = gp.get("steps") or rec.get("steps")
    step_s = gp.get("step_seconds")
    if steps and items and step_s:
        return (items / steps) / step_s
    return None


def throughput_attribution(rec_a: Dict, rec_b: Dict) -> Dict:
    """Decompose the words/sec delta between two run/bench records.

    The core of ``ledger-report --diff A B`` / ``tools/perf_diff.py``:
    per-step seconds for each goodput component (compute / h2d /
    host-blocked / other / unaccounted) are differenced A→B, per-scope
    comm-audit bytes likewise, and the **dominant contributor** is the
    component with the largest absolute per-step delta — the one a
    regression (or a win) should be attributed to. Pure host arithmetic
    over the records; tolerant of partial records (an un-decomposed side
    yields ``None`` deltas and an ``insufficient-data`` dominant).
    """
    comp_a = _per_step_components(rec_a)
    comp_b = _per_step_components(rec_b)
    rate_a = _record_rate(rec_a)
    rate_b = _record_rate(rec_b)

    components: Dict[str, Dict] = {}
    best_name, best_delta = None, 0.0
    for name in _ATTR_COMPONENTS:
        a, b = comp_a.get(name), comp_b.get(name)
        delta = (b - a) if (a is not None and b is not None) else None
        components[name] = {"a_s": a, "b_s": b, "delta_s": delta}
        if delta is not None and abs(delta) > abs(best_delta):
            best_name, best_delta = name, delta

    total_delta = sum(
        c["delta_s"] for c in components.values() if c["delta_s"] is not None
    )
    dominant_share = (
        abs(best_delta) / abs(total_delta)
        if best_name is not None and total_delta else None
    )

    # per-scope comm bytes (the audit's by_scope map, carried on run
    # records as comm_by_scope and on bench payloads inside the audit)
    def _by_scope(rec: Dict) -> Dict[str, float]:
        scopes = rec.get("comm_by_scope")
        if not scopes:
            scopes = (rec.get("audit") or {}).get("by_scope")
        out = {}
        for scope, v in (scopes or {}).items():
            bytes_ = v.get("bytes") if isinstance(v, dict) else v
            if isinstance(bytes_, (int, float)):
                out[scope] = float(bytes_)
        return out

    scopes_a, scopes_b = _by_scope(rec_a), _by_scope(rec_b)
    comm: Dict[str, Dict] = {}
    for scope in sorted(set(scopes_a) | set(scopes_b)):
        a = scopes_a.get(scope)
        b = scopes_b.get(scope)
        comm[scope] = {
            "a_bytes": a,
            "b_bytes": b,
            "delta_bytes": (b or 0.0) - (a or 0.0),
        }

    delta_pct = None
    if rate_a and rate_b:
        delta_pct = (rate_b - rate_a) / rate_a * 100.0
    return {
        "items_per_sec_a": rate_a,
        "items_per_sec_b": rate_b,
        "delta_pct": delta_pct,
        "components": components,
        "comm_bytes": comm,
        "dominant": best_name or "insufficient-data",
        "dominant_delta_s": best_delta if best_name else None,
        "dominant_share": dominant_share,
    }
