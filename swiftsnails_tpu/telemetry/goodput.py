"""Goodput / MFU accounting: hardware-utilization numbers for a run.

Combines the two raw signal sources PR 1 built —

* the host span tracer (prefetch-wait / h2d / step spans per step), and
* the compiled-HLO audit (per-step FLOPs, bytes accessed, collective bytes)

— into the metrics TPU training stacks report as first-class: **MFU**
(model FLOP utilization: achieved FLOP/s over the chip's peak), a
**step-time decomposition** (compute vs collective vs host-blocked vs h2d),
**goodput** (fraction of wall-clock spent inside productive steps), and a
**words/sec-vs-roofline ratio** (measured throughput over the
compute/memory-roofline bound for the compiled step).

Everything here is pure host-side arithmetic over already-recorded data:
no device work, no extra hot-path cost. Peaks come from a per-device-kind
table (published chip specs) overridable via the ``peak_flops`` /
``peak_hbm_gbps`` / ``peak_ici_gbps`` config keys — on CPU (tier-1 tests,
smoke runs) there is no meaningful peak, so MFU degrades to ``None``
rather than inventing a number.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

# Published per-chip peaks: (bf16 TFLOP/s, HBM GB/s, ICI GB/s per link-set).
# Keyed by substrings of jax's ``device_kind`` / platform names; first match
# wins. These anchor MFU the way the pjit-at-scale reports do (arXiv:
# 2204.06514 reports hardware FLOP/s utilization against the chip peak).
_PEAKS = (
    ("v6", (918.0, 1640.0, 448.0)),       # Trillium / v6e
    ("v5p", (459.0, 2765.0, 600.0)),
    ("v5 lite", (197.0, 819.0, 200.0)),   # v5e device_kind is "TPU v5 lite"
    ("v5e", (197.0, 819.0, 200.0)),
    ("v5", (459.0, 2765.0, 600.0)),
    ("v4", (275.0, 1228.0, 300.0)),
    ("v3", (123.0, 900.0, 100.0)),
    ("v2", (46.0, 700.0, 62.0)),
)


def peaks_for(device_kind: Optional[str]) -> Dict[str, Optional[float]]:
    """Peak FLOP/s, HBM B/s, ICI B/s for a device kind (None when unknown,
    e.g. CPU — never invent a utilization denominator)."""
    if device_kind:
        kind = device_kind.lower()
        for key, (tf, hbm, ici) in _PEAKS:
            if key in kind:
                return {
                    "flops_per_s": tf * 1e12,
                    "hbm_bytes_per_s": hbm * 1e9,
                    "ici_bytes_per_s": ici * 1e9,
                    "source": f"builtin table ({key})",
                }
    return {
        "flops_per_s": None,
        "hbm_bytes_per_s": None,
        "ici_bytes_per_s": None,
        "source": "unknown device kind",
    }


def peaks_from_config(cfg, device_kind: Optional[str]) -> Dict:
    """Table peaks with config-key overrides (``peak_flops`` in FLOP/s,
    ``peak_hbm_gbps`` / ``peak_ici_gbps`` in GB/s)."""
    peaks = peaks_for(device_kind)
    if cfg is not None:
        pf = cfg.get_float("peak_flops", 0.0)
        if pf > 0:
            peaks["flops_per_s"] = pf
            peaks["source"] = "config"
        hbm = cfg.get_float("peak_hbm_gbps", 0.0)
        if hbm > 0:
            peaks["hbm_bytes_per_s"] = hbm * 1e9
        ici = cfg.get_float("peak_ici_gbps", 0.0)
        if ici > 0:
            peaks["ici_bytes_per_s"] = ici * 1e9
    return peaks


# ------------------------------------------------------ span decomposition ---

# spans the TrainLoop emits, bucketed for the decomposition
_SPAN_BUCKETS = {
    "step": "compute_s",          # jitted dispatch + device sync
    "h2d": "h2d_s",
    "prefetch-wait": "host_blocked_s",
    "tier-fault": "host_blocked_s",       # tiered residency work on the step
    "tier-flush-wait": "host_blocked_s",  # async write-back drain barriers
    "metrics-flush": "other_s",
    "checkpoint": "other_s",
}


def step_time_decomposition(events: Iterable[Dict]) -> Dict:
    """Bucketed wall-clock split from tracer span events.

    ``events`` is ``Tracer.events()`` output (dicts with ``name``/``ts_us``/
    ``dur_us``). Top-level spans only (depth<=1 buckets; the per-step outer
    ``step_span`` carries the trainer name and is skipped so nothing is
    counted twice). Fractions are of the traced wall-clock between the first
    span start and the last span end.
    """
    out = {
        "wall_s": 0.0, "compute_s": 0.0, "h2d_s": 0.0,
        "host_blocked_s": 0.0, "other_s": 0.0, "steps": 0,
    }
    t0, t1 = float("inf"), float("-inf")
    for e in events:
        ts = float(e.get("ts_us", 0.0))
        dur = float(e.get("dur_us", 0.0))
        t0 = min(t0, ts)
        t1 = max(t1, ts + dur)
        bucket = _SPAN_BUCKETS.get(e.get("name"))
        if bucket is not None:
            out[bucket] += dur / 1e6
            if e.get("name") == "step":
                out["steps"] += 1
    if t1 > t0:
        out["wall_s"] = (t1 - t0) / 1e6
    wall = out["wall_s"]
    if wall > 0:
        accounted = (
            out["compute_s"] + out["h2d_s"] + out["host_blocked_s"] + out["other_s"]
        )
        out["compute_frac"] = out["compute_s"] / wall
        out["h2d_frac"] = out["h2d_s"] / wall
        out["host_blocked_frac"] = out["host_blocked_s"] / wall
        out["other_frac"] = out["other_s"] / wall
        out["unaccounted_frac"] = max(1.0 - accounted / wall, 0.0)
    return out


# ------------------------------------------------------------- roofline ---


def roofline_step_seconds(
    flops: Optional[float],
    hbm_bytes: Optional[float],
    collective_bytes: Optional[float],
    peaks: Dict,
) -> Optional[float]:
    """Lower bound on one step's duration from the compiled cost analysis:
    max over the compute, HBM, and interconnect rooflines (each skipped when
    its peak or numerator is unknown)."""
    bounds = []
    if flops and peaks.get("flops_per_s"):
        bounds.append(flops / peaks["flops_per_s"])
    if hbm_bytes and peaks.get("hbm_bytes_per_s"):
        bounds.append(hbm_bytes / peaks["hbm_bytes_per_s"])
    if collective_bytes and peaks.get("ici_bytes_per_s"):
        bounds.append(collective_bytes / peaks["ici_bytes_per_s"])
    return max(bounds) if bounds else None


def goodput_report(
    *,
    events: Optional[Sequence[Dict]] = None,
    audit: Optional[Dict] = None,
    steps: Optional[int] = None,
    items: Optional[int] = None,
    step_seconds: Optional[float] = None,
    peaks: Optional[Dict] = None,
    n_chips: int = 1,
) -> Dict:
    """The per-run goodput block.

    Inputs are all optional — the report states what it could compute and
    carries ``None`` for the rest (a CPU smoke run has spans but no peak;
    an audit-less run has timings but no FLOPs).

    * ``events``: tracer span dicts (gives the decomposition + step timing);
    * ``audit``: a :func:`telemetry.audit.audit_step` report (FLOPs, bytes
      accessed, collective bytes) for ONE step dispatch;
    * ``steps`` / ``items``: loop totals (items = words/examples);
    * ``step_seconds``: measured per-step seconds — derived from the spans
      when absent;
    * ``peaks``: :func:`peaks_for` / :func:`peaks_from_config` output;
    * ``n_chips``: devices sharing the audited step's FLOPs (per-chip MFU).
    """
    peaks = peaks or peaks_for(None)
    report: Dict = {"peaks": {k: v for k, v in peaks.items()}}

    dec = None
    if events:
        dec = step_time_decomposition(events)
        report["decomposition"] = dec
        if steps is None:
            steps = dec["steps"] or None
    if steps:
        report["steps"] = int(steps)
    if items is not None:
        report["items"] = int(items)

    if step_seconds is None and dec and dec["steps"]:
        step_seconds = dec["compute_s"] / dec["steps"]
    report["step_seconds"] = step_seconds

    # goodput: productive (in-step) fraction of the traced wall-clock
    if dec and dec["wall_s"] > 0:
        report["goodput"] = dec["compute_s"] / dec["wall_s"]

    flops = hbm_bytes = coll_bytes = None
    if audit:
        cost = audit.get("cost", {}) or {}
        flops = cost.get("flops")
        hbm_bytes = cost.get("bytes_accessed")
        coll_bytes = audit.get("total_bytes", audit.get("collective_bytes"))
        report["flops_per_step"] = flops
        report["hbm_bytes_per_step"] = hbm_bytes
        report["collective_bytes_per_step"] = coll_bytes

    # MFU: achieved FLOP/s over peak, per chip
    mfu = None
    if flops and step_seconds and peaks.get("flops_per_s"):
        mfu = (flops / n_chips) / step_seconds / peaks["flops_per_s"]
    report["mfu"] = mfu

    # model-based split of the measured step time into compute vs collective
    # (roofline estimates normalized onto the measured step — labeled est)
    if step_seconds and step_seconds > 0:
        comp_est = (
            flops / n_chips / peaks["flops_per_s"]
            if flops and peaks.get("flops_per_s") else None
        )
        coll_est = (
            coll_bytes / n_chips / peaks["ici_bytes_per_s"]
            if coll_bytes and peaks.get("ici_bytes_per_s") else None
        )
        if comp_est is not None or coll_est is not None:
            # the seconds estimates are kept alongside the fractions so the
            # collective share can be cross-checked directly against the
            # audited bytes / ICI peak (the scale-out lane records both and
            # attributes an overlap/quantization win to the right term)
            report["step_split_est"] = {
                "compute_frac": (comp_est or 0.0) / step_seconds,
                "collective_frac": (coll_est or 0.0) / step_seconds,
                "compute_seconds_est": comp_est,
                "collective_seconds_est": coll_est,
            }

    # words/sec vs roofline: measured items/s over the bound the compiled
    # step admits on this chip
    ideal_s = roofline_step_seconds(
        flops / n_chips if flops else None,
        hbm_bytes / n_chips if hbm_bytes else None,
        coll_bytes / n_chips if coll_bytes else None,
        peaks,
    )
    report["roofline_step_seconds"] = ideal_s
    if ideal_s and steps and items and step_seconds:
        items_per_step = items / steps
        measured_rate = items_per_step / step_seconds
        roofline_rate = items_per_step / ideal_s
        report["items_per_sec"] = measured_rate
        report["roofline_items_per_sec"] = roofline_rate
        report["vs_roofline"] = measured_rate / roofline_rate
    elif steps and items and step_seconds:
        report["items_per_sec"] = (items / steps) / step_seconds
    return report
