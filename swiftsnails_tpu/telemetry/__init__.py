"""Unified telemetry: span tracing, metric registry, communication audit.

Three legs, one subsystem (the observability the reference never had —
SURVEY §5 lists glog lines and a chrono ``Timer`` as its entire surface):

* :mod:`~swiftsnails_tpu.telemetry.tracer` — host-side nestable spans with
  Chrome trace-event export, bridged to ``jax.profiler`` step annotations;
* :mod:`~swiftsnails_tpu.telemetry.registry` — named counters / gauges /
  histograms flushed through pluggable sinks
  (:class:`~swiftsnails_tpu.utils.metrics.MetricsLogger` is the JSONL sink;
  :class:`StdoutSummarySink` the terminal one);
* :mod:`~swiftsnails_tpu.telemetry.audit` — per-collective op counts/bytes
  and cost/memory analysis from a step function's optimized HLO, sync and
  async collective forms alike.

Off by default: the TrainLoop only constructs these when the ``telemetry``
or ``trace_path`` config keys are set, and its hot path pays one
enabled-flag check otherwise.
"""

from swiftsnails_tpu.telemetry.audit import (
    audit_compiled,
    audit_step,
    collective_bytes,
    collective_stats,
    compiled_collective_bytes,
)
from swiftsnails_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    StdoutSummarySink,
)
from swiftsnails_tpu.telemetry.summary import summarize_file
from swiftsnails_tpu.telemetry.tracer import Tracer

# the JSONL sink IS the existing MetricsLogger (same ``log``/``close``
# surface) — imported under the sink name so call sites read as intended
from swiftsnails_tpu.utils.metrics import MetricsLogger as JsonlSink

__all__ = [
    "Tracer",
    "MetricRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "StdoutSummarySink",
    "audit_compiled",
    "audit_step",
    "collective_bytes",
    "collective_stats",
    "compiled_collective_bytes",
    "summarize_file",
]
