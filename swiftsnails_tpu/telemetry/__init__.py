"""Unified telemetry: tracing, metrics, audit — and the flight recorder.

Six legs, one subsystem (the observability the reference never had —
SURVEY §5 lists glog lines and a chrono ``Timer`` as its entire surface):

* :mod:`~swiftsnails_tpu.telemetry.tracer` — host-side nestable spans with
  Chrome trace-event export, bridged to ``jax.profiler`` step annotations;
* :mod:`~swiftsnails_tpu.telemetry.registry` — named counters / gauges /
  histograms flushed through pluggable sinks
  (:class:`~swiftsnails_tpu.utils.metrics.MetricsLogger` is the JSONL sink;
  :class:`StdoutSummarySink` the terminal one);
* :mod:`~swiftsnails_tpu.telemetry.audit` — per-collective op counts/bytes
  and cost/memory analysis from a step function's optimized HLO, sync and
  async collective forms alike;
* :mod:`~swiftsnails_tpu.telemetry.ledger` — durable append-only JSONL run
  ledger (atomic tmp+rename writes): bench results, training runs, outage
  events, black-box dumps; ``BENCH_LAST_GOOD.json`` is a derived view;
* :mod:`~swiftsnails_tpu.telemetry.goodput` — MFU, step-time decomposition
  (compute vs collective vs host-blocked), words/sec-vs-roofline, combining
  tracer spans with the HLO audit's cost analysis;
* :mod:`~swiftsnails_tpu.telemetry.blackbox` — bounded ring of the last N
  steps' spans/metrics, dumped to disk on exception, NaN/Inf loss, SIGTERM.

The serving/freshness plane adds three more (docs/OBSERVABILITY.md):

* :mod:`~swiftsnails_tpu.telemetry.request_trace` — request-scoped
  distributed tracing: propagable trace/span ids, deterministic head
  sampling plus always-keep tail sampling for anomalies, ring-buffered
  with JSONL / Chrome-trace export;
* :mod:`~swiftsnails_tpu.telemetry.slo` — windowed SLO tracker with
  multi-window burn-rate alerting, error-budget accounting, and a
  ``should_scale()`` hook, emitting ``slo_burn`` ledger events;
* :mod:`~swiftsnails_tpu.telemetry.ops` — the one-screen fleet dashboard
  (``python -m swiftsnails_tpu ops`` / the serve REPL's ``ops`` op).

And the training plane three more (docs/OBSERVABILITY.md §11–13):

* :mod:`~swiftsnails_tpu.telemetry.timeseries` — continuous profiling: a
  bounded ring of periodic registry/goodput samples, JSONL export, and
  terminal sparklines for ``ledger-report`` / ``ops``;
* :mod:`~swiftsnails_tpu.telemetry.drift` — the online drift sentinel:
  EWMA/CUSUM detectors over the training-plane signals, transition-edged
  ``drift`` ledger events, and atomic incident bundles;
* ``ledger-report --diff A B`` (:func:`goodput.throughput_attribution`) —
  regression attribution between two run/bench records.

Off by default: the TrainLoop only constructs these when the ``telemetry``
or ``trace_path`` config keys are set, and its hot path pays one
enabled-flag check otherwise.
"""

from swiftsnails_tpu.telemetry.audit import (
    audit_compiled,
    audit_step,
    collective_bytes,
    collective_stats,
    compiled_collective_bytes,
)
from swiftsnails_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    StdoutSummarySink,
)
from swiftsnails_tpu.telemetry.blackbox import BlackBox
from swiftsnails_tpu.telemetry.drift import (
    DriftSentinel,
    EwmaCusum,
    build_incident_bundle,
    bundle_complete,
)
from swiftsnails_tpu.telemetry.goodput import (
    goodput_report,
    peaks_for,
    step_time_decomposition,
    throughput_attribution,
)
from swiftsnails_tpu.telemetry.ledger import (
    Ledger,
    config_hash,
    derive_last_good,
    env_fingerprint,
    load_bench_cache,
    validate_bench_payload,
)
from swiftsnails_tpu.telemetry.ops import render_ops, render_ops_from_ledger
from swiftsnails_tpu.telemetry.request_trace import (
    RequestContext,
    RequestTracer,
    tree_complete,
)
from swiftsnails_tpu.telemetry.slo import SloObjective, SloTracker
from swiftsnails_tpu.telemetry.summary import summarize_file
from swiftsnails_tpu.telemetry.timeseries import (
    TimeSeriesStore,
    render_sparklines,
    sparkline,
)
from swiftsnails_tpu.telemetry.tracer import Tracer

# the JSONL sink IS the existing MetricsLogger (same ``log``/``close``
# surface) — imported under the sink name so call sites read as intended
from swiftsnails_tpu.utils.metrics import MetricsLogger as JsonlSink

__all__ = [
    "Tracer",
    "RequestContext",
    "RequestTracer",
    "SloObjective",
    "SloTracker",
    "tree_complete",
    "render_ops",
    "render_ops_from_ledger",
    "MetricRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "StdoutSummarySink",
    "BlackBox",
    "Ledger",
    "TimeSeriesStore",
    "DriftSentinel",
    "EwmaCusum",
    "build_incident_bundle",
    "bundle_complete",
    "render_sparklines",
    "sparkline",
    "throughput_attribution",
    "audit_compiled",
    "audit_step",
    "collective_bytes",
    "collective_stats",
    "compiled_collective_bytes",
    "config_hash",
    "derive_last_good",
    "env_fingerprint",
    "goodput_report",
    "load_bench_cache",
    "peaks_for",
    "step_time_decomposition",
    "summarize_file",
    "validate_bench_payload",
]
