"""Compiled-HLO communication audit.

Deterministic, hardware-independent accounting of a jitted step function's
collective traffic: per-collective op counts and bytes from the optimized
HLO text, plus compiler cost/memory analysis. This is the measurement the
labs already trusted ("compiled psum/all-gather volume transfers to
hardware; vCPU wall time does not" — ``tools/kernel_lab.py``), promoted to
a library and fixed to recognize ASYNC collective forms: XLA may emit
``all-gather-start``/``all-gather-done`` pairs instead of the sync op on
some backend/flag combinations, and the old anchor (``all-gather(``)
silently reported 0 bytes for those (ADVICE r5).

Parsing contract: only DEFINING instructions are counted (``= shape op(``) —
a loose match would also count every consumer line naming the collective's
result — and ``-done`` halves of async pairs never match (the op name must
be followed by ``(`` or ``-start(``). For async starts that define a tuple,
the traffic-carrying shape is taken as the largest tuple element (the
result; operand aliases and ``u32[]`` context scalars are smaller).

Reduce-scatter is the one family whose DEFINING shape understates the
wire: the sync form's result is the 1/N owned slice of the summed operand,
so billing the result alone undercounts the traffic N-fold (every element
of the full operand crosses the interconnect exactly as in an all-reduce's
reduce phase). For ``reduce-scatter``/``all-reduce-scatter`` the billed
bytes are therefore the max shape atom across the instruction's operand
list as well as its result, with the same dtype-exact sub-byte rule
(``(n*bits+7)//8``) as everywhere else.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

# collective op families, longest-prefix first so e.g. "all-gather" never
# swallows "all-to-all"'s hyphenated cousins
COLLECTIVE_OPS = (
    "all-reduce-scatter",  # historical alias, keep before all-reduce
    "reduce-scatter",
    "all-reduce",
    "all-gather",
    "ragged-all-to-all",
    "all-to-all",
    "collective-broadcast",
    "collective-permute",
)

# element widths in BITS: sub-byte dtypes (s4/u4, the native int4 planes)
# really cost half a byte per element on the wire, and counting them as u8
# elements would understate a quantized wire's measured reduction by 2x
_DTYPE_BITS = {
    "s4": 4, "u4": 4,
    "pred": 8, "s8": 8, "u8": 8,
    "f8e4m3fn": 8, "f8e5m2": 8, "f8e4m3b11fnuz": 8, "f8e4m3fnuz": 8,
    "f8e5m2fnuz": 8,
    "s16": 16, "u16": 16, "f16": 16, "bf16": 16,
    "s32": 32, "u32": 32, "f32": 32,
    "s64": 64, "u64": 64, "f64": 64, "c64": 64,
    "c128": 128,
}

# defining instruction: "<name> = <shape> <op>[-start](", where <shape> is a
# single "dtype[dims]{layout}" or a tuple "(shape, shape, ...)"
_DEFINING_RE = re.compile(
    r"=\s+(?P<shape>\([^=]*?\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<op>%s)(?P<start>-start)?\(" % "|".join(COLLECTIVE_OPS)
)
_SHAPE_ATOM_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_SCOPE_RE = re.compile(r"(ssn_[\w\-.]+)")

# ops whose defining shape is a 1/N slice of the moved payload: bill the
# operand list too (sync reduce-scatter results understate traffic N-fold)
_FULL_OPERAND_OPS = frozenset({"reduce-scatter", "all-reduce-scatter"})

# ops whose tuple result IS the payload, one element per peer: XLA lowers a
# tiled shard_map all-to-all to "(T[n,..]{..}, ...) all-to-all(T[n,..] a, ...)"
# with axis_size equal pieces — max-element billing would undercount the
# moved buffer axis_size-fold, so these sum every tuple element instead
_SUM_TUPLE_OPS = frozenset({"all-to-all", "ragged-all-to-all"})


def _atom_bytes(dtype: str, dims: str) -> int:
    bits = _DTYPE_BITS.get(dtype)
    if bits is None:  # token/opaque/tuple-in-tuple: carries no payload here
        return 0
    shape = [int(d) for d in dims.split(",") if d]
    n = int(np.prod(shape)) if shape else 1
    return (n * bits + 7) // 8  # dtype-exact: s4/u4 pack two per byte


def _shape_bytes(shape: str) -> int:
    """Bytes of the traffic-carrying result shape (largest tuple element)."""
    atoms = _SHAPE_ATOM_RE.findall(shape)
    if not atoms:
        return 0
    return max(_atom_bytes(dt, dims) for dt, dims in atoms)


def _shape_bytes_sum(shape: str) -> int:
    """Bytes summed over every shape atom (per-peer tuple pieces)."""
    return sum(_atom_bytes(dt, dims) for dt, dims in
               _SHAPE_ATOM_RE.findall(shape))


def collective_stats(hlo_text: str) -> Dict:
    """Per-collective counts/bytes (sync and async forms) from HLO text.

    Returns ``{"ops": {op: {"count", "bytes"}}, "total_bytes", "by_scope",
    "by_table"}`` where ``op`` is the base HLO name (``-start`` folded in)
    and ``by_scope`` groups bytes under the first non-table ``ssn_*`` label
    found in the instruction's ``op_name`` metadata (see the
    ``jax.named_scope`` labels in ``parallel/transfer.py`` /
    ``parallel/store.py``). ``ssn_tbl_*`` labels are the per-table
    attribution scopes the trainers wrap around whole pull/push call sites
    (outer scopes, so they co-occur with the collective's own label on one
    ``op_name``); they are routed to ``by_table`` keyed by the table name so
    the placement/bench stack can split exchange bytes per table without
    disturbing the existing per-collective scope keys.
    """
    ops: Dict[str, Dict[str, int]] = {}
    by_scope: Dict[str, int] = {}
    by_table: Dict[str, int] = {}
    total = 0
    for line in hlo_text.splitlines():
        m = _DEFINING_RE.search(line)
        if m is None:
            continue
        nbytes = _shape_bytes(m.group("shape"))
        op = m.group("op")
        if op in _SUM_TUPLE_OPS:
            nbytes = _shape_bytes_sum(m.group("shape"))
            if m.group("start"):
                # async start tuples carry operand aliases next to the
                # results; summing both would double-bill the payload
                nbytes //= 2
        if op in _FULL_OPERAND_OPS:
            # operand shapes sit inside the call parens; stop before the
            # metadata blob so op_name strings can't smuggle in fake atoms
            tail = line[m.end():]
            cut = tail.find("metadata=")
            if cut != -1:
                tail = tail[:cut]
            nbytes = max(nbytes, _shape_bytes(tail))
        entry = ops.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += nbytes
        total += nbytes
        name_m = _OP_NAME_RE.search(line)
        if name_m:
            scoped = False
            for scope_m in _SCOPE_RE.finditer(name_m.group(1)):
                scope = scope_m.group(1)
                if scope.startswith("ssn_tbl_"):
                    tbl = scope[len("ssn_tbl_"):]
                    by_table[tbl] = by_table.get(tbl, 0) + nbytes
                elif not scoped:
                    # first non-table label = the collective's own scope
                    by_scope[scope] = by_scope.get(scope, 0) + nbytes
                    scoped = True
    return {"ops": ops, "total_bytes": total, "by_scope": by_scope,
            "by_table": by_table}


def collective_bytes(hlo_text: str, op_pattern: Optional[str] = None) -> int:
    """Total bytes moved by collectives whose BASE op name matches
    ``op_pattern`` (regex, fullmatch; ``None`` = every collective). Async
    ``-start`` forms count under their base name."""
    stats = collective_stats(hlo_text)
    if op_pattern is None:
        return stats["total_bytes"]
    pat = re.compile(op_pattern)
    return sum(
        entry["bytes"]
        for op, entry in stats["ops"].items()
        if pat.fullmatch(op)
    )


def _normalize_cost(cost) -> Dict[str, float]:
    """``compiled.cost_analysis()`` returns a dict or a 1-list of dicts
    depending on jax version; keep the headline keys only."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    out = {}
    for key in ("flops", "bytes accessed", "transcendentals"):
        if key in cost:
            out[key.replace(" ", "_")] = float(cost[key])
    return out


_MEMORY_ATTRS = (
    "peak_memory_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def _normalize_memory(mem) -> Dict[str, int]:
    out = {}
    for attr in _MEMORY_ATTRS:
        v = getattr(mem, attr, None)
        if v is not None:
            out[attr] = int(v)
    return out


def audit_compiled(compiled) -> Dict:
    """Audit an already-compiled executable (``jit(f).lower(...).compile()``)."""
    report = collective_stats(compiled.as_text())
    try:
        report["cost"] = _normalize_cost(compiled.cost_analysis())
    except Exception as e:  # some backends don't implement it
        report["cost"] = {"error": str(e)}
    try:
        report["memory"] = _normalize_memory(compiled.memory_analysis())
    except Exception as e:
        report["memory"] = {"error": str(e)}
    return report


def audit_step(fn, *args, **kwargs) -> Dict:
    """Lower+compile ``fn(*args, **kwargs)`` and audit the optimized HLO.

    ``fn`` may be a plain callable or an existing ``jax.jit`` wrapper (it is
    lowered as-is when it already has ``.lower``). Compilation only — nothing
    executes, so donated/sharded arguments are safe to pass.
    """
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return audit_compiled(jitted.lower(*args, **kwargs).compile())


def compiled_collective_bytes(fn, args: Sequence, op_pattern: str) -> int:
    """Bytes moved by collectives matching ``op_pattern`` in the optimized
    HLO of ``jit(fn)(*args)`` — the hardware-transferable traffic number
    (ICI volume scales the same way the compiled shapes do). Recognizes both
    sync (``all-gather(``) and async (``all-gather-start(``) forms; pass the
    base op names, e.g. ``"all-gather|all-reduce"``.
    """
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    hlo = jitted.lower(*args).compile().as_text()
    return collective_bytes(hlo, op_pattern)
