"""The one-screen ops dashboard: what an operator checks before paging.

Two renderers over the same layout, one per vantage point:

* :func:`render_ops` — a **live** serving surface (a
  :class:`~swiftsnails_tpu.serving.engine.Servant` or
  :class:`~swiftsnails_tpu.serving.fleet.Fleet` ``stats()``/``health()``
  snapshot): per-replica traffic split, p50/p99, cache hit rate, breaker
  and degraded state — plus, for a TCP ``NetFleet``, each replica's
  transport state (connected / reconnecting / drained) — the SLO
  tracker's burn rates and error budget, the
  freshness watermark/lag, and the most recent anomaly traces (each line
  names a ``trace_id`` the request tracer can still produce in full). The
  serve REPL's ``ops`` op prints this.
* :func:`render_ops_from_ledger` — the **offline** view reconstructed
  from a run ledger: the newest fleet bench block's per-replica numbers
  and tracing-overhead leg, the newest freshness and net lanes, and the
  recent ``slo_burn`` / ``trace_anomaly`` / ``freshness_gap`` event tail.
  ``python -m swiftsnails_tpu ops`` (or ``tools/ops_report.py``) prints
  this.

Both stay within one terminal screen on a healthy system — the point is
that *nothing to see here* fits at a glance, and anything worth drilling
names the trace_id / kernel / replica to drill into.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

__all__ = ["render_ops", "render_ops_from_ledger", "main"]


def _fmt(v: Any, nd: int = 2) -> str:
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v) if v is not None else "-"


def _replica_rows(per_replica: Dict[str, Dict]) -> List[str]:
    # a NetFleet's rows carry the TCP client state per replica
    # (connected / reconnecting / drained) — show the column only then,
    # so in-process fleets keep their narrow table
    net = any(isinstance(rs, dict) and "transport" in rs
              for rs in per_replica.values())
    lines = [
        "  replica  state    requests  p50_ms   p99_ms   hit     "
        + ("transport     " if net else "") + "breakers"
    ]
    for rid, rs in sorted(per_replica.items()):
        # live fleet.stats() nests latencies under kernels.pull; the bench
        # ledger block flattens them — accept either
        kern = rs.get("kernels", {}).get("pull", rs)
        breakers = rs.get("breakers")
        if isinstance(breakers, dict):
            open_b = [k for k, s in breakers.items() if s != "closed"]
            btxt = ",".join(f"{k}:{breakers[k]}" for k in open_b) or "closed"
        else:
            btxt = "-"
        hit = rs.get("cache_hit_rate")
        qps = rs.get("qps")
        ttxt = f"{str(rs.get('transport', '-')):<13} " if net else ""
        lines.append(
            f"  {rid:<8} {str(rs.get('state', '-')):<8} "
            f"{_fmt(qps, 1) + '/s' if qps is not None else _fmt(rs.get('requests')):<9} "
            f"{_fmt(kern.get('p50_ms')):<8} {_fmt(kern.get('p99_ms')):<8} "
            f"{_fmt(hit, 3):<7} {ttxt}{btxt}"
        )
    return lines


def _slo_rows(slo: Dict[str, Dict]) -> List[str]:
    lines = ["  kernel  slo_ms  avail    burn(s/l)    budget  alerting"]
    for kernel, s in sorted(slo.items()):
        lines.append(
            f"  {kernel:<7} {_fmt(s.get('slo_latency_ms'), 1):<7} "
            f"{_fmt(s.get('slo_availability'), 4):<8} "
            f"{_fmt(s.get('burn_short'))}/{_fmt(s.get('burn_long')):<7} "
            f"{_fmt(s.get('budget_remaining_pct'), 1):>5}%  "
            f"{'ALERTING' if s.get('alerting') else 'ok'}"
        )
    return lines


def _anomaly_rows(anomalies: List[Dict]) -> List[str]:
    lines = []
    for t in anomalies:
        kinds = ",".join(t.get("anomalies") or [])
        lines.append(
            f"  {t.get('trace_id')}  {str(t.get('kernel', '?')):<14} "
            f"{_fmt(t.get('dur_ms')):>8}ms  {kinds}"
        )
    return lines


def render_ops(
    stats: Dict,
    *,
    health: Optional[Dict] = None,
    anomalies: Optional[List[Dict]] = None,
) -> str:
    """Live dashboard from a ``stats()`` snapshot (Fleet or Servant shape),
    optionally a ``health()`` snapshot and recent anomaly trace dicts."""
    lines: List[str] = []
    per_replica = stats.get("replicas")
    fleet_mode = isinstance(per_replica, dict)
    status = (health or {}).get("status", "?")
    if fleet_mode:
        head = (
            f"fleet: status={status} replicas={len(per_replica)} "
            f"reroutes={stats.get('reroutes', 0)} "
            f"spills={stats.get('spills', 0)}"
        )
        hedge = stats.get("hedge")
        if isinstance(hedge, dict):
            head += (f" hedged={hedge.get('hedged', 0)}"
                     f" ({_fmt(hedge.get('rate_pct'), 1)}%"
                     f" of {_fmt(hedge.get('budget_pct'), 0)}% budget)")
        lines.append(head)
        lines.extend(_replica_rows(per_replica))
    else:
        kern = stats.get("kernels", {}).get("pull", {})
        cache = stats.get("cache", {})
        lines.append(
            f"servant: status={status} "
            f"requests={stats.get('requests', kern.get('count', '-'))} "
            f"p99={_fmt(kern.get('p99_ms'))}ms "
            f"hit={_fmt(cache.get('hit_rate'), 3)} "
            f"degraded={stats.get('degraded_served', 0)} "
            f"shed={stats.get('shed', 0)}"
        )
    slo = stats.get("slo")
    if isinstance(slo, dict) and slo:
        lines.append("slo:")
        lines.extend(_slo_rows(slo))
        # the should_scale() advisory, derived from the same snapshot rows:
        # any kernel alerting or out of error budget wants capacity
        wanting = sorted(
            k for k, s in slo.items()
            if s.get("alerting")
            or (s.get("budget_remaining_pct") is not None
                and s["budget_remaining_pct"] <= 0.0)
        )
        if wanting:
            lines.append(
                f"SCALE-UP? yes — {', '.join(wanting)} alerting or out of "
                "error budget (advisory; scale_hint ledgered on the edge)"
            )
    else:
        lines.append("slo: (not configured — set slo_latency_ms)")
    fresh = (health or {}).get("freshness")
    if isinstance(fresh, dict):
        lines.append(
            f"freshness: applied_seq={fresh.get('applied_seq')} "
            f"step={fresh.get('applied_step')} "
            f"lag={_fmt(fresh.get('last_lag_ms'))}ms "
            f"(p99 {_fmt(fresh.get('lag_p99_ms'))}ms) "
            f"fallbacks={fresh.get('fallbacks')} "
            f"stale={_fmt(fresh.get('stale'))}"
        )
    else:
        lines.append("freshness: (not subscribed)")
    trace = stats.get("trace")
    if isinstance(trace, dict):
        lines.append(
            f"traces: started={trace.get('started')} "
            f"kept={trace.get('kept')} "
            f"anomalies={trace.get('anomalies')} "
            f"ring={trace.get('ring')} "
            f"sample_rate={trace.get('sample_rate')}"
        )
        if anomalies:
            lines.append("recent anomaly traces (drill with trace-summary):")
            lines.extend(_anomaly_rows(anomalies[-5:]))
    else:
        lines.append("traces: (tracing off — set trace_sample_rate "
                     "or trace_anomaly_keep)")
    return "\n".join(lines)


# -- the ledger-backed offline view -------------------------------------------


def render_ops_from_ledger(ledger) -> str:
    """Offline dashboard reconstructed from a run ledger (see module doc)."""
    lines = [f"ops report: {ledger.path}"]
    benches = [r for r in ledger.records("bench")
               if isinstance(r.get("payload"), dict)]
    fleet_recs = [r for r in benches
                  if isinstance(r["payload"].get("fleet"), dict)]
    if fleet_recs:
        rec = fleet_recs[-1]
        fb = rec["payload"]["fleet"]
        inner = fb.get("fleet") if isinstance(fb.get("fleet"), dict) else {}
        lines.append(
            f"fleet lane ({rec.get('ts', '?')}): "
            f"max_qps={fb.get('qps')} p99={fb.get('p99_ms')}ms "
            f"scaling={fb.get('scaling_x')}x "
            f"(floor {fb.get('scaling_floor')}x)"
        )
        per_replica = inner.get("per_replica")
        if isinstance(per_replica, dict) and per_replica:
            lines.extend(_replica_rows(per_replica))
        to = fb.get("trace_overhead")
        if isinstance(to, dict):
            lines.append(
                f"  trace overhead: qps {_fmt(to.get('overhead_qps_pct'))}% "
                f"p99 {_fmt(to.get('overhead_p99_pct'))}% "
                f"(ceiling {_fmt(to.get('overhead_ceil_pct'), 0)}%, "
                f"sample rate {to.get('sample_rate')})"
            )
    else:
        lines.append("fleet lane: (no fleet bench record)")
    fresh_recs = [r for r in benches
                  if isinstance(r["payload"].get("freshness"), dict)]
    if fresh_recs:
        fr = fresh_recs[-1]["payload"]["freshness"]
        gap = fr.get("gap_drill") or {}
        lines.append(
            f"freshness lane: lag_p99={fr.get('lag_p99_ms')}ms "
            f"(ceiling {fr.get('lag_ceiling_ms')}ms) "
            f"parity={fr.get('bit_parity')} "
            f"gap_recovered={gap.get('recovered')}"
        )
    else:
        lines.append("freshness lane: (no freshness bench record)")
    net_recs = [r for r in benches
                if isinstance(r["payload"].get("net"), dict)]
    if net_recs:
        nb = net_recs[-1]["payload"]["net"]
        pk = nb.get("proc_kill") or {}
        dl = nb.get("delta") or {}
        lines.append(
            f"net lane: availability={nb.get('availability_pct')}% "
            f"(floor {nb.get('availability_floor_pct')}%) "
            f"tcp_p99={nb.get('p99_tcp_ms')}ms "
            f"({_fmt(nb.get('envelope_x'))}x in-process, "
            f"limit {_fmt(nb.get('envelope_limit_x'), 0)}x) "
            f"respawns={nb.get('respawns')} "
            f"kill_recovered={_fmt(pk.get('recovered'))} "
            f"delta_parity={dl.get('parity')}"
        )
        transports = ledger.records("transport")
        if transports:
            lines.append(
                f"  transport events: {len(transports)} "
                f"(newest {transports[-1].get('ts', '?')} "
                f"{transports[-1].get('event')}; "
                "drill with ledger-report --failures)"
            )
    else:
        lines.append("net lane: (no net bench record)")
    burns = ledger.records("slo_burn")
    if burns:
        newest = burns[-1]
        lines.append(
            f"error budget: {_fmt(newest.get('budget_remaining_pct'), 1)}% "
            f"left on {newest.get('kernel')} "
            f"({len(burns)} slo_burn events, newest {newest.get('ts', '?')})"
        )
        for r in burns[-3:]:
            lines.append(
                f"  {r.get('ts', '?')}  {r.get('source')}/{r.get('kernel')} "
                f"burn={r.get('burn_short')}/{r.get('burn_long')} "
                f"budget_left={r.get('budget_remaining_pct')}%"
            )
    else:
        lines.append("error budget: (no slo_burn events)")
    anomalies = ledger.records("trace_anomaly")
    if anomalies:
        lines.append(f"anomaly traces ({len(anomalies)} ledgered, "
                     "newest last; drill with trace-summary):")
        for r in anomalies[-5:]:
            kinds = r.get("anomalies")
            lines.append(
                f"  {r.get('ts', '?')}  {r.get('trace_id')}  "
                f"{str(r.get('kernel', '?')):<14} "
                f"{_fmt(r.get('dur_ms'))}ms  "
                f"{','.join(kinds) if isinstance(kinds, list) else kinds}"
            )
    else:
        lines.append("anomaly traces: (none ledgered)")
    gaps = ledger.records("freshness_gap")
    if gaps:
        newest = gaps[-1]
        lines.append(
            f"freshness gaps: {len(gaps)} events, newest "
            f"{newest.get('ts', '?')} reason={newest.get('reason')} "
            f"phase={newest.get('phase', 'publish')}"
        )
    lines.extend(_training_rows(ledger))
    return "\n".join(lines)


# canonical sparkline set for the training section (whatever subset the
# run's timeseries summary actually carries is drawn)
_TRAINING_SPARKS = (
    "step_ms", "loss", "win_host_blocked_frac", "win_compute_frac",
    "prefetch_stall_ms", "tier_hit_rate", "tier_flush_queue_depth",
)


def _training_rows(ledger) -> List[str]:
    """The training-plane section: the newest run record's goodput
    decomposition + continuous-profiling sparklines, the drift sentinel
    state, and the recent ``drift`` / ``scale_hint`` event tail."""
    lines: List[str] = []
    runs = ledger.records("run")
    if not runs:
        lines.append("training: (no run records)")
    else:
        run = runs[-1]
        gp = run.get("goodput") if isinstance(run.get("goodput"), dict) else {}
        head = (f"training ({run.get('ts', '?')}): model={run.get('model')} "
                f"steps={run.get('steps')}")
        from swiftsnails_tpu.telemetry.goodput import _record_rate

        rate = _record_rate(run)  # wall-based, same rate `--diff` headlines
        if isinstance(rate, (int, float)):
            head += f" items/s={rate:,.0f}"
        lines.append(head)
        dec = gp.get("decomposition")
        if isinstance(dec, dict) and dec.get("wall_s"):
            lines.append(
                "  step time: "
                f"compute {_fmt(100 * dec.get('compute_frac', 0), 1)}% | "
                f"h2d {_fmt(100 * dec.get('h2d_frac', 0), 1)}% | "
                f"host-blocked {_fmt(100 * dec.get('host_blocked_frac', 0), 1)}% | "
                f"other {_fmt(100 * dec.get('other_frac', 0), 1)}% | "
                f"unaccounted {_fmt(100 * dec.get('unaccounted_frac', 0), 1)}%"
            )
        ts_block = run.get("timeseries")
        if isinstance(ts_block, dict) and ts_block.get("series"):
            from swiftsnails_tpu.telemetry.timeseries import render_sparklines

            names = [n for n in _TRAINING_SPARKS if n in ts_block["series"]]
            lines.append(
                f"  profile window: {ts_block.get('window')} samples, steps "
                f"{ts_block.get('first_step')}..{ts_block.get('last_step')}"
            )
            lines.extend(render_sparklines(ts_block, names=names,
                                           indent="    "))
        drift = run.get("drift")
        if isinstance(drift, dict):
            tripped = drift.get("tripped") or []
            lines.append(
                f"  drift sentinel: "
                f"{'DRIFTED on ' + ', '.join(tripped) if drift.get('drifted') else 'ok'}"
                f" ({drift.get('events', 0)} event(s))"
            )
        incidents = run.get("incidents")
        if isinstance(incidents, list) and incidents:
            lines.append(f"  incident bundles: {len(incidents)}, newest "
                         f"{incidents[-1]}")
    drifts = ledger.records("drift")
    if drifts:
        lines.append(f"drift events: {len(drifts)}, newest last:")
        for r in drifts[-3:]:
            sigs = r.get("signals")
            lines.append(
                f"  {r.get('ts', '?')}  step={r.get('step')}  "
                f"{','.join(sigs) if isinstance(sigs, list) else sigs}"
            )
    else:
        lines.append("drift events: (none ledgered)")
    hints = ledger.records("scale_hint")
    if hints:
        newest = hints[-1]
        kerns = newest.get("kernels")
        lines.append(
            f"scale hints: {len(hints)} events, newest {newest.get('ts', '?')} "
            f"({','.join(kerns) if isinstance(kerns, list) else kerns})"
        )
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m swiftsnails_tpu ops [LEDGER.jsonl]``."""
    import os

    from swiftsnails_tpu.telemetry.ledger import DEFAULT_LEDGER, Ledger

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print("usage: ops [LEDGER.jsonl]   # one-screen fleet dashboard "
              "from a run ledger")
        return 0
    path = argv[0] if argv else os.environ.get("SSN_LEDGER_PATH",
                                               DEFAULT_LEDGER)
    ledger = Ledger(path)
    if not os.path.exists(ledger.path):
        print(f"ops: no ledger at {ledger.path}", file=sys.stderr)
        return 1
    print(render_ops_from_ledger(ledger))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
