"""Windowed SLO tracking: availability + latency objectives per kernel,
multi-window burn-rate alerting, and the error budget the future
autoscaler will spend.

One :class:`SloTracker` watches the serving plane. Every request is
recorded as *good* or *bad* — bad means it raised a typed failure **or**
came back over the kernel's latency objective, the unified treatment: both
spend the same error budget. Two sliding windows are kept per kernel:

* the **long** window (``slo_window_s``) — the budget horizon;
* the **short** window (``slo_window_s / 12``) — the classic fast-burn
  companion (5m against 1h), so a sudden fire alerts in seconds while a
  slow leak still needs sustained evidence.

The *burn rate* is ``bad_fraction / (1 - availability)``: 1.0 spends the
budget exactly by the end of the window; the tracker alerts when **both**
windows burn at ``alert_burn`` or faster (two windows is what keeps a
single stray request from paging). Entering the alerting state emits one
structured ``slo_burn`` ledger event (transition-edged, so a sustained
burn is one line, not a line per request), and :meth:`should_scale` is
the hook the autoscaler will poll: it fires while any kernel is alerting
or has exhausted its budget.

The clock is injectable (``clock=``) so burn-rate math is testable with a
fake clock; recording is lock-guarded and O(1) amortized — record-keeping
never blocks the serve path.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

__all__ = ["SloObjective", "SloTracker"]

# Short window = long window / 12, the 5m-vs-1h ratio scaled to whatever
# horizon the config picks.
_SHORT_DIV = 12.0
_DEFAULT_WINDOW_S = 60.0
_DEFAULT_ALERT_BURN = 2.0


class SloObjective:
    """One kernel's objectives: latency bound and availability target."""

    __slots__ = ("latency_ms", "availability")

    def __init__(self, latency_ms: float, availability: float = 0.999):
        if not 0.0 < availability < 1.0:
            raise ValueError(f"availability must be in (0, 1): {availability}")
        self.latency_ms = float(latency_ms)
        self.availability = float(availability)

    @property
    def budget(self) -> float:
        """The error budget: the tolerated bad fraction."""
        return 1.0 - self.availability


class SloTracker:
    """Sliding-window burn-rate tracker over per-kernel objectives.

    ``objectives`` maps kernel name to an :class:`SloObjective` (or a
    ``(latency_ms, availability)`` tuple, or a bare latency float with the
    default availability). Unknown kernels recorded later are tracked
    against ``default`` when given, else ignored.
    """

    def __init__(
        self,
        objectives: Dict[str, Any],
        *,
        window_s: float = _DEFAULT_WINDOW_S,
        alert_burn: float = _DEFAULT_ALERT_BURN,
        default: Optional[SloObjective] = None,
        ledger=None,
        source: str = "serving",
        clock: Callable[[], float] = time.monotonic,
    ):
        self.window_s = float(window_s)
        self.short_s = self.window_s / _SHORT_DIV
        self.alert_burn = float(alert_burn)
        self.default = default
        self.ledger = ledger
        self.source = source
        self._clock = clock
        self._lock = threading.Lock()
        self.objectives: Dict[str, SloObjective] = {}
        for k, v in objectives.items():
            self.objectives[k] = self._coerce(v)
        # per-kernel: deque of (ts, bad) pairs, pruned past the long window
        self._events: Dict[str, deque] = {k: deque() for k in self.objectives}
        self._alerting: Dict[str, bool] = {k: False for k in self.objectives}
        self._burn_events = 0
        self._recorded = 0
        self._scale_hinted = False
        self._scale_hints = 0

    @classmethod
    def from_config(cls, config, *, ledger=None,
                    clock: Callable[[], float] = time.monotonic,
                    kernels=("pull", "topk", "score"),
                    source: str = "serving") -> Optional["SloTracker"]:
        """Build from typed config keys, or ``None`` when no latency
        objective is set (``slo_latency_ms`` <= 0 disables tracking)."""
        lat = config.get_float("slo_latency_ms", 0.0)
        if lat <= 0:
            return None
        obj = SloObjective(lat, config.get_float("slo_availability", 0.999))
        return cls(
            {k: obj for k in kernels},
            window_s=config.get_float("slo_window_s", _DEFAULT_WINDOW_S),
            default=obj, ledger=ledger, source=source, clock=clock,
        )

    @staticmethod
    def _coerce(v: Any) -> SloObjective:
        if isinstance(v, SloObjective):
            return v
        if isinstance(v, (tuple, list)):
            return SloObjective(*v)
        return SloObjective(float(v))

    # -- recording -------------------------------------------------------

    def record(self, kernel: str, latency_ms: float, ok: bool = True) -> None:
        """Record one request outcome; bad = failed or over latency SLO."""
        obj = self.objectives.get(kernel)
        if obj is None:
            if self.default is None:
                return
            obj = self.default
            with self._lock:
                self.objectives.setdefault(kernel, obj)
                self._events.setdefault(kernel, deque())
                self._alerting.setdefault(kernel, False)
        now = self._clock()
        bad = (not ok) or (float(latency_ms) > obj.latency_ms)
        with self._lock:
            ev = self._events[kernel]
            ev.append((now, bad))
            self._prune(ev, now)
            self._recorded += 1
            burn_s, burn_l = self._burns(kernel, now)
            alerting = (burn_s >= self.alert_burn
                        and burn_l >= self.alert_burn)
            entered = alerting and not self._alerting[kernel]
            self._alerting[kernel] = alerting
        if entered:
            self._note_burn(kernel, burn_s, burn_l, now)

    def _prune(self, ev: deque, now: float) -> None:
        horizon = now - self.window_s
        while ev and ev[0][0] < horizon:
            ev.popleft()

    # -- burn math (callers hold no lock; internal helpers assume it) ----

    def _window_counts(self, kernel: str, now: float, span_s: float):
        horizon = now - span_s
        total = bad = 0
        for ts, b in self._events.get(kernel, ()):
            if ts >= horizon:
                total += 1
                if b:
                    bad += 1
        return total, bad

    def _burns(self, kernel: str, now: float):
        obj = self.objectives[kernel]
        out = []
        for span in (self.short_s, self.window_s):
            total, bad = self._window_counts(kernel, now, span)
            out.append((bad / total) / obj.budget if total else 0.0)
        return out[0], out[1]

    def burn_rates(self, kernel: str) -> Dict[str, float]:
        """Current short/long burn rates (1.0 = budget gone by window end)."""
        now = self._clock()
        with self._lock:
            if kernel not in self.objectives:
                return {"short": 0.0, "long": 0.0}
            s, l = self._burns(kernel, now)
        return {"short": round(s, 4), "long": round(l, 4)}

    def error_budget_remaining(self, kernel: str) -> float:
        """Fraction of the long-window error budget left, in [0, 1]."""
        now = self._clock()
        with self._lock:
            obj = self.objectives.get(kernel)
            if obj is None:
                return 1.0
            total, bad = self._window_counts(kernel, now, self.window_s)
        if not total:
            return 1.0
        allowed = obj.budget * total
        if allowed <= 0:
            return 0.0 if bad else 1.0
        return max(0.0, min(1.0, 1.0 - bad / allowed))

    # -- surfaces --------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Per-kernel state for the ops dashboard."""
        now = self._clock()
        out: Dict[str, Any] = {}
        with self._lock:
            kernels = list(self.objectives)
        for k in kernels:
            with self._lock:
                obj = self.objectives[k]
                total, bad = self._window_counts(k, now, self.window_s)
                s, l = self._burns(k, now)
                alerting = self._alerting[k]
            allowed = obj.budget * total
            remaining = (1.0 if not total else
                         max(0.0, min(1.0, 1.0 - bad / allowed))
                         if allowed > 0 else (0.0 if bad else 1.0))
            out[k] = {
                "slo_latency_ms": obj.latency_ms,
                "slo_availability": obj.availability,
                "window_s": self.window_s,
                "total": total,
                "bad": bad,
                "burn_short": round(s, 4),
                "burn_long": round(l, 4),
                "budget_remaining_pct": round(remaining * 100.0, 2),
                "alerting": alerting,
            }
        return out

    def should_scale(self) -> bool:
        """The autoscaler hook: True while any kernel is alerting or has
        spent its whole long-window budget.

        Transition-edged like the burn alert: the False->True crossing
        appends one ``scale_hint`` ledger event naming the kernels that
        want capacity (the ``ops`` dashboard's ``SCALE-UP?`` advisory and
        the future autoscaler both read it); sustained pressure is one
        line, and the edge re-arms once the pressure clears."""
        now = self._clock()
        wanting = []
        with self._lock:
            for k in self.objectives:
                if self._alerting.get(k):
                    wanting.append(k)
                    continue
                obj = self.objectives[k]
                total, bad = self._window_counts(k, now, self.window_s)
                # budget fully spent counts even after the burn cooled off
                if total and obj.budget > 0 and bad >= obj.budget * total:
                    wanting.append(k)
            entered = bool(wanting) and not self._scale_hinted
            self._scale_hinted = bool(wanting)
            if entered:
                self._scale_hints += 1
        if entered:
            self._note_scale_hint(wanting)
        return bool(wanting)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"recorded": self._recorded,
                    "burn_events": self._burn_events,
                    "scale_hints": self._scale_hints}

    # -- ledger ----------------------------------------------------------

    def _note_burn(self, kernel: str, burn_s: float, burn_l: float,
                   now: float) -> None:
        with self._lock:
            self._burn_events += 1
        led = self.ledger
        if led is None:
            return
        obj = self.objectives[kernel]
        try:
            led.append("slo_burn", {
                "source": self.source,
                "kernel": kernel,
                "burn_short": round(burn_s, 3),
                "burn_long": round(burn_l, 3),
                "alert_burn": self.alert_burn,
                "budget_remaining_pct": round(
                    self.error_budget_remaining(kernel) * 100.0, 2),
                "slo_latency_ms": obj.latency_ms,
                "slo_availability": obj.availability,
                "window_s": self.window_s,
            })
        except Exception:
            pass  # record-keeping never blocks the serve path

    def _note_scale_hint(self, kernels) -> None:
        led = self.ledger
        if led is None:
            return
        try:
            led.append("scale_hint", {
                "source": self.source,
                "kernels": sorted(kernels),
                "burns": {k: self.burn_rates(k) for k in kernels},
                "budget_remaining_pct": {
                    k: round(self.error_budget_remaining(k) * 100.0, 2)
                    for k in kernels
                },
                "window_s": self.window_s,
            })
        except Exception:
            pass  # advisory only — never blocks the serve path
