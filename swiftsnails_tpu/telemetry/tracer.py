"""Host-side span tracer with Chrome trace-event export.

The reference's only timeline instrumentation was glog timestamps and a
chrono ``Timer`` (SURVEY §5). This tracer answers "where did this step's
time go" on the host side: nestable spans (per-thread stacks), thread-safe
recording, and export to the Chrome/Perfetto trace-event JSON format, so a
``trace_path`` file drops straight into ``chrome://tracing`` / ui.perfetto.dev
— or into ``tools/trace_summary.py`` for a terminal breakdown.

Device-side alignment: :meth:`Tracer.step_span` opens the host span inside a
``jax.profiler.StepTraceAnnotation``, so when a ``profile_dir`` capture runs
concurrently (utils/profiling.py), the host spans and the XLA device timeline
carry the same step numbers and line up in the combined view.

Cost contract: a Tracer only exists when telemetry is enabled (the TrainLoop
holds ``None`` otherwise and branches once per step). Recording one span is
one ``perf_counter_ns`` pair, one small tuple, and one lock-guarded append.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

# event tuples: (name, ts_ns, dur_ns, tid, depth, args_or_None) for "X"
# spans; counters are recorded separately as (name, ts_ns, value, tid).
_Event = Tuple[str, int, int, int, int, Optional[Dict]]


class _SpanCtx:
    """Reusable-shape context manager recording one complete ("X") event."""

    __slots__ = ("_tracer", "_name", "_args", "_t0", "_depth")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[Dict]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_SpanCtx":
        tls = self._tracer._tls
        self._depth = getattr(tls, "depth", 0)
        tls.depth = self._depth + 1
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter_ns()
        self._tracer._tls.depth = self._depth
        self._tracer._record(
            (self._name, self._t0, t1 - self._t0, threading.get_ident(),
             self._depth, self._args)
        )


class _StepSpanCtx:
    """Host span + ``jax.profiler.StepTraceAnnotation`` for device alignment."""

    __slots__ = ("_span", "_ann")

    def __init__(self, tracer: "Tracer", name: str, step: int):
        self._span = _SpanCtx(tracer, name, {"step": step})
        import jax

        self._ann = jax.profiler.StepTraceAnnotation(name, step_num=step)

    def __enter__(self) -> "_StepSpanCtx":
        self._ann.__enter__()
        self._span.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self._span.__exit__(*exc)
        self._ann.__exit__(*exc)


class Tracer:
    """Thread-safe span recorder with Chrome trace-event JSON export.

    ``path`` (optional): where :meth:`close` writes the trace. Spans nest per
    thread; concurrent threads (e.g. the prefetcher) record independently and
    render as separate tracks.
    """

    def __init__(self, path: Optional[str] = None, process_name: str = "swiftsnails_tpu"):
        self.path = path
        self.process_name = process_name
        self._events: List[_Event] = []
        self._counters: List[Tuple[str, int, float, int]] = []
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._epoch_ns = time.perf_counter_ns()
        self._closed = False

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **args) -> _SpanCtx:
        """Open a nestable span: ``with tracer.span("h2d"): ...``"""
        return _SpanCtx(self, name, args or None)

    def step_span(self, name: str, step: int) -> _StepSpanCtx:
        """A span that also labels the device timeline with the step number."""
        return _StepSpanCtx(self, name, step)

    def counter(self, name: str, value: float) -> None:
        """Record an instantaneous counter sample (Chrome "C" event)."""
        with self._lock:
            self._counters.append(
                (name, time.perf_counter_ns(), float(value), threading.get_ident())
            )

    def _record(self, event: _Event) -> None:
        with self._lock:
            self._events.append(event)

    # -- export ------------------------------------------------------------

    def events(self, start: int = 0) -> List[Dict]:
        """The recorded spans as dicts (name, ts_us, dur_us, tid, depth,
        args), from index ``start`` on — the continuous profiler reads only
        its window this way, instead of re-converting the whole run's spans
        every sample."""
        with self._lock:
            snap = self._events[start:]
        return [
            {
                "name": name,
                "ts_us": (t0 - self._epoch_ns) / 1e3,
                "dur_us": dur / 1e3,
                "tid": tid,
                "depth": depth,
                "args": args or {},
            }
            for name, t0, dur, tid, depth, args in snap
        ]

    def chrome_trace(self) -> Dict:
        """The trace as a Chrome trace-event object (``traceEvents`` list)."""
        pid = os.getpid()
        with self._lock:
            spans = list(self._events)
            counters = list(self._counters)
        events: List[Dict] = [
            {
                "ph": "M",
                "pid": pid,
                "name": "process_name",
                "args": {"name": self.process_name},
            }
        ]
        for name, t0, dur, tid, depth, args in spans:
            ev = {
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "name": name,
                "cat": "host",
                "ts": (t0 - self._epoch_ns) / 1e3,  # microseconds
                "dur": dur / 1e3,
            }
            if args:
                ev["args"] = args
            events.append(ev)
        for name, t0, value, tid in counters:
            events.append(
                {
                    "ph": "C",
                    "pid": pid,
                    "tid": tid,
                    "name": name,
                    "ts": (t0 - self._epoch_ns) / 1e3,
                    "args": {"value": value},
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(), f)

    def close(self) -> None:
        """Finalize: write the trace to ``path`` (idempotent, keeps events)."""
        if self._closed:
            return
        if self.path:
            self.export(self.path)
        self._closed = True
