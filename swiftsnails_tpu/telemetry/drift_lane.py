"""The bench ``drift`` lane: the scripted slow-step drift drill + the
continuous profiler's own-overhead measurement.

One implementation used by ``bench.py --lane drift``,
``tools/chaos_drill.py --drift``, and the tier-1 fast subset, mirroring
how :mod:`swiftsnails_tpu.resilience.drill` backs the ``chaos`` lane —
the drill and the gate cannot drift apart.

Two measurements, one JSON-ready block each:

* :func:`drift_drill` — a control run and a ``slow_step@A-B`` chaos run
  share one ledger; the chaos run must *detect* the injected drift
  within the run (step-time EWMA/CUSUM), emit exactly one
  transition-edged ``drift`` ledger event, leave a complete incident
  bundle behind, and the before/after run records' ``--diff``
  attribution must name host-blocked as the dominant contributor.
* :func:`profiler_overhead` — words/sec with the sampler + sentinel on
  vs off at equal work, warm-then-best-of-3 per leg (the chaos lane's
  guardrail-overhead recipe), with the off leg's own spread as the
  noise floor. ``ledger-report --check-regression`` fails the lane when
  the overhead clears both the 3% ceiling and the noise floor.

Everything is deterministic (fixed seeds, fixed fault schedule) and
CPU-sized: the whole lane runs in seconds under ``JAX_PLATFORMS=cpu``.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Dict, Optional

# the drill's fault schedule: slow_step on a late contiguous band, long
# enough that host-blocked dominates the A->B delta over compile jitter
DRILL_STEPS = 48
INJECT_FIRST = 16
INJECT_LAST = 43
SLOW_STEP_MS = 80.0
PROFILE_CADENCE = 4        # the overhead legs' realistic sampling cadence
OVERHEAD_CEIL_PCT = 3.0    # the acceptance bar the gate enforces


def _workdir(workdir: Optional[str]) -> str:
    if workdir:
        os.makedirs(workdir, exist_ok=True)
        return workdir
    return tempfile.mkdtemp(prefix="ssn-drift-")


def drift_drill(workdir: Optional[str] = None,
                small: bool = True) -> Dict:
    """Run the before/after drift drill; returns the gateable ``drift``
    block (detection, event count, bundle, attribution)."""
    from swiftsnails_tpu.resilience.drill import make_trainer, run_loop
    from swiftsnails_tpu.telemetry.drift import bundle_complete
    from swiftsnails_tpu.telemetry.goodput import throughput_attribution
    from swiftsnails_tpu.telemetry.ledger import Ledger

    t0 = time.monotonic()
    base = _workdir(workdir)
    ledger_path = os.path.join(base, "DRILL_LEDGER.jsonl")
    incident_dir = os.path.join(base, "incidents")
    common = {
        "telemetry": 1,
        "profile_cadence": 1,
        "profile_window": 256,
        "num_iters": 8,
        "ledger_path": ledger_path,
        "incident_dir": incident_dir,
    }

    # before: the undisturbed control run (drift sentinel off — its run
    # record is the --diff baseline, not a detection subject)
    ctrl_dir = os.path.join(base, "before")
    os.makedirs(ctrl_dir, exist_ok=True)
    tr = make_trainer(ctrl_dir, **dict(
        common, blackbox_dir=os.path.join(ctrl_dir, "blackbox")))
    run_loop(tr, max_steps=DRILL_STEPS)

    # after: same work + slow_step@A-B chaos, sentinel armed
    drift_dir = os.path.join(base, "after")
    os.makedirs(drift_dir, exist_ok=True)
    tr2 = make_trainer(drift_dir, **dict(
        common,
        blackbox_dir=os.path.join(drift_dir, "blackbox"),
        drift_detect=1,
        chaos_spec=f"slow_step@{INJECT_FIRST}-{INJECT_LAST}",
        chaos_slow_step_ms=SLOW_STEP_MS,
    ))
    loop, _state, _steps = run_loop(tr2, max_steps=DRILL_STEPS)

    ledger = Ledger(ledger_path)
    runs = ledger.records("run")
    drift_events = ledger.records("drift")
    det = (loop.drift.detectors.get("step_ms")
           if loop.drift is not None else None)
    detect_step = det.drift_step if det is not None else None
    detected = (detect_step is not None
                and INJECT_FIRST <= detect_step <= INJECT_LAST)
    bundle = loop.incidents[0] if loop.incidents else None
    attribution = (throughput_attribution(runs[-2], runs[-1])
                   if len(runs) >= 2 else {"dominant": "insufficient-data"})
    return {
        "detected": bool(detected),
        "detect_step": detect_step,
        "inject_step": INJECT_FIRST,
        "inject_last": INJECT_LAST,
        "slow_step_ms": SLOW_STEP_MS,
        "window_steps": DRILL_STEPS,
        "drift_events": len(drift_events),
        "signals": list(loop.drift.tripped) if loop.drift else [],
        "bundle": bundle,
        "bundle_complete": bool(bundle and bundle_complete(bundle)),
        "attribution": attribution,
        "ledger": ledger_path,
        "small": small,
        "elapsed_s": round(time.monotonic() - t0, 1),
    }


def profiler_overhead(workdir: Optional[str] = None,
                      small: bool = True) -> Dict:
    """Words/sec with continuous profiling (sampler + drift sentinel at
    ``PROFILE_CADENCE``) on vs off, equal work; returns the gateable
    ``profile_overhead`` block."""
    from swiftsnails_tpu.framework.trainer import TrainLoop
    from swiftsnails_tpu.resilience.drill import make_trainer

    t0 = time.monotonic()
    base = _workdir(workdir)
    over = {
        "telemetry": 1,
        "dim": 16 if small else 64,
        "batch_size": 512 if small else 2048,
        "window": 2,
        "num_iters": 60,
    }
    # reps long enough (hundreds of ms) to average over machine-load
    # bursts — sub-100ms reps made the ratio pure scheduler noise
    warm, steps, reps = (3, 768, 3) if small else (3, 1024, 3)

    def make_loop(extra: Dict):
        d = tempfile.mkdtemp(dir=base)
        tr = make_trainer(d, **dict(
            over,
            blackbox_dir=os.path.join(d, "blackbox"),
            incident_dir=os.path.join(d, "incidents"),
            **extra))
        loop = TrainLoop(tr, log_every=0)
        loop.run(max_steps=warm)  # pays the jit compile
        return loop

    def timed(loop) -> float:
        i0 = loop._items_seen
        t1 = time.monotonic()
        loop.run(max_steps=steps)
        dt = max(time.monotonic() - t1, 1e-9)
        # rate from items actually trained, not the requested step count —
        # a short epoch silently capping the run must not skew one leg
        return (loop._items_seen - i0) / dt

    # the legs are interleaved rep-by-rep so machine-load drift hits both
    # equally; per-leg MEDIAN is the robust estimator under bursty load.
    # The on leg pays the sampler + the sentinel's full detector
    # arithmetic; the trip threshold is parked out of reach because
    # incident-response I/O (bundle build on a spurious trip) is not
    # steady-state profiling cost.
    loop_off = make_loop({"profile_cadence": 0})
    loop_on = make_loop({"profile_cadence": PROFILE_CADENCE,
                         "drift_detect": 1, "drift_cusum_h": 1e6})
    off, on = [], []
    for _ in range(reps):
        off.append(timed(loop_off))
        on.append(timed(loop_on))
    off_s, on_s = sorted(off), sorted(on)
    wps_off, wps_on = off_s[len(off) // 2], on_s[len(on) // 2]
    overhead_pct = ((wps_off - wps_on) / wps_off * 100.0
                    if wps_off else None)
    noise_pct = ((max(off) - min(off)) / wps_off * 100.0
                 if wps_off else 0.0)
    return {
        "words_per_sec_off": round(wps_off, 1),
        "words_per_sec_on": round(wps_on, 1),
        "overhead_pct": (round(overhead_pct, 2)
                         if overhead_pct is not None else None),
        "noise_pct": round(noise_pct, 2),
        "overhead_ceil_pct": OVERHEAD_CEIL_PCT,
        "cadence": PROFILE_CADENCE,
        "small": small,
        "elapsed_s": round(time.monotonic() - t0, 1),
    }


def drift_bench(workdir: Optional[str] = None, small: bool = True) -> Dict:
    """The full lane: drill + overhead, as one JSON-ready block (lands in
    the bench line, the run ledger, and the ``--check-regression`` gate)."""
    base = _workdir(workdir)
    drill = drift_drill(os.path.join(base, "drill"), small=small)
    overhead = profiler_overhead(os.path.join(base, "overhead"), small=small)
    ok = (drill["detected"] and drill["drift_events"] == 1
          and drill["bundle_complete"]
          and (drill["attribution"] or {}).get("dominant") == "host_blocked"
          and overhead["overhead_pct"] is not None
          and overhead["overhead_pct"] <= max(
              OVERHEAD_CEIL_PCT, overhead["noise_pct"]))
    return {"drift": drill, "profile_overhead": overhead, "ok": ok}
