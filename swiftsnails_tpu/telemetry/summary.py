"""Render a trace / metrics file into a per-span time breakdown.

Input formats (auto-detected):

* Chrome trace-event JSON (``Tracer.export`` / ``trace_path``) — aggregates
  the complete ("X") events per span name: count, total/mean/min/max ms, and
  share of the traced wall-clock (first span start to last span end);
* metrics JSONL (``MetricsLogger`` / ``metrics_path``) — aggregates every
  numeric field across records: count, mean, min, max, last.

Used by ``tools/trace_summary.py`` and the ``trace-summary`` CLI command.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence


def load_events(path: str) -> Optional[List[Dict]]:
    """Chrome trace "X" events from ``path``, or None if not a trace file."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (ValueError, UnicodeDecodeError):
        return None
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
    elif isinstance(doc, list):  # bare event-array form is also valid
        events = doc
    else:
        return None
    if not isinstance(events, list):
        return None
    return [e for e in events if isinstance(e, dict) and e.get("ph") == "X"]


def summarize_events(events: Sequence[Dict]) -> List[Dict]:
    """Per-name aggregate rows, sorted by total time descending."""
    agg: Dict[str, Dict] = {}
    t_min, t_max = float("inf"), float("-inf")
    for e in events:
        name = e.get("name", "?")
        dur = float(e.get("dur", 0.0))
        ts = float(e.get("ts", 0.0))
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
        row = agg.setdefault(
            name, {"name": name, "count": 0, "total_us": 0.0,
                   "min_us": float("inf"), "max_us": 0.0}
        )
        row["count"] += 1
        row["total_us"] += dur
        row["min_us"] = min(row["min_us"], dur)
        row["max_us"] = max(row["max_us"], dur)
    wall_us = max(t_max - t_min, 1e-9)
    rows = sorted(agg.values(), key=lambda r: -r["total_us"])
    for row in rows:
        row["mean_us"] = row["total_us"] / row["count"]
        row["wall_pct"] = 100.0 * row["total_us"] / wall_us
    return rows


def render_events(rows: Sequence[Dict], wall_note: str = "") -> str:
    """Terminal table for :func:`summarize_events` rows."""
    if not rows:
        return "no spans recorded"
    name_w = max(len(r["name"]) for r in rows)
    name_w = max(name_w, len("span"))
    head = (f"{'span'.ljust(name_w)}  {'count':>6}  {'total ms':>10}  "
            f"{'mean ms':>9}  {'min ms':>8}  {'max ms':>8}  {'% wall':>6}")
    lines = [head, "-" * len(head)]
    for r in rows:
        lines.append(
            f"{r['name'].ljust(name_w)}  {r['count']:>6}  "
            f"{r['total_us'] / 1e3:>10.3f}  {r['mean_us'] / 1e3:>9.3f}  "
            f"{r['min_us'] / 1e3:>8.3f}  {r['max_us'] / 1e3:>8.3f}  "
            f"{r['wall_pct']:>6.1f}"
        )
    if wall_note:
        lines.append(wall_note)
    return "\n".join(lines)


def load_jsonl(path: str) -> List[Dict]:
    """Records of a metrics JSONL file (bad lines skipped)."""
    records = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def summarize_jsonl(records: Sequence[Dict]) -> List[Dict]:
    """Per-field aggregate rows over numeric JSONL fields."""
    agg: Dict[str, Dict] = {}
    for rec in records:
        for k, v in rec.items():
            if k == "ts" or isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            row = agg.setdefault(
                k, {"field": k, "count": 0, "sum": 0.0,
                    "min": float("inf"), "max": float("-inf"), "last": v}
            )
            row["count"] += 1
            row["sum"] += v
            row["min"] = min(row["min"], v)
            row["max"] = max(row["max"], v)
            row["last"] = v
    rows = sorted(agg.values(), key=lambda r: r["field"])
    for row in rows:
        row["mean"] = row["sum"] / row["count"]
    return rows


def render_jsonl(rows: Sequence[Dict], n_records: int) -> str:
    if not rows:
        return "no numeric fields found"
    field_w = max(max(len(r["field"]) for r in rows), len("field"))
    head = (f"{'field'.ljust(field_w)}  {'count':>6}  {'mean':>12}  "
            f"{'min':>12}  {'max':>12}  {'last':>12}")
    lines = [f"{n_records} records", head, "-" * len(head)]
    for r in rows:
        lines.append(
            f"{r['field'].ljust(field_w)}  {r['count']:>6}  {r['mean']:>12.6g}  "
            f"{r['min']:>12.6g}  {r['max']:>12.6g}  {r['last']:>12.6g}"
        )
    return "\n".join(lines)


def summarize_file(path: str) -> str:
    """Auto-detect trace vs JSONL and render the breakdown."""
    events = load_events(path)
    if events is not None:
        return render_events(summarize_events(events))
    records = load_jsonl(path)
    if records:
        return render_jsonl(summarize_jsonl(records), len(records))
    raise ValueError(
        f"{path}: neither a Chrome trace (traceEvents) nor a metrics JSONL file"
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="trace_summary",
        description="Per-span time breakdown of a trace_path / metrics_path file.",
    )
    p.add_argument("path", help="Chrome trace JSON or metrics JSONL file")
    args = p.parse_args(argv)
    try:
        print(summarize_file(args.path))
    except BrokenPipeError:  # `trace-summary ... | head` is a normal use
        import os
        import sys

        # point stdout at devnull so the interpreter's exit-time flush
        # doesn't raise the same error again
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    except (OSError, ValueError) as e:
        print(f"trace_summary: {e}")
        return 1
    return 0
