"""Online drift sentinel: EWMA/CUSUM detectors + atomic incident bundles.

``ledger-report --check-regression`` catches a regression *between* bench
snapshots; nothing watches a live run for the slow-burn kind — step time
creeping 10% over an hour, tier hit rate sagging as the zipf head drifts,
exchange bytes growing after a placement change. This module is that
watcher:

* :class:`EwmaCusum` — one detector per signal. An EWMA tracks the
  signal's location and an EWMA of squared residuals its scale; each new
  sample's standardized residual feeds a two-sided CUSUM
  (``s = max(0, s + |z| - k)``); the drift is *confirmed* when the CUSUM
  statistic exceeds ``h``. The EWMA pair adapts to slow legitimate trends
  (warmup, LR decay) while the CUSUM accumulates only persistent
  excursions — a single slow step decays away, a sustained shift trips.
* :class:`DriftSentinel` — detectors over the five signals the training
  plane actually regresses on (step time, loss, exchange bytes, tier hit
  rate, prefetch stall), fed from the same samples the
  :class:`~swiftsnails_tpu.telemetry.timeseries.TimeSeriesStore` takes.
  Confirmation is **transition-edged**: crossing from healthy to drifted
  emits exactly one ``drift`` ledger event (naming every tripped signal)
  and stays silent until :meth:`DriftSentinel.reset` — no event storm
  while the condition persists.
* :func:`build_incident_bundle` — capture-while-it-happens: one atomic
  directory holding the blackbox ring, the timeseries window, the
  config/env fingerprint, and the kept trace spans. Built in a staging
  dir and ``os.rename``\\ d into place, with collision-safe naming so a
  drift trigger and a NaN trip in the same second land as two distinct
  bundles, never one clobbered dir.

Everything is pure host arithmetic on already-sampled numbers; the hot
path pays nothing beyond the profiling cadence it already opted into.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, List, Optional, Sequence

# signal name -> metric key in the sampler's flat dict (the canonical five;
# the sentinel accepts any subset — a run without tiering simply never
# feeds tier_hit_rate)
DEFAULT_SIGNALS = (
    "step_ms",
    "loss",
    "exchange_bytes",
    "tier_hit_rate",
    "prefetch_stall_ms",
)


class EwmaCusum:
    """Two-sided CUSUM over EWMA-standardized residuals for one signal.

    ``alpha``   EWMA smoothing for mean/variance (higher adapts faster);
    ``k``       CUSUM slack in sigmas (excursions below ``k`` don't
                accumulate — absorbs ordinary noise);
    ``h``       decision threshold in accumulated sigmas;
    ``warmup``  samples used to seed mean/variance before the CUSUM arms
                (a cold detector would trip on the jit-compile step).
    """

    def __init__(self, name: str, alpha: float = 0.3, k: float = 1.0,
                 h: float = 6.0, warmup: int = 8):
        self.name = name
        self.alpha = float(alpha)
        self.k = float(k)
        self.h = float(h)
        self.warmup = max(int(warmup), 1)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0
        self.stat = 0.0          # current CUSUM statistic (sigmas)
        self.peak = 0.0          # high-water mark (kept for the event)
        self.drifted = False
        self.drift_step: Optional[int] = None
        self.last = None

    def update(self, x: float, step: int = 0) -> bool:
        """Feed one sample; returns True on the sample that *confirms* a
        drift (the False->True edge only)."""
        x = float(x)
        if not math.isfinite(x):
            return False
        self.last = x
        self.n += 1
        if self.n <= 2:
            # the first sample is the cold-start/jit-compile step — an
            # outlier that would inflate the seeded variance by orders of
            # magnitude (and push real detections out by dozens of steps),
            # so it is discarded outright; the second sample seeds location
            self.mean = x
            return False
        resid = x - self.mean
        if self.n <= self.warmup + 1:
            # seed location/scale; CUSUM not armed yet
            self.mean += self.alpha * resid
            self.var += self.alpha * (resid * resid - self.var)
            return False
        sigma = math.sqrt(self.var) if self.var > 0 else 0.0
        if sigma <= 0:
            # flat warmup (e.g. constant gauge): any change is a unit shock
            sigma = abs(resid) or 1.0
        z = abs(resid) / sigma
        self.stat = max(0.0, self.stat + z - self.k)
        if self.stat > self.peak:
            self.peak = self.stat
        # adapt location/scale AFTER scoring, so a persistent shift keeps
        # accumulating for a few samples before the EWMA absorbs it
        self.mean += self.alpha * resid
        self.var += self.alpha * (resid * resid - self.var)
        if not self.drifted and self.stat >= self.h:
            self.drifted = True
            self.drift_step = int(step)
            return True
        return False

    def reset(self) -> None:
        """Re-arm after an incident (keeps the learned mean/variance)."""
        self.stat = 0.0
        self.peak = 0.0
        self.drifted = False
        self.drift_step = None

    def state(self) -> Dict:
        return {
            "signal": self.name,
            "n": self.n,
            "mean": self.mean,
            "sigma": math.sqrt(self.var) if self.var > 0 else 0.0,
            "stat": round(self.stat, 3),
            "peak": round(self.peak, 3),
            "last": self.last,
            "drifted": self.drifted,
            "drift_step": self.drift_step,
        }


class DriftSentinel:
    """Detectors over the training-plane signals, transition-edged.

    ``observe(step, signals)`` feeds every detector whose key appears in
    ``signals``. The sentinel-level state machine mirrors
    ``SloTracker._note_burn``: the healthy->drifted crossing appends one
    ``drift`` ledger event (best-effort, never raises into the loop) and
    returns the list of tripped signal names; while drifted, further
    confirmations accumulate into the same incident until :meth:`reset`.
    """

    def __init__(self, signals: Sequence[str] = DEFAULT_SIGNALS, *,
                 alpha: float = 0.3, k: float = 1.0, h: float = 6.0,
                 warmup: int = 8, ledger=None, context: Optional[Dict] = None):
        self.detectors: Dict[str, EwmaCusum] = {
            name: EwmaCusum(name, alpha=alpha, k=k, h=h, warmup=warmup)
            for name in signals
        }
        self._ledger = ledger
        self._context = dict(context or {})
        self.drifted = False
        self.events = 0           # drift ledger events emitted (edges)
        self.tripped: List[str] = []
        self.incidents: List[Dict] = []

    def observe(self, step: int, signals: Dict) -> List[str]:
        """Feed one sample row; returns newly-confirmed signal names
        (non-empty exactly when this call crossed the healthy->drifted
        edge or widened an open incident)."""
        confirmed = []
        for name, det in self.detectors.items():
            v = signals.get(name)
            if v is None:
                continue
            if det.update(v, step=step):
                confirmed.append(name)
        if not confirmed:
            return []
        newly = [n for n in confirmed if n not in self.tripped]
        self.tripped.extend(newly)
        if not self.drifted:
            # the transition edge: exactly one ledger event per incident
            self.drifted = True
            detail = {
                "step": int(step),
                "signals": list(confirmed),
                "detectors": [self.detectors[n].state() for n in confirmed],
            }
            detail.update(self._context)
            self.incidents.append(detail)
            self.events += 1
            if self._ledger is not None:
                try:
                    self._ledger.append("drift", detail)
                except Exception:
                    pass
        return confirmed

    def reset(self) -> None:
        """Close the incident and re-arm every detector."""
        self.drifted = False
        self.tripped = []
        for det in self.detectors.values():
            det.reset()

    def summary(self) -> Dict:
        return {
            "drifted": self.drifted,
            "events": self.events,
            "tripped": list(self.tripped),
            "detectors": {n: d.state() for n, d in self.detectors.items()},
        }


# ---------------------------------------------------------- incident bundle ---


BUNDLE_PREFIX = "incident"


def build_incident_bundle(directory, reason: str, *, blackbox=None,
                          timeseries=None, tracer=None,
                          context: Optional[Dict] = None,
                          extra: Optional[Dict] = None) -> str:
    """Capture one atomic incident directory; returns its path.

    Contents (each best-effort — a missing source is recorded as absent in
    the manifest, not an exception):

    * ``blackbox.json``    — the last-N-steps flight ring;
    * ``timeseries.jsonl`` — the profiling window, one sample per line;
    * ``fingerprint.json`` — config/env fingerprint + caller context;
    * ``traces.json``      — kept tracer spans (tail of the span ring);
    * ``manifest.json``    — reason, step range, file inventory.

    The bundle is staged under a hidden temp dir and ``os.rename``\\ d to
    ``incident-<UTCstamp>-<reason>``; on collision (two incidents in the
    same second — the drift + NaN interplay) a ``-2``/``-3``... suffix is
    tried, so bundles are always distinct directories.
    """
    from .ledger import env_fingerprint

    directory = os.fspath(directory)
    os.makedirs(directory, exist_ok=True)
    slug = "".join(c if (c.isalnum() or c in "-_") else "-" for c in reason)
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    staging = os.path.join(
        directory, f".{BUNDLE_PREFIX}-tmp-{os.getpid()}-{stamp}-{slug}")
    n = 2
    while os.path.exists(staging):
        staging = os.path.join(
            directory,
            f".{BUNDLE_PREFIX}-tmp-{os.getpid()}-{stamp}-{slug}-{n}")
        n += 1
    os.makedirs(staging)

    manifest: Dict = {
        "reason": reason,
        "created_utc": stamp,
        "files": [],
    }

    def _write(name: str, payload) -> None:
        path = os.path.join(staging, name)
        try:
            if name.endswith(".jsonl"):
                body = "".join(
                    json.dumps(r, sort_keys=True, default=str) + "\n"
                    for r in payload)
            else:
                body = json.dumps(payload, indent=2, sort_keys=True,
                                  default=str)
            with open(path, "w", encoding="utf-8") as f:
                f.write(body)
            manifest["files"].append(name)
        except Exception as e:  # pragma: no cover - defensive
            manifest.setdefault("errors", []).append(f"{name}: {e}")

    if blackbox is not None:
        try:
            ring = blackbox.snapshot()
        except Exception:
            ring = []
        _write("blackbox.json", ring)
        if ring:
            manifest["first_step"] = ring[0].get("step")
            manifest["last_step"] = ring[-1].get("step")
    if timeseries is not None:
        try:
            rows = timeseries.snapshot()
        except Exception:
            rows = []
        _write("timeseries.jsonl", rows)
        manifest["timeseries_samples"] = len(rows)
    fp: Dict = {"env": None, "context": dict(context or {})}
    try:
        fp["env"] = env_fingerprint(include_devices=True)
    except Exception:
        pass
    _write("fingerprint.json", fp)
    if tracer is not None:
        try:
            spans = tracer.events()[-256:]
        except Exception:
            spans = []
        _write("traces.json", spans)
    if extra:
        _write("extra.json", extra)
    _write("manifest.json", manifest)

    # atomic publish with collision-safe naming
    final = os.path.join(directory, f"{BUNDLE_PREFIX}-{stamp}-{slug}")
    n = 2
    while True:
        try:
            os.rename(staging, final)
            return final
        except OSError:
            if not os.path.exists(final):
                raise
            final = os.path.join(
                directory, f"{BUNDLE_PREFIX}-{stamp}-{slug}-{n}")
            n += 1


def bundle_complete(path) -> bool:
    """True when a bundle directory has the three load-bearing artifacts
    (timeseries window + blackbox + fingerprint) the drill gates on."""
    path = os.fspath(path)
    required = ("blackbox.json", "timeseries.jsonl", "fingerprint.json",
                "manifest.json")
    return all(os.path.exists(os.path.join(path, f)) for f in required)
