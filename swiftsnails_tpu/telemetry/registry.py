"""Named counters / gauges / histograms behind pluggable sinks.

Replaces ad-hoc dict plumbing with one registry surface: any component takes
a :class:`MetricRegistry` (or reaches a shared one) and records against named
instruments; the owner decides when to :meth:`~MetricRegistry.flush` and to
which sinks. A sink is anything with ``log(record: dict)`` and ``close()`` —
:class:`swiftsnails_tpu.utils.metrics.MetricsLogger` is the JSONL sink
unchanged, and :class:`StdoutSummarySink` renders the same records for a
terminal.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Deque, Dict, IO, List, Optional


class Counter:
    """Monotonic count (steps, items, drops)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-set value (queue depth, learning rate).

    ``updated_at`` is the wall-clock of the last :meth:`set` (``None``
    until first set) — the timeseries sampler and the ``ops`` dashboard
    both read it to tell a live gauge from a stale one.
    """

    __slots__ = ("name", "_value", "_updated_at")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._updated_at: Optional[float] = None

    def set(self, value: float) -> None:
        self._value = float(value)
        self._updated_at = time.time()

    @property
    def value(self) -> float:
        return self._value

    @property
    def updated_at(self) -> Optional[float]:
        return self._updated_at


class Histogram:
    """Summary stats over observed samples (step latencies).

    Keeps exact count/sum/min/max plus a bounded window of recent samples for
    percentiles — enough for per-window records without unbounded memory.

    **Exemplars.** ``observe(value, trace_id=...)`` remembers a small window
    of traced observations; :meth:`summary` reports the slowest of them as
    ``exemplar_value`` / ``exemplar_trace_id``, so the ``p99`` in any report
    links to one concrete request trace instead of an anonymous aggregate.
    Only pass ids of traces that will actually be *kept* (head-sampled or
    anomalous), or the link dangles.
    """

    __slots__ = ("name", "count", "total", "min", "max", "_recent",
                 "_traced", "_lock")

    def __init__(self, name: str, window: int = 512):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._recent: Deque[float] = deque(maxlen=window)
        self._traced: Deque = deque(maxlen=8)  # (value, trace_id) exemplars
        self._lock = threading.Lock()

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            if value < self.min:
                self.min = value
            if value > self.max:
                self.max = value
            self._recent.append(value)
            if trace_id is not None:
                self._traced.append((value, trace_id))

    def exemplar(self) -> Optional[Dict[str, float]]:
        """The slowest recent traced observation (tail exemplar), if any."""
        with self._lock:
            if not self._traced:
                return None
            value, trace_id = max(self._traced, key=lambda vt: vt[0])
        return {"value": value, "trace_id": trace_id}

    def summary(self) -> Dict[str, float]:
        with self._lock:
            if not self.count:
                return {"count": 0}
            recent = sorted(self._recent)
            q = lambda p: recent[min(int(p * (len(recent) - 1)), len(recent) - 1)]
            out = {
                "count": self.count,
                "sum": self.total,
                "mean": self.total / self.count,
                "min": self.min,
                "max": self.max,
                "p50": q(0.50),
                "p95": q(0.95),
                "p99": q(0.99),
            }
            if self._traced:
                value, trace_id = max(self._traced, key=lambda vt: vt[0])
                out["exemplar_value"] = value
                out["exemplar_trace_id"] = trace_id
            return out


class StdoutSummarySink:
    """Human-readable one-line rendering of each flushed record."""

    def __init__(self, stream: Optional[IO[str]] = None, prefix: str = "metrics"):
        self._stream = stream if stream is not None else sys.stdout
        self._prefix = prefix

    @staticmethod
    def _fmt(v) -> str:
        if isinstance(v, float):
            return f"{v:.6g}"
        return str(v)

    def log(self, record: Dict) -> None:
        body = "  ".join(
            f"{k}={self._fmt(v)}" for k, v in sorted(record.items()) if k != "ts"
        )
        self._stream.write(f"{self._prefix}: {body}\n")
        self._stream.flush()

    def close(self) -> None:
        pass


class MetricRegistry:
    """Get-or-create named instruments; flush snapshots to sinks."""

    def __init__(self, sinks: Optional[List] = None):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._sinks: List = list(sinks or [])
        self._lock = threading.Lock()

    def add_sink(self, sink) -> None:
        self._sinks.append(sink)

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    def snapshot(self) -> Dict[str, float]:
        """Flat ``name -> value`` view (histograms expand to name.stat)."""
        out: Dict[str, float] = {}
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
        for c in counters:
            out[c.name] = c.value
        for g in gauges:
            out[g.name] = g.value
        for h in hists:
            for stat, v in h.summary().items():
                out[f"{h.name}.{stat}"] = v
        return out

    def flush(self, **extra) -> Dict[str, float]:
        """Emit the current snapshot (+``extra`` fields) to every sink."""
        rec = self.snapshot()
        rec.update(extra)
        for sink in self._sinks:
            sink.log(rec)
        return rec

    def close(self) -> None:
        for sink in self._sinks:
            sink.close()
