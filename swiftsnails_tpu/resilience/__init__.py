"""Resilience subsystem: make every run survivable, every failure drillable.

The observability stack (PRs 1-2: tracer, ledger, black box, goodput) is the
*recording* half of production readiness; this package is the *action* half:

* :mod:`~swiftsnails_tpu.resilience.chaos` — deterministic, seeded fault
  injection (``chaos_spec`` / ``chaos_seed``): NaN/Inf updates, poisoned
  parameter rows, checkpoint bit rot, transient data-stream I/O errors,
  simulated preemption — each injection a ``chaos`` ledger event;
* :mod:`~swiftsnails_tpu.resilience.guardrail` — jit-compatible per-step
  health check with donated-buffer-safe rollback, batch skip, a halving/
  recovering trust factor, and a bounded give-up into a black-box dump
  (``guardrail``, ``guard_max_update_norm``, ``guard_max_consecutive``);
* :mod:`~swiftsnails_tpu.resilience.resume` — auto-resume from the newest
  *verified* checkpoint (manifest CRC walk-back on corruption), restoring
  the data-stream cursor so resumed loss curves continue instead of restart
  (``resume: auto``, with ``framework/checkpoint.py``);
* :mod:`~swiftsnails_tpu.resilience.drill` — the canned chaos drill matrix
  and the bench ``chaos`` lane's recovery-goodput measurement
  (``bench.py --lane chaos``, ``tools/chaos_drill.py``);
* :mod:`~swiftsnails_tpu.resilience.retry` — the unified deadline + retry
  policy (exponential backoff, decorrelated jitter, injectable clock) that
  every fallible host I/O path shares: the data stream, checkpoint
  save/restore, tier master flush/gather, Servant reload
  (``retry_max_attempts``, ``retry_deadline_ms``).

Cost contract: nothing here is imported unless a resilience config key is
set; the TrainLoop hot path pays flag checks only.
"""

from swiftsnails_tpu.resilience.chaos import (
    ChaosPlan,
    ChaosSpecError,
    TransientDataError,
    corrupt_checkpoint_dir,
    parse_chaos_spec,
)
from swiftsnails_tpu.resilience.guardrail import GuardrailExhausted, StepGuardrail
from swiftsnails_tpu.resilience.resume import resume_mode, resume_state
from swiftsnails_tpu.resilience.retry import (
    Deadline,
    DeadlineExceeded,
    RetryBudget,
    RetryExhausted,
    RetryingIterator,
    RetryPolicy,
    retry_call,
)

__all__ = [
    "ChaosPlan",
    "ChaosSpecError",
    "Deadline",
    "DeadlineExceeded",
    "GuardrailExhausted",
    "RetryBudget",
    "RetryExhausted",
    "RetryingIterator",
    "RetryPolicy",
    "StepGuardrail",
    "TransientDataError",
    "corrupt_checkpoint_dir",
    "parse_chaos_spec",
    "resume_mode",
    "resume_state",
    "retry_call",
]
