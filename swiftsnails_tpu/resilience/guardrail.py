"""Per-step health guardrail: detect a poisoned update, roll it back, recover.

The reference had no numeric-health story at all — a NaN'd gradient walked
straight into the sparse table and every later pull served it to every
worker. Since the flight-recorder PR the black box *records* that corpse;
this module prevents it: the TrainLoop snapshots the tables before the
(donated-buffer) step, checks the step's outcome with one fused jitted
reduction, and on a trip restores the snapshot so **no non-finite value ever
reaches the master tables**.

Semantics (see ``docs/RESILIENCE.md``):

* **trip conditions** — non-finite loss, non-finite update (NaN/Inf anywhere
  in the new state's float leaves shows up as a non-finite update norm), or
  an update-norm spike above ``guard_max_update_norm`` (0 disables the spike
  check; non-finiteness is always checked);
* **on trip** — roll back to the pre-step snapshot, skip the batch, halve the
  internal *trust factor*;
* **trust factor** — after a trip, subsequent clean updates are applied
  scaled (``state + trust * update``) and trust recovers exponentially
  (doubling per clean step) back to 1.0 — a burst of marginal steps re-enters
  at reduced step size instead of full speed;
* **give-up** — ``guard_max_consecutive`` consecutive trips raise
  :class:`GuardrailExhausted` (TrainLoop dumps the black box first): a
  persistently sick run must die loudly, not spin forever skipping batches.

Cost contract: when the ``guardrail`` config key is off the TrainLoop pays
one flag check per step and this module is never imported. On-path the
TrainLoop runs a NON-donating compile of the step (the input buffers are the
rollback snapshot — 2x table memory, no copy), plus one fused reduction over
the state and one host sync of its scalar result per step (the sync is what
makes "roll back before the next step" possible at all). Measured in the
bench ``chaos`` lane as ``guard_overhead_pct`` (~2-3% on the CPU control
leg).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class GuardrailExhausted(RuntimeError):
    """``guard_max_consecutive`` consecutive unhealthy steps: giving up."""


def _is_float_leaf(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


class StepGuardrail:
    """Snapshot / health-check / rollback state machine (host-side driver,
    jit-compiled math)."""

    def __init__(
        self,
        max_update_norm: float = 0.0,
        max_consecutive: int = 3,
        min_trust: float = 0.05,
        recovery: float = 2.0,
    ):
        self.max_update_norm = float(max_update_norm)
        self.max_consecutive = max(int(max_consecutive), 1)
        self.min_trust = float(min_trust)
        self.recovery = float(recovery)
        self.trust = 1.0
        self.consecutive = 0
        self.trips_total = 0
        self.steps_skipped = 0
        self.last_update_norm: Optional[float] = None
        self.last_trip_reason: Optional[str] = None

        @jax.jit
        def _update_sq(snap, new):
            s = jnp.float32(0.0)
            for a, b in zip(jax.tree_util.tree_leaves(snap),
                            jax.tree_util.tree_leaves(new)):
                if _is_float_leaf(a):
                    d = b.astype(jnp.float32) - a.astype(jnp.float32)
                    s = s + jnp.sum(d * d)
            return s

        @jax.jit
        def _blend(snap, new, t):
            def leaf(a, b):
                if not _is_float_leaf(a):
                    return b
                af = a.astype(jnp.float32)
                return (af + t * (b.astype(jnp.float32) - af)).astype(a.dtype)

            return jax.tree_util.tree_map(leaf, snap, new)

        self._update_sq = _update_sq
        self._blend = _blend

    # -- per-step API (driven by TrainLoop._resilient_step) -----------------

    @staticmethod
    def snapshot(state: Any) -> Any:
        """Pre-step copy of the state. The step fn donates its input buffers,
        so rollback is only possible from an independent copy taken *before*
        the call — ``jnp.copy`` preserves device placement and sharding."""
        return jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, state
        )

    def commit(
        self, snap: Any, new_state: Any, metrics: Dict
    ) -> Tuple[Any, Dict, bool, bool]:
        """Accept or roll back one step's outcome.

        Returns ``(state, metrics, tripped, exhausted)``. ``exhausted`` means
        the consecutive-trip budget is spent — the caller dumps the black box
        and raises :class:`GuardrailExhausted`.
        """
        norm_sq = float(self._update_sq(snap, new_state))  # host sync point
        loss = metrics.get("loss")
        loss_f = float(loss) if loss is not None else 0.0
        if math.isfinite(norm_sq) and norm_sq >= 0:
            norm = math.sqrt(norm_sq)
        else:
            norm = float("nan")
        self.last_update_norm = norm

        reason = None
        if not math.isfinite(loss_f):
            reason = f"non-finite loss ({loss_f})"
        elif not math.isfinite(norm):
            reason = "non-finite update (NaN/Inf in the new tables)"
        elif self.max_update_norm > 0 and norm > self.max_update_norm:
            reason = (
                f"update-norm spike ({norm:.4g} > "
                f"guard_max_update_norm={self.max_update_norm:.4g})"
            )

        if reason is None:
            self.consecutive = 0
            if self.trust < 1.0:
                new_state = self._blend(snap, new_state, np.float32(self.trust))
                metrics = dict(metrics)
                metrics["guard_trust"] = np.float32(self.trust)
                self.trust = min(1.0, self.trust * self.recovery)
            return new_state, metrics, False, False

        # trip: roll back, skip the batch, shrink trust
        self.last_trip_reason = reason
        self.consecutive += 1
        self.trips_total += 1
        self.steps_skipped += 1
        self.trust = max(self.trust * 0.5, self.min_trust)
        exhausted = self.consecutive >= self.max_consecutive
        trip_metrics = {
            "guard_tripped": np.float32(1.0),
            "guard_trust": np.float32(self.trust),
            "guard_consecutive": np.float32(self.consecutive),
        }
        # keep any finite metrics for the window log; drop the poisoned ones
        for k, v in metrics.items():
            try:
                fv = float(v)
            except (TypeError, ValueError):
                continue
            if math.isfinite(fv):
                trip_metrics.setdefault(k, v)
        return snap, trip_metrics, True, exhausted

    def summary(self) -> Dict:
        """Run-level accounting for the ledger's run record."""
        return {
            "trips_total": self.trips_total,
            "steps_skipped": self.steps_skipped,
            "trust": round(self.trust, 6),
            "last_update_norm": (
                round(self.last_update_norm, 6)
                if isinstance(self.last_update_norm, float)
                and math.isfinite(self.last_update_norm)
                else None
            ),
            "last_trip_reason": self.last_trip_reason,
            "max_update_norm": self.max_update_norm or None,
            "max_consecutive": self.max_consecutive,
        }
