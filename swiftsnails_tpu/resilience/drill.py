"""Canned chaos drills + the bench ``chaos`` lane's recovery measurement.

One implementation used by ``tools/chaos_drill.py`` (the CI drill runner),
``tests/test_chaos_drill.py`` (the tier-1 fast subset), and
``bench.py --lane chaos`` (recovery-goodput numbers in the bench JSON line),
so the drill matrix and the bench cannot drift apart.

Every drill is deterministic: fixed ``chaos_seed``, fixed data seed, fixed
fault schedule — a failure reproduces bit-identically. A drill *passes* when
the run **recovers**: it finishes its step budget (or resumes and finishes),
no non-finite value is left in the master tables, and — for the
corruption+preemption drill — the resumed run's final eval loss lands within
``LOSS_PARITY_BAR`` of an undisturbed control run.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

LOSS_PARITY_BAR = 0.05  # resumed-vs-undisturbed relative eval-loss bound

DRILLS = (
    "nan_burst",
    "inf_update",
    "row_poison",
    "io_error",
    "ckpt_walkback",
    "preempt_resume",
    "tier_bitflip",
    "tier_bitflip_int8",
)


# ------------------------------------------------------------ harness bits ---


def _drill_corpus():
    """The shared 128-word paired probe corpus (framework/quality.py) — small
    enough that every drill runs in seconds on CPU."""
    from swiftsnails_tpu.framework.quality import paired_corpus

    return paired_corpus(n_pairs=64, reps=1500, seed=0)


def make_trainer(workdir: str, corpus=None, **overrides):
    """A dense-path word2vec trainer wired for drills (ledger + backups under
    ``workdir``); overrides land on top of the base config."""
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    ids, vocab = corpus if corpus is not None else _drill_corpus()
    base = {
        "dim": "16", "window": "1", "negatives": "4", "learning_rate": "0.3",
        "num_iters": "40", "batch_size": "256", "subsample": "0", "seed": "0",
        "packed": "0", "prefetch_batches": "0",
        "ledger_path": os.path.join(workdir, "LEDGER.jsonl"),
    }
    base.update({k: str(v) for k, v in overrides.items()})
    cfg = Config(base)
    return Word2VecTrainer(cfg, mesh=None, corpus_ids=ids, vocab=vocab)


def run_loop(trainer, max_steps: int):
    """Build + run a TrainLoop; returns ``(loop, state, steps_done)``."""
    from swiftsnails_tpu.framework.trainer import TrainLoop

    loop = TrainLoop(trainer, log_every=0)
    state = loop.run(max_steps=max_steps)
    steps_done = loop._items_seen // trainer.batch_size
    return loop, state, steps_done


def tables_finite(state) -> bool:
    import jax

    for leaf in jax.tree_util.tree_leaves(state):
        if hasattr(leaf, "dtype") and np.issubdtype(np.asarray(leaf).dtype,
                                                    np.floating):
            if not np.isfinite(np.asarray(leaf, dtype=np.float32)).all():
                return False
    return True


def eval_loss(trainer, state, n: int = 512) -> float:
    """Deterministic held-out SGNS eval loss of a drill state (dense path)."""
    import jax.numpy as jnp

    from swiftsnails_tpu.models.word2vec import sgns_loss
    from swiftsnails_tpu.parallel.store import pull

    ids = trainer.corpus_ids
    n = min(n, len(ids) // 2 - 1)
    c = np.asarray(ids[0:2 * n:2], np.int32)
    x = np.asarray(ids[1:2 * n:2], np.int32)
    rng = np.random.default_rng(99)
    negs = rng.integers(0, len(trainer.vocab),
                        (len(c), trainer.negatives)).astype(np.int32)
    v = pull(state.in_table, jnp.asarray(c))
    u_pos = pull(state.out_table, jnp.asarray(x))
    u_neg = pull(state.out_table, jnp.asarray(negs.reshape(-1))).reshape(
        len(c), trainer.negatives, -1)
    return float(sgns_loss(v.astype(jnp.float32), u_pos.astype(jnp.float32),
                           u_neg.astype(jnp.float32)))


def _workdir(workdir: Optional[str]) -> str:
    return workdir or tempfile.mkdtemp(prefix="chaos-drill-")


# ----------------------------------------------------------------- drills ---


def _poison_drill(workdir: str, spec: str, steps: int = 16) -> Dict:
    trainer = make_trainer(workdir, guardrail=1, guard_max_consecutive=5,
                           chaos_spec=spec, chaos_seed=11)
    loop, state, steps_done = run_loop(trainer, max_steps=steps)
    guard = loop.guardrail.summary()
    finite = tables_finite(state)
    return {
        "recovered": bool(finite and steps_done == steps
                          and guard["trips_total"] > 0
                          and loop.guardrail.trust == 1.0),
        "spec": spec,
        "steps": steps_done,
        "trips": guard["trips_total"],
        "steps_skipped": guard["steps_skipped"],
        "tables_finite": finite,
        "final_loss": round(eval_loss(trainer, state), 6),
    }


def drill_nan_burst(workdir: Optional[str] = None) -> Dict:
    """A 3-step NaN-gradient burst must be rolled back step by step, with
    zero non-finite values reaching the master tables, and trust recovering
    to 1.0 within the run."""
    return _poison_drill(_workdir(workdir), "nan_grad@4-6")


def drill_inf_update(workdir: Optional[str] = None) -> Dict:
    """An overflowed (+inf) update — the quantized-collective failure mode —
    must trip and roll back exactly like NaN."""
    return _poison_drill(_workdir(workdir), "inf_grad@5")


def drill_row_poison(workdir: Optional[str] = None) -> Dict:
    """A parameter row corrupted BEFORE the step (bad pull) must be detected
    at commit and the clean pre-poison snapshot restored."""
    return _poison_drill(_workdir(workdir), "row_poison@5")


def drill_io_error(workdir: Optional[str] = None, steps: int = 12) -> Dict:
    """A transient data-stream error must cost a retry, not the run."""
    workdir = _workdir(workdir)
    trainer = make_trainer(workdir, chaos_spec="io_error@3,io_error@7",
                           chaos_seed=11)
    loop, state, steps_done = run_loop(trainer, max_steps=steps)
    injected = [e for e in loop.chaos.events if e["fault"] == "io_error"]
    return {
        "recovered": bool(steps_done == steps and len(injected) == 2),
        "steps": steps_done,
        "injected": len(injected),
        "tables_finite": tables_finite(state),
    }


def drill_ckpt_walkback(workdir: Optional[str] = None) -> Dict:
    """Bit rot in the newest checkpoint must be caught by the manifest CRC
    and resume must walk back to the newest intact generation — recorded as
    a ``cache_error`` ledger event, never a crash."""
    from swiftsnails_tpu.framework.checkpoint import intact_steps
    from swiftsnails_tpu.resilience.chaos import corrupt_checkpoint_dir
    from swiftsnails_tpu.resilience.resume import resume_state
    from swiftsnails_tpu.telemetry.ledger import Ledger

    workdir = _workdir(workdir)
    root = os.path.join(workdir, "ck")
    ledger = Ledger(os.path.join(workdir, "LEDGER.jsonl"))
    trainer = make_trainer(workdir, param_backup_period=4,
                           param_backup_root=root)
    run_loop(trainer, max_steps=13)  # saves at 4, 8, 12
    newest = intact_steps(root)[0]
    corrupted = corrupt_checkpoint_dir(root, rng=np.random.default_rng(11),
                                       ledger=ledger)
    template = make_trainer(workdir, param_backup_root=root).init_state()
    restored = resume_state(root, template, mode="auto", ledger=ledger)
    ok = restored is not None and restored[1] < newest
    return {
        "recovered": bool(ok and ledger.latest("cache_error") is not None),
        "corrupted_step": newest,
        "corrupted_file": corrupted,
        "restored_step": restored[1] if restored else None,
        "cursor": restored[2] if restored else None,
    }


def drill_preempt_resume(workdir: Optional[str] = None, steps: int = 24,
                         preempt_at: int = 14, period: int = 5) -> Dict:
    """The full outage script: preemption mid-run (drain + final save),
    post-mortem corruption of that final save, then ``resume: auto`` walking
    back to the newest intact checkpoint, restoring the data cursor, and
    finishing the run with final loss at parity with an undisturbed one."""
    from swiftsnails_tpu.framework.checkpoint import intact_steps
    from swiftsnails_tpu.resilience.chaos import corrupt_checkpoint_dir
    from swiftsnails_tpu.resilience.resume import resume_state
    from swiftsnails_tpu.telemetry.ledger import Ledger

    workdir = _workdir(workdir)
    ledger = Ledger(os.path.join(workdir, "LEDGER.jsonl"))

    # undisturbed control
    control_tr = make_trainer(workdir)
    _, control_state, _ = run_loop(control_tr, max_steps=steps)
    loss_control = eval_loss(control_tr, control_state)

    # disturbed: preempt mid-run -> drain writes a final checkpoint
    root = os.path.join(workdir, "ck")
    tr1 = make_trainer(workdir, param_backup_period=period,
                       param_backup_root=root,
                       chaos_spec=f"preempt@{preempt_at}", chaos_seed=11)
    loop1, _, died_steps = run_loop(tr1, max_steps=steps)
    final_step = intact_steps(root)[0]

    # the final save rots on disk before the restart
    corrupt_checkpoint_dir(root, rng=np.random.default_rng(11), ledger=ledger)

    # measure the restore (walk-back) cost on a throwaway template, then
    # resume for real through the TrainLoop
    t0 = time.monotonic()
    probe = resume_state(root, make_trainer(workdir).init_state(),
                         mode="auto", ledger=ledger)
    restore_s = time.monotonic() - t0
    tr2 = make_trainer(workdir, param_backup_period=period,
                       param_backup_root=root, resume="auto")
    loop2, resumed_state, _ = run_loop(tr2, max_steps=steps)
    loss_resumed = eval_loss(tr2, resumed_state)
    parity = abs(loss_resumed - loss_control) / max(abs(loss_control), 1e-9)
    restored_step = loop2._restored_step
    return {
        "recovered": bool(
            loop1.preempted
            and probe is not None
            and restored_step is not None
            and restored_step < final_step
            and parity <= LOSS_PARITY_BAR
        ),
        "preempted": loop1.preempted,
        "died_at_step": died_steps,
        "final_save_step": final_step,
        "restored_step": restored_step,
        "steps_lost": (final_step - restored_step)
        if restored_step is not None else None,
        "time_to_recover_s": round(restore_s, 4),
        "loss_control": round(loss_control, 6),
        "loss_resumed": round(loss_resumed, 6),
        "loss_parity": round(parity, 6),
        "parity_bar": LOSS_PARITY_BAR,
    }


def drill_tier_bitflip(workdir: Optional[str] = None, steps: int = 12,
                       flip_at: int = 6, master_dtype: str = "float32",
                       **_ignored) -> Dict:
    """Silent host-RAM corruption of a tiered master plane: a seeded bit is
    XOR'd directly into a :class:`HostMaster` plane (bypassing ``scatter``,
    so only the integrity digests can see it). The per-step verify sweep
    must detect the corrupt plane, rebuild it from the newest verified
    checkpoint with the resident cache re-asserted on top, and the run must
    finish with eval loss at parity with an unfaulted tiered control.

    ``master_dtype: int8`` runs the same drill over quantized host masters
    (code planes + scale sidebands); on top of the in-run flip, the result
    carries a direct detection probe that flips one code byte AND one scale
    byte on a throwaway quantized master and checks ``verify()`` names both
    planes — the in-run rng picks only one plane, the probe pins coverage of
    both kinds deterministically."""
    from swiftsnails_tpu.telemetry.ledger import Ledger

    workdir = _workdir(workdir)
    tier_cfg = {
        "table_tier": "host",
        "tier_verify_period": 1,
        "steps_per_call": 1,
        "param_backup_period": 2,
        "tier_master_dtype": master_dtype,
    }

    # unfaulted tiered control (same step semantics, no chaos)
    ctl_dir = os.path.join(workdir, "control")
    os.makedirs(ctl_dir, exist_ok=True)
    ctl_tr = make_trainer(ctl_dir, param_backup_root=os.path.join(ctl_dir, "ck"),
                          **tier_cfg)
    _, ctl_state, _ = run_loop(ctl_tr, max_steps=steps)
    loss_control = eval_loss(ctl_tr, ctl_state)

    # faulted leg: the flip lands at `flip_at`, after checkpoints exist
    flt_dir = os.path.join(workdir, "faulted")
    os.makedirs(flt_dir, exist_ok=True)
    trainer = make_trainer(
        flt_dir, param_backup_root=os.path.join(flt_dir, "ck"),
        chaos_spec=f"tier_bitflip@{flip_at}", chaos_seed=11, **tier_cfg)
    loop, state, steps_done = run_loop(trainer, max_steps=steps)
    loss_faulted = eval_loss(trainer, state)
    parity = abs(loss_faulted - loss_control) / max(abs(loss_control), 1e-9)

    flips = [e for e in loop.chaos.events if e["fault"] == "tier_bitflip"]
    heal = None
    ledger = Ledger(os.path.join(flt_dir, "LEDGER.jsonl"))
    for r in ledger.records("cache_error"):
        if r.get("source") == "tier":
            heal = r
    detected = heal is not None and heal.get("rebuilt_from_step") is not None
    probe_ok = True
    probe: Optional[Dict] = None
    if master_dtype != "float32":
        probe = _quantized_plane_probe(master_dtype)
        probe_ok = probe["code_detected"] and probe["scale_detected"]
    out = {
        "recovered": bool(
            steps_done == steps
            and len(flips) == 1
            and detected
            and probe_ok
            and tables_finite(state)
            and parity <= LOSS_PARITY_BAR
        ),
        "steps": steps_done,
        "flip": flips[0] if flips else None,
        "detected_planes": (heal or {}).get("planes"),
        "rebuilt_from_step": (heal or {}).get("rebuilt_from_step"),
        "rebuilt_tables": (heal or {}).get("tables"),
        "master_dtype": master_dtype,
        "loss_control": round(loss_control, 6),
        "loss_faulted": round(loss_faulted, 6),
        "loss_parity": round(parity, 6),
        "parity_bar": LOSS_PARITY_BAR,
    }
    if probe is not None:
        out["plane_probe"] = probe
    return out


def _quantized_plane_probe(master_dtype: str) -> Dict:
    """Deterministic digest-coverage probe for quantized masters: flip one
    byte in the code plane and one in the scale sideband of a throwaway
    int8 :class:`HostMaster`; both flips must surface in ``verify()``."""
    from swiftsnails_tpu.parallel.store import TableState
    from swiftsnails_tpu.tiered.store import HostMaster

    rng = np.random.default_rng(3)
    state = TableState(
        table=rng.normal(size=(32, 8)).astype(np.float32), slots={})
    m = HostMaster(state, "dense", master_dtype=master_dtype)
    m.table.view(np.uint8).reshape(-1)[5] ^= np.uint8(1 << 3)
    code_detected = "table" in m.verify()
    m2 = HostMaster(state, "dense", master_dtype=master_dtype)
    m2.scales["table"].view(np.uint8)[9] ^= np.uint8(1 << 2)
    scale_detected = "table/scale" in m2.verify()
    return {"code_detected": bool(code_detected),
            "scale_detected": bool(scale_detected)}


def drill_tier_bitflip_int8(workdir: Optional[str] = None, **kw) -> Dict:
    """The tier bitflip drill over int8 (quantized) host masters."""
    kw.pop("master_dtype", None)
    return drill_tier_bitflip(workdir, master_dtype="int8", **kw)


_DRILL_FNS: Dict[str, Callable[..., Dict]] = {
    "nan_burst": drill_nan_burst,
    "inf_update": drill_inf_update,
    "row_poison": drill_row_poison,
    "io_error": drill_io_error,
    "ckpt_walkback": drill_ckpt_walkback,
    "preempt_resume": drill_preempt_resume,
    "tier_bitflip": drill_tier_bitflip,
    "tier_bitflip_int8": drill_tier_bitflip_int8,
}

FAST_DRILLS = ("nan_burst", "io_error", "ckpt_walkback")


def run_drill_matrix(fast: bool = False, workdir: Optional[str] = None) -> Dict[str, Dict]:
    """Run the drill matrix; each drill gets its own subdirectory so ledgers
    and checkpoints never cross-contaminate. A drill that *raises* is an
    unrecovered fault by definition."""
    base = _workdir(workdir)
    names = FAST_DRILLS if fast else DRILLS
    results: Dict[str, Dict] = {}
    for name in names:
        d = os.path.join(base, name)
        os.makedirs(d, exist_ok=True)
        t0 = time.monotonic()
        try:
            res = _DRILL_FNS[name](d)
        except Exception as e:
            res = {"recovered": False,
                   "error": f"{type(e).__name__}: {e}"}
        res["elapsed_s"] = round(time.monotonic() - t0, 2)
        results[name] = res
    return results


# ------------------------------------------------- bench `chaos` lane -------


def _bench_corpus(small: bool):
    """Zipf corpus big enough that the guardrail's per-step cost is measured
    against real step work (the paired probe corpus is too small for an
    honest overhead number)."""
    from swiftsnails_tpu.data.vocab import Vocab

    vocab_n = 512 if small else 4096
    n_tokens = 20_000 if small else 120_000
    rng = np.random.default_rng(5)
    ranks = np.arange(1, vocab_n + 1, dtype=np.float64)
    w = 1.0 / ranks ** 1.05
    cdf = np.cumsum(w) / w.sum()
    ids = np.searchsorted(cdf, rng.random(n_tokens)).astype(np.int32)
    counts = np.maximum(np.bincount(ids, minlength=vocab_n), 1).astype(np.int64)
    return ids, Vocab([f"w{i}" for i in range(vocab_n)], counts)


def chaos_bench(workdir: Optional[str] = None, small: bool = False) -> Dict:
    """The bench ``chaos`` lane: guardrail overhead on the no-fault control
    leg + the scripted fault drills' recovery numbers, as one JSON-ready
    block (lands in the bench line, the run ledger, and the
    ``ledger-report --check-regression`` gate)."""
    t_lane0 = time.monotonic()
    base = _workdir(workdir)
    corpus = _bench_corpus(small)
    over = {
        "dim": 16 if small else 64,
        "batch_size": 512 if small else 2048,
        "window": 2,
        "num_iters": 8,
    }
    warm, steps = (2, 12) if small else (3, 32)

    def wps(extra: Dict) -> float:
        """Steady-state pair rate of the control leg: one TrainLoop, a warm
        run that pays the jit compile, then best-of-3 timed runs on the
        already-compiled step fn (machine-load noise only ever slows a run,
        so max is the robust estimator — the headline bench's lesson). A
        rate for the overhead ratio, NOT comparable to words/sec/chip."""
        from swiftsnails_tpu.framework.trainer import TrainLoop

        d = tempfile.mkdtemp(dir=base)
        tr = make_trainer(d, corpus=corpus, **{**over, **extra})
        loop = TrainLoop(tr, log_every=0)
        loop.run(max_steps=warm)
        best = 0.0
        for _ in range(3):
            t0 = time.monotonic()
            loop.run(max_steps=steps)
            dt = max(time.monotonic() - t0, 1e-9)
            best = max(best, steps * over["batch_size"] / dt)
        return best

    control = wps({})
    guarded = wps({"guardrail": 1})
    overhead_pct = (control - guarded) / control * 100.0 if control else None

    drills = run_drill_matrix(fast=small, workdir=os.path.join(base, "drills"))
    resume_drill = drills.get("preempt_resume") or drills.get("ckpt_walkback")
    block = {
        "control_words_per_sec": round(control, 1),
        "guard_words_per_sec": round(guarded, 1),
        "guard_overhead_pct": (
            round(overhead_pct, 2) if overhead_pct is not None else None
        ),
        "nan_drill": drills.get("nan_burst"),
        "resume_drill": resume_drill,
        "drills": {k: {"recovered": v.get("recovered"),
                       "elapsed_s": v.get("elapsed_s")}
                   for k, v in drills.items()},
        "recovered_all": all(v.get("recovered") for v in drills.values()),
        "loss_parity": (resume_drill or {}).get("loss_parity"),
        "small": small,
        "elapsed_s": round(time.monotonic() - t_lane0, 1),
    }
    return block
