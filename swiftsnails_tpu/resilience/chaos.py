"""Deterministic fault injection: every failure mode the resilience stack
claims to survive must be *drillable*, on demand, reproducibly.

A :class:`ChaosPlan` is parsed from two config keys:

* ``chaos_spec`` — comma-separated ``kind@step`` / ``kind@first-last``
  entries, e.g. ``nan_grad@5-7,ckpt_corrupt@12,preempt@17``;
* ``chaos_seed`` — seeds the (numpy) generator that picks poisoned rows and
  corrupted byte offsets, so a drill replays bit-identically.

Fault kinds (all injected from the host side, so the jitted step function is
never recompiled or slowed by the harness):

==============  ============================================================
``nan_grad``    the step's update arrives with NaN rows (post-step poison of
                the new tables + NaN loss) — a blown-up gradient
``inf_grad``    same with +inf — an overflow (e.g. an int8-collective amax
                blow-up) rather than an invalid op
``row_poison``  a pulled parameter row is NaN *before* the step — corrupt
                table memory / a bad remote read
``io_error``    the data stream raises :class:`TransientDataError` once —
                a flaky filesystem / object-store read
``ckpt_corrupt``flips bytes mid-file in the newest on-disk checkpoint under
                ``param_backup_root`` — bit rot the manifest CRC must catch
``preempt``     requests a simulated SIGTERM at the step boundary — the
                TrainLoop drains, final-saves, and records an ``outage``
``serve_io_error`` a Servant kernel dispatch raises ``OSError`` at the
                scheduled request index — a flaky storage/device read on the
                serving read path (drives the circuit breakers)
``serve_slow``  a Servant kernel dispatch stalls past its latency budget at
                the scheduled request index — a straggling device
``tier_bitflip`` XORs one seeded-random bit directly in a tiered host master
                plane, bypassing ``scatter`` — silent host-RAM corruption
                that only ``HostMaster.verify()``'s digests can catch
``reload_corrupt`` corrupts the newest on-disk checkpoint right before a
                live Servant reload — the shadow-verify swap must reject it
                and keep serving the old version
``worker_dead`` a cluster worker stops heartbeating forever (silent host
                death) — its membership lease must expire and its stream
                range re-lease to survivors (cluster sim; scheduled by
                cluster-wide applied-batch tick)
``worker_slow`` a cluster worker's step time inflates while scheduled — the
                supervisor's EWMA-vs-median straggler policy must shrink its
                share / duplicate its substeps
``partition``   a cluster worker computes on but can't reach the supervisor
                — heartbeats drop, its lease expires, and its stale buffered
                commits must be refused by first-writer-wins
``slow_step``   the host sleeps ``chaos_slow_step_ms`` before dispatching the
                step — a sustained host-blocked regression (GC storm, noisy
                neighbor, storage stall) the drift sentinel must confirm
==============  ============================================================

Every injection appends a ``chaos`` ledger event (when a ledger is wired),
so a drill's timeline is auditable next to the outages and black-box dumps
it provokes (``ledger-report --failures``).
"""

from __future__ import annotations

import os
import re
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

FAULT_KINDS = (
    "nan_grad", "inf_grad", "row_poison", "io_error", "ckpt_corrupt", "preempt",
    # availability-hardening kinds (PR 7): serving + tiered-store faults.
    # The serve_* kinds index by REQUEST number (the serving fault hook),
    # tier_bitflip/reload_corrupt by train step / drill index.
    "serve_io_error", "serve_slow", "tier_bitflip", "reload_corrupt",
    # cluster-membership kinds (PR 9): consulted by the cluster simulator,
    # scheduled by cluster-wide applied-batch tick (see cluster/sim.py)
    "worker_dead", "worker_slow", "partition",
    # drift-sentinel kind (PR 17): host-side per-step sleep consulted by the
    # TrainLoop *outside* the traced step span, so the stall lands in the
    # host-blocked decomposition bucket exactly like a real host stall
    "slow_step",
    # process-level transport kinds (PR 19): consulted by the net drills,
    # scheduled by storm tick. proc_kill SIGKILLs a replica process
    # mid-load; net_partition black-holes its socket for a window;
    # net_slow injects RTT into every reply (see net/bench_lane.py)
    "proc_kill", "net_partition", "net_slow",
)

_ENTRY_RE = re.compile(r"^(?P<kind>[a-z_]+)@(?P<first>\d+)(?:-(?P<last>\d+))?$")


class ChaosSpecError(ValueError):
    """Malformed ``chaos_spec`` value."""


class TransientDataError(OSError):
    """The injected transient data-stream failure (an OSError so the
    TrainLoop's retry path treats it exactly like a real I/O hiccup)."""


def parse_chaos_spec(spec: str) -> List[Tuple[str, int]]:
    """``"nan_grad@5-7,preempt@17"`` -> ``[("nan_grad", 5), ("nan_grad", 6),
    ("nan_grad", 7), ("preempt", 17)]``."""
    faults: List[Tuple[str, int]] = []
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _ENTRY_RE.match(entry)
        if not m:
            raise ChaosSpecError(
                f"chaos_spec entry {entry!r} is not kind@step or kind@a-b"
            )
        kind = m.group("kind")
        if kind not in FAULT_KINDS:
            raise ChaosSpecError(
                f"unknown chaos fault {kind!r}; known: {', '.join(FAULT_KINDS)}"
            )
        first = int(m.group("first"))
        last = int(m.group("last") or first)
        if last < first:
            raise ChaosSpecError(f"chaos_spec entry {entry!r}: empty range")
        faults.extend((kind, s) for s in range(first, last + 1))
    return faults


def corrupt_checkpoint_dir(
    root: str,
    step: Optional[int] = None,
    rng: Optional[np.random.Generator] = None,
    ledger=None,
    n_bytes: int = 16,
) -> Optional[str]:
    """Flip ``n_bytes`` mid-file in the largest data file of the newest (or
    given) ``step_*`` dir under ``root``; returns the mangled file's path.

    The target is the largest non-manifest file — the array payload — so the
    storage layer usually still *reads* it back happily and only the manifest
    CRC exposes the rot (the case verified restore exists for). Deterministic
    under a seeded ``rng``.
    """
    from swiftsnails_tpu.framework.checkpoint import (
        MANIFEST_NAME, all_steps, _step_dir, wait_for_checkpoints,
    )

    wait_for_checkpoints()  # never race the writer we are about to sabotage
    steps = all_steps(root)
    if not steps:
        return None
    step = steps[-1] if step is None else step
    target_dir = _step_dir(root, step)
    candidates = []
    for dirpath, _, files in os.walk(target_dir):
        for name in files:
            if name == MANIFEST_NAME:
                continue
            p = os.path.join(dirpath, name)
            try:
                candidates.append((os.path.getsize(p), p))
            except OSError:
                continue
    if not candidates:
        return None
    size, path = max(candidates)
    rng = rng or np.random.default_rng(0)
    # mangle every payload file, not just the largest: small checkpoints may
    # inline array bytes anywhere in the container, and a drill whose flip
    # lands in dead bytes would "pass" without testing anything
    for fsize, fpath in candidates:
        span = max(n_bytes, fsize // 4)
        lo = fsize // 4
        hi = max(fsize - span, lo + 1)
        off = int(rng.integers(lo, hi)) if hi > lo else 0
        with open(fpath, "r+b") as f:
            f.seek(off)
            chunk = bytearray(f.read(span))
            for i in range(len(chunk)):
                chunk[i] ^= 0xFF
            f.seek(off)
            f.write(bytes(chunk))
            f.flush()
            os.fsync(f.fileno())
    if ledger is not None:
        try:
            ledger.append("chaos", {
                "fault": "ckpt_corrupt", "step": step, "path": path,
                "offset": off, "bytes": n_bytes,
            })
        except Exception:
            pass
    return path


class _ChaosStream:
    """Iterator adapter that raises the plan's ``io_error`` faults in front
    of the real batch — the batch is NOT consumed, so a retrying consumer
    loses nothing."""

    def __init__(self, inner: Iterator, plan: "ChaosPlan"):
        self._inner = inner
        self._plan = plan
        self._fetches = 0

    def __iter__(self):
        return self

    def __next__(self):
        step = self._fetches
        if self._plan._take("io_error", step):
            self._plan._log("io_error", step, {"detail": "injected stream error"})
            raise TransientDataError(
                f"chaos: injected transient data-stream error at fetch {step}"
            )
        self._fetches += 1
        return next(self._inner)


class ChaosPlan:
    """Seeded, scripted fault schedule consulted by the TrainLoop."""

    def __init__(self, faults: List[Tuple[str, int]], seed: int = 0, ledger=None,
                 slow_step_ms: float = 50.0):
        self._pending: Dict[Tuple[str, int], bool] = {
            (kind, step): True for kind, step in faults
        }
        self.seed = int(seed)
        self.rng = np.random.default_rng(self.seed)
        self.ledger = ledger
        self.slow_step_ms = float(slow_step_ms)
        self.events: List[Dict] = []

    @classmethod
    def from_config(cls, cfg, ledger=None) -> Optional["ChaosPlan"]:
        spec = cfg.get_str("chaos_spec", "")
        if not spec.strip():
            return None
        return cls(parse_chaos_spec(spec), seed=cfg.get_int("chaos_seed", 0),
                   ledger=ledger,
                   slow_step_ms=cfg.get_float("chaos_slow_step_ms", 50.0))

    # -- bookkeeping --------------------------------------------------------

    def _take(self, kind: str, step: int) -> bool:
        """True exactly once per scheduled (kind, step)."""
        key = (kind, step)
        if self._pending.get(key):
            self._pending[key] = False
            return True
        return False

    def _log(self, kind: str, step: int, detail: Dict) -> None:
        event = {"fault": kind, "step": int(step), "seed": self.seed, **detail}
        self.events.append(event)
        if self.ledger is not None:
            try:
                self.ledger.append("chaos", event)
            except Exception:
                pass

    def pending(self) -> List[Tuple[str, int]]:
        return sorted(k for k, live in self._pending.items() if live)

    def scheduled(self, kind: str, step: int) -> bool:
        """True when ``kind`` is still pending at ``step`` (peek — does not
        consume). Lets the TrainLoop skip span bookkeeping on unaffected
        steps."""
        return bool(self._pending.get((kind, step)))

    # -- injection hooks (called by TrainLoop._resilient_step) --------------

    def wrap_stream(self, it: Iterator) -> Iterator:
        if any(kind == "io_error" for kind, _ in self._pending):
            return _ChaosStream(it, self)
        return it

    def _poison_first_table(self, state, value: float):
        """Set one whole row of the first float table leaf to ``value``;
        returns (new_state, leaf_key, row)."""
        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(state)
        for i, leaf in enumerate(leaves):
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating) \
                    and getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] > 0:
                row = int(self.rng.integers(0, leaf.shape[0]))
                leaves[i] = leaf.at[row].set(jnp.asarray(value, leaf.dtype))
                return jax.tree_util.tree_unflatten(treedef, leaves), i, row
        return state, None, None

    def pre_step(self, state, step: int):
        """Pre-step faults: ``row_poison`` (a corrupt pulled row)."""
        if self._take("row_poison", step):
            state, leaf, row = self._poison_first_table(state, float("nan"))
            self._log("row_poison", step, {"leaf": leaf, "row": row})
        return state

    def post_step(self, state, metrics: Dict, step: int):
        """Post-step faults: ``nan_grad`` / ``inf_grad`` (the update that
        arrives at the commit point carries non-finite values)."""
        for kind, value in (("nan_grad", float("nan")),
                            ("inf_grad", float("inf"))):
            if self._take(kind, step):
                state, leaf, row = self._poison_first_table(state, value)
                metrics = dict(metrics)
                metrics["loss"] = np.float32(value)
                self._log(kind, step, {"leaf": leaf, "row": row})
        return state, metrics

    def maybe_slow_step(self, step: int) -> float:
        """``slow_step``: sleep ``chaos_slow_step_ms`` on the host before the
        step dispatch; returns the slept milliseconds (0.0 when unscheduled).

        The TrainLoop consults this BEFORE entering the traced step span
        (wrapped in a ``chaos-slow`` span on the instrumented path), so the
        injected stall is attributed to the host-blocked decomposition
        bucket — the signature the drift sentinel and ``--diff`` drill on.
        """
        if not self._take("slow_step", step):
            return 0.0
        ms = self.slow_step_ms
        self._log("slow_step", step, {"sleep_ms": ms})
        if ms > 0:
            time.sleep(ms / 1e3)
        return ms

    def wants_preempt(self, step: int) -> Optional[str]:
        if self._take("preempt", step):
            self._log("preempt", step, {"detail": "simulated SIGTERM"})
            return f"chaos preempt@{step}"
        return None

    def maybe_corrupt_checkpoint(self, root: str, step: int) -> Optional[str]:
        if not self._take("ckpt_corrupt", step):
            return None
        if not root:
            self._log("ckpt_corrupt", step,
                      {"detail": "skipped: no param_backup_root"})
            return None
        path = corrupt_checkpoint_dir(root, rng=self.rng)
        self._log("ckpt_corrupt", step, {"path": path})
        return path

    def maybe_flip_tier(self, tier, step: int) -> Optional[str]:
        """``tier_bitflip``: XOR one seeded-random bit directly in a host
        master plane's memory — deliberately bypassing
        :meth:`HostMaster.scatter` so only the integrity digests
        (:meth:`HostMaster.verify`) can catch it. Returns the hit table."""
        if not self._take("tier_bitflip", step):
            return None
        names = sorted(tier.tables)
        if not names:
            self._log("tier_bitflip", step, {"detail": "skipped: no tier"})
            return None
        name = names[int(self.rng.integers(0, len(names)))]
        # barrier the async flush queue: a landing that read the row before
        # the flip would scatter over it and erase the injected corruption
        # before the integrity sweep ever sees it
        drain = getattr(tier, "_drain", None)
        if drain is not None:
            drain()
        # any master plane is fair game — including a quantized master's
        # scale sidebands ("<plane>/scale"), where one flipped bit corrupts
        # every element of its unit on dequant
        planes = list(tier.tables[name].master._planes())
        plane, arr = planes[int(self.rng.integers(0, len(planes)))]
        flat = arr.view(np.uint8).reshape(-1)  # aliases the live plane
        off = int(self.rng.integers(0, flat.size))
        bit = int(self.rng.integers(0, 8))
        flat[off] ^= np.uint8(1 << bit)
        self._log("tier_bitflip", step,
                  {"table": name, "plane": plane, "byte": off, "bit": bit})
        return name

    # -- serving-surface faults (consulted by the Servant's fault hook / the
    # chaos-serve lane; "step" is the request index) -------------------------

    def serve_fault(self, index: int) -> Optional[str]:
        """The scheduled serving fault for request ``index`` (at most one:
        ``serve_io_error`` outranks ``serve_slow``), or None."""
        for kind in ("serve_io_error", "serve_slow"):
            if self._take(kind, index):
                self._log(kind, index, {"surface": "serve"})
                return kind
        return None

    # -- cluster-membership faults (consulted by the cluster simulator;
    # "step" is the cluster-wide applied-batch tick) --------------------------

    def cluster_fault(self, tick: int) -> List[str]:
        """The cluster faults scheduled at global tick ``tick``, in fire
        order. The caller picks the victim and ``_log``s the detail (the
        plan can't know worker identities)."""
        return [kind for kind in ("worker_dead", "worker_slow", "partition")
                if self._take(kind, tick)]

    # -- process-level transport faults (consulted by the net drills;
    # "step" is the storm tick) ----------------------------------------------

    def net_fault(self, tick: int) -> List[str]:
        """The transport faults scheduled at storm tick ``tick``, in fire
        order. The caller picks the victim replica/socket and ``_log``s the
        detail (the plan can't know process identities)."""
        return [kind for kind in ("proc_kill", "net_partition", "net_slow")
                if self._take(kind, tick)]

    def wants_reload_corrupt(self, index: int) -> bool:
        """True when a ``reload_corrupt`` drill is scheduled at ``index`` —
        the caller corrupts the newest checkpoint *before* asking the live
        Servant to reload it (the shadow-verify swap must reject it)."""
        if self._take("reload_corrupt", index):
            self._log("reload_corrupt", index, {"surface": "serve"})
            return True
        return False

    def summary(self) -> Dict:
        return {
            "seed": self.seed,
            "injected": len(self.events),
            "by_fault": {
                k: sum(1 for e in self.events if e["fault"] == k)
                for k in FAULT_KINDS
                if any(e["fault"] == k for e in self.events)
            },
            "unfired": [f"{k}@{s}" for k, s in self.pending()],
        }
