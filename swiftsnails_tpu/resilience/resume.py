"""Auto-resume: find the run's last *good* checkpoint and continue the run —
tables **and** data-stream cursor — never crashing on a corrupt save.

``resume: 1`` (legacy) restores the newest checkpoint that verifies, keeping
the old semantics of restarting the data stream. ``resume: auto`` goes
further: it consults the run ledger (``RUN_LEDGER.jsonl`` ``checkpoint``
events, written at every verified save) for the run's last known-good step,
verifies it against its manifest, walks back to the newest intact checkpoint
when anything is corrupt (each rejection is a ``cache_error`` ledger event,
never a crash), and returns the manifest's ``data_cursor`` so the TrainLoop
can skip the already-consumed batches — a resumed loss curve is a
*continuation* of the interrupted one, not a restart.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple


def _ledger_known_steps(ledger, root: str, config_hash: Optional[str]) -> List[int]:
    """Steps the ledger records as good saves under ``root`` (newest first).
    A config-hash mismatch does not disqualify a record — resuming across a
    benign config tweak is legal; shapes are enforced by the restore itself."""
    if ledger is None:
        return []
    root = os.path.abspath(root)
    try:
        records = ledger.records("checkpoint")
    except Exception:
        return []
    mine = [
        rec for rec in records
        if rec.get("root") == root and isinstance(rec.get("step"), int)
    ]
    # prefer records of this exact config, then the rest, each newest-first
    same = [r["step"] for r in mine
            if config_hash and r.get("config_hash") == config_hash]
    rest = [r["step"] for r in mine if r["step"] not in same]
    ordered = list(reversed(same)) + list(reversed(rest))
    seen: set = set()
    return [s for s in ordered if not (s in seen or seen.add(s))]


def resume_state(
    root: str,
    template: Any,
    mode: str = "latest",
    ledger=None,
    config_hash: Optional[str] = None,
) -> Optional[Tuple[Any, int, Dict]]:
    """Restore the newest intact checkpoint under ``root``.

    Returns ``(state, step, data_cursor)`` or ``None`` when nothing under
    ``root`` is restorable (a fresh run). Candidates are tried newest-first
    — ledger-known-good steps first in ``auto`` mode — and every corrupt or
    unrestorable candidate is recorded as a ``cache_error`` ledger event and
    skipped, so a flipped bit in the newest save costs one backup period,
    not the run.
    """
    from swiftsnails_tpu.framework.checkpoint import (
        candidate_steps, read_manifest, restore_checkpoint, _step_dir,
    )

    preferred: List[int] = []
    if mode == "auto":
        preferred = _ledger_known_steps(ledger, root, config_hash)
    # shared walk ordering (also the serving loader's): intact-manifest
    # steps outrank torn dirs, newest first within each tier
    candidates = candidate_steps(root, preferred=preferred)
    if not candidates:
        return None

    for step in candidates:
        try:
            state = restore_checkpoint(root, template, step=step, verify=True)
        except Exception as e:
            if ledger is not None:
                try:
                    ledger.append("cache_error", {
                        "source": "checkpoint",
                        "path": _step_dir(root, step),
                        "error": f"{type(e).__name__}: {e}",
                        "action": "walking back to an older checkpoint",
                    })
                except Exception:
                    pass
            continue
        manifest = read_manifest(root, step) or {}
        cursor = manifest.get("data_cursor") or {"step": step}
        return state, step, cursor
    return None


def resume_mode(cfg) -> str:
    """The ``resume`` config key, normalized: ``off`` / ``latest`` /
    ``auto``. (``resume`` predates auto mode as a bool, so truthy words map
    to ``latest``.)"""
    raw = cfg.get_str("resume", "0").strip().lower()
    if raw == "auto":
        return "auto"
    if raw in ("1", "true", "yes", "on"):
        return "latest"
    return "off"
