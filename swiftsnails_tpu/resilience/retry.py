"""Unified retry/deadline layer for every fallible host-side I/O path.

One policy object replaces the ad-hoc per-site loops (the old
``_RetryingStream`` 3x loop, the checkpoint restore try/except, the tier
flush fail-fast): exponential backoff with *decorrelated jitter* (each
sleep is drawn from ``uniform(base, prev * 3)`` capped at ``cap`` — the
AWS-style schedule that avoids retry synchronization across workers),
bounded by both an attempt budget and a wall-clock deadline, whichever
runs out first.

Everything that can tick or sleep is injectable (``clock`` / ``sleep`` /
seeded ``rng``) so tests drive the schedule with a fake clock and assert
exact backoff bounds without real sleeping. Every exhausted budget is a
structured ``retry_exhausted`` ledger event — a retry loop that gives up
silently is an outage with no black box.

Config keys (all optional):

* ``retry_max_attempts`` — total tries per operation (default 4, i.e. one
  initial try + three retries, matching the old stream loop);
* ``retry_deadline_ms``  — wall-clock budget per operation (default 30000);
* ``retry_base_ms``      — first backoff draw lower bound (default 25);
* ``retry_cap_ms``       — backoff upper clamp (default 2000).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional, Tuple, Type

__all__ = [
    "Deadline",
    "DeadlineExceeded",
    "RetryBudget",
    "RetryExhausted",
    "RetryPolicy",
    "RetryingIterator",
    "retry_call",
]


class RetryExhausted(RuntimeError):
    """Raised when an operation's retry budget (attempts or deadline) is
    spent. Chains from the last underlying error via ``__cause__``."""

    def __init__(self, op: str, attempts: int, elapsed_ms: float,
                 reason: str, last_error: Optional[BaseException] = None):
        msg = (f"{op}: retry budget exhausted after {attempts} attempt(s) "
               f"in {elapsed_ms:.0f} ms ({reason})")
        if last_error is not None:
            msg += f"; last error: {type(last_error).__name__}: {last_error}"
        super().__init__(msg)
        self.op = op
        self.attempts = attempts
        self.elapsed_ms = elapsed_ms
        self.reason = reason
        self.last_error = last_error


class DeadlineExceeded(RetryExhausted):
    """The wall-clock deadline ran out (possibly before the attempt budget)."""


@dataclass
class Deadline:
    """A wall-clock budget pinned at creation. ``clock`` is injectable and
    must be monotonic-like (seconds as float)."""

    expires_at: float
    clock: Callable[[], float] = time.monotonic

    @classmethod
    def after_ms(cls, ms: float,
                 clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(expires_at=clock() + ms / 1000.0, clock=clock)

    def remaining(self) -> float:
        """Seconds left, clamped at zero."""
        return max(0.0, self.expires_at - self.clock())

    @property
    def expired(self) -> bool:
        return self.clock() >= self.expires_at

    def check(self, op: str = "operation", attempts: int = 0,
              started: Optional[float] = None) -> None:
        if self.expired:
            elapsed = 0.0 if started is None else (self.clock() - started) * 1e3
            raise DeadlineExceeded(op, attempts, elapsed, "deadline")


@dataclass
class RetryBudget:
    """Attempt counter: ``max_attempts`` total tries (first try included)."""

    max_attempts: int
    used: int = 0

    def spend(self) -> bool:
        """Consume one attempt; True while tries remain."""
        self.used += 1
        return self.used <= self.max_attempts

    @property
    def exhausted(self) -> bool:
        return self.used >= self.max_attempts

    @property
    def remaining(self) -> int:
        return max(0, self.max_attempts - self.used)


@dataclass
class RetryPolicy:
    """The reusable knob bundle. One policy serves many operations; each
    :meth:`call` gets a fresh :class:`RetryBudget` + :class:`Deadline`."""

    max_attempts: int = 4
    deadline_ms: float = 30_000.0
    base_ms: float = 25.0
    cap_ms: float = 2_000.0
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)
    clock: Callable[[], float] = time.monotonic
    sleep: Callable[[float], None] = time.sleep
    rng: random.Random = field(default_factory=lambda: random.Random(0))
    ledger = None  # duck-typed: needs .append(kind, record)

    @classmethod
    def from_config(cls, cfg, ledger=None, **overrides) -> "RetryPolicy":
        kw = dict(
            max_attempts=cfg.get_int("retry_max_attempts", 4),
            deadline_ms=cfg.get_float("retry_deadline_ms", 30_000.0),
            base_ms=cfg.get_float("retry_base_ms", 25.0),
            cap_ms=cfg.get_float("retry_cap_ms", 2_000.0),
        )
        kw.update(overrides)
        pol = cls(**kw)
        pol.ledger = ledger
        return pol

    def deadline(self) -> Deadline:
        return Deadline.after_ms(self.deadline_ms, clock=self.clock)

    def budget(self) -> RetryBudget:
        return RetryBudget(max_attempts=self.max_attempts)

    def next_backoff_s(self, prev_s: Optional[float]) -> float:
        """Decorrelated jitter: uniform(base, prev*3) clamped to [base, cap].
        The first draw uses ``prev = base``."""
        base = self.base_ms / 1000.0
        cap = self.cap_ms / 1000.0
        prev = base if prev_s is None else prev_s
        hi = max(base, min(cap, prev * 3.0))
        return self.rng.uniform(base, hi)

    # -- the loop -------------------------------------------------------------

    def call(self, fn: Callable, *args, op: str = "operation",
             on_retry: Optional[Callable] = None,
             extra: Optional[dict] = None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under this policy. Exceptions matching
        ``retry_on`` are retried with backoff until the attempt budget or the
        deadline runs out; anything else propagates immediately. Exhaustion
        raises :class:`RetryExhausted` (or :class:`DeadlineExceeded`) and —
        when a ledger is attached — appends a ``retry_exhausted`` event
        (``extra`` fields, e.g. a peer address, merge into that record)."""
        budget = self.budget()
        deadline = self.deadline()
        started = self.clock()
        backoff: Optional[float] = None
        last: Optional[BaseException] = None
        while True:
            budget.spend()
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:  # noqa: PERF203 — the whole point
                last = e
                elapsed_ms = (self.clock() - started) * 1e3
                if budget.exhausted:
                    self._give_up(op, budget.used, elapsed_ms, "attempts", e,
                                  extra)
                backoff = self.next_backoff_s(backoff)
                if deadline.remaining() < backoff:
                    self._give_up(op, budget.used, elapsed_ms, "deadline", e,
                                  extra)
                if on_retry is not None:
                    on_retry(e, budget.used, backoff)
                self.sleep(backoff)

    def _give_up(self, op: str, attempts: int, elapsed_ms: float,
                 reason: str, err: BaseException,
                 extra: Optional[dict] = None) -> None:
        exc_cls = DeadlineExceeded if reason == "deadline" else RetryExhausted
        exc = exc_cls(op, attempts, elapsed_ms, reason, err)
        if self.ledger is not None:
            try:
                record = {
                    "op": op,
                    "attempts": attempts,
                    "elapsed_ms": round(elapsed_ms, 3),
                    "reason": reason,
                    "error": f"{type(err).__name__}: {err}",
                }
                if extra:
                    record.update(extra)
                self.ledger.append("retry_exhausted", record)
            except Exception:
                pass  # bookkeeping never fails the failure path
        raise exc from err


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               op: str = "operation", **kwargs):
    """Module-level convenience: ``retry_call(f, x, policy=p, op="load")``."""
    return (policy or RetryPolicy()).call(fn, *args, op=op, **kwargs)


class RetryingIterator:
    """Iterator adapter built on :class:`RetryPolicy` — replaces the old
    ``_RetryingStream`` hardcoded 3x loop. Each fetch gets a fresh attempt
    budget + deadline; ``StopIteration`` always passes through untouched.
    ``on_error(exc, attempt, recovered)`` keeps the old callback shape so
    existing counters/ledger hooks plug straight in."""

    def __init__(self, inner: Iterator, policy: RetryPolicy,
                 on_error: Optional[Callable] = None, op: str = "data_stream"):
        self._inner = inner
        self.policy = policy
        self._on_error = on_error
        self.op = op
        self.retried = 0

    def __iter__(self) -> "RetryingIterator":
        return self

    def __next__(self):
        def _fetch():
            return next(self._inner)

        def _note(exc, attempt, backoff):
            self.retried += 1
            if self._on_error is not None:
                self._on_error(exc, attempt - 1, True)

        try:
            return self.policy.call(_fetch, op=self.op, on_retry=_note)
        except RetryExhausted as e:
            self.retried += 1
            if self._on_error is not None and e.last_error is not None:
                self._on_error(e.last_error, e.attempts - 1, False)
            if e.last_error is not None:
                raise e.last_error from e
            raise
