// libsnails — native data-pipeline core for swiftsnails_tpu.
//
// TPU-native re-implementation of the reference's host-side hot path
// (C++11 header-only utils, survey §2.1):
//   * LineFileReader / scan_file_by_line (src/utils/string.h, file.h:11-33)
//       -> buffered whole-file tokenizer (vocab_build / encode)
//   * TextBuffer::get_math number parsing (src/utils/Buffer.h:240-324)
//       -> strtol-at-cursor CTR record parser (read_ctr)
//   * google dense_hash_map vocab (src/utils/hashmap.h)
//       -> std::unordered_map with reserved buckets
//   * queue_with_capacity bounded queue + poison-value shutdown
//       (src/utils/queue.h:100-108) -> Prefetcher ring (mutex+condvar,
//       producer thread, explicit close)
//   * MurmurHash3 finalizer (src/utils/HashFunction.h:17-25) -> murmur64
//
// Exposed as a plain C ABI for ctypes (no pybind11). All buffers are
// caller-owned numpy allocations unless documented otherwise.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

// ---------------------------------------------------------------- murmur ---

// Exact HashFunction.h:17-25 finalizer.
static inline uint64_t fmix64(uint64_t x) {
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33;
  x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33;
  return x;
}

extern "C" void ssn_murmur64(const uint64_t* in, uint64_t* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = fmix64(in[i]);
}

extern "C" void ssn_hash_row(const uint32_t* keys, int64_t n, uint64_t capacity,
                  int64_t* rows) {
  for (int64_t i = 0; i < n; ++i)
    rows[i] = (int64_t)(fmix64((uint64_t)keys[i]) % capacity);
}

// ----------------------------------------------------------------- vocab ---

struct Vocab {
  std::vector<std::string> words;
  std::vector<int64_t> counts;
  std::unordered_map<std::string, int32_t> index;
};

static bool read_file(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->resize((size_t)size);
  size_t got = size ? std::fread(&(*out)[0], 1, (size_t)size, f) : 0;
  std::fclose(f);
  out->resize(got);
  return true;
}

static inline bool is_space(char c) {
  return c == ' ' || c == '\n' || c == '\t' || c == '\r' || c == '\v' || c == '\f';
}

// Tokenize `data` in place, calling fn(ptr, len) per token.
template <typename Fn>
static void for_tokens(const std::string& data, Fn fn) {
  const char* p = data.data();
  const char* end = p + data.size();
  while (p < end) {
    while (p < end && is_space(*p)) ++p;
    const char* start = p;
    while (p < end && !is_space(*p)) ++p;
    if (p > start) fn(start, (size_t)(p - start));
  }
}

// Shared ordering contract (identical to Vocab.from_counter): freq desc,
// then lexicographic, min-count filtered, truncated to max_size.
static Vocab* make_vocab(std::unordered_map<std::string, int64_t>& counter,
                         int min_count, int max_size) {
  std::vector<std::pair<std::string, int64_t>> items;
  items.reserve(counter.size());
  for (auto& kv : counter)
    if (kv.second >= min_count) items.emplace_back(kv.first, kv.second);
  std::sort(items.begin(), items.end(), [](const auto& a, const auto& b) {
    if (a.second != b.second) return a.second > b.second;
    return a.first < b.first;
  });
  if (max_size > 0 && (int)items.size() > max_size) items.resize(max_size);
  Vocab* v = new Vocab();
  v->words.reserve(items.size());
  v->counts.reserve(items.size());
  v->index.reserve(items.size());
  for (size_t i = 0; i < items.size(); ++i) {
    v->words.push_back(items[i].first);
    v->counts.push_back(items[i].second);
    v->index.emplace(items[i].first, (int32_t)i);
  }
  return v;
}

extern "C" void* ssn_vocab_build(const char* path, int min_count, int max_size) {
  std::string data;
  if (!read_file(path, &data)) return nullptr;
  std::unordered_map<std::string, int64_t> counter;
  counter.reserve(1 << 20);
  for_tokens(data, [&](const char* s, size_t len) {
    counter[std::string(s, len)] += 1;
  });
  return make_vocab(counter, min_count, max_size);
}

extern "C" int64_t ssn_vocab_size(void* h) { return h ? (int64_t)((Vocab*)h)->words.size() : -1; }

extern "C" void ssn_vocab_counts(void* h, int64_t* out) {
  Vocab* v = (Vocab*)h;
  std::memcpy(out, v->counts.data(), v->counts.size() * sizeof(int64_t));
}

extern "C" int ssn_vocab_word(void* h, int64_t idx, char* buf, int buflen) {
  Vocab* v = (Vocab*)h;
  if (idx < 0 || idx >= (int64_t)v->words.size()) return -1;
  const std::string& w = v->words[(size_t)idx];
  if ((int)w.size() + 1 > buflen) return -(int)w.size() - 1;
  std::memcpy(buf, w.data(), w.size());
  buf[w.size()] = 0;
  return (int)w.size();
}

extern "C" void ssn_vocab_free(void* h) { delete (Vocab*)h; }

// Encode corpus file -> int32 ids (OOV dropped). Returns count, or -needed if
// `cap` too small (call once with cap=0 to size), or -1 on IO error.
extern "C" int64_t ssn_encode(void* h, const char* path, int32_t* out, int64_t cap) {
  Vocab* v = (Vocab*)h;
  std::string data;
  if (!read_file(path, &data)) return -1;
  int64_t n = 0;
  bool overflow = false;
  for_tokens(data, [&](const char* s, size_t len) {
    auto it = v->index.find(std::string(s, len));
    if (it != v->index.end()) {
      if (out && n < cap) out[n] = it->second;
      else overflow = true;
      ++n;
    }
  });
  if (out && overflow) return -n;  // caller's buffer was too small
  return n;
}

// ------------------------------------------------------------ streaming ---
//
// Bounded-memory file ingestion (scan_file_by_line / LineFileReader parity,
// src/utils/file.h:11-33): a fixed read buffer + a carry for the token or
// line straddling the buffer edge. RSS stays O(buffer + chunk) regardless of
// file size — the whole-file read_file() paths above are kept for small
// inputs; these streams are what the 1TB-scale configs feed from.

// defined in the ctr section below; shared with the streaming reader
static bool parse_ctr_line(const char* q, const char* line_end, int num_fields,
                           float* label_out, int32_t* feats);

namespace {
constexpr size_t kStreamBuf = 1 << 20;  // 1 MiB read buffer

struct TokenStream {
  FILE* f = nullptr;
  const Vocab* vocab = nullptr;  // borrowed; owner must outlive the stream
  std::string buf;               // read buffer
  std::string carry;             // partial token at buffer edge
  size_t pos = 0;                // cursor into buf
  bool eof = false;
  int64_t abs_base = 0;  // file offset of buf[0]
  int64_t end = 0;       // byte-range shard limit (0 = whole file): a token
                         // belongs to this shard iff it STARTS before `end`
                         // (Hadoop split semantics; run_worker.sh parity)

  bool fill() {  // refill buf from file; false at EOF
    if (eof) return false;
    abs_base += (int64_t)buf.size();
    buf.resize(kStreamBuf);
    size_t got = std::fread(&buf[0], 1, kStreamBuf, f);
    buf.resize(got);
    pos = 0;
    if (got == 0) eof = true;
    return got > 0;
  }
};

struct CtrStream {
  FILE* f = nullptr;
  int num_fields = 0;
  std::string buf;
  std::string carry;  // partial line at buffer edge
  size_t pos = 0;
  bool eof = false;
  int64_t abs_base = 0;  // file offset of buf[0]
  int64_t end = 0;       // byte-range limit: a line belongs to the span its
                         // first byte falls in (Hadoop TextInputFormat)
};
}  // namespace

// Open a (byte_start, byte_end) span; 0,0 = whole file. A token straddling
// byte_start belongs to the PREVIOUS shard (skipped here); a token starting
// before byte_end is read to completion even past byte_end.
extern "C" void* ssn_stream_open(void* vocab_h, const char* path,
                                 int64_t byte_start, int64_t byte_end) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  TokenStream* s = new TokenStream();
  s->f = f;
  s->vocab = (const Vocab*)vocab_h;
  s->end = byte_end;
  if (byte_start > 0) {
    // Hadoop convention: a token starting EXACTLY at byte_start is ours iff
    // the previous byte is whitespace; otherwise we're mid-token and the
    // owner is the previous shard — skip to the first whitespace.
    std::fseek(f, (long)(byte_start - 1), SEEK_SET);
    int prev = std::fgetc(f);
    s->abs_base = byte_start;
    if (prev != EOF && !is_space((char)prev)) {
      for (;;) {
        if (!s->fill()) break;
        size_t i = 0;
        while (i < s->buf.size() && !is_space(s->buf[i])) ++i;
        if (i < s->buf.size()) { s->pos = i; break; }
        s->pos = s->buf.size();
      }
    }
  }
  return s;
}

// Fill out with up to cap encoded ids (OOV dropped). Returns count written;
// 0 = end of file. Bounded memory: one read buffer + one partial token.
extern "C" int64_t ssn_stream_next(void* h, int32_t* out, int64_t cap) {
  TokenStream* s = (TokenStream*)h;
  int64_t n = 0;
  while (n < cap) {
    if (s->pos >= s->buf.size()) {
      if (!s->fill()) break;
    }
    const char* base = s->buf.data();
    size_t size = s->buf.size();
    while (s->pos < size && n < cap) {
      // skip spaces; a pending carry token ends at the first space
      if (is_space(base[s->pos])) {
        if (!s->carry.empty()) {
          auto it = s->vocab->index.find(s->carry);
          if (it != s->vocab->index.end()) out[n++] = it->second;
          s->carry.clear();
          if (n >= cap) { ++s->pos; break; }
        }
        ++s->pos;
        continue;
      }
      // a NEW token starting at/after the shard's byte_end belongs to the
      // next shard (a carried token started before it — finish that one)
      if (s->end > 0 && s->carry.empty() &&
          s->abs_base + (int64_t)s->pos >= s->end) {
        s->eof = true;
        break;
      }
      size_t start = s->pos;
      while (s->pos < size && !is_space(base[s->pos])) ++s->pos;
      if (s->pos >= size) {  // token may continue in the next buffer
        s->carry.append(base + start, s->pos - start);
        break;
      }
      if (!s->carry.empty()) {
        s->carry.append(base + start, s->pos - start);
        auto it = s->vocab->index.find(s->carry);
        if (it != s->vocab->index.end()) out[n++] = it->second;
        s->carry.clear();
      } else {
        auto it = s->vocab->index.find(std::string(base + start, s->pos - start));
        if (it != s->vocab->index.end()) out[n++] = it->second;
      }
    }
    if (s->eof) break;
  }
  if (s->eof && !s->carry.empty() && n < cap) {  // final unterminated token
    auto it = s->vocab->index.find(s->carry);
    if (it != s->vocab->index.end()) out[n++] = it->second;
    s->carry.clear();
  }
  return n;
}

extern "C" void ssn_stream_close(void* h) {
  TokenStream* s = (TokenStream*)h;
  if (s->f) std::fclose(s->f);
  delete s;
}

// Streaming vocab build: same ordering contract as ssn_vocab_build, bounded
// memory (counter is O(vocab), read buffer is fixed).
extern "C" void* ssn_vocab_build_stream(const char* path, int min_count,
                                        int max_size) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::unordered_map<std::string, int64_t> counter;
  counter.reserve(1 << 20);
  std::string buf;
  std::string carry;
  for (;;) {
    buf.resize(kStreamBuf);
    size_t got = std::fread(&buf[0], 1, kStreamBuf, f);
    buf.resize(got);
    if (got == 0) break;
    size_t pos = 0;
    while (pos < got) {
      if (is_space(buf[pos])) {
        if (!carry.empty()) { counter[carry] += 1; carry.clear(); }
        ++pos;
        continue;
      }
      size_t start = pos;
      while (pos < got && !is_space(buf[pos])) ++pos;
      if (pos >= got) { carry.append(buf, start, pos - start); break; }
      if (!carry.empty()) {
        carry.append(buf, start, pos - start);
        counter[carry] += 1;
        carry.clear();
      } else {
        counter[std::string(buf, start, pos - start)] += 1;
      }
    }
  }
  if (!carry.empty()) counter[carry] += 1;
  std::fclose(f);
  return make_vocab(counter, min_count, max_size);
}

extern "C" void* ssn_ctr_stream_open(const char* path, int num_fields,
                                     int64_t byte_start, int64_t byte_end) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  CtrStream* s = new CtrStream();
  s->f = f;
  s->num_fields = num_fields;
  s->end = byte_end;
  if (byte_start > 0) {
    // a line starting exactly at byte_start is ours iff the previous byte
    // is '\n'; otherwise discard the partial line (previous shard's)
    std::fseek(f, (long)(byte_start - 1), SEEK_SET);
    int prev = std::fgetc(f);
    int64_t skipped = 0;
    if (prev != EOF && prev != '\n') {
      int ch;
      while ((ch = std::fgetc(f)) != EOF) {
        ++skipped;
        if (ch == '\n') break;
      }
    }
    s->abs_base = byte_start + skipped;
  }
  return s;
}

// Fill up to max_rows parsed rows (parse_ctr_line is shared with the
// whole-file ssn_read_ctr above). Returns rows written; 0 = EOF.
extern "C" int64_t ssn_ctr_stream_next(void* h, float* labels_out,
                                       int32_t* feats_out, int64_t max_rows) {
  CtrStream* s = (CtrStream*)h;
  int64_t row = 0;
  while (row < max_rows) {
    if (s->pos >= s->buf.size()) {
      if (s->eof) break;
      s->abs_base += (int64_t)s->buf.size();
      s->buf.resize(kStreamBuf);
      size_t got = std::fread(&s->buf[0], 1, kStreamBuf, s->f);
      s->buf.resize(got);
      s->pos = 0;
      if (got == 0) { s->eof = true; break; }
    }
    // a NEW line starting at/after the span's byte_end belongs to the next
    // shard (a carried line started before it and is finished normally)
    if (s->end > 0 && s->carry.empty() &&
        s->abs_base + (int64_t)s->pos >= s->end) {
      s->eof = true;
      break;
    }
    const char* base = s->buf.data();
    const char* end = base + s->buf.size();
    const char* p = base + s->pos;
    const char* line_end = (const char*)memchr(p, '\n', (size_t)(end - p));
    if (!line_end) {  // partial line: carry to the next buffer
      s->carry.append(p, (size_t)(end - p));
      s->pos = s->buf.size();
      continue;
    }
    if (!s->carry.empty()) {
      s->carry.append(p, (size_t)(line_end - p));
      if (parse_ctr_line(s->carry.data(), s->carry.data() + s->carry.size(),
                         s->num_fields, labels_out + row,
                         feats_out + row * s->num_fields))
        ++row;
      s->carry.clear();
    } else if (parse_ctr_line(p, line_end, s->num_fields, labels_out + row,
                              feats_out + row * s->num_fields)) {
      ++row;
    }
    s->pos = (size_t)(line_end - base) + 1;
  }
  if (s->eof && !s->carry.empty() && row < max_rows) {  // final line, no \n
    if (parse_ctr_line(s->carry.data(), s->carry.data() + s->carry.size(),
                       s->num_fields, labels_out + row,
                       feats_out + row * s->num_fields))
      ++row;
    s->carry.clear();
  }
  return row;
}

extern "C" void ssn_ctr_stream_close(void* h) {
  CtrStream* s = (CtrStream*)h;
  if (s->f) std::fclose(s->f);
  delete s;
}

// ------------------------------------------------------------- skip-gram ---

// splitmix64: deterministic, matches nothing external — seeds the pair RNG.
static inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// Stateless splitmix64 draw at stream position i: identical output to
// advancing a splitmix64 stream i+1 times, but random-access — every
// position's draw is computable independently, so pair/window generation
// parallelizes (and shards of a corpus can be processed in any order)
// without changing the generated pair set for a given seed.
static inline uint64_t splitmix64_at(uint64_t seed, int64_t i) {
  uint64_t z = seed + 0x9e3779b97f4a7c15ULL * (uint64_t)(i + 1);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// b ~ U(1, window) for center position i (word2vec dynamic window).
static inline int draw_b(uint64_t seed, int64_t i, int window, int dynamic) {
  if (!dynamic) return window;
  return (int)(splitmix64_at(seed ^ 0xdeadbeefcafef00dULL, i) %
               (uint64_t)window) + 1;
}

// Worker count for the parallel producers: hardware cores, env-overridable.
// On a 1-core host everything stays sequential (threads would only add
// contention); on real TPU-host CPUs (dozens of cores) the generation and
// batch-assembly fan out.
static int default_workers() {
  const char* env = std::getenv("SSN_NATIVE_THREADS");
  if (env && *env) {
    int v = std::atoi(env);
    if (v > 0) return v;
  }
  unsigned hw = std::thread::hardware_concurrency();
  return hw > 0 ? (int)(hw > 16 ? 16 : hw) : 1;
}

// Run fn(shard_lo, shard_hi) over [0, n) in contiguous shards across the
// worker pool; sequential when one worker (or tiny n).
template <typename F>
static void parallel_spans(int64_t n, int nworkers, F fn) {
  if (nworkers <= 1 || n < (1 << 16)) {
    fn((int64_t)0, n);
    return;
  }
  int64_t shard = (n + nworkers - 1) / nworkers;
  std::vector<std::thread> ts;
  for (int w = 0; w < nworkers; ++w) {
    int64_t lo = w * shard, hi = std::min(n, lo + shard);
    if (lo >= hi) break;
    ts.emplace_back([=] { fn(lo, hi); });
  }
  for (auto& t : ts) t.join();
}

// Dynamic-window pair generation (word2vec b ~ U(1, window)).
// Returns npairs; if out arrays are null, only counts. Per-position draws
// (splitmix64_at) make the pair set independent of sharding, so the count
// and fill passes parallelize over contiguous spans.
extern "C" int64_t ssn_skipgram_pairs(const int32_t* ids, int64_t n, int window,
                           uint64_t seed, int dynamic, int32_t* centers,
                           int32_t* contexts, int64_t cap) {
  if (n <= 0) return 0;  // empty chunk (e.g. fully subsampled away)
  int nw = default_workers();
  // pass 1: pairs per span (exact prefix offsets for the parallel fill)
  int64_t shard = nw <= 1 ? n : (n + nw - 1) / nw;
  if (shard <= 0) shard = 1;
  int nshards = (int)((n + shard - 1) / shard);
  std::vector<int64_t> span_pairs((size_t)std::max(nshards, 1), 0);
  parallel_spans(n, nw, [&](int64_t lo, int64_t hi) {
    int64_t k = 0;
    for (int64_t i = lo; i < hi; ++i) {
      int b = draw_b(seed, i, window, dynamic);
      int64_t lo_j = i - b < 0 ? 0 : i - b;
      int64_t hi_j = i + b >= n ? n - 1 : i + b;
      k += (hi_j - lo_j);  // minus the center itself: (hi-lo+1) - 1
    }
    span_pairs[(size_t)(lo / shard)] = k;
  });
  int64_t total = 0;
  for (int64_t c : span_pairs) total += c;
  if (!centers) return total;
  if (total > cap) return -total;  // undersized buffer
  std::vector<int64_t> offs((size_t)nshards, 0);
  for (int s = 1; s < nshards; ++s)
    offs[(size_t)s] = offs[(size_t)s - 1] + span_pairs[(size_t)s - 1];
  parallel_spans(n, nw, [&](int64_t lo, int64_t hi) {
    int64_t k = offs[(size_t)(lo / shard)];
    for (int64_t i = lo; i < hi; ++i) {
      int b = draw_b(seed, i, window, dynamic);
      int64_t lo_j = i - b < 0 ? 0 : i - b;
      int64_t hi_j = i + b >= n ? n - 1 : i + b;
      int32_t ci = ids[i];
      for (int64_t j = lo_j; j <= hi_j; ++j) {
        if (j == i) continue;
        centers[k] = ci;
        contexts[k] = ids[j];
        ++k;
      }
    }
  });
  return total;
}

// Center-major windows: contexts[i, slot] for slot offsets [-w..-1, 1..w],
// -1 where out of range or beyond the drawn b ~ U(1, window). SAME b draw
// (draw_b at position i) as ssn_skipgram_pairs for a given seed, so the
// flat and grouped schemas generate the identical pair set (the invariant
// the Python twins keep via _dynamic_window_valid). Parallel over spans.
extern "C" int64_t ssn_skipgram_windows(const int32_t* ids, int64_t n,
                                        int window, uint64_t seed, int dynamic,
                                        int32_t* ctxs /* [n, 2*window] */) {
  const int cw = 2 * window;
  parallel_spans(n, default_workers(), [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int b = draw_b(seed, i, window, dynamic);
      int32_t* row = ctxs + i * cw;
      for (int o = -window; o <= window; ++o) {
        if (o == 0) continue;
        int slot = o < 0 ? o + window : o + window - 1;
        int64_t j = i + o;
        int ab = o < 0 ? -o : o;
        row[slot] = (j >= 0 && j < n && ab <= b) ? ids[j] : -1;
      }
    }
  });
  return n;
}

// Frequent-word subsampling: keep w with p = sqrt(t/f) + t/f (word2vec).
// Writes kept ids to out, returns kept count. The keep draw is per-position
// (splitmix64_at), so the kept set is independent of sharding: count +
// compact passes parallelize over spans with exact prefix offsets.
extern "C" int64_t ssn_subsample(const int32_t* ids, int64_t n, const int64_t* counts,
                      int64_t vocab, double total, double threshold,
                      uint64_t seed, int32_t* out) {
  if (n <= 0) return 0;  // empty chunk
  if (threshold <= 0) {
    std::memcpy(out, ids, (size_t)n * sizeof(int32_t));
    return n;
  }
  const uint64_t s = seed ^ 0x12345678abcdefULL;
  const double inv = 1.0 / 9007199254740992.0;  // 2^-53
  // precompute per-id keep probability once (vocab << n): the sqrt/div per
  // TOKEN was the old loop's cost; per-id it amortizes across the corpus
  std::vector<float> keep_p((size_t)vocab);
  parallel_spans(vocab, default_workers(), [&](int64_t lo, int64_t hi) {
    for (int64_t v = lo; v < hi; ++v) {
      double f = (double)counts[v] / total;
      keep_p[(size_t)v] =
          (float)std::min(1.0, std::sqrt(threshold / f) + threshold / f);
    }
  });
  int nw = default_workers();
  int64_t shard = nw <= 1 ? n : (n + nw - 1) / nw;
  if (shard <= 0) shard = 1;
  int nshards = (int)((n + shard - 1) / shard);
  std::vector<int64_t> span_kept((size_t)std::max(nshards, 1), 0);
  auto kept_at = [&](int64_t i) -> bool {
    int32_t id = ids[i];
    float keep = (id >= 0 && id < vocab) ? keep_p[(size_t)id] : 1.0f;
    double u = (double)(splitmix64_at(s, i) >> 11) * inv;
    return u < keep;
  };
  parallel_spans(n, nw, [&](int64_t lo, int64_t hi) {
    int64_t k = 0;
    for (int64_t i = lo; i < hi; ++i) k += kept_at(i);
    span_kept[(size_t)(lo / shard)] = k;
  });
  std::vector<int64_t> offs((size_t)nshards, 0);
  for (int sI = 1; sI < nshards; ++sI)
    offs[(size_t)sI] = offs[(size_t)sI - 1] + span_kept[(size_t)sI - 1];
  parallel_spans(n, nw, [&](int64_t lo, int64_t hi) {
    int64_t k = offs[(size_t)(lo / shard)];
    for (int64_t i = lo; i < hi; ++i)
      if (kept_at(i)) out[k++] = ids[i];
  });
  int64_t totalk = 0;
  for (int64_t c : span_kept) totalk += c;
  return totalk;
}

// ------------------------------------------------------------------- ctr ---

// Parse one complete "label f0 f1 ..." line (TextBuffer::get_math parity,
// PAD = -1) into the given row slots. Shared by the whole-file reader and
// the streaming reader so the two can never drift. Returns false for
// blank/garbage-label lines (row skipped, strtod-failure semantics).
static bool parse_ctr_line(const char* q, const char* line_end, int num_fields,
                           float* label_out, int32_t* feats) {
  while (q < line_end && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
  if (q >= line_end) return false;
  char* next = nullptr;
  double label = std::strtod(q, &next);
  if (next == q) return false;
  if (label_out) {
    *label_out = (float)label;
    for (int fidx = 0; fidx < num_fields; ++fidx) feats[fidx] = -1;
    const char* cur = next;
    for (int fidx = 0; fidx < num_fields && cur < line_end; ++fidx) {
      while (cur < line_end && (*cur == ' ' || *cur == '\t')) ++cur;
      if (cur >= line_end) break;
      char* after = nullptr;
      long v = std::strtol(cur, &after, 10);
      if (after == cur) break;
      // "field:id" form — take the id after ':'
      if (after < line_end && *after == ':') {
        cur = after + 1;
        v = std::strtol(cur, &after, 10);
        if (after == cur) break;
      }
      feats[fidx] = (int32_t)v;
      cur = after;
    }
  }
  return true;
}

// Parse "label f0 f1 ..." lines. Returns row count; sizes only when outputs
// are null.
extern "C" int64_t ssn_read_ctr(const char* path, int num_fields, float* labels_out,
                     int32_t* feats_out, int64_t max_rows) {
  std::string data;
  if (!read_file(path, &data)) return -1;
  const char* p = data.data();
  const char* end = p + data.size();
  int64_t row = 0;
  while (p < end) {
    const char* line_end = (const char*)memchr(p, '\n', (size_t)(end - p));
    if (!line_end) line_end = end;
    // validate first (label-only parse): blank/garbage lines after the last
    // valid row must NOT trip the overflow check
    if (parse_ctr_line(p, line_end, num_fields, nullptr, nullptr)) {
      if (labels_out) {
        if (row >= max_rows) return -row;
        parse_ctr_line(p, line_end, num_fields, labels_out + row,
                       feats_out + row * num_fields);
      }
      ++row;
    }
    p = line_end + 1;
  }
  return row;
}

// --------------------------------------------------------- sgns baseline ---
//
// Compiled single-node SGNS worker loop for bench.py's CPU baseline: the
// reference's worker hot path was C++ (app layer absent from the snapshot;
// contract at src/core/framework/SwiftWorker.h:88-124), so the "8-node CPU
// parameter server" baseline must be calibrated from compiled code, not
// numpy (np.add.at is 10-50x slower than a C loop and would inflate
// vs_baseline). Shape follows the classic word2vec.c hot loop: sigmoid
// lookup table, unigram^0.75 negative table, per-pair gather -> sigmoid ->
// scatter-update.

namespace {
constexpr int kExpTableSize = 1000;
constexpr float kMaxExp = 6.0f;

struct NegTable {
  std::vector<int32_t> table;
};
}  // namespace

extern "C" void* ssn_neg_table_build(const int64_t* counts, int64_t vocab,
                                     int64_t table_size) {
  if (vocab <= 0 || table_size <= 0) return nullptr;
  NegTable* t = new NegTable();
  t->table.resize((size_t)table_size);
  double total = 0.0;
  for (int64_t i = 0; i < vocab; ++i) total += std::pow((double)counts[i], 0.75);
  int64_t w = 0;
  double cum = std::pow((double)counts[0], 0.75) / total;
  for (int64_t a = 0; a < table_size; ++a) {
    t->table[(size_t)a] = (int32_t)w;
    if ((double)(a + 1) / (double)table_size > cum && w < vocab - 1) {
      ++w;
      cum += std::pow((double)counts[w], 0.75) / total;
    }
  }
  return t;
}

extern "C" void ssn_neg_table_free(void* h) { delete (NegTable*)h; }

// Train over n (center, context) pairs with `negatives` samples each.
// Returns elapsed seconds (monotonic, excludes table setup).
extern "C" double ssn_sgns_train(float* syn0, float* syn1, int dim,
                                 const int32_t* centers, const int32_t* contexts,
                                 int64_t n, int negatives, float lr,
                                 void* neg_table_h, uint64_t seed) {
  NegTable* nt = (NegTable*)neg_table_h;
  const int64_t tsize = (int64_t)nt->table.size();
  // precomputed sigmoid over [-kMaxExp, kMaxExp)
  std::vector<float> exp_table((size_t)kExpTableSize);
  for (int i = 0; i < kExpTableSize; ++i) {
    float x = ((float)i / kExpTableSize * 2.0f - 1.0f) * kMaxExp;
    float e = std::exp(x);
    exp_table[(size_t)i] = e / (e + 1.0f);
  }
  std::vector<float> neu1e((size_t)dim);
  uint64_t s = seed ^ 0xabcdef0123456789ULL;
  auto t0 = std::chrono::steady_clock::now();
  for (int64_t p = 0; p < n; ++p) {
    float* v = syn0 + (int64_t)centers[p] * dim;
    std::memset(neu1e.data(), 0, (size_t)dim * sizeof(float));
    for (int d = 0; d <= negatives; ++d) {
      int32_t target;
      float label;
      if (d == 0) {
        target = contexts[p];
        label = 1.0f;
      } else {
        target = nt->table[(size_t)(splitmix64(s) % (uint64_t)tsize)];
        if (target == contexts[p]) continue;
        label = 0.0f;
      }
      float* u = syn1 + (int64_t)target * dim;
      float f = 0.0f;
      for (int c = 0; c < dim; ++c) f += v[c] * u[c];
      float g;
      if (f > kMaxExp) g = (label - 1.0f) * lr;
      else if (f < -kMaxExp) g = label * lr;
      else
        g = (label -
             exp_table[(size_t)(int)((f + kMaxExp) *
                                     (kExpTableSize / kMaxExp / 2.0f))]) *
            lr;
      for (int c = 0; c < dim; ++c) neu1e[c] += g * u[c];
      for (int c = 0; c < dim; ++c) u[c] += g * v[c];
    }
    for (int c = 0; c < dim; ++c) v[c] += neu1e[c];
  }
  auto t1 = std::chrono::steady_clock::now();
  return std::chrono::duration<double>(t1 - t0).count();
}

// -------------------------------------------------------------- prefetch ---

// Fisher-Yates with splitmix64 draws + Lemire multiply-shift bounded
// mapping: ~3x std::shuffle (which pays a division per element in
// uniform_int_distribution). Bias is O(2^-64) per draw — irrelevant for
// batch ordering.
template <typename T>
static void fy_shuffle(T* a, int64_t n, uint64_t seed) {
  uint64_t s = seed ^ 0x5bf0363546536b1dULL;
  // a second rng cursor runs LA steps ahead issuing prefetches for the
  // random swap targets (the swaps themselves are DRAM-miss-bound on big
  // arrays); the draw sequence of the actual swaps is unchanged
  constexpr int LA = 12;
  uint64_t s_pre = s;
  int64_t i_pre = n - 1;
  for (int k = 0; k < LA && i_pre > 0; ++k, --i_pre) {
    uint64_t r = splitmix64(s_pre);
    __builtin_prefetch(
        a + (int64_t)(((unsigned __int128)r * (uint64_t)(i_pre + 1)) >> 64),
        1, 0);
  }
  for (int64_t i = n - 1; i > 0; --i) {
    if (i_pre > 0) {
      uint64_t r = splitmix64(s_pre);
      __builtin_prefetch(
          a + (int64_t)(((unsigned __int128)r * (uint64_t)(i_pre + 1)) >> 64),
          1, 0);
      --i_pre;
    }
    uint64_t r = splitmix64(s);
    int64_t j = (int64_t)(((unsigned __int128)r * (uint64_t)(i + 1)) >> 64);
    T t = a[i];
    a[i] = a[j];
    a[j] = t;
  }
}

// Bounded-queue shuffled-batch producer (queue_with_capacity parity:
// capacity-bounded, blocking push/pop, explicit end_input poison).
struct Prefetcher {
  // pairs stored INTERLEAVED [c0,x0,c1,x1,...]: the shuffled gather is the
  // producer's cost and is cache-miss-bound — one 8-byte access per pair
  // instead of two 4-byte accesses into arrays ~n*4 bytes apart
  std::vector<int32_t> cx;
  int64_t n = 0;
  int64_t batch;
  int epochs;
  uint64_t seed;
  size_t capacity;

  std::deque<std::vector<int32_t>> queue;  // interleaved [c0,x0,c1,x1,...]
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  bool done = false, closed = false;
  std::thread worker;

  void produce() {
    int64_t nb = n / batch;
    // 32-bit order indices: the Fisher-Yates pass and the gather's index
    // reads are cache-miss-bound, so halving the index footprint matters
    // (pair counts < 2^31 by the open() guard)
    std::vector<uint32_t> order((size_t)n);
    const uint32_t* ord = order.data();
    for (int e = 0; e < epochs; ++e) {
      for (int64_t i = 0; i < n; ++i) order[(size_t)i] = (uint32_t)i;
      fy_shuffle(order.data(), n, seed + (uint64_t)e);
      for (int64_t bi = 0; bi < nb; ++bi) {
        std::vector<int32_t> item((size_t)(2 * batch));
        // memcpy (not int64_t* punning — strict aliasing) still compiles to
        // one 8-byte load/store per pair; the gather is random-access over
        // the whole pair array, so prefetch a few iterations ahead to
        // overlap the DRAM misses
        const uint32_t* o = ord + bi * batch;
        for (int64_t j = 0; j < batch; ++j) {
          if (j + 8 < batch)
            __builtin_prefetch(cx.data() + 2 * (int64_t)o[j + 8], 0, 0);
          std::memcpy(item.data() + 2 * j, cx.data() + 2 * (int64_t)o[j],
                      2 * sizeof(int32_t));
        }
        std::unique_lock<std::mutex> lk(mu);
        cv_push.wait(lk, [&] { return queue.size() < capacity || closed; });
        if (closed) return;
        queue.push_back(std::move(item));
        cv_pop.notify_one();
      }
    }
    std::lock_guard<std::mutex> lk(mu);
    done = true;
    cv_pop.notify_all();
  }
};

extern "C" void* ssn_prefetch_open(const int32_t* centers, const int32_t* contexts,
                        int64_t n, int64_t batch, int epochs, int capacity,
                        uint64_t seed) {
  if (n <= 0 || batch <= 0 || batch > n) return nullptr;
  if (n >= (int64_t)1 << 31) return nullptr;  // pair counts < 2^31 (uint32 shuffle indices)
  Prefetcher* p = new Prefetcher();
  p->n = n;
  p->cx.resize((size_t)(2 * n));
  for (int64_t i = 0; i < n; ++i) {
    p->cx[(size_t)(2 * i)] = centers[i];
    p->cx[(size_t)(2 * i + 1)] = contexts[i];
  }
  p->batch = batch;
  p->epochs = epochs;
  p->seed = seed;
  p->capacity = (size_t)(capacity > 0 ? capacity : 4);
  p->worker = std::thread([p] { p->produce(); });
  return p;
}

// 1 = batch written; 0 = end of input (reference poison value semantics).
extern "C" int ssn_prefetch_next(void* h, int32_t* centers_out, int32_t* contexts_out) {
  Prefetcher* p = (Prefetcher*)h;
  std::vector<int32_t> item;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_pop.wait(lk, [&] { return !p->queue.empty() || p->done; });
    if (p->queue.empty()) return 0;
    item = std::move(p->queue.front());
    p->queue.pop_front();
    p->cv_push.notify_one();
  }
  for (int64_t j = 0; j < p->batch; ++j) {
    centers_out[j] = item[(size_t)(2 * j)];
    contexts_out[j] = item[(size_t)(2 * j + 1)];
  }
  return 1;
}

extern "C" void ssn_prefetch_close(void* h) {
  Prefetcher* p = (Prefetcher*)h;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->closed = true;
    p->cv_push.notify_all();
    p->cv_pop.notify_all();
  }
  if (p->worker.joinable()) p->worker.join();
  delete p;
}

// ----------------------------------------------- window batch producer ---
//
// Center-major batch producer for the grouped/dedup kernels: shuffles
// BLOCKS of `block` consecutive windows (block = 1 -> plain row shuffle)
// and assembles {centers [batch], contexts [batch, cw]} items on a pool of
// worker threads behind a bounded ORDER-PRESERVING ticket ring, so the
// batch sequence is deterministic in (seed, epochs) regardless of worker
// count. Block mode copies whole contiguous spans (memcpy per block) — the
// assembly cost the Python batch_stream paid per-row in numpy. Bounded
// queue + poison-free end: queue_with_capacity parity
// (src/utils/queue.h:100-108), like the pair Prefetcher above.
struct WinPrefetcher {
  // BORROWED buffers (the Python wrapper keeps the arrays alive for the
  // handle's lifetime): a [n, 2w] window array is the chunk's dominant
  // allocation — copying it would double peak memory per chunk
  const int32_t* c = nullptr;   // [n]
  const int32_t* x = nullptr;   // [n, cw] flattened
  int cw = 0;
  int64_t batch = 0, block = 1;
  int64_t nblocks = 0, blocks_per_batch = 0, batches_per_epoch = 0;
  int64_t total_batches = 0;
  std::vector<int64_t> order;  // [epochs * nblocks] block schedule
  size_t capacity = 4;

  std::vector<std::vector<int32_t>> slots;  // ticket ring
  std::vector<int64_t> slot_ticket;         // -1 = empty
  std::atomic<int64_t> next_ticket{0};
  int64_t consumed = 0;
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  bool closed = false;
  std::vector<std::thread> workers;

  void work() {
    for (;;) {
      int64_t t = next_ticket.fetch_add(1);
      if (t >= total_batches) break;
      std::vector<int32_t> item((size_t)(batch * (1 + cw)));
      int32_t* co = item.data();
      int32_t* xo = item.data() + batch;
      const int64_t* ord = order.data() +
                           (t / batches_per_epoch) * nblocks +
                           (t % batches_per_epoch) * blocks_per_batch;
      for (int64_t bi = 0; bi < blocks_per_batch; ++bi) {
        int64_t src = ord[bi] * block;
        std::memcpy(co + bi * block, c + src,
                    (size_t)block * sizeof(int32_t));
        std::memcpy(xo + bi * block * cw, x + src * cw,
                    (size_t)(block * cw) * sizeof(int32_t));
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_free.wait(lk, [&] {
        return closed || t - consumed < (int64_t)capacity;
      });
      if (closed) return;
      size_t s = (size_t)(t % (int64_t)capacity);
      slots[s] = std::move(item);
      slot_ticket[s] = t;
      cv_ready.notify_all();
    }
  }
};

extern "C" void* ssn_win_prefetch_open(const int32_t* centers,
                                       const int32_t* ctxs, int64_t n, int cw,
                                       int64_t batch, int64_t block, int epochs,
                                       int capacity, int nworkers,
                                       uint64_t seed) {
  if (n <= 0 || cw <= 0 || batch <= 0 || batch > n || epochs <= 0)
    return nullptr;
  if (block <= 0) block = 1;
  if (batch % block) return nullptr;  // kernel blocks must tile batches
  WinPrefetcher* p = new WinPrefetcher();
  p->c = centers;
  p->x = ctxs;
  p->cw = cw;
  p->batch = batch;
  p->block = block;
  p->nblocks = n / block;
  p->blocks_per_batch = batch / block;
  p->batches_per_epoch = p->nblocks / p->blocks_per_batch;
  p->total_batches = (int64_t)epochs * p->batches_per_epoch;
  if (p->total_batches <= 0) {
    delete p;
    return nullptr;
  }
  p->capacity = (size_t)(capacity > 0 ? capacity : 4);
  p->slots.resize(p->capacity);
  p->slot_ticket.assign(p->capacity, -1);
  p->order.resize((size_t)((int64_t)epochs * p->nblocks));
  for (int e = 0; e < epochs; ++e) {
    int64_t* o = p->order.data() + (int64_t)e * p->nblocks;
    for (int64_t i = 0; i < p->nblocks; ++i) o[i] = i;
    fy_shuffle(o, p->nblocks, seed + (uint64_t)e);
  }
  int nw = nworkers > 0 ? nworkers : default_workers();
  if ((int64_t)nw > p->total_batches) nw = (int)p->total_batches;
  for (int w = 0; w < nw; ++w)
    p->workers.emplace_back([p] { p->work(); });
  return p;
}

// 1 = batch written; 0 = end of input (poison-free shutdown semantics).
extern "C" int ssn_win_prefetch_next(void* h, int32_t* centers_out,
                                     int32_t* ctxs_out) {
  WinPrefetcher* p = (WinPrefetcher*)h;
  std::vector<int32_t> item;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    if (p->consumed >= p->total_batches) return 0;
    size_t s = (size_t)(p->consumed % (int64_t)p->capacity);
    p->cv_ready.wait(lk, [&] {
      return p->closed || p->slot_ticket[s] == p->consumed;
    });
    if (p->closed) return 0;
    item = std::move(p->slots[s]);
    p->slot_ticket[s] = -1;
    ++p->consumed;
    p->cv_free.notify_all();
  }
  std::memcpy(centers_out, item.data(), (size_t)p->batch * sizeof(int32_t));
  std::memcpy(ctxs_out, item.data() + p->batch,
              (size_t)(p->batch * p->cw) * sizeof(int32_t));
  return 1;
}

extern "C" void ssn_win_prefetch_close(void* h) {
  WinPrefetcher* p = (WinPrefetcher*)h;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->closed = true;
    p->cv_ready.notify_all();
    p->cv_free.notify_all();
  }
  for (auto& w : p->workers)
    if (w.joinable()) w.join();
  delete p;
}


// ---------------------------------------------------------------- tiered ---
// Host-side hot loops of the tiered parameter store (tiered/store.py). Both
// run per step on the _Prefetcher producer/consumer threads; ctypes releases
// the GIL for the duration of the call, so the other thread keeps moving.

// Master-row ids -> cache-slot-space ids (TieredTable.remap). slot_of maps
// unit -> slot (-1 = non-resident); group > 1 packs G logical rows per cache
// unit (packed-small tiles). Returns the number of non-resident hits; out is
// fully written either way so the caller can raise with context.
extern "C" int64_t ssn_tier_remap(const int64_t* slot_of, const int32_t* rows,
                                  int64_t n, int64_t group, int32_t* out) {
  int64_t bad = 0;
  if (group > 1) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t r = (int64_t)rows[i];
      int64_t s = slot_of[r / group];
      if (s < 0) ++bad;
      out[i] = (int32_t)(s * group + r % group);
    }
  } else {
    for (int64_t i = 0; i < n; ++i) {
      int64_t s = slot_of[(int64_t)rows[i]];
      if (s < 0) ++bad;
      out[i] = (int32_t)s;
    }
  }
  return bad;
}

// CLOCK hand sweep with pinned-slot masking (TieredTable._allocate eviction
// loop, bit-exact): skip pinned slots, halve nonzero reference counters as
// the hand passes (hot rows survive O(log ref) sweeps), take zero-ref slots
// as victims and pin them so one sweep never picks a slot twice. Mutates
// ref and pinned in place, writes n victim slots to out, returns the new
// hand position. The caller guarantees n reachable victims exist (the
// working-set-vs-budget check in ensure()), matching the Python loop's
// termination contract.
extern "C" int64_t ssn_tier_clock_sweep(uint8_t* ref, uint8_t* pinned,
                                        int64_t budget, int64_t hand,
                                        int64_t n, int64_t* out) {
  int64_t k = 0;
  while (k < n) {
    int64_t h = hand;
    hand = (hand + 1) % budget;
    if (pinned[h]) continue;
    if (ref[h] > 0) {
      ref[h] >>= 1;
      continue;
    }
    out[k++] = h;
    pinned[h] = 1;
  }
  return hand;
}
