"""ctypes bindings for the native data-pipeline core (libsnails.cpp).

Compiled on demand with g++ (no pybind11 — plain C ABI + ctypes, per the
environment's binding guidance) and cached next to the source. Every entry
point has a pure-Python fallback in :mod:`swiftsnails_tpu.data`; callers check
:func:`available` or rely on the wrappers which raise cleanly when the
toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "libsnails.cpp")
# SSN_NATIVE_SO points at an alternate build (e.g. the ASan/TSan builds made
# by tools/native_sanitize.sh); the default is built on demand next to _SRC.
_SO = os.environ.get("SSN_NATIVE_SO") or os.path.join(_DIR, "libsnails.so")

_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None


def _build() -> Optional[str]:
    """Compile the shared library if stale; returns error text or None."""
    if os.environ.get("SSN_NATIVE_SO"):
        return None if os.path.exists(_SO) else f"SSN_NATIVE_SO not found: {_SO}"
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return None
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", _SO, _SRC,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ invocation failed: {e}"
    if proc.returncode != 0:
        return f"g++ failed:\n{proc.stderr}"
    return None


def _load():
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()
        if err is not None:
            _build_error = err
            return None
        lib = ctypes.CDLL(_SO)
        c = ctypes
        try:
            _bind(lib, c)
        except AttributeError as e:
            # e.g. SSN_NATIVE_SO pointing at a build of older source: treat
            # as unavailable (callers fall back to Python) instead of raising
            _build_error = f"native library missing symbols (stale build?): {e}"
            return None
        _lib = lib
        return _lib


def _bind(lib, c):
        lib.ssn_murmur64.argtypes = [c.c_void_p, c.c_void_p, c.c_int64]
        lib.ssn_hash_row.argtypes = [c.c_void_p, c.c_int64, c.c_uint64, c.c_void_p]
        lib.ssn_vocab_build.restype = c.c_void_p
        lib.ssn_vocab_build.argtypes = [c.c_char_p, c.c_int, c.c_int]
        lib.ssn_vocab_size.restype = c.c_int64
        lib.ssn_vocab_size.argtypes = [c.c_void_p]
        lib.ssn_vocab_counts.argtypes = [c.c_void_p, c.c_void_p]
        lib.ssn_vocab_word.restype = c.c_int
        lib.ssn_vocab_word.argtypes = [c.c_void_p, c.c_int64, c.c_char_p, c.c_int]
        lib.ssn_vocab_free.argtypes = [c.c_void_p]
        lib.ssn_encode.restype = c.c_int64
        lib.ssn_encode.argtypes = [c.c_void_p, c.c_char_p, c.c_void_p, c.c_int64]
        lib.ssn_skipgram_pairs.restype = c.c_int64
        lib.ssn_skipgram_pairs.argtypes = [
            c.c_void_p, c.c_int64, c.c_int, c.c_uint64, c.c_int,
            c.c_void_p, c.c_void_p, c.c_int64,
        ]
        lib.ssn_skipgram_windows.restype = c.c_int64
        lib.ssn_skipgram_windows.argtypes = [
            c.c_void_p, c.c_int64, c.c_int, c.c_uint64, c.c_int, c.c_void_p,
        ]
        lib.ssn_subsample.restype = c.c_int64
        lib.ssn_subsample.argtypes = [
            c.c_void_p, c.c_int64, c.c_void_p, c.c_int64,
            c.c_double, c.c_double, c.c_uint64, c.c_void_p,
        ]
        lib.ssn_read_ctr.restype = c.c_int64
        lib.ssn_read_ctr.argtypes = [c.c_char_p, c.c_int, c.c_void_p, c.c_void_p, c.c_int64]
        lib.ssn_neg_table_build.restype = c.c_void_p
        lib.ssn_neg_table_build.argtypes = [c.c_void_p, c.c_int64, c.c_int64]
        lib.ssn_neg_table_free.argtypes = [c.c_void_p]
        lib.ssn_sgns_train.restype = c.c_double
        lib.ssn_sgns_train.argtypes = [
            c.c_void_p, c.c_void_p, c.c_int, c.c_void_p, c.c_void_p,
            c.c_int64, c.c_int, c.c_float, c.c_void_p, c.c_uint64,
        ]
        lib.ssn_prefetch_open.restype = c.c_void_p
        lib.ssn_prefetch_open.argtypes = [
            c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_int, c.c_int, c.c_uint64,
        ]
        lib.ssn_prefetch_next.restype = c.c_int
        lib.ssn_prefetch_next.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
        lib.ssn_prefetch_close.argtypes = [c.c_void_p]
        lib.ssn_win_prefetch_open.restype = c.c_void_p
        lib.ssn_win_prefetch_open.argtypes = [
            c.c_void_p, c.c_void_p, c.c_int64, c.c_int, c.c_int64, c.c_int64,
            c.c_int, c.c_int, c.c_int, c.c_uint64,
        ]
        lib.ssn_win_prefetch_next.restype = c.c_int
        lib.ssn_win_prefetch_next.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
        lib.ssn_win_prefetch_close.argtypes = [c.c_void_p]
        lib.ssn_vocab_build_stream.restype = c.c_void_p
        lib.ssn_vocab_build_stream.argtypes = [c.c_char_p, c.c_int, c.c_int]
        lib.ssn_stream_open.restype = c.c_void_p
        lib.ssn_stream_open.argtypes = [c.c_void_p, c.c_char_p, c.c_int64, c.c_int64]
        lib.ssn_stream_next.restype = c.c_int64
        lib.ssn_stream_next.argtypes = [c.c_void_p, c.c_void_p, c.c_int64]
        lib.ssn_stream_close.argtypes = [c.c_void_p]
        lib.ssn_ctr_stream_open.restype = c.c_void_p
        lib.ssn_ctr_stream_open.argtypes = [c.c_char_p, c.c_int, c.c_int64, c.c_int64]
        lib.ssn_ctr_stream_next.restype = c.c_int64
        lib.ssn_ctr_stream_next.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64]
        lib.ssn_ctr_stream_close.argtypes = [c.c_void_p]
        lib.ssn_tier_remap.restype = c.c_int64
        lib.ssn_tier_remap.argtypes = [
            c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_void_p,
        ]
        lib.ssn_tier_clock_sweep.restype = c.c_int64
        lib.ssn_tier_clock_sweep.argtypes = [
            c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_int64, c.c_void_p,
        ]


def available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


def _require():
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native pipeline unavailable: {_build_error}")
    return lib


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def murmur64(x: np.ndarray) -> np.ndarray:
    lib = _require()
    x = np.ascontiguousarray(x, dtype=np.uint64)
    out = np.empty_like(x)
    lib.ssn_murmur64(_ptr(x), _ptr(out), x.size)
    return out


def hash_row(keys: np.ndarray, capacity: int) -> np.ndarray:
    lib = _require()
    keys = np.ascontiguousarray(keys, dtype=np.uint32)
    out = np.empty(keys.size, dtype=np.int64)
    lib.ssn_hash_row(_ptr(keys), keys.size, capacity, _ptr(out))
    return out


class NativeVocab:
    """C++ vocab builder (reference hashmap.h + scan_file_by_line parity).

    ``stream=True`` (default) reads through a fixed buffer — O(vocab) memory
    regardless of corpus size, same ordering contract as the whole-file path.
    """

    def __init__(self, path: str, min_count: int = 5, max_size: int = 0,
                 stream: bool = True):
        lib = _require()
        self._lib = lib
        build = lib.ssn_vocab_build_stream if stream else lib.ssn_vocab_build
        self._h = build(path.encode(), min_count, max_size)
        if not self._h:
            raise OSError(f"cannot read {path}")

    def __len__(self) -> int:
        return int(self._lib.ssn_vocab_size(self._h))

    def counts(self) -> np.ndarray:
        out = np.empty(len(self), dtype=np.int64)
        self._lib.ssn_vocab_counts(self._h, _ptr(out))
        return out

    def words(self) -> List[str]:
        buf = ctypes.create_string_buffer(65536)
        out = []
        for i in range(len(self)):
            n = self._lib.ssn_vocab_word(self._h, i, buf, len(buf))
            if n < 0:
                raise ValueError(f"word {i} too long")
            out.append(buf.value.decode("utf-8", "replace"))
        return out

    def encode_file(self, path: str) -> np.ndarray:
        # Size guess: for the vocab's own source file the kept-token count is
        # exactly counts().sum(), avoiding a second full tokenize pass. For a
        # different file the guess may be short; ssn_encode then returns the
        # true count negated and we retry once with the exact size.
        guess = int(self.counts().sum()) if len(self) else 0
        out = np.empty(max(guess, 1), dtype=np.int32)
        got = self._lib.ssn_encode(self._h, path.encode(), _ptr(out), out.size)
        if got == -1:
            # -1 is unambiguously an IO error: overflow returns -(total) and
            # a 1-token corpus always fits the >=1-sized buffer
            raise OSError(f"cannot read {path}")
        if got < 0:
            needed = -got
            out = np.empty(needed, dtype=np.int32)
            got = self._lib.ssn_encode(self._h, path.encode(), _ptr(out), needed)
            if got < 0:
                raise RuntimeError("corpus changed size during encode")
        return out[:got]

    def encode_stream(self, path: str, chunk_tokens: int,
                      byte_start: int = 0, byte_end: int = 0):
        """Yield encoded int32 chunks of <= chunk_tokens ids (OOV dropped).

        Bounded memory (one read buffer + one chunk): the streaming twin of
        :meth:`encode_file` for corpora that don't fit in RAM —
        ``scan_file_by_line`` parity (src/utils/file.h:11-33). A nonzero
        ``(byte_start, byte_end)`` reads that span with Hadoop split
        semantics (a token belongs to the span its first byte falls in), the
        multi-host stdin-split equivalent.
        """
        lib = self._lib
        h = lib.ssn_stream_open(self._h, path.encode(), byte_start, byte_end)
        if not h:
            raise OSError(f"cannot read {path}")
        try:
            while True:
                out = np.empty(chunk_tokens, dtype=np.int32)
                got = lib.ssn_stream_next(h, _ptr(out), chunk_tokens)
                if got <= 0:
                    return
                yield out[:got]
        finally:
            lib.ssn_stream_close(h)

    def to_python(self):
        from swiftsnails_tpu.data.vocab import Vocab

        return Vocab(self.words(), self.counts())

    def close(self):
        if self._h:
            self._lib.ssn_vocab_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def skipgram_pairs(
    ids: np.ndarray, window: int, seed: int = 0, dynamic: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    lib = _require()
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    n = lib.ssn_skipgram_pairs(_ptr(ids), ids.size, window, seed, int(dynamic), None, None, 0)
    centers = np.empty(n, dtype=np.int32)
    contexts = np.empty(n, dtype=np.int32)
    got = lib.ssn_skipgram_pairs(
        _ptr(ids), ids.size, window, seed, int(dynamic), _ptr(centers), _ptr(contexts), n
    )
    assert got == n, (got, n)
    return centers, contexts


def skipgram_windows(
    ids: np.ndarray, window: int, seed: int = 0, dynamic: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    """Center-major window schema (centers [n], contexts [n, 2w], -1 pads).

    Same b-draw sequence as :func:`skipgram_pairs` for a given seed, so the
    flat and grouped schemas generate the identical pair set.
    """
    lib = _require()
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    ctxs = np.empty((ids.size, 2 * window), dtype=np.int32)
    got = lib.ssn_skipgram_windows(
        _ptr(ids), ids.size, window, seed, int(dynamic), _ptr(ctxs)
    )
    assert got == ids.size, (got, ids.size)
    return ids.copy(), ctxs


def subsample(
    ids: np.ndarray, counts: np.ndarray, threshold: float, seed: int = 0
) -> np.ndarray:
    lib = _require()
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    out = np.empty(ids.size, dtype=np.int32)
    k = lib.ssn_subsample(
        _ptr(ids), ids.size, _ptr(counts), counts.size,
        float(counts.sum()), threshold, seed, _ptr(out),
    )
    return out[:k]


def read_ctr(path: str, num_fields: int) -> Tuple[np.ndarray, np.ndarray]:
    lib = _require()
    n = lib.ssn_read_ctr(path.encode(), num_fields, None, None, 0)
    if n < 0:
        raise OSError(f"cannot read {path}")
    labels = np.empty(n, dtype=np.float32)
    feats = np.empty((n, num_fields), dtype=np.int32)
    got = lib.ssn_read_ctr(path.encode(), num_fields, _ptr(labels), _ptr(feats), n)
    if got < 0:
        raise RuntimeError("file changed size during read")
    return labels[:got], feats[:got]


def read_ctr_stream(path: str, num_fields: int, rows_per_chunk: int = 1 << 20,
                    byte_start: int = 0, byte_end: int = 0):
    """Yield (labels, feats) chunks of <= rows_per_chunk parsed CTR records.

    Bounded-memory twin of :func:`read_ctr` (line carry across read-buffer
    edges) — what the Criteo-1TB-scale configs feed from. A nonzero byte
    span reads that shard with Hadoop line-split semantics.
    """
    lib = _require()
    h = lib.ssn_ctr_stream_open(path.encode(), num_fields, byte_start, byte_end)
    if not h:
        raise OSError(f"cannot read {path}")
    try:
        while True:
            labels = np.empty(rows_per_chunk, dtype=np.float32)
            feats = np.empty((rows_per_chunk, num_fields), dtype=np.int32)
            got = lib.ssn_ctr_stream_next(h, _ptr(labels), _ptr(feats), rows_per_chunk)
            if got <= 0:
                return
            yield labels[:got], feats[:got]
    finally:
        lib.ssn_ctr_stream_close(h)


def sgns_train(
    syn0: np.ndarray,
    syn1: np.ndarray,
    centers: np.ndarray,
    contexts: np.ndarray,
    counts: np.ndarray,
    negatives: int = 5,
    lr: float = 0.025,
    table_size: int = 1 << 22,
    seed: int = 0,
) -> float:
    """Run the compiled single-node SGNS worker loop in place.

    Returns elapsed seconds for the training loop (excluding the one-time
    negative-table build). ``syn0``/``syn1`` are updated in place — this is
    bench.py's calibrated per-node CPU parameter-server baseline.
    """
    lib = _require()
    # The C loop trusts its pointers; validate everything that could write
    # out of bounds (real raises, not asserts — must survive python -O).
    for name, a in (("syn0", syn0), ("syn1", syn1)):
        if a.dtype != np.float32 or not a.flags.c_contiguous or a.ndim != 2:
            raise ValueError(f"{name} must be a C-contiguous float32 matrix")
    if syn0.shape[1] != syn1.shape[1]:
        raise ValueError(f"dim mismatch: {syn0.shape} vs {syn1.shape}")
    centers = np.ascontiguousarray(centers, dtype=np.int32)
    contexts = np.ascontiguousarray(contexts, dtype=np.int32)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    if centers.shape != contexts.shape:
        raise ValueError("centers/contexts length mismatch")
    if centers.size and (
        centers.min() < 0 or centers.max() >= syn0.shape[0]
    ):
        raise ValueError("center id out of range for syn0")
    if contexts.size and (
        contexts.min() < 0 or contexts.max() >= syn1.shape[0]
    ):
        raise ValueError("context id out of range for syn1")
    # negative-table targets index syn1 rows in [0, counts.size)
    if counts.size > syn1.shape[0]:
        raise ValueError("counts longer than syn1 rows")
    table = lib.ssn_neg_table_build(_ptr(counts), counts.size, table_size)
    if not table:
        raise ValueError("empty vocab for negative table")
    try:
        return float(
            lib.ssn_sgns_train(
                _ptr(syn0), _ptr(syn1), syn0.shape[1], _ptr(centers),
                _ptr(contexts), centers.size, negatives, lr, table, seed,
            )
        )
    finally:
        lib.ssn_neg_table_free(table)


class PairPrefetcher:
    """Bounded-queue shuffled batch producer (queue_with_capacity parity).

    A C++ producer thread shuffles and slices (centers, contexts) into
    fixed-size batches; iteration blocks on the bounded queue and ends when
    the producer finishes all epochs (poison-free close semantics).
    """

    def __init__(
        self,
        centers: np.ndarray,
        contexts: np.ndarray,
        batch_size: int,
        epochs: int = 1,
        capacity: int = 8,
        seed: int = 0,
    ):
        lib = _require()
        self._lib = lib
        self.batch_size = batch_size
        c = np.ascontiguousarray(centers, dtype=np.int32)
        x = np.ascontiguousarray(contexts, dtype=np.int32)
        self._h = lib.ssn_prefetch_open(
            _ptr(c), _ptr(x), c.size, batch_size, epochs, capacity, seed
        )
        if not self._h:
            raise ValueError("bad prefetcher arguments (empty data or batch > n)")

    def __iter__(self):
        while self._h:  # guard: next() after close() must end, not segfault
            centers = np.empty(self.batch_size, dtype=np.int32)
            contexts = np.empty(self.batch_size, dtype=np.int32)
            ok = self._lib.ssn_prefetch_next(self._h, _ptr(centers), _ptr(contexts))
            if not ok:
                return
            yield {"centers": centers, "contexts": contexts}

    def close(self):
        if self._h:
            self._lib.ssn_prefetch_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class WindowPrefetcher:
    """Center-major window-batch producer (grouped/dedup kernel schema).

    C++ worker threads shuffle BLOCKS of ``block`` consecutive windows
    (``block=1`` = plain row shuffle) and assemble
    ``{"centers": [B], "contexts": [B, cw]}`` batches behind a bounded
    order-preserving ticket ring — the batch sequence is deterministic in
    ``seed``/``epochs`` regardless of worker count. This replaces the
    Python ``batch_stream``/``batch_stream_blocks`` loop in the hot path
    (same schema, native assembly).
    """

    def __init__(
        self,
        centers: np.ndarray,
        contexts: np.ndarray,
        batch_size: int,
        block: int = 1,
        epochs: int = 1,
        capacity: int = 8,
        workers: int = 0,
        seed: int = 0,
    ):
        lib = _require()
        self._lib = lib
        self.batch_size = batch_size
        # the C producer BORROWS these buffers (no copy — a [n, 2w] window
        # array is already the chunk's dominant allocation); the refs below
        # keep them alive for the handle's lifetime. Callers must not
        # mutate them while iterating.
        self._c = np.ascontiguousarray(centers, dtype=np.int32)
        self._x = np.ascontiguousarray(contexts, dtype=np.int32)
        if self._x.ndim != 2 or self._x.shape[0] != self._c.size:
            raise ValueError(f"contexts must be [n, cw], got {self._x.shape}")
        self.cw = self._x.shape[1]
        self._h = lib.ssn_win_prefetch_open(
            _ptr(self._c), _ptr(self._x), self._c.size, self.cw, batch_size,
            block, epochs, capacity, workers, seed,
        )
        if not self._h:
            raise ValueError(
                "bad window-prefetcher arguments (empty data, batch > n, or "
                "batch not a multiple of block)"
            )

    def __iter__(self):
        while self._h:  # guard: next() after close() must end, not segfault
            centers = np.empty(self.batch_size, dtype=np.int32)
            contexts = np.empty((self.batch_size, self.cw), dtype=np.int32)
            ok = self._lib.ssn_win_prefetch_next(self._h, _ptr(centers), _ptr(contexts))
            if not ok:
                return
            yield {"centers": centers, "contexts": contexts}

    def close(self):
        if self._h:
            self._lib.ssn_win_prefetch_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass



# ---------------------------------------------------------------- tiered ---


def tier_remap(slot_of: np.ndarray, rows: np.ndarray,
               group: int = 1) -> Tuple[np.ndarray, int]:
    """Master-row ids -> cache-slot ids for the tiered store's per-step remap
    (``TieredTable.remap`` hot path). Returns ``(slots, n_nonresident)``;
    the caller raises on a nonzero miss count. Releases the GIL for the
    duration, so the prefetch producer thread keeps staging."""
    lib = _require()
    slot_of = np.ascontiguousarray(slot_of, dtype=np.int64)
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    out = np.empty(rows.size, dtype=np.int32)
    bad = lib.ssn_tier_remap(
        _ptr(slot_of), _ptr(rows), rows.size, int(group), _ptr(out))
    return out, int(bad)


def tier_clock_sweep(ref: np.ndarray, pinned: np.ndarray, hand: int,
                     n: int) -> Tuple[np.ndarray, int]:
    """CLOCK victim selection (``TieredTable._allocate`` eviction sweep,
    bit-exact vs the Python loop). Mutates ``ref`` (aging) and ``pinned``
    (selected slots become pinned) IN PLACE; returns ``(victim_slots,
    new_hand)``. ``ref`` must be a writable contiguous uint8 array and
    ``pinned`` a writable contiguous bool/uint8 array of the same length;
    the caller guarantees ``n`` unpinned slots exist."""
    lib = _require()
    assert ref.dtype == np.uint8 and ref.flags.c_contiguous and ref.flags.writeable
    pin8 = pinned.view(np.uint8)
    assert pin8.flags.c_contiguous and pin8.flags.writeable
    assert ref.size == pin8.size
    out = np.empty(max(int(n), 0), dtype=np.int64)
    new_hand = lib.ssn_tier_clock_sweep(
        _ptr(ref), _ptr(pin8), ref.size, int(hand), int(n), _ptr(out))
    return out, int(new_hand)
