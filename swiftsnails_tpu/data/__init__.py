from swiftsnails_tpu.data.vocab import Vocab
from swiftsnails_tpu.data.text import read_tokens, encode_corpus
from swiftsnails_tpu.data.sampler import (
    AliasTable,
    build_unigram_alias,
    skipgram_pairs,
    subsample_mask,
)

__all__ = [
    "Vocab",
    "read_tokens",
    "encode_corpus",
    "AliasTable",
    "build_unigram_alias",
    "skipgram_pairs",
    "subsample_mask",
]
