"""Sparse CTR-style record parsing and batching.

The reference's app layer parsed records worker-side via
``BaseAlgorithm::parse_record(line)`` (``src/core/framework/SwiftWorker.h:19-57``)
with whitespace-int features (``src/tools/gen-word2vec-data.py`` format).
This module is the equivalent for the CTR model families (LR, Wide&Deep,
FM/FFM — the BASELINE.json Criteo/Avazu configs):

* record format: ``label f0 f1 ... f{F-1}`` — one categorical feature id per
  field (Criteo/Avazu shape). ``field:value`` tokens are accepted and the
  field index is taken from position; missing fields pad with ``-1``;
* batches are fixed-shape ``{"labels": f32[B], "feats": i32[B, F]}`` with
  ``-1`` padding (masked out in the models) — static shapes for jit;
* feature ids are *global* (already field-offset or hashed upstream); models
  apply the hashing trick (``hash_row``) for table placement.
"""

from __future__ import annotations

import re
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

PAD = -1
_INT_PREFIX = re.compile(r"[+-]?\d+")


def parse_record(line: str, num_fields: int) -> Optional[Tuple[float, np.ndarray]]:
    """``label f0 f1 ...`` -> (label, i32[num_fields] with PAD fill).

    Malformed-input semantics match the native parser (``ssn_read_ctr``):
    a non-numeric label (e.g. a header line) skips the whole row (returns
    None); a non-numeric feature token stops feature parsing for that row,
    leaving the remaining fields PAD. Same file, same rows, either path.
    """
    parts = line.split()
    if not parts:
        return None
    try:
        label = float(parts[0])
    except ValueError:
        return None  # header/garbage row — skipped, like strtod failure
    feats = np.full(num_fields, PAD, dtype=np.int32)
    for i, tok in enumerate(parts[1 : num_fields + 1]):
        if ":" in tok:  # "field:id" or "id:value" — take the id portion
            tok = tok.split(":", 1)[1]
        m = _INT_PREFIX.match(tok)
        if not m:
            break  # stop at first bad token, like strtol failure
        feats[i] = int(m.group(0))
        if len(m.group(0)) != len(tok):
            break  # trailing junk halts the row, like strtol leaving a cursor
    return label, feats


def read_ctr_file(path: str, num_fields: int) -> Tuple[np.ndarray, np.ndarray]:
    labels: List[float] = []
    rows: List[np.ndarray] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            rec = parse_record(line, num_fields)
            if rec is None:
                continue
            labels.append(rec[0])
            rows.append(rec[1])
    return (
        np.asarray(labels, dtype=np.float32),
        np.stack(rows) if rows else np.empty((0, num_fields), np.int32),
    )


def read_ctr_stream(
    path: str,
    num_fields: int,
    rows_per_chunk: int = 1 << 20,
    byte_start: int = 0,
    byte_end: int = 0,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield (labels, feats) chunks of <= rows_per_chunk records — pure-Python
    twin of the native streaming reader (bounded memory; Hadoop line-split
    semantics for a nonzero byte span: a line belongs to the span its first
    byte falls in)."""
    labels: List[float] = []
    rows: List[np.ndarray] = []

    def flush():
        out = (
            np.asarray(labels, dtype=np.float32),
            np.stack(rows) if rows else np.empty((0, num_fields), np.int32),
        )
        labels.clear()
        rows.clear()
        return out

    with open(path, "rb") as f:
        if byte_start > 0:
            f.seek(byte_start - 1)
            if f.read(1) != b"\n":
                f.readline()  # partial first line: previous shard's
        pos = f.tell()
        while True:
            if byte_end > 0 and pos >= byte_end:
                break
            line = f.readline()
            if not line:
                break
            pos += len(line)
            rec = parse_record(line.decode("utf-8", "replace"), num_fields)
            if rec is None:
                continue
            labels.append(rec[0])
            rows.append(rec[1])
            if len(labels) >= rows_per_chunk:
                yield flush()
    if labels:
        yield flush()


def ctr_batches(
    labels: np.ndarray,
    feats: np.ndarray,
    batch_size: int,
    rng: np.random.Generator,
    shuffle: bool = True,
    epochs: int = 1,
) -> Iterator[Dict[str, np.ndarray]]:
    n = len(labels)
    usable = (n // batch_size) * batch_size
    for _ in range(epochs):
        order = rng.permutation(n) if shuffle else np.arange(n)
        for start in range(0, usable, batch_size):
            sel = order[start : start + batch_size]
            yield {"labels": labels[sel], "feats": feats[sel]}


def synth_ctr(
    n: int,
    num_fields: int,
    vocab_per_field: int,
    seed: int = 0,
    noise: float = 0.25,
    interaction: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Synthetic CTR data with planted weights (and optional pairwise
    interactions, for FM tests). Returns (labels, feats, true_weights).

    Feature ids are field-offset: field i draws from
    ``[i*vocab_per_field, (i+1)*vocab_per_field)``.
    """
    rng = np.random.default_rng(seed)
    total_vocab = num_fields * vocab_per_field
    w = rng.normal(0, 1.0, size=total_vocab).astype(np.float32)
    feats = np.stack(
        [
            rng.integers(0, vocab_per_field, size=n) + i * vocab_per_field
            for i in range(num_fields)
        ],
        axis=1,
    ).astype(np.int32)
    logits = w[feats].sum(axis=1)
    if interaction:
        v = rng.normal(0, 0.5, size=(total_vocab, 4)).astype(np.float32)
        emb = v[feats]  # [n, F, 4]
        s = emb.sum(axis=1)
        inter = 0.5 * ((s**2).sum(-1) - (emb**2).sum(axis=(1, 2)))
        logits = logits + inter
    logits = logits + rng.normal(0, noise, size=n)
    labels = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    return labels, feats, w
