"""Vocabulary: token -> id with frequency counts.

The reference keeps its vocab in a host-side hashmap (``src/utils/hashmap.h``
wrappers over google sparsehash) and its word2vec data as whitespace-separated
int features (``src/tools/gen-word2vec-data.py``). Here the vocab is a plain
dict built once on the host; the hot encode path is vectorized through numpy
(and later the C++ pipeline extension).
"""

from __future__ import annotations

import collections
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np


class Vocab:
    """Frequency-ranked vocabulary with min-count filtering."""

    def __init__(self, words: List[str], counts: np.ndarray):
        assert len(words) == len(counts)
        self.words = words
        self.counts = np.asarray(counts, dtype=np.int64)
        self.index: Dict[str, int] = {w: i for i, w in enumerate(words)}

    @classmethod
    def from_counter(
        cls,
        counter: Dict[str, int],
        min_count: int = 5,
        max_size: Optional[int] = None,
    ) -> "Vocab":
        """The single source of the ordering contract (also mirrored by the
        native builder): frequency desc, then lexicographic, min-count
        filtered, truncated to max_size."""
        items = [(w, c) for w, c in counter.items() if c >= min_count]
        items.sort(key=lambda wc: (-wc[1], wc[0]))
        if max_size is not None:
            items = items[:max_size]
        words = [w for w, _ in items]
        counts = np.array([c for _, c in items], dtype=np.int64)
        return cls(words, counts)

    @classmethod
    def build(
        cls,
        tokens: Iterable[str],
        min_count: int = 5,
        max_size: Optional[int] = None,
    ) -> "Vocab":
        return cls.from_counter(collections.Counter(tokens), min_count, max_size)

    def __len__(self) -> int:
        return len(self.words)

    def __contains__(self, word: str) -> bool:
        return word in self.index

    def frequency_ranks(self) -> np.ndarray:
        """Per-id frequency rank (0 = most frequent; ties broken by id, which
        is already lexicographic under the ordering contract). Vocab ids are
        frequency-ranked at build time, so for a freshly built vocab this is
        ``arange``; a loaded/merged vocab may not be sorted, hence the
        explicit double argsort. Consumers: the tiered store pre-warms its
        HBM cache with the hottest rows before step 0."""
        order = np.argsort(-self.counts, kind="stable")
        ranks = np.empty(len(self.counts), dtype=np.int64)
        ranks[order] = np.arange(len(self.counts), dtype=np.int64)
        return ranks

    def hottest_rows(self, k: Optional[int] = None) -> np.ndarray:
        """Vocab ids ordered hottest-first (inverse of frequency_ranks).
        Consumers: tiered prewarm (`tier_warm_rows`) and the placement
        auto-partitioner's head candidates."""
        order = np.argsort(self.frequency_ranks(), kind="stable")
        return order if k is None else order[:k]

    def cumulative_coverage(self) -> np.ndarray:
        """CDF over frequency ranks: ``out[k]`` is the fraction of token
        accesses covered by the ``k`` hottest rows (``out[0] == 0``,
        ``out[len(vocab)] == 1``). The placement cost model reads the
        coverage of a candidate head cut straight off this curve."""
        hot = self.counts[self.hottest_rows()].astype(np.float64)
        total = hot.sum()
        cdf = np.cumsum(hot) / (total if total > 0 else 1.0)
        return np.concatenate([[0.0], cdf])

    def coverage_at(self, k: int) -> float:
        """Fraction of accesses the ``k`` hottest rows cover."""
        cdf = self.cumulative_coverage()
        return float(cdf[min(max(int(k), 0), len(cdf) - 1)])

    def encode(self, tokens: Iterable[str]) -> np.ndarray:
        """Token stream -> int32 ids, dropping OOV (word2vec convention)."""
        idx = self.index
        return np.fromiter(
            (idx[t] for t in tokens if t in idx), dtype=np.int32
        )

    # -- persistence (text format: "word<TAB>count" per line, rank order) ----

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            for w, c in zip(self.words, self.counts):
                f.write(f"{w}\t{int(c)}\n")

    @classmethod
    def load(cls, path: str) -> "Vocab":
        words: List[str] = []
        counts: List[int] = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                w, c = line.split("\t")
                words.append(w)
                counts.append(int(c))
        return cls(words, np.array(counts, dtype=np.int64))
