"""Skip-gram pair generation and negative sampling.

Implements the word2vec training-data pipeline the reference shipped as an
(absent) app over ``BaseAlgorithm`` (survey §2.7): dynamic-window skip-gram
pairs, frequent-word subsampling, and unigram^0.75 negative sampling.

Negative sampling runs **on device** via the alias method: two O(vocab)
arrays built once on the host, O(1) sampling per draw inside the jit'd step —
no host RNG in the hot loop (the original word2vec.c uses a 100M-entry
resampling table; the alias table is the exact-distribution equivalent).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class AliasTable(NamedTuple):
    """Walker alias table for a discrete distribution over [0, n)."""

    prob: jax.Array  # f32[n] — acceptance probability of the home bucket
    alias: jax.Array  # i32[n] — fallback outcome per bucket

    @property
    def n(self) -> int:
        return self.prob.shape[0]


def build_alias(weights: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Vose's alias construction (host, O(n))."""
    w = np.asarray(weights, dtype=np.float64)
    if w.ndim != 1 or len(w) == 0 or np.any(w < 0) or w.sum() == 0:
        raise ValueError("weights must be a nonempty 1-D nonnegative array with positive sum")
    n = len(w)
    p = w * (n / w.sum())
    prob = np.zeros(n, dtype=np.float32)
    alias = np.zeros(n, dtype=np.int32)
    small = [i for i in range(n) if p[i] < 1.0]
    large = [i for i in range(n) if p[i] >= 1.0]
    while small and large:
        s = small.pop()
        l = large.pop()
        prob[s] = p[s]
        alias[s] = l
        p[l] = (p[l] + p[s]) - 1.0
        (small if p[l] < 1.0 else large).append(l)
    for i in large:
        prob[i] = 1.0
        alias[i] = i
    for i in small:  # numerical leftovers
        prob[i] = 1.0
        alias[i] = i
    return prob, alias


def build_unigram_alias(counts: np.ndarray, power: float = 0.75) -> AliasTable:
    """word2vec negative-sampling distribution: freq^0.75."""
    weights = np.asarray(counts, dtype=np.float64) ** power
    prob, alias = build_alias(weights)
    return AliasTable(prob=jnp.asarray(prob), alias=jnp.asarray(alias))


def alias_sample(table: AliasTable, rng: jax.Array, shape) -> jax.Array:
    """Draw ids from the alias table on device. Jittable, O(1) per draw."""
    k_bucket, k_coin = jax.random.split(rng)
    bucket = jax.random.randint(k_bucket, shape, 0, table.n, dtype=jnp.int32)
    coin = jax.random.uniform(k_coin, shape, dtype=jnp.float32)
    keep = coin < table.prob[bucket]
    return jnp.where(keep, bucket, table.alias[bucket])


def subsample_mask(
    ids: np.ndarray, counts: np.ndarray, threshold: float, rng: np.random.Generator
) -> np.ndarray:
    """Frequent-word subsampling (word2vec): keep word w with probability
    ``min(1, sqrt(t/f(w)) + t/f(w))`` where f is the corpus frequency."""
    if threshold <= 0:
        return np.ones(len(ids), dtype=bool)
    freqs = counts / counts.sum()
    f = freqs[ids]
    keep_p = np.minimum(1.0, np.sqrt(threshold / f) + threshold / f)
    return rng.random(len(ids)) < keep_p


def skipgram_pairs(
    ids: np.ndarray,
    window: int,
    rng: np.random.Generator,
    dynamic: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized (center, context) pair generation over an id stream.

    For each position, a per-position window ``b ~ U(1, window)`` (word2vec's
    dynamic window) selects neighbors at offsets ``-b..-1, 1..b``. Returns
    int32 arrays (centers, contexts).
    """
    pos, valid = _dynamic_window_valid(ids, window, rng, dynamic)
    if pos is None:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    n = len(ids)
    centers = np.repeat(np.arange(n), valid.sum(axis=1))
    contexts = pos[valid]
    return ids[centers].astype(np.int32), ids[contexts].astype(np.int32)


def _dynamic_window_valid(ids, window, rng, dynamic):
    """Shared dynamic-window geometry: (pos [n, 2w], valid [n, 2w]).

    The single source of the b ~ U(1, window) draw and boundary clipping —
    skipgram_pairs and skipgram_windows MUST generate the same pair set
    (flat vs grouped quality comparisons depend on it)."""
    n = len(ids)
    if n < 2:
        return None, None
    b = rng.integers(1, window + 1, size=n) if dynamic else np.full(n, window)
    offsets = np.arange(-window, window + 1)
    offsets = offsets[offsets != 0]  # [2w]
    pos = np.arange(n)[:, None] + offsets[None, :]  # [n, 2w]
    valid = (pos >= 0) & (pos < n) & (np.abs(offsets)[None, :] <= b[:, None])
    return pos, valid


def skipgram_windows(
    ids: np.ndarray,
    window: int,
    rng: np.random.Generator,
    dynamic: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Center-major skip-gram: ``(centers [n], contexts [n, 2*window])``.

    Same pair set as :func:`skipgram_pairs` (identical dynamic-window draw),
    but grouped by center position with ``-1`` padding in unused context
    slots — the layout of word2vec.c's inner loop, and what the grouped
    fused kernel consumes (the center row is loaded ONCE for its whole
    window instead of once per pair; the per-row copy issue rate is the
    kernel's bound).
    """
    n = len(ids)
    cw = 2 * window
    pos, valid = _dynamic_window_valid(ids, window, rng, dynamic)
    if pos is None:
        return np.empty(0, np.int32), np.empty((0, cw), np.int32)
    ctxs = np.where(valid, ids[np.clip(pos, 0, n - 1)], -1).astype(np.int32)
    return ids.astype(np.int32, copy=True), ctxs


def batch_stream(
    centers: np.ndarray,
    contexts: np.ndarray,
    batch_size: int,
    rng: np.random.Generator,
    shuffle: bool = True,
    drop_remainder: bool = True,
):
    """Yield {'centers', 'contexts'} batches of exactly ``batch_size``.

    ``contexts`` may be 2-D (the window schema [N, 2w] from
    :func:`skipgram_windows`): rows shuffle whole — windows move together,
    pair order inside a window stays sequential, word2vec.c-style.
    """
    n = len(centers)
    order = rng.permutation(n) if shuffle else np.arange(n)
    end = (n // batch_size) * batch_size if drop_remainder else n
    for start in range(0, end, batch_size):
        sel = order[start : start + batch_size]
        yield {"centers": centers[sel], "contexts": contexts[sel]}


def batch_stream_blocks(
    centers: np.ndarray,
    contexts: np.ndarray,
    batch_size: int,
    rng: np.random.Generator,
    block: int,
):
    """:func:`batch_stream` shuffling BLOCKS of ``block`` consecutive
    windows instead of individual windows.

    Within a block the corpus order is preserved, so a kernel block of
    ``block`` centers spans ~``block`` consecutive tokens and touches only
    ~``block`` DISTINCT context rows (adjacent windows overlap) — the
    locality the dedup kernel's per-block unique-row copy list turns into
    ~5x fewer read DMAs. word2vec.c trains fully sequentially; shuffling at
    block granularity keeps SGD mixing across blocks/epochs while restoring
    that local structure.
    """
    if batch_size % block:
        # batches must be EXACTLY batch_size (train_step reshapes by it):
        # shrink to the largest divisor of batch_size not exceeding block
        block = next(d for d in range(min(block, batch_size), 0, -1)
                     if batch_size % d == 0)
    n = (len(centers) // block) * block
    nblocks = n // block
    order = rng.permutation(nblocks)
    blocks_per_batch = batch_size // block
    end = (nblocks // blocks_per_batch) * blocks_per_batch
    for start in range(0, end, blocks_per_batch):
        sel = (order[start : start + blocks_per_batch, None] * block
               + np.arange(block)[None, :]).reshape(-1)
        yield {"centers": centers[sel], "contexts": contexts[sel]}
