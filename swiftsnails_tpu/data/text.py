"""Corpus reading and encoding.

Host-side line/whitespace tokenization — the role of the reference's
``TextBuffer``/``LineFileReader``/``scan_file_by_line`` (``src/utils/Buffer.h:240-324``,
``string.h``, ``file.h:11-33``). The pure-Python path here is the portable
fallback; a C++ fast path is planned as ``swiftsnails_tpu.data.native``.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from swiftsnails_tpu.data.vocab import Vocab


def read_tokens(path: str, limit_bytes: Optional[int] = None) -> List[str]:
    """Whitespace-tokenize a corpus file (text8-style: one giant line is fine)."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        data = f.read(limit_bytes) if limit_bytes else f.read()
    return data.split()


def encode_corpus(
    path: str,
    min_count: int = 5,
    max_vocab: Optional[int] = None,
    limit_bytes: Optional[int] = None,
    vocab: Optional[Vocab] = None,
) -> Tuple[np.ndarray, Vocab]:
    """Read, build (or reuse) a vocab, and encode to an int32 id stream."""
    tokens = read_tokens(path, limit_bytes=limit_bytes)
    if vocab is None:
        vocab = Vocab.build(tokens, min_count=min_count, max_size=max_vocab)
    ids = vocab.encode(tokens)
    return ids, vocab


def iter_line_records(path: str, process_index: int = 0, process_count: int = 1) -> Iterator[str]:
    """Line records, round-robin sharded by process.

    Replaces the reference's Hadoop-Streaming data split (each worker's stdin
    was its split: ``src/tools/run_worker.sh`` ``cat > ./data.txt``) with
    deterministic sharding by process index.
    """
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for i, line in enumerate(f):
            if i % process_count == process_index:
                yield line.rstrip("\n")
