"""Corpus reading and encoding.

Host-side line/whitespace tokenization — the role of the reference's
``TextBuffer``/``LineFileReader``/``scan_file_by_line`` (``src/utils/Buffer.h:240-324``,
``string.h``, ``file.h:11-33``). The pure-Python path here is the portable
fallback; a C++ fast path is planned as ``swiftsnails_tpu.data.native``.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from swiftsnails_tpu.data.vocab import Vocab


def read_tokens(path: str, limit_bytes: Optional[int] = None) -> List[str]:
    """Whitespace-tokenize a corpus file (text8-style: one giant line is fine).

    Splits at the *byte* level on ASCII whitespace, then decodes each token
    (errors='replace') — exactly the native tokenizer's behavior
    (``libsnails.cpp`` ``for_tokens``), so the two paths produce identical
    token streams for any UTF-8-clean corpus. (Residual edge: two distinct
    invalid-UTF-8 byte tokens can decode to the same replacement string here
    while remaining distinct in the byte-keyed native vocab.)
    """
    with open(path, "rb") as f:
        data = f.read(limit_bytes) if limit_bytes else f.read()
    return [t.decode("utf-8", "replace") for t in data.split()]


def encode_corpus(
    path: str,
    min_count: int = 5,
    max_vocab: Optional[int] = None,
    limit_bytes: Optional[int] = None,
    vocab: Optional[Vocab] = None,
    use_native: Optional[bool] = None,
) -> Tuple[np.ndarray, Vocab]:
    """Read, build (or reuse) a vocab, and encode to an int32 id stream.

    Prefers the C++ pipeline (tokenize + count + encode in one pass) when the
    toolchain is available and no byte limit / preexisting vocab forces the
    Python path; results are identical (tested).
    """
    from swiftsnails_tpu.data import native

    if use_native is None:
        use_native = vocab is None and limit_bytes is None and native.available()
    if use_native and vocab is None and limit_bytes is None:
        nv = native.NativeVocab(path, min_count=min_count, max_size=max_vocab or 0)
        ids = nv.encode_file(path)
        py_vocab = nv.to_python()
        nv.close()
        return ids, py_vocab
    tokens = read_tokens(path, limit_bytes=limit_bytes)
    if vocab is None:
        vocab = Vocab.build(tokens, min_count=min_count, max_size=max_vocab)
    ids = vocab.encode(tokens)
    return ids, vocab


def iter_line_records(path: str, process_index: int = 0, process_count: int = 1) -> Iterator[str]:
    """Line records, round-robin sharded by process.

    Replaces the reference's Hadoop-Streaming data split (each worker's stdin
    was its split: ``src/tools/run_worker.sh`` ``cat > ./data.txt``) with
    deterministic sharding by process index.
    """
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for i, line in enumerate(f):
            if i % process_count == process_index:
                yield line.rstrip("\n")
