"""Corpus reading and encoding.

Host-side line/whitespace tokenization — the role of the reference's
``TextBuffer``/``LineFileReader``/``scan_file_by_line`` (``src/utils/Buffer.h:240-324``,
``string.h``, ``file.h:11-33``). The pure-Python path here is the portable
fallback; a C++ fast path is planned as ``swiftsnails_tpu.data.native``.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from swiftsnails_tpu.data.vocab import Vocab


def read_tokens(path: str, limit_bytes: Optional[int] = None) -> List[str]:
    """Whitespace-tokenize a corpus file (text8-style: one giant line is fine).

    Splits at the *byte* level on ASCII whitespace, then decodes each token
    (errors='replace') — exactly the native tokenizer's behavior
    (``libsnails.cpp`` ``for_tokens``), so the two paths produce identical
    token streams for any UTF-8-clean corpus. (Residual edge: two distinct
    invalid-UTF-8 byte tokens can decode to the same replacement string here
    while remaining distinct in the byte-keyed native vocab.)
    """
    with open(path, "rb") as f:
        data = f.read(limit_bytes) if limit_bytes else f.read()
    return [t.decode("utf-8", "replace") for t in data.split()]


def encode_corpus(
    path: str,
    min_count: int = 5,
    max_vocab: Optional[int] = None,
    limit_bytes: Optional[int] = None,
    vocab: Optional[Vocab] = None,
    use_native: Optional[bool] = None,
) -> Tuple[np.ndarray, Vocab]:
    """Read, build (or reuse) a vocab, and encode to an int32 id stream.

    Prefers the C++ pipeline (tokenize + count + encode in one pass) when the
    toolchain is available and no byte limit / preexisting vocab forces the
    Python path; results are identical (tested).
    """
    from swiftsnails_tpu.data import native

    if use_native is None:
        use_native = vocab is None and limit_bytes is None and native.available()
    if use_native and vocab is None and limit_bytes is None:
        nv = native.NativeVocab(path, min_count=min_count, max_size=max_vocab or 0)
        ids = nv.encode_file(path)
        py_vocab = nv.to_python()
        nv.close()
        return ids, py_vocab
    tokens = read_tokens(path, limit_bytes=limit_bytes)
    if vocab is None:
        vocab = Vocab.build(tokens, min_count=min_count, max_size=max_vocab)
    ids = vocab.encode(tokens)
    return ids, vocab


_SPACE = b" \t\n\r\v\f"  # the native tokenizer's is_space set


def iter_encoded_chunks(
    path: str,
    vocab: Vocab,
    chunk_tokens: int,
    byte_start: int = 0,
    byte_end: int = 0,
    buf_size: int = 1 << 20,
) -> Iterator[np.ndarray]:
    """Stream the corpus as encoded int32 chunks of <= chunk_tokens ids.

    Bounded-memory ingestion (``scan_file_by_line`` parity,
    ``src/utils/file.h:11-33``): RSS is O(read buffer + chunk) regardless of
    file size; the token straddling a read-buffer edge is carried. A nonzero
    ``(byte_start, byte_end)`` span applies Hadoop split semantics — a token
    belongs to the span its FIRST byte falls in (the token straddling
    ``byte_start`` is the previous shard's; one starting before ``byte_end``
    is read to completion). Pure-Python twin of the native
    ``NativeVocab.encode_stream`` (identical id stream, tested).
    """
    index = vocab.index
    chunk: List[int] = []

    def emit(tok: bytes):
        i = index.get(tok.decode("utf-8", "replace"))
        if i is not None:
            chunk.append(i)

    with open(path, "rb") as f:
        skipping = False
        if byte_start > 0:
            f.seek(byte_start - 1)
            prev = f.read(1)
            skipping = bool(prev) and prev[0] not in _SPACE
        abs_base = byte_start
        carry = b""
        stop = False
        while not stop:
            block = f.read(buf_size)
            if not block:
                break
            pos, n = 0, len(block)
            while pos < n:
                if block[pos] in _SPACE:
                    skipping = False
                    if carry:
                        emit(carry)
                        carry = b""
                        if len(chunk) >= chunk_tokens:
                            yield np.asarray(chunk[:chunk_tokens], dtype=np.int32)
                            chunk = chunk[chunk_tokens:]
                    pos += 1
                    continue
                start = pos
                while pos < n and block[pos] not in _SPACE:
                    pos += 1
                if skipping:
                    continue  # discarding the pre-byte_start partial token
                if carry:
                    carry += block[start:pos]
                    if pos < n:
                        emit(carry)
                        carry = b""
                else:
                    if byte_end > 0 and abs_base + start >= byte_end:
                        stop = True
                        break
                    if pos < n:
                        emit(block[start:pos])
                    else:
                        carry = block[start:pos]
                if len(chunk) >= chunk_tokens:
                    yield np.asarray(chunk[:chunk_tokens], dtype=np.int32)
                    chunk = chunk[chunk_tokens:]
            abs_base += n
        if carry and not skipping:
            emit(carry)
    while chunk:
        yield np.asarray(chunk[:chunk_tokens], dtype=np.int32)
        chunk = chunk[chunk_tokens:]


def encode_corpus_stream(
    path: str,
    chunk_tokens: int,
    min_count: int = 5,
    max_vocab: Optional[int] = None,
    use_native: Optional[bool] = None,
    byte_start: int = 0,
    byte_end: int = 0,
) -> Tuple[Vocab, "object"]:
    """(vocab, chunk_factory) for bounded-memory training.

    The vocab build streams the WHOLE file once (O(vocab) memory — the vocab
    must be global so ids and row placement agree across hosts); the
    returned zero-arg factory opens a fresh encoded-chunk iterator over
    ``[byte_start, byte_end)`` (0,0 = whole file) — call it once per epoch.
    Global total tokens for lr-decay progress = ``vocab.counts.sum()``.
    """
    from swiftsnails_tpu.data import native

    if use_native is None:
        use_native = native.available()
    if use_native:
        nv = native.NativeVocab(path, min_count=min_count, max_size=max_vocab or 0)
        py_vocab = nv.to_python()

        def factory():
            return nv.encode_stream(path, chunk_tokens, byte_start, byte_end)

        return py_vocab, factory
    # Python fallback: one streaming pass to count, then stream-encode
    from collections import Counter

    counter: Counter = Counter()
    buf_size = 1 << 20
    carry = b""
    with open(path, "rb") as f:
        while True:
            block = f.read(buf_size)
            if not block:
                break
            block = carry + block
            if block[-1:].isspace():
                carry = b""
                parts = block.split()
            else:
                parts = block.split()
                carry = parts.pop() if parts else b""
            counter.update(t.decode("utf-8", "replace") for t in parts)
    if carry:
        counter.update([carry.decode("utf-8", "replace")])
    vocab = Vocab.from_counter(counter, min_count=min_count, max_size=max_vocab)

    def factory():
        return iter_encoded_chunks(path, vocab, chunk_tokens, byte_start, byte_end)

    return vocab, factory


def iter_line_records(path: str, process_index: int = 0, process_count: int = 1) -> Iterator[str]:
    """Line records, round-robin sharded by process.

    Replaces the reference's Hadoop-Streaming data split (each worker's stdin
    was its split: ``src/tools/run_worker.sh`` ``cat > ./data.txt``) with
    deterministic sharding by process index.
    """
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for i, line in enumerate(f):
            if i % process_count == process_index:
                yield line.rstrip("\n")
