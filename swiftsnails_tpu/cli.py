"""CLI entry points — the reference's role binaries, collapsed TPU-style.

Reference contract (survey §2.7): per-app ``master``/``server``/``worker``
binaries taking ``-config <file>`` (``src/tools/run_master.sh``) and workers
additionally ``-data <file>`` (``run_worker.sh``), launched by Hadoop
Streaming. On TPU the three roles dissolve into one SPMD ``train`` role: the
parameter table lives sharded across the same processes that compute
(survey §7 design stance), and rendezvous is the JAX coordination service.

Usage::

    python -m swiftsnails_tpu train  -config train.conf [-data corpus.txt]
    python -m swiftsnails_tpu export -config train.conf -checkpoint ROOT -out vec.txt
    python -m swiftsnails_tpu serve  -config train.conf -checkpoint ROOT   # query REPL
    python -m swiftsnails_tpu serve  ... -replicas 4   # replica fleet behind the router
    # in the serve REPL: `subscribe <dir>` follows the trainer's live
    # hot-row delta log (freshness pipeline, docs/FRESHNESS.md);
    # `subscribe tcp://HOST:PORT` streams it over a socket instead
    # (docs/NETWORK.md) — the trainer side sets `freshness_listen`
    python -m swiftsnails_tpu net-serve --root ROOT --listen HOST:PORT
    #   one replica process serving pull/topk/score/health over TCP
    #   (the multi-host fleet's unit; spawned by net.fleet.ReplicaSpawner)
    python -m swiftsnails_tpu models
    python -m swiftsnails_tpu trace-summary TRACE_OR_JSONL   # telemetry breakdown
    python -m swiftsnails_tpu ledger-report [LEDGER.jsonl]   # run-ledger history
    python -m swiftsnails_tpu ledger-report --check-regression 10   # bench gate
    python -m swiftsnails_tpu ledger-report --failures   # outage/chaos timeline
    python -m swiftsnails_tpu ledger-report --diff A B   # attribute a words/sec delta
    python -m swiftsnails_tpu supervisor-status [LEDGER.jsonl]   # membership view
    python -m swiftsnails_tpu ops [LEDGER.jsonl]   # one-screen fleet dashboard
    python -m swiftsnails_tpu worker -config ...   # alias of train (parity)

Resilience (docs/RESILIENCE.md): ``resume: auto`` continues an interrupted
run from the newest verified checkpoint (tables + data cursor); a real
SIGTERM drains with a final save and a ledger ``outage`` record instead of
dying mid-step; ``guardrail: 1`` arms the NaN/rollback step guardrail; the
fault-injection drills live in ``tools/chaos_drill.py`` and
``bench.py --lane chaos``.

``master`` / ``server`` are accepted for parity and explain the collapse.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from swiftsnails_tpu.utils.config import Config, ConfigError, global_config
from swiftsnails_tpu.utils.flags import parse_role_argv
from swiftsnails_tpu.utils.metrics import MetricsLogger


def _build_trainer(cfg: Config):
    from swiftsnails_tpu.models.registry import get_model
    from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh

    import jax

    model_name = cfg.get_str("model", "word2vec")
    trainer_cls = get_model(model_name)
    n = len(jax.devices())
    if cfg.get_bool("local_train", False) or n == 1:
        mesh = None  # reference local_train parity (SwiftWorker.h:114-123)
    else:
        model_axis = cfg.get_int("model_axis", 0)
        if model_axis <= 0:
            model_axis = next((c for c in (4, 2, 1) if n % c == 0 and n > c), 1)
        mesh = make_mesh({DATA_AXIS: n // model_axis, MODEL_AXIS: model_axis})
    return trainer_cls(cfg, mesh=mesh)


def cmd_train(argv: List[str]) -> int:
    from swiftsnails_tpu.framework.trainer import TrainLoop
    from swiftsnails_tpu.parallel.cluster import barrier, initialize_cluster

    cfg = parse_role_argv(argv)
    initialize_cluster(cfg)
    trainer = _build_trainer(cfg)
    metrics = MetricsLogger(path=cfg.get_str("metrics_path", "") or None, echo=True)
    loop = TrainLoop(trainer, metrics=metrics, log_every=cfg.get_int("log_every", 100))
    state = loop.run(seed=cfg.get_int("seed", 0))
    if loop.preempted:
        print(
            "preempted (SIGTERM): drained with a final checkpoint; "
            "restart with `resume: auto` to continue this run",
            file=sys.stderr,
        )
    barrier("end_of_training")  # MasterTerminate parity
    out = cfg.get_str("output", "")
    if out:
        trainer.export_text(state, out)
        print(f"exported parameters to {out}", file=sys.stderr)
    return 0


def cmd_export(argv: List[str]) -> int:
    from swiftsnails_tpu.framework.checkpoint import restore_checkpoint

    cfg = parse_role_argv(argv)
    trainer = _build_trainer(cfg)
    root = cfg.get_str("checkpoint")
    out = cfg.get_str("out")
    state = restore_checkpoint(root, trainer.init_state())
    trainer.export_text(state, out)
    print(f"exported {root} -> {out}", file=sys.stderr)
    return 0


def _serve_mesh(cfg: Config):
    """The serving twin of ``_build_trainer``'s mesh heuristic: query-only
    replicas shard the table the same way training did."""
    import jax

    from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh

    n = len(jax.devices())
    if cfg.get_bool("local_train", False) or n == 1:
        return None
    model_axis = cfg.get_int("model_axis", 0)
    if model_axis <= 0:
        model_axis = next((c for c in (4, 2, 1) if n % c == 0 and n > c), 1)
    return make_mesh({DATA_AXIS: n // model_axis, MODEL_AXIS: model_axis})


def cmd_serve(argv: List[str]) -> int:
    """Query-only REPL over a verified checkpoint (docs/SERVING.md).

    One request per stdin line, one JSON response per stdout line::

        pull <id> [id...]            row values
        topk <id> [k]                nearest rows to row <id> (cosine)
        score <f0> <f1> ...          CTR probability (registry models)
        stats                        latency/cache/shed snapshot
        health                       breaker / tier / version state
        ops                          one-screen dashboard (SLO / traces)
        add                          (fleet) add a replica to the ring
        drain <replica>              (fleet) drain + remove a replica
        subscribe <dir|tcp://h:p>    follow a hot-row delta log (freshness)
        freshness                    applied-seq watermark / lag / fallbacks
        quit

    ``-replicas N`` (or config ``serve_replicas``) > 1 serves through a
    :class:`~swiftsnails_tpu.serving.fleet.Fleet` — N replicas sharing the
    loaded planes behind the affinity/hedging router; the same REPL ops
    work (``Fleet`` mirrors the ``Servant`` query surface) plus elastic
    ``add``/``drain``, and ``health`` reports fleet-level liveness.

    ``subscribe <dir>`` attaches a background
    :class:`~swiftsnails_tpu.freshness.subscriber.DeltaSubscriber` polling
    the trainer's delta log (docs/FRESHNESS.md): hot-row batches apply
    behind the version-keyed cache with atomic cutover, and any gap /
    publisher restart / CRC mismatch falls back to a full
    ``reload_from_checkpoint`` of this checkpoint root. ``freshness``
    reports the applied-seq watermark, lag, and fallback count (also
    rolled into ``health``; fleets add per-replica versions).
    """
    import json

    from swiftsnails_tpu.serving import Fleet, Overloaded, Servant, Unavailable
    from swiftsnails_tpu.telemetry.ledger import Ledger

    cfg = parse_role_argv(argv)
    root = cfg.get_str("checkpoint")
    ledger_path = cfg.get_str("ledger_path", "")
    ledger = Ledger(ledger_path) if ledger_path else None
    replicas = cfg.get_int("replicas", cfg.get_int("serve_replicas", 1))
    fleet_mode = replicas > 1
    if fleet_mode:
        server_cm = Fleet.from_checkpoint(
            root, cfg, mesh=_serve_mesh(cfg), replicas=replicas,
            ledger=ledger)
    else:
        server_cm = Servant.from_checkpoint(
            root, cfg, mesh=_serve_mesh(cfg), ledger=ledger)
    subscriber = None
    delta_source = None
    with server_cm as servant:
        if fleet_mode:
            banner = (f"serving fleet of {replicas} replicas "
                      f"(one request per line; pull/topk/score/stats/"
                      "health/ops/add/drain/subscribe/freshness/quit)")
        else:
            banner = (f"serving step {servant.step} tables "
                      f"{servant.stats()['tables']} (one request per line; "
                      "pull/topk/score/stats/health/ops/subscribe/freshness/"
                      "quit)")
        print(banner, file=sys.stderr)
        for line in sys.stdin:
            toks = line.split()
            if not toks:
                continue
            op, args = toks[0], toks[1:]
            try:
                if op in ("quit", "exit"):
                    break
                elif op == "pull":
                    rows = servant.pull([int(a) for a in args])
                    out = {"rows": [[round(float(v), 6) for v in r]
                                    for r in rows]}
                elif op == "topk":
                    row = int(args[0])
                    k = int(args[1]) if len(args) > 1 else None
                    query = servant.pull([row])[0]
                    out = {"topk": servant.topk(query, k=k, exclude=(row,))}
                elif op == "score":
                    scores = servant.score([int(a) for a in args])
                    out = {"scores": [round(float(s), 6) for s in scores]}
                elif op == "stats":
                    out = servant.stats()
                elif op == "health":
                    out = servant.health()
                elif op == "ops":
                    from swiftsnails_tpu.telemetry.ops import render_ops

                    tracer = getattr(servant, "request_tracer", None)
                    anomalies = ([c.to_dict()
                                  for c in tracer.anomaly_traces(5)]
                                 if tracer is not None else None)
                    text = render_ops(servant.stats(),
                                      health=servant.health(),
                                      anomalies=anomalies)
                    print(text, file=sys.stderr)
                    out = {"ops": "printed"}
                elif op == "add" and fleet_mode:
                    out = {"added": servant.add_replica()}
                elif op == "drain" and fleet_mode:
                    out = {"drained": servant.drain(args[0])}
                elif op == "subscribe":
                    from swiftsnails_tpu.freshness.subscriber import (
                        DeltaSubscriber)

                    if subscriber is not None:
                        subscriber.stop()
                    if delta_source is not None:
                        delta_source.stop()
                        delta_source = None
                    target = args[0]
                    if target.startswith("tcp://"):
                        # socket-fed: the TCP source drives apply_batch;
                        # the subscriber never polls a local directory
                        # (docs/NETWORK.md) — base adoption, gap detection
                        # and the fallback ladder are unchanged
                        from swiftsnails_tpu.net.delta_stream import (
                            TcpDeltaSource)

                        host, _, port = target[len("tcp://"):].rpartition(":")
                        subscriber = DeltaSubscriber(
                            servant, cfg.get_str("freshness_dir", "")
                            or root + ".deltas", config=cfg,
                            checkpoint_root=root,
                            max_lag_ms=cfg.get_float(
                                "freshness_max_lag_ms", 0.0),
                            ledger=ledger)
                        delta_source = TcpDeltaSource(
                            subscriber, host, int(port), config=cfg,
                            ledger=ledger).start()
                        servant.attach_freshness(subscriber)
                        out = {"subscribed": target, "stream_open": True}
                    else:
                        subscriber = DeltaSubscriber(
                            servant, target, config=cfg,
                            checkpoint_root=root,
                            max_lag_ms=cfg.get_float(
                                "freshness_max_lag_ms", 0.0),
                            ledger=ledger)
                        found = subscriber.subscribe()
                        subscriber.start()
                        servant.attach_freshness(subscriber)
                        out = {"subscribed": target, "stream_open": found}
                elif op == "freshness":
                    if subscriber is None:
                        out = {"error": "not subscribed (use: subscribe "
                               "<dir> or subscribe tcp://HOST:PORT)"}
                    else:
                        out = subscriber.status()
                        if delta_source is not None:
                            out["source"] = delta_source.status()
                else:
                    out = {"error": f"unknown op {op!r}"}
            except Overloaded as e:
                out = {"error": f"overloaded: {e}", "shed": True}
            except Unavailable as e:
                out = {"error": f"unavailable: {e}", "shed": True}
            except Exception as e:  # noqa: BLE001 — a REPL must not die
                out = {"error": f"{type(e).__name__}: {e}"}
            print(json.dumps(out), flush=True)
        if delta_source is not None:
            delta_source.stop()
        if subscriber is not None:
            subscriber.stop()
        print(json.dumps({"final_stats": servant.stats()}), flush=True)
    return 0


def cmd_models(argv: List[str]) -> int:
    from swiftsnails_tpu.models.registry import available_models

    for name in available_models():
        print(name)
    return 0


def cmd_trace_summary(argv: List[str]) -> int:
    from swiftsnails_tpu.telemetry.summary import main as summary_main

    return summary_main(argv)


def cmd_ledger_report(argv: List[str]) -> int:
    from swiftsnails_tpu.telemetry.ledger import main as ledger_main

    return ledger_main(argv)


def cmd_ops(argv: List[str]) -> int:
    """One-screen fleet dashboard from the run ledger (docs/OBSERVABILITY.md):
    newest fleet/freshness bench blocks, SLO error budget from ``slo_burn``
    events, and the recent ``trace_anomaly`` tail with drillable trace ids."""
    from swiftsnails_tpu.telemetry.ops import main as ops_main

    return ops_main(argv)


def cmd_net_serve(argv: List[str]) -> int:
    """One replica process serving a checkpoint over TCP (docs/NETWORK.md):
    pull/topk/score/health RPCs behind the SSD1 frame codec, spawnable by
    hand here or by ``net.fleet.ReplicaSpawner``; prints one JSON ready
    line (``{"port": ..., "incarnation": ...}``) and serves until killed."""
    from swiftsnails_tpu.net.replica_server import main as replica_main

    return replica_main(argv)


def cmd_supervisor_status(argv: List[str]) -> int:
    """Replay a run ledger's membership events into the supervisor's view:
    per-worker state (alive/lost, joins, straggler flags, where reassigned
    ranges went) plus the newest exactly-once accounting verdict."""
    import os

    from swiftsnails_tpu.cluster.status import render_supervisor_status
    from swiftsnails_tpu.telemetry.ledger import DEFAULT_LEDGER, Ledger

    path = argv[0] if argv else os.environ.get("SSN_LEDGER_PATH",
                                               DEFAULT_LEDGER)
    ledger = Ledger(path)
    if not os.path.exists(ledger.path):
        print(f"supervisor-status: no ledger at {ledger.path}",
              file=sys.stderr)
        return 1
    print(render_supervisor_status(ledger))
    return 0


_ROLE_NOTE = (
    "swiftsnails_tpu has no separate {role} role: the parameter table lives\n"
    "sharded across the same TPU processes that train. Run\n"
    "  python -m swiftsnails_tpu train -config <file>\n"
    "on every host (jax.distributed handles rendezvous via master_addr)."
)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # An explicit JAX_PLATFORMS must win even when a site plugin re-pins the
    # platform after env processing (e.g. the axon TPU plugin's
    # sitecustomize) — otherwise CPU-only runs try to grab the accelerator.
    from swiftsnails_tpu.utils.platform_pin import repin_from_env

    repin_from_env()
    if not argv or argv[0] in ("-h", "--help", "help"):
        print(__doc__)
        return 0
    cmd, rest = argv[0], argv[1:]
    try:
        if cmd in ("train", "worker"):
            return cmd_train(rest)
        if cmd == "export":
            return cmd_export(rest)
        if cmd == "serve":
            return cmd_serve(rest)
        if cmd == "models":
            return cmd_models(rest)
        if cmd == "trace-summary":
            return cmd_trace_summary(rest)
        if cmd == "ledger-report":
            return cmd_ledger_report(rest)
        if cmd == "supervisor-status":
            return cmd_supervisor_status(rest)
        if cmd == "ops":
            return cmd_ops(rest)
        if cmd == "net-serve":
            return cmd_net_serve(rest)
        if cmd in ("master", "server"):
            print(_ROLE_NOTE.format(role=cmd), file=sys.stderr)
            return 0
        print(
            f"unknown command {cmd!r}; try: train, export, serve, models, "
            "trace-summary, ledger-report, supervisor-status, ops, "
            "net-serve",
            file=sys.stderr,
        )
        return 2
    except ConfigError as e:
        print(f"config error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
