"""swiftsnails_tpu — a TPU-native distributed sparse-training framework.

A ground-up re-design of the capabilities of SwiftSnails (a C++11 ZeroMQ
parameter server: master/server/worker roles, hash-sharded sparse parameter
table, async pull/push SGD) for TPUs:

* the sharded KV parameter table (reference ``src/core/parameter/sparsetable.h``)
  becomes a pjit-sharded dense ``jax.Array`` with hashed-row placement
  (:mod:`swiftsnails_tpu.parallel.store`);
* the ZeroMQ Transfer/Route/Listener RPC stack (reference
  ``src/core/transfer/transfer.h``) becomes XLA collectives over ICI/DCN inside
  a jit'd step (:mod:`swiftsnails_tpu.parallel`);
* master rendezvous / cluster lifecycle (reference ``src/core/system/``)
  becomes ``jax.distributed`` + the coordination service
  (:mod:`swiftsnails_tpu.parallel.cluster`, multi-host runtime);
* pluggable trainers (reference ``BaseAlgorithm``,
  ``src/core/framework/SwiftWorker.h:19-57``) become
  :class:`swiftsnails_tpu.framework.trainer.Trainer` subclasses
  (:mod:`swiftsnails_tpu.models`);
* pluggable update rules (reference ``Pull/PushAccessMethod``,
  ``src/core/parameter/sparse_access_method.h:10-48``) become
  :class:`swiftsnails_tpu.parallel.access.AccessMethod` optimizer defs.
"""

__version__ = "0.1.0"

# Partitionable threefry is sharding-invariant by construction: the legacy
# lowering lets XLA specialize random-bit computation to the output sharding,
# so jax.random under jit with sharded operands/outputs (e.g. the grouped
# mesh plane's negative-pool sampling inside a donated-state train_step, or
# table init under out_shardings) produces DIFFERENT values per mesh layout.
# Training must not depend on mesh shape; flip the default library-wide.
# An explicit JAX_THREEFRY_PARTITIONABLE=0 env still wins (user override).
import os as _os

if "JAX_THREEFRY_PARTITIONABLE" not in _os.environ:
    import jax as _jax

    _jax.config.update("jax_threefry_partitionable", True)

from swiftsnails_tpu.utils.config import Config, global_config, load_config

__all__ = [
    "Config",
    "global_config",
    "load_config",
    "__version__",
]
