"""swiftsnails_tpu — a TPU-native distributed sparse-training framework.

A ground-up re-design of the capabilities of SwiftSnails (a C++11 ZeroMQ
parameter server: master/server/worker roles, hash-sharded sparse parameter
table, async pull/push SGD) for TPUs:

* the sharded KV parameter table (reference ``src/core/parameter/sparsetable.h``)
  becomes a pjit-sharded dense ``jax.Array`` with hashed-row placement
  (:mod:`swiftsnails_tpu.parallel.store`);
* the ZeroMQ Transfer/Route/Listener RPC stack (reference
  ``src/core/transfer/transfer.h``) becomes XLA collectives over ICI/DCN inside
  a jit'd step (:mod:`swiftsnails_tpu.parallel`);
* master rendezvous / cluster lifecycle (reference ``src/core/system/``)
  becomes ``jax.distributed`` + the coordination service
  (:mod:`swiftsnails_tpu.parallel.cluster`, multi-host runtime);
* pluggable trainers (reference ``BaseAlgorithm``,
  ``src/core/framework/SwiftWorker.h:19-57``) become
  :class:`swiftsnails_tpu.framework.trainer.Trainer` subclasses
  (:mod:`swiftsnails_tpu.models`);
* pluggable update rules (reference ``Pull/PushAccessMethod``,
  ``src/core/parameter/sparse_access_method.h:10-48``) become
  :class:`swiftsnails_tpu.parallel.access.AccessMethod` optimizer defs.
"""

__version__ = "0.1.0"

from swiftsnails_tpu.utils.config import Config, global_config, load_config

__all__ = [
    "Config",
    "global_config",
    "load_config",
    "__version__",
]
