"""Length-prefixed stream frames over the SSD1 codec (docs/NETWORK.md).

One frame, the same byte discipline as a delta batch on disk
(``freshness/log.py`` — one codec for file and wire)::

    b"SSD1" | uint32 header_len | header JSON | payload | uint32 CRC32

The CRC covers header JSON + payload. The only stream-specific addition is
``header["payload_len"]`` — a file's payload length is implied by file
size; a stream must be told it up front so a reader can budget the read
*before* touching the payload.

Hardening contract (drilled by ``tests/test_net_wire.py``):

* oversize length prefixes are rejected BEFORE any allocation — a hostile
  or corrupt 4-byte prefix can never balloon memory;
* truncation anywhere (header, payload, CRC) raises a typed
  :class:`FrameTruncated`, never hangs and never returns a partial frame;
* a CRC mismatch or bad magic raises :class:`FrameError`;
* :func:`read_frame` consumes a ``recv(n)``-shaped callable and loops over
  arbitrary partial reads, so frames survive any ``recv`` boundary.

Typed arrays ride in the payload via :func:`pack_arrays` /
:func:`unpack_arrays` — the header carries an index of dtype/shape/offset
entries, bounds-checked against ``payload_len`` before slicing.
"""

from __future__ import annotations

import json
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from swiftsnails_tpu.freshness.log import MAGIC

# Pre-allocation caps: a length prefix beyond these is rejected before any
# buffer is sized from it. Generous enough for a full delta batch or a
# batched pull reply; far below anything that could hurt a host.
MAX_HEADER_BYTES = 1 << 20  # 1 MiB of header JSON
MAX_PAYLOAD_BYTES = 1 << 28  # 256 MiB of payload

_PREFIX_LEN = len(MAGIC) + 4  # magic + uint32 header_len
_CRC_LEN = 4


class FrameError(Exception):
    """A frame failed its magic/length/CRC/shape check (typed; the server
    loop and the reconnecting client both survive it)."""


class FrameTruncated(FrameError):
    """The stream ended (or the blob ran out) mid-frame."""


class FrameTooLarge(FrameError):
    """A length prefix exceeded the pre-allocation cap — rejected before
    any buffer was sized from it."""


def encode_frame(header: Dict, payload: bytes = b"") -> bytes:
    """One wire frame. ``header`` is JSON-serializable; ``payload_len`` is
    stamped in automatically (the stream reader's read budget)."""
    hdr = dict(header)
    hdr["payload_len"] = len(payload)
    hjson = json.dumps(hdr).encode("utf-8")
    if len(hjson) > MAX_HEADER_BYTES:
        raise FrameTooLarge(
            f"header JSON {len(hjson)} bytes exceeds cap {MAX_HEADER_BYTES}")
    if len(payload) > MAX_PAYLOAD_BYTES:
        raise FrameTooLarge(
            f"payload {len(payload)} bytes exceeds cap {MAX_PAYLOAD_BYTES}")
    crc = zlib.crc32(hjson + payload) & 0xFFFFFFFF
    return (MAGIC + np.uint32(len(hjson)).tobytes() + hjson + payload
            + np.uint32(crc).tobytes())


def decode_frame(blob: bytes) -> Tuple[Dict, bytes]:
    """Decode one complete frame blob -> ``(header, payload)``."""

    view = memoryview(blob)
    pos = [0]

    def _take(n: int) -> bytes:
        chunk = bytes(view[pos[0]: pos[0] + n])
        pos[0] += len(chunk)
        return chunk

    return read_frame(_take)


def read_frame(
    recv: Callable[[int], bytes],
    *,
    max_header: int = MAX_HEADER_BYTES,
    max_payload: int = MAX_PAYLOAD_BYTES,
) -> Tuple[Dict, bytes]:
    """Incrementally read one frame from ``recv(n)`` (returns <= n bytes;
    empty = EOF) -> ``(header, payload)``.

    Reads exactly one frame's bytes and no more. Every length is validated
    against its cap before the corresponding buffer is read, and every
    partial-read boundary is handled by looping — a frame split into 1-byte
    chunks decodes identically to one arriving whole.
    """
    prefix = _read_exact(recv, _PREFIX_LEN, "frame prefix")
    if prefix[: len(MAGIC)] != MAGIC:
        raise FrameError(f"bad magic {prefix[:len(MAGIC)]!r}")
    hlen = int(np.frombuffer(prefix[len(MAGIC):], np.uint32)[0])
    if hlen > max_header:
        raise FrameTooLarge(
            f"header length prefix {hlen} exceeds cap {max_header}")
    hjson = _read_exact(recv, hlen, "frame header")
    try:
        header = json.loads(hjson.decode("utf-8"))
    except ValueError as e:
        raise FrameError(f"unparseable frame header: {e}") from e
    if not isinstance(header, dict):
        raise FrameError(f"frame header is {type(header).__name__}, not dict")
    try:
        plen = int(header["payload_len"])
    except (KeyError, TypeError, ValueError) as e:
        raise FrameError(f"frame header missing payload_len: {e}") from e
    if plen < 0 or plen > max_payload:
        raise FrameTooLarge(
            f"payload length {plen} outside [0, {max_payload}]")
    payload = _read_exact(recv, plen, "frame payload")
    stored = int(np.frombuffer(
        _read_exact(recv, _CRC_LEN, "frame CRC"), np.uint32)[0])
    if (zlib.crc32(hjson + payload) & 0xFFFFFFFF) != stored:
        raise FrameError("frame CRC mismatch")
    return header, payload


def _read_exact(recv: Callable[[int], bytes], n: int, what: str) -> bytes:
    """Loop ``recv`` until exactly ``n`` bytes arrive; :class:`FrameTruncated`
    on EOF mid-read. The chunks list keeps per-read allocation bounded by
    what the peer actually sent."""
    if n == 0:
        return b""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = recv(n - got)
        if not chunk:
            raise FrameTruncated(f"{what}: stream ended {got}/{n} bytes in")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def sock_recv(sock) -> Callable[[int], bytes]:
    """Adapt a socket to :func:`read_frame`'s ``recv(n)`` shape. Socket
    timeouts surface as ``socket.timeout`` (an ``OSError`` — the retry
    policy's native food); a closed peer surfaces as EOF."""

    def _recv(n: int) -> bytes:
        return sock.recv(min(n, 1 << 16))

    return _recv


# -- typed arrays in the payload ---------------------------------------------


def pack_arrays(
    arrays: Dict[str, np.ndarray],
) -> Tuple[List[Dict], bytes]:
    """``{name: ndarray}`` -> (header index, payload bytes). Order is
    name-sorted so identical inputs produce identical bytes."""
    index: List[Dict] = []
    chunks: List[bytes] = []
    off = 0
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        index.append({
            "name": name,
            "dtype": a.dtype.str,
            "shape": list(a.shape),
            "offset": off,
        })
        chunks.append(a.tobytes())
        off += a.nbytes
    return index, b"".join(chunks)


def unpack_arrays(index: List[Dict], payload: bytes) -> Dict[str, np.ndarray]:
    """Invert :func:`pack_arrays`; every slice is bounds-checked against the
    payload before :func:`np.frombuffer` touches it."""
    out: Dict[str, np.ndarray] = {}
    for entry in index or []:
        try:
            name = entry["name"]
            dt = np.dtype(entry["dtype"])
            shape = tuple(int(s) for s in entry["shape"])
            off = int(entry["offset"])
        except (KeyError, TypeError, ValueError) as e:
            raise FrameError(f"bad array index entry {entry!r}: {e}") from e
        count = 1
        for s in shape:
            if s < 0:
                raise FrameError(f"{name}: negative dim in shape {shape}")
            count *= s
        nbytes = count * dt.itemsize
        if off < 0 or off + nbytes > len(payload):
            raise FrameError(
                f"{name}: claims [{off}, {off + nbytes}) of a "
                f"{len(payload)}-byte payload")
        out[name] = np.frombuffer(
            payload, dt, count=count, offset=off).reshape(shape)
    return out
