"""RemoteServant: an out-of-process replica behind the in-process surface.

The fleet router, breaker demotion, hedging, drain, and freshness cutover
all talk to ``rep.servant`` (``serving/fleet.py``); this class implements
exactly that surface over :class:`~swiftsnails_tpu.net.rpc.RpcClient`, so
a remote replica rides the ring with ZERO router changes:

* kernel ops (``pull``/``topk``/``score``) are RPCs under the retry
  policy; a transport failure (connection lost, partition, exhausted
  budget) raises :class:`~swiftsnails_tpu.serving.breaker.Unavailable` —
  the router's native re-route/hedge food — so a dead replica costs
  affinity, not availability;
* hot-path introspection (``queue_depths()``, ``breakers.get(k).state``)
  is served from a locally cached snapshot — the router reads these on
  EVERY routing decision, and a routing decision must never block on the
  network. The snapshot refreshes on each :meth:`health` poll (the
  liveness loop's heartbeat probe); while the transport is down the
  breakers read OPEN, which is precisely the demotion the router wants;
* ``apply_rows`` carries the fleet's shared epoch; the server refuses
  epochs at/below its own (``StaleEpoch``) — a healed partition cannot
  accept a stale write (``tier_budget_mb = 1`` keeps the fleet on the
  per-replica apply path, matching tiered replicas that own their
  masters).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from swiftsnails_tpu.net.rpc import (
    CONNECTED,
    RpcClient,
    RpcRemoteError,
    net_retry_policy,
)
from swiftsnails_tpu.net.wire import pack_arrays, unpack_arrays
from swiftsnails_tpu.resilience.retry import RetryExhausted
from swiftsnails_tpu.serving.breaker import OPEN, Unavailable
from swiftsnails_tpu.serving.engine import Overloaded


class StaleEpoch(RuntimeError):
    """A write carried a cache epoch at/below the replica's current one —
    refused (first-writer-wins: a healed partition must resync, not
    regress)."""


class _RemoteBreaker:
    """The router only reads ``.state``; this mirrors the server's breaker
    when connected and reads OPEN while the transport is down."""

    __slots__ = ("_servant", "_kernel")

    def __init__(self, servant: "RemoteServant", kernel: str):
        self._servant = servant
        self._kernel = kernel

    @property
    def state(self) -> str:
        return self._servant._breaker_state(self._kernel)


class _RemoteBreakers:
    def __init__(self, servant: "RemoteServant"):
        self._servant = servant
        self._cache: Dict[str, _RemoteBreaker] = {}

    def get(self, kernel: str) -> _RemoteBreaker:
        br = self._cache.get(kernel)
        if br is None:
            br = self._cache[kernel] = _RemoteBreaker(self._servant, kernel)
        return br

    def items(self):
        for k in ("pull", "topk", "score"):
            yield k, self.get(k)


class RemoteServant:
    """The client half of a :mod:`~swiftsnails_tpu.net.replica_server`."""

    # truthy -> Fleet.apply_rows takes the per-replica path (remote
    # replicas own their planes exactly like tiered replicas do)
    tier_budget_mb = 1

    def __init__(
        self,
        host: str,
        port: int,
        *,
        config=None,
        ledger=None,
        replica: Optional[str] = None,
        connect_timeout_ms: Optional[float] = None,
        read_timeout_ms: Optional[float] = None,
    ):
        if config is not None:
            connect_timeout_ms = connect_timeout_ms if connect_timeout_ms \
                is not None else config.get_float(
                    "net_connect_timeout_ms", 1_000.0)
            read_timeout_ms = read_timeout_ms if read_timeout_ms \
                is not None else config.get_float(
                    "net_read_timeout_ms", 2_000.0)
        # kernel ops re-route fast: two tries against one peer, then let
        # the router take the request elsewhere — the retry policy's job
        # here is the reconnect jitter, not heroics against a dead host
        policy = net_retry_policy(
            config, ledger=ledger, max_attempts=2,
            deadline_ms=2.5 * (read_timeout_ms or 2_000.0))
        self.client = RpcClient(
            host, port, policy=policy,
            connect_timeout_ms=connect_timeout_ms or 1_000.0,
            read_timeout_ms=read_timeout_ms or 2_000.0,
            ledger=ledger, replica=replica)
        self.ledger = ledger
        self.replica = replica
        self.incarnation: Optional[str] = None
        self._version = 0
        self._step = 0
        self._queue_depths: Dict[str, int] = {}
        self._breakers_snapshot: Dict[str, str] = {}
        self._last_health: Dict = {}
        self.breakers = _RemoteBreakers(self)
        self.request_tracer = None  # fleet-level tracing owns the spans

    # -- cached introspection (hot path: NEVER an RPC) -----------------------

    @property
    def transport(self) -> str:
        return self.client.transport_state

    @property
    def version(self) -> int:
        return self._version

    @property
    def step(self) -> int:
        return self._step

    def queue_depths(self) -> Dict[str, int]:
        return dict(self._queue_depths)

    def _breaker_state(self, kernel: str) -> str:
        if self.client.transport_state != CONNECTED:
            return OPEN
        return self._breakers_snapshot.get(kernel, "closed")

    # -- kernel RPCs ---------------------------------------------------------

    def pull(self, ids, table: Optional[str] = None) -> np.ndarray:
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).reshape(-1))
        index, payload = pack_arrays({"ids": ids})
        hdr, data = self._call("pull", {"table": table, "arrays": index},
                               payload)
        return unpack_arrays(hdr["arrays"], data)["rows"]

    def topk(self, query, k: Optional[int] = None,
             table: Optional[str] = None, exclude: Sequence[int] = (),
             normalize: bool = True) -> List[Tuple[int, float]]:
        q = np.ascontiguousarray(np.asarray(query, np.float32).reshape(-1))
        index, payload = pack_arrays({"query": q})
        hdr, _ = self._call("topk", {
            "k": k, "table": table, "exclude": [int(i) for i in exclude],
            "normalize": bool(normalize), "arrays": index,
        }, payload)
        return [(int(i), float(s)) for i, s in hdr["topk"]]

    def score(self, feats) -> np.ndarray:
        feats = np.ascontiguousarray(np.asarray(feats, np.int32))
        index, payload = pack_arrays({"feats": feats})
        hdr, data = self._call("score", {"arrays": index}, payload)
        return unpack_arrays(hdr["arrays"], data)["scores"]

    # -- control RPCs --------------------------------------------------------

    def health(self, read_timeout_ms: Optional[float] = None) -> Dict:
        """Liveness probe + snapshot refresh. Transport failure returns
        ``status: "unreachable"`` instead of raising — the liveness loop
        (and the fleet health rollup) needs the answer, not the traceback."""
        try:
            hdr, _ = self._call("health", {},
                                read_timeout_ms=read_timeout_ms)
        except (Unavailable, Overloaded):
            return {"status": "unreachable",
                    "transport": self.client.transport_state,
                    "peer": self.client.peer}
        h = hdr["health"]
        self._adopt_snapshot(hdr)
        h["transport"] = self.client.transport_state
        h["incarnation"] = self.incarnation
        self._last_health = h
        return h

    def stats(self) -> Dict:
        try:
            hdr, _ = self._call("stats", {})
        except (Unavailable, Overloaded):
            return {"kernels": {}, "cache": {"hit_rate": 0.0},
                    "breakers": {}, "tables": [],
                    "transport": self.client.transport_state,
                    "peer": self.client.peer}
        self._adopt_snapshot(hdr)
        st = hdr["stats"]
        st["transport"] = self.client.transport_state
        st["peer"] = self.client.peer
        return st

    def apply_rows(self, updates: Dict, *, version: Optional[int] = None,
                   step: Optional[int] = None) -> int:
        """Apply absolute row values at the fleet's shared epoch. The
        server refuses stale epochs typed (:class:`StaleEpoch`)."""
        arrays: Dict[str, np.ndarray] = {}
        tables_meta = {}
        for name, t in updates.items():
            if isinstance(t, dict):
                rows, values = t["rows"], t["values"]
                scales = t.get("scales")
            else:
                rows, values = t
                scales = None
            arrays[f"{name}/rows"] = np.asarray(rows, np.int64).reshape(-1)
            arrays[f"{name}/values"] = np.asarray(values)
            tables_meta[name] = {"scales": scales is not None}
            if scales is not None:
                arrays[f"{name}/scales"] = np.asarray(scales, np.float32)
        index, payload = pack_arrays(arrays)
        hdr, _ = self._call("apply_rows", {
            "version": version, "step": step,
            "tables": tables_meta, "arrays": index,
        }, payload)
        self._version = int(hdr.get("version", self._version))
        if step is not None:
            self._step = max(self._step, int(step))
        return self._version

    def reload_checkpoint(self, root: str, *, step: Optional[int] = None,
                          version: Optional[int] = None) -> int:
        """Ask the replica process to reload from its checkpoint root at
        the fleet's shared epoch (the wire ships a path, not the planes)."""
        hdr, _ = self._call("reload_checkpoint", {
            "root": root, "step": step, "version": version,
        })
        self._version = int(hdr.get("version", self._version))
        self._step = int(hdr.get("step", self._step))
        return self._version

    def chaos(self, *, slow_ms: Optional[float] = None,
              partition_ms: Optional[float] = None) -> Dict:
        """Drill control: arm ``net_slow`` / ``net_partition`` on the
        server (out-of-band of the data ops)."""
        req = {}
        if slow_ms is not None:
            req["slow_ms"] = float(slow_ms)
        if partition_ms is not None:
            req["partition_ms"] = float(partition_ms)
        hdr, _ = self._call("chaos", req)
        return hdr

    def close(self) -> None:
        self.client.close()

    # -- plumbing ------------------------------------------------------------

    def _adopt_snapshot(self, hdr: Dict) -> None:
        snap = hdr.get("snapshot") or {}
        self._version = int(snap.get("version", self._version))
        self._step = int(snap.get("step", self._step))
        self._queue_depths = {
            k: int(v) for k, v in (snap.get("queue_depths") or {}).items()}
        self._breakers_snapshot = {
            k: str(v) for k, v in (snap.get("breakers") or {}).items()}
        inc = snap.get("incarnation")
        if inc is not None:
            self.incarnation = str(inc)

    def _call(self, op: str, header: Dict, payload: bytes = b"",
              read_timeout_ms: Optional[float] = None) -> Tuple[Dict, bytes]:
        try:
            return self.client.call(op, header, payload,
                                    read_timeout_ms=read_timeout_ms)
        except RpcRemoteError as e:
            raise _map_remote_error(e) from e
        except (RetryExhausted, OSError) as e:
            raise Unavailable(
                f"replica {self.replica or self.client.peer} unreachable "
                f"({type(e).__name__})") from e

    def __repr__(self) -> str:
        return (f"RemoteServant({self.client.peer}, "
                f"transport={self.client.transport_state}, "
                f"incarnation={self.incarnation})")


def _map_remote_error(e: RpcRemoteError) -> Exception:
    """Known remote exception types come back as their local classes, so
    the router's shed/re-route logic treats a remote replica exactly like
    an in-process one."""
    if e.kind == "Overloaded":
        return Overloaded(e.message)
    if e.kind == "Unavailable":
        return Unavailable(e.message)
    if e.kind == "StaleEpoch":
        return StaleEpoch(e.message)
    return RuntimeError(f"remote {e.kind}: {e.message}")


def jsonable(obj):
    """Best-effort JSON sanitizer for health/stats dicts crossing the wire
    (numpy scalars -> Python scalars)."""
    return json.loads(json.dumps(obj, default=_np_default))


def _np_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)
