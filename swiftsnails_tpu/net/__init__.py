"""Multi-host wire: TCP transport under the serving + freshness plane.

The reference's entire identity is sockets — a ZeroMQ Master/Server/Worker
cluster exchanging framed binary meta+payload messages — and this package
puts that wire back under the roles we rebuilt in-process (docs/NETWORK.md):

* :mod:`~swiftsnails_tpu.net.wire` — length-prefixed stream frames reusing
  the SSD1 magic + CRC32 discipline from ``freshness/log.py`` (one codec,
  already fuzz-hardened), with oversize prefixes rejected *before*
  allocation and typed :class:`~swiftsnails_tpu.net.wire.FrameError`\\ s;
* :mod:`~swiftsnails_tpu.net.rpc` — a threaded RPC server + reconnecting
  client; every connect/read/write runs under a
  :class:`~swiftsnails_tpu.resilience.retry.RetryPolicy` deadline with
  decorrelated-jitter reconnect, never a bare ``recv``;
* :mod:`~swiftsnails_tpu.net.replica_server` — a spawnable process wrapping
  a :class:`~swiftsnails_tpu.serving.engine.Servant` behind pull/topk/
  score/health RPCs, with a fresh incarnation id per process;
* :mod:`~swiftsnails_tpu.net.remote` — :class:`RemoteServant`, the client
  that plugs into ``serving/fleet.py`` behind the exact same router/
  breaker/hedge interfaces as an in-process replica;
* :mod:`~swiftsnails_tpu.net.fleet` — :class:`NetFleet` (remote replicas on
  the consistent-hash ring) + :class:`ReplicaManager` (supervisor-lease
  liveness: heartbeat-renewed, expiry → ring drain → membership event →
  respawn/rejoin with a fresh incarnation; autoscale hook);
* :mod:`~swiftsnails_tpu.net.delta_stream` — freshness delta subscription
  over TCP: a stream source replaces the file poll in front of
  ``DeltaSubscriber.apply_batch`` with the same seq/gap/fallback semantics.

Drilled by ``bench.py --lane net`` and ``tools/chaos_drill.py --net`` with
the process-level chaos kinds ``proc_kill`` / ``net_partition`` /
``net_slow``; gated in ``ledger-report --check-regression``.
"""

from swiftsnails_tpu.net.wire import (  # noqa: F401
    FrameError,
    FrameTooLarge,
    FrameTruncated,
    decode_frame,
    encode_frame,
    read_frame,
)
