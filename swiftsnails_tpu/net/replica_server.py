"""A replica process: one Servant behind pull/topk/score/health RPCs.

The reference's ``server`` role binary reborn (survey §2.7) — spawnable as::

    python -m swiftsnails_tpu.net.replica_server \\
        --root CKPT_ROOT --listen 127.0.0.1:0 --config dim=16 ...

On startup it loads the checkpoint, binds (port 0 = ephemeral), and prints
ONE JSON ready line to stdout — ``{"port": ..., "incarnation": ...}`` —
which is how the spawner (``net/fleet.py``) learns the address. A fresh
``incarnation`` id is minted per process start (the same uuid discipline
as a delta publisher's id in ``freshness/log.py``): a respawned replica
rejoining the ring is distinguishable from the one that died.

Every reply to a ``health``/``stats``/write op carries a ``snapshot``
(version / step / queue depths / breaker states / incarnation) that the
client caches for the router's hot-path introspection.

Write ops carry the fleet's shared cache epoch; an epoch at/below the
replica's current version is refused with a typed ``StaleEpoch`` — the
heal-side guarantee that a partitioned replica cannot accept a stale
write (it must resync via ``reload_checkpoint`` instead).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import uuid
from typing import Dict, Optional, Tuple

import numpy as np

from swiftsnails_tpu.net.remote import StaleEpoch, jsonable
from swiftsnails_tpu.net.rpc import RpcServer
from swiftsnails_tpu.net.wire import pack_arrays, unpack_arrays


class ServantRpcServer:
    """Wrap a live Servant in an :class:`RpcServer` (the process entry
    below uses this; tests wrap an in-process Servant the same way)."""

    def __init__(self, servant, *, host: str = "127.0.0.1", port: int = 0,
                 config=None, checkpoint_root: Optional[str] = None,
                 ledger=None):
        self.servant = servant
        self.config = config
        self.checkpoint_root = checkpoint_root
        self.incarnation = uuid.uuid4().hex[:12]
        self._write_lock = threading.Lock()
        self.server = RpcServer({
            "pull": self._pull,
            "topk": self._topk,
            "score": self._score,
            "health": self._health,
            "stats": self._stats,
            "apply_rows": self._apply_rows,
            "reload_checkpoint": self._reload_checkpoint,
            "ping": self._ping,
        }, host=host, port=port, ledger=ledger, name="replica")

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def start(self) -> "ServantRpcServer":
        self.server.start()
        return self

    def stop(self) -> None:
        self.server.stop()

    # -- snapshot ------------------------------------------------------------

    def _snapshot(self) -> Dict:
        s = self.servant
        return {
            "version": int(s.version),
            "step": int(s.step),
            "queue_depths": {k: int(v) for k, v in s.queue_depths().items()},
            "breakers": {k: br.state for k, br in s.breakers.items()},
            "incarnation": self.incarnation,
        }

    # -- handlers ------------------------------------------------------------

    def _pull(self, header: Dict, payload: bytes) -> Tuple[Dict, bytes]:
        ids = unpack_arrays(header.get("arrays"), payload)["ids"]
        rows = np.asarray(self.servant.pull(ids, table=header.get("table")))
        index, out = pack_arrays({"rows": rows})
        return {"arrays": index}, out

    def _topk(self, header: Dict, payload: bytes) -> Tuple[Dict, bytes]:
        q = unpack_arrays(header.get("arrays"), payload)["query"]
        hits = self.servant.topk(
            q, k=header.get("k"), table=header.get("table"),
            exclude=tuple(header.get("exclude") or ()),
            normalize=bool(header.get("normalize", True)))
        return {"topk": [[int(i), float(s)] for i, s in hits]}, b""

    def _score(self, header: Dict, payload: bytes) -> Tuple[Dict, bytes]:
        feats = unpack_arrays(header.get("arrays"), payload)["feats"]
        scores = np.asarray(self.servant.score(feats), np.float32)
        index, out = pack_arrays({"scores": scores})
        return {"arrays": index}, out

    def _health(self, header: Dict, payload: bytes) -> Tuple[Dict, bytes]:
        return {"health": jsonable(self.servant.health()),
                "snapshot": self._snapshot()}, b""

    def _stats(self, header: Dict, payload: bytes) -> Tuple[Dict, bytes]:
        return {"stats": jsonable(self.servant.stats()),
                "snapshot": self._snapshot()}, b""

    def _ping(self, header: Dict, payload: bytes) -> Tuple[Dict, bytes]:
        return {"snapshot": self._snapshot()}, b""

    def _apply_rows(self, header: Dict, payload: bytes) -> Tuple[Dict, bytes]:
        version = header.get("version")
        arrays = unpack_arrays(header.get("arrays"), payload)
        updates: Dict[str, Tuple] = {}
        for name, meta in (header.get("tables") or {}).items():
            values = arrays[f"{name}/values"]
            if meta.get("scales"):
                # int8-quantized rows cross the wire raw; dequantize with
                # the delta log's own codec before the scatter
                from swiftsnails_tpu.tiered.store import (
                    _np_dequant_unit_rows,
                )

                values = _np_dequant_unit_rows(
                    values, arrays[f"{name}/scales"], np.float32)
            updates[name] = (arrays[f"{name}/rows"], values)
        with self._write_lock:
            if version is not None and int(version) <= self.servant.version:
                raise StaleEpoch(
                    f"epoch {version} <= served version "
                    f"{self.servant.version} (resync, don't regress)")
            new_version = self.servant.apply_rows(
                updates,
                version=int(version) if version is not None else None,
                step=header.get("step"))
        return {"version": int(new_version),
                "snapshot": self._snapshot()}, b""

    def _reload_checkpoint(self, header: Dict,
                           payload: bytes) -> Tuple[Dict, bytes]:
        root = header.get("root") or self.checkpoint_root
        if root is None:
            raise ValueError("reload_checkpoint: no checkpoint root")
        with self._write_lock:
            version = self.servant.reload_from_checkpoint(
                root, self.config, step=header.get("step"))
        return {"version": int(version), "step": int(self.servant.step),
                "snapshot": self._snapshot()}, b""


def main(argv=None) -> int:
    from swiftsnails_tpu.utils.platform_pin import repin_from_env

    repin_from_env()
    ap = argparse.ArgumentParser(
        prog="replica_server",
        description="serve one checkpoint over TCP (pull/topk/score/health)")
    ap.add_argument("--root", required=True, help="checkpoint root")
    ap.add_argument("--listen", default="127.0.0.1:0",
                    help="host:port (port 0 = ephemeral, printed on stdout)")
    ap.add_argument("--config", action="append", default=[],
                    metavar="K=V", help="typed config overrides")
    ap.add_argument("--ledger", default="", help="run-ledger path")
    args = ap.parse_args(argv)

    from swiftsnails_tpu.serving.engine import Servant
    from swiftsnails_tpu.utils.config import Config

    cfg = Config()
    for kv in args.config:
        k, _, v = kv.partition("=")
        cfg.set(k.strip(), v.strip())
    ledger = None
    if args.ledger:
        from swiftsnails_tpu.telemetry.ledger import Ledger

        ledger = Ledger(args.ledger)
    host, _, port = args.listen.rpartition(":")
    servant = Servant.from_checkpoint(args.root, cfg, ledger=ledger)
    rs = ServantRpcServer(servant, host=host or "127.0.0.1",
                          port=int(port or 0), config=cfg,
                          checkpoint_root=args.root, ledger=ledger).start()
    print(json.dumps({
        "port": rs.address[1], "host": rs.address[0],
        "incarnation": rs.incarnation, "step": int(servant.step),
    }), flush=True)
    try:
        threading.Event().wait()  # serve until killed (SIGTERM/SIGKILL)
    except KeyboardInterrupt:
        pass
    finally:
        rs.stop()
        servant.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
