"""Remote replicas on the fleet ring + lease-driven membership.

Three pieces compose what the in-process fleet already does into a
multi-process deployment (docs/NETWORK.md):

* :class:`ReplicaProcess` / :class:`ReplicaSpawner` — spawn
  ``python -m swiftsnails_tpu.net.replica_server`` over a checkpoint root
  and read its one-line JSON ready handshake (port + incarnation);
* :class:`NetFleet` — a :class:`~swiftsnails_tpu.serving.fleet.Fleet`
  whose replicas are :class:`~swiftsnails_tpu.net.remote.RemoteServant`\\ s.
  The router/breaker/hedge machinery is inherited UNCHANGED — remote
  replicas satisfy the same servant surface. Freshness reload fans out as
  ``reload_checkpoint`` RPCs (the wire ships a path, not planes);
* :class:`ReplicaManager` — replica liveness on the
  :class:`~swiftsnails_tpu.cluster.supervisor.Supervisor` lease protocol:
  a background loop health-probes every replica and renews its lease on
  success; an expired lease (SIGKILL'd process, black-holed host) emits
  the ``membership`` worker-lost event, drains the replica from the ring,
  SIGKILLs any still-running process, and — when a spawner is attached —
  respawns a replacement that rejoins with a fresh incarnation. The same
  loop runs the autoscale hook: a p95 above the measured knee or a stale
  freshness watermark spawns one more replica (``net_autoscale``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from swiftsnails_tpu.cluster.supervisor import Supervisor, WorkerLost
from swiftsnails_tpu.net.remote import RemoteServant
from swiftsnails_tpu.serving.fleet import Fleet

DEFAULT_LEASE_MS = 3_000.0
DEFAULT_PROBE_TIMEOUT_MS = 500.0


class ReplicaProcess:
    """One spawned replica_server process and its ready handshake."""

    def __init__(self, proc: subprocess.Popen, host: str, port: int,
                 incarnation: str):
        self.proc = proc
        self.host = host
        self.port = int(port)
        self.incarnation = incarnation

    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL — the ``proc_kill`` chaos kind and the manager's
        cleanup both use the no-goodbyes signal on purpose."""
        try:
            self.proc.kill()
        except OSError:
            pass

    def terminate(self) -> None:
        try:
            self.proc.terminate()
        except OSError:
            pass

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def close(self) -> None:
        self.kill()
        self.wait(timeout=5.0)


class ReplicaSpawner:
    """Spawn replica processes over one checkpoint root + config."""

    def __init__(
        self,
        root: str,
        config=None,
        *,
        host: str = "127.0.0.1",
        ledger_path: str = "",
        env: Optional[Dict[str, str]] = None,
        startup_timeout_s: float = 180.0,
    ):
        self.root = root
        self.config = config
        self.host = host
        self.ledger_path = ledger_path
        self.env = env
        self.startup_timeout_s = float(startup_timeout_s)

    def spawn(self) -> ReplicaProcess:
        cmd = [sys.executable, "-m", "swiftsnails_tpu.net.replica_server",
               "--root", self.root, "--listen", f"{self.host}:0"]
        if self.config is not None:
            for k, v in sorted(self.config.as_dict().items()):
                cmd += ["--config", f"{k}={v}"]
        if self.ledger_path:
            cmd += ["--ledger", self.ledger_path]
        env = dict(os.environ)
        # replicas are query-only row servers: CPU serving is the correct
        # default even on an accelerator host (don't fight for the chips)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.env:
            env.update(self.env)
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True)
        ready = _read_ready_line(proc, self.startup_timeout_s)
        return ReplicaProcess(proc, ready.get("host", self.host),
                              ready["port"], ready.get("incarnation", ""))


def _read_ready_line(proc: subprocess.Popen, timeout_s: float) -> Dict:
    """Read the one-line JSON handshake with a hard deadline (a replica
    that never comes up is killed, not waited on forever)."""
    result: Dict = {}
    err: List[BaseException] = []

    def _reader():
        try:
            line = proc.stdout.readline()
            result.update(json.loads(line))
        except BaseException as e:  # noqa: BLE001 — reported below
            err.append(e)

    t = threading.Thread(target=_reader, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive() or err or "port" not in result:
        try:
            proc.kill()
        except OSError:
            pass
        detail = err[0] if err else "no ready line"
        raise RuntimeError(
            f"replica_server failed to start within {timeout_s:.0f}s "
            f"({detail})")
    return result


class NetFleet(Fleet):
    """A Fleet of RemoteServants. Construction takes endpoints instead of
    a checkpoint (the replicas already loaded their own planes)."""

    @classmethod
    def connect(
        cls,
        endpoints: Sequence[Tuple[str, int]],
        config,
        *,
        checkpoint_root: Optional[str] = None,
        ledger=None,
        registry=None,
        **fleet_kwargs,
    ) -> "NetFleet":
        eps = list(endpoints)
        if not eps:
            raise ValueError("NetFleet.connect: no endpoints")

        def factory(rid: str) -> RemoteServant:
            if not eps:
                raise RuntimeError(
                    "NetFleet: out of endpoints (use add_remote to grow)")
            host, port = eps.pop(0)
            return RemoteServant(host, port, config=config, ledger=ledger,
                                 replica=rid)

        fleet = cls(factory, replicas=len(eps), ledger=ledger,
                    registry=registry, **fleet_kwargs)
        fleet._net_config = config
        fleet._checkpoint_root = checkpoint_root
        # adopt the servers' current state before the first health poll
        for rep in fleet.replicas():
            rep.servant.health()
        return fleet

    def add_remote(self, host: str, port: int,
                   incarnation: str = "") -> str:
        """Ring-add a remote replica (elastic scale-up / respawn rejoin)."""
        rid_holder: List[str] = []

        def factory(rid: str) -> RemoteServant:
            rid_holder.append(rid)
            return RemoteServant(host, port, config=self._net_config,
                                 ledger=self.ledger, replica=rid)

        old_factory, self._factory = self._factory, factory
        try:
            rep = self._add()
        finally:
            self._factory = old_factory
        rep.servant.health()  # adopt version/step/breakers before traffic
        self.registry.counter("fleet.replicas_added").inc()
        return rep.id

    def reload_from_checkpoint(self, root: str, config=None, *,
                               step: Optional[int] = None,
                               retry=None) -> int:
        """Fan the reload out as RPCs — each replica shadow-loads from its
        own disk and swaps at its own bumped version; the fleet version is
        the max (remote replicas own their planes like tiered ones do)."""
        version = 0
        for rep in self.replicas():
            version = max(version, rep.servant.reload_checkpoint(
                root, step=step))
        return version

    def stats(self) -> Dict:
        st = super().stats()
        per = st.get("replicas")
        if isinstance(per, dict):
            for rid, rs in per.items():
                rep = self._replicas.get(rid)
                if rep is not None and hasattr(rep.servant, "transport"):
                    rs["transport"] = rep.servant.transport
                    rs["peer"] = rep.servant.client.peer
                    rs["incarnation"] = rep.servant.incarnation
        return st


class ReplicaManager:
    """Lease-driven liveness + respawn + autoscale over a NetFleet."""

    def __init__(
        self,
        fleet: NetFleet,
        *,
        spawner: Optional[ReplicaSpawner] = None,
        config=None,
        ledger=None,
        lease_ms: float = DEFAULT_LEASE_MS,
        probe_timeout_ms: float = DEFAULT_PROBE_TIMEOUT_MS,
        autoscale: Optional[bool] = None,
        max_replicas: int = 8,
        knee_p95_ms: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if config is not None:
            lease_ms = config.get_float("net_lease_ms", lease_ms)
            if autoscale is None:
                autoscale = config.get_bool("net_autoscale", False)
            max_replicas = config.get_int("net_max_replicas", max_replicas)
            knee_p95_ms = config.get_float("net_knee_p95_ms", knee_p95_ms)
        self.fleet = fleet
        self.spawner = spawner
        self.ledger = ledger
        self.autoscale = bool(autoscale)
        self.max_replicas = int(max_replicas)
        self.knee_p95_ms = float(knee_p95_ms)
        self.probe_timeout_ms = float(probe_timeout_ms)
        self.supervisor = Supervisor(lease_ms=lease_ms, ledger=ledger,
                                     clock=clock)
        self._procs: Dict[str, ReplicaProcess] = {}
        self.respawns = 0
        self.scaleups = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.RLock()
        for rep in fleet.replicas():
            self.supervisor.register(rep.id)

    def attach_process(self, rid: str, proc: ReplicaProcess) -> None:
        with self._lock:
            self._procs[rid] = proc

    def process_of(self, rid: str) -> Optional[ReplicaProcess]:
        return self._procs.get(rid)

    # -- the liveness loop ---------------------------------------------------

    def tick(self) -> List[str]:
        """One liveness round: probe + heartbeat every replica, sweep
        expired leases, replace the lost, run the autoscale hook. Returns
        the replicas declared lost this round."""
        for rep in self.fleet.replicas():
            h = rep.servant.health(read_timeout_ms=self.probe_timeout_ms)
            if h.get("status") != "unreachable":
                try:
                    self.supervisor.heartbeat(rep.id, step=h.get("step"))
                except WorkerLost:
                    # the lease lapsed but the replica ANSWERED the probe —
                    # the liveness loop was paused, not the replica dead.
                    # Rejoin it; replacement is for replicas that stay dark.
                    self.supervisor.register(rep.id)
        self.supervisor.poll()
        # a heartbeat's internal sweep may have declared the loss already
        # (poll() only reports NEWLY lost workers), so the authoritative
        # question is membership state: ring replicas whose lease is gone
        workers = self.supervisor.status().get("workers", {})
        lost = [rep.id for rep in self.fleet.replicas()
                if not workers.get(rep.id, {}).get("alive", True)]
        for rid in lost:
            self._replace(rid)
        if self.autoscale:
            self.maybe_autoscale()
        return lost

    def _replace(self, rid: str) -> None:
        proc = self._procs.pop(rid, None)
        self._transport_event("drained", replica=rid,
                              pid=proc.pid if proc else None)
        try:
            self.fleet.drain(rid, timeout_s=2.0)
        except KeyError:
            pass  # already gone (double sweep)
        if proc is not None:
            proc.close()  # SIGKILL any half-dead process, reap it
        if self.spawner is None:
            return
        replacement = self.spawner.spawn()
        new_rid = self.fleet.add_remote(replacement.host, replacement.port,
                                        incarnation=replacement.incarnation)
        self.attach_process(new_rid, replacement)
        self.supervisor.register(new_rid)
        self.respawns += 1
        self._transport_event(
            "respawn", replica=rid, replacement=new_rid,
            incarnation=replacement.incarnation, pid=replacement.pid)

    def maybe_autoscale(self) -> Optional[str]:
        """Spawn one replica when the serving knee or the freshness lag
        watermark degrades; returns the new replica id (or None)."""
        if self.spawner is None or \
                len(self.fleet.replicas()) >= self.max_replicas:
            return None
        reason = None
        p95 = self.fleet.hedge_budget("pull")
        if p95 > self.knee_p95_ms:
            reason = f"pull p95 {p95:.1f}ms > knee {self.knee_p95_ms:.0f}ms"
        fr = self.fleet._freshness
        if reason is None and fr is not None:
            try:
                if fr.status().get("stale"):
                    reason = "freshness lag watermark degraded"
            except Exception:
                pass
        if reason is None:
            return None
        proc = self.spawner.spawn()
        rid = self.fleet.add_remote(proc.host, proc.port,
                                    incarnation=proc.incarnation)
        self.attach_process(rid, proc)
        self.supervisor.register(rid)
        self.scaleups += 1
        if self.ledger is not None:
            try:
                self.ledger.append("scale_hint", {
                    "source": "net", "action": "scale_up",
                    "replica": rid, "reason": reason,
                    "replicas": len(self.fleet.replicas()),
                })
            except Exception:
                pass
        return rid

    # -- background ----------------------------------------------------------

    def start(self, interval_s: float = 0.2) -> "ReplicaManager":
        if self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception:
                    pass  # liveness must outlive any single bad round

        t = threading.Thread(target=loop, name="ssn-net-liveness",
                             daemon=True)
        t.start()
        self._thread = t
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        """Stop the loop and SIGKILL every tracked process."""
        self.stop()
        with self._lock:
            procs, self._procs = list(self._procs.values()), {}
        for p in procs:
            p.close()

    def status(self) -> Dict:
        return {
            "replicas": [r.id for r in self.fleet.replicas()],
            "respawns": self.respawns,
            "scaleups": self.scaleups,
            "supervisor": self.supervisor.status(),
        }

    def _transport_event(self, event: str, **extra) -> None:
        if self.ledger is None:
            return
        try:
            self.ledger.append("transport", {"event": event, **extra})
        except Exception:
            pass


def kill_pid(pid: int) -> None:
    """SIGKILL by pid (the chaos drill's victim switch)."""
    try:
        os.kill(pid, signal.SIGKILL)
    except OSError:
        pass
