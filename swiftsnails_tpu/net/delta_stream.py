"""Freshness delta subscription over TCP (docs/NETWORK.md).

``DeltaSubscriber.apply_batch`` is transport-agnostic by design
(``freshness/subscriber.py``); this module replaces the file poll in front
of it with a TCP stream, keeping EVERY semantics the file path has — seq
ordering, duplicate drop, gap window, publisher-restart detection, CRC
fallback — because the bytes on the wire ARE the bytes on disk:

* :class:`DeltaStreamServer` tails a delta-log directory and pushes each
  batch file verbatim as one frame payload (``op: "delta"``). A new
  connection — and any publisher incarnation change — first gets a
  ``base`` frame (the ``BASE.json`` record plus the oldest seq the server
  can still deliver).
* :class:`TcpDeltaSource` runs a background receive loop: connect under
  the retry policy (decorrelated-jitter reconnect, read timeouts — never
  a bare ``recv``), decode each batch with the SAME
  :func:`~swiftsnails_tpu.freshness.log.decode_batch` codec the file
  reader uses, and feed :meth:`DeltaSubscriber.apply_batch`. A corrupt
  batch triggers :meth:`corrupt_fallback`; a changed publisher id
  triggers :meth:`restart_fallback` — bit-for-bit the file poll's
  recovery ladder, now reachable over a killed-and-respawned publisher.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from swiftsnails_tpu.freshness.log import (
    DeltaCorrupt,
    decode_batch,
    list_seqs,
    read_base,
    seg_path,
)
from swiftsnails_tpu.net.rpc import net_retry_policy
from swiftsnails_tpu.net.wire import FrameError, encode_frame, read_frame, \
    sock_recv
from swiftsnails_tpu.resilience.retry import RetryExhausted

import socket


class DeltaStreamServer:
    """Push a delta-log directory to TCP subscribers."""

    def __init__(self, dirpath: str, *, host: str = "127.0.0.1",
                 port: int = 0, poll_interval_s: float = 0.02,
                 ledger=None):
        self.dir = os.path.abspath(dirpath)
        self.poll_interval_s = float(poll_interval_s)
        self.ledger = ledger
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(8)
        self.address = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()

    def start(self) -> "DeltaStreamServer":
        t = threading.Thread(target=self._accept_loop,
                             name="ssn-delta-stream-accept", daemon=True)
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def __enter__(self) -> "DeltaStreamServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._stream_to, args=(conn,),
                             name="ssn-delta-stream-conn",
                             daemon=True).start()

    def _stream_to(self, conn: socket.socket) -> None:
        """One subscriber: base frame, every available batch, then tail the
        directory. A publisher restart (changed id in BASE.json) re-sends
        the base — the subscriber's restart signal, same as the file poll's
        ``read_base`` check."""
        publisher: Optional[str] = None
        next_send = 1
        try:
            while not self._stop.is_set():
                base = read_base(self.dir)
                if base is None:
                    time.sleep(self.poll_interval_s)
                    continue
                if base.get("publisher") != publisher:
                    publisher = base.get("publisher")
                    seqs = list_seqs(self.dir)
                    next_send = seqs[0] if seqs else int(
                        base.get("first_seq", 1) or 1)
                    conn.sendall(encode_frame({
                        "frame": "base", **base, "first_seq": next_send,
                    }))
                sent_any = False
                for seq in list_seqs(self.dir):
                    if seq < next_send:
                        continue
                    try:
                        with open(seg_path(self.dir, seq), "rb") as f:
                            blob = f.read()
                    except OSError:
                        continue  # pruned under us: subscriber sees a gap
                    conn.sendall(encode_frame(
                        {"frame": "delta", "seq": int(seq)}, blob))
                    next_send = seq + 1
                    sent_any = True
                if not sent_any:
                    time.sleep(self.poll_interval_s)
        except OSError:
            pass  # subscriber went away
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)


class TcpDeltaSource:
    """Feed a :class:`DeltaSubscriber` from a :class:`DeltaStreamServer`."""

    def __init__(self, subscriber, host: str, port: int, *,
                 config=None, ledger=None):
        self.sub = subscriber
        self.host = host
        self.port = int(port)
        self.peer = f"{host}:{int(port)}"
        self.ledger = ledger
        self.policy = net_retry_policy(config, ledger=ledger)
        self.connect_timeout_ms = config.get_float(
            "net_connect_timeout_ms", 1_000.0) if config is not None \
            else 1_000.0
        self.read_timeout_ms = config.get_float(
            "net_read_timeout_ms", 2_000.0) if config is not None else \
            2_000.0
        self.frames = 0
        self.batches = 0
        self.reconnects = 0
        self.state = "reconnecting"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self, *_args, **_kwargs) -> "TcpDeltaSource":
        if self._thread is not None:
            return self
        self._stop.clear()
        t = threading.Thread(target=self._loop, name="ssn-delta-source",
                             daemon=True)
        t.start()
        self._thread = t
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def status(self) -> Dict:
        return {"peer": self.peer, "state": self.state,
                "frames": self.frames, "batches": self.batches,
                "reconnects": self.reconnects}

    # -- the receive loop ----------------------------------------------------

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_ms / 1e3)
        sock.settimeout(self.read_timeout_ms / 1e3)
        return sock

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock = self.policy.call(self._connect,
                                        op="net.delta_subscribe",
                                        extra={"peer": self.peer})
            except RetryExhausted:
                # budget spent (event already ledgered with the peer);
                # a stream source outlives one budget — try again unless
                # the drill/caller stopped us
                if self._stop.wait(0.05):
                    return
                continue
            if self.reconnects > 0:
                self._transport_event("reconnect",
                                      reconnects=self.reconnects)
            self.state = "connected"
            try:
                self._pump(sock)
            except (OSError, FrameError) as e:
                self._transport_event(
                    "conn_lost", error=f"{type(e).__name__}: {e}")
            finally:
                self.state = "reconnecting"
                self.reconnects += 1
                try:
                    sock.close()
                except OSError:
                    pass

    def _pump(self, sock: socket.socket) -> None:
        raw = sock_recv(sock)
        while not self._stop.is_set():
            got = [0]

            def recv(n: int) -> bytes:
                chunk = raw(n)
                got[0] += len(chunk)
                return chunk

            try:
                header, payload = read_frame(recv)
            except socket.timeout:
                if self._stop.is_set():
                    return
                if got[0] == 0:
                    continue  # idle at a frame boundary: keep listening
                raise  # deadline fired MID-frame: a real stall, reconnect
            self.frames += 1
            kind = header.get("frame")
            if kind == "base":
                self._on_base(header)
            elif kind == "delta":
                self._on_delta(header, payload)

    def _on_base(self, base: Dict) -> None:
        sub = self.sub
        if sub.publisher is not None and \
                base.get("publisher") != sub.publisher:
            # the publisher restarted while we were connected (or across a
            # reconnect): the file poll's read_base check, as a frame
            sub.restart_fallback()
        if sub.publisher is None:
            # dir-less resubscribe (or first subscribe): adopt the stream's
            # own base — first_seq is the oldest batch it will deliver
            sub.adopt_base(base, first_seq=base.get("first_seq"))

    def _on_delta(self, header: Dict, payload: bytes) -> None:
        sub = self.sub
        try:
            bheader, tables = decode_batch(
                payload, label=f"tcp:{self.peer}:seq{header.get('seq')}")
        except DeltaCorrupt:
            sub.corrupt_fallback(failed_seq=header.get("seq"))
            return
        self.batches += 1
        sub.apply_batch(bheader, tables)

    def _transport_event(self, event: str, **extra) -> None:
        if self.ledger is None:
            return
        try:
            self.ledger.append("transport", {
                "event": event, "peer": self.peer,
                "source": "delta_stream", **extra})
        except Exception:
            pass
